"""Mosaic compile-hang quarantine.

TPU-first operational infrastructure with no direct reference counterpart
(nearest analogue: the reference's compile-race regression protection,
``tests/utils/test_load_cubin_compile_race_condition.py``, and its tactics
blocklist).  On TPU the failure mode that matters is different: a bad
Mosaic compile can wedge the *chip*, not just the process — after which
every compile from any process hangs until the chip recovers.  One wedge
must therefore cost one kernel slot, never a whole session:

- Before the first compile of a kernel variant, a *pending marker*
  (fingerprint, pid, timestamp) is written to the cache dir; it is removed
  as soon as the compile+run completes.
- On startup, a stale marker whose owning process is dead and whose age
  exceeded the hang threshold is treated as evidence of a wedge: that
  fingerprint is moved to the persistent quarantine list and subsequent
  calls raise :class:`KernelQuarantined` (callers fall back to the XLA
  path) instead of re-wedging the chip.
- ``python -m flashinfer_tpu probe`` compiles a trivial kernel in a
  subprocess under a timeout — the recovery detector.

Fingerprints hash the op name, the kernel module's source text, and the
launch statics, so editing the kernel (the fix) automatically clears its
quarantine, while the same bad variant stays blocked across processes.
"""

from __future__ import annotations

import contextlib
import hashlib
import inspect
import json
import os
import time
from pathlib import Path
from typing import Any, Callable, Dict, Optional

from flashinfer_tpu import env

# a compile that survives this long without finishing is presumed wedged
# when its process is found dead (normal Mosaic compiles take 20-60s)
HANG_THRESHOLD_S = 180.0

_seen_ok: set = set()
# quarantined fps seen this process -> last disk check time: the negative
# cache keeps disk I/O off per-step fallback paths, but expires so an
# operator's external `quarantine --clear` takes effect within a minute
_seen_bad: Dict[str, float] = {}
_SEEN_BAD_TTL_S = 60.0
_source_digest_cache: Dict[str, str] = {}
_fp_cache: Dict[tuple, str] = {}


def trace_state_clean() -> bool:
    """True when not under a jax trace.  ``jax.core.trace_state_clean`` was
    removed from the public namespace in newer JAX; fall back to the _src
    location rather than silently losing in-trace detection (the old
    ``except ImportError: pass`` pattern disabled it without notice)."""
    try:
        from jax.core import trace_state_clean as f
    except (ImportError, AttributeError):
        try:
            from jax._src.core import trace_state_clean as f
        except (ImportError, AttributeError):
            return True  # undetectable -> behave as the pre-helper code did
    return f()


class KernelQuarantined(RuntimeError):
    """Raised when a kernel variant is quarantined after a suspected
    compile wedge; callers should fall back to their XLA path."""


def _qdir() -> Path:
    return env.cache_dir() / "quarantine"


def _qlist_path() -> Path:
    return _qdir() / "kernels.json"


def _pending_dir() -> Path:
    return _qdir() / "pending"


def _module_source_digest(module: Any) -> str:
    key = getattr(module, "__name__", str(module))
    if key not in _source_digest_cache:
        try:
            src = inspect.getsource(module)
        except Exception:
            src = key
        _source_digest_cache[key] = hashlib.sha256(src.encode()).hexdigest()
    return _source_digest_cache[key]


def fingerprint(op_name: str, statics: Any, module: Any = None) -> str:
    # memoized per (op, statics-repr, module): the steady-state guarded()
    # pass-through sits on µs-scale decode hot paths and must not re-hash
    # kernel source text per call
    mkey = getattr(module, "__name__", None) if module is not None else None
    ck = (op_name, repr(statics), mkey)
    fp = _fp_cache.get(ck)
    if fp is None:
        blob = ck[0] + "|" + ck[1]
        if module is not None:
            blob += "|" + _module_source_digest(module)
        fp = hashlib.sha256(blob.encode()).hexdigest()[:24]
        _fp_cache[ck] = fp
    return fp


def _load_qlist() -> Dict[str, dict]:
    try:
        return json.loads(_qlist_path().read_text())
    except Exception:
        return {}


def _save_qlist(q: Dict[str, dict]) -> None:
    from flashinfer_tpu.utils import atomic_write_text

    atomic_write_text(_qlist_path(), json.dumps(q, indent=1))


def quarantine(fp: str, op_name: str, reason: str) -> None:
    q = _load_qlist()
    q[fp] = {"op": op_name, "reason": reason, "ts": time.time()}
    _save_qlist(q)


def clear(fp: Optional[str] = None) -> int:
    """Remove one fingerprint (or all) from the quarantine list."""
    q = _load_qlist()
    n = len(q)
    if fp is None:
        q = {}
        _seen_bad.clear()
    else:
        q.pop(fp, None)
        _seen_bad.pop(fp, None)
    _save_qlist(q)
    return n - len(q)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def _sweep_stale_markers() -> None:
    """Promote dead-process pending markers older than the hang threshold
    into the quarantine list (the cross-process wedge detector)."""
    d = _pending_dir()
    if not d.is_dir():
        return
    now = time.time()
    for p in d.glob("*.json"):
        try:
            info = json.loads(p.read_text())
        except Exception:
            p.unlink(missing_ok=True)
            continue
        if _pid_alive(int(info.get("pid", -1))):
            continue
        if now - float(info.get("ts", now)) >= HANG_THRESHOLD_S:
            quarantine(
                p.stem, info.get("op", "?"),
                "stale compile marker from dead process "
                f"(pid {info.get('pid')}, started {info.get('ts')})",
            )
        p.unlink(missing_ok=True)


def _enabled() -> bool:
    flag = os.environ.get("FLASHINFER_TPU_COMPILE_GUARD")
    if flag is not None:
        return flag not in ("0", "false", "")
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def guarded(
    op_name: str,
    statics: Any,
    thunk: Callable[[], Any],
    module: Any = None,
):
    """Run ``thunk`` under the quarantine protocol.

    First sight of a (op, statics, kernel-source) fingerprint: check the
    quarantine list, sweep stale markers, write a pending marker, run the
    thunk to completion (``block_until_ready`` so the Mosaic compile is
    inside the guarded window), then clear the marker.  Later calls with
    the same fingerprint are zero-overhead pass-throughs."""
    fp = fingerprint(op_name, statics, module)
    if fp in _seen_ok or not _enabled():
        return thunk()
    if module is not None:
        # static wedge-pattern lint runs once per module per process,
        # BEFORE the first hardware compile: a kernel matching a
        # known-wedging Mosaic pattern refuses to compile in strict mode
        # (default on real TPU) rather than risking the chip.  The
        # wedge lint lives in the analyzer package (the old wedge_lint
        # shim is retired — docs/migration.md)
        from flashinfer_tpu.analysis import wedge

        wedge.check_module(module)
    try:
        if not trace_state_clean():
            # Under an outer jit trace the thunk returns a tracer and
            # block_until_ready is a no-op — the real Mosaic compile happens
            # later, outside this window.  Recording OK here would be a false
            # claim of guard coverage, so pass through with no bookkeeping.
            return thunk()
    except Exception:
        pass
    last = _seen_bad.get(fp)
    if last is not None and time.time() - last < _SEEN_BAD_TTL_S:
        raise KernelQuarantined(
            f"{op_name} variant {fp} is quarantined (clear with "
            f"`python -m flashinfer_tpu quarantine --clear {fp}`; an "
            f"external clear takes effect within {int(_SEEN_BAD_TTL_S)}s)"
        )
    _seen_bad.pop(fp, None)
    _sweep_stale_markers()
    if fp in _load_qlist():
        _seen_bad[fp] = time.time()
        raise KernelQuarantined(
            f"{op_name} variant {fp} is quarantined after a suspected "
            "compile wedge; falling back (clear with "
            f"`python -m flashinfer_tpu quarantine --clear {fp}`)"
        )
    d = _pending_dir()
    d.mkdir(parents=True, exist_ok=True)
    marker = d / f"{fp}.json"
    # O_EXCL: when two processes race to first-compile the same variant,
    # only one owns the marker — the other must not erase it on success
    # while the owner may still be mid-compile
    owns_marker = False
    try:
        fd = os.open(marker, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
        with os.fdopen(fd, "w") as fh:
            fh.write(json.dumps(
                {"op": op_name, "pid": os.getpid(), "ts": time.time()}
            ))
        owns_marker = True
    except FileExistsError:
        pass
    t0 = time.time()
    try:
        import jax

        out = thunk()
        jax.block_until_ready(out)
    finally:
        # reached on success or a *raising* failure; a hard hang leaves the
        # marker for the next process's sweep — by design
        if owns_marker:
            with contextlib.suppress(OSError):
                marker.unlink()
    _seen_ok.add(fp)
    _record_status(fp, op_name, time.time() - t0)
    return out


def guarded_jit(fn: Callable, op_name: Optional[str] = None, **jit_kwargs):
    """``jax.jit`` whose first execution per argument-signature runs under
    :func:`guarded` — the helper bench/ad-hoc scripts must use so *every*
    first Mosaic compile is inside the quarantine protocol (the round-2
    wedge escaped through an unguarded ad-hoc bench; see repo memory).

    The signature fingerprint is (shape, dtype) of every array argument
    plus reprs of non-arrays, matching jit's own retrace key closely
    enough that each fresh compile gets its own guarded window."""
    import jax

    jf = jax.jit(fn, **jit_kwargs)
    name = op_name or getattr(fn, "__name__", "guarded_jit")

    def _sig(x):
        s = getattr(x, "shape", None)
        return (s, str(getattr(x, "dtype", ""))) if s is not None else repr(x)

    def wrapper(*args, **kwargs):
        statics = jax.tree_util.tree_map(_sig, (args, kwargs))
        return guarded(name, statics, lambda: jf(*args, **kwargs))

    wrapper.__wrapped__ = jf
    return wrapper


def _status_path() -> Path:
    return _qdir() / "compile_status.json"


def _record_status(fp: str, op_name: str, duration: float) -> None:
    """Compile-status registry (reference jit-core's module status role):
    every first compile that completed under the guard, with its duration —
    ``python -m flashinfer_tpu module-status`` surfaces it."""
    try:
        try:
            reg = json.loads(_status_path().read_text())
        except Exception:
            reg = {}
        reg[fp] = {
            "op": op_name, "status": "ok",
            "compile_s": round(duration, 2), "ts": round(time.time(), 1),
        }
        from flashinfer_tpu.utils import atomic_write_text

        atomic_write_text(_status_path(), json.dumps(reg, indent=1))
    except Exception:
        pass  # telemetry must never break the op


def compile_status() -> Dict[str, dict]:
    try:
        return json.loads(_status_path().read_text())
    except Exception:
        return {}


def probe(timeout_s: float = 240.0, interpret: bool = False) -> dict:
    """Compile a trivial Pallas kernel in a subprocess under a timeout.

    Returns ``{"healthy": bool, "elapsed": s, "detail": str}`` — the
    recovery detector to run after a wedge before resuming kernel work.
    ``interpret=True`` probes the interpret path instead (pallas_call on
    CPU refuses the compiled path outright, so an off-TPU bring-up
    selftest would read every probe as a wedge without it)."""
    import subprocess
    import sys

    flag = ", interpret=True" if interpret else ""
    code = (
        "import jax, jax.numpy as jnp\n"
        "from jax.experimental import pallas as pl\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * 2.0\n"
        "x = jnp.ones((8, 128), jnp.float32)\n"
        "y = pl.pallas_call(k, out_shape=jax.ShapeDtypeStruct((8, 128), "
        f"jnp.float32){flag})(x)\n"
        "jax.block_until_ready(y)\n"
        "print('PROBE_OK')\n"
    )
    t0 = time.time()
    # Popen + bounded reaps, not subprocess.run: a wedged compile can leave
    # the child unkillable (stuck in tunnel I/O), and run()'s internal
    # post-kill wait() would then hang the *prober* too
    p = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        out, err = p.communicate(timeout=timeout_s)
        ok = "PROBE_OK" in out
        detail = out[-200:] if ok else (err or out)[-500:]
    except subprocess.TimeoutExpired:
        p.kill()
        with contextlib.suppress(Exception):
            p.communicate(timeout=10)
        ok, detail = False, f"probe timed out after {timeout_s}s (chip wedged?)"
    return {"healthy": ok, "elapsed": round(time.time() - t0, 1), "detail": detail}
