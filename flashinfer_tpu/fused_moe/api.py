"""Unified MoE configuration API.

Re-design of the reference's frozen-dataclass MoE config surface
(``flashinfer/fused_moe/api.py:1-133`` — explicitly called out in SURVEY
§2.3 as the pattern to mirror): decouples MoE callers from the kernels'
many positional arguments.  A ``MoE`` layer object holds the config +
weights and exposes one ``__call__``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.fused_moe.core import fused_moe, fused_moe_ep
from flashinfer_tpu.fused_moe.routing import (
    RoutingMethodType,
    route_deepseek_v3,
    route_llama4,
    route_renormalize,
    route_topk,
)


class QuantVariant(enum.Enum):
    """Weight/activation precision variants (reference QuantVariant).
    TPU mapping: BF16 native; FP8/INT8 = stored low-precision, bf16/int8
    MXU compute (gemm.py docs)."""

    BF16 = "bf16"
    FP8 = "fp8"
    INT8 = "int8"


@dataclass(frozen=True)
class RoutingConfig:
    """Routing configuration (reference RoutingConfig)."""

    method: RoutingMethodType = RoutingMethodType.Renormalize
    top_k: int = 2
    # DeepSeek-V3 extras
    n_group: int = 1
    topk_group: int = 1
    routed_scaling_factor: float = 1.0

    def __call__(self, logits: jax.Array, bias: Optional[jax.Array] = None):
        m = self.method
        if m == RoutingMethodType.Default:
            return route_topk(logits, self.top_k)
        if m in (RoutingMethodType.Renormalize, RoutingMethodType.RenormalizeNaive):
            return route_renormalize(logits, self.top_k)
        if m == RoutingMethodType.DeepSeekV3:
            if bias is None:
                bias = jnp.zeros((logits.shape[-1],), jnp.float32)
            return route_deepseek_v3(
                logits, bias, self.top_k, self.n_group, self.topk_group,
                self.routed_scaling_factor,
            )
        if m == RoutingMethodType.Llama4:
            return route_llama4(logits)
        raise ValueError(f"unsupported routing method {m}")


@dataclass(frozen=True)
class QuantConfig:
    """Quantization configuration (reference QuantConfig)."""

    variant: QuantVariant = QuantVariant.BF16


@dataclass(frozen=True)
class MoEConfig:
    num_experts: int
    hidden_size: int
    intermediate_size: int
    routing: RoutingConfig = field(default_factory=RoutingConfig)
    quant: QuantConfig = field(default_factory=QuantConfig)
    activation: str = "silu"
    # expert parallelism
    ep_axis: Optional[str] = None  # mesh axis when called inside shard_map
    ep_dispatch: str = "allgather"
    # per-destination bucket size for the all_to_all modes, as a multiple
    # of the balanced load: "alltoall" drops routes beyond it;
    # "alltoall_exact" never drops but runs ceil(max_load/cap) exchange
    # rounds, so a tiny value multiplies dispatch latency instead
    ep_capacity_factor: float = 2.0


class MoE:
    """Config-driven MoE layer (reference unified ``MoE`` layer API).

    >>> layer = MoE(cfg, router_weight, w_gate_up, w_down)
    >>> out = layer(x)          # route + fused expert compute
    """

    def __init__(
        self,
        config: MoEConfig,
        router_weight: jax.Array,  # [hidden, num_experts]
        w_gate_up: jax.Array,  # [E(_local), hidden, 2*inter]
        w_down: jax.Array,  # [E(_local), inter, hidden]
        router_bias: Optional[jax.Array] = None,
    ):
        self.config = config
        self.router_weight = router_weight
        self.router_bias = router_bias
        # honor the quant variant at weight-storage level (the TPU mapping:
        # low-precision HBM storage, bf16/int8-adjacent MXU compute)
        v = config.quant.variant
        if v == QuantVariant.BF16:
            self._wq1, self._ws1 = w_gate_up, None
            self._wq2, self._ws2 = w_down, None
        elif v == QuantVariant.FP8:
            from flashinfer_tpu.quantization import quantize_fp8_per_channel

            self._wq1, self._ws1 = quantize_fp8_per_channel(w_gate_up, axis=1)
            self._wq2, self._ws2 = quantize_fp8_per_channel(w_down, axis=1)
        elif v == QuantVariant.INT8:
            from flashinfer_tpu.quantization import quantize_int8

            self._wq1, self._ws1 = quantize_int8(w_gate_up, axis=1)
            self._wq2, self._ws2 = quantize_int8(w_down, axis=1)
        else:
            raise ValueError(f"unsupported quant variant {v}")

    def _weights(self):
        if self._ws1 is None:
            return self._wq1, self._wq2
        w1 = (self._wq1.astype(jnp.float32) * self._ws1).astype(jnp.bfloat16)
        w2 = (self._wq2.astype(jnp.float32) * self._ws2).astype(jnp.bfloat16)
        return w1, w2

    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        # routing precision follows the input dtype (fp32 stays fp32 — bf16
        # rounding can flip near-tied expert selections)
        logits = jnp.dot(
            x, self.router_weight.astype(x.dtype),
            preferred_element_type=jnp.float32,
        )
        weights, ids = cfg.routing(logits, self.router_bias)
        if cfg.quant.variant == QuantVariant.INT8 and cfg.ep_axis is None:
            # native int8 MXU grouped GEMMs (no bf16 dequant copy)
            return fused_moe(
                x, self._wq1, self._wq2, weights, ids, cfg.num_experts,
                cfg.activation, w1_scale=self._ws1, w2_scale=self._ws2,
            )
        w1, w2 = self._weights()
        if cfg.ep_axis is None:
            return fused_moe(
                x, w1, w2, weights, ids, cfg.num_experts, cfg.activation
            )
        return fused_moe_ep(
            x, w1, w2, weights, ids, cfg.num_experts,
            axis=cfg.ep_axis, activation=cfg.activation,
            dispatch=cfg.ep_dispatch,
            capacity_factor=cfg.ep_capacity_factor,
        )
