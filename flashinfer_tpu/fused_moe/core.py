"""Fused MoE pipeline: permute -> grouped GEMM -> act -> grouped GEMM -> finalize.

Re-design of ``cutlass_fused_moe`` (reference fused_moe/core.py:873): the
five CUDA stages map to

1. permute: stable argsort of the flattened (token, expert-choice) pairs by
   expert id (the reference's expert-major permutation);
2/4. grouped GEMMs: ``jax.lax.ragged_dot`` over the expert-sorted rows
   (megablox-style — group offsets come from a bincount, no capacity
   padding, no wasted MXU work on empty experts);
3. activation: silu_and_mul on the gate|up halves;
5. finalize: inverse-permute + weighted sum over each token's k choices.

Weight layout: ``w_gate_up [E, hidden, 2*inter]`` ([gate | up] columns),
``w_down [E, inter, hidden]`` — the reference's reorder_rows_for_gated_act
shuffling (core.py:245) is unnecessary because XLA owns the layout.

Expert parallelism (``fused_moe_ep``): the reference's moe_ep subsystem
(SURVEY §2.3 — NCCL-EP / NIXL-RDMA dispatch+combine) maps to the
allgather-dispatch / psum-combine pattern over a mesh axis: every rank
computes its local experts for the full (gathered) token set and the
partial outputs sum over the axis.  An all_to_all dispatch variant is a
later optimization for large EP degrees.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.activation import silu_and_mul


@functools.partial(jax.jit, static_argnames=("num_experts", "activation"))
def fused_moe(
    hidden: jax.Array,  # [T, hidden]
    w_gate_up: jax.Array,  # [E, hidden, 2*inter]
    w_down: jax.Array,  # [E, inter, hidden]
    topk_weights: jax.Array,  # [T, K] f32
    topk_ids: jax.Array,  # [T, K] int32
    num_experts: int,
    activation: str = "silu",
) -> jax.Array:
    """Single-device fused MoE forward -> [T, hidden]."""
    T, K = topk_ids.shape
    dtype = hidden.dtype

    flat_expert = topk_ids.reshape(-1)  # [T*K]
    order = jnp.argsort(flat_expert, stable=True)
    inv_token = order // K  # source token of each sorted row
    x_sorted = hidden[inv_token]  # [T*K, hidden]
    group_sizes = jnp.bincount(flat_expert, length=num_experts).astype(jnp.int32)

    h1 = jax.lax.ragged_dot(x_sorted, w_gate_up, group_sizes)  # [T*K, 2I]
    if activation == "silu":
        a = silu_and_mul(h1)
    elif activation == "gelu":
        d = h1.shape[-1] // 2
        a = (
            jax.nn.gelu(h1[..., :d].astype(jnp.float32))
            * h1[..., d:].astype(jnp.float32)
        ).astype(h1.dtype)
    else:
        raise ValueError(f"unknown activation {activation!r}")
    h2 = jax.lax.ragged_dot(a, w_down, group_sizes)  # [T*K, hidden]

    # finalize: route each sorted row back to (token, choice) and weight-sum
    w_sorted = topk_weights.reshape(-1)[order].astype(jnp.float32)
    contrib = h2.astype(jnp.float32) * w_sorted[:, None]
    out = jnp.zeros((T, hidden.shape[1]), jnp.float32).at[inv_token].add(contrib)
    return out.astype(dtype)


def fused_moe_ep(
    hidden: jax.Array,  # [T_local, hidden] (this rank's tokens)
    w_gate_up: jax.Array,  # [E_local, hidden, 2*inter] (this rank's experts)
    w_down: jax.Array,  # [E_local, inter, hidden]
    topk_weights: jax.Array,  # [T_local, K]
    topk_ids: jax.Array,  # [T_local, K] GLOBAL expert ids
    num_experts: int,
    axis: str = "tp",
    activation: str = "silu",
) -> jax.Array:
    """Expert-parallel fused MoE (call inside shard_map).

    Experts are contiguously sharded over ``axis`` (rank r owns
    ``[r*E_local, (r+1)*E_local)``, the Mapping.ep_experts partition).
    Dispatch = all_gather of tokens+routing; combine = psum of partials."""
    ep = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    e_local = w_gate_up.shape[0]

    xg = jax.lax.all_gather(hidden, axis, tiled=True)  # [T_global, hidden]
    wg = jax.lax.all_gather(topk_weights, axis, tiled=True)
    idg = jax.lax.all_gather(topk_ids, axis, tiled=True)

    lo = rank * e_local
    local = (idg >= lo) & (idg < lo + e_local)
    # non-local choices route to a local dummy slot with zero weight
    ids_local = jnp.where(local, idg - lo, 0).astype(jnp.int32)
    w_local = jnp.where(local, wg, 0.0)

    partial = fused_moe(
        xg, w_gate_up, w_down, w_local, ids_local, e_local, activation
    )
    # combine: sum partials, then take this rank's token slice
    return jax.lax.psum_scatter(partial, axis, tiled=True)
