"""Fused MoE pipeline: permute -> grouped GEMM -> act -> grouped GEMM -> finalize.

Re-design of ``cutlass_fused_moe`` (reference fused_moe/core.py:873): the
five CUDA stages map to

1. permute: stable argsort of the flattened (token, expert-choice) pairs by
   expert id (the reference's expert-major permutation);
2/4. grouped GEMMs: ``jax.lax.ragged_dot`` over the expert-sorted rows
   (megablox-style — group offsets come from a bincount, no capacity
   padding, no wasted MXU work on empty experts);
3. activation: silu_and_mul on the gate|up halves;
5. finalize: inverse-permute + weighted sum over each token's k choices.

Weight layout: ``w_gate_up [E, hidden, 2*inter]`` ([gate | up] columns),
``w_down [E, inter, hidden]`` — the reference's reorder_rows_for_gated_act
shuffling (core.py:245) is unnecessary because XLA owns the layout.

Expert parallelism (``fused_moe_ep``): the reference's moe_ep subsystem
(SURVEY §2.3 — NCCL-EP / NIXL-RDMA dispatch+combine) maps to the
allgather-dispatch / psum-combine pattern over a mesh axis: every rank
computes its local experts for the full (gathered) token set and the
partial outputs sum over the axis.  An all_to_all dispatch variant is a
later optimization for large EP degrees.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.activation import silu_and_mul
from flashinfer_tpu.utils import lax_axis_size


def _act(h1: jax.Array, activation: str) -> jax.Array:
    if activation == "silu":
        return silu_and_mul(h1)
    if activation == "gelu":
        d = h1.shape[-1] // 2
        return (
            jax.nn.gelu(h1[..., :d].astype(jnp.float32))
            * h1[..., d:].astype(jnp.float32)
        ).astype(h1.dtype)
    raise ValueError(f"unknown activation {activation!r}")


def _quant_rows_int8(x: jax.Array):
    """Dynamic symmetric per-row int8 quantization (activation side)."""
    from flashinfer_tpu.quantization import quantize_int8

    return quantize_int8(x, axis=-1)


def _gmm_tileable(hidden_dim: int, inter2: int) -> bool:
    # gmm picks tk as the largest power-of-two divisor >= 128, so 128
    # alignment of every contraction/output dim is the whole requirement
    return hidden_dim % 128 == 0 and inter2 % 128 == 0 and inter2 // 2 % 128 == 0


def fused_moe(
    hidden: jax.Array,
    w_gate_up: jax.Array,
    w_down: jax.Array,
    topk_weights: jax.Array,
    topk_ids: jax.Array,
    num_experts: int,
    activation: str = "silu",
    w1_scale: Optional[jax.Array] = None,
    w2_scale: Optional[jax.Array] = None,
    backend: str = "auto",
    gather_variant: str = "auto",
    gmm_tiles=None,
) -> jax.Array:
    """Single-device fused MoE forward -> [T, hidden].

    Backends (reference analogue: cutlass vs trtllm-gen backend dispatch,
    fused_moe/core.py:873):

    - ``"gmm"``: Pallas grouped-matmul pipeline (``ops/moe_gmm.py``) — the
      first GEMM gathers token rows straight from the unsorted ``hidden``
      (no ``[T*K, hidden]`` sorted copy in HBM), the second runs over the
      already-grouped activation rows; int8 variants quantize per-token
      BEFORE routing (T rows, not T*K) and fold all scales into the store
      epilogues.
    - ``"ragged"``: ``jax.lax.ragged_dot`` over materialized sorted rows
      (the XLA fallback, and the oracle for tests).
    - ``"auto"``: env ``FLASHINFER_TPU_MOE_BACKEND`` if set, else
      ``"gmm"`` on hardware BY MEASUREMENT: with tuned tile shapes the
      sorted-gather GMM kernel beats ragged_dot at every banked v5e point
      (BENCH_BANKED.md 2026-07-31, Mixtral 8x7B: T=1024 int8
      132 vs 76 TFLOP/s, bf16 85 vs 53; T=256 int8 68 vs 33, bf16
      39 vs 20 — the round-4 "ragged wins 2.6-2.9x" verdict was an
      artifact of the stock (128, 128, 512) tiles, re-banked).  Interpret
      mode (CPU tests) and non-128-aligned shapes stay ragged.

    Backend resolution happens outside the jitted body so the env var is
    re-read on every *eager* call; a caller that wraps fused_moe in its own
    jax.jit pins the trace-time value in that outer cache.
    """
    tileable = _gmm_tileable(hidden.shape[1], w_gate_up.shape[2])
    if backend == "auto":
        import os

        from flashinfer_tpu.utils import use_interpret

        default = "ragged" if use_interpret() else "gmm"
        backend = os.environ.get("FLASHINFER_TPU_MOE_BACKEND", default)
        if backend == "gmm" and not tileable:
            backend = "ragged"  # auto falls back; explicit "gmm" raises
    if backend not in ("gmm", "ragged"):
        raise ValueError(f"unknown fused_moe backend {backend!r}")
    if backend == "gmm" and not tileable:
        raise ValueError(
            "gmm backend requires 128-aligned hidden/inter dims, got "
            f"hidden={hidden.shape[1]} 2*inter={w_gate_up.shape[2]}"
        )
    if backend == "gmm":
        gmm_tiles = _resolve_gmm_tiles(
            gmm_tiles, hidden, w_gate_up, w_down, topk_ids
        )
    else:
        gmm_tiles = None
    return _fused_moe_impl(
        hidden, w_gate_up, w_down, topk_weights, topk_ids, num_experts,
        activation, w1_scale, w2_scale, backend, gather_variant, gmm_tiles,
    )


# Grouped-GEMM tile-shape selection.  The megablox-form kernel's HBM
# traffic scales as tiles_n * M * K (lhs re-streaming across the n sweep)
# + group_visits * K * N (expert-weight streaming), so both shrink with
# bigger tiles: the banked v5e sweep (scripts/exp_moe_tiles.py,
# BENCH_BANKED.md 2026-07-31, Mixtral 8x7B) has the stock (128, 128, 512)
# blocks at 20-27 TFLOP/s vs (256, 2048, 1024) at 85 bf16 / 132 int8 —
# a 3-4x swing on tile shape alone.  The heuristic below picks
# largest-that-fits tiles; tuning_configs/ ships measured per-shape
# winners and a user autotune() overrides both.
_GMM_VMEM_BUDGET = 13 * 1024 * 1024  # double-buffered blocks + f32 acc


def _heuristic_gmm_tiles(m, k, n, itemsize, out_itemsize=2):
    """Largest (tm, tn, tk) whose double-buffered block footprint fits the
    VMEM budget, with tn an exact divisor of n and tk of k (both stay
    128-aligned; callers validated 128-alignment)."""
    from flashinfer_tpu.ops.moe_gmm import tile_footprint

    def _div_cap(x, cap):
        # largest 128-multiple divisor of x that is <= cap (x is
        # 128-aligned, so d == 128 always succeeds)
        d = (min(cap, x) // 128) * 128
        while d > 128 and x % d:
            d -= 128
        return max(d, 128)

    tm = 256 if m >= 256 else 128
    tn, tk = _div_cap(n, 2048), _div_cap(k, 1024)
    while True:
        footprint = tile_footprint(tm, tn, tk, itemsize, out_itemsize)
        if footprint <= _GMM_VMEM_BUDGET or (tn <= 128 and tk <= 128):
            return (tm, tn, tk)
        # shrink the dominant block first
        if tk * tn >= tm * tn and tn > 128:
            tn = _div_cap(n, tn - 128)
        elif tk > 128:
            tk = _div_cap(k, tk - 128)
        else:
            tn = _div_cap(n, tn - 128)


def _resolve_gmm_tiles(gmm_tiles, hidden, w_gate_up, w_down, topk_ids):
    """Normalize to ((tm1, tn1, tk1), (tm2, tn2, tk2)) for the two grouped
    GEMMs; None consults the autotuner cache keyed by each GEMM's
    (M, K, N, dtype), falling back to the VMEM-bounded heuristic."""
    if gmm_tiles is not None:
        gmm_tiles = tuple(map(tuple, gmm_tiles)) if isinstance(
            gmm_tiles[0], (tuple, list)
        ) else (tuple(gmm_tiles),) * 2
        if len(gmm_tiles) != 2 or any(len(t) != 3 for t in gmm_tiles):
            raise ValueError(
                f"gmm_tiles must be (tm, tn, tk) or a pair of them, got "
                f"{gmm_tiles!r}"
            )
        return gmm_tiles
    from flashinfer_tpu.autotuner import AutoTuner

    tuner = AutoTuner.get()
    m = topk_ids.shape[0] * topk_ids.shape[1]
    h, n1 = w_gate_up.shape[1], w_gate_up.shape[2]
    esz = w_gate_up.dtype.itemsize
    dt = w_gate_up.dtype
    # per-GEMM epilogue output dtypes (must match _fused_moe_impl): the
    # int8 first GEMM stores bf16 directly, the second stores f32 for the
    # combine; bf16 path stores bf16 everywhere
    o1 = jnp.bfloat16 if esz == 1 else dt
    o2 = jnp.float32 if esz == 1 else dt
    h1_def = _heuristic_gmm_tiles(m, h, n1, esz, jnp.dtype(o1).itemsize)
    h2_def = _heuristic_gmm_tiles(
        m, w_down.shape[1], h, esz, jnp.dtype(o2).itemsize
    )
    gemm1, gemm2 = (m, h, n1), (m, w_down.shape[1], h)
    if tuner.tuning_enabled:
        # autotune() context: profile candidates per GEMM geometry with
        # the standalone kernel (writes the same cache keys lookup reads)
        from flashinfer_tpu.ops.moe_gmm import tune_tiles

        t1 = tune_tiles(*gemm1, dt, h1_def, out_dtype=o1)
        t2 = tune_tiles(*gemm2, dt, h2_def, out_dtype=o2)
    else:
        t1 = tuner.lookup("moe_gmm.tiles", (*gemm1, dt), default=h1_def)
        t2 = tuner.lookup("moe_gmm.tiles", (*gemm2, dt), default=h2_def)
    return (tuple(t1), tuple(t2))


@functools.partial(
    jax.jit,
    static_argnames=("num_experts", "activation", "backend",
                     "gather_variant", "gmm_tiles"),
)
def _fused_moe_impl(
    hidden: jax.Array,  # [T, hidden]
    w_gate_up: jax.Array,  # [E, hidden, 2*inter] bf16 OR int8
    w_down: jax.Array,  # [E, inter, hidden]
    topk_weights: jax.Array,  # [T, K] f32
    topk_ids: jax.Array,  # [T, K] int32
    num_experts: int,
    activation: str = "silu",
    w1_scale: Optional[jax.Array] = None,  # [E, 1, 2*inter] (int8 weights)
    w2_scale: Optional[jax.Array] = None,  # [E, 1, hidden]
    backend: str = "ragged",
    gather_variant: str = "auto",
    gmm_tiles=None,
) -> jax.Array:
    """Jitted body of :func:`fused_moe` (backend already resolved).

    With int8 weights (+ per-channel scales), both grouped GEMMs run on the
    native int8 MXU path (int8 x int8 -> int32, the v5e low-precision
    story) with dynamic per-row activation quantization — weights cross
    HBM at half width and the MXU runs at its doubled int8 rate.
    """
    T, K = topk_ids.shape
    dtype = hidden.dtype
    quantized = w_gate_up.dtype == jnp.int8

    flat_expert = topk_ids.reshape(-1)  # [T*K]
    order = jnp.argsort(flat_expert, stable=True)
    inv_token = order // K  # source token of each sorted row
    group_sizes = jnp.bincount(flat_expert, length=num_experts).astype(jnp.int32)

    if backend == "gmm":
        from flashinfer_tpu.ops.moe_gmm import gather_gmm, gmm

        assert gmm_tiles is not None  # resolved by fused_moe for gmm
        (tm1, tn1, tk1), (tm2, tn2, tk2) = gmm_tiles
        if quantized:
            assert w1_scale is not None and w2_scale is not None
            xq, xs = _quant_rows_int8(hidden)  # per-TOKEN: T rows, not T*K
            # out_dtype=dtype: the scaled epilogue stores bf16 directly —
            # writing f32 and casting after costs an extra [M, 2I] f32
            # round-trip (235 MB at Mixtral T=1024) for precision the
            # activation immediately discards
            h1 = gather_gmm(
                xq, inv_token, w_gate_up, group_sizes,
                xs[:, 0], w1_scale.reshape(num_experts, -1),
                variant=gather_variant, tm=tm1, tn=tn1, tk=tk1,
                out_dtype=dtype,
            )
            a = _act(h1, activation)
            aq, as_ = _quant_rows_int8(a)
            h2 = gmm(
                aq, w_down, group_sizes,
                as_[:, 0], w2_scale.reshape(num_experts, -1),
                tm=tm2, tn=tn2, tk=tk2,
            )
        else:
            h1 = gather_gmm(hidden, inv_token, w_gate_up, group_sizes,
                            variant=gather_variant, tm=tm1, tn=tn1, tk=tk1)
            a = _act(h1, activation)
            h2 = gmm(a, w_down, group_sizes, tm=tm2, tn=tn2, tk=tk2)
    elif quantized:
        assert w1_scale is not None and w2_scale is not None
        x_sorted = hidden[inv_token]  # [T*K, hidden]
        expert_sorted = flat_expert[order]  # [T*K]
        xq, xs = _quant_rows_int8(x_sorted)
        h1i = jax.lax.ragged_dot(
            xq, w_gate_up, group_sizes, preferred_element_type=jnp.int32
        )
        ws1 = w1_scale.reshape(num_experts, -1)[expert_sorted]  # [T*K, 2I]
        h1 = (h1i.astype(jnp.float32) * xs * ws1).astype(dtype)
        a = _act(h1, activation)
        aq, as_ = _quant_rows_int8(a)
        h2i = jax.lax.ragged_dot(
            aq, w_down, group_sizes, preferred_element_type=jnp.int32
        )
        ws2 = w2_scale.reshape(num_experts, -1)[expert_sorted]  # [T*K, H]
        h2 = h2i.astype(jnp.float32) * as_ * ws2
    else:
        x_sorted = hidden[inv_token]  # [T*K, hidden]
        h1 = jax.lax.ragged_dot(x_sorted, w_gate_up, group_sizes)  # [T*K, 2I]
        a = _act(h1, activation)
        h2 = jax.lax.ragged_dot(a, w_down, group_sizes)  # [T*K, hidden]

    # finalize: route each sorted row back to (token, choice) and weight-sum
    w_sorted = topk_weights.reshape(-1)[order].astype(jnp.float32)
    contrib = h2.astype(jnp.float32) * w_sorted[:, None]
    out = jnp.zeros((T, hidden.shape[1]), jnp.float32).at[inv_token].add(contrib)
    return out.astype(dtype)


def fused_moe_ep(
    hidden: jax.Array,  # [T_local, hidden] (this rank's tokens)
    w_gate_up: jax.Array,  # [E_local, hidden, 2*inter] (this rank's experts)
    w_down: jax.Array,  # [E_local, inter, hidden]
    topk_weights: jax.Array,  # [T_local, K]
    topk_ids: jax.Array,  # [T_local, K] GLOBAL expert ids
    num_experts: int,
    axis: str = "tp",
    activation: str = "silu",
    dispatch: str = "allgather",
    capacity_factor: float = 2.0,
    return_dropped: bool = False,
):
    """Expert-parallel fused MoE (call inside shard_map).

    Experts are contiguously sharded over ``axis`` (rank r owns
    ``[r*E_local, (r+1)*E_local)``, the Mapping.ep_experts partition).

    Three dispatch modes mirroring the reference moe_ep design space:
    - ``"allgather"``: all_gather tokens + psum_scatter combine — minimal
      latency at small world sizes, bandwidth O(T_global * hidden);
    - ``"alltoall"``: capacity-bucketed token exchange (the reference's
      split-mode NCCL/NIXL dispatch+combine as ``lax.all_to_all``) —
      bandwidth O(T_local * K * hidden), the scalable bounded-latency
      mode.  Tokens beyond ``capacity_factor * T_local * K / ep`` per
      destination are dropped (standard capacity semantics): a dropped
      (token, choice) route contributes ZERO to that token's output, so
      under-capacity routing silently degrades quality rather than
      erroring.
    - ``"alltoall_exact"``: NO-DROP token exchange — parity with the
      reference EP, which delivers every routed token by sizing NCCL
      transfers from an exchanged size tensor
      (moe_ep/modes/split_layer.py:52).  XLA buffers are static-shaped,
      so the TPU-native equivalent runs the same capacity-bucketed
      exchange in ROUNDS under a ``lax.while_loop`` whose trip count all
      ranks agree on via a pmax of the max destination load: balanced
      routing costs exactly one round (identical traffic to
      ``"alltoall"`` plus one scalar pmax), pathological routing costs
      extra rounds instead of dropped tokens.  Latency is data-dependent;
      use ``"alltoall"`` when bounded step time matters more than exact
      delivery.

    Mode selection is backed by the banked skew study (BENCH_BANKED.md
    round 5, `benchmarks/bench_ep_skew.py`): at balanced routing exact
    delivery is FREE (1 round, same bytes/time as capacity mode), so
    ``alltoall_exact`` is the right default for load-balanced routers;
    at zipf-1.5 skew capacity mode silently zeroes ~31% of routes while
    exact pays ~3 rounds (~2.5x step time, 3x bytes) — pick per your
    router's balance and step-time budget.  ``allgather`` stays the
    small-world/latency option (bandwidth O(T_global * hidden),
    skew-insensitive).

    With ``return_dropped=True`` returns ``(out, dropped)`` where
    ``dropped`` is a shape-``[1]`` int32 count of this rank's (token,
    choice) routes that exceeded a destination bucket — the observability
    hook for the capacity-drop semantics (reference analogue: per-split
    token accounting, moe_ep/modes/split_layer.py:52).  Shaped ``[1]`` so
    a shard_map ``out_specs=P(axis)`` concatenates it into per-rank
    counts.  Always 0 for ``"allgather"`` and ``"alltoall_exact"``
    (those modes never drop).
    """
    if dispatch == "allgather":
        ep = lax_axis_size(axis)
        rank = jax.lax.axis_index(axis)
        e_local = w_gate_up.shape[0]

        xg = jax.lax.all_gather(hidden, axis, tiled=True)  # [T_global, hidden]
        wg = jax.lax.all_gather(topk_weights, axis, tiled=True)
        idg = jax.lax.all_gather(topk_ids, axis, tiled=True)

        lo = rank * e_local
        local = (idg >= lo) & (idg < lo + e_local)
        # non-local choices route to a local dummy slot with zero weight
        ids_local = jnp.where(local, idg - lo, 0).astype(jnp.int32)
        w_local = jnp.where(local, wg, 0.0)

        partial = fused_moe(
            xg, w_gate_up, w_down, w_local, ids_local, e_local, activation
        )
        # combine: sum partials, then take this rank's token slice
        out = jax.lax.psum_scatter(partial, axis, tiled=True)
        return (out, jnp.zeros((1,), jnp.int32)) if return_dropped else out
    if dispatch == "alltoall":
        _record_ep_a2a_bytes(hidden, topk_ids, axis, capacity_factor,
                             dispatch)
        out, dropped = _fused_moe_ep_alltoall(
            hidden, w_gate_up, w_down, topk_weights, topk_ids, num_experts,
            axis, activation, capacity_factor,
        )
        # obs wiring for the capacity-drop semantics: a no-op while
        # `dropped` is a tracer (the shard_map/jit steady state — there
        # the caller reads it via return_dropped=True and may feed the
        # concrete per-rank counts to obs.record_dropped_tokens itself)
        from flashinfer_tpu import obs

        obs.record_dropped_tokens(dropped, dispatch)
        return (out, dropped) if return_dropped else out
    if dispatch == "alltoall_exact":
        _record_ep_a2a_bytes(hidden, topk_ids, axis, capacity_factor,
                             dispatch)
        out, dropped = _fused_moe_ep_alltoall_exact(
            hidden, w_gate_up, w_down, topk_weights, topk_ids, num_experts,
            axis, activation, capacity_factor,
        )
        return (out, dropped) if return_dropped else out
    raise ValueError(f"unknown dispatch {dispatch!r}")


def _record_ep_a2a_bytes(hidden, topk_ids, axis, capacity_factor,
                         dispatch: str) -> None:
    """Count this call site's all_to_all payload (dispatch + combine
    activation buffers, ``2 * ep * cap * H`` elements at the hidden
    dtype; eid/valid sideband excluded — noise against H-wide rows).

    Shapes are static even under trace, so this runs host-side at
    TRACE time: the counter is per-call traffic of the compiled
    program (per-ROUND for alltoall_exact, whose round count is
    data-dependent).  obs catalog ``moe.ep_a2a_bytes``; zero-overhead
    with the gate off (default, pinned)."""
    from flashinfer_tpu import obs

    if not obs.metrics_enabled():
        return
    ep = lax_axis_size(axis)
    if not isinstance(ep, int):  # outside shard_map (tests call eager)
        return
    T, K = topk_ids.shape
    cap = _bucket_capacity(T * K, ep, capacity_factor)
    nbytes = 2 * ep * cap * hidden.shape[1] * hidden.dtype.itemsize
    obs.counter_inc("moe.ep_a2a_bytes", int(nbytes), dispatch=dispatch)


def _bucket_capacity(routes: int, ep: int, capacity_factor: float) -> int:
    """Per-destination bucket capacity of the all_to_all dispatch —
    THE capacity rule (shared by :func:`_route_buckets` and the
    ``moe.ep_a2a_bytes`` telemetry so the counted buffer sizes can
    never drift from the exchanged ones)."""
    import math

    return max(1, int(math.ceil(routes / ep * capacity_factor)))


def _route_buckets(topk_ids, e_local, ep, capacity_factor):
    """Shared all_to_all routing prologue.

    Stable-sorts this rank's (token, choice) routes by destination rank
    and returns ``(cap, order, sd, stok, eid, within)``: the bucket
    capacity, the sort permutation, sorted destination ranks, source
    token of each sorted route, destination-LOCAL expert ids, and each
    route's rank within its destination bucket (the capacity/round
    coordinate).  Both the capacity-drop and the exact dispatch build on
    exactly this decomposition — keep them in lockstep.
    """
    T, K = topk_ids.shape
    TK = T * K
    cap = _bucket_capacity(TK, ep, capacity_factor)
    flat_ids = topk_ids.reshape(-1)
    dst = (flat_ids // e_local).astype(jnp.int32)
    order = jnp.argsort(dst, stable=True)
    sd = dst[order]  # sorted destinations
    stok = order // K  # source token of each sorted entry
    eid = (flat_ids[order] % e_local).astype(jnp.int32)
    # index within each destination bucket
    first = jnp.searchsorted(sd, sd, side="left")
    within = jnp.arange(TK) - first
    return cap, order, sd, stok, eid, within


def _fused_moe_ep_alltoall(
    hidden, w_gate_up, w_down, topk_weights, topk_ids, num_experts,
    axis, activation, capacity_factor,
):
    ep = lax_axis_size(axis)
    e_local = w_gate_up.shape[0]
    T, K = topk_ids.shape
    H = hidden.shape[1]
    TK = T * K
    cap, order, sd, stok, eid, within = _route_buckets(
        topk_ids, e_local, ep, capacity_factor
    )

    # capacity-bucketed send buffers; overflow (within >= cap) drops
    send_x = jnp.zeros((ep, cap, H), hidden.dtype).at[sd, within].set(
        hidden[stok], mode="drop"
    )
    send_eid = jnp.zeros((ep, cap), jnp.int32).at[sd, within].set(
        eid, mode="drop"
    )
    send_valid = jnp.zeros((ep, cap), jnp.float32).at[sd, within].set(
        1.0, mode="drop"
    )

    # dispatch: entry j of the received buffer came from rank j
    recv_x = jax.lax.all_to_all(send_x, axis, 0, 0)
    recv_eid = jax.lax.all_to_all(send_eid, axis, 0, 0)
    recv_valid = jax.lax.all_to_all(send_valid, axis, 0, 0)

    out = fused_moe(
        recv_x.reshape(ep * cap, H), w_gate_up, w_down,
        recv_valid.reshape(ep * cap, 1),  # weight 1 for valid, 0 for empty
        recv_eid.reshape(ep * cap, 1), e_local, activation,
    )

    # combine: send results back along the same routes
    back = jax.lax.all_to_all(out.reshape(ep, cap, H), axis, 0, 0)
    kept = (within < cap)[:, None].astype(jnp.float32)
    gathered = back[sd, jnp.minimum(within, cap - 1)] * kept  # sorted order
    contrib = jnp.zeros((TK, H), jnp.float32).at[order].set(
        gathered.astype(jnp.float32)
    )
    combined = (
        contrib.reshape(T, K, H)
        * topk_weights.astype(jnp.float32)[..., None]
    ).sum(1)
    dropped = jnp.sum((within >= cap).astype(jnp.int32)).reshape(1)
    return combined.astype(hidden.dtype), dropped


def _fused_moe_ep_alltoall_exact(
    hidden, w_gate_up, w_down, topk_weights, topk_ids, num_experts,
    axis, activation, capacity_factor,
):
    """Exact (no-drop) all_to_all EP dispatch: rounds under a while_loop.

    The reference sizes its dispatch transfer from an exchanged size
    tensor, so every routed token is delivered
    (moe_ep/modes/split_layer.py:52).  XLA cannot size a buffer
    dynamically, so delivery-exactness is bought with TIME instead of
    SHAPE: round ``r`` exchanges the routes whose per-destination rank
    ``within`` falls in ``[r*cap, (r+1)*cap)``, and the loop runs
    ``ceil(max destination load / cap)`` rounds — a traced scalar every
    rank derives from the same ``pmax``, keeping the SPMD program
    uniform.  Every (token, choice) route is exchanged in exactly one
    round, so the combined output is the same weighted sum the
    single-device oracle computes — bit-for-bit in f32 at K=2 (per-route
    expert rows are row-independent dots, and two-addend float sums are
    order-free); at K>2 the K-way addition order can differ from the
    oracle's expert-sorted scatter-add by an ulp.
    """
    ep = lax_axis_size(axis)
    e_local = w_gate_up.shape[0]
    T, K = topk_ids.shape
    H = hidden.shape[1]
    TK = T * K
    cap, order, sd, stok, eid_src, within = _route_buckets(
        topk_ids, e_local, ep, capacity_factor
    )

    # all ranks agree on the round count: ceil(max bucket load / cap)
    counts = jnp.bincount(sd, length=ep)
    rounds = jax.lax.pmax(
        ((counts.max() + cap - 1) // cap).astype(jnp.int32), axis
    )

    x_src = hidden[stok]  # [TK, H] route payloads, sorted order

    def round_body(state):
        r, contrib = state
        lo = r * cap
        in_round = (within >= lo) & (within < lo + cap)
        # routes outside this round park in a spill slot that the final
        # slice discards — keeps the scatter mask-free and in-bounds
        slot = jnp.where(in_round, within - lo, cap)
        send_x = (
            jnp.zeros((ep, cap + 1, H), hidden.dtype)
            .at[sd, slot].set(x_src)[:, :cap]
        )
        send_eid = (
            jnp.zeros((ep, cap + 1), jnp.int32)
            .at[sd, slot].set(eid_src)[:, :cap]
        )
        send_valid = (
            jnp.zeros((ep, cap + 1), jnp.float32)
            .at[sd, slot].set(in_round.astype(jnp.float32))[:, :cap]
        )

        recv_x = jax.lax.all_to_all(send_x, axis, 0, 0)
        recv_eid = jax.lax.all_to_all(send_eid, axis, 0, 0)
        recv_valid = jax.lax.all_to_all(send_valid, axis, 0, 0)

        out = fused_moe(
            recv_x.reshape(ep * cap, H), w_gate_up, w_down,
            recv_valid.reshape(ep * cap, 1),  # weight 1 valid, 0 empty
            recv_eid.reshape(ep * cap, 1), e_local, activation,
        )

        back = jax.lax.all_to_all(out.reshape(ep, cap, H), axis, 0, 0)
        got = back[sd, jnp.clip(within - lo, 0, cap - 1)]
        got = got * in_round[:, None].astype(got.dtype)
        return r + 1, contrib + got.astype(jnp.float32)

    _, contrib_sorted = jax.lax.while_loop(
        lambda s: s[0] < rounds,
        round_body,
        (jnp.int32(0), jnp.zeros((TK, H), jnp.float32)),
    )
    contrib = jnp.zeros((TK, H), jnp.float32).at[order].set(contrib_sorted)
    combined = (
        contrib.reshape(T, K, H)
        * topk_weights.astype(jnp.float32)[..., None]
    ).sum(1)
    return combined.astype(hidden.dtype), jnp.zeros((1,), jnp.int32)
