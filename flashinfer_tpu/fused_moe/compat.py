"""Reference ``flashinfer.fused_moe`` name surface beyond the core ops.

Three groups (cited: /root/reference/flashinfer/fused_moe/__init__.py):

- **config/runner records**: the reference wraps each CUDA backend in a
  Config + Runner pair dispatched by dtype/arch.  One TPU pipeline
  serves them all, so the classes are thin records whose ``run``
  delegates to :func:`fused_moe` — constructed-and-called reference
  code runs, with the numerics of the TPU path;
- **weight preprocessors**: SM90 TMA/WGMMA interleaves are CUDA layout
  prep — identity here (XLA owns layout);
- **real ops**: ``bgmv_moe`` (multi-LoRA MoE deltas, bgmv_moe.py:199 —
  implemented with gathers + small einsums; LoRA ranks are tiny so the
  MXU path is a gather-then-batched-matmul) and ``mono_moe``
  (monomoe.py:280 — single-kernel MoE == the fused pipeline with
  routing folded in).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from flashinfer_tpu.activation import silu_and_mul
from flashinfer_tpu.fused_moe.core import fused_moe
from flashinfer_tpu.fused_moe.routing import route_renormalize, route_topk

__all__ = [
    "ActivationConfig", "B12xNvfp4Config", "B12xNvfp4Runner",
    "B12xW4A16Config", "B12xW4A16Runner", "BackendOptions",
    "CuteDslConfig", "CuteDslNvfp4Runner", "CutlassConfig",
    "ExecutionConfig", "ExpertConfig", "Fp8QuantizationType",
    "MoEActivationPack", "MoELayer", "MoEWeightPack", "RoutingInputMode",
    "TrtllmBf16Config", "TrtllmFp4Config", "TrtllmFp4RoutedRunner",
    "TrtllmFp8BlockConfig", "TrtllmFp8BlockRunner",
    "TrtllmFp8PerTensorConfig", "TrtllmFp8PerTensorRunner",
    "TrtllmMxInt4Config", "WeightLayout", "alloc_scratchpad",
    "bgmv_moe", "bgmv_moe_expand", "bgmv_moe_gemm1_lora_delta",
    "bgmv_moe_gemm2_lora_delta", "bgmv_moe_shrink",
    "convert_to_block_layout", "cutlass_fused_moe_workspace_size",
    "fill_w_ptr", "get_scratchpad_size_bytes", "has_bgmv_moe",
    "has_monomoe", "hash_topk", "interleave_for_tma_wgmma_up",
    "interleave_moe_scales_for_sm90_mixed_gemm",
    "interleave_moe_weights_for_sm90_mixed_gemm", "mono_moe",
    "preprocess_moe_weights_for_sm90_mixed_gemm_humming",
]


# ---------------------------------------------------------------------------
# enums
# ---------------------------------------------------------------------------


class WeightLayout(enum.IntEnum):
    """Reference weight layouts; MajorK (logical [E, out, in]) is the one
    accepted layout on TPU (block-major is a CUDA swizzle)."""

    MajorK = 0
    MajorMn = 1
    BlockMajorK = 2


class Fp8QuantizationType(enum.IntEnum):
    DeepSeekFp8 = 0
    PerTensorFp8 = 1
    MxFp8 = 2


class RoutingInputMode(enum.IntEnum):
    """Routing input handed to the kernel: logits or pre-routed ids."""

    Logits = 0
    Routed = 1


# ---------------------------------------------------------------------------
# config / runner records
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ExpertConfig:
    # 0 = infer from the weight pack at run time (a non-zero value here
    # must match the weights; the runner's `or` fallback fires on 0)
    num_experts: int = 0
    top_k: int = 2
    intermediate_size: int = 0
    hidden_size: int = 0


@dataclasses.dataclass
class ActivationConfig:
    activation: str = "silu"


@dataclasses.dataclass
class ExecutionConfig:
    tune_max_num_tokens: int = 8192


@dataclasses.dataclass
class BackendOptions:
    backend: str = "auto"


@dataclasses.dataclass
class MoEWeightPack:
    gemm1: Any = None
    gemm2: Any = None
    gemm1_scale: Any = None
    gemm2_scale: Any = None


@dataclasses.dataclass
class MoEActivationPack:
    hidden_states: Any = None
    hidden_states_scale: Any = None


@dataclasses.dataclass
class _BackendConfig:
    """Base for the per-backend Config records (CutlassConfig etc.)."""

    expert: ExpertConfig = dataclasses.field(default_factory=ExpertConfig)
    activation: ActivationConfig = dataclasses.field(
        default_factory=ActivationConfig
    )
    weight_layout: int = WeightLayout.MajorK


class CutlassConfig(_BackendConfig):
    pass


class TrtllmBf16Config(_BackendConfig):
    pass


class TrtllmFp8BlockConfig(_BackendConfig):
    pass


class TrtllmFp8PerTensorConfig(_BackendConfig):
    pass


class TrtllmFp4Config(_BackendConfig):
    pass


class TrtllmMxInt4Config(_BackendConfig):
    pass


class B12xNvfp4Config(_BackendConfig):
    pass


class B12xW4A16Config(_BackendConfig):
    pass


class CuteDslConfig(_BackendConfig):
    pass


class _Runner:
    """Base runner: ``run(hidden, weights, topk_weights, topk_ids)`` on
    the one fused pipeline.  Backend-branded runners share it."""

    def __init__(self, config: Optional[_BackendConfig] = None, **_):
        self.config = config or _BackendConfig()

    def run(self, hidden, weights: MoEWeightPack, topk_weights, topk_ids,
            **kw):
        e = self.config.expert
        return fused_moe(
            hidden,
            jnp.swapaxes(jnp.asarray(weights.gemm1), 1, 2),
            jnp.swapaxes(jnp.asarray(weights.gemm2), 1, 2),
            topk_weights, topk_ids,
            e.num_experts or jnp.asarray(weights.gemm1).shape[0],
            activation=self.config.activation.activation, **kw,
        )

    __call__ = run


class TrtllmFp8BlockRunner(_Runner):
    pass


class TrtllmFp8PerTensorRunner(_Runner):
    pass


class TrtllmFp4RoutedRunner(_Runner):
    pass


class B12xNvfp4Runner(_Runner):
    pass


class B12xW4A16Runner(_Runner):
    pass


class CuteDslNvfp4Runner(_Runner):
    pass


class MoELayer(_Runner):
    """Reference MoELayer object form — the config-driven layer here is
    flashinfer_tpu.fused_moe.MoE; this record keeps the runner shape."""


# ---------------------------------------------------------------------------
# weight preprocessors / workspace sizers — identity / zero (XLA owns
# layout and scratch)
# ---------------------------------------------------------------------------


def convert_to_block_layout(w, *_, **__):
    return w


def interleave_for_tma_wgmma_up(w, *_, **__):
    return w


def interleave_moe_weights_for_sm90_mixed_gemm(w, *_, **__):
    return w


def interleave_moe_scales_for_sm90_mixed_gemm(s, *_, **__):
    return s


def preprocess_moe_weights_for_sm90_mixed_gemm_humming(w, *_, **__):
    return w


def fill_w_ptr(*_, **__):
    """Reference fills device pointer arrays for grouped GEMM batching;
    XLA addresses expert stacks directly."""
    return None


def alloc_scratchpad(*_, **__):
    return None


def get_scratchpad_size_bytes(*_, **__) -> int:
    return 0


def cutlass_fused_moe_workspace_size(*_, **__) -> int:
    return 0


def has_bgmv_moe() -> bool:
    return True


def has_monomoe() -> bool:
    return True


def hash_topk(topk_ids) -> int:
    """Stable content hash of a routing decision (reference hash_topk,
    used for cache keys / routing replay checks)."""
    import hashlib

    import numpy as np

    return int.from_bytes(
        hashlib.sha1(np.asarray(topk_ids).tobytes()).digest()[:8], "little"
    )


# ---------------------------------------------------------------------------
# bgmv: multi-LoRA MoE deltas (reference bgmv_moe.py)
# ---------------------------------------------------------------------------


def _slot_select(w, lora_idx, expert_idx):
    """Gather per-slot LoRA matrices: w [L, E, a, b] -> [M, a, b]."""
    return jnp.asarray(w)[lora_idx, expert_idx]


def bgmv_moe_shrink(x, lora_a_weights, sorted_token_ids, expert_ids,
                    lora_indices, **_unused):
    """LoRA-A projections per routed slot (reference bgmv_moe_shrink):
    for slot m -> ``x[token_m] @ A[lora_m, expert_m].T`` per slice.
    Returns a list of [M, rank] intermediates (one per slice)."""
    tok = jnp.asarray(sorted_token_ids, jnp.int32)
    e = jnp.asarray(expert_ids, jnp.int32)
    lora = jnp.asarray(lora_indices, jnp.int32)[tok]
    xs = jnp.asarray(x)[tok].astype(jnp.float32)  # [M, H]
    outs = []
    for a in (lora_a_weights if isinstance(lora_a_weights, (list, tuple))
              else [lora_a_weights]):
        A = _slot_select(a, lora, e).astype(jnp.float32)  # [M, r, H]
        outs.append(jnp.einsum("mh,mrh->mr", xs, A))
    return outs


def bgmv_moe_expand(intermediates, lora_b_weights, sorted_token_ids,
                    expert_ids, lora_indices, topk_weights,
                    num_tokens: Optional[int] = None, **_unused):
    """LoRA-B expansion + weighted scatter back to tokens (reference
    bgmv_moe_expand): slices concat on the output dim."""
    tok = jnp.asarray(sorted_token_ids, jnp.int32)
    e = jnp.asarray(expert_ids, jnp.int32)
    lora = jnp.asarray(lora_indices, jnp.int32)[tok]
    w = jnp.asarray(topk_weights, jnp.float32)
    # reference contract: PER-PAIR weights [num_pairs], aligned with the
    # slot schedule — a [T, K] routing matrix is only slot-aligned for
    # the token-major schedule, so anything non-1-D is rejected rather
    # than silently mis-scaled under a sorted schedule
    if w.ndim != 1:
        raise ValueError(
            "TPU backend: bgmv topk_weights must be per-pair [num_pairs] "
            "aligned with sorted_token_ids/expert_ids (reference "
            "bgmv_moe.py contract); reshape/gather your [T, K] routing "
            "weights into slot order first"
        )
    blist = (lora_b_weights if isinstance(lora_b_weights, (list, tuple))
             else [lora_b_weights])
    parts = []
    for h, b in zip(intermediates, blist):
        B = _slot_select(b, lora, e).astype(jnp.float32)  # [M, o, r]
        parts.append(jnp.einsum("mr,mor->mo", h, B))
    delta = jnp.concatenate(parts, axis=-1) * w.reshape(-1)[:, None]
    if num_tokens is None:
        # inferring from tok.max() breaks under jit and undersizes when
        # the highest-index tokens receive no slots — require it
        raise ValueError(
            "TPU backend: bgmv_moe_expand needs num_tokens= (the output "
            "row count cannot be inferred from the slot schedule)"
        )
    return jnp.zeros((int(num_tokens), delta.shape[-1]),
                     jnp.float32).at[tok].add(delta)


def bgmv_moe(x, lora_a_weights, lora_b_weights, sorted_token_ids,
             expert_ids, lora_indices, topk_weights, num_experts: int,
             output_dim: Optional[int] = None, **_unused):
    """Multi-LoRA MoE BGMV (reference bgmv_moe.py:199): the summed LoRA
    delta ``sum_k w_k * x @ A[e_k].T @ B[e_k].T`` per token, slices
    concatenated on the output dim."""
    hs = bgmv_moe_shrink(
        x, lora_a_weights, sorted_token_ids, expert_ids, lora_indices
    )
    out = bgmv_moe_expand(
        hs, lora_b_weights, sorted_token_ids, expert_ids, lora_indices,
        topk_weights, num_tokens=x.shape[0],
    )
    if output_dim is not None:
        out = out[:, :output_dim]
    return out.astype(jnp.asarray(x).dtype)


def bgmv_moe_gemm1_lora_delta(x, lora_a, lora_b, sorted_token_ids,
                              expert_ids, lora_indices, topk_weights,
                              num_experts: int, **kw):
    """gemm1 (gate_up) LoRA delta — bgmv over the first-GEMM slices."""
    return bgmv_moe(x, lora_a, lora_b, sorted_token_ids, expert_ids,
                    lora_indices, topk_weights, num_experts, **kw)


def bgmv_moe_gemm2_lora_delta(x, lora_a, lora_b, sorted_token_ids,
                              expert_ids, lora_indices, topk_weights,
                              num_experts: int, **kw):
    """gemm2 (down) LoRA delta."""
    return bgmv_moe(x, lora_a, lora_b, sorted_token_ids, expert_ids,
                    lora_indices, topk_weights, num_experts, **kw)


# ---------------------------------------------------------------------------
# mono_moe: single-kernel MoE (reference monomoe.py:280)
# ---------------------------------------------------------------------------


def _deinterleave_up(w):
    """SM90 monomoe interleaves gate/up columns; recover the [gate|up]
    halves silu_and_mul expects."""
    return jnp.concatenate([w[..., 0::2], w[..., 1::2]], axis=-1)


def mono_moe(
    activations_in, router_logits, expert_weights_up, expert_scales_up,
    expert_weights_down, expert_scales_down, top_k: int,
    scoring_func: str = "softmax", renormalize: bool = True,
    out=None, scratchpad=None, interleave_up: bool = True, **_unused,
):
    """Single-kernel MoE (reference mono_moe): routing + both grouped
    GEMMs in one call — which is exactly the fused pipeline.  Quantized
    expert weights (int8) ride the native int8 MXU path with their
    scales; float weights use bf16.  ``interleave_up`` de-interleaves
    the SM90 gate/up column layout."""
    if out is not None:
        raise ValueError(
            "TPU backend: mono_moe(out=...) is not supported — use the "
            "return value"
        )
    logits = jnp.asarray(router_logits, jnp.float32)
    if scoring_func == "softmax":
        wts, ids = (route_renormalize(logits, top_k) if renormalize
                    else route_topk(logits, top_k))
    elif scoring_func == "sigmoid":
        v, ids = jax.lax.top_k(jax.nn.sigmoid(logits), top_k)
        wts = (v / jnp.maximum(v.sum(-1, keepdims=True), 1e-20)
               if renormalize else v)
        ids = ids.astype(jnp.int32)
    else:
        raise ValueError(
            f"TPU backend: mono_moe scoring_func={scoring_func!r} not "
            "supported (softmax, sigmoid)"
        )
    w1 = jnp.asarray(expert_weights_up)
    w2 = jnp.asarray(expert_weights_down)
    # reference layout is output-major [E, out, in]
    if interleave_up:
        w1 = _deinterleave_up(jnp.swapaxes(w1, 1, 2))
    else:
        w1 = jnp.swapaxes(w1, 1, 2)
    w2 = jnp.swapaxes(w2, 1, 2)
    E = w1.shape[0]
    quantized = w1.dtype == jnp.int8
    if quantized:
        s1 = jnp.asarray(expert_scales_up, jnp.float32).reshape(E, 1, -1)
        if interleave_up and s1.shape[-1] == w1.shape[-1]:
            s1 = _deinterleave_up(s1)
        s2 = jnp.asarray(expert_scales_down, jnp.float32).reshape(E, 1, -1)
        return fused_moe(
            jnp.asarray(activations_in), w1, w2, wts, ids, E,
            w1_scale=s1, w2_scale=s2,
        )
    return fused_moe(jnp.asarray(activations_in), w1, w2, wts, ids, E)
