"""Fused Mixture-of-Experts subsystem.

TPU re-design of the reference MoE stack (``flashinfer/fused_moe/``,
SURVEY §2.3): routing methods (core RoutingMethodType surface), the fused
permute -> grouped-GEMM -> activation -> grouped-GEMM -> finalize pipeline
(``cutlass_fused_moe`` core.py:873), and expert parallelism (moe_ep).

TPU mapping: token permutation is an argsort, the grouped GEMMs are
``jax.lax.ragged_dot`` (megablox-style MXU grouped matmul), and EP
dispatch/combine are mesh collectives inside shard_map — the reference's
NCCL/NIXL device channels collapse into compiled ICI collectives.
"""

from flashinfer_tpu.fused_moe.routing import (  # noqa: F401
    RoutingMethodType,
    route_deepseek_v3,
    route_llama4,
    route_renormalize,
    route_topk,
)
from flashinfer_tpu.fused_moe.core import (  # noqa: F401
    fused_moe,
    fused_moe_ep,
)
from flashinfer_tpu.fused_moe.api import (  # noqa: F401
    MoE,
    MoEConfig,
    QuantConfig,
    QuantVariant,
    RoutingConfig,
)
from flashinfer_tpu.fused_moe.compat import *  # noqa: F401,F403
from flashinfer_tpu.fused_moe.compat import (  # noqa: F401
    MoEWeightPack,
    WeightLayout,
    bgmv_moe,
    mono_moe,
)
from flashinfer_tpu.dsv3_ops import fused_topk_deepseek  # noqa: F401
