"""MoE routing methods.

Re-design of the reference routing kernels (``flashinfer/fused_moe/
fused_routing_dsv3.py``, ``csrc/fused_moe/noAuxTcKernels.cu``,
RoutingMethodType in ``flashinfer/tllm_enums.py``): pure-XLA fused
softmax/sigmoid + top-k selections; each returns
``(topk_weights [T, K] f32, topk_ids [T, K] int32)``.
"""

from __future__ import annotations

import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class RoutingMethodType(enum.IntEnum):
    """Mirrors the reference enum (tllm_enums.py RoutingMethodType)."""

    Default = 0  # softmax -> topk
    Renormalize = 1  # topk -> softmax over the k
    DeepSeekV3 = 2  # sigmoid + bias, grouped top-k, renorm, scale
    Llama4 = 3  # top-1 sigmoid
    RenormalizeNaive = 4


@functools.partial(jax.jit, static_argnames=("top_k",))
def route_topk(logits: jax.Array, top_k: int) -> Tuple[jax.Array, jax.Array]:
    """Default: softmax over all experts, then top-k."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    w, ids = jax.lax.top_k(probs, top_k)
    return w, ids.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("top_k",))
def route_renormalize(logits: jax.Array, top_k: int) -> Tuple[jax.Array, jax.Array]:
    """Renormalize: top-k over logits, softmax over the selected k."""
    v, ids = jax.lax.top_k(logits.astype(jnp.float32), top_k)
    return jax.nn.softmax(v, axis=-1), ids.astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("top_k", "n_group", "topk_group", "routed_scaling_factor"),
)
def route_deepseek_v3(
    logits: jax.Array,  # [T, E]
    bias: jax.Array,  # [E] e_score_correction_bias
    top_k: int,
    n_group: int,
    topk_group: int,
    routed_scaling_factor: float = 1.0,
) -> Tuple[jax.Array, jax.Array]:
    """DeepSeek-V3 no-aux-loss routing (reference noAuxTcKernels.cu):
    sigmoid scores + correction bias; experts partitioned into ``n_group``
    groups; only the best ``topk_group`` groups (by sum of their top-2
    member scores) are eligible; final top-k over eligible experts; weights
    are the *unbiased* sigmoid scores renormalized and scaled."""
    T, E = logits.shape
    scores = jax.nn.sigmoid(logits.astype(jnp.float32))
    biased = scores + bias.astype(jnp.float32)[None, :]
    g = biased.reshape(T, n_group, E // n_group)
    # group score = sum of top-2 member scores
    top2 = jax.lax.top_k(g, 2)[0].sum(-1)  # [T, n_group]
    grp_kth = jax.lax.top_k(top2, topk_group)[0][:, -1:]
    grp_mask = top2 >= grp_kth  # [T, n_group]
    eligible = jnp.where(
        jnp.repeat(grp_mask, E // n_group, axis=-1), biased, -jnp.inf
    )
    _, ids = jax.lax.top_k(eligible, top_k)
    w = jnp.take_along_axis(scores, ids, axis=-1)
    w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-20)
    return w * routed_scaling_factor, ids.astype(jnp.int32)


@jax.jit
def route_llama4(logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Llama-4: top-1 expert, sigmoid gate weight."""
    v, ids = jax.lax.top_k(logits.astype(jnp.float32), 1)
    return jax.nn.sigmoid(v), ids.astype(jnp.int32)
