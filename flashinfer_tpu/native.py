"""Native planner loader: builds csrc/planner.cpp on first use (the TPU
analogue of the reference's JIT build layer, flashinfer/jit/core.py:225 —
cached .so, file lock, graceful Python fallback when a toolchain is
missing)."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

from flashinfer_tpu import env

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False

_SRC = Path(__file__).resolve().parent.parent / "csrc" / "planner.cpp"


def _build_and_load() -> Optional[ctypes.CDLL]:
    import logging

    cache = env.cache_dir() / "native"
    cache.mkdir(parents=True, exist_ok=True)
    so = cache / "libfi_planner.so"
    try:
        if (not so.exists()) or so.stat().st_mtime < _SRC.stat().st_mtime:
            # pid-unique tmp: concurrent cold-start builds each write their
            # own file; os.replace is atomic so whichever finishes last wins
            # with a complete .so
            tmp = so.with_suffix(f".so.tmp.{os.getpid()}")
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                 str(_SRC), "-o", str(tmp)],
                check=True, capture_output=True,
            )
            os.replace(tmp, so)
        lib = ctypes.CDLL(str(so))
        lib.decode_plan.restype = ctypes.c_int
        lib.token_axis_plan.restype = ctypes.c_int
        lib.paged_gather_plan.restype = ctypes.c_int
        lib.bsr_plan.restype = ctypes.c_int
        lib.prefill_mask_plan.restype = ctypes.c_int
        return lib
    except subprocess.CalledProcessError as e:
        logging.getLogger("flashinfer_tpu").warning(
            "native planner build failed (falling back to Python loops): %s",
            (e.stderr or b"").decode(errors="replace")[:500],
        )
        return None
    except Exception as e:
        logging.getLogger("flashinfer_tpu").warning(
            "native planner unavailable (falling back to Python loops): %r", e
        )
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The native planner library, or None (callers fall back to numpy)."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOCK:
        if not _TRIED:
            _LIB = _build_and_load()
            _TRIED = True
    return _LIB


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def decode_plan(
    indptr: np.ndarray, indices: np.ndarray, last_page_len: np.ndarray,
    page_size: int, b_bucket: int, p_bucket: int,
):
    """Padded page-table build; native when available, numpy otherwise.
    Returns (table [b_bucket, p_bucket] i32, kv_lens [b_bucket] i32)."""
    batch = len(indptr) - 1
    indptr = np.ascontiguousarray(indptr, np.int32)
    indices = np.ascontiguousarray(indices, np.int32)
    last_page_len = np.ascontiguousarray(last_page_len, np.int32)
    table = np.zeros((b_bucket, p_bucket), np.int32)
    kv_lens = np.zeros((b_bucket,), np.int32)
    lib = get_lib()
    if lib is not None:
        rc = lib.decode_plan(
            _ptr(indptr), _ptr(indices), _ptr(last_page_len),
            batch, len(indices), page_size, b_bucket, p_bucket,
            _ptr(table), _ptr(kv_lens),
        )
        if rc == 0:
            return table, kv_lens
        if rc == -2:
            raise ValueError("decode_plan: indptr inconsistent with indices")
        raise ValueError(
            f"decode_plan: geometry exceeds buckets "
            f"(batch {batch} > {b_bucket} or pages > {p_bucket})"
        )
    for b in range(batch):
        n = int(indptr[b + 1] - indptr[b])
        table[b, :n] = indices[int(indptr[b]) : int(indptr[b]) + n]
        kv_lens[b] = (n - 1) * page_size + int(last_page_len[b]) if n else 0
    return table, kv_lens


def token_axis_plan(
    indptr: np.ndarray, pos_offset: np.ndarray, pad_to: int, pad_seg: int,
):
    """Flatten ragged requests onto a padded token axis -> (seg, pos)."""
    batch = len(indptr) - 1
    indptr64 = np.ascontiguousarray(indptr, np.int64)
    off64 = np.ascontiguousarray(pos_offset, np.int64)
    seg = np.empty((pad_to,), np.int32)
    pos = np.empty((pad_to,), np.int32)
    lib = get_lib()
    if lib is not None:
        rc = lib.token_axis_plan(
            _ptr(indptr64), _ptr(off64), batch, pad_to, pad_seg,
            _ptr(seg), _ptr(pos),
        )
        if rc == 0:
            return seg, pos
        if rc == -2:
            raise ValueError("token_axis_plan: non-monotonic or negative indptr")
        raise ValueError(f"token_axis_plan: {indptr64[-1]} tokens > pad {pad_to}")
    seg.fill(pad_seg)
    pos.fill(0)
    for r in range(batch):
        s, e = int(indptr64[r]), int(indptr64[r + 1])
        if s < 0 or e < s or e > pad_to:
            raise ValueError("token_axis_plan: non-monotonic or negative indptr")
        seg[s:e] = r
        pos[s:e] = np.arange(e - s) + int(off64[r])
    return seg, pos


def paged_gather_plan(
    kv_tok_indptr: np.ndarray, page_indptr: np.ndarray,
    page_indices: np.ndarray, page_size: int, pad_to: int,
):
    """Flat cache-row ids per kv token -> rows [pad_to] i32."""
    batch = len(page_indptr) - 1
    tok64 = np.ascontiguousarray(kv_tok_indptr, np.int64)
    pip = np.ascontiguousarray(page_indptr, np.int32)
    pidx = np.ascontiguousarray(page_indices, np.int32)
    rows = np.zeros((pad_to,), np.int32)
    lib = get_lib()
    if lib is not None:
        rc = lib.paged_gather_plan(
            _ptr(tok64), _ptr(pip), _ptr(pidx), batch, len(pidx), page_size,
            pad_to, _ptr(rows),
        )
        if rc == 0:
            return rows
        if rc == -2:
            raise ValueError(
                "paged_gather_plan: kv lengths inconsistent with page lists"
            )
        raise ValueError("paged_gather_plan: tokens exceed pad")
    for r in range(batch):
        s = int(tok64[r])
        n = int(tok64[r + 1] - s)
        # mirror the native path's per-request validation
        if n < 0 or s < 0 or s + n > pad_to:
            raise ValueError(
                "paged_gather_plan: kv lengths inconsistent with page lists"
            )
        pages = pidx[int(pip[r]) : int(pip[r + 1])]
        npages_needed = (n - 1) // page_size + 1 if n > 0 else 0
        if npages_needed > len(pages):
            raise ValueError(
                "paged_gather_plan: kv lengths inconsistent with page lists"
            )
        tok = np.arange(n)
        rows[s : s + n] = pages[tok // page_size] * page_size + tok % page_size
    return rows


def bsr_plan(indptr: np.ndarray, indices: np.ndarray, max_nnz: int):
    """Pad BSR per-row column lists -> cols [MB * max_nnz] i32."""
    mb = len(indptr) - 1
    ip = np.ascontiguousarray(indptr, np.int32)
    idx = np.ascontiguousarray(indices, np.int32)
    cols = np.zeros((mb * max_nnz,), np.int32)
    lib = get_lib()
    if lib is not None:
        rc = lib.bsr_plan(_ptr(ip), _ptr(idx), mb, len(idx), max_nnz, _ptr(cols))
        if rc == 0:
            return cols
        raise ValueError(
            "bsr_plan: invalid BSR structure (non-monotonic indptr, nnz > "
            "max_nnz, or indices out of bounds)"
        )
    for i in range(mb):
        n = int(ip[i + 1] - ip[i])
        cols[i * max_nnz : i * max_nnz + n] = idx[int(ip[i]) : int(ip[i]) + n]
    return cols


def prefill_mask_plan(
    mask_bits: np.ndarray,  # bool flat bits OR uint8 LSB-first packed bytes
    total_bits: int,
    qo_indptr: np.ndarray,  # [B+1]
    kv_lens: np.ndarray,  # [B]
    block_q: int,
    chunk_tokens: int,
    mb: int,
    num_units: int,
) -> np.ndarray:
    """Per-unit packed custom-mask bitmaps for the fused prefill kernel
    -> uint8 [num_units, block_q, mb].

    ``mask_bits`` may be the raw LSB-first packed bytes straight from the
    caller's ``packed_custom_mask`` (no unpack/repack round trip on the
    hottest host-plan loop) or a flat bool array.  Raises when the native
    library is unavailable — callers gate on :func:`get_lib` and keep
    their numpy loop for the fallback (unlike the other wrappers here,
    the fallback logic lives with the unit builder, so a silent None
    would risk a mask-less plan)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "prefill_mask_plan: native planner unavailable "
            "(gate on native.get_lib() and use the numpy path)"
        )
    if mask_bits.dtype == np.uint8:
        bits = np.ascontiguousarray(mask_bits.reshape(-1))
    else:
        bits = np.packbits(
            np.ascontiguousarray(mask_bits, bool), bitorder="little"
        )
    if bits.size * 8 < total_bits:
        raise ValueError(
            f"prefill_mask_plan: {bits.size * 8} packed bits < {total_bits}"
        )
    qip = np.ascontiguousarray(qo_indptr, np.int64)
    kvl = np.ascontiguousarray(kv_lens, np.int64)
    out = np.zeros((num_units, block_q, mb), np.uint8)
    rc = lib.prefill_mask_plan(
        _ptr(bits), _ptr(qip), _ptr(kvl), len(qip) - 1,
        block_q, chunk_tokens, mb,
        ctypes.c_int64(int(total_bits)), ctypes.c_int64(num_units),
        _ptr(out),
    )
    if rc == 0:
        return out
    raise ValueError(f"prefill_mask_plan: rc={rc} (geometry mismatch)")
