"""Holistic mixed-batch attention, POD, and attention sinks.

TPU re-design of the reference's unified-attention layer:

- ``BatchAttention`` (reference ``flashinfer/attention/_core.py:44``): one
  wrapper serving a mixed prefill+decode batch.  The reference needs a
  two-stage cost-balanced plan (``TwoStageHolisticPlan`` scheduler.cuh:1241
  with a MinHeap) and a persistent kernel (persistent.cuh:682) to keep SMs
  busy; on TPU the segment flash kernel already *is* holistic — all
  requests (1-token decodes and long prefills alike) live on one flattened
  token axis, and a decode-heavy batch degenerates to "one q block reads
  each kv block once", which is the bandwidth-optimal schedule.  So this
  wrapper is the paged-prefill plan/run surface under the holistic name.

- ``PODWithPagedKVCacheWrapper`` (reference pod.py:61): Prefill-On-Decode
  fuses prefill and decode CTAs into one kernel for the same reason; on TPU
  it aliases the holistic path (documented design decision, SURVEY §7
  step 3).

- Attention sinks (reference ``BatchAttentionWithAttentionSinkWrapper``,
  attention/_core.py:330; StreamingLLM): a per-head learnable sink logit
  joins the softmax denominator.  With the (out, lse) pair this is a pure
  epilogue: ``out * exp(lse) / (exp(lse) + exp(sink))`` — the LSE algebra
  again, no kernel change.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from flashinfer_tpu.prefill import BatchPrefillWithPagedKVCacheWrapper


class BatchAttention(BatchPrefillWithPagedKVCacheWrapper):
    """Holistic mixed prefill+decode attention (reference
    flashinfer/attention/_core.py:44).  plan() takes the same geometry as
    the reference: per-request qo lens may mix 1 (decode) and many
    (prefill/append)."""

    def plan(
        self,
        qo_indptr,
        kv_indptr,
        kv_indices,
        kv_len_arr,
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        page_size: int,
        causal: bool = True,
        sm_scale: Optional[float] = None,
        logits_soft_cap: Optional[float] = None,
        window_left: int = -1,
        q_data_type=jnp.bfloat16,
        kv_data_type=None,
        use_profiler: bool = False,
        **_unused,
    ) -> None:
        import numpy as np

        kv_len_arr = np.asarray(kv_len_arr)
        kv_indptr = np.asarray(kv_indptr)
        pages_per_req = kv_indptr[1:] - kv_indptr[:-1]
        # reconstruct last_page_len from token lengths
        last = kv_len_arr - (np.maximum(pages_per_req, 1) - 1) * page_size
        super().plan(
            qo_indptr, kv_indptr, kv_indices, last.astype(np.int32),
            num_qo_heads, num_kv_heads, head_dim, page_size,
            causal=causal, sm_scale=sm_scale,
            logits_soft_cap=logits_soft_cap, window_left=window_left,
            q_data_type=q_data_type, kv_data_type=kv_data_type,
        )

    def run(self, q, paged_kv_cache, *, out=None, lse=None, return_lse=False,
            **kw):
        return super().run(q, paged_kv_cache, return_lse=return_lse, **kw)


class PODWithPagedKVCacheWrapper(BatchAttention):
    """Prefill-On-Decode (reference flashinfer/pod.py:61).  On TPU the
    holistic segment kernel already co-schedules prefill and decode work;
    this class exists for API parity and routes to BatchAttention."""


@jax.jit
def apply_attention_sink(
    out: jax.Array,  # [total_q, num_heads, head_dim]
    lse: jax.Array,  # [total_q, num_heads] natural-log LSE
    sink: jax.Array,  # [num_heads] per-head sink logits
) -> jax.Array:
    """Renormalize attention output as if a zero-value sink token with logit
    ``sink[h]`` participated in the softmax (StreamingLLM epilogue)."""
    lse32 = lse.astype(jnp.float32)
    sink32 = sink.astype(jnp.float32)[None, :]
    m = jnp.maximum(lse32, sink32)
    denom = jnp.exp(lse32 - m) + jnp.exp(sink32 - m)
    scale = jnp.exp(lse32 - m) / denom
    return (out.astype(jnp.float32) * scale[..., None]).astype(out.dtype)


class BatchAttentionWithAttentionSinkWrapper(BatchAttention):
    """Holistic attention + sink epilogue (reference attention/_core.py:330)."""

    def __init__(self, *args, sink: Optional[jax.Array] = None, **kw):
        super().__init__(*args, **kw)
        self._sink = sink

    def set_sink(self, sink: jax.Array) -> None:
        self._sink = sink

    def run(self, q, paged_kv_cache, *, sink: Optional[jax.Array] = None,
            return_lse: bool = False, **kw):
        s = sink if sink is not None else self._sink
        if s is None:
            raise ValueError("attention sink logits not provided")
        out, lse = super().run(q, paged_kv_cache, return_lse=True, **kw)
        out = apply_attention_sink(out, lse, s)
        if return_lse:
            # combined lse includes the sink term
            lse_new = jnp.logaddexp(lse, jnp.broadcast_to(
                s.astype(jnp.float32)[None, :], lse.shape))
            return out, lse_new
        return out
