"""Holistic mixed-batch attention, POD, and attention sinks.

TPU re-design of the reference's unified-attention layer:

- ``BatchAttention`` (reference ``flashinfer/attention/_core.py:44``): one
  wrapper serving a mixed prefill+decode batch.  The reference needs a
  two-stage cost-balanced plan (``TwoStageHolisticPlan`` scheduler.cuh:1241
  with a MinHeap) and a persistent kernel (persistent.cuh:682) to keep SMs
  busy; on TPU the segment flash kernel already *is* holistic — all
  requests (1-token decodes and long prefills alike) live on one flattened
  token axis, and a decode-heavy batch degenerates to "one q block reads
  each kv block once", which is the bandwidth-optimal schedule.  So this
  wrapper is the paged-prefill plan/run surface under the holistic name.

- ``PODWithPagedKVCacheWrapper`` (reference pod.py:61): Prefill-On-Decode
  fuses prefill and decode CTAs into one kernel for the same reason; on TPU
  it aliases the holistic path (documented design decision, SURVEY §7
  step 3).

- Attention sinks (reference ``BatchAttentionWithAttentionSinkWrapper``,
  attention/_core.py:330; StreamingLLM): a per-head learnable sink logit
  joins the softmax denominator.  With the (out, lse) pair this is a pure
  epilogue: ``out * exp(lse) / (exp(lse) + exp(sink))`` — the LSE algebra
  again, no kernel change.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from flashinfer_tpu.prefill import BatchPrefillWithPagedKVCacheWrapper


class BatchAttention(BatchPrefillWithPagedKVCacheWrapper):
    """Holistic mixed prefill+decode attention (reference
    flashinfer/attention/_core.py:44).  plan() takes the same geometry as
    the reference: per-request qo lens may mix 1 (decode) and many
    (prefill/append).

    The inherited ``plan_arrays`` export is how the compile-once mixed
    serving step (``flashinfer_tpu.serve.step.MixedServingStep``)
    closes this wrapper's frozen holistic plan — token axes, gather
    rows, attention statics — into its single donated-buffer XLA
    program (the ``TwoStageHolisticPlan``/persistent-kernel analog)."""

    def plan(
        self,
        qo_indptr,
        kv_indptr,
        kv_indices,
        kv_len_arr,
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim_qk: int,
        head_dim_vo: int,
        page_size: int,
        causal: bool = False,
        sm_scale: Optional[float] = None,
        logits_soft_cap: Optional[float] = None,
        *,
        window_left: int = -1,
        q_data_type=jnp.bfloat16,
        kv_data_type=None,
        use_profiler: bool = False,
        **_unused,
    ) -> None:
        """Reference arity (attention/_core.py:95): both head dims are
        positional (DeepSeek-style qk 192 / vo 128 splits exist there);
        this build's paged path is square — asymmetric dims raise with
        the MLA alternative.

        ``window_left`` (a TPU-port extension) and everything after it
        are KEYWORD-ONLY: the reference plan has no window_left between
        logits_soft_cap and q_data_type, so a verbatim reference caller
        passing the dtypes positionally would silently bind a dtype
        into window_left (ADVICE.md round-5 item 2).  Reference
        positional calls past logits_soft_cap now raise TypeError —
        loud, never misbound.  The reference arity is recorded in the
        L002 signature bank (analysis/reference_signatures.json)."""
        import numpy as np

        if head_dim_qk != head_dim_vo:
            raise NotImplementedError(
                f"asymmetric head dims (qk {head_dim_qk} != vo "
                f"{head_dim_vo}) — use flashinfer_tpu.mla for the "
                "compressed-KV DeepSeek form")
        kv_len_arr = np.asarray(kv_len_arr)
        kv_indptr = np.asarray(kv_indptr)
        pages_per_req = kv_indptr[1:] - kv_indptr[:-1]
        # reconstruct last_page_len from token lengths
        last = kv_len_arr - (np.maximum(pages_per_req, 1) - 1) * page_size
        super().plan(
            qo_indptr, kv_indptr, kv_indices, last.astype(np.int32),
            num_qo_heads, num_kv_heads, head_dim_qk, page_size,
            causal=causal, sm_scale=sm_scale,
            logits_soft_cap=logits_soft_cap, window_left=window_left,
            q_data_type=q_data_type, kv_data_type=kv_data_type,
        )
        # record of the PLANNED cap (run() no longer validates against
        # it — a differing per-run cap rebinds the frozen plan instead);
        # set only once the plan is actually live so a failed re-plan
        # cannot desync it from the still-active previous plan
        self._plan_soft_cap = float(logits_soft_cap or 0.0)

    def run(self, q, paged_kv_cache, out=None, lse=None, k_scale=None,
            v_scale=None, logits_soft_cap: float = 0.0,
            profiler_buffer=None, *, kv_cache_sf=None, **kw):
        """Reference contract (attention/_core.py:216): ALWAYS returns
        ``(out, lse)``; ``k_scale`` folds into sm_scale for this call,
        ``v_scale`` scales the output.  ``logits_soft_cap``: the 0.0
        default INHERITS the planned cap; a non-zero value takes effect
        FOR THIS CALL (the reference forwards the run value to the
        kernel, attention/_core.py:250) — a value differing from the
        planned one rebinds the frozen plan for the call, the same
        mechanism as the per-run sm_scale rebind (a novel cap compiles
        a fresh kernel variant; counted via plan.soft_cap_rebinds).
        ``profiler_buffer`` is inert (op timelines come from
        flashinfer_tpu.profiler); ``out``/``lse``/``kv_cache_sf``
        prealloc/fp8-sf are rejected loudly; the scale/epilogue
        mechanics live in the base paged wrapper's run (one copy)."""
        if kv_cache_sf is not None:
            raise NotImplementedError(
                "kv_cache_sf fp8 scale factors: quantize the cache via "
                "flashinfer_tpu.page append helpers instead")
        if "return_lse" in kw:
            if not kw.pop("return_lse"):
                raise ValueError(
                    "BatchAttention.run always returns (out, lse) "
                    "(reference attention/_core.py:216); return_lse="
                    "False is not available — drop the kwarg")
        soft_cap = float(logits_soft_cap or 0.0)
        restore_plan = None
        if soft_cap != 0.0:
            # ADVICE r5 item 3: the verbatim reference caller varies the
            # cap per run; honor it instead of raising on the mismatch
            restore_plan = self._rebind_soft_cap(soft_cap)
        try:
            return super().run(
                q, paged_kv_cache, out=out, lse=lse, k_scale=k_scale,
                v_scale=v_scale, return_lse=True, **kw)
        finally:
            if restore_plan is not None:
                self._plan = restore_plan

    # rebind: the paged base class set `forward = run` to ITS run at
    # class-definition time; without this, forward() would skip the
    # (out, lse) holistic contract above (L001; ADVICE.md round-5 item 1)
    forward = run


class PODWithPagedKVCacheWrapper(BatchAttention):
    """Prefill-On-Decode (reference flashinfer/pod.py:61).  On TPU the
    holistic segment kernel already co-schedules prefill and decode work;
    this class exists for API parity and routes to BatchAttention (the
    reference POD run signature with separate prefill/decode operand
    sets is a CUDA-stream concept — documented alias, single-output
    run)."""

    def run(self, q, paged_kv_cache, *, return_lse: bool = False, **kw):
        out, lse = super().run(q, paged_kv_cache, **kw)
        return (out, lse) if return_lse else out

    # rebind so forward() honors THIS run's single-output contract
    # rather than the alias inherited from BatchAttention (L001)
    forward = run


def sink_epilogue(out, lse, sink, return_lse: bool):
    """Shared sink epilogue: renormalized output, and (optionally) the
    combined lse including the sink term — the ONE copy of this algebra
    (used by both the paged sink wrapper and the ragged custom-variant
    path)."""
    sink = jnp.asarray(sink)
    out = apply_attention_sink(out, lse, sink)
    if return_lse:
        lse_new = jnp.logaddexp(lse, jnp.broadcast_to(
            sink.astype(jnp.float32)[None, :], lse.shape))
        return out, lse_new
    return out


@jax.jit
def apply_attention_sink(
    out: jax.Array,  # [total_q, num_heads, head_dim]
    lse: jax.Array,  # [total_q, num_heads] natural-log LSE
    sink: jax.Array,  # [num_heads] per-head sink logits
) -> jax.Array:
    """Renormalize attention output as if a zero-value sink token with logit
    ``sink[h]`` participated in the softmax (StreamingLLM epilogue)."""
    lse32 = lse.astype(jnp.float32)
    sink32 = sink.astype(jnp.float32)[None, :]
    m = jnp.maximum(lse32, sink32)
    denom = jnp.exp(lse32 - m) + jnp.exp(sink32 - m)
    scale = jnp.exp(lse32 - m) / denom
    return (out.astype(jnp.float32) * scale[..., None]).astype(out.dtype)


class BatchAttentionWithAttentionSinkWrapper(
        BatchPrefillWithPagedKVCacheWrapper):
    """Paged attention + sink epilogue (reference attention/_core.py:330).

    Matches the reference's contract exactly: the class derives from the
    PAGED PREFILL wrapper (its plan's 4th positional is
    ``paged_kv_last_page_len``, NOT token lengths), the ctor accepts the
    reference kwargs (``q_data_type``/``kv_data_type``/``head_dim_qk``/
    ``head_dim_vo``/``window_left`` — window_left from the ctor is the
    plan default), and ``run`` accepts the custom-variant POSITIONAL
    extras in declared order: ``run(q, paged_kv_cache, sink, sm_scale)``
    (jit_args additional_tensor_names=["sink"],
    additional_scalar_names=["sm_scale"]).  A per-run ``sm_scale``
    rebinds the planned scale exactly (frozen-plan replace), mirroring
    the reference kernel's per-call scalar."""

    def __init__(self, float_workspace_buffer=None, kv_layout: str = "NHD",
                 use_cuda_graph: bool = False, backend: str = "auto",
                 q_data_type=None, kv_data_type=None,
                 head_dim_qk: int = 128, head_dim_vo: int = 128,
                 window_left: int = -1,
                 sink: Optional[jax.Array] = None, **kw):
        super().__init__(float_workspace_buffer, kv_layout, use_cuda_graph,
                         backend, **kw)
        self._sink = sink
        self._ctor_window_left = int(window_left)

    def set_sink(self, sink: jax.Array) -> None:
        self._sink = sink

    def plan(self, *args, window_left: Optional[int] = None, **kw):
        if window_left is None:
            window_left = self._ctor_window_left
        return super().plan(*args, window_left=window_left, **kw)

    def run(self, q, paged_kv_cache, *extra,
            sink: Optional[jax.Array] = None, sm_scale=None,
            out=None, lse=None, return_lse: bool = False, **kw):
        if extra:
            if sink is None:
                sink = extra[0]
            if len(extra) > 1 and sm_scale is None:
                sm_scale = extra[1]
            if len(extra) > 2:
                raise TypeError(
                    f"run() takes at most (sink, sm_scale) positional "
                    f"extras, got {len(extra)}")
        if out is not None or lse is not None:
            raise NotImplementedError(
                "pre-allocated out=/lse= buffers are not supported (XLA "
                "owns buffers; docs/migration.md) — drop the kwargs and "
                "use the returned arrays")
        s = sink if sink is not None else self._sink
        if s is None:
            raise ValueError("attention sink logits not provided")
        # per-call sm_scale (reference run scalar): the shared rebind
        # helper + the lazy-rebuild carry-over keep it alive on any path
        restore_plan = self._rebind_sm_scale(absolute=sm_scale)
        try:
            o, l = super().run(q, paged_kv_cache, return_lse=True, **kw)
        finally:
            if restore_plan is not None:
                self._plan = restore_plan
        return sink_epilogue(o, l, s, return_lse)

    # rebind: the base paged wrapper's `forward = run` alias was bound
    # to the BASE run at class-definition time — inherited as-is it
    # would silently skip the sink epilogue above (wrong numerics, no
    # error; the reference's deprecated forward dispatches through
    # self.run virtually, so ITS sink wrapper does apply the sink).
    # This was ADVICE.md round-5 item 1 / the motivating L001 shape.
    forward = run
