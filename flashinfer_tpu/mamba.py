"""Mamba/SSM ops: selective state update (decode) + selective scan (prefill).

TPU re-design of the reference Mamba family (``flashinfer/mamba/``,
``csrc/selective_state_update.cu``, ``include/flashinfer/mamba/``):

- ``selective_state_update``: one-token SSM state recurrence used at decode
  time (supports GQA-style head broadcast of B/C groups, dt bias/softplus,
  D skip and z gating — the reference kernel's surface).
- ``selective_scan``: sequential prefill scan (lax.scan over time — XLA
  keeps the recurrence on-chip; the reference's chunked SSD kernel is a
  planned optimization, the semantics here are the oracle).

Functional: state tensors are returned, not mutated (donation makes this
in-place under jit).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _softplus(x):
    return jnp.logaddexp(x, 0.0)


@functools.partial(jax.jit, static_argnames=("dt_softplus",))
def selective_state_update(
    state: jax.Array,  # [B, H, dim, dstate]
    x: jax.Array,  # [B, H, dim]
    dt: jax.Array,  # [B, H, dim]
    A: jax.Array,  # [H, dim, dstate]
    B: jax.Array,  # [B, G, dstate]  (G divides H)
    C: jax.Array,  # [B, G, dstate]
    D: Optional[jax.Array] = None,  # [H, dim]
    z: Optional[jax.Array] = None,  # [B, H, dim]
    dt_bias: Optional[jax.Array] = None,  # [H, dim]
    dt_softplus: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One SSM decode step -> (y [B, H, dim], new_state).

    Recurrence (reference selective_state_update.cu):
        dt' = softplus(dt + dt_bias)              (if enabled)
        state' = state * exp(dt' * A) + dt' * x (outer) B
        y = (state' . C) + D * x, gated by silu(z).
    """
    Bsz, H, dim = x.shape
    G = B.shape[1]
    rep = H // G
    dtf = dt.astype(jnp.float32)
    if dt_bias is not None:
        dtf = dtf + dt_bias.astype(jnp.float32)[None]
    if dt_softplus:
        dtf = _softplus(dtf)
    xf = x.astype(jnp.float32)
    Af = A.astype(jnp.float32)[None]  # [1, H, dim, dstate]
    Bf = jnp.repeat(B.astype(jnp.float32), rep, axis=1)  # [B, H, dstate]
    Cf = jnp.repeat(C.astype(jnp.float32), rep, axis=1)
    dA = jnp.exp(dtf[..., None] * Af)  # [B, H, dim, dstate]
    dBx = (dtf * xf)[..., None] * Bf[:, :, None, :]  # [B, H, dim, dstate]
    new_state = state.astype(jnp.float32) * dA + dBx
    y = jnp.einsum("bhds,bhs->bhd", new_state, Cf)
    if D is not None:
        y = y + D.astype(jnp.float32)[None] * xf
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    return y.astype(x.dtype), new_state.astype(state.dtype)


@functools.partial(jax.jit, static_argnames=("dt_softplus",))
def selective_scan(
    x: jax.Array,  # [B, L, H, dim]
    dt: jax.Array,  # [B, L, H, dim]
    A: jax.Array,  # [H, dim, dstate]
    B: jax.Array,  # [B, L, G, dstate]
    C: jax.Array,  # [B, L, G, dstate]
    D: Optional[jax.Array] = None,
    z: Optional[jax.Array] = None,  # [B, L, H, dim]
    dt_bias: Optional[jax.Array] = None,
    dt_softplus: bool = False,
    initial_state: Optional[jax.Array] = None,  # [B, H, dim, dstate]
) -> Tuple[jax.Array, jax.Array]:
    """Prefill scan -> (y [B, L, H, dim], final_state)."""
    Bsz, L, H, dim = x.shape
    dstate = A.shape[-1]
    if initial_state is None:
        initial_state = jnp.zeros((Bsz, H, dim, dstate), jnp.float32)

    def step(state, inp):
        xt, dtt, Bt, Ct, zt = inp
        y, state = selective_state_update(
            state, xt, dtt, A, Bt, Ct, D,
            zt if z is not None else None,
            dt_bias, dt_softplus,
        )
        return state, y

    zs = (
        jnp.moveaxis(z, 1, 0)
        if z is not None
        else jnp.zeros((L,) + x.shape[:1] + x.shape[2:], x.dtype)
    )
    final, ys = jax.lax.scan(
        step,
        initial_state.astype(jnp.float32),
        (
            jnp.moveaxis(x, 1, 0), jnp.moveaxis(dt, 1, 0),
            jnp.moveaxis(B, 1, 0), jnp.moveaxis(C, 1, 0), zs,
        ),
    )
    return jnp.moveaxis(ys, 0, 1), final.astype(jnp.float32)


def mamba_chunk_scan_combined(
    x: jax.Array,  # [B, L, H, dim]
    dt: jax.Array,  # [B, L, H]  (scalar per head/step — Mamba-2/SSD form)
    A: jax.Array,  # [H] negative decay rates
    B: jax.Array,  # [B, L, G, dstate]
    C: jax.Array,  # [B, L, G, dstate]
    chunk_size: int = 64,
    D: Optional[jax.Array] = None,  # [H]
    z: Optional[jax.Array] = None,  # [B, L, H, dim]
    dt_bias: Optional[jax.Array] = None,  # [H]
    dt_softplus: bool = False,  # matches selective_scan + reference default
    initial_state: Optional[jax.Array] = None,  # [B, H, dim, dstate]
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Chunked SSD scan (Mamba-2; reference ``mamba_chunk_scan_combined``
    family, flashinfer/mamba/ SSD combined/chunked scan).

    ``backend="pallas"`` (or env ``FLASHINFER_TPU_MAMBA_BACKEND=pallas``)
    routes to the fused VMEM-resident kernel (``ops/mamba_kernel.py``,
    chunk 128); env-selected auto falls back here on ineligible shapes.
    ``"auto"`` stays on this XLA form BY MEASUREMENT: the banked v5e A/B
    (BENCH_BANKED.md 2026-07-31, B=4 L=4096 H=24 dim=64 ds=128) has the
    kernel at 6565 us vs 2539 us XLA — XLA's SSD lowering wins 2.6x, so
    the kernel stays opt-in (it exists for shapes/fusions where VMEM
    residency pays; re-flip only on a banked win).

    The sequence splits into chunks of ``chunk_size``; within a chunk the
    recurrence unrolls into an attention-like matmul (MXU work:
    ``scores[i,j] = (C_i . B_j) * exp(Acum_i - Acum_j) * dt_j``), and chunk
    boundary states pass through one lax.scan — O(L * chunk) FLOPs with
    O(L / chunk) sequential depth instead of O(L).

    Requires ``L % chunk_size == 0`` (pad upstream).  Returns
    ``(y [B, L, H, dim], final_state [B, H, dim, dstate])``.
    """
    from_env = False
    if backend == "auto":
        import os

        backend = os.environ.get("FLASHINFER_TPU_MAMBA_BACKEND", "xla")
        from_env = True
    if backend == "pallas":
        from flashinfer_tpu.ops import mamba_kernel

        if mamba_kernel.eligible(x, B):
            return mamba_kernel.mamba_chunk_scan_pallas(
                x, dt, A, B, C, D=D, z=z, dt_bias=dt_bias,
                dt_softplus=dt_softplus, initial_state=initial_state,
            )
        if not from_env:
            raise ValueError(
                "backend='pallas' needs L % 128 == 0, 128-aligned dstate, "
                "8-aligned dim, H % G == 0; got "
                f"L={x.shape[1]} ds={B.shape[-1]} dim={x.shape[-1]} "
                f"H={x.shape[2]} G={B.shape[2]}"
            )
        backend = "xla"
    if backend != "xla":
        raise ValueError(f"unknown mamba backend {backend!r}")
    return _mamba_chunk_scan_xla(
        x, dt, A, B, C, chunk_size, D, z, dt_bias, dt_softplus,
        initial_state,
    )


@functools.partial(
    jax.jit, static_argnames=("chunk_size", "dt_softplus")
)
def _mamba_chunk_scan_xla(x, dt, A, B, C, chunk_size=64, D=None, z=None,
                          dt_bias=None, dt_softplus=False,
                          initial_state=None):
    Bsz, L, H, dim = x.shape
    G, ds = B.shape[2], B.shape[3]
    assert L % chunk_size == 0, "pad L to a chunk multiple"
    nC = L // chunk_size
    rep = H // G

    dtf = dt.astype(jnp.float32)
    if dt_bias is not None:
        dtf = dtf + dt_bias.astype(jnp.float32)[None, None]
    if dt_softplus:
        dtf = _softplus(dtf)

    xf = x.astype(jnp.float32).reshape(Bsz, nC, chunk_size, H, dim)
    dtc = dtf.reshape(Bsz, nC, chunk_size, H)
    Bf = jnp.repeat(B.astype(jnp.float32), rep, axis=2).reshape(
        Bsz, nC, chunk_size, H, ds
    )
    Cf = jnp.repeat(C.astype(jnp.float32), rep, axis=2).reshape(
        Bsz, nC, chunk_size, H, ds
    )
    a = dtc * A.astype(jnp.float32)[None, None, None, :]  # [B,nC,Q,H] log-decay
    acum = jnp.cumsum(a, axis=2)  # inclusive cumulative decay in-chunk
    a_total = acum[:, :, -1]  # [B, nC, H]

    # intra-chunk quadratic part
    li = acum[:, :, :, None, :]  # [B,nC,Q(i),1,H]
    lj = acum[:, :, None, :, :]  # [B,nC,1,Q(j),H]
    causal = jnp.tril(jnp.ones((chunk_size, chunk_size), bool))
    decay = jnp.where(
        causal[None, None, :, :, None], jnp.exp(li - lj), 0.0
    )  # [B,nC,Q,Q,H]
    cb = jnp.einsum("bnihs,bnjhs->bnijh", Cf, Bf)  # [B,nC,Q,Q,H]
    scores = cb * decay * dtc[:, :, None, :, :]  # weight dt_j
    y = jnp.einsum("bnijh,bnjhd->bnihd", scores, xf)

    # chunk-final states: S_c = sum_j exp(a_total - acum_j) dt_j B_j x_j^T
    w = jnp.exp(a_total[:, :, None, :] - acum) * dtc  # [B,nC,Q,H]
    S_chunk = jnp.einsum("bnjh,bnjhs,bnjhd->bnhds", w, Bf, xf)

    # inter-chunk scan over boundary states
    if initial_state is None:
        initial_state = jnp.zeros((Bsz, H, dim, ds), jnp.float32)

    def scan_body(S, inp):
        S_c, a_tot = inp  # [B,H,dim,ds], [B,H]
        S_prev = S
        S = jnp.exp(a_tot)[:, :, None, None] * S + S_c
        return S, S_prev

    final, S_prevs = jax.lax.scan(
        scan_body,
        initial_state.astype(jnp.float32),
        (jnp.moveaxis(S_chunk, 1, 0), jnp.moveaxis(a_total, 1, 0)),
    )
    S_prevs = jnp.moveaxis(S_prevs, 0, 1)  # [B,nC,H,dim,ds]: state entering chunk

    # inter-chunk contribution: y_inter[i] = exp(acum_i) * C_i . S_prev
    y_inter = jnp.einsum(
        "bnihs,bnhds->bnihd", Cf * jnp.exp(acum)[..., None], S_prevs
    )
    y = (y + y_inter).reshape(Bsz, L, H, dim)
    if D is not None:
        y = y + D.astype(jnp.float32)[None, None, :, None] * x.astype(jnp.float32)
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    return y.astype(x.dtype), final


@functools.partial(jax.jit, static_argnames=("dt_softplus",))
def selective_state_update_mtp(
    state: jax.Array,  # [B, H, dim, dstate]
    x: jax.Array,  # [B, T, H, dim] — T draft/MTP tokens per request
    dt: jax.Array,  # [B, T, H, dim]
    A: jax.Array,  # [H, dim, dstate]
    B: jax.Array,  # [B, T, G, dstate]
    C: jax.Array,  # [B, T, G, dstate]
    D: Optional[jax.Array] = None,
    z: Optional[jax.Array] = None,  # [B, T, H, dim]
    dt_bias: Optional[jax.Array] = None,
    dt_softplus: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Multi-token (MTP) decode step -> (y [B, T, H, dim], new_state).

    The reference ships a dedicated MTP kernel for T >= 1 draft tokens
    per call (``checkpointing_ssu`` / selective_state_update MTP
    variants); on TPU the T-step recurrence IS ``selective_scan`` at
    small L — XLA keeps the state on-chip across the scan, so this is a
    named delegation, not a new kernel."""
    y, final = selective_scan(
        x, dt, A, B, C, D, z, dt_bias, dt_softplus, initial_state=state
    )
    # round-trip the caller's state dtype (scan carries f32): MTP loops
    # feed the state back as a carry and must not change dtype per step
    return y, final.astype(state.dtype)


@functools.partial(jax.jit, static_argnames=("dt_softplus",))
def checkpointing_ssu(
    state: jax.Array,  # [B, H, dim, dstate] — COMMITTED checkpoint
    x_cache: jax.Array,  # [B, H, R, dim] ring of cached draft inputs
    B_cache: jax.Array,  # [B, G, R, dstate]
    dt_cache: jax.Array,  # [B, H, R] f32 PROCESSED dt (tie_hdim)
    ring_start: jax.Array,  # [B] int32 oldest live ring row
    prev_num_accepted_tokens: jax.Array,  # [B] int32 rows to replay
    x: jax.Array,  # [B, T, H, dim] new draft tokens
    dt: jax.Array,  # [B, T, H] tie_hdim raw dt
    A: jax.Array,  # [H, dim, dstate]
    B: jax.Array,  # [B, T, G, dstate]
    C: jax.Array,  # [B, T, G, dstate]
    D: Optional[jax.Array] = None,  # [H, dim]
    z: Optional[jax.Array] = None,  # [B, T, H, dim]
    dt_bias: Optional[jax.Array] = None,  # [H]
    dt_softplus: bool = False,
):
    """Speculative-decoding SSU with lazy state recomputation (reference
    ``flashinfer.mamba.checkpointing_ssu``, mamba/checkpointing_ssu.py).

    The SSM state is enormous next to one token's inputs, so instead of
    checkpointing states per draft token, the ring caches the draft
    INPUTS and rebuilds the committed state by REPLAY:

    1. advance ``state`` through the first ``prev_num_accepted_tokens``
       cached ring rows (the draft tokens the verifier accepted) — this
       is the only way the committed state moves;
    2. slide ``ring_start`` past the replayed rows (rejected drafts are
       simply never replayed and get overwritten);
    3. emit outputs for the T NEW draft tokens from a TRANSIENT copy of
       the committed state (drafts are not committed), and cache their
       (x, B, processed dt) into the ring for the next call's replay.

    Functional twin of the reference's in-place kernel: returns
    ``(y [B, T, H, dim], state, x_cache, B_cache, dt_cache,
    ring_start)``.  tie_hdim contract as in the reference kernel: dt is
    per-head (``[B, T, H]``), dt_bias ``[H]``.  Capacity rule: the ring
    must hold the pending window — R >= prev_accepted_max + T (the
    reference's ``pnat + 2T > RING_BUFFER_LEN`` flush rule)."""
    Bsz, T, H, dim = x.shape
    R = x_cache.shape[2]
    G = B.shape[2]
    rep = H // G
    Af = A.astype(jnp.float32)[None]  # [1, H, dim, dstate]
    accepted = prev_num_accepted_tokens.astype(jnp.int32)

    # ---- 1. replay the accepted prefix from the ring ----
    def replay_step(j, st):
        idx = (ring_start + j) % R  # [B]
        xj = jnp.take_along_axis(
            x_cache, idx[:, None, None, None], axis=2
        )[:, :, 0].astype(jnp.float32)  # [B, H, dim]
        Bj = jnp.take_along_axis(
            B_cache, idx[:, None, None, None], axis=2
        )[:, :, 0].astype(jnp.float32)  # [B, G, dstate]
        dtj = jnp.take_along_axis(
            dt_cache, idx[:, None, None], axis=2
        )[:, :, 0].astype(jnp.float32)  # [B, H]
        Bjr = jnp.repeat(Bj, rep, axis=1)  # [B, H, dstate]
        dA = jnp.exp(dtj[..., None, None] * Af)
        dBx = (dtj[..., None] * xj)[..., None] * Bjr[:, :, None, :]
        stepped = st * dA + dBx
        live = (j < accepted)[:, None, None, None]
        return jnp.where(live, stepped, st)

    # dynamic upper bound: O(max accepted) replay work instead of O(R)
    # (a traced bound lowers fori_loop to while_loop); the j < accepted
    # mask still handles per-request variation inside the bound
    committed = jax.lax.fori_loop(
        0, jnp.max(accepted), replay_step, state.astype(jnp.float32)
    )
    new_start = (ring_start + accepted) % R

    # ---- 2. process the T new drafts transiently, emitting y ----
    dtf = dt.astype(jnp.float32)
    if dt_bias is not None:
        dtf = dtf + dt_bias.astype(jnp.float32)[None, None]
    if dt_softplus:
        dtf = _softplus(dtf)  # [B, T, H] processed

    def draft_step(st, inp):
        xt, dtt, Bt, Ct = inp  # [B,H,dim], [B,H], [B,G,ds], [B,G,ds]
        Btr = jnp.repeat(Bt.astype(jnp.float32), rep, axis=1)
        Ctr = jnp.repeat(Ct.astype(jnp.float32), rep, axis=1)
        dA = jnp.exp(dtt[..., None, None] * Af)
        dBx = (dtt[..., None] * xt.astype(jnp.float32))[..., None] * (
            Btr[:, :, None, :]
        )
        st = st * dA + dBx
        y = jnp.einsum("bhds,bhs->bhd", st, Ctr)
        return st, y

    _, ys = jax.lax.scan(
        draft_step,
        committed,
        (
            jnp.moveaxis(x, 1, 0), jnp.moveaxis(dtf, 1, 0),
            jnp.moveaxis(B, 1, 0), jnp.moveaxis(C, 1, 0),
        ),
    )
    y = jnp.moveaxis(ys, 0, 1)  # [B, T, H, dim]
    if D is not None:
        y = y + D.astype(jnp.float32)[None, None] * x.astype(jnp.float32)
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))

    # ---- 3. cache the new drafts into the ring ----
    bidx = jnp.broadcast_to(jnp.arange(Bsz)[:, None], (Bsz, T))
    pos = (new_start[:, None] + jnp.arange(T)[None, :]) % R  # [B, T]
    x_cache = x_cache.at[bidx, :, pos].set(x.astype(x_cache.dtype))
    B_cache = B_cache.at[bidx, :, pos].set(B.astype(B_cache.dtype))
    dt_cache = dt_cache.at[bidx, :, pos].set(dtf.astype(dt_cache.dtype))
    return (
        y.astype(x.dtype), committed.astype(state.dtype),
        x_cache, B_cache, dt_cache, new_start,
    )
