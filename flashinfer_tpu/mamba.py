"""Mamba/SSM ops: selective state update (decode) + selective scan (prefill).

TPU re-design of the reference Mamba family (``flashinfer/mamba/``,
``csrc/selective_state_update.cu``, ``include/flashinfer/mamba/``):

- ``selective_state_update``: one-token SSM state recurrence used at decode
  time (supports GQA-style head broadcast of B/C groups, dt bias/softplus,
  D skip and z gating — the reference kernel's surface).
- ``selective_scan``: sequential prefill scan (lax.scan over time — XLA
  keeps the recurrence on-chip; the reference's chunked SSD kernel is a
  planned optimization, the semantics here are the oracle).

Functional: state tensors are returned, not mutated (donation makes this
in-place under jit).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _softplus(x):
    return jnp.logaddexp(x, 0.0)


@functools.partial(jax.jit, static_argnames=("dt_softplus",))
def selective_state_update(
    state: jax.Array,  # [B, H, dim, dstate]
    x: jax.Array,  # [B, H, dim]
    dt: jax.Array,  # [B, H, dim]
    A: jax.Array,  # [H, dim, dstate]
    B: jax.Array,  # [B, G, dstate]  (G divides H)
    C: jax.Array,  # [B, G, dstate]
    D: Optional[jax.Array] = None,  # [H, dim]
    z: Optional[jax.Array] = None,  # [B, H, dim]
    dt_bias: Optional[jax.Array] = None,  # [H, dim]
    dt_softplus: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One SSM decode step -> (y [B, H, dim], new_state).

    Recurrence (reference selective_state_update.cu):
        dt' = softplus(dt + dt_bias)              (if enabled)
        state' = state * exp(dt' * A) + dt' * x (outer) B
        y = (state' . C) + D * x, gated by silu(z).
    """
    Bsz, H, dim = x.shape
    G = B.shape[1]
    rep = H // G
    dtf = dt.astype(jnp.float32)
    if dt_bias is not None:
        dtf = dtf + dt_bias.astype(jnp.float32)[None]
    if dt_softplus:
        dtf = _softplus(dtf)
    xf = x.astype(jnp.float32)
    Af = A.astype(jnp.float32)[None]  # [1, H, dim, dstate]
    Bf = jnp.repeat(B.astype(jnp.float32), rep, axis=1)  # [B, H, dstate]
    Cf = jnp.repeat(C.astype(jnp.float32), rep, axis=1)
    dA = jnp.exp(dtf[..., None] * Af)  # [B, H, dim, dstate]
    dBx = (dtf * xf)[..., None] * Bf[:, :, None, :]  # [B, H, dim, dstate]
    new_state = state.astype(jnp.float32) * dA + dBx
    y = jnp.einsum("bhds,bhs->bhd", new_state, Cf)
    if D is not None:
        y = y + D.astype(jnp.float32)[None] * xf
    if z is not None:
        y = y * jax.nn.silu(z.astype(jnp.float32))
    return y.astype(x.dtype), new_state.astype(state.dtype)


@functools.partial(jax.jit, static_argnames=("dt_softplus",))
def selective_scan(
    x: jax.Array,  # [B, L, H, dim]
    dt: jax.Array,  # [B, L, H, dim]
    A: jax.Array,  # [H, dim, dstate]
    B: jax.Array,  # [B, L, G, dstate]
    C: jax.Array,  # [B, L, G, dstate]
    D: Optional[jax.Array] = None,
    z: Optional[jax.Array] = None,  # [B, L, H, dim]
    dt_bias: Optional[jax.Array] = None,
    dt_softplus: bool = False,
    initial_state: Optional[jax.Array] = None,  # [B, H, dim, dstate]
) -> Tuple[jax.Array, jax.Array]:
    """Prefill scan -> (y [B, L, H, dim], final_state)."""
    Bsz, L, H, dim = x.shape
    dstate = A.shape[-1]
    if initial_state is None:
        initial_state = jnp.zeros((Bsz, H, dim, dstate), jnp.float32)

    def step(state, inp):
        xt, dtt, Bt, Ct, zt = inp
        y, state = selective_state_update(
            state, xt, dtt, A, Bt, Ct, D,
            zt if z is not None else None,
            dt_bias, dt_softplus,
        )
        return state, y

    zs = (
        jnp.moveaxis(z, 1, 0)
        if z is not None
        else jnp.zeros((L,) + x.shape[:1] + x.shape[2:], x.dtype)
    )
    final, ys = jax.lax.scan(
        step,
        initial_state.astype(jnp.float32),
        (
            jnp.moveaxis(x, 1, 0), jnp.moveaxis(dt, 1, 0),
            jnp.moveaxis(B, 1, 0), jnp.moveaxis(C, 1, 0), zs,
        ),
    )
    return jnp.moveaxis(ys, 0, 1), final.astype(jnp.float32)
