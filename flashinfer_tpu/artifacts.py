"""Compiled-artifact bundles — the TPU re-design of the reference's cubin
artifactory (``/root/reference/flashinfer/artifacts.py:131-335``).

The reference ships pre-compiled device binaries (cubins) from an
artifactory: ``ArtifactPath`` names the paths, ``CheckSumHash`` pins
sha256 sums, ``download_artifacts()`` fetches them and
``get_artifacts_status()`` audits presence.  On TPU the equivalent
"pre-compiled device binary" is an **XLA persistent-cache entry** (a
serialized Mosaic/XLA executable keyed by HLO hash) plus the **tuned
tactic tables** that select kernel schedules.  Both are host-portable
across machines with the same chip generation and jax version, so the
artifact story becomes pack/unpack of a checksummed bundle:

- :func:`build_artifacts` — populate the local cache by compiling the
  serving-critical kernel set (aot.prewarm) — the zero-egress analogue of
  "download" (artifacts are *built once* then shipped).
- :func:`pack_artifacts` / :func:`unpack_artifacts` — tar the cache +
  tactics into a bundle with a sha256 manifest, and restore it on an
  air-gapped or fleet host (checksum-verified, like the reference's
  ``get_checksums``).
- :func:`get_artifacts_status` — presence audit, reference-shaped
  ``tuple[tuple[str, bool], ...]``.
- :func:`clear_artifacts` — the ``clear_cubin()`` analogue.

``download_artifacts()`` is kept as a reference-named alias: it unpacks
``$FLASHINFER_TPU_ARTIFACT_BUNDLE`` if set (the fleet-distribution hook),
else builds locally.
"""

from __future__ import annotations

import hashlib
import json
import os
import tarfile
from pathlib import Path
from typing import Optional, Tuple

from flashinfer_tpu import env


class ArtifactPath:
    """Bundle subdirectories (reference ArtifactPath names cubin dirs)."""

    XLA_CACHE: str = "xla_cache"          # serialized executables
    TACTICS: str = "autotuner"            # user-tuned tactic cache
    TUNING_CONFIGS: str = "tuning_configs"  # shipped per-chip tables


_MANIFEST = "MANIFEST.sha256.json"


def _tuning_configs_dir() -> Path:
    return Path(__file__).parent / "tuning_configs"


def _sha256(p: Path) -> str:
    h = hashlib.sha256()
    with open(p, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _bundle_members(cache_root: Path):
    """Yield (arcname, path) for every file the bundle carries."""
    for sub, root in (
        (ArtifactPath.XLA_CACHE, cache_root / ArtifactPath.XLA_CACHE),
        (ArtifactPath.TACTICS, cache_root / ArtifactPath.TACTICS),
    ):
        if not root.is_dir():
            continue
        for p in sorted(root.rglob("*")):
            if p.is_file():
                yield f"{sub}/{p.relative_to(root)}", p
    # tuning tables: a bundle-installed copy in the cache dir is the
    # NEWER table (autotuner._load lets it override the package copy) —
    # re-packing must relay it, not the stale package file
    by_stem = {}
    for root in (_tuning_configs_dir(),
                 cache_root / ArtifactPath.TUNING_CONFIGS):
        if root.is_dir():
            for p in sorted(root.glob("*.json")):
                by_stem[p.name] = p
    for name, p in sorted(by_stem.items()):
        yield f"{ArtifactPath.TUNING_CONFIGS}/{name}", p


def build_artifacts(verbose: bool = True) -> None:
    """Compile the serving-critical kernel set into the persistent cache
    (the zero-egress ``download_artifacts`` body: artifacts are built,
    not fetched).  Reference: ``download_artifacts`` artifacts.py:277."""
    from flashinfer_tpu import aot

    env.enable_compilation_cache()
    aot.prewarm(verbose=verbose)


def pack_artifacts(out_path: str, cache_dir: Optional[str] = None) -> Path:
    """Tar the compilation cache + tactic tables with a sha256 manifest.

    The bundle is valid for hosts with the same chip generation and jax
    version (the autotuner additionally validates device_kind metadata on
    load, so a mismatched bundle degrades to defaults, never misapplies).
    """
    root = Path(cache_dir) if cache_dir else env.cache_dir()
    out = Path(out_path)
    manifest = {}
    with tarfile.open(out, "w:gz") as tar:
        for arcname, p in _bundle_members(root):
            manifest[arcname] = _sha256(p)
            tar.add(p, arcname=arcname)
        mbytes = json.dumps(manifest, indent=1, sort_keys=True).encode()
        import io

        info = tarfile.TarInfo(_MANIFEST)
        info.size = len(mbytes)
        tar.addfile(info, io.BytesIO(mbytes))
    return out


def unpack_artifacts(bundle_path: str,
                     cache_dir: Optional[str] = None) -> int:
    """Restore a bundle into the local cache, verifying every checksum
    (reference ``get_checksums`` role).  Returns the file count.

    Raises ``ValueError`` on any integrity failure (checksum mismatch,
    missing manifest entry, unsafe path) and writes NOTHING in that case
    — a damaged bundle must not partially seed the executable cache.
    (This is corruption/truncation DETECTION, not tamper-proofing: the
    manifest travels inside the bundle, so an adversary who can rewrite
    the bundle can re-sign it; distribute bundles over channels with
    their own authenticity guarantees.)
    """
    root = Path(cache_dir) if cache_dir else env.cache_dir()

    def _members(tar, manifest):
        for member in tar.getmembers():
            if not member.isfile() or member.name == _MANIFEST:
                continue
            rel = Path(member.name)
            # refuse path escapes; tarfile data filter exists only on
            # newer pythons, so normalize by hand
            if rel.is_absolute() or ".." in rel.parts:
                raise ValueError(f"unsafe member path {member.name!r}")
            if member.name not in manifest:
                raise ValueError(f"{member.name}: not in manifest")
            yield member, rel

    # pass 1: stream every member through sha256 — nothing is written
    # until the WHOLE bundle has verified (O(chunk) memory, not O(bundle))
    seen = set()
    with tarfile.open(bundle_path, "r:gz") as tar:
        if _MANIFEST not in tar.getnames():
            raise ValueError(f"{bundle_path}: missing {_MANIFEST}")
        manifest = json.loads(tar.extractfile(_MANIFEST).read().decode())
        for member, _rel in _members(tar, manifest):
            h = hashlib.sha256()
            f = tar.extractfile(member)
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
            if h.hexdigest() != manifest[member.name]:
                raise ValueError(f"{member.name}: checksum mismatch")
            seen.add(member.name)
    dropped = set(manifest) - seen
    if dropped:
        raise ValueError(
            f"{bundle_path}: manifest entries missing from the bundle "
            f"(truncated/repacked?): {sorted(dropped)[:5]}"
        )
    # pass 2: extract (the autotuner reads bundle-installed
    # tuning_configs from the cache dir too — autotuner._load second
    # root — overriding the package copy)
    n = 0
    root.mkdir(parents=True, exist_ok=True)
    with tarfile.open(bundle_path, "r:gz") as tar:
        manifest = json.loads(tar.extractfile(_MANIFEST).read().decode())
        for member, rel in _members(tar, manifest):
            dest = root / rel
            dest.parent.mkdir(parents=True, exist_ok=True)
            f = tar.extractfile(member)
            with open(dest, "wb") as out:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    out.write(chunk)
            n += 1
    return n


def get_artifacts_status() -> Tuple[Tuple[str, bool], ...]:
    """Presence audit, reference-shaped (artifacts.py:318).

    Deliberately queries NO device: this must answer on a host whose
    accelerator is absent or wedged (it is part of the recovery
    tooling), so the tuning-config rows list each available stem rather
    than resolving the current chip."""
    root = env.cache_dir()
    status = [
        (ArtifactPath.XLA_CACHE,
         any((root / ArtifactPath.XLA_CACHE).rglob("*"))
         if (root / ArtifactPath.XLA_CACHE).is_dir() else False),
        (ArtifactPath.TACTICS,
         (root / ArtifactPath.TACTICS / "tactics.json").is_file()),
    ]
    # glob on a missing directory yields nothing, so no existence check
    stems = sorted(
        {p.stem for p in _tuning_configs_dir().glob("*.json")}
        | {p.stem
           for p in (root / ArtifactPath.TUNING_CONFIGS).glob("*.json")}
    )
    if stems:
        for s in stems:
            status.append((f"{ArtifactPath.TUNING_CONFIGS}/{s}", True))
    else:
        status.append((ArtifactPath.TUNING_CONFIGS, False))
    return tuple(status)


def clear_artifacts(cache_dir: Optional[str] = None) -> None:
    """Remove cached executables + user tactics (``clear_cubin`` role,
    artifacts.py:335).  Shipped tuning_configs are package data and are
    NOT touched."""
    import shutil

    root = Path(cache_dir) if cache_dir else env.cache_dir()
    for sub in (ArtifactPath.XLA_CACHE, ArtifactPath.TACTICS):
        d = root / sub
        if d.is_dir():
            shutil.rmtree(d)


def download_artifacts() -> None:
    """Reference-named entry (artifacts.py:277): unpack the bundle named
    by ``$FLASHINFER_TPU_ARTIFACT_BUNDLE`` if set, else build locally."""
    bundle = os.environ.get("FLASHINFER_TPU_ARTIFACT_BUNDLE")
    if bundle:
        unpack_artifacts(bundle)
    else:
        build_artifacts()


# ---------------------------------------------------------------------------
# Reference-named surface (artifacts.py) on the bundle model
# ---------------------------------------------------------------------------

import contextlib
from contextlib import contextmanager  # noqa: F401  (reference re-export)
from concurrent.futures import (  # noqa: F401  (reference re-export)
    ThreadPoolExecutor, as_completed,
)
from dataclasses import dataclass  # noqa: F401  (reference re-export)
from typing import Generator  # noqa: F401  (reference re-export)

# Reference module constants (artifacts.py): the repository URL becomes
# the bundle env hook, the cubin dir the local cache root.
FLASHINFER_CUBINS_REPOSITORY = os.environ.get(
    "FLASHINFER_TPU_ARTIFACT_BUNDLE", ""
)
FLASHINFER_CUBIN_DIR = str(env.cache_dir())


def safe_urljoin(base: str, part: str) -> str:
    """Reference path-join helper; bundles are local paths here."""
    return os.path.join(base, part)


def download_file(src: str, dest: str) -> str:
    """Reference single-file fetch -> local copy (zero-egress env)."""
    import shutil

    Path(dest).parent.mkdir(parents=True, exist_ok=True)
    shutil.copyfile(src, dest)
    return dest


def verify_cubin(path: str, sha256: str) -> bool:
    """Checksum check under the reference's name."""
    return _sha256(Path(path)) == sha256


@contextlib.contextmanager
def temp_env_var(key: str, value: str):
    """Reference helper (artifacts.py:46) — unchanged semantics."""
    prev = os.environ.get(key)
    os.environ[key] = value
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev


def get_subdir_file_list():
    """(subdir, file) pairs the bundle would carry (artifacts.py:227)."""
    for arcname, _ in _bundle_members(env.cache_dir()):
        sub, _, rest = arcname.partition("/")
        yield sub, rest


def get_available_cubin_files(*_a, **_k) -> Tuple[str, ...]:
    """Reference lists cubins present for a path (artifacts.py:58); here:
    serialized XLA executables in the local cache."""
    d = env.cache_dir() / ArtifactPath.XLA_CACHE
    if not d.is_dir():
        return ()
    return tuple(sorted(p.name for p in d.rglob("*") if p.is_file()))


def get_available_header_files(*_a, **_k) -> Tuple[str, ...]:
    """Headers have no TPU meaning (no JIT-compiled C++ on this path);
    the shipped tuning tables are the closest 'interface' files."""
    return tuple(sorted(p.name for p in _tuning_configs_dir().glob("*.json")))


class CheckSumHash:
    """Reference pins static cubin checksums (artifacts.py:152); TPU
    bundles carry their manifest INSIDE the tarball (``MANIFEST`` name
    here), so the class only names the manifest file."""

    MANIFEST: str = _MANIFEST


def get_checksums(subdirs=None):
    """Live checksums of the local artifact set (artifacts.py:198)."""
    want = set(subdirs) if subdirs else None
    out = {}
    for arcname, p in _bundle_members(env.cache_dir()):
        sub = arcname.partition("/")[0]
        if want is None or sub in want:
            out[arcname] = _sha256(p)
    return out


clear_cubin = clear_artifacts  # reference name (artifacts.py:335)
