"""Green-context SM partitioning — intentionally absent on TPU.

The reference's ``flashinfer/green_ctx.py`` (split_device_green_ctx,
green_ctx.py:126) carves a GPU's SMs into partitions to colocate prefill
with decode or compute with communication.  A TPU core has no SM pool to
partition: concurrency between compute and DMA/collectives is handled by
the compiler's async scheduling, and prefill/decode colocation is achieved
by the holistic mixed-batch kernel (flashinfer_tpu.attention.BatchAttention)
instead of spatial partitioning.  These stubs document the mapping and
fail loudly rather than silently no-op.
"""

from __future__ import annotations


def split_device_green_ctx(*args, **kwargs):
    raise NotImplementedError(
        "Green contexts are CUDA SM partitioning; on TPU use "
        "flashinfer_tpu.attention.BatchAttention (holistic mixed batches) — "
        "compute/communication overlap is compiler-scheduled."
    )


def split_device_green_ctx_by_sm_count(*args, **kwargs):
    raise NotImplementedError(
        "Green contexts are CUDA SM partitioning; no TPU equivalent. "
        "See flashinfer_tpu.green_ctx module docstring for the mapping."
    )


def _cuda_only(name):
    def stub(*args, **kwargs):
        raise NotImplementedError(
            f"{name} is CUDA green-context machinery (SM partitioning / "
            "CUdevice resources); no TPU equivalent — see this module's "
            "docstring for the mapping."
        )

    stub.__name__ = name
    return stub


create_green_ctx_streams = _cuda_only("create_green_ctx_streams")
get_cudevice = _cuda_only("get_cudevice")
get_device_resource = _cuda_only("get_device_resource")
split_resource = _cuda_only("split_resource")
split_resource_by_sm_count = _cuda_only("split_resource_by_sm_count")


def get_sm_count_constraint(*args, **kwargs):
    """Reference returns the (min, multiple) SM-count granularity; the
    TPU analogue is one indivisible core."""
    return (1, 1)
