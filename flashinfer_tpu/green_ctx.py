"""Green-context SM partitioning — intentionally absent on TPU.

The reference's ``flashinfer/green_ctx.py`` (split_device_green_ctx,
green_ctx.py:126) carves a GPU's SMs into partitions to colocate prefill
with decode or compute with communication.  A TPU core has no SM pool to
partition: concurrency between compute and DMA/collectives is handled by
the compiler's async scheduling, and prefill/decode colocation is achieved
by the holistic mixed-batch kernel (flashinfer_tpu.attention.BatchAttention)
instead of spatial partitioning.  These stubs document the mapping and
fail loudly rather than silently no-op.
"""

from __future__ import annotations


def split_device_green_ctx(*args, **kwargs):
    raise NotImplementedError(
        "Green contexts are CUDA SM partitioning; on TPU use "
        "flashinfer_tpu.attention.BatchAttention (holistic mixed batches) — "
        "compute/communication overlap is compiler-scheduled."
    )


def split_device_green_ctx_by_sm_count(*args, **kwargs):
    raise NotImplementedError(
        "Green contexts are CUDA SM partitioning; no TPU equivalent. "
        "See flashinfer_tpu.green_ctx module docstring for the mapping."
    )
