"""AOT cache pre-warm.

TPU re-design of the reference's AOT batch builder (``flashinfer/aot.py`` —
enumerate all JitSpecs and build them into the jit-cache wheel): here the
artifact store is the XLA persistent compilation cache, and pre-warming
means tracing + compiling the common kernel configurations once so serving
processes hit the cache cold-start-free.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# (num_qo_heads, num_kv_heads, head_dim) families to pre-warm by default —
# the reference AOT's head-dim/GQA enumeration collapsed to common LLM
# shapes (MLA kernels are shape-stable and warm on first use)
DEFAULT_SHAPES = [
    (32, 8, 128),   # Llama-3-8B/70B
    (32, 32, 128),  # MHA
    (64, 8, 128),   # Qwen-72B-ish
]


def prewarm(
    shapes: Optional[Sequence[Tuple[int, int, int]]] = None,
    batch_sizes: Sequence[int] = (8, 64),
    page_size: int = 16,
    dtype=jnp.bfloat16,
    verbose: bool = True,
) -> int:
    """Compile the core decode/prefill kernels for common configs into the
    persistent cache.  Returns the number of configs compiled."""
    from flashinfer_tpu import env
    from flashinfer_tpu.decode import BatchDecodeWithPagedKVCacheWrapper
    from flashinfer_tpu.prefill import single_prefill_with_kv_cache

    env.enable_compilation_cache()
    count = 0
    for (hq, hkv, hd) in shapes or DEFAULT_SHAPES:
        if hd <= 0 or hq % max(hkv, 1) != 0:
            raise ValueError(f"invalid prewarm shape (hq={hq}, hkv={hkv}, hd={hd})")
        for bs in batch_sizes:
            pages_per = 64
            indptr = np.arange(bs + 1, dtype=np.int32) * pages_per
            indices = np.arange(bs * pages_per, dtype=np.int32)
            last = np.full((bs,), page_size, np.int32)
            kc = jnp.zeros((bs * pages_per, hkv, page_size, hd), dtype)
            vc = jnp.zeros_like(kc)
            q = jnp.zeros((bs, hq, hd), dtype)
            w = BatchDecodeWithPagedKVCacheWrapper(kv_layout="HND")
            w.plan(indptr, indices, last, hq, hkv, hd, page_size)
            w.run(q, (kc, vc)).block_until_ready()
            count += 1
            if verbose:
                print(f"prewarmed decode hq={hq} hkv={hkv} hd={hd} bs={bs}")
        # one prefill shape per head config
        T = 2048
        q = jnp.zeros((T, hq, hd), dtype)
        k = jnp.zeros((T, hkv, hd), dtype)
        single_prefill_with_kv_cache(q, k, k, causal=True).block_until_ready()
        count += 1
        if verbose:
            print(f"prewarmed prefill hq={hq} hkv={hkv} hd={hd} T={T}")
    return count
