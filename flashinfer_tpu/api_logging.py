"""API-call logging/tracing/metrics decorator.

TPU re-design of the reference's ``@flashinfer_api``
(``flashinfer/api_logging.py:34-90``): leveled logging driven by
``FLASHINFER_TPU_LOGLEVEL`` (0 = off; 1+ = call names; 3+ = arg/shape/
dtype summaries; 10 = full tensor dumps to ``FLASHINFER_TPU_DUMP_DIR``
as .npy), plus the trace-capture/substitution hooks
(``FLASHINFER_TPU_TRACE_*``, flashinfer_tpu.trace), the op timeline
(flashinfer_tpu.profiler), and the obs metrics registry
(``FLASHINFER_TPU_METRICS``: per-op call counters + host-dispatch
histograms — flashinfer_tpu.obs).  The reference's CUDAGraph-awareness
is unnecessary (nothing mutates under trace); dumps use host transfers
and are for debugging only.

Zero-overhead contract: with every surface disabled (the default env),
a decorated call is ONE :func:`_instrumentation_active` check and then
the plain function call — the shape
``tests/test_obs.py::test_zero_overhead_fast_path`` pins so the
disabled path can never quietly grow per-call work.  The call index in
log lines comes from the registry's ``api.calls_total`` counter (the
successor of the ad-hoc module ``_call_counter``), so log indexes and
metrics share one counting authority.
"""

from __future__ import annotations

import functools
import logging
import os
import time
from typing import Any, Callable

from flashinfer_tpu import env

logger = logging.getLogger("flashinfer_tpu")


def _summarize(x: Any) -> str:
    try:
        import jax

        if isinstance(x, jax.Array):
            return f"Array{tuple(x.shape)}:{x.dtype}"
    except Exception:
        pass
    import numpy as np

    if isinstance(x, np.ndarray):
        return f"ndarray{tuple(x.shape)}:{x.dtype}"
    if isinstance(x, (list, tuple)) and len(x) > 4:
        return f"{type(x).__name__}[{len(x)}]"
    return repr(x)[:80]


def _dump(name: str, idx: int, args, kwargs) -> None:
    import json

    import numpy as np

    d = env.dump_dir() / f"{name}_{idx}"
    d.mkdir(parents=True, exist_ok=True)
    meta = {"skipped": [], "scalars": {}}

    def save(key: str, a) -> None:
        if a is None or isinstance(a, (bool, int, float, str)):
            # static/scalar kwargs (causal flags, sm_scale, layout strings)
            # must round-trip as native Python values: a 0-d numpy array is
            # unhashable as a static jit arg and fails string checks
            meta["scalars"][key] = a
            return
        try:
            arr = np.asarray(a)
            if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
                # ml_dtypes (bf16/fp8) don't survive np.save: store as f32
                # and record the original dtype for replay
                meta[key] = str(getattr(a, "dtype", arr.dtype))
                arr = np.asarray(a, dtype=np.float32)
            np.save(d / f"{key}.npy", arr)
        except Exception:
            meta["skipped"].append(key)

    for i, a in enumerate(args):
        save(f"arg{i}", a)
    for k, v in kwargs.items():
        save(f"kw_{k}", v)
    (d / "meta.json").write_text(json.dumps(meta))


def _instrumentation_active() -> bool:
    """THE fast-path branch: True iff any observability surface is on.
    Kept as one function so the disabled path is a single call site
    (pinned by the zero-overhead regression test) and new surfaces must
    register here rather than adding branches to the wrapper."""
    if env.log_level() > 0:
        return True
    from flashinfer_tpu import profiler as _prof

    if _prof.timeline_active():
        return True
    from flashinfer_tpu import trace as _trace

    if _trace._trace_enabled() or _trace._apply_enabled():
        return True
    from flashinfer_tpu.obs.registry import metrics_enabled, spans_enabled

    return metrics_enabled() or spans_enabled()


def _instrumented_call(f: Callable, api_name: str, args, kwargs):
    """The slow path: metrics, trace hooks, leveled logging, timeline.

    Ordering contract (unchanged from the pre-obs design):
    - the timeline span and the dispatch histogram cover the WHOLE
      dispatch including any trace-apply substitution, so a profiled
      run measures the SAME configuration production executes;
    - substituted calls are not log-line'd or dumped (they are counted:
      ``trace.solution_hits``).
    """
    from flashinfer_tpu import profiler as _prof
    from flashinfer_tpu import trace as _trace
    from flashinfer_tpu.obs import registry as _registry

    level = env.log_level()
    metrics_on = _registry.metrics_enabled()
    reg = _registry.get() if (metrics_on or level > 0) else None

    idx = reg.counter_inc("api.calls_total") if reg is not None else 0
    if metrics_on:
        reg.counter_inc("api.calls", op=api_name)

    target, substituted = f, False
    if _trace._trace_enabled() or _trace._apply_enabled():
        t_axes = _trace._axes_of(args, kwargs)
        if _trace._trace_enabled():
            _trace._dump_trace(api_name, t_axes)
        if _trace._apply_enabled():
            sub = _trace._find_solution(api_name, t_axes)
            if metrics_on:
                reg.counter_inc(
                    "trace.solution_hits" if sub is not None
                    else "trace.solution_misses", op=api_name)
            if sub is not None:
                target, substituted = sub, True

    if not substituted and level >= 1:
        if level >= 3:
            arg_s = ", ".join(_summarize(a) for a in args)
            kw_s = ", ".join(f"{k}={_summarize(v)}" for k, v in kwargs.items())
            logger.info("[%d] %s(%s%s%s)", idx, api_name, arg_s,
                        ", " if kw_s and arg_s else "", kw_s)
        else:
            logger.info("[%d] %s", idx, api_name)
        if level >= 10:
            _dump(api_name, idx, args, kwargs)

    timeline_on = _prof.timeline_active()
    t0 = time.perf_counter()
    out = target(*args, **kwargs)
    t_host = time.perf_counter()
    if metrics_on:
        # host dispatch cost: wrapper entry to op return, no device sync
        reg.observe("api.dispatch_us", (t_host - t0) * 1e6, op=api_name)
    if _registry.spans_enabled():
        # flight-recorder dispatch span over the SAME window as the
        # dispatch histogram; parented under whatever request/phase
        # span is open on this thread (obs.spans nesting), so serving
        # ops land inside their request's lifecycle on the unified
        # trace.  Substituted calls are covered too — same rule as the
        # timeline span below.
        from flashinfer_tpu.obs import spans as _spans

        _spans.record(api_name, "dispatch", t0, t_host)
    if timeline_on:
        if os.environ.get("FLASHINFER_TPU_TIMELINE_SYNC") == "1":
            import jax

            jax.block_until_ready(out)
        _prof.record_event(api_name, t0, time.perf_counter())
    if not substituted and level >= 5:
        logger.info(
            "[%d] %s done in %.3f ms (host)", idx, api_name,
            (t_host - t0) * 1e3,
        )
    return out


def flashinfer_api(fn: Callable = None, *, name: str = None) -> Callable:
    """Decorator adding leveled call logging, obs metrics, op-timeline
    recording, and trace-capture/substitution hooks to a public API
    function (the trace hooks are flashinfer_tpu.trace's
    FLASHINFER_TPU_TRACE_DUMP / FLASHINFER_TPU_TRACE_APPLY surface).

    The op name (``name`` or the function's qualname) must be listed in
    ``flashinfer_tpu.obs.catalog.API_OPS`` — the L005 analysis pass
    enforces it, so every public op ships observed."""

    def deco(f):
        api_name = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if _instrumentation_active():
                return _instrumented_call(f, api_name, args, kwargs)
            return f(*args, **kwargs)

        wrapper.__flashinfer_api_name__ = api_name
        return wrapper

    if fn is not None:
        return deco(fn)
    return deco
