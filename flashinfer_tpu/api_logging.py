"""API-call logging/tracing decorator.

TPU re-design of the reference's ``@flashinfer_api``
(``flashinfer/api_logging.py:34-90``): leveled logging driven by
``FLASHINFER_TPU_LOGLEVEL`` (0 = off — zero overhead, the decorator is a
pass-through; 1+ = call names; 3+ = arg/shape/dtype summaries; 10 = full
tensor dumps to ``FLASHINFER_TPU_DUMP_DIR`` as .npy).  The reference's
CUDAGraph-awareness is unnecessary (nothing mutates under trace); dumps
use host transfers and are for debugging only.
"""

from __future__ import annotations

import functools
import itertools
import logging
import os
import time
from typing import Any, Callable

from flashinfer_tpu import env

logger = logging.getLogger("flashinfer_tpu")
_call_counter = itertools.count()


def _summarize(x: Any) -> str:
    try:
        import jax

        if isinstance(x, jax.Array):
            return f"Array{tuple(x.shape)}:{x.dtype}"
    except Exception:
        pass
    import numpy as np

    if isinstance(x, np.ndarray):
        return f"ndarray{tuple(x.shape)}:{x.dtype}"
    if isinstance(x, (list, tuple)) and len(x) > 4:
        return f"{type(x).__name__}[{len(x)}]"
    return repr(x)[:80]


def _dump(name: str, idx: int, args, kwargs) -> None:
    import json

    import numpy as np

    d = env.dump_dir() / f"{name}_{idx}"
    d.mkdir(parents=True, exist_ok=True)
    meta = {"skipped": [], "scalars": {}}

    def save(key: str, a) -> None:
        if a is None or isinstance(a, (bool, int, float, str)):
            # static/scalar kwargs (causal flags, sm_scale, layout strings)
            # must round-trip as native Python values: a 0-d numpy array is
            # unhashable as a static jit arg and fails string checks
            meta["scalars"][key] = a
            return
        try:
            arr = np.asarray(a)
            if arr.dtype.kind == "V" or arr.dtype.name not in np.sctypeDict:
                # ml_dtypes (bf16/fp8) don't survive np.save: store as f32
                # and record the original dtype for replay
                meta[key] = str(getattr(a, "dtype", arr.dtype))
                arr = np.asarray(a, dtype=np.float32)
            np.save(d / f"{key}.npy", arr)
        except Exception:
            meta["skipped"].append(key)

    for i, a in enumerate(args):
        save(f"arg{i}", a)
    for k, v in kwargs.items():
        save(f"kw_{k}", v)
    (d / "meta.json").write_text(json.dumps(meta))


def flashinfer_api(fn: Callable = None, *, name: str = None) -> Callable:
    """Decorator adding leveled call logging + trace-capture/substitution
    hooks to a public API function (the trace hooks are flashinfer_tpu.trace's
    FLASHINFER_TPU_TRACE_DUMP / FLASHINFER_TPU_TRACE_APPLY surface)."""

    def deco(f):
        api_name = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            from flashinfer_tpu import profiler as _prof

            # timeline recording wraps the whole wrapper (including any
            # trace-apply substitution) so the profiled run executes the
            # SAME configuration as production, not a bypassed one
            if _prof._timeline_events is not None:
                t0 = time.perf_counter()
                out = _dispatch(*args, **kwargs)
                if os.environ.get("FLASHINFER_TPU_TIMELINE_SYNC") == "1":
                    import jax

                    jax.block_until_ready(out)
                _prof.record_event(api_name, t0, time.perf_counter())
                return out
            return _dispatch(*args, **kwargs)

        def _dispatch(*args, **kwargs):
            from flashinfer_tpu import trace as _trace

            level = env.log_level()
            tracing = _trace._trace_enabled() or _trace._apply_enabled()
            if level <= 0 and not tracing:
                return f(*args, **kwargs)
            if tracing:
                t_axes = _trace._axes_of(args, kwargs)
                if _trace._trace_enabled():
                    _trace._dump_trace(api_name, t_axes)
                if _trace._apply_enabled():
                    sub = _trace._find_solution(api_name, t_axes)
                    if sub is not None:
                        return sub(*args, **kwargs)
            if level <= 0:
                return f(*args, **kwargs)
            idx = next(_call_counter)
            if level >= 3:
                arg_s = ", ".join(_summarize(a) for a in args)
                kw_s = ", ".join(f"{k}={_summarize(v)}" for k, v in kwargs.items())
                logger.info("[%d] %s(%s%s%s)", idx, api_name, arg_s,
                            ", " if kw_s and arg_s else "", kw_s)
            else:
                logger.info("[%d] %s", idx, api_name)
            if level >= 10:
                _dump(api_name, idx, args, kwargs)
            t0 = time.perf_counter()
            out = f(*args, **kwargs)
            if level >= 5:
                logger.info(
                    "[%d] %s done in %.3f ms (host)", idx, api_name,
                    (time.perf_counter() - t0) * 1e3,
                )
            return out

        return wrapper

    if fn is not None:
        return deco(fn)
    return deco
