"""CLI: ``python -m flashinfer_tpu <cmd>``.

TPU re-design of the reference CLI (``flashinfer/__main__.py:63-462``).
Command mapping: cubin/jit-cache management collapses into the XLA
persistent compilation cache + native-planner cache under
``FLASHINFER_TPU_CACHE_DIR``.

Commands: collect-env | show-config | clear-cache | module-status |
list-modules | tuner-status.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys


def cmd_collect_env(_args) -> int:
    from flashinfer_tpu.collect_env import main as ce

    ce()
    return 0


def cmd_show_config(_args) -> int:
    from flashinfer_tpu import env

    print(f"cache_dir        : {env.cache_dir()}")
    print(f"dump_dir         : {env.dump_dir()}")
    print(f"log_level        : {env.log_level()}")
    print(f"backend_override : {env.backend_override()}")
    print(f"force_interpret  : {env.force_interpret()}")
    d = env.cache_dir()
    if d.exists():
        n = sum(1 for _ in d.rglob("*") if _.is_file())
        sz = sum(f.stat().st_size for f in d.rglob("*") if f.is_file())
        print(f"cache contents   : {n} files, {sz / 1e6:.1f} MB")
    return 0


def cmd_clear_cache(_args) -> int:
    from flashinfer_tpu import env

    d = env.cache_dir()
    if d.exists():
        shutil.rmtree(d)
        print(f"cleared {d}")
    else:
        print(f"nothing to clear at {d}")
    return 0


def cmd_module_status(_args) -> int:
    from flashinfer_tpu import native

    lib = native.get_lib()
    print(f"native planner  : {'built+loaded' if lib else 'numpy fallback'}")
    from flashinfer_tpu import env

    xc = env.cache_dir() / "xla_cache"
    n = sum(1 for _ in xc.rglob("*") if _.is_file()) if xc.exists() else 0
    print(f"xla compile cache: {n} entries ({xc})")
    from flashinfer_tpu import compile_guard

    reg = compile_guard.compile_status()
    q = compile_guard._load_qlist()
    print(f"kernel compiles  : {len(reg)} recorded, {len(q)} quarantined")
    for fp, info in sorted(reg.items(), key=lambda kv: -kv[1].get("ts", 0))[:10]:
        print(f"  {fp}  {info['op']:<24} {info['compile_s']:7.2f}s  {info['status']}")
    return 0


def cmd_list_modules(_args) -> int:
    mods = [
        "decode (single + BatchDecodeWithPagedKVCacheWrapper)",
        "prefill (single + BatchPrefill{Paged,Ragged}KVCacheWrapper)",
        "attention (BatchAttention holistic, POD, attention sinks)",
        "mla (BatchMLAPagedAttentionWrapper)",
        "cascade (MultiLevelCascadeAttentionWrapper, merge ops)",
        "sparse (BlockSparse, VariableBlockSparse)",
        "page (append_paged_kv_cache, MLA append)",
        "rope / norm / activation",
        "sampling + logits_processor pipeline",
        "gemm (mm/bmm bf16/fp8/int8, grouped, SegmentGEMMWrapper)",
        "quantization (packbits, fp8/int8)",
        "fused_moe (routing, fused_moe, EP)",
        "comm (Mapping, allreduce fusion) / parallel (ulysses, ring, dcp)",
        "topk",
    ]
    for m in mods:
        print(f"  {m}")
    return 0


def cmd_replay(args) -> int:
    """Re-invoke a dumped API call (reference CLI ``replay``,
    flashinfer/__main__.py:462): loads ``arg*.npy`` / ``kw_*.npy`` from a
    FLASHINFER_TPU_LOGLEVEL=10 dump directory and calls the op again."""
    import re
    from pathlib import Path

    import numpy as np

    import flashinfer_tpu as fi

    d = Path(args.dump_dir)
    if not d.is_dir():
        print(f"no such dump dir: {d}")
        return 1
    op_name = re.sub(r"_\d+$", "", d.name)
    fn = getattr(fi, op_name, None)
    if fn is None:
        print(f"unknown op {op_name!r} (dir name must be <op>_<callidx>)")
        return 1
    import json

    meta = {}
    meta_f = d / "meta.json"
    if meta_f.exists():
        meta = json.loads(meta_f.read_text())

    def load(f: Path):
        arr = np.load(f)
        orig = meta.get(f.stem)
        if orig:  # bf16/fp8 stored as f32 with the original dtype recorded
            import jax.numpy as jnp

            arr = jnp.asarray(arr).astype(orig)
        return arr

    pos = {}
    kws = {}
    for f in sorted(d.glob("*.npy")):
        m = re.fullmatch(r"arg(\d+)", f.stem)
        if m:
            pos[int(m.group(1))] = load(f)
        elif f.stem.startswith("kw_"):
            kws[f.stem[3:]] = load(f)
    for key, val in meta.get("scalars", {}).items():
        m = re.fullmatch(r"arg(\d+)", key)
        if m:
            pos[int(m.group(1))] = val
        elif key.startswith("kw_"):
            kws[key[3:]] = val
    if meta.get("skipped"):
        print(f"cannot replay: args were not dumpable: {meta['skipped']}")
        return 1
    if sorted(pos) != list(range(len(pos))):
        print(f"cannot replay: positional dump gap (have {sorted(pos)})")
        return 1
    args_list = [pos[i] for i in sorted(pos)]
    out = fn(*args_list, **kws)
    import jax

    jax.block_until_ready(out)
    leaves = jax.tree_util.tree_leaves(out)
    print(
        f"replayed {op_name} with {len(args_list)} args, {len(kws)} kwargs -> "
        + ", ".join(f"{getattr(l, 'shape', l)}" for l in leaves[:4])
    )
    return 0


def cmd_prewarm(_args) -> int:
    from flashinfer_tpu.aot import prewarm

    n = prewarm()
    print(f"prewarmed {n} configs into the persistent compile cache")
    return 0


def cmd_tuner_status(_args) -> int:
    from flashinfer_tpu.autotuner import AutoTuner

    t = AutoTuner.get()
    t._load()
    print(f"cache file : {t._cache_path()}")
    print(f"entries    : {len(t._cache)}")
    for k, v in sorted(t._cache.items()):
        print(f"  {k} -> {v}")
    return 0


def cmd_tune(args) -> int:
    """Profile the serving-critical op families on the live chip and write
    the tactics straight into tuning_configs/<chip>.json after every
    stage — the production path the recovery watchdog invokes after the
    hardware tier (no manual merge step)."""
    from flashinfer_tpu.tune import run_tuning_workload

    path = run_tuning_workload(
        stages=args.stage or None, merge_stem=args.stem,
        log=lambda m: print(m, flush=True),
    )
    print(f"tuning config written: {path}")
    return 0


def cmd_artifacts(args) -> int:
    """Artifact bundles (reference artifacts.py role): pack the local
    compile cache + tactic tables into a checksummed tarball, restore
    one, or audit presence."""
    from flashinfer_tpu import artifacts

    if args.action == "pack":
        out = artifacts.pack_artifacts(args.path or "flashinfer_tpu_artifacts.tgz")
        print(f"packed -> {out}")
    elif args.action == "unpack":
        if not args.path:
            print("unpack requires a bundle path", file=sys.stderr)
            return 2
        n = artifacts.unpack_artifacts(args.path)
        print(f"restored {n} files into {artifacts.env.cache_dir()}")
    else:
        for name, present in artifacts.get_artifacts_status():
            print(f"{'present' if present else 'MISSING':8s} {name}")
    return 0


def cmd_probe(args) -> int:
    """Chip-health probe: compile a trivial kernel in a subprocess under a
    timeout (the post-wedge recovery detector)."""
    from flashinfer_tpu import compile_guard

    r = compile_guard.probe(timeout_s=args.timeout)
    print(json.dumps(r, indent=1))
    return 0 if r["healthy"] else 1


def cmd_quarantine(args) -> int:
    from flashinfer_tpu import compile_guard

    if args.clear is not None:
        n = compile_guard.clear(args.clear or None)
        print(f"cleared {n} quarantine entries")
        return 0
    q = compile_guard._load_qlist()
    print(f"quarantine file: {compile_guard._qlist_path()}")
    print(f"entries        : {len(q)}")
    for fp, info in sorted(q.items()):
        print(f"  {fp}  op={info.get('op')}  reason={info.get('reason')}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="flashinfer_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn in [
        ("collect-env", cmd_collect_env),
        ("show-config", cmd_show_config),
        ("clear-cache", cmd_clear_cache),
        ("module-status", cmd_module_status),
        ("list-modules", cmd_list_modules),
        ("tuner-status", cmd_tuner_status),
        ("prewarm", cmd_prewarm),
    ]:
        sp = sub.add_parser(name)
        sp.set_defaults(fn=fn)
    sp = sub.add_parser("replay")
    sp.add_argument("dump_dir", help="a <op>_<idx> dir from LOGLEVEL=10 dumps")
    sp.set_defaults(fn=cmd_replay)
    sp = sub.add_parser("probe")
    sp.add_argument("--timeout", type=float, default=240.0)
    sp.set_defaults(fn=cmd_probe)
    sp = sub.add_parser("artifacts")
    sp.add_argument("action", choices=["status", "pack", "unpack"])
    sp.add_argument("path", nargs="?")
    sp.set_defaults(fn=cmd_artifacts)
    sp = sub.add_parser("tune")
    sp.add_argument(
        "--stage", action="append",
        choices=["norm", "decode", "prefill", "moe", "mla", "flash"],
        help="run only these stages (default: all, wedge-safe order)",
    )
    sp.add_argument(
        "--stem", default=None,
        help="tuning_configs file stem (default: from device_kind)",
    )
    sp.set_defaults(fn=cmd_tune)
    sp = sub.add_parser("quarantine")
    sp.add_argument(
        "--clear", nargs="?", const="", default=None,
        help="clear one fingerprint (or all with no value)",
    )
    sp.set_defaults(fn=cmd_quarantine)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
