"""Roofline attribution: cost x wall time x chip spec -> efficiency.

The joining half of the roofline model (Williams et al., CACM 2009):
:func:`attribute` takes a :class:`~flashinfer_tpu.obs.costmodel.Cost`,
a measured wall time, and a :class:`~flashinfer_tpu.obs.hwspec.ChipSpec`
and answers the only performance question that is portable across chip
generations — *what fraction of the binding hardware ceiling did this
run achieve?*

- ``t_mem = bytes_total / peak_HBM`` and ``t_comp = flops / peak_MXU``
  are the two roofline floors; the larger is the binding one
  (``bound`` = ``"memory"`` | ``"compute"``, decided by the op's
  arithmetic intensity vs the chip's ridge point).
- ``pct_roofline = max(t_mem, t_comp) / t_measured`` — 1.0 means the
  op runs exactly at the hardware ceiling for its *launched* work.
- ``effective_pct_roofline`` is the same fraction counting only
  *effective* (useful) work — for the fused work-unit prefill the gap
  to ``pct_roofline`` is exactly the padding/pruning waste PR 3's
  packing exists to shrink.  Equal when the op has no waste.

:func:`stamp_row` writes the canonical field set onto a bench row;
:func:`build_perf_report` is the ``obs perf`` doctor — it reproduces
the round-5 VERDICT analysis (per-op efficiency, bound classification,
worst offenders by pct-below-roofline x time share, waste attribution,
per-serving-phase MFU) from banked rows with no hand math.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import (Dict, Iterable, List, Mapping, Optional, Sequence,
                    Tuple)

from flashinfer_tpu.obs import costmodel, hwspec
from flashinfer_tpu.obs.costmodel import Cost
from flashinfer_tpu.obs.hwspec import ChipSpec

# the canonical roofline field set every stamped bench row carries
ROW_FIELDS = ("flops", "bytes_read", "bytes_written", "intensity",
              "bound", "pct_roofline", "effective_pct_roofline", "chip",
              "dtype")

# The BASELINE.md tracked-metric cells the VERDICT headline fractions
# quote (bench.py's non-sweep default configurations).  ``obs perf``
# computes its headline ranges over ok-quality rows of exactly these
# cells — the sweep grid's other cells inform the efficiency table but
# never the headline, matching how the round-5 numbers were derived.
HEADLINE_CELLS: Dict[str, tuple] = {
    "decode": ({"bs": 64, "ctx": 4096},),
    "prefill": (
        {"kind": "paged_chunked", "bs": 8, "qlen": 512, "ctx": 4096},
        {"kind": "ragged_flash", "qlen": 8192},
    ),
    "mla": ({"bs": 64, "ctx": 4096},),
}


@dataclasses.dataclass(frozen=True)
class RooflineResult:
    chip: str
    dtype: str
    achieved_tflops: float  # launched flops / t
    achieved_tflops_effective: float  # useful flops / t (== launched
    # when the op has no padding/pruning waste) — the number every
    # banked ``tflops`` field reports
    achieved_tbps: float  # launched bytes / t
    intensity: float  # flops/byte, launched
    ridge: float  # chip ridge point at this dtype
    bound: str  # "memory" | "compute" | "ici"
    pct_roofline: float  # fraction of the binding roofline, launched
    effective_pct_roofline: float  # same, useful work only
    mfu: float  # achieved_tflops / peak_tflops (launched)
    peak_tflops: float
    peak_tbps: float
    # the ICI dimension (0 for single-chip ops): fraction of the
    # measured time the predicted collective floor explains, and the
    # chip's interconnect ceiling it was priced against
    pct_ici_roofline: float = 0.0
    peak_ici_gbps: float = 0.0


def attribute(cost: Cost, seconds: float, spec: ChipSpec) -> RooflineResult:
    """Join one cost with one measured wall time on one chip.

    Three floors: HBM transfer, MXU compute, and — when the cost
    carries collective traffic (``ici_bytes``, the sharded serving
    families) — the ICI wire floor.  ``bound`` names the deepest one;
    ``pct_roofline`` keeps its meaning (binding floor / measured) with
    the ICI floor folded into the max."""
    if seconds <= 0:
        raise ValueError(f"seconds must be positive, got {seconds}")
    peak_tflops = spec.peak_tflops(cost.dtype)
    peak_tbps = spec.hbm_tbps
    t_mem = cost.bytes_total / (peak_tbps * 1e12)
    t_comp = cost.flops / (peak_tflops * 1e12)
    t_ici = cost.ici_bytes / (spec.ici_gbps * 1e9) \
        if cost.ici_bytes else 0.0
    if t_ici > max(t_mem, t_comp):
        bound = "ici"
    else:
        bound = "memory" if t_mem >= t_comp else "compute"
    eff_flops = cost.effective_flops
    t_roof_eff = max(t_mem, eff_flops / (peak_tflops * 1e12), t_ici)
    return RooflineResult(
        chip=spec.name, dtype=hwspec.normalize_dtype(cost.dtype),
        achieved_tflops=cost.flops / seconds / 1e12,
        achieved_tflops_effective=eff_flops / seconds / 1e12,
        achieved_tbps=cost.bytes_total / seconds / 1e12,
        intensity=cost.intensity,
        ridge=spec.ridge_intensity(cost.dtype),
        bound=bound,
        pct_roofline=max(t_mem, t_comp, t_ici) / seconds,
        effective_pct_roofline=t_roof_eff / seconds,
        mfu=cost.flops / seconds / 1e12 / peak_tflops,
        peak_tflops=peak_tflops, peak_tbps=peak_tbps,
        pct_ici_roofline=t_ici / seconds,
        peak_ici_gbps=spec.ici_gbps,
    )


def stamp_row(row: Dict, cost: Cost, seconds: float,
              spec: ChipSpec, *, num_splits: Optional[int] = None,
              merge_bytes: Optional[float] = None,
              step_mode: Optional[str] = None,
              mesh_axes: Optional[str] = None,
              attention_backend: Optional[str] = None,
              fused_ingest: Optional[bool] = None,
              ingest_bytes_avoided: Optional[float] = None) -> Dict:
    """Write the canonical roofline fields onto a bench row in place.
    Every bench.py routine stamps through here — the uniform schema is
    what makes ``obs perf`` and the auditor's roofline-fraction rule
    possible.

    ``num_splits``/``merge_bytes`` are the split-KV decode metadata
    (docs/observability.md): ``num_splits`` is part of the row's
    configuration identity (rows at different split factors never
    compete in the quality audit); ``merge_bytes`` is the cost model's
    partial-state traffic term (``costmodel.decode_split_breakdown``),
    a derived measurement field.

    ``step_mode`` is the serving-loop dispatch-structure identity
    (``"fused"`` — the compile-once donated serve/step.py program —
    vs ``"per_op"``, the per-phase jitted micro-loop): like
    num_splits it is CONFIGURATION, so the two serving-loop shapes
    keep separate audit histories even at identical model shapes.

    ``mesh_axes`` is the mesh-shape identity of a SHARDED row
    (``ShardingPlan.mesh_axes``, e.g. ``"dp1.tp8"``): configuration
    like step_mode — a tp8 row must never compete with tp1 history.
    Costs carrying collective traffic additionally stamp ``ici_bytes``
    and ``pct_ici_roofline`` (measurement fields: the predicted ICI
    wire bytes and the fraction of measured time the ICI floor
    explains).

    ``attention_backend`` is the serving-engine attention-tier
    identity (``"reference"`` — the dense XLA oracle — vs
    ``"kernel"`` — the Pallas work-unit lowering,
    serve/engine_kernels.py): configuration like step_mode, so a
    kernel-tier row never competes with reference-row history in the
    quality audit.

    ``fused_ingest`` is the prefill ingest-mode identity (the ISSUE 14
    RoPE + quantize-append fusion, ops/paged_prefill.py): configuration
    like step_mode/attention_backend, so an A/B pair's fused and
    separate rows keep separate banked histories and never compete.
    ``ingest_bytes_avoided`` is the cost model's predicted avoided-HBM
    delta for the row's shape (``costmodel.prefill_ingest_breakdown``)
    — derived, a MEASUREMENT field like merge_bytes."""
    res = attribute(cost, seconds, spec)
    if num_splits is not None:
        row["num_splits"] = int(num_splits)
    if merge_bytes is not None:
        row["merge_bytes"] = float(merge_bytes)
    if step_mode is not None:
        row["step_mode"] = str(step_mode)
    if mesh_axes is not None:
        row["mesh_axes"] = str(mesh_axes)
    if attention_backend is not None:
        row["attention_backend"] = str(attention_backend)
    if fused_ingest is not None:
        row["fused_ingest"] = bool(fused_ingest)
    if ingest_bytes_avoided is not None:
        row["ingest_bytes_avoided"] = float(ingest_bytes_avoided)
    if cost.ici_bytes:
        row["ici_bytes"] = float(cost.ici_bytes)
        row["pct_ici_roofline"] = round(res.pct_ici_roofline, 4)
    row["flops"] = float(cost.flops)
    row["bytes_read"] = float(cost.bytes_read)
    row["bytes_written"] = float(cost.bytes_written)
    row["intensity"] = round(res.intensity, 3)
    row["bound"] = res.bound
    row["pct_roofline"] = round(res.pct_roofline, 4)
    row["effective_pct_roofline"] = round(res.effective_pct_roofline, 4)
    row["chip"] = res.chip
    row["dtype"] = res.dtype
    # self-describing rows: a banked row re-attributes with no shape
    # reconstruction (costmodel.cost_from_stamped_row), so the waste
    # split must ride along when it exists
    if cost.flops_effective is not None \
            and cost.flops_effective != cost.flops:
        row["flops_effective"] = float(cost.flops_effective)
    return row


def spec_for_row(row: Mapping,
                 default: Optional[ChipSpec] = None) -> ChipSpec:
    """The chip a banked row was measured on: its ``chip`` field, else
    its ``peak`` (HBM TB/s) mapped back through the registry, else
    `default` (v5e — every pre-roofline banked row)."""
    if row.get("chip"):
        return hwspec.spec(str(row["chip"]))
    s = hwspec.spec_for_peak_tbps(row.get("peak"))
    if s is not None:
        return s
    return default or hwspec.CHIP_SPECS[hwspec.DEFAULT_CHIP]


def timeline_phase_mfu(events: Iterable[Mapping],
                       phase_costs: Mapping[str, Cost],
                       spec: ChipSpec,
                       prefix: str = "serving.") -> Dict[str, dict]:
    """Join profiler timeline spans with per-phase costs: aggregate
    span durations by name (stripping `prefix`) and attribute each
    phase that has a cost.  The device-trace cross-check for the
    micro-loop decomposition numbers."""
    durs: Dict[str, float] = {}
    for e in events:
        name = str(e.get("name", ""))
        if name.startswith(prefix):
            name = name[len(prefix):]
        durs[name] = durs.get(name, 0.0) + float(e.get("dur", 0.0))
    out: Dict[str, dict] = {}
    for phase, cost in phase_costs.items():
        t = durs.get(phase)
        if t and t > 0:
            res = attribute(cost, t, spec)
            out[phase] = {"dur_s": t, "mfu": round(res.mfu, 4),
                          "bound": res.bound,
                          "pct_roofline": round(res.pct_roofline, 4)}
    return out


# -------------------------------------------------------------------------
# `obs perf`: the doctor report over banked bench rows
# -------------------------------------------------------------------------


def _row_group(row: Mapping) -> str:
    """Stable per-op grouping key for the efficiency table."""
    parts = [str(row.get("phase"))]
    for f in ("kind", "op", "variant", "backend", "mode", "layout",
              "step_mode", "mesh_axes"):
        if row.get(f) is not None:
            parts.append(f"{row[f]}")
    return "/".join(parts)


# -------------------------------------------------------------------------
# ICI-aware predictions: per-phase collective attribution + the
# tp1->tp8 scaling curve per chip generation (the before-hardware
# multi-chip story: dryrun + model instead of blocked on the driver)
# -------------------------------------------------------------------------

SCALING_CHIPS = ("v5e", "v5p")
SCALING_TPS = (1, 2, 4, 8)
# the canonical sharded serving cell the predictions quote (the
# BASELINE.md serving north star at full model depth)
SCALING_CELL = dict(bs=64, ctx=4096, layers=80, model="llama70b_int8")


def predict_serving_scaling(*, bs: int = 64, ctx: int = 4096,
                            layers: int = 80,
                            model: str = "llama70b_int8",
                            chips: Sequence[str] = SCALING_CHIPS,
                            tps: Sequence[int] = SCALING_TPS) -> dict:
    """Predicted tp scaling of the sharded serving step per chip gen:
    for each tp, the roofline-forward step time (HBM/MXU floor + serial
    ICI floor, ``costmodel.predict_step_seconds``) of the PER-CHIP
    shard, plus speedup vs tp1 and scaling efficiency (speedup/tp —
    the number that says where ICI starts eating the linear win)."""
    shape = costmodel.SHARDED_SERVING_SHAPES[model]
    out: Dict[str, dict] = {}
    for chip in chips:
        spec = hwspec.spec(chip)
        rows: Dict[str, dict] = {}
        t1 = None
        for tp in tps:
            cost = costmodel.serving_step_sharded(
                bs, ctx, layers, dp=1, tp=tp, **shape)
            t = costmodel.predict_step_seconds(
                cost, hbm_tbps=spec.hbm_tbps,
                peak_tflops=spec.peak_tflops(cost.dtype),
                ici_gbps=spec.ici_gbps)
            t_ici = cost.ici_bytes / (spec.ici_gbps * 1e9)
            if t1 is None:
                t1 = t
            res = attribute(cost, t, spec)
            rows[str(tp)] = {
                "pred_us": round(t * 1e6, 1),
                "ici_us": round(t_ici * 1e6, 2),
                "ici_bytes": cost.ici_bytes,
                "bound": res.bound,
                "speedup_vs_tp1": round(t1 / t, 3),
                "scaling_efficiency": round(t1 / t / tp, 3),
            }
        out[spec.name] = rows
    return out


def predict_kv_migrate(*, ctx: int = 4096, layers: int = 80,
                       model: str = "llama70b_int8",
                       chips: Sequence[str] = SCALING_CHIPS) -> dict:
    """Predicted prefill->decode KV handoff cost per REQUEST at one
    context length: the ``costmodel.kv_migrate`` page-run x
    kv-byte-width wire formula priced per chip generation — the
    before-hardware half of the disaggregated serving story
    (serve/kv_tier.py), joined against measured migration stamps by
    the perf/3 ``serving_disagg`` section."""
    shape = costmodel.SHARDED_SERVING_SHAPES[model]
    cost = costmodel.kv_migrate(
        ctx, page_size=shape["page_size"], num_kv_heads=shape["hkv"],
        head_dim=shape["hd"], layers=layers,
        kv_bytes=shape["kv_bytes"])
    return {
        "model": model, "ctx": ctx, "layers": layers,
        "ici_bytes_per_request": cost.ici_bytes,
        "pred_ici_us": {
            hwspec.spec(c).name: round(
                cost.ici_bytes / (hwspec.spec(c).ici_gbps * 1e9) * 1e6,
                2)
            for c in chips},
    }


# the headline prefill cells' ingest geometry: (name, total_q,
# total_kv, HQ, HKV, D) — the bench.py prefill phase shapes the VERDICT
# fractions quote (HEADLINE_CELLS), flattened to token totals
_INGEST_CELLS = (
    ("paged_bs8_q512_ctx4096", 8 * 512, 8 * 4096, 32, 8, 128),
    ("ragged_T8192", 8192, 8192, 32, 8, 128),
)


def predict_prefill_ingest(*, chips: Sequence[str] = SCALING_CHIPS,
                           cells: Sequence[tuple] = _INGEST_CELLS) -> dict:
    """The perf/4 prefill-ingest section, predicted half: for each
    headline prefill cell, the separate-vs-fused modeled HBM bytes
    (``costmodel.prefill_ingest_breakdown``) and the per-chip chooser
    verdict (``predict_prefill_ingest_win`` — the rule that decides the
    ``prefill.fused_ingest`` knob default).  The ISSUE 14 acceptance
    bar — headline shapes drop >= 20% of modeled HBM bytes — is read
    straight off ``avoided_fraction`` here."""
    out: Dict[str, dict] = {}
    for name, tq, tkv, hq, hkv, hd in cells:
        bd = costmodel.prefill_ingest_breakdown(tq, tkv, hq, hkv, hd)
        verdicts = {}
        for chip in chips:
            spec = hwspec.spec(chip)
            use, ev = costmodel.predict_prefill_ingest_win(
                tq, tkv, hq, hkv, hd, hbm_tbps=spec.hbm_tbps,
                peak_tflops=spec.peak_tflops("bf16"))
            verdicts[spec.name] = {
                "use_fused": use,
                "pred_sep_us": round(ev["separate_s"] * 1e6, 1),
                "pred_fused_us": round(ev["fused_s"] * 1e6, 1),
            }
        out[name] = {
            "separate_bytes": bd["separate_bytes"],
            "fused_bytes": bd["fused_bytes"],
            "bytes_avoided": bd["bytes_avoided"],
            "avoided_fraction": round(bd["avoided_fraction"], 4),
            "chips": verdicts,
        }
    return out


def _prefill_ingest(attributed: Sequence[Mapping]) -> dict:
    """The perf/4 prefill-ingest section: the predicted byte drop per
    headline cell joined with every banked prefill row that carries the
    ingest identity stamp (``fused_ingest`` + ``ingest_bytes_avoided``,
    the bench prefill A/B pair) — so the MFU table's effective-vs-
    launched story shows what the fusion accounted for."""
    measured: List[dict] = []
    for a in attributed:
        row = a["row"]
        # both A/B harnesses join: bench.py's prefill phase pair AND
        # the bench_prefill_blocks.py --sweep-ingest cells
        if row.get("phase") not in ("prefill", "prefill_blocks") \
                or row.get("fused_ingest") is None:
            continue
        m = {k: row[k] for k in (
            "kind", "bs", "qlen", "ctx", "fused_ingest",
            "ingest_bytes_avoided", "us", "tflops", "bound",
            "pct_roofline", "effective_pct_roofline", "chip")
            if row.get(k) is not None}
        measured.append(m)
    return {"predicted": predict_prefill_ingest(), "rows": measured}


def predict_serving_ici(*, bs: int = 64, ctx: int = 4096,
                        layers: int = 80, tp: int = 8, dp: int = 1,
                        model: str = "llama70b_int8",
                        chips: Sequence[str] = SCALING_CHIPS) -> dict:
    """Per-serving-phase predicted collective traffic and wire time at
    one mesh shape: which phase's collectives cost what, per chip gen —
    the attribution half of the ICI dimension (`obs perf`).  The
    ``kv_migrate`` key rides alongside the per-step phases: the
    PER-REQUEST prefill->decode handoff wire cost of the disaggregated
    tier at the same cell (it is not a per-step collective, so it
    never joins the phase sum)."""
    shape = costmodel.SHARDED_SERVING_SHAPES[model]
    phases = costmodel.serving_phase_costs_sharded(
        bs, ctx, layers, dp=dp, tp=tp, **shape)
    table: Dict[str, dict] = {}
    for name in costmodel.SERVING_PHASES:
        cost = phases[name]
        if not cost.ici_bytes:
            continue
        table[name] = {
            "ici_bytes": cost.ici_bytes,
            "pred_ici_us": {
                hwspec.spec(c).name: round(
                    cost.ici_bytes / (hwspec.spec(c).ici_gbps * 1e9)
                    * 1e6, 2)
                for c in chips},
        }
    return {"model": model, "bs": bs, "ctx": ctx, "layers": layers,
            "mesh_axes": f"dp{dp}.tp{tp}", "phases": table,
            "kv_migrate": predict_kv_migrate(
                ctx=ctx, layers=layers, model=model, chips=chips)}


def _attributed_rows(rows: Sequence[Mapping],
                     default_spec: Optional[ChipSpec] = None
                     ) -> Tuple[List[dict], int]:
    """Attribute every attributable row: stamped fields when present,
    else the cost model's reconstruction from config.  Returns
    ``(attributed, n_implausible)``.

    Every row is first RE-audited against the full history (a
    :class:`~flashinfer_tpu.obs.bench_audit.RowAuditor` seeded with all
    rows): pre-stamping banked rows carry no ``quality`` field, and an
    emit-time ``ok`` can become retroactively implausible once later
    runs measured the same cell 3x faster.  Re-audited poison rows are
    dropped, and so is any row whose attributed fraction exceeds the
    binding hardware ceiling (pre-roofline banked rows carry no
    ``pct_roofline`` for the auditor's own too-fast rule to see) — the
    report never quotes a machine-flagged artifact."""
    from flashinfer_tpu.obs import bench_audit

    auditor = bench_audit.RowAuditor(rows)
    out: List[dict] = []
    implausible = 0
    for row in rows:
        quality = auditor.stamp(dict(row)).get("quality", "ok")
        if quality == "poison":
            continue  # machine-flagged artifacts never drive analysis
        spec = spec_for_row(row, default_spec)
        rec = costmodel.cost_for_bench_row(row)
        if rec is None:
            continue
        cost, seconds = rec
        if not (seconds > 0):
            continue
        res = attribute(cost, seconds, spec)
        if res.pct_roofline > bench_audit.IMPLAUSIBLY_FAST_ROOFLINE:
            implausible += 1
            continue
        out.append({
            "group": _row_group(row), "phase": row.get("phase"),
            "row": dict(row), "seconds": seconds, "cost": cost,
            "res": res, "quality": quality,
        })
    return out, implausible


def _in_headline_cell(a: Mapping) -> bool:
    cells = HEADLINE_CELLS.get(a["phase"], ())
    return any(all(a["row"].get(k) == v for k, v in cell.items())
               for cell in cells)


def _headline(attributed: List[dict]) -> dict:
    """The round-5 VERDICT fractions, recomputed — no hand math.
    Ranges run over ok-quality rows of the HEADLINE_CELLS only (the
    tracked-metric configurations), exactly the rows the VERDICT
    quoted: decode across repeated runs of the bs64/ctx4k cell,
    prefill MFU across the paged + ragged headline shapes, MLA across
    both layouts of its headline cell."""
    ok = [a for a in attributed
          if a["quality"] == "ok" and _in_headline_cell(a)]

    def fracs(phase, eff=False):
        return sorted(
            (a["res"].effective_pct_roofline if eff
             else a["res"].pct_roofline)
            for a in ok if a["phase"] == phase)

    decode = fracs("decode")
    prefill = fracs("prefill", eff=True)
    mla = fracs("mla")
    h: dict = {}
    if decode:
        h["decode_bs64_ctx4k_pct_roofline"] = {
            "min": round(decode[0], 4), "max": round(decode[-1], 4)}
    if prefill:
        h["prefill_mfu"] = {"min": round(prefill[0], 4),
                            "max": round(prefill[-1], 4)}
    if mla:
        h["mla_pct_roofline"] = {"min": round(mla[0], 4),
                                 "max": round(mla[-1], 4)}
    return h


def _serving_disagg(attributed: Sequence[Mapping]) -> dict:
    """The perf/3 disaggregation section: the predicted per-request
    ``kv_migrate`` wire cost at the canonical cell, joined against
    every banked ``serving_disagg`` row's MEASURED migration stamps
    (``migrate_bytes`` / ``migrate_us`` are measurement fields the
    bench phase emits).  ``measured_vs_pred_wire`` > 1 means the real
    handoff ran slower than the ICI floor — the gap is scheduling +
    staging overhead, exactly what the disagg session must shrink."""
    pred = predict_kv_migrate(
        ctx=SCALING_CELL["ctx"], layers=SCALING_CELL["layers"],
        model=SCALING_CELL["model"])
    measured: List[dict] = []
    for a in attributed:
        row = a["row"]
        if row.get("phase") != "serving_disagg":
            continue
        m = {k: row[k] for k in (
            "mode", "migrations", "migrate_bytes", "migrate_us",
            "spills", "restores", "recomputes", "ici_bytes",
            "pct_ici_roofline", "bound", "chip")
            if row.get(k) is not None}
        mb = row.get("migrate_bytes")
        if isinstance(mb, (int, float)) and mb > 0:
            spec = spec_for_row(row)
            wire_us = mb / (spec.ici_gbps * 1e9) * 1e6
            m["pred_wire_us"] = round(wire_us, 2)
            mu = row.get("migrate_us")
            if isinstance(mu, (int, float)) and mu > 0 and wire_us > 0:
                m["measured_vs_pred_wire"] = round(mu / wire_us, 3)
        measured.append(m)
    return {"predicted_kv_migrate": pred, "rows": measured}


def _host_loop(attributed: Sequence[Mapping]) -> dict:
    """The perf/5 host-loop section: the step-loop flight deck's
    host-gap decomposition joined from two directions.

    Banked side: serving rows stamped with ``host_frac`` /
    ``host_gap_us`` / ``pred_step_ratio`` (bench measurement fields,
    never identity) each get the Amdahl projection ``1 / (1 -
    host_frac)`` — the speedup CEILING ROADMAP item 4's host/device
    pipeline refactor can buy for that cell (the host work still
    exists, it just overlaps; real wins land below the ceiling).

    Live side: when the calling process has already loaded the steploop
    ledger (``obs steploop --selftest``, an instrumented run ending in
    ``obs perf``), its summary joins as ``live`` — real ledger data.
    The module is looked up, NEVER imported: a plain ``obs perf`` over
    banked rows keeps the zero-overhead default intact."""
    import sys as _sys

    measured: List[dict] = []
    for a in attributed:
        row = a["row"]
        hf = row.get("host_frac")
        if hf is None or not str(row.get("phase", "")).startswith(
                "serving"):
            continue
        m = {k: row[k] for k in (
            "phase", "model", "mode", "variant", "step_mode",
            "attention_backend", "bs", "ctx", "us_step", "host_gap_us",
            "host_frac", "pred_step_ratio", "chip")
            if row.get(k) is not None}
        m["amdahl_ceiling"] = round(1.0 / max(1.0 - float(hf), 1e-3), 3)
        measured.append(m)
    out: dict = {"rows": measured}
    if measured:
        worst = max(measured, key=lambda m: float(m["host_frac"]))
        fracs = sorted(float(m["host_frac"]) for m in measured)
        out["host_frac_median"] = round(fracs[len(fracs) // 2], 4)
        out["worst"] = {
            "phase": worst.get("phase"), "mode": worst.get("mode"),
            "host_frac": worst["host_frac"],
            "amdahl_ceiling": worst["amdahl_ceiling"],
        }
    sl = _sys.modules.get("flashinfer_tpu.obs.steploop")
    if sl is not None:
        s = sl.summarize()
        if s.get("steps"):
            out["live"] = {
                "steps": s["steps"],
                "idle_ticks": s["idle_ticks"],
                "surfaces": s["surfaces"],
                "host_frac": s["host_frac"],
                "overlap_efficiency": s["overlap_efficiency"],
                "amdahl_ceiling": s["amdahl_ceiling"],
                "worst_phase": s["worst_phase"],
                "phases_us": s["phases"],
                "drift": s["drift"],
            }
    return out


def _graduation(attributed: Sequence[Mapping]) -> dict:
    """The perf/6 graduation section: per tuning-config section, where
    it stands in the hardware graduation pipeline —

    - ``measured``: provenance already flipped by ``obs bringup
      --graduate`` (carries journal_id + banked_row references that
      L006 requires),
    - ``quarantined``: a bring-up smoke-ladder rung that feeds this
      section wedged the chip (the quarantine entry's ``bench_phases``
      intersect the section's banked phases),
    - ``pending``: still shipping seed/model-derived tactics.

    Plus the predicted-vs-measured audit join ROADMAP item 1 demands:
    for each perf/2–perf/4 prediction family, how many banked rows of
    its measuring phase exist — the count that turns a prediction
    section from forecast into audit."""
    try:
        from flashinfer_tpu.obs import bringup
        section_phases = bringup.SECTION_BANK_PHASES
        quarantined_phases = set(bringup.quarantined_bench_phases())
        cfg_dir = bringup._default_configs_dir()
    except Exception:
        return {"sections": [], "audit": {}}
    sections: List[dict] = []
    try:
        cfg_files = sorted(fn for fn in os.listdir(cfg_dir)
                           if fn.endswith(".json"))
    except OSError:
        cfg_files = []
    for fn in cfg_files:
        try:
            cfg = json.loads(open(os.path.join(cfg_dir, fn)).read())
        except Exception:
            continue
        for name, sec in sorted(cfg.items()):
            if not isinstance(sec, dict) or "tactics" not in sec \
                    or name == "tactics":
                continue
            phases = section_phases.get(name, (name,))
            if sec.get("provenance") == "measured":
                status = "measured"
            elif quarantined_phases.intersection(phases):
                status = "quarantined"
            else:
                status = "pending"
            entry = {
                "chip": fn[:-5], "section": name, "status": status,
                "provenance": sec.get("provenance"),
                "tactics": len(sec.get("tactics") or {}),
            }
            if sec.get("journal_id"):
                entry["journal_id"] = sec["journal_id"]
            if sec.get("banked_row"):
                entry["banked_row"] = sec["banked_row"]
            sections.append(entry)
    # audit join: prediction family -> measured banked rows by phase
    by_phase: Dict[str, int] = {}
    for a in attributed:
        ph = a["row"].get("phase")
        if isinstance(ph, str):
            by_phase[ph] = by_phase.get(ph, 0) + 1
    audit = {
        "serving_ici": {"predicted_schema": "perf/2",
                        "measured_rows": by_phase.get("serving_sharded", 0)},
        "serving_disagg": {"predicted_schema": "perf/3",
                           "measured_rows": by_phase.get(
                               "serving_disagg", 0)},
        "prefill_ingest": {"predicted_schema": "perf/4",
                           "measured_rows": by_phase.get("prefill", 0)},
        "host_loop": {"predicted_schema": "perf/5",
                      "measured_rows": sum(
                          n for ph, n in by_phase.items()
                          if ph.startswith("serving"))},
    }
    counts: Dict[str, int] = {}
    for s in sections:
        counts[s["status"]] = counts.get(s["status"], 0) + 1
    return {"sections": sections, "status_counts": counts, "audit": audit}


def build_perf_report(rows: Sequence[Mapping], *,
                      chip: Optional[str] = None) -> dict:
    """The ``obs perf`` report over bench rows (typically the banked
    history): per-op efficiency, bound classification, worst offenders
    by (pct-below-roofline x time share), waste attribution, per-phase
    serving MFU, and the recomputed VERDICT headline fractions."""
    default_spec = hwspec.spec(chip) if chip else None
    attributed, implausible = _attributed_rows(rows, default_spec)

    groups: Dict[str, List[dict]] = {}
    for a in attributed:
        groups.setdefault(a["group"], []).append(a)

    total_time = sum(a["seconds"] for a in attributed) or 1.0
    ops = []
    for name in sorted(groups):
        g = groups[name]
        pcts = sorted(a["res"].pct_roofline for a in g)
        effs = sorted(a["res"].effective_pct_roofline for a in g)
        best = max(g, key=lambda a: a["res"].pct_roofline)
        share = sum(a["seconds"] for a in g) / total_time
        ops.append({
            "op": name, "rows": len(g),
            "bound": best["res"].bound,
            "chip": best["res"].chip, "dtype": best["res"].dtype,
            "intensity": round(best["res"].intensity, 2),
            "pct_roofline": {
                "median": round(pcts[len(pcts) // 2], 4),
                "best": round(pcts[-1], 4)},
            "effective_pct_roofline": {
                "median": round(effs[len(effs) // 2], 4),
                "best": round(effs[-1], 4)},
            "best_achieved": {
                "tflops": round(best["res"].achieved_tflops, 2),
                "tbps": round(best["res"].achieved_tbps, 4)},
            "time_share": round(share, 4),
        })

    # worst offenders: how much of the measured time budget is lost to
    # running below roofline — (1 - best pct) x time share, the ranking
    # the VERDICT derived by hand for "make prefill fast" / "fix MLA"
    offenders = sorted(
        ({"op": o["op"], "bound": o["bound"],
          "pct_below_roofline": round(1.0 - o["pct_roofline"]["best"], 4),
          "time_share": o["time_share"],
          "severity": round((1.0 - o["pct_roofline"]["best"])
                            * o["time_share"], 4)}
         for o in ops if o["pct_roofline"]["best"] < 1.0),
        key=lambda d: -d["severity"])

    # padding/pruning waste: launched-vs-effective on rows that carry
    # the fused-prefill stats (new rows) — the packing attribution
    waste = []
    for a in attributed:
        c = a["cost"]
        if c.flops_effective is not None and c.flops > 0 \
                and c.flops_effective < c.flops:
            waste.append({
                "op": a["group"],
                "launched_flops": c.flops,
                "effective_flops": c.flops_effective,
                "waste_pct": round(
                    100.0 * (1.0 - c.flops_effective / c.flops), 2),
            })

    # serving-loop per-phase MFU: join the e2e row's measured
    # overhead_decomposition with the phase cost model
    serving = []
    for a in attributed:
        row = a["row"]
        if row.get("mode") != "e2e_measured":
            continue
        decomp = row.get("overhead_decomposition") or {}
        shape = costmodel.SERVING_SHAPES.get(str(row.get("model", "")))
        if not decomp or shape is None:
            continue
        phase_costs = costmodel.serving_phase_costs(
            int(row["bs"]), int(row["ctx"]), int(row["layers"]), **shape)
        spec = spec_for_row(row, default_spec)
        phases = {}
        for name, cost in phase_costs.items():
            us = decomp.get(name + "_us")
            if isinstance(us, (int, float)) and us > 0:
                res = attribute(cost, us * 1e-6, spec)
                phases[name] = {
                    "us": us, "bound": res.bound,
                    "mfu": round(res.mfu, 4),
                    "pct_roofline": round(res.pct_roofline, 4)}
        serving.append({
            "model": row.get("model"), "bs": row.get("bs"),
            "ctx": row.get("ctx"), "layers": row.get("layers"),
            "residual_us": decomp.get("residual_us"),
            "phases": phases,
        })

    return {
        "schema": "flashinfer_tpu.obs.perf/6",
        "chips": {name: dataclasses.asdict(s)
                  for name, s in sorted(hwspec.CHIP_SPECS.items())
                  if any(a["res"].chip == name for a in attributed)},
        "rows_total": len(rows),
        "rows_attributed": len(attributed),
        "rows_implausible": implausible,
        "ops": ops,
        "worst_offenders": offenders,
        "waste": waste,
        "serving_phase_mfu": serving,
        # the ICI dimension (perf/2): model-predicted, so it exists
        # before any multi-chip hardware does — per-phase collective
        # attribution at the canonical sharded cell + the tp scaling
        # curve per chip generation
        "serving_ici": predict_serving_ici(**SCALING_CELL),
        "scaling_prediction": predict_serving_scaling(**SCALING_CELL),
        # the tiered-KV dimension (perf/3): predicted per-request
        # kv_migrate wire cost + the measured migration stamps of
        # banked serving_disagg rows, joined
        "serving_disagg": _serving_disagg(attributed),
        # the prefill-ingest dimension (perf/4): predicted separate-vs-
        # fused byte drop at the headline prefill cells + the banked
        # ingest A/B rows, joined (ISSUE 14)
        "prefill_ingest": _prefill_ingest(attributed),
        # the host-loop dimension (perf/5): step-loop flight-deck
        # host-gap decomposition + the Amdahl projection, from banked
        # host_frac stamps and (when present) the live steploop ledger
        "host_loop": _host_loop(attributed),
        # the graduation dimension (perf/6): per tuning-config section,
        # pending | measured | quarantined in the hardware bring-up
        # pipeline, plus the predicted-vs-measured audit join of the
        # perf/2-perf/4 prediction families against banked phases
        "graduation": _graduation(attributed),
        "headline": _headline(attributed),
    }


def render_perf_report(report: Mapping) -> str:
    """Human rendering of :func:`build_perf_report` output."""
    lines: List[str] = []
    lines.append(f"# roofline attribution — "
                 f"{report['rows_attributed']}/{report['rows_total']} "
                 f"rows attributed")
    if report.get("rows_implausible"):
        lines.append(f"# {report['rows_implausible']} row(s) dropped: "
                     f"measured above the hardware ceiling (timer "
                     f"artifacts)")
    for name, s in report.get("chips", {}).items():
        lines.append(
            f"# chip {name}: {s['hbm_tbps']} TB/s HBM, "
            f"{s['mxu_tflops']['bf16']:g} bf16 / "
            f"{s['mxu_tflops']['int8']:g} int8 TFLOP/s")
    lines.append("")
    lines.append(f"{'op':38s} {'bound':7s} {'pct_roof':>9s} "
                 f"{'eff_pct':>8s} {'t_share':>8s}  best achieved")
    for o in report["ops"]:
        ach = o["best_achieved"]
        a = (f"{ach['tbps']:.3f} TB/s" if o["bound"] == "memory"
             else f"{ach['tflops']:.1f} TFLOP/s ({o['dtype']})")
        lines.append(
            f"{o['op'][:38]:38s} {o['bound']:7s} "
            f"{o['pct_roofline']['best']:9.3f} "
            f"{o['effective_pct_roofline']['best']:8.3f} "
            f"{o['time_share']:8.3f}  {a}")
    if report["worst_offenders"]:
        lines.append("")
        lines.append("worst offenders (pct-below-roofline x time share):")
        for w in report["worst_offenders"][:8]:
            lines.append(
                f"  {w['op'][:40]:40s} severity {w['severity']:.4f} "
                f"({w['pct_below_roofline']:.0%} below, "
                f"{w['time_share']:.1%} of time, {w['bound']}-bound)")
    if report["waste"]:
        lines.append("")
        lines.append("padding/pruning waste (launched vs effective):")
        for w in report["waste"][:8]:
            lines.append(f"  {w['op'][:40]:40s} {w['waste_pct']:.1f}% "
                         f"of launched FLOPs were padding")
    for s in report["serving_phase_mfu"]:
        lines.append("")
        lines.append(f"serving phase MFU ({s['model']} bs={s['bs']} "
                     f"ctx={s['ctx']} L={s['layers']}, residual "
                     f"{s['residual_us']} us):")
        for name, p in s["phases"].items():
            lines.append(f"  {name:12s} {p['us']:10.1f} us  "
                         f"mfu {p['mfu']:.3f}  "
                         f"pct_roofline {p['pct_roofline']:.3f} "
                         f"({p['bound']})")
    si = report.get("serving_ici")
    if si and si.get("phases"):
        lines.append("")
        lines.append(
            f"predicted serving collectives ({si['model']} bs={si['bs']} "
            f"ctx={si['ctx']} L={si['layers']}, {si['mesh_axes']}):")
        for name, p in si["phases"].items():
            per_chip = "  ".join(f"{c} {us:.1f} us"
                                 for c, us in p["pred_ici_us"].items())
            lines.append(f"  {name:12s} {p['ici_bytes'] / 1e6:10.2f} MB "
                         f"ICI/step  {per_chip}")
    sd = report.get("serving_disagg")
    if sd:
        p = sd["predicted_kv_migrate"]
        per_chip = "  ".join(f"{c} {us:.1f} us"
                             for c, us in p["pred_ici_us"].items())
        lines.append("")
        lines.append(
            f"predicted kv_migrate handoff ({p['model']} ctx={p['ctx']} "
            f"L={p['layers']}): "
            f"{p['ici_bytes_per_request'] / 1e6:.2f} MB/request  "
            f"{per_chip}")
        for m in sd.get("rows", []):
            ratio = m.get("measured_vs_pred_wire")
            lines.append(
                f"  measured {m.get('mode', '?'):10s} "
                f"{m.get('migrations', 0):5d} migrations, "
                f"{float(m.get('migrate_bytes', 0)) / 1e6:10.2f} MB"
                + (f"  {ratio:.2f}x pred wire" if ratio else ""))
    pi = report.get("prefill_ingest")
    if pi:
        lines.append("")
        lines.append("predicted prefill-ingest byte drop (separate-op "
                     "vs fused, headline cells):")
        for name, cell in pi["predicted"].items():
            chips = "  ".join(
                f"{c} {'ON' if v['use_fused'] else 'off'}"
                for c, v in cell["chips"].items())
            lines.append(
                f"  {name:24s} {cell['separate_bytes'] / 1e6:9.1f} -> "
                f"{cell['fused_bytes'] / 1e6:9.1f} MB  "
                f"(-{cell['avoided_fraction']:.0%})  knob: {chips}")
        for m in pi.get("rows", []):
            lines.append(
                f"  measured {'fused ' if m.get('fused_ingest') else 'separate'}"
                f" {m.get('kind', '?')} qlen={m.get('qlen')}: "
                f"{m.get('us', 0):.1f} us"
                + (f"  ({float(m['ingest_bytes_avoided']) / 1e6:.1f} MB"
                   f" avoided pred)" if m.get("ingest_bytes_avoided")
                   else ""))
    hl = report.get("host_loop")
    if hl and (hl.get("rows") or hl.get("live")):
        lines.append("")
        lines.append("host loop (step-loop flight deck — Amdahl ceiling "
                     "= max speedup a perfect host/device pipeline buys):")
        for m in hl.get("rows", []):
            tag = m.get("mode") or m.get("variant") \
                or m.get("step_mode") or ""
            lines.append(
                f"  {m.get('phase', '?'):16s} {str(tag):12s} "
                f"host_frac {float(m['host_frac']):.3f}  "
                f"gap {float(m.get('host_gap_us', 0)):9.1f} us  "
                f"ceiling {m['amdahl_ceiling']:.2f}x"
                + (f"  pred/meas {float(m['pred_step_ratio']):.3f}"
                   if m.get("pred_step_ratio") is not None else ""))
        live = hl.get("live")
        if live:
            drift = live.get("drift") or {}
            lines.append(
                f"  live ledger: {live['steps']} steps "
                f"({live['idle_ticks']} idle), host_frac "
                f"{live['host_frac']:.3f}, ceiling "
                f"{live['amdahl_ceiling']:.2f}x, worst sub-phase "
                f"{live['worst_phase']}"
                + (f", drift p50 {drift.get('p50', 0):.3f}"
                   if drift else ""))
    grad = report.get("graduation")
    if grad and grad.get("sections"):
        lines.append("")
        counts = grad.get("status_counts", {})
        lines.append(
            "graduation (hardware bring-up pipeline): "
            + "  ".join(f"{k} {v}" for k, v in sorted(counts.items())))
        for s in grad["sections"]:
            ref = ""
            if s["status"] == "measured":
                ref = f"  journal {s.get('journal_id', '?')}"
            lines.append(
                f"  {s['chip']:6s} {s['section']:16s} "
                f"{s['status']:11s} ({s['tactics']} tactic(s)){ref}")
        audit = grad.get("audit") or {}
        if audit:
            lines.append("  predicted-vs-measured audit join:")
            for fam, a in audit.items():
                lines.append(
                    f"    {fam:16s} {a['predicted_schema']:7s} "
                    f"measured rows: {a['measured_rows']}")
    sc = report.get("scaling_prediction")
    if sc:
        lines.append("")
        lines.append("predicted tp scaling (sharded serving step, "
                     "speedup vs tp1 / scaling efficiency):")
        for chip, rows in sc.items():
            cells = "  ".join(
                f"tp{tp}: {r['speedup_vs_tp1']:.2f}x/"
                f"{r['scaling_efficiency']:.2f}"
                for tp, r in rows.items())
            lines.append(f"  {chip}: {cells}")
    h = report.get("headline", {})
    if h:
        lines.append("")
        lines.append("headline fractions (the round-5 VERDICT numbers, "
                     "recomputed):")
        for key, rng in h.items():
            lines.append(f"  {key}: {rng['min']:.3f} - {rng['max']:.3f}")
    return "\n".join(lines) + "\n"
