"""Hardware graduation observatory — ``python -m flashinfer_tpu.obs bringup``.

ROADMAP item 1's chip session, turned from a prose checklist into a
machine-driven harness (the reference library survives this class of
risk by making kernel specialization + validation machine-driven; our
analog consumes the machine-readable risk registries directly):

**Smoke ladder.**  One minimal real launch per risky
(kernel, construct, tactic) triple, generated from three registries —
L015 ``mosaic_risks`` in analysis/baseline.json (riskiest construct
class first: strided-lane, then lane-slice, then cast), L007
``PLANNER_KERNELS`` (plan/run contract pairs), and L009
``KNOB_LAUNCHES`` (one rung per autotuned knob, carrying the shipped
tactic for the session's chip).  Each rung runs in its own subprocess
under a timeout, with a ``compile_guard.probe`` re-check between rungs
on hardware — so a Mosaic-compile wedge is attributed to the EXACT
rung instead of poisoning fourteen hours of session (the BENCH_r04/r05
failure mode).  A wedge halts the session and writes a quarantine
entry to ``bringup_quarantine.json``; knob-rung entries carry
``op``/``tactic`` so ``tactics_blocklist.blocked`` (hence the
autotuner resolver and the choosers) skips the wedge-proven tactic,
and ``bench_phases`` so bench.py's orchestrator skips the phases that
would re-launch it.

**Session journal.**  Append-only JSONL (``bringup_journal.jsonl`` in
the cache dir) recording every rung/phase/sweep/probe with outcome and
wall time.  ``--resume`` skips entries whose last outcome is ``pass``
(and quarantined rungs), so a mid-session wedge costs one rung.
Journal entries and graduated tuning sections join to BENCH_BANKED.md
rows by the RowAuditor configuration stamp (``bench_audit.row_stamp``).

**Provenance graduation.**  ``--graduate`` consumes the
``--emit-config`` outputs of bench_prefill_blocks / bench_decode_splits
/ bench_sharded_step plus the journal and rewrites tuning_configs
sections ``seed -> "provenance": "measured"``, carrying
``{journal_id, banked_row}`` references that L006 requires on every
measured section.  ``obs perf`` reports per-section graduation status
(pending | measured | quarantined) in the perf/6 ``graduation``
section.

**Selftest.**  ``--selftest`` proves the whole contract on CPU: rung
coverage (every mosaic_risks entry and every KNOB_LAUNCHES binding
maps to exactly one rung), the full ladder in interpret mode, a
simulated wedge (a rung subprocess sleeping past its timeout) with
exact-rung attribution + quarantine + resume, and a graduation rewrite
on a synthetic emit-config validated by L006.

Module import stays jax-free (the doctor section and bench.py consult
it on broken trees); drivers import jax lazily inside the rung
subprocess.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from flashinfer_tpu import tactics_blocklist

# construct classes of L015 mosaic_risks, riskiest first: strided lane
# reads and lane slices are the wedge-proven Mosaic territory (the PR 14
# head_dim//2 lane slice, the stride-2 token-pair interleave, the
# rowcache lane slice); cast-heavy kernels wedge rarely but still first-
# compile on the session's Mosaic version
RISK_ORDER = {"strided-lane": 0, "lane-slice": 1, "gather": 2, "cast": 3}

SIM_WEDGE_RUNG = "sim:wedge"
DEFAULT_RUNG_TIMEOUT_S = 420.0
DEFAULT_PROBE_TIMEOUT_S = 330.0

# kernel / launcher name -> driver key (the minimal-launch recipes below).
# Coverage is a selftest invariant: every mosaic_risks ``func`` and every
# KNOB_LAUNCHES launcher must resolve here, or the selftest fails — the
# same no-silent-skip rule as PLANNER_KERNELS / KNOB_LAUNCHES themselves.
DRIVER_FOR = {
    # L015 kernel functions
    "_rms_kernel": "rmsnorm",
    "_fused_add_rms_kernel": "fused_add_rmsnorm",
    "_bsr_kernel": "bsr",
    "_bsr_token_select_kernel": "bsr_token_select",
    "_vbsr_kernel": "vbsr",
    "_flash_kernel": "flash_attention",
    "_gdn_chunk_kernel": "gdn",
    "_kda_chunk_kernel": "kda",
    "_ssd_chunk_kernel": "mamba",
    "_mla_decode_kernel": "mla_decode",
    "_gather_gmm_rowcache_kernel": "gather_gmm_rowcache",
    "_decode_split_kernel_fused_heads": "decode_split",
    "_fp4_decode_kernel": "fp4_decode",
    "_fused_prefill_ingest_kernel": "prefill_ingest",
    "_fused_prefill_kernel": "fused_prefill",
    # L009 launchers (KNOB_LAUNCHES values) and L007 planners
    "fused_paged_prefill": "fused_prefill",
    "flash_attention": "flash_attention",
    "paged_decode_attention_split": "decode_split",
    "_paged_decode_hnd_launch": "paged_decode",
    "gmm": "gmm",
    "fused_paged_prefill_ingest": "prefill_ingest",
    "build_prefill_work_units": "fused_prefill",
    "build_prefill_ingest_units": "prefill_ingest",
    "build_decode_split_units": "decode_split",
    "build_engine_work_units": "engine_step",
}

# the engine.attention_backend knob launches through the whole serving
# engine, not a bare kernel — give it the engine driver, not the
# launcher-derived fused_prefill one
KNOB_DRIVER = {"engine.attention_backend": "engine_step"}

# bench.py phases a wedged rung poisons (written into the quarantine
# entry; bench.py's orchestrator skips them).  Knob rungs by knob name,
# kernel/planner rungs by kernel function.
KNOB_BENCH_PHASES = {
    "decode.splits": ["decode_splits"],
    "fused_prefill.blocks": ["prefill"],
    "flash_attention.blocks": ["prefill"],
    "prefill.fused_ingest": ["prefill"],
    "paged_decode.pages_per_chunk": ["decode"],
    "moe_gmm.tiles": ["moe"],
    "engine.attention_backend": ["serving_engine"],
}
KERNEL_BENCH_PHASES = {
    "_flash_kernel": ["prefill"],
    "_fused_prefill_kernel": ["prefill"],
    "_fused_prefill_ingest_kernel": ["prefill"],
    "_decode_split_kernel_fused_heads": ["decode_splits"],
    "_fp4_decode_kernel": ["decode"],
    "_mla_decode_kernel": ["mla"],
    "_gather_gmm_rowcache_kernel": ["moe"],
    "_gdn_chunk_kernel": ["scans"],
    "_kda_chunk_kernel": ["scans"],
    "_ssd_chunk_kernel": ["scans"],
}

# tuning_configs section -> banked phases whose RowAuditor stamps back a
# graduation (the join demanded by ISSUE 20's banked_row reference)
SECTION_BANK_PHASES = {
    "decode": ("decode_splits", "decode"),
    "prefill": ("prefill",),
    "prefill_ingest": ("prefill",),
    "parallel": ("serving_sharded",),
    "engine": ("serving_engine",),
    "kv_tier": ("serving_disagg",),
    "paged_decode": ("decode",),
    "moe": ("moe",),
}

# hardware-session sweeps after the ladder: (journal id, argv tail).
# Outputs land in the cache dir and feed --graduate.
SESSION_SWEEPS = [
    ("bench_decode_splits", ["benchmarks/bench_decode_splits.py",
                             "--emit-config"]),
    ("bench_prefill_blocks", ["benchmarks/bench_prefill_blocks.py",
                              "--emit-config", "--sweep-ingest"]),
    ("bench_sharded_step", ["benchmarks/bench_sharded_step.py",
                            "--emit-config"]),
]


# --------------------------------------------------------------------------
# Paths / journal
# --------------------------------------------------------------------------


def journal_path() -> str:
    p = os.environ.get("FLASHINFER_TPU_BRINGUP_JOURNAL")
    if p:
        return p
    from flashinfer_tpu import env

    return str(env.cache_dir() / "bringup_journal.jsonl")


def quarantine_path() -> str:
    return tactics_blocklist.bringup_quarantine_path()


class Journal:
    """Append-only JSONL session journal.  Every write is a full line
    flushed before return — a wedged process loses at most the entry it
    never got to write, never a partial file."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or journal_path()

    def entries(self) -> List[dict]:
        out: List[dict] = []
        try:
            with open(self.path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue  # a torn tail line from a killed writer
                    if isinstance(e, dict):
                        out.append(e)
        except OSError:
            pass
        return out

    def append(self, **entry) -> dict:
        entry.setdefault("ts", round(time.time(), 1))
        entries = None
        if "seq" not in entry:
            entries = self.entries()
            entry["seq"] = (max((e.get("seq", 0) for e in entries),
                                default=0) + 1)
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        with open(self.path, "a") as fh:
            fh.write(json.dumps(entry) + "\n")
            fh.flush()
        return entry

    def rung_outcomes(self) -> Dict[str, str]:
        """Last recorded outcome per rung id (skipped entries don't
        overwrite a real outcome — a resumed run must not launder a
        ``pass`` into ``skipped``)."""
        out: Dict[str, str] = {}
        for e in self.entries():
            if e.get("kind") != "rung" or not e.get("id"):
                continue
            if e.get("outcome") == "skipped" and e["id"] in out:
                continue
            out[e["id"]] = e.get("outcome", "")
        return out

    def step_outcomes(self, kind: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for e in self.entries():
            if e.get("kind") == kind and e.get("id"):
                if e.get("outcome") == "skipped" and e["id"] in out:
                    continue
                out[e["id"]] = e.get("outcome", "")
        return out

    def last_session_id(self) -> Optional[str]:
        for e in reversed(self.entries()):
            if e.get("journal_id"):
                return e["journal_id"]
        return None


def new_journal_id() -> str:
    return "bringup-%s-%d" % (time.strftime("%Y%m%d-%H%M%S"), os.getpid())


def _load_quarantine(path: Optional[str] = None) -> List[dict]:
    path = path or quarantine_path()
    try:
        data = json.loads(open(path).read())
        return [e for e in data if isinstance(e, dict)]
    except Exception:
        return []


def quarantine_add(entry: dict, path: Optional[str] = None) -> None:
    path = path or quarantine_path()
    entries = _load_quarantine(path)
    entries.append(entry)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(json.dumps(entries, indent=1) + "\n")
    os.replace(tmp, path)


def quarantined_bench_phases() -> List[str]:
    """Bench phases any quarantine entry names (bench.py's orchestrator
    drops them from its dispatch list)."""
    out: List[str] = []
    for e in tactics_blocklist.bringup_entries():
        for p in e.get("bench_phases") or ():
            if p not in out:
                out.append(p)
    return out


def _counter_inc(outcome: str) -> None:
    try:  # telemetry must never cost a rung
        from flashinfer_tpu import obs

        obs.counter_inc("bringup.rungs", outcome=outcome)
    except Exception:
        pass


# --------------------------------------------------------------------------
# Ladder generation
# --------------------------------------------------------------------------


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_mosaic_risks() -> List[dict]:
    path = os.path.join(_pkg_root(), "analysis", "baseline.json")
    data = json.loads(open(path).read())
    return [e for e in data.get("mosaic_risks", []) if isinstance(e, dict)]


def _config_tactics(chip: str) -> Dict[str, Any]:
    path = os.path.join(_pkg_root(), "tuning_configs", f"{chip}.json")
    try:
        cfg = json.loads(open(path).read())
    except Exception:
        return {}
    out: Dict[str, Any] = {}
    for sec in cfg.values():
        if isinstance(sec, dict) and isinstance(sec.get("tactics"), dict):
            out.update(sec["tactics"])
    if isinstance(cfg.get("tactics"), dict):
        out.update(cfg["tactics"])
    return out


def _knob_tactic(knob: str, tactics: Dict[str, Any]):
    """(shape_key, tactic) of the first shipped entry for ``knob``, or
    (None, None) — the rung then smokes the driver's default tactic."""
    prefix = knob + "|"
    for key in sorted(tactics):
        if key.startswith(prefix):
            return key[len(prefix):], tactics[key]
    return None, None


def build_ladder(chip: str = "v5e") -> List[dict]:
    """The session's rung list: L015 mosaic_risks (riskiest class
    first), then L007 planner pairs, then L009 knob bindings with the
    shipped tactic for ``chip``.  Deterministic — the subprocess child
    rebuilds it to find its rung by id."""
    rungs: List[dict] = []
    risks = sorted(
        enumerate(load_mosaic_risks()),
        key=lambda ie: (RISK_ORDER.get(ie[1].get("rule"), 9), ie[0]))
    for _, e in risks:
        rungs.append({
            "rung_id": "l015:%s:%s" % (e.get("rule"), e.get("func")),
            "kind": "mosaic_risk", "rule": e.get("rule"),
            "path": e.get("path"), "func": e.get("func"),
            "driver": DRIVER_FOR.get(e.get("func")),
            "bench_phases": KERNEL_BENCH_PHASES.get(e.get("func"), []),
        })
    from flashinfer_tpu.analysis.pallas_contract import PLANNER_KERNELS

    for planner, kernel in PLANNER_KERNELS.items():
        rungs.append({
            "rung_id": f"l007:{planner}",
            "kind": "planner", "planner": planner, "func": kernel,
            "driver": DRIVER_FOR.get(planner),
            "bench_phases": KERNEL_BENCH_PHASES.get(kernel, []),
        })
    from flashinfer_tpu.analysis.vmem_budget import KNOB_LAUNCHES

    tactics = _config_tactics(chip)
    for knob, kl in KNOB_LAUNCHES.items():
        shape_key, tactic = _knob_tactic(knob, tactics)
        rungs.append({
            "rung_id": f"l009:{knob}",
            "kind": "knob", "knob": knob, "launcher": kl.launcher,
            "shape_key": shape_key, "tactic": tactic,
            "driver": KNOB_DRIVER.get(knob, DRIVER_FOR.get(kl.launcher)),
            "op": knob,
            "bench_phases": KNOB_BENCH_PHASES.get(knob, []),
        })
    return rungs


def coverage_problems(rungs: List[dict]) -> List[str]:
    """The selftest's bijection proof: every registry entry maps to
    exactly one rung, and every rung has a driver."""
    problems: List[str] = []
    ids = [r["rung_id"] for r in rungs]
    dupes = sorted({i for i in ids if ids.count(i) > 1})
    if dupes:
        problems.append(f"duplicate rung ids: {dupes}")
    by_id = {r["rung_id"]: r for r in rungs}
    for e in load_mosaic_risks():
        rid = "l015:%s:%s" % (e.get("rule"), e.get("func"))
        if rid not in by_id:
            problems.append(f"mosaic_risks entry without a rung: {rid}")
    from flashinfer_tpu.analysis.vmem_budget import KNOB_LAUNCHES

    for knob in KNOB_LAUNCHES:
        if f"l009:{knob}" not in by_id:
            problems.append(f"KNOB_LAUNCHES binding without a rung: {knob}")
    from flashinfer_tpu.analysis.pallas_contract import PLANNER_KERNELS

    for planner in PLANNER_KERNELS:
        if f"l007:{planner}" not in by_id:
            problems.append(f"PLANNER_KERNELS pair without a rung: {planner}")
    for r in rungs:
        if not r.get("driver") or r["driver"] not in DRIVERS:
            problems.append(
                "rung %s has no launch driver (kernel %r) — extend "
                "bringup.DRIVER_FOR" % (r["rung_id"],
                                        r.get("func") or r.get("launcher")))
    return problems


# --------------------------------------------------------------------------
# Minimal-launch drivers (cribbed from the hw tier recipes; shapes kept
# tiny but tile-legal so the interpret-mode selftest stays fast).  Each
# driver runs ONE real launch of its kernel and blocks on the result.
# ``tactic`` is the knob rung's shipped value, clamped to the minimal
# shape where needed — the rung proves the construct (and the tactic
# where it is shape-independent) Mosaic-compiles, not its performance.
# --------------------------------------------------------------------------


def _keys(n):
    import jax

    k = jax.random.PRNGKey(0)
    return [jax.random.fold_in(k, i) for i in range(n)]


def _drv_rmsnorm(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu import rmsnorm

    x = jax.random.normal(_keys(1)[0], (256, 512), jnp.bfloat16)
    w = jnp.ones((512,), jnp.bfloat16)
    jax.block_until_ready(rmsnorm(x, w, backend="pallas"))


def _drv_fused_add_rmsnorm(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu import fused_add_rmsnorm

    ka, kb = _keys(2)
    x = jax.random.normal(ka, (256, 512), jnp.bfloat16)
    r = jax.random.normal(kb, (256, 512), jnp.bfloat16)
    w = jnp.ones((512,), jnp.bfloat16)
    jax.block_until_ready(fused_add_rmsnorm(x, r, w, backend="pallas"))


def _drv_flash_attention(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops import flash_attention

    T, HQ, HKV, D = 256, 4, 2, 128
    ka, kb, kc = _keys(3)
    q = jax.random.normal(ka, (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(kb, (T, HKV, D), jnp.bfloat16)
    v = jax.random.normal(kc, (T, HKV, D), jnp.bfloat16)
    seg = jnp.zeros((T,), jnp.int32)
    pos = jnp.arange(T)
    kw = {}
    if isinstance(tactic, (list, tuple)) and len(tactic) == 2:
        kw = dict(block_q=min(int(tactic[0]), T),
                  block_kv=min(int(tactic[1]), T))
    jax.block_until_ready(flash_attention(
        q, k, v, seg, seg, pos, pos, causal=True, sm_scale=D ** -0.5, **kw))


def _drv_bsr(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    MB = NB = 2
    R = C = 128
    HQ, HKV, D = 4, 2, 128
    indptr = np.asarray([0, 2, 4], np.int32)   # dense 2x2 block mask
    indices = np.asarray([0, 1, 0, 1], np.int32)
    w = fi.sparse.BlockSparseAttentionWrapper(jnp.zeros(1024, jnp.uint8),
                                              backend="pallas")
    w.plan(indptr, indices, MB * R, NB * C, R, C, HQ, HKV, D)
    ka, kb, kc = _keys(3)
    q = jax.random.normal(ka, (MB * R, HQ, D), jnp.bfloat16)
    k = jax.random.normal(kb, (NB * C, HKV, D), jnp.bfloat16)
    v = jax.random.normal(kc, (NB * C, HKV, D), jnp.bfloat16)
    jax.block_until_ready(w.run(q, k, v))


def _drv_bsr_token_select(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.msa_ops import msa_sparse_attention

    N, HQ, HKV, D = 256, 4, 2, 128
    ka, kb, kc = _keys(3)
    q = jax.random.normal(ka, (N, HQ, D), jnp.bfloat16)
    k = jax.random.normal(kb, (N, HKV, D), jnp.bfloat16)
    v = jax.random.normal(kc, (N, HKV, D), jnp.bfloat16)
    jax.block_until_ready(msa_sparse_attention(
        q, k, v, top_k=2, backend="pallas", granularity="token"))


def _drv_vbsr(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    HQ, HKV, D = 4, 2, 128
    row_sz = np.asarray([128, 128], np.int32)
    col_sz = np.asarray([128, 128], np.int32)
    mask = np.ones((1, 2, 2), bool)
    M = int(row_sz.sum())
    N = int(col_sz.sum())
    w = fi.sparse.VariableBlockSparseAttentionWrapper(
        jnp.zeros(1024, jnp.float32), backend="pallas")
    w.plan(block_mask_map=mask[0], block_row_sz=row_sz,
           block_col_sz=col_sz, num_qo_heads=HQ, num_kv_heads=HKV,
           head_dim=D, q_data_type=jnp.bfloat16)
    ka, kb, kc = _keys(3)
    q = jax.random.normal(ka, (HQ, M, D), jnp.bfloat16)
    k = jax.random.normal(kb, (HKV, N, D), jnp.bfloat16)
    v = jax.random.normal(kc, (HKV, N, D), jnp.bfloat16)
    jax.block_until_ready(w.run(q, k, v))


def _drv_gdn(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.ops.gdn_kernel import gdn_chunk_prefill_pallas

    rng = np.random.default_rng(0)
    B, L, H, dk, dv = 1, 128, 2, 128, 128
    qn = rng.standard_normal((B, L, H, dk))
    kn = rng.standard_normal((B, L, H, dk))
    q = jnp.asarray(qn / np.linalg.norm(qn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    k = jnp.asarray(kn / np.linalg.norm(kn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, L, H, dv)), jnp.bfloat16)
    alpha = jnp.asarray(np.exp(-0.1 * rng.random((B, L, H))), jnp.float32)
    beta = jnp.asarray(rng.random((B, L, H)), jnp.float32)
    jax.block_until_ready(gdn_chunk_prefill_pallas(q, k, v, alpha, beta))


def _drv_kda(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.gdn import kda_chunk_prefill

    rng = np.random.default_rng(2)
    B, L, H, dk, dv = 1, 128, 2, 128, 128
    qn = rng.standard_normal((B, L, H, dk))
    kn = rng.standard_normal((B, L, H, dk))
    q = jnp.asarray(qn / np.linalg.norm(qn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    k = jnp.asarray(kn / np.linalg.norm(kn, axis=-1, keepdims=True),
                    jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, L, H, dv)), jnp.bfloat16)
    alpha = jnp.asarray(np.exp(-0.05 * rng.random((B, L, H, dk))),
                        jnp.float32)
    beta = jnp.asarray(rng.random((B, L, H)), jnp.float32)
    jax.block_until_ready(
        kda_chunk_prefill(q, k, v, alpha, beta, backend="pallas"))


def _drv_mamba(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.mamba import mamba_chunk_scan_combined

    rng = np.random.default_rng(1)
    B, L, H, G, dim, ds = 1, 128, 2, 1, 64, 128
    x = jnp.asarray(rng.standard_normal((B, L, H, dim)), jnp.bfloat16)
    dt = jnp.asarray(rng.random((B, L, H)) + 0.1, jnp.float32)
    A = jnp.asarray(-np.abs(rng.standard_normal(H)) - 0.1, jnp.float32)
    Bm = jnp.asarray(rng.standard_normal((B, L, G, ds)) * 0.3, jnp.bfloat16)
    Cm = jnp.asarray(rng.standard_normal((B, L, G, ds)) * 0.3, jnp.bfloat16)
    jax.block_until_ready(
        mamba_chunk_scan_combined(x, dt, A, Bm, Cm, backend="pallas"))


def _drv_mla_decode(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops.mla_decode import mla_paged_decode_attention

    B, H, d_ckv, d_kpe, PS, ctx = 2, 128, 512, 64, 16, 128
    npages = B * (ctx // PS)
    ka, kb, kc, kd = _keys(4)
    ckv = jax.random.normal(ka, (npages, PS, d_ckv), jnp.bfloat16)
    kpe = jax.random.normal(kb, (npages, PS, d_kpe), jnp.bfloat16)
    qn = jax.random.normal(kc, (B, H, d_ckv), jnp.bfloat16)
    qp = jax.random.normal(kd, (B, H, d_kpe), jnp.bfloat16)
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ctx // PS)
    lens = jnp.asarray([ctx, ctx // 2], jnp.int32)
    sm = (d_ckv + d_kpe) ** -0.5
    # packed layout is the lane-slice risk entry (0:512 / 512:640 dst
    # slices); the split layout rides along in the same compile session
    jax.block_until_ready(mla_paged_decode_attention(
        qn, qp, ckv, kpe, pt, lens, sm_scale=sm, layout="packed"))


def _drv_gather_gmm_rowcache(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.ops.moe_gmm import gather_gmm

    rng = np.random.default_rng(9)
    t_rows, k, n, m = 64, 256, 256, 128
    sizes = np.asarray([37, 91], np.int32)  # mid-tile group starts
    x = jnp.asarray(rng.standard_normal((t_rows, k)), jnp.bfloat16)
    row_ids = jnp.asarray(rng.integers(0, t_rows, m), jnp.int32)
    rhs = jnp.asarray(rng.standard_normal((2, k, n)) / np.sqrt(k),
                      jnp.bfloat16)
    jax.block_until_ready(gather_gmm(
        x, row_ids, rhs, jnp.asarray(sizes), tm=64, tn=128, tk=128,
        variant="rowcache"))


def _drv_gmm(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.ops.moe_gmm import gmm

    rng = np.random.default_rng(3)
    M, K, N, E = 256, 512, 256, 2
    lhs = jnp.asarray(rng.standard_normal((M, K)), jnp.bfloat16)
    rhs = jnp.asarray(rng.standard_normal((E, K, N)) / np.sqrt(K),
                      jnp.bfloat16)
    sizes = jnp.asarray([128, 128], jnp.int32)
    kw = {}
    if isinstance(tactic, (list, tuple)) and len(tactic) == 3:
        kw = dict(tm=min(int(tactic[0]), M), tn=min(int(tactic[1]), N),
                  tk=min(int(tactic[2]), K))
    jax.block_until_ready(gmm(lhs, rhs, sizes, **kw))


def _paged_inputs(B, ctx, HKV, D, PS):
    import jax
    import jax.numpy as jnp
    import numpy as np

    ppr = ctx // PS
    npages = B * ppr
    pt = jnp.asarray(
        np.random.default_rng(0).permutation(npages).astype(np.int32)
    ).reshape(B, ppr)
    lens = jnp.asarray(
        np.random.default_rng(1).integers(1, ctx + 1, B).astype(np.int32))
    ka, kb, kc = _keys(3)
    kc_ = jax.random.normal(ka, (npages, HKV, PS, D), jnp.bfloat16)
    vc_ = jax.random.normal(kb, (npages, HKV, PS, D), jnp.bfloat16)
    return pt, lens, kc_, vc_, kc


def _drv_paged_decode(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops import paged_decode_attention

    B, ctx, HQ, HKV, D, PS = 4, 256, 32, 8, 128, 16
    pt, lens, kc, vc, kq = _paged_inputs(B, ctx, HKV, D, PS)
    q = jax.random.normal(kq, (B, HQ, D), jnp.bfloat16)
    kw = {}
    if isinstance(tactic, int):
        kw = dict(pages_per_chunk=max(1, min(tactic, ctx // PS)))
    jax.block_until_ready(paged_decode_attention(
        q, kc, vc, pt, lens, sm_scale=D ** -0.5, kv_layout="HND", **kw))


def _drv_decode_split(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops.paged_decode import (build_decode_split_units,
                                                 paged_decode_attention_split,
                                                 split_pages_per_chunk)

    B, ctx, HQ, HKV, D, PS = 4, 256, 32, 8, 128, 16
    pt, lens, kc, vc, kq = _paged_inputs(B, ctx, HKV, D, PS)
    q = jax.random.normal(kq, (B, HQ, D), jnp.bfloat16)
    S = tactic if isinstance(tactic, int) else 2
    S = max(1, min(S, ctx // PS))
    ppc = split_pages_per_chunk(PS, HKV, D, itemsize=2)
    plan_np = build_decode_split_units(
        pt, lens, num_splits=S, page_size=PS, pages_per_chunk=ppc)
    statics = {k: plan_np.pop(k) for k in
               ("num_units", "num_splits", "single_chunk",
                "pages_per_chunk")}
    plan_np.pop("stats")
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    jax.block_until_ready(paged_decode_attention_split(
        q, kc, vc, plan, sm_scale=D ** -0.5, **statics))


def _drv_fp4_decode(tactic=None):
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu.ops.paged_decode_fp4 import (
        fp4_paged_decode_attention, quantize_kv_int4_paged)

    B, ctx, HQ, HKV, D, PS = 2, 128, 32, 8, 128, 16
    npages = B * (ctx // PS)
    ka, kb, kc = _keys(3)
    pt = jnp.arange(npages, dtype=jnp.int32).reshape(B, ctx // PS)
    lens = jnp.full((B,), ctx, jnp.int32)
    kcache = jax.random.normal(ka, (npages, HKV, PS, D), jnp.float32)
    vcache = jax.random.normal(kb, (npages, HKV, PS, D), jnp.float32)
    q = jax.random.normal(kc, (B, HQ, D), jnp.bfloat16)
    k4, ksc = quantize_kv_int4_paged(kcache)
    v4, vsc = quantize_kv_int4_paged(vcache)
    jax.block_until_ready(fp4_paged_decode_attention(
        q, k4, ksc, v4, vsc, pt, lens, sm_scale=D ** -0.5))


def _drv_fused_prefill(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.ops.paged_prefill import (build_prefill_work_units,
                                                  fused_paged_prefill)

    PS, HQ, HKV, D = 16, 4, 2, 128
    qo_len, kv_len = 256, 256
    pages = kv_len // PS
    block_q, ppc = 128, 8
    if isinstance(tactic, (list, tuple)) and len(tactic) == 2:
        block_q = min(int(tactic[0]), 256)
        ppc = min(int(tactic[1]), pages)
    plan_np = build_prefill_work_units(
        np.asarray([0, qo_len], np.int64), np.asarray([0, pages], np.int64),
        np.arange(pages, dtype=np.int64), np.asarray([kv_len], np.int64),
        block_q=block_q, pages_per_chunk=ppc, page_size=PS)
    num_units = plan_np.pop("num_units")
    plan_np.pop("block_q"), plan_np.pop("pages_per_chunk")
    plan_np.pop("stats")
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    ka, kb, kc = _keys(3)
    q = jax.random.normal(ka, (qo_len, HQ, D), jnp.bfloat16)
    kcache = jax.random.normal(kb, (pages, HKV, PS, D), jnp.bfloat16)
    vcache = jax.random.normal(kc, (pages, HKV, PS, D), jnp.bfloat16)
    jax.block_until_ready(fused_paged_prefill(
        q, kcache, vcache, plan, num_units=num_units, block_q=block_q,
        pages_per_chunk=ppc, sm_scale=D ** -0.5, causal=True))


def _drv_prefill_ingest(tactic=None):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.ops.paged_prefill import (build_prefill_ingest_units,
                                                  fused_paged_prefill_ingest)

    PS, HQ, HKV, D = 16, 4, 2, 128
    lens = [128, 64]
    BQ, PPC = 128, 8
    qo_indptr = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    pages_per = [int(np.ceil(n / PS)) for n in lens]
    kv_page_indptr = np.concatenate(
        [[0], np.cumsum(pages_per)]).astype(np.int64)
    npages = int(kv_page_indptr[-1])
    kv_page_indices = np.arange(npages, dtype=np.int64)
    plan_np = build_prefill_ingest_units(
        qo_indptr, kv_page_indptr, kv_page_indices,
        np.asarray(lens, np.int64), block_q=BQ, pages_per_chunk=PPC,
        page_size=PS, causal=True, fused_ingest=True)
    statics = {k: plan_np.pop(k) for k in
               ("num_units", "block_q", "pages_per_chunk")}
    plan_np.pop("stats")
    plan = {k: jnp.asarray(v) for k, v in plan_np.items()}
    total = int(qo_indptr[-1])
    pad = (-total) % BQ
    ka, kb, kc = _keys(3)
    q = jax.random.normal(ka, (total, HQ, D), jnp.bfloat16)
    qp = jnp.pad(q, ((0, pad), (0, 0), (0, 0)))
    k = jax.random.normal(kb, (total, HKV, D), jnp.bfloat16)
    v = jax.random.normal(kc, (total, HKV, D), jnp.bfloat16)
    kp = jnp.pad(k, ((0, pad), (0, 0), (0, 0)))
    vp = jnp.pad(v, ((0, pad), (0, 0), (0, 0)))
    kcache = jnp.zeros((npages, HKV, PS, D), jnp.bfloat16)
    vcache = jnp.zeros((npages, HKV, PS, D), jnp.bfloat16)
    out, caches = fused_paged_prefill_ingest(
        qp, kp, vp, kcache, vcache, plan, sm_scale=D ** -0.5, causal=True,
        attend=True, **statics)
    jax.block_until_ready((out, caches))


def _drv_engine_step(tactic=None):
    import jax
    import numpy as np
    import jax.numpy as jnp

    from flashinfer_tpu.models.llama import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve import (EngineConfig, EngineRequest,
                                      SamplingConfig, ServingEngine)

    cfg = LlamaConfig.tiny(num_layers=1, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    backend = tactic if tactic in ("kernel", "reference") else "kernel"
    eng = ServingEngine(cfg, params, EngineConfig(
        num_pages=32, page_size=8, max_batch=2, prefill_budget_tokens=16,
        max_seq_tokens=32, sampling=SamplingConfig(temperature=0.8,
                                                   top_k=10),
        attention_backend=backend))
    rng = np.random.default_rng(0)
    for i in range(2):
        prompt = [int(t) for t in rng.integers(1, cfg.vocab_size, 9 + i)]
        eng.submit(EngineRequest(f"r{i}", prompt, max_new_tokens=2))
    eng.run()


DRIVERS: Dict[str, Callable] = {
    "rmsnorm": _drv_rmsnorm,
    "fused_add_rmsnorm": _drv_fused_add_rmsnorm,
    "flash_attention": _drv_flash_attention,
    "bsr": _drv_bsr,
    "bsr_token_select": _drv_bsr_token_select,
    "vbsr": _drv_vbsr,
    "gdn": _drv_gdn,
    "kda": _drv_kda,
    "mamba": _drv_mamba,
    "mla_decode": _drv_mla_decode,
    "gather_gmm_rowcache": _drv_gather_gmm_rowcache,
    "gmm": _drv_gmm,
    "paged_decode": _drv_paged_decode,
    "decode_split": _drv_decode_split,
    "fp4_decode": _drv_fp4_decode,
    "fused_prefill": _drv_fused_prefill,
    "prefill_ingest": _drv_prefill_ingest,
    "engine_step": _drv_engine_step,
}


# --------------------------------------------------------------------------
# Rung execution
# --------------------------------------------------------------------------


def run_rung_inproc(rung_id: str, chip: str = "v5e") -> None:
    """Execute one rung's launch in THIS process (the subprocess child
    entry).  The simulated wedge never imports jax — it exists to hang."""
    if rung_id == SIM_WEDGE_RUNG:
        time.sleep(3600)
        return
    rung = next((r for r in build_ladder(chip) if r["rung_id"] == rung_id),
                None)
    if rung is None:
        raise SystemExit(f"unknown rung id {rung_id!r}")
    drv = DRIVERS.get(rung.get("driver") or "")
    if drv is None:
        raise SystemExit(f"rung {rung_id!r} has no driver")
    drv(tactic=rung.get("tactic"))


def _spawn_rung(rung: dict, *, timeout_s: float, interpret: bool,
                chip: str = "v5e") -> dict:
    """One rung in its own subprocess under a timeout.  Outcome:
    ``pass`` | ``fail`` (driver error, chip presumed healthy) |
    ``wedge`` (timeout — the subprocess had to be killed)."""
    cmd = [sys.executable, "-m", "flashinfer_tpu.obs.bringup",
           "--run-rung", rung["rung_id"], "--chip", chip]
    child_env = dict(os.environ)
    if interpret:
        child_env.setdefault("JAX_PLATFORMS", "cpu")
        child_env["FLASHINFER_TPU_INTERPRET"] = "1"
    t0 = time.time()
    # Popen + bounded reaps (the compile_guard.probe pattern): a wedged
    # Mosaic compile can leave the child unkillable mid-tunnel-I/O
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True, env=child_env)
    try:
        out, err = p.communicate(timeout=timeout_s)
        if p.returncode == 0:
            outcome, detail = "pass", ""
        else:
            tail = (err or out or "").strip().splitlines()[-8:]
            outcome, detail = "fail", "\n".join(tail)[-800:]
    except subprocess.TimeoutExpired:
        p.kill()
        try:
            p.communicate(timeout=10)
        except Exception:
            pass
        outcome = "wedge"
        detail = f"rung timed out after {timeout_s:.0f}s (chip wedged?)"
    return {"outcome": outcome, "wall_s": round(time.time() - t0, 2),
            "detail": detail}


def quarantine_entry(rung: dict, journal_id: str, detail: str) -> dict:
    entry = {
        "rung_id": rung["rung_id"], "kind": rung.get("kind"),
        "op": rung.get("op"), "kernel": rung.get("func"),
        "reason": detail, "journal_id": journal_id,
        "bench_phases": rung.get("bench_phases") or [],
        "ts": round(time.time(), 1),
    }
    if rung.get("op") is not None and "tactic" in rung:
        entry["tactic"] = rung.get("tactic")
    return entry


def run_ladder(rungs: List[dict], *, journal: Journal, journal_id: str,
               quarantine: Optional[str] = None,
               rung_timeout_s: float = DEFAULT_RUNG_TIMEOUT_S,
               interpret: Optional[bool] = None,
               probe_every: Optional[int] = None,
               probe_timeout_s: float = DEFAULT_PROBE_TIMEOUT_S,
               resume: bool = False, chip: str = "v5e",
               runner: Optional[Callable] = None,
               prober: Optional[Callable] = None,
               verbose: bool = True) -> dict:
    """Walk the smoke ladder.  A wedge (rung timeout, or an unhealthy
    post-rung probe) is attributed to the current rung, quarantined,
    and HALTS the session — remaining rungs are journaled ``pending``
    so ``--resume`` picks up exactly there after recovery."""
    from flashinfer_tpu import compile_guard

    if interpret is None:
        interpret = not _is_tpu()
    if probe_every is None:
        # interpret-mode rungs cannot wedge a chip: probe only around
        # suspicious outcomes off-hardware, after every rung on it
        probe_every = 0 if interpret else 1
    quarantine = quarantine or quarantine_path()
    runner = runner or _spawn_rung
    prober = prober or (lambda: compile_guard.probe(
        timeout_s=probe_timeout_s, interpret=interpret))
    done = {rid for rid, o in journal.rung_outcomes().items()
            if o == "pass"} if resume else set()
    qids = {e.get("rung_id") for e in _load_quarantine(quarantine)}
    summary = {"total": len(rungs), "passed": 0, "skipped": 0,
               "failed": [], "wedged": [], "pending": [], "halted": False}
    ran = 0
    for rung in rungs:
        rid = rung["rung_id"]
        if summary["halted"]:
            journal.append(journal_id=journal_id, kind="rung", id=rid,
                           outcome="pending",
                           detail="session halted by earlier wedge")
            summary["pending"].append(rid)
            continue
        if rid in done or rid in qids:
            why = "already passed (resume)" if rid in done else "quarantined"
            journal.append(journal_id=journal_id, kind="rung", id=rid,
                           outcome="skipped", detail=why)
            summary["skipped"] += 1
            continue
        res = runner(rung, timeout_s=rung_timeout_s, interpret=interpret,
                     chip=chip)
        outcome, detail = res["outcome"], res.get("detail", "")
        ran += 1
        probe_state = None
        if outcome != "pass" or (probe_every and ran % probe_every == 0):
            probe_state = prober()
            if not probe_state.get("healthy"):
                # the rung may have "passed" or "failed" cleanly and
                # still left the chip wedged — the probe is the arbiter
                outcome = "wedge"
                detail = (detail + "\npost-rung probe unhealthy: "
                          + str(probe_state.get("detail", ""))[:300]).strip()
        journal.append(journal_id=journal_id, kind="rung", id=rid,
                       outcome=outcome, wall_s=res.get("wall_s"),
                       probe=probe_state, detail=detail)
        _counter_inc(outcome)
        if verbose:
            print(f"  rung {rid}: {outcome} ({res.get('wall_s', 0):.1f}s)")
        if outcome == "pass":
            summary["passed"] += 1
        elif outcome == "fail":
            summary["failed"].append(rid)
        elif outcome == "wedge":
            quarantine_add(quarantine_entry(rung, journal_id, detail),
                           quarantine)
            summary["wedged"].append(rid)
            summary["halted"] = True
    journal.append(journal_id=journal_id, kind="session", id="ladder",
                   outcome="halted" if summary["halted"] else "complete",
                   detail=json.dumps({k: v for k, v in summary.items()
                                      if k != "pending"}))
    return summary


def record_phases_pending(phases: List[str], probe: Optional[dict] = None,
                          journal: Optional[Journal] = None) -> None:
    """bench.py's orchestrator calls this when a post-timeout probe
    comes back unhealthy: the phases it refuses to dispatch are
    journaled ``pending`` so ``obs bringup --resume`` re-runs them."""
    j = journal or Journal()
    jid = j.last_session_id() or new_journal_id()
    for name in phases:
        j.append(journal_id=jid, kind="phase", id=name, outcome="pending",
                 probe=probe, detail="chip unhealthy after phase timeout")


def _is_tpu() -> bool:
    try:
        from flashinfer_tpu.utils import is_tpu

        return bool(is_tpu())
    except Exception:
        return False


# --------------------------------------------------------------------------
# Provenance graduation
# --------------------------------------------------------------------------


def _default_configs_dir() -> str:
    return os.path.join(_pkg_root(), "tuning_configs")


def _default_banked_path() -> str:
    return os.path.join(os.path.dirname(_pkg_root()), "BENCH_BANKED.md")


def graduate(emit_paths: List[str], *, chip: str = "v5e",
             journal: Optional[Journal] = None,
             journal_id: Optional[str] = None,
             configs_dir: Optional[str] = None,
             banked_path: Optional[str] = None,
             write: bool = True) -> dict:
    """Rewrite tuning_configs sections named by the emit-config outputs
    to ``"provenance": "measured"``, carrying the session journal id
    and the RowAuditor stamps of the banked rows that measured them
    (L006 refuses a measured section without both references)."""
    from flashinfer_tpu.obs import bench_audit

    journal = journal or Journal()
    journal_id = journal_id or journal.last_session_id() or new_journal_id()
    cfg_path = os.path.join(configs_dir or _default_configs_dir(),
                            f"{chip}.json")
    cfg = json.loads(open(cfg_path).read())
    rows = bench_audit.load_banked_history(
        banked_path or _default_banked_path())
    by_phase: Dict[str, List[str]] = {}
    for r in rows:
        ph = r.get("phase")
        if isinstance(ph, str):
            stamp = bench_audit.row_stamp(r)
            if stamp not in by_phase.setdefault(ph, []):
                by_phase[ph].append(stamp)
    result = {"config": cfg_path, "journal_id": journal_id,
              "graduated": [], "skipped": []}
    for path in emit_paths:
        try:
            data = json.loads(open(path).read())
        except Exception as e:
            result["skipped"].append({"emit": path,
                                      "reason": f"unreadable: {e!r}"})
            continue
        for name, sec in data.items():
            if not (isinstance(sec, dict)
                    and isinstance(sec.get("tactics"), dict)
                    and sec["tactics"]):
                continue
            phases = SECTION_BANK_PHASES.get(name, (name,))
            refs = [rid for ph in phases for rid in by_phase.get(ph, [])]
            if not refs:
                result["skipped"].append({
                    "section": name,
                    "reason": "no banked rows for phase(s) %s — bank the "
                              "sweep before graduating" % list(phases)})
                continue
            old = cfg.get(name) if isinstance(cfg.get(name), dict) else {}
            tactics = dict(old.get("tactics") or {})
            tactics.update(sec["tactics"])
            merged = {
                "comment": sec.get("comment") or old.get("comment")
                or f"measured by obs bringup session {journal_id}",
                "provenance": "measured",
                "journal_id": journal_id,
                # cap the reference list: the join is by configuration
                # stamp, a handful anchors the audit without bloating
                # the shipped config
                "banked_row": refs[:8],
                "tactics": tactics,
            }
            seed_left = sorted(k for k in tactics
                               if k not in sec["tactics"])
            if seed_left:
                merged["seed_keys"] = seed_left
            cfg[name] = merged
            journal.append(journal_id=journal_id, kind="graduate", id=name,
                           outcome="pass",
                           detail=f"{len(sec['tactics'])} tactic(s), "
                                  f"{len(refs)} banked row ref(s)")
            result["graduated"].append(name)
    if write and result["graduated"]:
        tmp = cfg_path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(json.dumps(cfg, indent=1) + "\n")
        os.replace(tmp, cfg_path)
    return result


# --------------------------------------------------------------------------
# Doctor / status
# --------------------------------------------------------------------------


def doctor_summary() -> dict:
    """The ``obs doctor`` bringup section: session state at a glance,
    import-light and never raising."""
    j = Journal()
    entries = j.entries()
    outcomes = j.rung_outcomes()
    counts: Dict[str, int] = {}
    for o in outcomes.values():
        counts[o] = counts.get(o, 0) + 1
    qentries = _load_quarantine()
    seed_sections: Dict[str, List[str]] = {}
    cfg_dir = _default_configs_dir()
    try:
        for fn in sorted(os.listdir(cfg_dir)):
            if not fn.endswith(".json"):
                continue
            try:
                cfg = json.loads(open(os.path.join(cfg_dir, fn)).read())
            except Exception:
                continue
            pending = [name for name, sec in cfg.items()
                       if isinstance(sec, dict) and "tactics" in sec
                       and name != "tactics"
                       and sec.get("provenance") != "measured"]
            seed_sections[fn[:-5]] = pending
    except OSError:
        pass
    return {
        "journal": j.path,
        "journal_entries": len(entries),
        "session": j.last_session_id(),
        "rungs": counts,
        "quarantined": [e.get("rung_id") for e in qentries],
        "seed_sections_remaining": seed_sections,
    }


# --------------------------------------------------------------------------
# Selftest (the CI gate)
# --------------------------------------------------------------------------


def selftest(chip: str = "v5e", rung_timeout_s: float = 240.0,
             skip_ladder: bool = False) -> int:
    """CPU proof of the whole bring-up contract; exit 2 on any
    violation (the obs trace/steploop selftest convention)."""
    import shutil
    import tempfile

    problems: List[str] = []
    tmp = tempfile.mkdtemp(prefix="bringup_selftest_")
    jpath = os.path.join(tmp, "journal.jsonl")
    qpath = os.path.join(tmp, "quarantine.json")
    try:
        # -- A: ladder coverage (registry <-> rung bijection) ----------
        rungs = build_ladder(chip)
        problems += coverage_problems(rungs)
        n_risks = len(load_mosaic_risks())
        print(f"selftest: ladder has {len(rungs)} rungs "
              f"({n_risks} mosaic_risks + planners + knobs); "
              f"coverage problems: {len(problems)}")

        # -- B: simulated wedge attributes to its exact rung -----------
        sim = {"rung_id": SIM_WEDGE_RUNG, "kind": "sim", "driver": None,
               "op": "sim.wedge", "tactic": "on", "bench_phases": ["sim"]}
        journal = Journal(jpath)
        jid = new_journal_id()
        s1 = run_ladder([sim] + rungs, journal=journal, journal_id=jid,
                        quarantine=qpath, rung_timeout_s=3.0,
                        interpret=True, probe_every=0, chip=chip,
                        verbose=False)
        if s1["wedged"] != [SIM_WEDGE_RUNG]:
            problems.append(f"simulated wedge not attributed: {s1}")
        if len(s1["pending"]) != len(rungs):
            problems.append(
                "wedge did not halt the session: %d pending, expected %d"
                % (len(s1["pending"]), len(rungs)))
        qids = [e.get("rung_id") for e in _load_quarantine(qpath)]
        if qids != [SIM_WEDGE_RUNG]:
            problems.append(f"quarantine list wrong: {qids}")
        # the quarantined (op, tactic) pair reaches the blocklist
        os.environ["FLASHINFER_TPU_BRINGUP_QUARANTINE"] = qpath
        try:
            if not tactics_blocklist.blocked("sim.wedge", "on"):
                problems.append(
                    "quarantined tactic not visible to tactics_blocklist")
        finally:
            os.environ.pop("FLASHINFER_TPU_BRINGUP_QUARANTINE", None)
            tactics_blocklist._bringup_cache = None

        # -- C: --resume skips the quarantined rung, completes the rest
        if not skip_ladder:
            t0 = time.time()
            s2 = run_ladder([sim] + rungs, journal=journal, journal_id=jid,
                            quarantine=qpath,
                            rung_timeout_s=rung_timeout_s, interpret=True,
                            probe_every=0, resume=True, chip=chip)
            print("selftest: resume ladder %d passed / %d failed / "
                  "%d skipped in %.0fs" % (s2["passed"], len(s2["failed"]),
                                           s2["skipped"], time.time() - t0))
            if s2["skipped"] != 1:
                problems.append(
                    f"resume should skip exactly the quarantined rung, "
                    f"skipped {s2['skipped']}")
            for rid in s2["failed"]:
                o = journal.rung_outcomes().get(rid)
                problems.append(f"rung {rid} failed in interpret mode "
                                f"(outcome {o})")
            if s2["wedged"]:
                problems.append(f"interpret ladder wedged: {s2['wedged']}")
            # a third run must skip everything (journal-complete)
            s3 = run_ladder([sim] + rungs, journal=journal, journal_id=jid,
                            quarantine=qpath, rung_timeout_s=5.0,
                            interpret=True, probe_every=0, resume=True,
                            chip=chip, verbose=False,
                            runner=lambda *a, **k: problems.append(
                                "resume re-ran a completed rung") or
                            {"outcome": "fail", "wall_s": 0, "detail": ""})
            if s3["skipped"] != len(rungs) + 1 - len(s2["failed"]):
                problems.append(
                    f"journal-complete resume skipped {s3['skipped']} of "
                    f"{len(rungs) + 1}")

        # -- D: graduation flips seed -> measured with valid refs ------
        cfg_dir = os.path.join(tmp, "tuning_configs")
        os.makedirs(cfg_dir)
        shipped = json.loads(open(os.path.join(
            _default_configs_dir(), f"{chip}.json")).read())
        json.dump(shipped, open(os.path.join(cfg_dir, f"{chip}.json"), "w"),
                  indent=1)
        emit = {"decode": {"comment": "selftest sweep", "seed": False,
                           "tactics": {
                               "decode.splits|256_32_32_8_128_16_16_bfloat16": 2}}}
        emit_path = os.path.join(tmp, "emit_decode.json")
        json.dump(emit, open(emit_path, "w"))
        banked = os.path.join(tmp, "BENCH_BANKED.md")
        row = {"phase": "decode_splits", "bs": 32, "ctx": 256,
               "num_splits": 2, "us": 12.0}
        open(banked, "w").write(
            "```json\n" + json.dumps({"rows": [row]}) + "\n```\n")
        g = graduate([emit_path], chip=chip, journal=journal,
                     journal_id=jid, configs_dir=cfg_dir,
                     banked_path=banked)
        if g["graduated"] != ["decode"]:
            problems.append(f"graduation did not flip decode: {g}")
        graduated = json.loads(open(os.path.join(cfg_dir,
                                                 f"{chip}.json")).read())
        sec = graduated.get("decode", {})
        if sec.get("provenance") != "measured" \
                or sec.get("journal_id") != jid \
                or not sec.get("banked_row"):
            problems.append(f"graduated section missing references: "
                            f"{ {k: sec.get(k) for k in ('provenance', 'journal_id', 'banked_row')} }")
        # L006 must accept the rewrite (and would reject it without refs)
        from flashinfer_tpu.analysis import tuning_schema
        from flashinfer_tpu.analysis.core import Project

        proj_dir = os.path.join(tmp, "proj")
        os.makedirs(os.path.join(proj_dir, "tuning_configs"))
        open(os.path.join(proj_dir, "mod.py"), "w").write("x = 1\n")
        shutil.copy(os.path.join(cfg_dir, f"{chip}.json"),
                    os.path.join(proj_dir, "tuning_configs", "gen.json"))
        findings = tuning_schema.run(Project.from_paths([proj_dir]))
        if findings:
            problems.append("L006 rejects the graduated config: %s"
                            % [f.message[:120] for f in findings])
        stripped = dict(sec)
        stripped.pop("journal_id", None)
        json.dump({"decode": stripped},
                  open(os.path.join(proj_dir, "tuning_configs", "gen.json"),
                       "w"), indent=1)
        findings = tuning_schema.run(Project.from_paths([proj_dir]))
        if not any("journal_id" in f.message for f in findings):
            problems.append("L006 accepts a measured section WITHOUT a "
                            "journal_id reference")

        # -- E: perf/6 graduation section ------------------------------
        from flashinfer_tpu.obs.roofline import build_perf_report

        report = build_perf_report([])
        if report.get("schema") != "flashinfer_tpu.obs.perf/6":
            problems.append(f"perf schema is {report.get('schema')!r}, "
                            "expected perf/6")
        grad = report.get("graduation")
        if not (isinstance(grad, dict) and grad.get("sections")):
            problems.append("perf report missing the graduation section")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    print(json.dumps({"bringup_selftest": "ok" if not problems else "FAIL",
                      "problems": problems}, indent=1))
    return 2 if problems else 0


# --------------------------------------------------------------------------
# Full hardware session + CLI
# --------------------------------------------------------------------------


def _run_step(name: str, cmd: List[str], *, journal: Journal,
              journal_id: str, kind: str, timeout_s: float,
              capture_to: Optional[str] = None) -> bool:
    """One journaled bench/sweep subprocess of the hardware session."""
    t0 = time.time()
    try:
        p = subprocess.run(cmd, timeout=timeout_s, capture_output=True,
                           text=True)
        ok = p.returncode == 0
        detail = "" if ok else (p.stderr or p.stdout or "")[-500:]
        if ok and capture_to:
            # the sweeps print the emit-config JSON last; keep the tail
            # starting at its first top-level brace
            out = p.stdout or ""
            start = out.find("{")
            if start >= 0:
                open(capture_to, "w").write(out[start:])
            else:
                ok, detail = False, "no emit-config JSON in sweep output"
    except subprocess.TimeoutExpired:
        ok, detail = False, f"timed out after {timeout_s:.0f}s"
    journal.append(journal_id=journal_id, kind=kind, id=name,
                   outcome="pass" if ok else "fail",
                   wall_s=round(time.time() - t0, 2), detail=detail)
    print(f"  {kind} {name}: {'pass' if ok else 'FAIL'}")
    return ok


def run_session(args) -> int:
    """The graduation session: ladder -> banked bench -> emit-config
    sweeps -> graduation, all journaled and resumable."""
    journal = Journal(args.journal)
    jid = (journal.last_session_id() if args.resume else None) \
        or new_journal_id()
    print(f"bringup session {jid} (journal: {journal.path})")
    rungs = build_ladder(args.chip)
    summary = run_ladder(
        rungs, journal=journal, journal_id=jid,
        quarantine=args.quarantine, rung_timeout_s=args.timeout,
        probe_every=args.probe_every, resume=args.resume, chip=args.chip)
    print(json.dumps({k: v for k, v in summary.items() if k != "pending"}))
    if summary["halted"]:
        print("session halted: wedge quarantined — recover the chip and "
              "re-run `obs bringup --resume`")
        return 3
    repo = os.path.dirname(_pkg_root())
    done = journal.step_outcomes("phase") if args.resume else {}
    if done.get("bench") != "pass":
        _run_step("bench", [sys.executable, os.path.join(repo, "bench.py"),
                            "--bank"],
                  journal=journal, journal_id=jid, kind="phase",
                  timeout_s=7200)
    emit_paths: List[str] = []
    sweeps_done = journal.step_outcomes("sweep") if args.resume else {}
    for name, tail in SESSION_SWEEPS:
        out_path = os.path.join(os.path.dirname(journal.path),
                                f"bringup_emit_{name}.json")
        if sweeps_done.get(name) == "pass" and os.path.exists(out_path):
            emit_paths.append(out_path)
            continue
        cmd = [sys.executable, os.path.join(repo, tail[0])] + tail[1:]
        if _run_step(name, cmd, journal=journal, journal_id=jid,
                     kind="sweep", timeout_s=7200, capture_to=out_path):
            emit_paths.append(out_path)
    if emit_paths:
        g = graduate(emit_paths, chip=args.chip, journal=journal,
                     journal_id=jid, banked_path=args.banked)
        print(json.dumps(g, indent=1))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs bringup",
        description="hardware graduation session harness (ISSUE 20)")
    ap.add_argument("--selftest", action="store_true",
                    help="prove the whole contract on CPU (CI gate)")
    ap.add_argument("--resume", action="store_true",
                    help="skip journal-completed rungs/phases/sweeps")
    ap.add_argument("--graduate", action="store_true",
                    help="only run provenance graduation on --emit-config")
    ap.add_argument("--emit-config", action="append", default=[],
                    metavar="PATH", help="sweep emit-config JSON(s)")
    ap.add_argument("--list", action="store_true",
                    help="print the generated ladder and exit")
    ap.add_argument("--chip", default="v5e")
    ap.add_argument("--journal", default=None)
    ap.add_argument("--quarantine", default=None)
    ap.add_argument("--banked", default=None)
    ap.add_argument("--timeout", type=float,
                    default=DEFAULT_RUNG_TIMEOUT_S,
                    help="per-rung subprocess timeout (s)")
    ap.add_argument("--probe-every", type=int, default=None,
                    help="probe cadence in rungs (default: 1 on TPU, "
                         "suspicious-only off it)")
    ap.add_argument("--run-rung", default=None, metavar="RUNG_ID",
                    help=argparse.SUPPRESS)  # internal subprocess entry
    args = ap.parse_args(argv)

    if args.run_rung:
        run_rung_inproc(args.run_rung, chip=args.chip)
        print(f"RUNG_OK {args.run_rung}")
        return 0
    if args.list:
        for r in build_ladder(args.chip):
            print(json.dumps(r))
        return 0
    if args.selftest:
        return selftest(chip=args.chip)
    if args.graduate:
        if not args.emit_config:
            ap.error("--graduate requires at least one --emit-config")
        journal = Journal(args.journal)
        g = graduate(args.emit_config, chip=args.chip, journal=journal,
                     banked_path=args.banked)
        print(json.dumps(g, indent=1))
        return 0 if not g["skipped"] or g["graduated"] else 1
    return run_session(args)


if __name__ == "__main__":
    sys.exit(main())
