"""Exporters for the obs registry snapshot.

Three formats, matching the reference's split between machine-readable
artifacts and Perfetto-loadable traces (``flashinfer/profiler``):

- :func:`to_json` — the canonical snapshot (what ``obs report`` prints);
- :func:`to_prometheus` — Prometheus text exposition format (counters
  as ``_total``, histograms as ``_bucket``/``_sum``/``_count`` plus
  pre-computed quantile gauges), for scraping a long-lived server;
- :func:`to_chrome_trace` — merges the profiler's op-timeline spans and
  the snapshot into ONE chrome://tracing / Perfetto-loadable JSON: the
  spans render on the timeline, the metrics ride as a metadata event so
  a trace file is self-describing.
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "flashinfer_tpu_" + _NAME_RE.sub("_", name)


def _prom_labels(flat_key: str, extra: str = "") -> str:
    """Snapshot flat label key ``{k=v,...}`` (or ``""``) -> prometheus
    ``{k="v",...}``."""
    parts = []
    if flat_key:
        for kv in flat_key.strip("{}").split(","):
            k, _, v = kv.partition("=")
            parts.append(f'{k}="{v}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def to_json(snapshot: dict, indent: int = 1) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True)


def to_prometheus(snapshot: dict) -> str:
    from flashinfer_tpu.obs.catalog import METRICS

    lines: List[str] = []

    def help_for(name: str) -> None:
        spec = METRICS.get(name)
        if spec:
            lines.append(f"# HELP {_prom_name(name)} {spec[2]}")

    for name, cells in snapshot.get("counters", {}).items():
        help_for(name)
        lines.append(f"# TYPE {_prom_name(name)} counter")
        for key, val in cells.items():
            lines.append(f"{_prom_name(name)}_total{_prom_labels(key)} {val}")
    for name, cells in snapshot.get("gauges", {}).items():
        help_for(name)
        lines.append(f"# TYPE {_prom_name(name)} gauge")
        for key, val in cells.items():
            lines.append(f"{_prom_name(name)}{_prom_labels(key)} {val}")
    for name, cells in snapshot.get("histograms", {}).items():
        help_for(name)
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        for key, h in cells.items():
            acc = 0
            for le, c in h.get("buckets", {}).items():
                acc += c
                le_lbl = 'le="%s"' % le
                lines.append(f"{pn}_bucket{_prom_labels(key, le_lbl)} {acc}")
            # the running acc already equals count; still emit the +Inf
            # bucket when no overflow landed (prometheus requires it)
            if "+Inf" not in h.get("buckets", {}):
                inf_lbl = 'le="+Inf"'
                lines.append(
                    f"{pn}_bucket{_prom_labels(key, inf_lbl)} {h['count']}")
            lines.append(f"{pn}_sum{_prom_labels(key)} {h['sum']}")
            lines.append(f"{pn}_count{_prom_labels(key)} {h['count']}")
            for q in ("p50", "p90", "p99"):
                if q in h:
                    q_lbl = 'quantile="0.%s"' % q[1:]
                    lines.append(f"{pn}{_prom_labels(key, q_lbl)} {h[q]}")
    return "\n".join(lines) + "\n"


def to_chrome_trace(snapshot: dict,
                    timeline_events: Optional[list] = None) -> dict:
    """Merge op-timeline spans (``profiler.stop_timeline`` events) with
    the metrics snapshot into one chrome-trace dict (same span encoding
    as profiler.stop_timeline's file form, so tooling treats both
    identically)."""
    pid = os.getpid()
    events = [
        {
            "name": e["name"], "ph": "X", "pid": pid, "tid": 0,
            "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6,
            "cat": "flashinfer_tpu",
        }
        for e in (timeline_events or [])
    ]
    events.append({
        "name": "flashinfer_tpu.obs.snapshot", "ph": "M", "pid": pid,
        "tid": 0, "args": {"snapshot": snapshot},
    })
    return {"traceEvents": events}


def write_chrome_trace(path: str, snapshot: dict,
                       timeline_events: Optional[list] = None) -> None:
    from flashinfer_tpu.utils import atomic_write_text

    atomic_write_text(path, json.dumps(
        to_chrome_trace(snapshot, timeline_events)))
