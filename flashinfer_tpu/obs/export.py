"""Exporters for the obs registry snapshot.

Three formats, matching the reference's split between machine-readable
artifacts and Perfetto-loadable traces (``flashinfer/profiler``):

- :func:`to_json` — the canonical snapshot (what ``obs report`` prints);
- :func:`to_prometheus` — Prometheus text exposition format (counters
  as ``_total``, histograms as ``_bucket``/``_sum``/``_count`` plus
  pre-computed quantile gauges), for scraping a long-lived server;
- :func:`to_chrome_trace` — merges the profiler's op-timeline spans and
  the snapshot into ONE chrome://tracing / Perfetto-loadable JSON: the
  spans render on the timeline, the metrics ride as a metadata event so
  a trace file is self-describing.
"""

from __future__ import annotations

import json
import os
import re
from typing import List, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return "flashinfer_tpu_" + _NAME_RE.sub("_", name)


def _prom_labels(flat_key: str, extra: str = "") -> str:
    """Snapshot flat label key ``{k=v,...}`` (or ``""``) -> prometheus
    ``{k="v",...}``."""
    parts = []
    if flat_key:
        for kv in flat_key.strip("{}").split(","):
            k, _, v = kv.partition("=")
            parts.append(f'{k}="{v}"')
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def to_json(snapshot: dict, indent: int = 1) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True)


def to_prometheus(snapshot: dict) -> str:
    from flashinfer_tpu.obs.catalog import METRICS

    lines: List[str] = []

    def help_for(name: str) -> None:
        spec = METRICS.get(name)
        if spec:
            lines.append(f"# HELP {_prom_name(name)} {spec[2]}")

    for name, cells in snapshot.get("counters", {}).items():
        help_for(name)
        lines.append(f"# TYPE {_prom_name(name)} counter")
        for key, val in cells.items():
            lines.append(f"{_prom_name(name)}_total{_prom_labels(key)} {val}")
    for name, cells in snapshot.get("gauges", {}).items():
        help_for(name)
        lines.append(f"# TYPE {_prom_name(name)} gauge")
        for key, val in cells.items():
            lines.append(f"{_prom_name(name)}{_prom_labels(key)} {val}")
    for name, cells in snapshot.get("histograms", {}).items():
        help_for(name)
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        for key, h in cells.items():
            acc = 0
            for le, c in h.get("buckets", {}).items():
                acc += c
                le_lbl = 'le="%s"' % le
                lines.append(f"{pn}_bucket{_prom_labels(key, le_lbl)} {acc}")
            # the running acc already equals count; still emit the +Inf
            # bucket when no overflow landed (prometheus requires it)
            if "+Inf" not in h.get("buckets", {}):
                inf_lbl = 'le="+Inf"'
                lines.append(
                    f"{pn}_bucket{_prom_labels(key, inf_lbl)} {h['count']}")
            lines.append(f"{pn}_sum{_prom_labels(key)} {h['sum']}")
            lines.append(f"{pn}_count{_prom_labels(key)} {h['count']}")
            for q in ("p50", "p90", "p99"):
                if q in h:
                    q_lbl = 'quantile="0.%s"' % q[1:]
                    lines.append(f"{pn}{_prom_labels(key, q_lbl)} {h[q]}")
    return "\n".join(lines) + "\n"


def to_chrome_trace(snapshot: dict,
                    timeline_events: Optional[list] = None) -> dict:
    """Merge op-timeline spans (``profiler.stop_timeline`` events) with
    the metrics snapshot into one chrome-trace dict (same span encoding
    as profiler.stop_timeline's file form, so tooling treats both
    identically — including the shared epoch clock base)."""
    from flashinfer_tpu.profiler import perf_to_epoch_us

    pid = os.getpid()
    events = [
        {
            "name": e["name"], "ph": "X", "pid": pid, "tid": 0,
            "ts": perf_to_epoch_us(e["ts"]), "dur": e["dur"] * 1e6,
            "cat": "flashinfer_tpu",
        }
        for e in (timeline_events or [])
    ]
    events.append({
        "name": "flashinfer_tpu.obs.snapshot", "ph": "M", "pid": pid,
        "tid": 0, "args": {"snapshot": snapshot},
    })
    return {"traceEvents": events}


def write_chrome_trace(path: str, snapshot: dict,
                       timeline_events: Optional[list] = None) -> None:
    from flashinfer_tpu.utils import atomic_write_text

    atomic_write_text(path, json.dumps(
        to_chrome_trace(snapshot, timeline_events)))


# ---------------------------------------------------------------------------
# Unified flight-recorder trace (`obs trace`, ISSUE 10): lifecycle +
# retrace spans (obs.spans) nested with the @flashinfer_api op timeline
# and the metrics snapshot in ONE Perfetto-loadable file — possible
# because every recorder stamps time.perf_counter and every exporter
# converts through profiler.perf_to_epoch_us (one clock base).
# ---------------------------------------------------------------------------


def to_unified_chrome_trace(snapshot: dict,
                            timeline_events: Optional[list] = None,
                            spans: Optional[list] = None,
                            extra_events: Optional[list] = None) -> dict:
    """One trace: flight-recorder spans (dicts from ``obs.spans.drain``)
    on per-thread tracks, op-timeline events on the ``ops`` track, the
    registry snapshot as the self-describing metadata event.

    ``extra_events``: pre-built chrome-trace event dicts appended
    verbatim — already on the epoch clock base (the contract of
    ``obs.steploop.trace_events``, whose host/device step lanes merge
    here)."""
    from flashinfer_tpu.profiler import perf_to_epoch_us

    pid = os.getpid()
    events: List[dict] = []
    for s in (spans or []):
        args = dict(s.get("attrs") or {})
        args["span_id"] = s.get("span_id")
        if s.get("parent_id") is not None:
            args["parent_id"] = s["parent_id"]
        ev = {
            "name": s["name"], "pid": pid, "tid": int(s.get("tid", 0)),
            "cat": s.get("cat", "host"),
            "ts": perf_to_epoch_us(s["ts"]),
            "args": args,
        }
        if s.get("dur", 0.0) > 0.0:
            ev.update(ph="X", dur=s["dur"] * 1e6)
        else:
            ev.update(ph="i", s="t")
        events.append(ev)
    # the op timeline rides a dedicated synthetic track so dispatch
    # spans (which cover the same wall window from the calling thread)
    # don't visually collide with it
    for e in (timeline_events or []):
        events.append({
            "name": e["name"], "ph": "X", "pid": pid, "tid": 0,
            "cat": "op", "ts": perf_to_epoch_us(e["ts"]),
            "dur": e["dur"] * 1e6,
        })
    events.append({
        "name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "ops (@flashinfer_api timeline)"},
    })
    events.extend(extra_events or [])
    events.append({
        "name": "flashinfer_tpu.obs.snapshot", "ph": "M", "pid": pid,
        "tid": 0, "args": {"snapshot": snapshot},
    })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_unified_trace(path: str, snapshot: dict,
                        timeline_events: Optional[list] = None,
                        spans: Optional[list] = None,
                        extra_events: Optional[list] = None) -> dict:
    from flashinfer_tpu.utils import atomic_write_text

    trace = to_unified_chrome_trace(snapshot, timeline_events, spans,
                                    extra_events)
    atomic_write_text(path, json.dumps(trace))
    return trace


_VALID_PH = frozenset({"X", "i", "M", "B", "E"})


def validate_chrome_trace(trace: dict, *,
                          require_lifecycle: bool = False) -> List[str]:
    """Schema check of a unified trace (the `obs trace --selftest` CI
    gate): returns the list of violations, empty when valid.

    ``require_lifecycle`` additionally demands at least one
    request-lifecycle span and the TTFT/TPOT histograms in the embedded
    snapshot — the acceptance shape of a metered serving run."""
    problems: List[str] = []
    if not isinstance(trace, dict) or not isinstance(
            trace.get("traceEvents"), list):
        return ["trace is not a dict with a traceEvents list"]
    snapshot = None
    cats = set()
    for i, ev in enumerate(trace["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not a dict")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing name")
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            problems.append(f"{where}: bad ph {ph!r}")
            continue
        if ph != "M":
            if not isinstance(ev.get("ts"), (int, float)):
                problems.append(f"{where}: missing numeric ts")
            if not isinstance(ev.get("pid"), int) \
                    or not isinstance(ev.get("tid"), int):
                problems.append(f"{where}: missing pid/tid")
        if ph == "X" and (not isinstance(ev.get("dur"), (int, float))
                          or ev["dur"] < 0):
            problems.append(f"{where}: X event needs dur >= 0")
        cats.add(ev.get("cat"))
        if ev.get("name") == "flashinfer_tpu.obs.snapshot":
            snapshot = (ev.get("args") or {}).get("snapshot")
    if snapshot is None:
        problems.append("no flashinfer_tpu.obs.snapshot metadata event")
    if require_lifecycle:
        if "request" not in cats:
            problems.append("no request-lifecycle span (cat='request')")
        hists = (snapshot or {}).get("histograms", {})
        for name in ("lifecycle.ttft_us", "lifecycle.tpot_us"):
            if name not in hists:
                problems.append(f"snapshot missing histogram {name}")
    return problems
