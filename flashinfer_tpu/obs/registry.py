"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

TPU re-design of the reference's observability layer (device event
buffers -> Perfetto in ``include/flashinfer/profiler.cuh:33-80``, leveled
``@flashinfer_api`` logging): the *metrics* half.  Where the profiler
answers "what ran when", this registry answers "how often / how long /
how wasteful" across a process lifetime, cheap enough to leave wired
into the hot paths.

Design constraints (ISSUE 2 tentpole):

- **No-op-cheap when disabled.**  ``FLASHINFER_TPU_METRICS=0`` (the
  default) must cost instrumented call sites one function call + one
  env-dict lookup; the ``@flashinfer_api`` fast path additionally folds
  the check into its single instrumentation-active branch
  (api_logging.py).  The gate lives in the ``flashinfer_tpu.obs``
  facade; the registry itself is ALWAYS functional, so infrastructure
  that has already paid for the slow path (the api-log call index, the
  bench auditor) can count unconditionally.
- **Thread-safe when on.**  One lock per registry around every mutation
  and snapshot — serving loops call decorated ops from executor threads
  (the same reason trace.py takes a lock for its jsonl writes).
- **Fixed buckets, derived quantiles.**  Histograms use immutable
  bucket boundaries fixed at first observation (log-spaced defaults
  suited to host-dispatch latencies); p50/p90/p99 are interpolated from
  bucket counts at snapshot time, so ``observe()`` is O(len(buckets))
  bisection with no sample retention.

Metric names and label schemas are declared in ``obs.catalog`` — the
analysis pass L005 cross-checks the public-API surface against it.
"""

from __future__ import annotations

import bisect
import os
import threading
from typing import Dict, Iterable, Optional, Tuple

# log-spaced µs boundaries covering sub-µs host bookkeeping up to the
# multi-second first-compile outliers seen through the axon tunnel
DEFAULT_BUCKETS_US: Tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1e3, 2e3, 5e3, 1e4, 2e4, 5e4, 1e5, 2e5, 5e5, 1e6, 5e6,
)

# percentage-valued histograms (padding waste): linear buckets
PERCENT_BUCKETS: Tuple[float, ...] = (
    0.0, 1.0, 2.0, 5.0, 10.0, 20.0, 30.0, 40.0, 50.0, 60.0, 70.0, 80.0,
    90.0, 95.0, 100.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def metrics_enabled() -> bool:
    """The ``FLASHINFER_TPU_METRICS`` gate (default off), read lazily per
    call like every other ``FLASHINFER_TPU_*`` flag so tests can
    monkeypatch it (env.py module docstring)."""
    return os.environ.get("FLASHINFER_TPU_METRICS", "0") not in ("", "0")


def spans_enabled() -> bool:
    """The ``FLASHINFER_TPU_SPANS`` gate (default off) for the serving
    flight recorder (obs.spans).  Lives HERE, not in spans.py, so the
    gate check never imports the spans machinery — with the flag unset,
    plain library use must not load obs.spans at all (the subprocess
    pin in tests/test_obs_spans.py, the costmodel precedent)."""
    return os.environ.get("FLASHINFER_TPU_SPANS", "0") not in ("", "0")


def steploop_enabled() -> bool:
    """The ``FLASHINFER_TPU_STEPLOOP`` gate (default off) for the
    step-loop flight deck (obs.steploop): per-step host/device overlap
    ledger + predicted-vs-measured drift join.  Same placement rule as
    :func:`spans_enabled` — the gate lives HERE so checking it never
    imports the steploop machinery (the zero-overhead subprocess pin in
    tests/test_steploop.py).  Gate-ON steps pay a completion probe
    (device sync per step), so this is a measurement mode, never a
    production default."""
    return os.environ.get("FLASHINFER_TPU_STEPLOOP", "0") not in ("", "0")


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Histogram:
    """Fixed-boundary histogram with interpolated quantiles.

    Not self-locking: the owning :class:`Registry` serializes access
    (one registry lock beats one lock per metric cell for snapshot
    consistency).
    """

    __slots__ = ("boundaries", "counts", "count", "total", "vmin", "vmax")

    def __init__(self, boundaries: Iterable[float]):
        self.boundaries = tuple(float(b) for b in boundaries)
        if list(self.boundaries) != sorted(set(self.boundaries)):
            raise ValueError("histogram boundaries must be sorted, unique")
        # counts[i] covers (boundaries[i-1], boundaries[i]]; the final
        # slot is the +Inf overflow bucket
        self.counts = [0] * (len(self.boundaries) + 1)
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")

    def observe(self, value: float) -> None:
        value = float(value)
        self.counts[bisect.bisect_left(self.boundaries, value)] += 1
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)

    def quantile(self, q: float) -> Optional[float]:
        """Linear interpolation within the bucket holding rank q*count
        (Prometheus histogram_quantile semantics), clamped to the
        observed [min, max] so tiny samples don't report a bucket edge
        far beyond any real observation."""
        if self.count == 0:
            return None
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if acc + c >= rank:
                lo = self.boundaries[i - 1] if i > 0 else 0.0
                hi = (self.boundaries[i] if i < len(self.boundaries)
                      else self.vmax)
                frac = (rank - acc) / c
                est = lo + (hi - lo) * frac
                return max(self.vmin, min(est, self.vmax))
            acc += c
        return self.vmax

    def to_dict(self) -> dict:
        d = {
            "count": self.count,
            "sum": self.total,
        }
        if self.count:
            d.update(
                min=self.vmin, max=self.vmax,
                p50=self.quantile(0.50), p90=self.quantile(0.90),
                p99=self.quantile(0.99),
                buckets={
                    ("+Inf" if i == len(self.boundaries)
                     else repr(self.boundaries[i])): c
                    for i, c in enumerate(self.counts) if c
                },
            )
        return d


class Registry:
    """Thread-safe metric store.  Cells are created on first touch; a
    metric name maps to a dict of label-sets so ``snapshot()`` can emit
    the Prometheus-style grouped form."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[LabelKey, int]] = {}
        self._gauges: Dict[str, Dict[LabelKey, float]] = {}
        self._hists: Dict[str, Dict[LabelKey, Histogram]] = {}
        self._hist_buckets: Dict[str, Tuple[float, ...]] = {}

    # -- mutation ---------------------------------------------------------

    def counter_inc(self, name: str, value: int = 1, **labels) -> int:
        key = _label_key(labels)
        with self._lock:
            cells = self._counters.setdefault(name, {})
            cells[key] = new = cells.get(key, 0) + int(value)
        return new

    def gauge_set(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges.setdefault(name, {})[_label_key(labels)] = \
                float(value)

    def observe(self, name: str, value: float,
                buckets: Optional[Iterable[float]] = None, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            cells = self._hists.setdefault(name, {})
            h = cells.get(key)
            if h is None:
                bounds = (tuple(buckets) if buckets is not None
                          else self._hist_buckets.get(name,
                                                      DEFAULT_BUCKETS_US))
                h = cells[key] = Histogram(bounds)
            h.observe(value)

    def declare_histogram(self, name: str,
                          buckets: Iterable[float]) -> None:
        """Pin bucket boundaries for `name` ahead of the first observe
        (the catalog declares percent-valued histograms this way)."""
        with self._lock:
            self._hist_buckets[name] = tuple(buckets)

    # -- read side --------------------------------------------------------

    def snapshot(self) -> dict:
        """One JSON-ready dict of everything recorded so far.  Label
        sets render as ``name{k=v,...}`` flat keys — trivially diffable
        and greppable, and the exporters re-parse them losslessly."""

        def flat(cells, render):
            out = {}
            for key, val in sorted(cells.items()):
                lbl = ",".join(f"{k}={v}" for k, v in key)
                out["{" + lbl + "}" if lbl else ""] = render(val)
            return out

        with self._lock:
            return {
                "counters": {
                    name: flat(cells, int)
                    for name, cells in sorted(self._counters.items())
                },
                "gauges": {
                    name: flat(cells, float)
                    for name, cells in sorted(self._gauges.items())
                },
                "histograms": {
                    name: flat(cells, Histogram.to_dict)
                    for name, cells in sorted(self._hists.items())
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


_global = Registry()


def get() -> Registry:
    return _global
