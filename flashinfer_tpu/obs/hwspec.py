"""Chip-spec registry: the single source of truth for hardware ceilings.

Every number the repo used to scatter (bench.py's ``HBM_PEAK_TBPS``
table, per-phase ``hbm_gbps`` recomputations, the analysis pass's
literal ``VMEM_CAPS``) lives here once, so the roofline attribution in
:mod:`~flashinfer_tpu.obs.roofline` and the VMEM-budget lint (L009)
can never disagree about what the hardware can do.

Import contract: **plain data, no side effects**.  This module reads no
env vars and touches no backend at import time — ``analysis/
vmem_budget.py`` imports ``VMEM_CAPS`` from here and must stay usable
in a lint process with no accelerator.  Detection (:func:`detect_chip`
/ :func:`current_spec`) reads ``FLASHINFER_TPU_CHIP`` and the jax
device kind lazily, per call.

Provenance of the numbers:

- HBM peak TB/s: the values bench.py has banked against since round 1
  (v5e 0.819 validated by the 87.6-90.9% decode rows — a wrong peak
  would put measurements over 100%).
- MXU peak TFLOP/s by dtype: published per-chip peaks (v5e 197 bf16 /
  394 int8 — the "197 TFLOP/s chip" every VERDICT MFU number divides
  by; v5p 459/918; v4 275 bf16, no int8 MXU mode → bf16 rate; v6e 918/
  1836).  ``fp8`` maps to the int8 rate where no native fp8 mode
  exists — same MXU width.
- VMEM bytes: compile-budget ceilings, not datasheet capacities —
  v5e 64 MiB is on-chip-validated by this repo's own kernels (they
  request vmem_limit_bytes=64 MiB and compile, HW_TIER_LOG); v5p
  carries 2x per tuning_configs/v5p.json; v4/v6e conservatively
  inherit the v5e bound.
- ICI GB/s: per-chip aggregate interconnect bandwidth (v4 2400 Gbps,
  v5e 1600, v5p 4800, v6e 3584 — /8 to bytes), for sizing the
  all-reduce terms the single-chip bench excludes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    """Peak ceilings for one TPU generation."""

    name: str
    hbm_tbps: float  # peak HBM bandwidth, TB/s
    mxu_tflops: Mapping[str, float]  # dtype -> peak TFLOP/s
    vmem_bytes: int  # compile-budget VMEM ceiling (see module doc)
    ici_gbps: float  # per-chip aggregate ICI bandwidth, GB/s
    hbm_gib: float  # HBM capacity, GiB (fits-in-memory sizing)

    def peak_tflops(self, dtype: str = "bf16") -> float:
        """Peak MXU TFLOP/s for `dtype` (normalized; unknown dtypes
        fall back to the conservative bf16 rate)."""
        return self.mxu_tflops.get(normalize_dtype(dtype),
                                   self.mxu_tflops["bf16"])

    def ridge_intensity(self, dtype: str = "bf16") -> float:
        """The roofline ridge point in FLOPs/byte: arithmetic
        intensities below this are memory-bound on this chip."""
        return self.peak_tflops(dtype) / self.hbm_tbps


_DTYPE_ALIASES = {
    "bfloat16": "bf16", "bf16": "bf16", "float32": "bf16", "f32": "bf16",
    "float16": "bf16", "fp16": "bf16",
    "int8": "int8", "i8": "int8",
    "fp8": "fp8", "float8_e4m3fn": "fp8", "float8_e5m2": "fp8",
    "e4m3": "fp8", "e5m2": "fp8",
}


def normalize_dtype(dtype: str) -> str:
    return _DTYPE_ALIASES.get(str(dtype).lower(), "bf16")


CHIP_SPECS: Dict[str, ChipSpec] = {
    "v4": ChipSpec(
        name="v4", hbm_tbps=1.228,
        mxu_tflops={"bf16": 275.0, "int8": 275.0, "fp8": 275.0},
        vmem_bytes=64 * 1024 * 1024, ici_gbps=300.0, hbm_gib=32.0,
    ),
    "v5e": ChipSpec(
        name="v5e", hbm_tbps=0.819,
        mxu_tflops={"bf16": 197.0, "int8": 394.0, "fp8": 394.0},
        vmem_bytes=64 * 1024 * 1024, ici_gbps=200.0, hbm_gib=16.0,
    ),
    "v5p": ChipSpec(
        name="v5p", hbm_tbps=2.765,
        mxu_tflops={"bf16": 459.0, "int8": 918.0, "fp8": 918.0},
        vmem_bytes=128 * 1024 * 1024, ici_gbps=600.0, hbm_gib=95.0,
    ),
    "v6e": ChipSpec(
        name="v6e", hbm_tbps=1.64,
        mxu_tflops={"bf16": 918.0, "int8": 1836.0, "fp8": 1836.0},
        vmem_bytes=64 * 1024 * 1024, ici_gbps=448.0, hbm_gib=32.0,
    ),
}

# device_kind substrings / user shorthands -> canonical spec name.
# "v5" alone is v5 lite (the device_kind bench.py's matcher saw).
CHIP_ALIASES: Dict[str, str] = {
    "v5": "v5e", "v5litepod": "v5e", "v5e": "v5e",
    "v5p": "v5p", "v4": "v4", "v6e": "v6e", "v6": "v6e",
    "trillium": "v6e",
}

DEFAULT_CHIP = "v5e"  # the chip every banked row so far was measured on

# Plain per-generation VMEM compile-budget dict: what analysis/
# vmem_budget.py (L009) imports.  Kept as a dict of ints (not specs) so
# the lint path stays trivially serializable and import-light.
VMEM_CAPS: Dict[str, int] = {
    name: s.vmem_bytes for name, s in CHIP_SPECS.items()
}


def spec(name: str) -> ChipSpec:
    """Spec by canonical name, alias, or device-kind-ish string
    (``"TPU v5 lite"`` -> v5e).  Unknown names fall back to the
    DEFAULT_CHIP spec — a bench row must never die on a new chip
    string, it just attributes against the conservative default."""
    key = str(name).lower().replace(" ", "")
    if key in CHIP_SPECS:
        return CHIP_SPECS[key]
    if key in CHIP_ALIASES:
        return CHIP_SPECS[CHIP_ALIASES[key]]
    # substring match, longest alias first (so "v5p" beats "v5")
    for alias, canon in sorted(CHIP_ALIASES.items(),
                               key=lambda kv: -len(kv[0])):
        if alias in key:
            return CHIP_SPECS[canon]
    return CHIP_SPECS[DEFAULT_CHIP]


def spec_for_peak_tbps(peak: float,
                       rel_tol: float = 0.02) -> Optional[ChipSpec]:
    """Map a banked row's ``peak`` field (HBM TB/s) back to its chip —
    pre-roofline rows carry only that number.  None when nothing is
    within `rel_tol`."""
    try:
        peak = float(peak)
    except (TypeError, ValueError):
        return None
    for s in CHIP_SPECS.values():
        if peak > 0 and abs(s.hbm_tbps - peak) <= rel_tol * s.hbm_tbps:
            return s
    return None


def detect_chip(device_kind: Optional[str] = None) -> str:
    """Canonical chip name: ``FLASHINFER_TPU_CHIP`` env override first
    (works off-accelerator and in CI), else the jax device kind, else
    DEFAULT_CHIP.  Env/read and backend touch happen HERE, per call —
    never at import."""
    import os

    override = os.environ.get("FLASHINFER_TPU_CHIP")
    if override:
        return spec(override).name
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:  # no backend (lint/CI process) -> default
            return DEFAULT_CHIP
    key = str(device_kind).lower().replace(" ", "")
    if "tpu" not in key and not any(a in key for a in CHIP_ALIASES):
        return DEFAULT_CHIP
    return spec(key).name


def current_spec() -> ChipSpec:
    """The spec roofline attribution should run against right now."""
    return CHIP_SPECS[detect_chip()]


def registry_table() -> Tuple[Tuple[str, ...], ...]:
    """(header, *rows) for docs / ``obs perf`` human output."""
    rows = [("chip", "HBM TB/s", "bf16 TFLOP/s", "int8 TFLOP/s",
             "VMEM MiB", "ICI GB/s", "HBM GiB")]
    for name in sorted(CHIP_SPECS):
        s = CHIP_SPECS[name]
        rows.append((
            name, f"{s.hbm_tbps:g}", f"{s.mxu_tflops['bf16']:g}",
            f"{s.mxu_tflops['int8']:g}",
            f"{s.vmem_bytes // (1024 * 1024)}", f"{s.ici_gbps:g}",
            f"{s.hbm_gib:g}",
        ))
    return tuple(rows)
