"""Self-auditing bench telemetry: machine-stamped row quality.

VERDICT weak #3: the banked bench record contains poison rows (tunnel
degraded-window artifacts reading ~19x low) that were only caught by
manual cross-checking, and the in-phase ``<0.35x best`` re-measure
guard had never executed.  This module moves the audit to EMIT time:
every ``bench.py`` row is routed through :class:`RowAuditor`, which
compares the row's primary throughput metric against the best known
measurement of the same configuration — across this run AND the
``BENCH_BANKED.md`` history — and stamps::

    quality: "ok"        >= 0.70x best   (normal run-to-run spread;
                                          banked dispersion is ~2x
                                          across the grid, ~4% at a
                                          fixed cell)
    quality: "degraded"  [0.35x, 0.70x)  (suspicious window; keep but
                                          don't bank as the cell's
                                          number without a re-measure)
    quality: "poison"    < 0.35x best    (the committed implausibility
                                          rule from phase_decode —
                                          never quote this row)

plus ``vs_best`` (the ratio) so the stamp is auditable.  Rows with no
comparable history are ``ok`` by definition (best = self).

The key is the row's full non-measurement identity (phase + every
config field), so a bs=64 ctx=4096 decode row only ever competes with
other bs=64 ctx=4096 decode rows.
"""

from __future__ import annotations

import hashlib
import json
import re
from typing import Dict, Iterable, List, Optional, Tuple

# measurement outputs (never part of a row's identity key).  The
# roofline stamp fields (obs.roofline.ROW_FIELDS) live here too — and
# so does ``chip``: a bs=64 ctx=4096 decode row measured on v5p MUST
# compete with the v5e history for the same configuration, which is
# exactly what the roofline-fraction comparison below makes fair.
MEASUREMENT_FIELDS = frozenset({
    "us", "tbps", "tok_s", "tflops", "gbps", "pct_roofline",
    "kernel_us", "xla_us", "speedup", "us_per_layer", "us_step_80l",
    "tok_s_per_chip", "linearity", "us_step", "tok_s_at_depth",
    "slope_pred_us", "overhead_vs_slope", "overhead_decomposition",
    "peak", "quality", "vs_best", "vs_best_roofline",
    "flops", "bytes_read", "bytes_written", "intensity", "bound",
    "effective_pct_roofline", "chip", "dtype", "flops_effective",
    # split-KV decode stamp: merge_bytes is derived from the cost model
    # and pred_us from its predictor (both recalibrate-able — like
    # slope_pred_us, never identity); num_splits is deliberately NOT
    # here — rows at different split factors are different
    # configurations and must not compete in the quality audit
    "merge_bytes", "pred_us",
    # serving_fused A/B: the per-step host-dispatch residual (us_step
    # minus the shared slope floor) — derived, never identity.
    # step_mode (fused | per_op) is deliberately NOT here: the two
    # dispatch structures are different configurations with separate
    # banked histories, the num_splits precedent
    "dispatch_residual_us",
    # sharded serving step: predicted ICI wire bytes + the fraction of
    # measured time the ICI floor explains (both derived from the cost
    # model — recalibrate-able, never identity).  mesh_axes
    # (ShardingPlan.mesh_axes, e.g. "dp1.tp8") is deliberately NOT
    # here: mesh SHAPE is configuration, so a tp8 row never competes
    # with tp1 history — the step_mode/num_splits precedent
    "ici_bytes", "pct_ici_roofline",
    # request-lifecycle stamps on serving rows (ISSUE 10): steady-state
    # time-per-output-token and first-step-from-fresh-state latency —
    # measurements of the same run, never identity
    "tpot_us", "ttft_us",
    # continuous-batching engine rows (serving_engine phase): the
    # measured prefix-cache hit rate, the cost-model-priced prefill
    # FLOPs the hits avoided, and the run's compile/retrace/preempt/
    # evict outcomes — all measurements of the same workload replay
    # (the Zipf skew + request mix ARE identity and stay so)
    "prefix_hit_rate", "prefill_flops_avoided", "num_traces",
    "preemptions", "evictions",
    "ttft_p50_us", "ttft_p99_us", "tpot_p50_us", "tpot_p99_us",
    # attention_backend ("reference" — the dense XLA oracle tier — vs
    # "kernel" — the Pallas work-unit lowering) is deliberately NOT
    # here: the two attention tiers are different configurations with
    # separate banked histories even at identical engine shapes, so a
    # kernel-tier row never competes with reference-row history — the
    # step_mode/mesh_axes precedent (roofline.stamp_row stamps it)
    # backend-token agreement of the serving_engine A/B pair — derived
    # cross-row check results, never identity (exact on f32 models,
    # rate-reported on bf16 where the kernel tier's bf16 MXU dots
    # legitimately round differently from the f32-upcast reference)
    "backend_tokens_equal", "backend_token_match",
    # tiered-KV rows (serving_disagg phase): migration/spill/restore
    # traffic, host-copy wall time, and the resume-miss count — all
    # measurements of the same workload replay.  ``mode``
    # (handoff | kv_migrate | spill) is deliberately NOT here: the
    # three tier exercises are different configurations with separate
    # banked histories (the step_mode/mesh_axes precedent), and so is
    # the engine/pool geometry that shapes them
    "migrations", "migrate_bytes", "migrate_us", "unified_wall_s",
    "spills", "restores", "spill_bytes", "restore_bytes",
    "recomputes", "host_evictions", "disagg_tokens_equal",
    "spill_tokens_equal",
    # prefill ingest A/B (ISSUE 14): the cost model's predicted
    # avoided-HBM delta for the row's shape — derived like merge_bytes,
    # never identity.  ``fused_ingest`` (the ingest-mode flag itself)
    # is deliberately NOT here: fused and separate rows of the A/B
    # pair are different configurations with separate banked histories
    # (the step_mode/attention_backend precedent;
    # roofline.stamp_row stamps it)
    "ingest_bytes_avoided",
    # step-loop flight-deck stamps on serving rows (ISSUE 17): device
    # idle per step, the host-serialization fraction of the cadence,
    # and the cost model's predicted/measured step-time ratio — all
    # measurements of the same run (the tpot_us/ttft_us precedent),
    # never identity; perf/6's host_loop section joins on them
    "host_gap_us", "host_frac", "pred_step_ratio",
    # configuration-identity digest (ISSUE 20): sha256[:12] of row_key,
    # stamped by RowAuditor so bring-up journal entries and graduated
    # tuning sections can reference banked rows; derived from identity,
    # never part of it (and recomputable for pre-stamp history rows)
    "row_id",
})

# primary throughput metric, in preference order; all higher-is-better
THROUGHPUT_FIELDS = ("tbps", "tflops", "gbps", "tok_s_per_chip",
                     "tok_s_at_depth", "tok_s", "speedup")

POISON_THRESHOLD = 0.35  # the committed phase_decode implausibility rule
DEGRADED_THRESHOLD = 0.70
# a measurement above the binding hardware ceiling is a timer artifact
# (the <0.35x rule only catches too-SLOW artifacts; the banked history
# carries decode rows at 1.5-2.0x the v5e roofline from slope-fit noise
# on ~20 us kernels) — small tolerance for spec rounding
IMPLAUSIBLY_FAST_ROOFLINE = 1.05

_JSON_BLOCK_RE = re.compile(r"^```json\s*$(.*?)^```\s*$",
                            re.MULTILINE | re.DOTALL)


def row_key(row: dict) -> Tuple:
    """Hashable identity of a row's configuration."""
    return tuple(sorted(
        (k, str(v)) for k, v in row.items()
        if k not in MEASUREMENT_FIELDS
    ))


def row_stamp(row: dict) -> str:
    """12-hex configuration-identity digest (sha256 of :func:`row_key`).

    The join key between the bring-up session journal / graduated tuning
    sections and banked rows: rows of the same configuration share a
    stamp across runs, and the stamp is recomputable for history rows
    banked before RowAuditor started writing ``row_id``."""
    key = json.dumps(row_key(row))
    return hashlib.sha256(key.encode()).hexdigest()[:12]


# fields obs.roofline.stamp_row always writes alongside pct_roofline —
# their presence identifies a stamped (fraction-valued) row
_STAMP_MARKERS = ("bound", "chip", "flops")


def roofline_fraction(row: dict) -> Optional[float]:
    """The row's fraction-of-binding-roofline, normalized.  Rows
    stamped by obs.roofline (identified by the stamp fields riding
    along) carry a 0..1 fraction; pre-roofline scans rows banked a
    PERCENT under the same name, and the banked history spans 0.5-94.0
    percent — magnitude can't discriminate (a 0.6-percent artifact row
    would read as a winning 0.6 fraction), the stamp's presence can."""
    v = row.get("pct_roofline")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
        return None
    v = float(v)
    if any(row.get(k) is not None for k in _STAMP_MARKERS):
        return v
    return v / 100.0


def primary_metric(row: dict) -> Optional[Tuple[str, float]]:
    """(field, higher-is-better value) or None if the row carries no
    recognized throughput number (latency-only rows fall back to 1/us)."""
    for f in THROUGHPUT_FIELDS:
        v = row.get(f)
        if isinstance(v, (int, float)) and v > 0:
            return f, float(v)
    v = row.get("us") or row.get("us_step") or row.get("kernel_us")
    if isinstance(v, (int, float)) and v > 0:
        return "inv_us", 1.0 / float(v)
    return None


def load_banked_history(path: str, strict: bool = False) -> List[dict]:
    """Rows from every ```json block of a BENCH_BANKED.md-style file
    (each block is a full run record with a "rows" list).  Tolerant by
    default: a malformed block is skipped, an absent file is empty
    history.  ``strict=True`` (the ``obs perf`` CI smoke gate) raises
    ``ValueError`` naming every malformed block / non-dict row instead
    of silently dropping data."""
    rows: List[dict] = []
    errors: List[str] = []
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError as e:
        if strict:
            raise ValueError(f"{path}: {e}") from e
        return rows
    for m in _JSON_BLOCK_RE.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        try:
            record = json.loads(m.group(1))
        except json.JSONDecodeError as e:
            errors.append(f"{path}:{line}: malformed json block ({e})")
            continue
        got = record.get("rows", []) if isinstance(record, dict) else []
        bad = sum(1 for r in got if not isinstance(r, dict))
        if bad:
            errors.append(f"{path}:{line}: {bad} non-dict row(s)")
        rows.extend(r for r in got if isinstance(r, dict))
    if strict and errors:
        raise ValueError("; ".join(errors))
    return rows


class RowAuditor:
    """Tracks best-by-configuration and stamps rows in place.

    Two comparison spaces per configuration key:

    - **raw** (the original rule): the primary throughput metric vs the
      best known raw measurement — meaningful when history and row come
      from the same chip generation;
    - **roofline-fraction** (chip-generation-portable): the row's
      ``pct_roofline`` vs the best known fraction for the key.  ``chip``
      is a measurement field, so a v5p row and the v5e history share a
      key — raw TB/s would mis-compare across that boundary in either
      direction, while fraction-of-own-roofline stays honest.  When
      both spaces are available the fraction ratio decides the quality
      stamp; the raw ratio still rides along as ``vs_best``.
    """

    def __init__(self, history: Iterable[dict] = ()):
        self._best: Dict[Tuple, float] = {}
        self._best_frac: Dict[Tuple, float] = {}
        for row in history:
            self._account(row)

    def _account(self, row: dict) -> None:
        # a row some past auditor already stamped poison never defines
        # the baseline.  Low artifacts can't raise the max() anyway;
        # this guards the residual case — history trimmed down to a
        # lone flagged row for a key (pre-stamping banked rows carry no
        # quality field and are accounted normally)
        if row.get("quality") == "poison":
            return
        key = row_key(row)
        pm = primary_metric(row)
        if pm is not None:
            _, value = pm
            if value > self._best.get(key, 0.0):
                self._best[key] = value
        frac = roofline_fraction(row)
        if frac is not None and frac > self._best_frac.get(key, 0.0):
            self._best_frac[key] = frac

    def stamp(self, row: dict) -> dict:
        """Add ``quality`` (+ ``vs_best`` / ``vs_best_roofline`` when
        history exists) to `row` in place and fold it into the running
        best.  Never raises."""
        try:
            key = row_key(row)
            row["row_id"] = row_stamp(row)
            pm = primary_metric(row)
            ratio_raw = None
            if pm is not None:
                _, value = pm
                best = max(self._best.get(key, 0.0), value)
                ratio_raw = value / best
                if best > value:
                    row["vs_best"] = round(ratio_raw, 3)
            ratio_frac = None
            frac = roofline_fraction(row)
            if frac is not None and frac > IMPLAUSIBLY_FAST_ROOFLINE:
                # faster than the hardware ceiling: a timer artifact,
                # poisoned outright (and never folded into the best)
                row["quality"] = "poison"
                return row
            if frac is not None:
                best_frac = max(self._best_frac.get(key, 0.0), frac)
                ratio_frac = frac / best_frac
                if best_frac > frac:
                    row["vs_best_roofline"] = round(ratio_frac, 3)
            # fraction space takes precedence: it is the comparison
            # that stays valid when the chip generation changed
            ratio = ratio_frac if ratio_frac is not None else ratio_raw
            if ratio is None:
                row["quality"] = "ok"  # nothing measurable to audit
                return row
            if ratio < POISON_THRESHOLD:
                row["quality"] = "poison"
            elif ratio < DEGRADED_THRESHOLD:
                row["quality"] = "degraded"
            else:
                row["quality"] = "ok"
            self._account(row)
        except Exception:  # noqa: BLE001 - the audit must never cost a row
            row.pop("quality", None)
        return row
