"""Self-auditing bench telemetry: machine-stamped row quality.

VERDICT weak #3: the banked bench record contains poison rows (tunnel
degraded-window artifacts reading ~19x low) that were only caught by
manual cross-checking, and the in-phase ``<0.35x best`` re-measure
guard had never executed.  This module moves the audit to EMIT time:
every ``bench.py`` row is routed through :class:`RowAuditor`, which
compares the row's primary throughput metric against the best known
measurement of the same configuration — across this run AND the
``BENCH_BANKED.md`` history — and stamps::

    quality: "ok"        >= 0.70x best   (normal run-to-run spread;
                                          banked dispersion is ~2x
                                          across the grid, ~4% at a
                                          fixed cell)
    quality: "degraded"  [0.35x, 0.70x)  (suspicious window; keep but
                                          don't bank as the cell's
                                          number without a re-measure)
    quality: "poison"    < 0.35x best    (the committed implausibility
                                          rule from phase_decode —
                                          never quote this row)

plus ``vs_best`` (the ratio) so the stamp is auditable.  Rows with no
comparable history are ``ok`` by definition (best = self).

The key is the row's full non-measurement identity (phase + every
config field), so a bs=64 ctx=4096 decode row only ever competes with
other bs=64 ctx=4096 decode rows.
"""

from __future__ import annotations

import json
import re
from typing import Dict, Iterable, List, Optional, Tuple

# measurement outputs (never part of a row's identity key)
MEASUREMENT_FIELDS = frozenset({
    "us", "tbps", "tok_s", "tflops", "gbps", "pct_roofline",
    "kernel_us", "xla_us", "speedup", "us_per_layer", "us_step_80l",
    "tok_s_per_chip", "linearity", "us_step", "tok_s_at_depth",
    "slope_pred_us", "overhead_vs_slope", "overhead_decomposition",
    "peak", "quality", "vs_best",
})

# primary throughput metric, in preference order; all higher-is-better
THROUGHPUT_FIELDS = ("tbps", "tflops", "gbps", "tok_s_per_chip",
                     "tok_s_at_depth", "tok_s", "speedup")

POISON_THRESHOLD = 0.35  # the committed phase_decode implausibility rule
DEGRADED_THRESHOLD = 0.70

_JSON_BLOCK_RE = re.compile(r"^```json\s*$(.*?)^```\s*$",
                            re.MULTILINE | re.DOTALL)


def row_key(row: dict) -> Tuple:
    """Hashable identity of a row's configuration."""
    return tuple(sorted(
        (k, str(v)) for k, v in row.items()
        if k not in MEASUREMENT_FIELDS
    ))


def primary_metric(row: dict) -> Optional[Tuple[str, float]]:
    """(field, higher-is-better value) or None if the row carries no
    recognized throughput number (latency-only rows fall back to 1/us)."""
    for f in THROUGHPUT_FIELDS:
        v = row.get(f)
        if isinstance(v, (int, float)) and v > 0:
            return f, float(v)
    v = row.get("us") or row.get("us_step") or row.get("kernel_us")
    if isinstance(v, (int, float)) and v > 0:
        return "inv_us", 1.0 / float(v)
    return None


def load_banked_history(path: str) -> List[dict]:
    """Rows from every ```json block of a BENCH_BANKED.md-style file
    (each block is a full run record with a "rows" list).  Tolerant:
    a malformed block is skipped, an absent file is empty history."""
    rows: List[dict] = []
    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return rows
    for m in _JSON_BLOCK_RE.finditer(text):
        try:
            record = json.loads(m.group(1))
        except json.JSONDecodeError:
            continue
        got = record.get("rows", []) if isinstance(record, dict) else []
        rows.extend(r for r in got if isinstance(r, dict))
    return rows


class RowAuditor:
    """Tracks best-by-configuration and stamps rows in place."""

    def __init__(self, history: Iterable[dict] = ()):
        self._best: Dict[Tuple, float] = {}
        for row in history:
            self._account(row)

    def _account(self, row: dict) -> None:
        pm = primary_metric(row)
        if pm is None:
            return
        # a row some past auditor already stamped poison never defines
        # the baseline.  Low artifacts can't raise the max() anyway;
        # this guards the residual case — history trimmed down to a
        # lone flagged row for a key (pre-stamping banked rows carry no
        # quality field and are accounted normally)
        if row.get("quality") == "poison":
            return
        key = row_key(row)
        _, value = pm
        if value > self._best.get(key, 0.0):
            self._best[key] = value

    def stamp(self, row: dict) -> dict:
        """Add ``quality`` (+ ``vs_best`` when history exists) to `row`
        in place and fold it into the running best.  Never raises."""
        try:
            pm = primary_metric(row)
            if pm is None:
                row["quality"] = "ok"  # nothing measurable to audit
                return row
            _, value = pm
            best = max(self._best.get(row_key(row), 0.0), value)
            ratio = value / best
            if ratio < POISON_THRESHOLD:
                row["quality"] = "poison"
            elif ratio < DEGRADED_THRESHOLD:
                row["quality"] = "degraded"
            else:
                row["quality"] = "ok"
            if best > value:
                row["vs_best"] = round(ratio, 3)
            self._account(row)
        except Exception:  # noqa: BLE001 - the audit must never cost a row
            row.pop("quality", None)
        return row
