"""CLI: ``python -m flashinfer_tpu.obs <cmd>``.

Commands:

- ``report``: run a small tier-1-sized workload with metrics enabled
  (decorated stateless ops + the decode/prefill plan/run lifecycle,
  CPU-safe under ``JAX_PLATFORMS=cpu``) and print the snapshot —
  ``--format json`` (default) or ``--format prom``; ``--chrome-trace
  PATH`` additionally records an op timeline during the workload and
  writes the merged trace.  ``--no-workload`` skips the built-in
  workload and reports whatever this process already recorded (for use
  from a REPL / atexit hook).
- ``doctor``: device/env/backend health — collect_env, the
  FLASHINFER_TPU_* flag matrix, backend resolution, compile-guard
  quarantine state, tuner cache, registry liveness, lint hygiene
  (the reasonless-suppression count the analyzer would fail on), and
  cost-model coverage (``@flashinfer_api`` ops with no roofline
  attribution formula).
- ``perf``: the roofline doctor — attribute banked bench rows
  (``--banked BENCH_BANKED.md``) through obs.costmodel/obs.roofline
  and print the per-op efficiency table, bound classification, worst
  offenders, padding-waste and per-serving-phase MFU report that the
  round-5 VERDICT computed by hand.  ``--json`` for the
  schema-stable machine form; exits non-zero on malformed banked
  blocks (the CI smoke gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _workload() -> None:
    """A tier-1-sized pass over the instrumented surface: stateless
    decorated ops plus one full plan/run lifecycle per batch wrapper
    family (small shapes; runs in seconds on CPU)."""
    from flashinfer_tpu.env import apply_platform_from_env

    apply_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 256), jnp.float32)
    fi.rmsnorm(x, jnp.ones((256,), jnp.float32))
    fi.silu_and_mul(jax.random.normal(key, (4, 512), jnp.float32))
    probs = jax.nn.softmax(jax.random.normal(key, (2, 64), jnp.float32))
    fi.sampling_from_probs(probs, key)

    T, HQ, HKV, D = 8, 4, 2, 64
    q = jax.random.normal(key, (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), (T, HKV, D),
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (T, HKV, D),
                          jnp.bfloat16)
    fi.single_prefill_with_kv_cache(q, k, v, causal=True)

    # decode wrapper lifecycle: plan, re-plan (counted), run
    bs, PS, ppr = 2, 4, 2
    npages = bs * ppr
    kc = jax.random.normal(key, (npages, PS, HKV, D), jnp.bfloat16)
    vc = jax.random.normal(jax.random.fold_in(key, 3),
                           (npages, PS, HKV, D), jnp.bfloat16)
    indptr = np.arange(bs + 1, dtype=np.int32) * ppr
    indices = np.arange(npages, dtype=np.int32)
    last = np.full((bs,), PS, np.int32)
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="NHD")
    w.plan(indptr, indices, last, HQ, HKV, D, PS)
    w.plan(indptr, indices, last, HQ, HKV, D, PS)  # re-plan
    qd = jax.random.normal(jax.random.fold_in(key, 4), (bs, HQ, D),
                           jnp.bfloat16)
    w.run(qd, (kc, vc))

    # paged-prefill wrapper lifecycle (the gather path off-TPU)
    wp = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="NHD")
    wp.plan(np.arange(bs + 1, dtype=np.int32) * 2, indptr, indices, last,
            HQ, HKV, D, PS, causal=True)
    qp = jax.random.normal(jax.random.fold_in(key, 5), (bs * 2, HQ, D),
                           jnp.bfloat16)
    wp.run(qp, (kc, vc))


def cmd_report(args) -> int:
    from flashinfer_tpu import obs, profiler
    from flashinfer_tpu.obs import export

    os.environ["FLASHINFER_TPU_METRICS"] = "1"
    events = None
    if not args.no_workload:
        if args.chrome_trace:
            profiler.start_timeline()
        _workload()
        if args.chrome_trace:
            events = profiler.stop_timeline()
    snap = obs.snapshot()
    if args.chrome_trace:
        export.write_chrome_trace(args.chrome_trace, snap, events)
        print(f"# chrome trace -> {args.chrome_trace}", file=sys.stderr)
    if args.format == "prom":
        sys.stdout.write(export.to_prometheus(snap))
    else:
        print(export.to_json(snap))
    return 0


def cmd_doctor(args) -> int:
    """Health report: environment, devices, backend resolution, caches,
    quarantine — everything a bug report / perf triage needs up front."""
    from flashinfer_tpu.collect_env import collect_env

    report = {"env": collect_env()}

    flags = {}
    for name in ("FLASHINFER_TPU_METRICS", "FLASHINFER_TPU_LOGLEVEL",
                 "FLASHINFER_TPU_BACKEND", "FLASHINFER_TPU_INTERPRET",
                 "FLASHINFER_TPU_TIMELINE_SYNC", "FLASHINFER_TPU_TRACE_DUMP",
                 "FLASHINFER_TPU_TRACE_APPLY", "FLASHINFER_TPU_CACHE_DIR",
                 "FLASHINFER_TPU_DUMP_DIR"):
        flags[name] = os.environ.get(name, "<unset>")
    report["flags"] = flags

    try:
        from flashinfer_tpu.utils import is_tpu, resolve_backend

        report["backend_resolution"] = {
            "is_tpu": bool(is_tpu()),
            "single_decode_auto": resolve_backend("auto", "single_decode"),
        }
    except Exception as e:  # device init can fail off-accelerator
        report["backend_resolution"] = f"<unavailable: {type(e).__name__}>"

    from flashinfer_tpu import compile_guard

    q = compile_guard._load_qlist()
    report["quarantine"] = {
        "entries": len(q),
        "ops": sorted({i.get("op", "?") for i in q.values()}),
    }
    try:
        from flashinfer_tpu.autotuner import AutoTuner

        t = AutoTuner.get()
        t._load()
        report["tuner"] = {"cache": str(t._cache_path()),
                          "entries": len(t._cache)}
    except Exception as e:
        report["tuner"] = f"<unavailable: {type(e).__name__}>"

    from flashinfer_tpu import obs, profiler

    snap = obs.snapshot()
    report["registry"] = {
        "metrics_enabled": obs.metrics_enabled(),
        "counters": len(snap["counters"]),
        "gauges": len(snap["gauges"]),
        "histograms": len(snap["histograms"]),
        "timeline_active": profiler.timeline_active(),
    }

    # static-analysis hygiene: a reasonless `# graft-lint: ok` /
    # `# wedge-lint: ok` is an unreviewable waiver (L000/W000 — the
    # analyzer fails on them, they can never be baselined); a non-zero
    # count here means the tree cannot pass lint
    try:
        import flashinfer_tpu as _fi
        from flashinfer_tpu.analysis import core as _acore

        pkg = os.path.dirname(os.path.abspath(_fi.__file__))
        total = reasonless = 0
        for path in _acore.iter_python_files([pkg]):
            sf = _acore.load_file(path)
            for table in (sf.suppressions, sf.wedge_suppressions):
                total += len(table)
                reasonless += sum(
                    1 for reason in table.values() if not reason)
        report["lint"] = {
            "suppressions": total,
            "reasonless_suppressions": reasonless,
        }
    except Exception as e:  # doctor must never crash on a broken tree
        report["lint"] = f"<unavailable: {type(e).__name__}>"

    # cost-model coverage (mirrors analysis L005's obs-coverage idea):
    # a decorated public op with no obs.costmodel family can bench but
    # never roofline-attribute — new ops must not silently ship
    # unattributed, so the uncovered list must stay empty
    try:
        from flashinfer_tpu.obs import costmodel, hwspec

        report["costmodel"] = {
            "api_ops_covered": len(costmodel.API_OP_COSTS),
            "uncovered_api_ops": list(costmodel.uncovered_api_ops()),
            "chip": hwspec.detect_chip(),
        }
    except Exception as e:
        report["costmodel"] = f"<unavailable: {type(e).__name__}>"
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0


def cmd_perf(args) -> int:
    """Roofline doctor over banked bench rows — the VERDICT analysis,
    reproduced mechanically (no jax / no device needed)."""
    from flashinfer_tpu.obs import bench_audit, roofline

    path = args.banked
    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "BENCH_BANKED.md")
    try:
        rows = bench_audit.load_banked_history(path, strict=True)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not rows:
        print(f"error: no bench rows found in {path}", file=sys.stderr)
        return 2
    report = roofline.build_perf_report(rows, chip=args.chip)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        sys.stdout.write(roofline.render_perf_report(report))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m flashinfer_tpu.obs")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("report", help="metrics snapshot (runs a small "
                                       "built-in workload by default)")
    sp.add_argument("--format", choices=["json", "prom"], default="json")
    sp.add_argument("--no-workload", action="store_true",
                    help="report this process's registry as-is")
    sp.add_argument("--chrome-trace", metavar="PATH", default=None,
                    help="also write the merged op-timeline chrome trace")
    sp.set_defaults(fn=cmd_report)
    sp = sub.add_parser("doctor", help="device/env/backend health report")
    sp.set_defaults(fn=cmd_doctor)
    sp = sub.add_parser("perf", help="roofline attribution report over "
                                     "banked bench rows")
    sp.add_argument("--banked", metavar="PATH", default=None,
                    help="BENCH_BANKED.md-style history "
                         "(default: the repo's BENCH_BANKED.md)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable report (schema "
                         "flashinfer_tpu.obs.perf/2: + serving_ici / "
                         "scaling_prediction ICI fields)")
    sp.add_argument("--chip", default=None,
                    help="default chip for rows that name none "
                         "(default: v5e, the banked history's chip)")
    sp.set_defaults(fn=cmd_perf)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
