"""CLI: ``python -m flashinfer_tpu.obs <cmd>``.

Commands:

- ``report``: run a small tier-1-sized workload with metrics enabled
  (decorated stateless ops + the decode/prefill plan/run lifecycle,
  CPU-safe under ``JAX_PLATFORMS=cpu``) and print the snapshot —
  ``--format json`` (default) or ``--format prom``; ``--chrome-trace
  PATH`` additionally records an op timeline during the workload and
  writes the merged trace.  ``--no-workload`` skips the built-in
  workload and reports whatever this process already recorded (for use
  from a REPL / atexit hook).
- ``doctor``: device/env/backend health — collect_env, the
  FLASHINFER_TPU_* flag matrix, backend resolution, compile-guard
  quarantine state, tuner cache, registry liveness, lint hygiene
  (the reasonless-suppression count the analyzer would fail on),
  cost-model coverage (``@flashinfer_api`` ops with no roofline
  attribution formula), flight-recorder state (span coverage of the
  serving ops — the L005 rule extended to spans), and the ranked
  top-retrace-causes table.
- ``perf``: the roofline doctor — attribute banked bench rows
  (``--banked BENCH_BANKED.md``) through obs.costmodel/obs.roofline
  and print the per-op efficiency table, bound classification, worst
  offenders, padding-waste and per-serving-phase MFU report that the
  round-5 VERDICT computed by hand.  ``--json`` for the
  schema-stable machine form; exits non-zero on malformed banked
  blocks (the CI smoke gate).
- ``trace``: the flight-recorder export (ISSUE 10) — run a small
  compile-once fused serving loop (``--steps``, default 9) with the
  spans gate + metrics + op timeline ALL on, a metered request
  lifecycle per batch lane, and (unless ``--no-perturb``) one
  deliberately perturbed static at the end, then write the UNIFIED
  chrome trace (lifecycle spans + op events + registry snapshot on one
  clock base) to ``--out``.  ``--selftest`` exits non-zero unless the
  export is schema-valid, the loop held the compile-once retrace
  budget (<= 1 trace), and the perturbed static was named in the
  retrace-cause table — the CI gate (lint.yml), the perf/3 smoke-gate
  precedent.
- ``steploop``: the step-loop flight deck (obs.steploop) — run the
  compile-once fused serving loop with the ``FLASHINFER_TPU_STEPLOOP``
  gate on, write the unified trace with the host/device step lanes
  merged in, and print the ledger summary (host_frac, Amdahl ceiling,
  sub-phase decomposition, drift).  ``--selftest`` exits non-zero on a
  missing device lane, any negative gap (clock-base skew), host time
  the named sub-phases did not attribute, or a ledger decomposition
  that does not sum to the measured loop wall within 5% — the CI gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _workload() -> None:
    """A tier-1-sized pass over the instrumented surface: stateless
    decorated ops plus one full plan/run lifecycle per batch wrapper
    family (small shapes; runs in seconds on CPU)."""
    from flashinfer_tpu.env import apply_platform_from_env

    apply_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    import flashinfer_tpu as fi

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (4, 256), jnp.float32)
    fi.rmsnorm(x, jnp.ones((256,), jnp.float32))
    fi.silu_and_mul(jax.random.normal(key, (4, 512), jnp.float32))
    probs = jax.nn.softmax(jax.random.normal(key, (2, 64), jnp.float32))
    fi.sampling_from_probs(probs, key)

    T, HQ, HKV, D = 8, 4, 2, 64
    q = jax.random.normal(key, (T, HQ, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), (T, HKV, D),
                          jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (T, HKV, D),
                          jnp.bfloat16)
    fi.single_prefill_with_kv_cache(q, k, v, causal=True)

    # decode wrapper lifecycle: plan, re-plan (counted), run
    bs, PS, ppr = 2, 4, 2
    npages = bs * ppr
    kc = jax.random.normal(key, (npages, PS, HKV, D), jnp.bfloat16)
    vc = jax.random.normal(jax.random.fold_in(key, 3),
                           (npages, PS, HKV, D), jnp.bfloat16)
    indptr = np.arange(bs + 1, dtype=np.int32) * ppr
    indices = np.arange(npages, dtype=np.int32)
    last = np.full((bs,), PS, np.int32)
    w = fi.BatchDecodeWithPagedKVCacheWrapper(kv_layout="NHD")
    w.plan(indptr, indices, last, HQ, HKV, D, PS)
    w.plan(indptr, indices, last, HQ, HKV, D, PS)  # re-plan
    qd = jax.random.normal(jax.random.fold_in(key, 4), (bs, HQ, D),
                           jnp.bfloat16)
    w.run(qd, (kc, vc))

    # paged-prefill wrapper lifecycle (the gather path off-TPU)
    wp = fi.BatchPrefillWithPagedKVCacheWrapper(kv_layout="NHD")
    wp.plan(np.arange(bs + 1, dtype=np.int32) * 2, indptr, indices, last,
            HQ, HKV, D, PS, causal=True)
    qp = jax.random.normal(jax.random.fold_in(key, 5), (bs * 2, HQ, D),
                           jnp.bfloat16)
    wp.run(qp, (kc, vc))


def _serving_workload(steps: int, perturb: bool) -> dict:
    """A tiny compile-once fused serving loop (tiny Llama, CPU-safe)
    with the request lifecycle metered per batch lane: begin ->
    prefill chunk -> ``steps`` fused decode steps -> finish.  With
    ``perturb``, one extra run afterwards moves EXACTLY ONE run-state
    static (the carried logits dtype) so the retrace-cause attribution
    has a known answer.  Returns the selftest facts."""
    from flashinfer_tpu.env import apply_platform_from_env

    apply_platform_from_env()
    import jax
    import jax.numpy as jnp

    from flashinfer_tpu import obs
    from flashinfer_tpu.models import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve import SamplingConfig, ServingStep

    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    B, PS, PPR = 2, 8, 4
    npages = B * PPR

    def mk_caches():
        return [
            (jnp.zeros((npages, cfg.num_kv_heads, PS, cfg.head_dim),
                       cfg.dtype),
             jnp.zeros((npages, cfg.num_kv_heads, PS, cfg.head_dim),
                       cfg.dtype))
            for _ in range(cfg.num_layers)
        ]

    def mk_pt():
        return jnp.arange(npages, dtype=jnp.int32).reshape(B, PPR)

    prompt_lens = [3, 5]
    rids = [f"req{b}" for b in range(B)]
    for rid in rids:
        obs.request_begin(rid)

    step = ServingStep()
    with obs.span("serving.plan", cat="plan"):
        step.plan(cfg, page_table=mk_pt(),
                  kv_lens=jnp.asarray(prompt_lens, jnp.int32),
                  sampling=SamplingConfig(temperature=0.8, top_k=40,
                                          top_p=0.95), use_pallas=False)
    # stand-in prefill: seed each lane's handoff logits (the real
    # prefill flow is examples/generate.py's; the lifecycle shape —
    # queue window closed by the first chunk — is identical)
    with obs.span("serving.prefill", cat="prefill"):
        logits = jax.random.normal(jax.random.PRNGKey(1),
                                   (B, cfg.vocab_size), jnp.float32)
        for rid, n in zip(rids, prompt_lens):
            obs.prefill_chunk(rid, n)
    state = step.make_state(mk_caches(), mk_pt(),
                            jnp.asarray(prompt_lens, jnp.int32), logits,
                            jax.random.PRNGKey(2))
    import time as _time

    loop_t0 = _time.perf_counter()
    for _ in range(int(steps)):
        tokens, state = step.run(params, state)
        for rid in rids:
            obs.decode_step(rid)
    loop_wall_s = _time.perf_counter() - loop_t0
    summaries = [obs.request_finish(rid) for rid in rids]
    traces_loop = step.num_traces

    cause_keys = []
    if perturb:
        # the deliberate perturbation: ONE static moves (logits dtype
        # f32 -> bf16); the attribution must name exactly "logits"
        bad = (jax.random.normal(jax.random.PRNGKey(3),
                                 (B, cfg.vocab_size), jnp.bfloat16),
               mk_caches(), mk_pt(),
               jnp.asarray(prompt_lens, jnp.int32),
               jax.random.PRNGKey(4))
        step.run(params, bad)
        from flashinfer_tpu.obs import spans as _spans

        cause_keys = [r["key"] for r in
                      _spans.top_retrace_causes(obs.snapshot())
                      if r["wrapper"] == "ServingStep"]
    return {
        "num_traces_loop": traces_loop,
        "steps": int(steps),
        "loop_wall_s": loop_wall_s,
        "cause_keys": cause_keys,
        "requests": [s for s in summaries if s],
    }


def _engine_workload(num_requests: int,
                     backend: str = "reference") -> dict:
    """A short Zipf-skewed continuous-batching run through the serving
    engine (tiny Llama, CPU-safe) with the request lifecycle metered —
    the ``obs trace --engine`` selftest workload.  Returns the facts
    the selftest gates on: total traces vs the 9-step retrace budget,
    the measured prefix-cache hit rate (must be non-zero under a Zipf
    prompt mix, or the trie is dead), and the served tokens — the
    selftest replays the SAME seeded workload on both attention
    backends and fails on any token divergence (the kernel tier's
    parity gate)."""
    from flashinfer_tpu.env import apply_platform_from_env

    apply_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu import obs
    from flashinfer_tpu.models import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve import (EngineConfig, EngineRequest,
                                      SamplingConfig, ServingEngine)

    snap0 = obs.snapshot()

    def _hits(snap):
        return (sum(snap["counters"].get(
                    "engine.prefix_hit_tokens", {}).values()),
                sum(snap["counters"].get(
                    "engine.prefix_miss_tokens", {}).values()))

    h0, m0 = _hits(snap0)
    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    eng = ServingEngine(cfg, params, EngineConfig(
        num_pages=96, page_size=8, max_batch=4,
        prefill_budget_tokens=24, max_seq_tokens=64,
        sampling=SamplingConfig(temperature=0.8, top_k=20),
        attention_backend=backend))
    rng = np.random.default_rng(0)
    prefixes = [[int(t) for t in rng.integers(1, cfg.vocab_size, 17)]
                for _ in range(4)]
    zipf = np.minimum(rng.zipf(1.5, num_requests) - 1, len(prefixes) - 1)
    with obs.span("engine.workload", cat="request",
                  backend=backend):
        for i in range(num_requests):
            prompt = prefixes[int(zipf[i])] + [
                int(t) for t in rng.integers(1, cfg.vocab_size, 4)]
            eng.submit(EngineRequest(f"req{i}", prompt,
                                     max_new_tokens=3))
        results = eng.run()
    h1, m1 = _hits(obs.snapshot())
    hits, misses = h1 - h0, m1 - m0
    return {
        "num_traces": eng.num_traces,
        "rungs": len(eng._rung_traced),
        "requests": num_requests,
        "prefix_hit_rate": hits / max(hits + misses, 1),
        "flops_avoided": eng.flops_avoided,
        "results": results,
    }


def _engine_spill_workload(spill: bool) -> dict:
    """The ``obs trace --engine --spill`` workload: low-priority
    requests mid-decode are preempted by later high-priority arrivals
    under a pool sized to force it.  ``spill=True`` runs the tiered
    engine (kv_offload=host, spill_policy=spill — every resume must
    RESTORE); ``spill=False`` runs the never-preempted oracle (big
    pool, no tier) over the SAME seeded requests, so the selftest can
    pin token equality.  Returns the tier facts the gates read."""
    from flashinfer_tpu.env import apply_platform_from_env

    apply_platform_from_env()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from flashinfer_tpu.models import LlamaConfig, init_llama_params
    from flashinfer_tpu.serve import (EngineConfig, EngineRequest,
                                      SamplingConfig, ServingEngine)

    cfg = LlamaConfig.tiny(num_layers=2, dtype=jnp.float32)
    params = init_llama_params(jax.random.PRNGKey(0), cfg)
    tier = (dict(num_pages=9, kv_offload="host", spill_policy="spill",
                 host_gib=1) if spill else dict(num_pages=64))
    eng = ServingEngine(cfg, params, EngineConfig(
        page_size=8, max_batch=2, prefill_budget_tokens=16,
        max_seq_tokens=48,
        sampling=SamplingConfig(temperature=0.8, top_k=20), **tier))
    rng = np.random.default_rng(3)
    lo = [[int(t) for t in rng.integers(1, cfg.vocab_size, 20)]
          for _ in range(2)]
    hi = [[int(t) for t in rng.integers(1, cfg.vocab_size, 20)]
          for _ in range(2)]
    for i, p in enumerate(lo):
        eng.submit(EngineRequest(f"lo{i}", p, max_new_tokens=6,
                                 priority=5))
    for _ in range(5):
        eng.step()  # low-priority lanes are mid-decode
    for i, p in enumerate(hi):
        eng.submit(EngineRequest(f"hi{i}", p, max_new_tokens=4,
                                 priority=0))
    results = eng.run()
    return {
        "num_traces": eng.num_traces,
        "rungs": len(eng._rung_traced),
        "results": results,
        **{k: eng.kv_tier_stats[k]
           for k in ("spills", "restores", "recomputes")},
    }


def cmd_trace(args) -> int:
    os.environ["FLASHINFER_TPU_SPANS"] = "1"
    os.environ["FLASHINFER_TPU_METRICS"] = "1"
    from flashinfer_tpu import obs, profiler
    from flashinfer_tpu.obs import export, spans

    profiler.start_timeline()
    kfacts = None
    if args.engine and args.spill:
        facts = _engine_spill_workload(spill=True)
        kfacts = None
        oracle = _engine_spill_workload(spill=False)
    elif args.engine:
        facts = _engine_workload(args.requests)
        # the kernel attention tier over the SAME seeded workload: the
        # selftest gates BOTH backends on the retrace budget and pins
        # cross-tier token parity (docs/serving.md backend matrix)
        kfacts = _engine_workload(args.requests, backend="kernel")
    else:
        facts = _serving_workload(args.steps, perturb=not args.no_perturb)
    events = profiler.stop_timeline()
    snap = obs.snapshot()
    trace = export.write_unified_trace(args.out, snap, events,
                                       spans.drain())
    problems = export.validate_chrome_trace(trace,
                                            require_lifecycle=True)
    if args.engine and args.spill:
        # the TIERED-KV gates (docs/serving.md §"Tiered KV &
        # disaggregation"): forced preemption under spill_policy=spill
        # must actually SPILL (a zero count means the tier silently
        # fell back), every resume must RESTORE (zero recomputes),
        # restored tokens must equal the never-preempted oracle's
        # bitwise, and the rung ladder must hold
        if facts["spills"] <= 0:
            problems.append(
                "silent spill: preemption was forced under "
                "spill_policy=spill but zero page runs reached the "
                "host tier")
        if facts["restores"] <= 0:
            problems.append(
                "spilled requests resumed without a restore — the "
                "staged-entry admission path is dead")
        if facts["recomputes"] > 0:
            problems.append(
                f"{facts['recomputes']} resume(s) RECOMPUTED under "
                "spill_policy=spill — the host tier lost entries it "
                "had capacity for")
        if facts["results"] != oracle["results"]:
            bad = [rid for rid in oracle["results"]
                   if facts["results"].get(rid) != oracle["results"][rid]]
            problems.append(
                f"spill-restore token mismatch on {len(bad)} "
                f"request(s) (first: {bad[:3]}) vs the never-preempted "
                "oracle — the restore path is not bit-exact")
        if facts["num_traces"] > 9:
            problems.append(
                f"spill-mode retrace budget: {facts['num_traces']} "
                "traces (budget: 9)")
        if facts["num_traces"] > facts["rungs"]:
            problems.append(
                f"spill mode retraced: {facts['num_traces']} traces "
                f"for {facts['rungs']} rungs (compile-once broke)")
    elif args.engine:
        # the ENGINE retrace budget: the whole Zipf run must stay on
        # the pre-compiled rung ladder (<= 9 traces, the same budget
        # the fused-step loop pins), and the prefix cache must be LIVE
        # (a zero hit rate under a Zipf prompt mix means the trie or
        # the block-sharing path silently broke)
        if facts["num_traces"] > 9:
            problems.append(
                f"engine retrace budget: {facts['num_traces']} traces "
                f"across {facts['requests']} requests (budget: 9)")
        if facts["num_traces"] > facts["rungs"]:
            problems.append(
                f"engine retraced: {facts['num_traces']} traces for "
                f"{facts['rungs']} rungs (compile-once broke)")
        if facts["prefix_hit_rate"] <= 0.0:
            problems.append(
                "prefix-cache hit rate is ZERO under a Zipf-shared "
                "prompt mix — the prefix trie is not taking hits")
        # the kernel tier: same budget, plus token parity vs the
        # reference tier (everything is seeded, so agreement is exact)
        if kfacts["num_traces"] > 9:
            problems.append(
                f"kernel-tier retrace budget: {kfacts['num_traces']} "
                f"traces across {kfacts['requests']} requests "
                "(budget: 9)")
        if kfacts["num_traces"] > kfacts["rungs"]:
            problems.append(
                f"kernel tier retraced: {kfacts['num_traces']} traces "
                f"for {kfacts['rungs']} rungs (compile-once broke)")
        if kfacts["results"] != facts["results"]:
            bad = [rid for rid in facts["results"]
                   if kfacts["results"].get(rid) != facts["results"][rid]]
            problems.append(
                f"kernel-vs-reference token mismatch on {len(bad)} "
                f"request(s) (first: {bad[:3]}) — the work-unit "
                "lowering diverged from the oracle tier")
    else:
        # the compile-once retrace budget over the fused serving loop
        # (test_serve_step's 9-step pin, now CI-gated with attribution)
        if facts["num_traces_loop"] > 1:
            problems.append(
                f"retrace budget: {facts['num_traces_loop']} traces "
                f"across {facts['steps']} fused steps (budget: 1)")
        if not args.no_perturb and facts["cause_keys"] != ["logits"]:
            problems.append(
                "deliberate logits-dtype perturb attributed to "
                f"{facts['cause_keys']!r}, expected ['logits']")

    ls = obs.lifecycle_snapshot()

    def pcts(name):
        h = ls.get(name)
        if not h:
            return "n/a"
        return (f"p50={h.get('p50', 0):.0f} p99={h.get('p99', 0):.0f} "
                f"(n={h['count']})")

    print(f"# unified trace -> {args.out} "
          f"({len(trace['traceEvents'])} events)", file=sys.stderr)
    print(f"# lifecycle: ttft_us {pcts('lifecycle.ttft_us')} | "
          f"tpot_us {pcts('lifecycle.tpot_us')} | "
          f"queue_us {pcts('lifecycle.queue_us')}", file=sys.stderr)
    causes = spans.top_retrace_causes(snap)
    if causes:
        print("# top retrace causes:", file=sys.stderr)
        for r in causes:
            print(f"#   {r['count']:4d}  {r['wrapper']}.{r['key']}",
                  file=sys.stderr)
    summary = {
        "out": args.out,
        "events": len(trace["traceEvents"]),
        "retrace_causes": causes,
        "problems": problems,
        **{k: v for k, v in facts.items()
           if k not in ("requests", "results")},
    }
    if kfacts is not None:
        summary["kernel_backend"] = {
            k: v for k, v in kfacts.items()
            if k not in ("requests", "results")}
        summary["kernel_backend"]["tokens_equal"] = \
            kfacts["results"] == facts["results"]
    print(json.dumps(summary, indent=1, sort_keys=True))
    if problems and args.selftest:
        for p in problems:
            print(f"error: {p}", file=sys.stderr)
        return 2
    return 0


def cmd_steploop(args) -> int:
    """Step-loop flight deck selftest (ISSUE 17): drive the compile-once
    fused serving loop with the steploop gate ON, merge the host/device
    step lanes into the unified trace, and gate on the ledger's internal
    consistency — every step must carry a device window (the completion
    probe ran), no gap may be negative (both edges share one
    perf_counter base, so a negative gap means the clock math broke),
    the named sub-phases must attribute the host window, and the
    gap/device decomposition must sum to the EXTERNALLY measured loop
    wall within 5% (dropped records or clock skew cannot hide)."""
    os.environ["FLASHINFER_TPU_STEPLOOP"] = "1"
    os.environ["FLASHINFER_TPU_SPANS"] = "1"
    os.environ["FLASHINFER_TPU_METRICS"] = "1"
    from flashinfer_tpu import obs, profiler
    from flashinfer_tpu.obs import export, spans, steploop

    steploop.reset()
    profiler.start_timeline()
    facts = _serving_workload(args.steps, perturb=False)
    events = profiler.stop_timeline()
    snap = obs.snapshot()
    recs = steploop.ledger().records()
    trace = export.write_unified_trace(
        args.out, snap, events, spans.drain(),
        extra_events=steploop.trace_events(recs))
    problems = export.validate_chrome_trace(trace)
    s = steploop.summarize(recs)

    if s["steps"] < int(args.steps):
        problems.append(
            f"ledger recorded {s['steps']} steps across a "
            f"{args.steps}-step loop — the ServingStep wiring is dead")
    if s["missing_device_lane"]:
        problems.append(
            f"{s['missing_device_lane']} step(s) missing the device "
            "window — the completion probe did not run")
    if s["negative_gaps"]:
        problems.append(
            f"{s['negative_gaps']} negative gap(s) — dispatch/done "
            "stamps disagree on the clock base")
    if s["unattributed_frac"] is not None \
            and s["unattributed_frac"] > 0.10:
        problems.append(
            f"{s['unattributed_frac']:.1%} of host time unattributed "
            "(> 10%) — a call site skipped a sub-phase mark")
    if not any(ev.get("cat") == "steploop"
               and ev.get("tid") == steploop.TRACE_TID_DEVICE
               for ev in trace["traceEvents"]):
        problems.append("no steploop device lane in the unified trace")
    # the wall check: host(first) + device + gap covers begin(first) ->
    # done(last) by construction, so it must match the externally timed
    # loop wall — a mismatch means records were lost or clocks skewed
    comp_us = 0.0
    for r in recs:
        if r["idle"]:
            continue
        comp_us += r["host_us"] if r["gap_us"] is None \
            else max(r["gap_us"], 0.0)
        comp_us += r["device_us"] or 0.0
    wall = facts["loop_wall_s"]
    if wall > 0 and abs(comp_us / 1e6 - wall) / wall > 0.05:
        problems.append(
            f"ledger decomposition {comp_us / 1e6:.4f}s vs measured "
            f"loop wall {wall:.4f}s — more than 5% apart")

    print(f"# unified trace -> {args.out} "
          f"({len(trace['traceEvents'])} events)", file=sys.stderr)
    summary = {
        "out": args.out,
        "events": len(trace["traceEvents"]),
        "loop_wall_s": wall,
        "decomposed_s": comp_us / 1e6,
        "problems": problems,
        "steploop": s,
    }
    print(json.dumps(summary, indent=1, sort_keys=True))
    if problems and args.selftest:
        for p in problems:
            print(f"error: {p}", file=sys.stderr)
        return 2
    return 0


def cmd_report(args) -> int:
    from flashinfer_tpu import obs, profiler
    from flashinfer_tpu.obs import export

    os.environ["FLASHINFER_TPU_METRICS"] = "1"
    events = None
    if not args.no_workload:
        if args.chrome_trace:
            profiler.start_timeline()
        _workload()
        if args.chrome_trace:
            events = profiler.stop_timeline()
    snap = obs.snapshot()
    if args.chrome_trace:
        export.write_chrome_trace(args.chrome_trace, snap, events)
        print(f"# chrome trace -> {args.chrome_trace}", file=sys.stderr)
    if args.format == "prom":
        sys.stdout.write(export.to_prometheus(snap))
    else:
        print(export.to_json(snap))
    return 0


def cmd_doctor(args) -> int:
    """Health report: environment, devices, backend resolution, caches,
    quarantine — everything a bug report / perf triage needs up front."""
    from flashinfer_tpu.collect_env import collect_env

    report = {"env": collect_env()}

    flags = {}
    for name in ("FLASHINFER_TPU_METRICS", "FLASHINFER_TPU_SPANS",
                 "FLASHINFER_TPU_SPANS_CAP", "FLASHINFER_TPU_STEPLOOP",
                 "FLASHINFER_TPU_STEPLOOP_CAP", "FLASHINFER_TPU_LOGLEVEL",
                 "FLASHINFER_TPU_BACKEND", "FLASHINFER_TPU_INTERPRET",
                 "FLASHINFER_TPU_TIMELINE_SYNC", "FLASHINFER_TPU_TRACE_DUMP",
                 "FLASHINFER_TPU_TRACE_APPLY", "FLASHINFER_TPU_CACHE_DIR",
                 "FLASHINFER_TPU_DUMP_DIR"):
        flags[name] = os.environ.get(name, "<unset>")
    report["flags"] = flags

    try:
        from flashinfer_tpu.utils import is_tpu, resolve_backend

        report["backend_resolution"] = {
            "is_tpu": bool(is_tpu()),
            "single_decode_auto": resolve_backend("auto", "single_decode"),
        }
    except Exception as e:  # device init can fail off-accelerator
        report["backend_resolution"] = f"<unavailable: {type(e).__name__}>"

    from flashinfer_tpu import compile_guard

    q = compile_guard._load_qlist()
    report["quarantine"] = {
        "entries": len(q),
        "ops": sorted({i.get("op", "?") for i in q.values()}),
    }

    # hardware bring-up session state (ISSUE 20): journal length, rung
    # outcomes, wedge quarantine, and which tuning sections still ship
    # seed tactics — the at-a-glance answer to "where did the chip
    # session get to"
    try:
        from flashinfer_tpu.obs import bringup

        report["bringup"] = bringup.doctor_summary()
    except Exception as e:
        report["bringup"] = f"<unavailable: {type(e).__name__}>"
    try:
        from flashinfer_tpu.autotuner import AutoTuner

        t = AutoTuner.get()
        t._load()
        report["tuner"] = {"cache": str(t._cache_path()),
                          "entries": len(t._cache)}
    except Exception as e:
        report["tuner"] = f"<unavailable: {type(e).__name__}>"

    from flashinfer_tpu import obs, profiler

    snap = obs.snapshot()
    report["registry"] = {
        "metrics_enabled": obs.metrics_enabled(),
        "counters": len(snap["counters"]),
        "gauges": len(snap["gauges"]),
        "histograms": len(snap["histograms"]),
        "timeline_active": profiler.timeline_active(),
    }

    # flight recorder (ISSUE 10): gate + ring state, serving-op span
    # coverage (every catalog.SERVING_OPS op must declare its span
    # category in spans.SPAN_CATEGORIES — the L005 ships-observed rule
    # extended to the span layer, so the unspanned list must stay
    # empty), and the ranked top-retrace-causes table from this
    # process's plan.retrace_cause cells
    try:
        from flashinfer_tpu.obs import spans as _spans
        from flashinfer_tpu.obs.catalog import SERVING_OPS

        rec = _spans.get_recorder()
        # delegated to the L013 registry_coverage pass — the ONE
        # implementation of the span-coverage rule (ISSUE 15); same
        # sorted-list output as the pre-delegation inline set
        # difference, byte for byte.  The fallback mirrors the
        # delegated implementation so the spans section stays alive
        # when the ANALYSIS package is the broken part of the tree
        # (importing the pass runs the full package init) — the pass
        # remains the enforcement point.
        try:
            from flashinfer_tpu.analysis import registry_coverage as _rc
            unspanned = _rc.unspanned_serving_ops()
        except Exception:
            unspanned = sorted(SERVING_OPS - set(_spans.SPAN_CATEGORIES))
        report["spans"] = {
            "enabled": obs.spans_enabled(),
            "capacity": rec.capacity,
            "recorded": rec.total,
            "dropped": rec.dropped(),
            "serving_ops": sorted(SERVING_OPS),
            "unspanned_serving_ops": unspanned,
        }
        report["retrace_causes"] = _spans.top_retrace_causes(snap)
    except Exception as e:  # doctor must never crash on a broken tree
        report["spans"] = f"<unavailable: {type(e).__name__}>"

    # static-analysis hygiene: a reasonless `# graft-lint: ok` /
    # `# wedge-lint: ok` is an unreviewable waiver (L000/W000 — the
    # analyzer fails on them, they can never be baselined); a non-zero
    # count here means the tree cannot pass lint
    try:
        import flashinfer_tpu as _fi
        from flashinfer_tpu.analysis import core as _acore

        pkg = os.path.dirname(os.path.abspath(_fi.__file__))
        total = reasonless = 0
        for path in _acore.iter_python_files([pkg]):
            sf = _acore.load_file(path)
            for table in (sf.suppressions, sf.wedge_suppressions):
                total += len(table)
                reasonless += sum(
                    1 for reason in table.values() if not reason)
        report["lint"] = {
            "suppressions": total,
            "reasonless_suppressions": reasonless,
        }

        # L014/L015 kernel coverage: a silently-skipped kernel body is
        # an unanalyzed DMA pipeline — surface analyzed-vs-skipped here
        # so the skip count is visible without reading analyzer output
        # (docs/static_analysis.md §"L014 hazard classes")
        from flashinfer_tpu.analysis import dma_race as _dma
        from flashinfer_tpu.analysis import mosaic_lowering as _mosaic

        proj = _acore.Project.from_paths([pkg])
        d14 = _dma.stats(proj)
        d15 = _mosaic.stats(proj)
        report["lint"]["l014_kernels"] = {
            "analyzed": d14["kernels_analyzed"],
            "skipped": d14["kernels_skipped"],
            "no_dma": d14["kernels_no_dma"],
            "sites_unresolved": d14["sites_unresolved"],
        }
        report["lint"]["l015_kernels"] = {
            "linted": d15["kernels_linted"],
            "sites_unresolved": d15["sites_unresolved"],
            "findings_by_rule": dict(d15["findings_by_rule"]),
        }

        # L016/L017 cost-parity coverage: a skipped family is a cost
        # model nothing checks, an unpriced knob a choice nothing
        # proves — surface checked-vs-skipped and the worst observed
        # deviation so drift headroom is visible at a glance
        from flashinfer_tpu.analysis import chooser_coverage as _chz
        from flashinfer_tpu.analysis import cost_parity as _cpar

        d16 = _cpar.stats(proj)
        report["lint"]["l016_kernels"] = {
            "families_checked": d16["families_checked"],
            "families_skipped": d16["families_skipped"],
            "max_deviation": d16["max_deviation"],
            "skip_reasons": dict(d16["skip_reasons"]),
        }
        d17 = _chz.stats(proj)
        report["lint"]["l017"] = {
            "choosers": d17["choosers"],
            "waivers": d17["waivers"],
            "bindings": d17["bindings"],
            "findings": d17["findings"],
        }
    except Exception as e:  # doctor must never crash on a broken tree
        report["lint"] = f"<unavailable: {type(e).__name__}>"

    # continuous-batching engine (serve/engine.py): pool occupancy,
    # prefix-cache hit rate, eviction/preemption pressure — read from
    # this process's registry cells (zeros in a fresh process; the
    # serving process's doctor shows the live numbers)
    try:
        counters = snap.get("counters", {})
        gauges = snap.get("gauges", {})

        def cell(name):
            return sum(counters.get(name, {}).values())

        def gauge(name):
            cells = gauges.get(name, {})
            return cells.get("") if cells else None

        hits = cell("engine.prefix_hit_tokens")
        misses = cell("engine.prefix_miss_tokens")
        report["engine"] = {
            "requests": cell("engine.requests"),
            "finished": cell("engine.finished"),
            "steps": cell("engine.steps"),
            "prefix_hit_tokens": hits,
            "prefix_miss_tokens": misses,
            "prefix_hit_rate": (hits / (hits + misses)
                                if hits + misses else None),
            "evictions": cell("engine.evictions"),
            "preemptions": cell("engine.preemptions"),
            "pool_pages_in_use": gauge("engine.pool_pages_in_use"),
            "pool_pages_free": gauge("engine.pool_pages_free"),
        }
        # tiered KV (serve/kv_tier.py): per-tier occupancy + the
        # spill/restore/migration traffic and resume-miss attribution
        # — zeros in a fresh process, live numbers in the serving one
        restores = cell("engine.kv_tier.restores")
        recomputes = cell("engine.kv_tier.recomputes")
        report["kv_tier"] = {
            "spills": cell("engine.kv_tier.spills"),
            "spill_bytes": cell("engine.kv_tier.spill_bytes"),
            "restores": restores,
            "restore_bytes": cell("engine.kv_tier.restore_bytes"),
            "migrations": cell("engine.kv_tier.migrations"),
            "migrate_bytes": cell("engine.kv_tier.migrate_bytes"),
            "recomputes": recomputes,
            "restore_rate": (restores / (restores + recomputes)
                             if restores + recomputes else None),
            "host_evictions": cell("engine.kv_tier.host_evictions"),
            "host_pages": gauge("engine.kv_tier.host_pages"),
            "host_bytes": gauge("engine.kv_tier.host_bytes"),
        }
    except Exception as e:  # doctor must never crash on a broken tree
        report["engine"] = f"<unavailable: {type(e).__name__}>"
        report["kv_tier"] = f"<unavailable: {type(e).__name__}>"

    # step-loop flight deck (obs.steploop): gate state plus the live
    # ledger summary — looked up via sys.modules, never imported, so
    # doctor itself cannot defeat the zero-overhead default (the same
    # rule roofline's live join follows); zeros/absent in a fresh
    # process, live host_frac / worst sub-phase / drift tails in the
    # serving one
    try:
        report["host_loop"] = {"enabled": obs.steploop_enabled()}
        _sl = sys.modules.get("flashinfer_tpu.obs.steploop")
        if _sl is not None:
            s = _sl.summarize()
            report["host_loop"].update(
                steps=s["steps"], idle_ticks=s["idle_ticks"],
                dropped=s["dropped"], surfaces=s["surfaces"],
                host_frac=s["host_frac"],
                overlap_efficiency=s["overlap_efficiency"],
                amdahl_ceiling=s["amdahl_ceiling"],
                worst_phase=s["worst_phase"],
                phases_us=s["phases"],
                unattributed_frac=s["unattributed_frac"],
                negative_gaps=s["negative_gaps"],
                missing_device_lane=s["missing_device_lane"],
                drift=s["drift"])
    except Exception as e:  # doctor must never crash on a broken tree
        report["host_loop"] = f"<unavailable: {type(e).__name__}>"

    # cost-model coverage (mirrors analysis L005's obs-coverage idea):
    # a decorated public op with no obs.costmodel family can bench but
    # never roofline-attribute — new ops must not silently ship
    # unattributed, so the uncovered list must stay empty
    try:
        from flashinfer_tpu.obs import costmodel, hwspec

        report["costmodel"] = {
            "api_ops_covered": len(costmodel.API_OP_COSTS),
            "uncovered_api_ops": list(costmodel.uncovered_api_ops()),
            "chip": hwspec.detect_chip(),
        }
    except Exception as e:
        report["costmodel"] = f"<unavailable: {type(e).__name__}>"
    print(json.dumps(report, indent=1, sort_keys=True))
    return 0


def cmd_bringup(args) -> int:
    """Hardware graduation session harness (ISSUE 20) — flags are owned
    by :mod:`flashinfer_tpu.obs.bringup` (``--selftest``, ``--resume``,
    ``--graduate``, ``--list``, ...)."""
    from flashinfer_tpu.obs import bringup

    return bringup.main(list(args.rest))


def cmd_perf(args) -> int:
    """Roofline doctor over banked bench rows — the VERDICT analysis,
    reproduced mechanically (no jax / no device needed)."""
    from flashinfer_tpu.obs import bench_audit, roofline

    path = args.banked
    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "BENCH_BANKED.md")
    try:
        rows = bench_audit.load_banked_history(path, strict=True)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not rows:
        print(f"error: no bench rows found in {path}", file=sys.stderr)
        return 2
    report = roofline.build_perf_report(rows, chip=args.chip)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        sys.stdout.write(roofline.render_perf_report(report))
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "bringup":
        # delegated wholesale: bringup owns its flags (--selftest,
        # --resume, --graduate, ...) and argparse REMAINDER cannot
        # forward leading options through a subparser
        from flashinfer_tpu.obs import bringup

        return bringup.main(argv[1:])
    p = argparse.ArgumentParser(prog="python -m flashinfer_tpu.obs")
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("report", help="metrics snapshot (runs a small "
                                       "built-in workload by default)")
    sp.add_argument("--format", choices=["json", "prom"], default="json")
    sp.add_argument("--no-workload", action="store_true",
                    help="report this process's registry as-is")
    sp.add_argument("--chrome-trace", metavar="PATH", default=None,
                    help="also write the merged op-timeline chrome trace")
    sp.set_defaults(fn=cmd_report)
    sp = sub.add_parser("doctor", help="device/env/backend health report")
    sp.set_defaults(fn=cmd_doctor)
    sp = sub.add_parser("perf", help="roofline attribution report over "
                                     "banked bench rows")
    sp.add_argument("--banked", metavar="PATH", default=None,
                    help="BENCH_BANKED.md-style history "
                         "(default: the repo's BENCH_BANKED.md)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable report (schema "
                         "flashinfer_tpu.obs.perf/3: + serving_ici / "
                         "scaling_prediction ICI fields + the "
                         "serving_disagg kv_migrate join)")
    sp.add_argument("--chip", default=None,
                    help="default chip for rows that name none "
                         "(default: v5e, the banked history's chip)")
    sp.set_defaults(fn=cmd_perf)
    sp = sub.add_parser("trace", help="flight-recorder export: unified "
                                      "chrome trace of a metered fused "
                                      "serving loop")
    sp.add_argument("--out", metavar="PATH",
                    default="/tmp/flashinfer_tpu_unified_trace.json",
                    help="unified chrome-trace output path")
    sp.add_argument("--steps", type=int, default=9,
                    help="fused serving steps (retrace budget: <= 1 "
                         "trace across all of them)")
    sp.add_argument("--no-perturb", action="store_true",
                    help="skip the deliberate one-static perturbation "
                         "(and its attribution assert)")
    sp.add_argument("--engine", action="store_true",
                    help="run the continuous-batching ENGINE workload "
                         "instead of the fused-step loop: a short "
                         "Zipf-shared-prefix request mix through "
                         "serve/engine.py; --selftest then fails on a "
                         "retrace-budget breach (> 9 traces or any "
                         "trace beyond the rung ladder) or a ZERO "
                         "prefix-cache hit rate")
    sp.add_argument("--requests", type=int, default=24,
                    help="engine-mode request count (Zipf-skewed "
                         "shared prefixes)")
    sp.add_argument("--spill", action="store_true",
                    help="with --engine: run the TIERED-KV workload "
                         "instead — forced preemption under "
                         "spill_policy=spill; --selftest then fails "
                         "on token divergence vs the never-preempted "
                         "oracle, a silent spill (zero spills/"
                         "restores), any recompute fallback, or a "
                         "retrace breach")
    sp.add_argument("--selftest", action="store_true",
                    help="exit non-zero unless the export is "
                         "schema-valid, the retrace budget held, and "
                         "the perturbed static was named (the CI gate)")
    sp.set_defaults(fn=cmd_trace)
    sp = sub.add_parser("steploop",
                        help="step-loop flight deck: host/device "
                             "overlap ledger over the fused serving "
                             "loop (gate forced ON for the run)")
    sp.add_argument("--out", metavar="PATH",
                    default="/tmp/flashinfer_tpu_steploop_trace.json",
                    help="unified chrome-trace output path (host/"
                         "device step lanes merged in)")
    sp.add_argument("--steps", type=int, default=9,
                    help="fused serving steps to ledger")
    sp.add_argument("--selftest", action="store_true",
                    help="exit non-zero on a missing device lane, a "
                         "negative gap, unattributed host time, or a "
                         "decomposition that misses the measured loop "
                         "wall by > 5% (the CI gate)")
    sp.set_defaults(fn=cmd_steploop)
    sp = sub.add_parser(
        "bringup",
        help="hardware graduation session: mosaic-risk smoke ladder -> "
             "banked bench -> emit-config sweeps -> provenance "
             "graduation, journaled and resumable (ISSUE 20); flags "
             "are owned by obs.bringup (--selftest, --resume, "
             "--graduate, --list, ...)",
        add_help=False)
    sp.set_defaults(fn=cmd_bringup, rest=[])
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
