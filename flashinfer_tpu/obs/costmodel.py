"""Analytic FLOP / HBM-byte cost model per op family.

The counting half of roofline attribution (Williams et al., CACM 2009):
each public op family gets one formula for total FLOPs and HBM bytes
moved (read/write split), computed from the *plan objects the library
already builds* — fused-prefill work-unit stats report both *launched*
work (what the MXU actually executed, padding included) and *effective*
work (the attended tokens a perfect packing would compute).
:mod:`~flashinfer_tpu.obs.roofline` joins a :class:`Cost` with a
measured wall time and a :class:`~flashinfer_tpu.obs.hwspec.ChipSpec`.

Conventions (pinned by ``tests/test_roofline.py`` against brute-force
tiny-shape counts):

- a multiply-add is 2 FLOPs (matching ``testing.utils.attention_flops``
  and every banked TFLOP/s number);
- bytes are *algorithmic* HBM traffic: every operand read once, every
  output written once, caches at their storage width (quantized-KV
  cost halves/quarters with the byte width) — re-fetch inefficiency is
  what the measured-vs-roofline gap exposes, so it must not be modeled
  away here;
- elementwise/sampling ops count 2 FLOPs/element so intensity stays
  honest-tiny (they are bandwidth attributions, not MFU claims).

Import contract: pure Python, no jax / no env reads — ``obs perf``
runs in CI lint processes, and the zero-overhead test pins that merely
importing ``flashinfer_tpu`` and running ops never loads this module.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Cost:
    """Launched (+ optionally effective) work for one op invocation."""

    flops: float
    bytes_read: float
    bytes_written: float
    # effective (useful) work after padding/pruning waste; None == all
    # launched work was useful
    flops_effective: Optional[float] = None
    dtype: str = "bf16"  # compute dtype -> which MXU peak applies
    op: str = ""
    # per-chip ICI wire bytes (the collective cost family) — a THIRD
    # roofline dimension alongside HBM bytes and FLOPs, priced against
    # hwspec.ici_gbps by obs.roofline.  0 for every single-chip op.
    ici_bytes: float = 0.0

    @property
    def bytes_total(self) -> float:
        return self.bytes_read + self.bytes_written

    @property
    def effective_flops(self) -> float:
        return self.flops if self.flops_effective is None \
            else self.flops_effective

    @property
    def intensity(self) -> float:
        """Arithmetic intensity, FLOPs per HBM byte (launched work)."""
        return self.flops / self.bytes_total if self.bytes_total else 0.0

    def __add__(self, other: "Cost") -> "Cost":
        return Cost(
            flops=self.flops + other.flops,
            bytes_read=self.bytes_read + other.bytes_read,
            bytes_written=self.bytes_written + other.bytes_written,
            flops_effective=self.effective_flops + other.effective_flops
            if (self.flops_effective is not None
                or other.flops_effective is not None) else None,
            dtype=self.dtype, op=self.op or other.op,
            ici_bytes=self.ici_bytes + other.ici_bytes,
        )


def attended_tokens(qo_len: int, kv_len: int, causal: bool = False,
                    window_left: int = -1) -> int:
    """Number of attended (q, kv) pairs for one request — THE counted
    term of every attention formula (bottom-right causal alignment,
    matching testing.utils.attention_ref)."""
    total = 0
    off = kv_len - qo_len
    for qi in range(qo_len):
        hi = min(qi + off, kv_len - 1) if causal else kv_len - 1
        lo = max(qi + off - window_left, 0) if window_left >= 0 else 0
        if hi >= lo:
            total += hi - lo + 1
    return total


def _attended_closed(qo_len: int, kv_len: int, causal: bool) -> float:
    # closed form of attended_tokens for window_left=-1 (the bench
    # shapes) — O(1) so stamping a 16-cell sweep costs nothing
    if causal and qo_len > 1:
        return qo_len * (kv_len - qo_len) + (qo_len * (qo_len + 1)) // 2
    return qo_len * kv_len


def attention(qo_len: int, kv_len: int, num_qo_heads: int,
              num_kv_heads: int, head_dim_qk: int,
              head_dim_vo: Optional[int] = None, *, causal: bool = False,
              batch: int = 1, q_bytes: int = 2, kv_bytes: int = 2,
              out_bytes: int = 2, dtype: str = "bf16") -> Cost:
    """Generic (ragged/flash/single/decode) attention: QK^T + PV FLOPs,
    q+k+v read / o written once.  ``kv_bytes`` carries the quantized-KV
    byte width (int8 cache -> 1, fp8 -> 1)."""
    dvo = head_dim_qk if head_dim_vo is None else head_dim_vo
    att = _attended_closed(qo_len, kv_len, causal)
    return Cost(
        flops=2.0 * batch * att * num_qo_heads * (head_dim_qk + dvo),
        bytes_read=float(batch) * (
            qo_len * num_qo_heads * head_dim_qk * q_bytes
            + kv_len * num_kv_heads * (head_dim_qk + dvo) * kv_bytes),
        bytes_written=float(batch) * qo_len * num_qo_heads * dvo
        * out_bytes,
        dtype=dtype, op="attention",
    )


def paged_decode(batch: int, ctx: int, num_qo_heads: int,
                 num_kv_heads: int, head_dim: int, *, kv_bytes: int = 2,
                 q_bytes: int = 2, dtype: str = "bf16") -> Cost:
    """Batched paged-KV decode: one query token per request streams the
    whole cache — the bandwidth-bound headline op."""
    c = attention(1, ctx, num_qo_heads, num_kv_heads, head_dim,
                  causal=False, batch=batch, q_bytes=q_bytes,
                  kv_bytes=kv_bytes, dtype=dtype)
    return dataclasses.replace(c, op="paged_decode")


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def split_chunk_pages(page_size: int, num_kv_heads: int, head_dim: int,
                      itemsize: int = 2) -> int:
    """Pages-per-chunk of the split-KV decode path — MUST equal
    ``ops/paged_decode.split_pages_per_chunk`` (duplicated because this
    module stays jax-free by import contract; equality is pinned by
    tests/test_split_decode.py)."""
    ppc = max(1, min(512 // page_size, 16))
    per_page = 4 * num_kv_heads * page_size * head_dim * itemsize
    return max(1, min(ppc, (8 << 20) // per_page))


def decode_split_breakdown(
    batch: int, ctx: int, num_qo_heads: int, num_kv_heads: int,
    head_dim: int, *, num_splits: int, page_size: int = 16,
    pages_per_chunk: Optional[int] = None, kv_bytes: int = 2,
    q_bytes: int = 2, out_bytes: int = 2, lse_lanes: int = 128,
) -> Dict[str, float]:
    """Traffic/shape breakdown of split-KV decode at one split factor —
    the terms :func:`decode_split` sums and the bench stamp's
    ``merge_bytes`` field.  Mirrors ``build_decode_split_units``
    geometry exactly: per-request span ``per = ceil(pages/S)`` rounded
    up to whole DMA chunks, so sub-chunk splits degenerate into empty
    units (their kernel grid steps still write identity partials, which
    the merge traffic charges).

    Keys: ``kv_bytes`` (cache streamed once — splits are disjoint),
    ``q_bytes`` (one padded-group q-block fetch per unit),
    ``merge_bytes`` (f32 partial out+lse written by the kernel and read
    back by merge_states; 0 at S=1), ``out_bytes`` (merged output),
    ``units_real``/``units_total``, ``max_chunks_per_unit``,
    ``kv_tokens_launched`` (whole-chunk walks incl. the masked tail)."""
    S = int(num_splits)
    ppc = pages_per_chunk if pages_per_chunk else split_chunk_pages(
        page_size, num_kv_heads, head_dim, kv_bytes)
    chunk_tokens = ppc * page_size
    pages = _cdiv(max(ctx, 1), page_size)
    per = _cdiv(_cdiv(max(pages, 1), S), ppc) * ppc
    units_real = 0
    max_chunks = 0
    kv_launched = 0
    for s in range(S):
        start = s * per * page_size
        uk = min(start + per * page_size, ctx) - start
        if uk <= 0:
            continue
        units_real += 1
        c = _cdiv(uk, chunk_tokens)
        max_chunks = max(max_chunks, c)
        kv_launched += c * chunk_tokens
    group = num_qo_heads // max(num_kv_heads, 1)
    gp = _cdiv(max(group, 1), 8) * 8
    partial_elems = (float(batch) * S * num_kv_heads * gp
                     * (head_dim + lse_lanes))
    return {
        "kv_bytes": float(batch) * ctx * num_kv_heads * head_dim * 2
        * kv_bytes,
        "q_bytes": float(batch) * units_real * num_kv_heads * gp
        * head_dim * q_bytes,
        "merge_bytes": 2.0 * 4.0 * partial_elems if S > 1 else 0.0,
        "out_bytes": float(batch) * num_qo_heads * head_dim * out_bytes,
        "units_real": units_real,
        "units_total": S,
        "max_chunks_per_unit": max_chunks,
        "kv_tokens_launched": float(batch) * kv_launched,
    }


def decode_split(batch: int, ctx: int, num_qo_heads: int,
                 num_kv_heads: int, head_dim: int, *, num_splits: int,
                 page_size: int = 16,
                 pages_per_chunk: Optional[int] = None,
                 kv_bytes: int = 2, q_bytes: int = 2,
                 dtype: str = "bf16") -> Cost:
    """Split-KV paged decode: S partial passes + the merge reduction.
    At ``num_splits=1`` this is exactly :func:`paged_decode` (no
    partials exist).  Launched FLOPs count the whole-chunk KV walk
    (masked tails included); effective FLOPs count the attended
    tokens — the launched/effective gap is the split padding waste."""
    if num_splits <= 1:
        return dataclasses.replace(
            paged_decode(batch, ctx, num_qo_heads, num_kv_heads,
                         head_dim, kv_bytes=kv_bytes, q_bytes=q_bytes,
                         dtype=dtype),
            op="decode_split")
    bd = decode_split_breakdown(
        batch, ctx, num_qo_heads, num_kv_heads, head_dim,
        num_splits=num_splits, page_size=page_size,
        pages_per_chunk=pages_per_chunk, kv_bytes=kv_bytes,
        q_bytes=q_bytes)
    per_tok = 2.0 * num_qo_heads * (head_dim + head_dim)
    merge_elems = bd["merge_bytes"] / (2.0 * 4.0)
    return Cost(
        flops=bd["kv_tokens_launched"] * per_tok + 2.0 * merge_elems,
        flops_effective=float(batch) * ctx * per_tok,
        bytes_read=bd["kv_bytes"] + bd["q_bytes"]
        + bd["merge_bytes"] / 2.0,
        bytes_written=bd["merge_bytes"] / 2.0 + bd["out_bytes"],
        dtype=dtype, op="decode_split",
    )


# per-grid-step fixed overhead of the split predictor's stall model
# (DMA issue + epilogue per work unit) — a committed estimate pending
# on-chip calibration; the DECISIONS it drives (S>1 on short-ctx/
# large-batch, S=1 on long-ctx) are pinned by tests/test_split_decode.py
DECODE_UNIT_OVERHEAD_S = 0.3e-6


def predict_decode_seconds(batch: int, ctx: int, num_qo_heads: int,
                           num_kv_heads: int, head_dim: int, *,
                           num_splits: int, hbm_tbps: float,
                           page_size: int = 16,
                           pages_per_chunk: Optional[int] = None,
                           kv_bytes: int = 2) -> float:
    """Predicted wall time of one decode step at a candidate split
    factor: roofline transfer time of the algorithmic traffic, plus a
    cold-start stall term — each multi-chunk work unit (and each
    request of the unsplit kernel) exposes one chunk's DMA before its
    double buffer fills, while an all-single-chunk unit stream is
    cross-unit prefetched and exposes none — plus a per-unit fixed
    overhead.  This is the invert-the-cost-model selection rule
    (ROADMAP item 5): the same physics ``obs perf`` attributes with,
    used *forward* at plan time."""
    ppc = pages_per_chunk if pages_per_chunk else split_chunk_pages(
        page_size, num_kv_heads, head_dim, kv_bytes)
    cost = decode_split(
        batch, ctx, num_qo_heads, num_kv_heads, head_dim,
        num_splits=num_splits, page_size=page_size, pages_per_chunk=ppc,
        kv_bytes=kv_bytes)
    bd = decode_split_breakdown(
        batch, ctx, num_qo_heads, num_kv_heads, head_dim,
        num_splits=num_splits, page_size=page_size, pages_per_chunk=ppc,
        kv_bytes=kv_bytes)
    bw = hbm_tbps * 1e12
    chunk_bytes = (min(ppc * page_size, max(ctx, 1)) * num_kv_heads
                   * head_dim * 2 * kv_bytes)
    if num_splits <= 1:
        exposed = batch  # one cold start per request
        units = batch
    elif bd["max_chunks_per_unit"] <= 1:
        exposed = 0  # cross-unit double buffer: no cold start anywhere
        units = batch * bd["units_total"]
    else:
        exposed = batch * bd["units_real"]
        units = batch * bd["units_total"]
    return (cost.bytes_total / bw + exposed * chunk_bytes / bw
            + units * DECODE_UNIT_OVERHEAD_S)


def _quarantined(op_name: str, tactic) -> bool:
    """True when the bring-up quarantine blocklists (op, tactic) — the
    ISSUE 20 wedge-attribution plumbing.  Lazy import and never-raise:
    the import contract above stays intact (tactics_blocklist only
    loads when a chooser actually runs), and a broken quarantine file
    must not take the chooser down with it."""
    try:
        from flashinfer_tpu import tactics_blocklist

        return tactics_blocklist.blocked(op_name, tactic)
    except Exception:
        return False


def choose_decode_splits(batch: int, ctx: int, num_qo_heads: int,
                         num_kv_heads: int, head_dim: int, *,
                         hbm_tbps: float, page_size: int = 16,
                         pages_per_chunk: Optional[int] = None,
                         kv_bytes: int = 2,
                         candidates: Tuple[int, ...] = (1, 2, 4, 8),
                         feasible=None) -> Tuple[int, Dict[int, dict]]:
    """Plan-time split-factor selection: predict each candidate S with
    :func:`predict_decode_seconds`, drop candidates ``feasible``
    rejects (the L009 VMEM-feasibility evaluator at the decode.py call
    site), and return ``(best_S, table)`` where ``table[S]`` carries
    the predicted seconds / bytes / intensity evidence.  A larger S
    must beat the incumbent by >2% predicted time — on ties (e.g. a
    sub-chunk split degenerating to the same real partition) the
    smaller S wins, so S=1 stays the default wherever splitting has
    nothing to remove.

    Candidates the bring-up quarantine names (a smoke-ladder rung that
    wedged the chip on this (op, tactic) pair — ISSUE 20) are pruned
    the same way ``feasible`` rejections are: S=1 always survives, so
    a fully quarantined sweep degrades to unsplit, never to a wedge."""
    best, best_t = 1, None
    table: Dict[int, dict] = {}
    for S in sorted(set(int(s) for s in candidates)):
        if S < 1:
            continue
        if S > 1 and feasible is not None and not feasible(S):
            continue
        if S > 1 and _quarantined("decode.splits", S):
            continue
        cost = decode_split(
            batch, ctx, num_qo_heads, num_kv_heads, head_dim,
            num_splits=S, page_size=page_size,
            pages_per_chunk=pages_per_chunk, kv_bytes=kv_bytes)
        t = predict_decode_seconds(
            batch, ctx, num_qo_heads, num_kv_heads, head_dim,
            num_splits=S, hbm_tbps=hbm_tbps, page_size=page_size,
            pages_per_chunk=pages_per_chunk, kv_bytes=kv_bytes)
        table[S] = {
            "seconds": t, "bytes": cost.bytes_total,
            "intensity": cost.intensity,
        }
        if best_t is None or t < best_t * 0.98:
            best, best_t = S, t
    return best, table


def mla_decode(batch: int, ctx: int, num_heads: int, *,
               latent_dim: int = 512, rope_dim: int = 64,
               lane_pad: int = 128, cache_bytes: int = 2,
               q_bytes: int = 2, out_bytes: int = 2,
               dtype: str = "bf16") -> Cost:
    """MLA absorbed decode (DeepSeek ckv 512 + kpe 64): the latent cache
    is read ONCE for all heads (the MLA memory win); the TPU kpe layout
    is lane-padded to `lane_pad` columns, so cache bytes charge
    ``latent_dim + lane_pad`` per token — the padding is real HBM
    traffic.  FLOPs count the live dims only: q.k over
    (latent+rope) and p.v over latent."""
    att = float(batch) * ctx * num_heads
    return Cost(
        flops=2.0 * att * ((latent_dim + rope_dim) + latent_dim),
        bytes_read=(
            float(batch) * ctx * (latent_dim + lane_pad) * cache_bytes
            + batch * num_heads * (latent_dim + rope_dim) * q_bytes),
        bytes_written=float(batch) * num_heads * latent_dim * out_bytes,
        dtype=dtype, op="mla_decode",
    )


def fused_prefill_from_stats(
    stats: Mapping[str, int], *, block_q: int, pages_per_chunk: int,
    page_size: int, num_qo_heads: int, num_kv_heads: int, head_dim: int,
    total_q: int, q_bytes: int = 2, kv_bytes: int = 2,
    out_bytes: int = 2, dtype: str = "bf16",
) -> Cost:
    """Launched + effective work of the pipelined work-unit prefill,
    straight from the plan's post-pruning/post-packing ``stats`` (PR 3:
    ``mxu_cells_total`` = every (q-row, kv-col) MXU position the real
    units execute; ``mxu_cells_valid`` = the in-bounds ones).  The gap
    IS the padding waste ``plan.padding_waste_pct`` histograms."""
    chunk_tokens = pages_per_chunk * page_size
    per_cell = 2.0 * num_qo_heads * (head_dim + head_dim)
    return Cost(
        flops=stats["mxu_cells_total"] * per_cell,
        flops_effective=stats["mxu_cells_valid"] * per_cell,
        # q is fetched once per packed tile (the double-buffered qslot
        # stream); k+v stream once per unit chunk
        bytes_read=(
            stats["tiles"] * block_q * num_qo_heads * head_dim * q_bytes
            + stats["units"] * chunk_tokens * num_kv_heads
            * (head_dim + head_dim) * kv_bytes),
        bytes_written=float(total_q) * num_qo_heads * head_dim
        * out_bytes,
        dtype=dtype, op="fused_prefill",
    )


def paged_prefill(batch: int, qo_len: int, kv_len: int,
                  num_qo_heads: int, num_kv_heads: int, head_dim: int,
                  *, causal: bool = True, stats: Optional[Mapping] = None,
                  block_q: Optional[int] = None,
                  pages_per_chunk: Optional[int] = None,
                  page_size: int = 16, q_bytes: int = 2,
                  kv_bytes: int = 2, dtype: str = "bf16") -> Cost:
    """Batch chunked paged prefill.  With live plan ``stats`` (the
    fused backend) the launched work comes from the work-unit grid and
    the effective work from the attended tokens; without (gather
    fallback, or a banked row reconstructed from config alone) the
    cost is effective-only."""
    eff = attention(qo_len, kv_len, num_qo_heads, num_kv_heads,
                    head_dim, causal=causal, batch=batch,
                    q_bytes=q_bytes, kv_bytes=kv_bytes, dtype=dtype)
    if stats is None or block_q is None or pages_per_chunk is None:
        return dataclasses.replace(eff, op="paged_prefill")
    launched = fused_prefill_from_stats(
        stats, block_q=block_q, pages_per_chunk=pages_per_chunk,
        page_size=page_size, num_qo_heads=num_qo_heads,
        num_kv_heads=num_kv_heads, head_dim=head_dim,
        total_q=batch * qo_len, q_bytes=q_bytes, kv_bytes=kv_bytes,
        dtype=dtype)
    return dataclasses.replace(launched, flops_effective=eff.flops,
                               op="paged_prefill")


def prefill_ingest_breakdown(
    total_q: int, total_kv: int, num_qo_heads: int, num_kv_heads: int,
    head_dim: int, *, q_bytes: int = 2, kv_bytes: int = 2,
    cache_bytes: int = 2, out_bytes: int = 2,
) -> Dict[str, float]:
    """Algorithmic HBM traffic of the prefill INGEST fusion vs the
    separate-op composition (ISSUE 14): per the module convention these
    are algorithmic bytes — every operand read once, outputs written
    once — so the avoided terms are exactly the round trips the fusion
    structurally removes, independent of kernel re-streaming (which the
    measured-vs-roofline gap exposes separately).

    Separate path (rope -> quantize-append -> attention re-read):

    - rope_q: read raw q + write rotated q            = 2 Q
    - rope_k: read raw k + write rotated k            = 2 K
    - append: read rotated k + raw v, write cache     = K + V + Kc + Vc
    - attention: read rotated q + cache k/v, write o  = Q + Kc + Vc + O

    Fused path: read raw q + raw k + raw v, write cache + o.

    Avoided = 2 Q + 2 K + Kc + Vc — the rope round trips and the
    attention's cache re-read (the quantize-append write survives: the
    cache must exist for decode either way)."""
    Q = float(total_q) * num_qo_heads * head_dim * q_bytes
    K = float(total_kv) * num_kv_heads * head_dim * kv_bytes
    V = K
    Kc = float(total_kv) * num_kv_heads * head_dim * cache_bytes
    Vc = Kc
    O = float(total_q) * num_qo_heads * head_dim * out_bytes
    separate = 3 * Q + 3 * K + V + 2 * Kc + 2 * Vc + O
    fused = Q + K + V + Kc + Vc + O
    return {
        "separate_bytes": separate,
        "fused_bytes": fused,
        "bytes_avoided": separate - fused,
        "avoided_fraction": (separate - fused) / separate if separate
        else 0.0,
        # per-launch traffic of the separate composition (the chooser
        # prices these as SEQUENTIAL memory passes — rope and append
        # are elementwise and cannot hide under attention's MXU floor)
        "rope_bytes": 2 * Q + 2 * K,
        "append_bytes": K + V + Kc + Vc,
        "attention_bytes": Q + Kc + Vc + O,
    }


def prefill_ingest(
    total_q: int, total_kv: int, num_qo_heads: int, num_kv_heads: int,
    head_dim: int, *, causal: bool = True,
    stats: Optional[Mapping] = None, block_q: Optional[int] = None,
    pages_per_chunk: Optional[int] = None, page_size: int = 16,
    q_bytes: int = 2, kv_bytes: int = 2, cache_bytes: int = 2,
    out_bytes: int = 2, dtype: str = "bf16",
) -> Cost:
    """The fused prefill-ingest launch's cost: attention FLOPs (plus
    the ~6 FLOP/element rotation and 2 FLOP/element quantize riding
    in-register) over ONE raw q/k/v read + one quantized-page write +
    the output.  With live plan ``stats`` the launched work comes from
    the real work-unit grid (``fused_prefill_from_stats`` MXU cells;
    raw chunks stream once per unit, finished pages write once per
    ``ingest_chunks`` owner) and effective work is the attended pairs;
    without, the cost is the algorithmic fused-path traffic."""
    att = attention(total_q, total_kv, num_qo_heads, num_kv_heads,
                    head_dim, causal=causal, q_bytes=q_bytes,
                    kv_bytes=kv_bytes, out_bytes=out_bytes, dtype=dtype)
    rope_flops = 6.0 * (total_q * num_qo_heads
                        + total_kv * num_kv_heads) * head_dim
    quant_flops = 2.0 * 2.0 * total_kv * num_kv_heads * head_dim
    bd = prefill_ingest_breakdown(
        total_q, total_kv, num_qo_heads, num_kv_heads, head_dim,
        q_bytes=q_bytes, kv_bytes=kv_bytes, cache_bytes=cache_bytes,
        out_bytes=out_bytes)
    cache_w = 2.0 * total_kv * num_kv_heads * head_dim * cache_bytes
    out_w = float(total_q) * num_qo_heads * head_dim * out_bytes
    if stats is not None and block_q and pages_per_chunk:
        chunk_tokens = pages_per_chunk * page_size
        per_cell = 2.0 * num_qo_heads * (head_dim + head_dim)
        flops = (stats["mxu_cells_total"] * per_cell + rope_flops
                 + quant_flops)
        # effective follows the fused_prefill_from_stats convention:
        # the in-bounds MXU cells (plus the rotate/quantize work,
        # which is useful on every real row) — never att.flops, whose
        # causal accounting can exceed a tightly-pruned launch
        effective = (stats["mxu_cells_valid"] * per_cell + rope_flops
                     + quant_flops)
        reads = (
            stats["tiles"] * block_q * num_qo_heads * head_dim * q_bytes
            + stats["units"] * chunk_tokens * num_kv_heads
            * (head_dim + head_dim) * kv_bytes)
        return Cost(
            flops=flops, flops_effective=min(effective, flops),
            bytes_read=reads, bytes_written=cache_w + out_w,
            dtype=dtype, op="prefill_ingest")
    return Cost(
        flops=att.flops + rope_flops + quant_flops,
        flops_effective=att.flops,
        bytes_read=bd["fused_bytes"] - cache_w - out_w,
        bytes_written=cache_w + out_w,
        dtype=dtype, op="prefill_ingest")


def prefill_ingest_separate(
    total_q: int, total_kv: int, num_qo_heads: int, num_kv_heads: int,
    head_dim: int, *, causal: bool = True, q_bytes: int = 2,
    kv_bytes: int = 2, cache_bytes: int = 2, out_bytes: int = 2,
    dtype: str = "bf16",
) -> Cost:
    """The separate-op composition (rope → quantize-append → attention)
    priced at the SAME ``prefill_ingest`` op family as the fused launch
    — the A/B's separate-mode rows.  FLOPs are identical (the same
    rotate/quantize/attend work executes, just split over three
    launches); bytes are the three-pass traffic
    :func:`prefill_ingest_breakdown` itemizes (``separate_bytes``), so
    a separate-mode row's roofline fraction rates the composition
    against what it actually moved, not attention alone."""
    att = attention(total_q, total_kv, num_qo_heads, num_kv_heads,
                    head_dim, causal=causal, q_bytes=q_bytes,
                    kv_bytes=kv_bytes, out_bytes=out_bytes, dtype=dtype)
    rope_flops = 6.0 * (total_q * num_qo_heads
                        + total_kv * num_kv_heads) * head_dim
    quant_flops = 2.0 * 2.0 * total_kv * num_kv_heads * head_dim
    Q = float(total_q) * num_qo_heads * head_dim * q_bytes
    K = float(total_kv) * num_kv_heads * head_dim * kv_bytes
    V = K
    Kc = float(total_kv) * num_kv_heads * head_dim * cache_bytes
    Vc = Kc
    O = float(total_q) * num_qo_heads * head_dim * out_bytes
    # rope reads Q+K / writes Q+K; append reads K+V / writes Kc+Vc;
    # attention reads Q+Kc+Vc / writes O  (sum == separate_bytes)
    return Cost(
        flops=att.flops + rope_flops + quant_flops,
        flops_effective=att.flops,
        bytes_read=2 * Q + 2 * K + V + Kc + Vc,
        bytes_written=Q + K + Kc + Vc + O,
        dtype=dtype, op="prefill_ingest")


def predict_prefill_ingest_win(
    total_q: int, total_kv: int, num_qo_heads: int, num_kv_heads: int,
    head_dim: int, *, hbm_tbps: float, peak_tflops: float = 0.0,
    causal: bool = True, q_bytes: int = 2, kv_bytes: int = 2,
    cache_bytes: int = 2, feasible=None,
) -> Tuple[bool, Dict[str, float]]:
    """Plan-time fused-ingest selection (the ``choose_decode_splits``
    pattern, ISSUE 14): roofline-forward seconds of the separate-op
    composition vs the fused launch; fused must beat separate by >2%
    predicted time or the knob default stays OFF — ties and noise keep
    the proven composition.

    The separate path is THREE sequential launches — rope and
    quantize-append are elementwise memory passes that cannot hide
    under the attention launch's MXU floor — so it is priced as
    ``rope_bytes/bw + append_bytes/bw + max(attention_bytes/bw,
    t_flops)``, while the fused launch overlaps everything under one
    roofline (the rotation/quantize FLOPs ride the VPU inside the DMA
    shadow).  Compute-bound shapes therefore still show the win of the
    two deleted memory passes; tiny shapes where everything rounds to
    noise keep the proven composition via the 2% bar.  ``feasible``
    is the L009 VMEM-feasibility evaluator of the fused launch at the
    caller's shape (the ``choose_decode_splits`` prune applied to a
    two-candidate choice): when it rejects, the fused candidate is
    pruned before the roofline race and the proven separate
    composition wins unconditionally.  Returns ``(use_fused,
    evidence_table)``."""
    bd = prefill_ingest_breakdown(
        total_q, total_kv, num_qo_heads, num_kv_heads, head_dim,
        q_bytes=q_bytes, kv_bytes=kv_bytes, cache_bytes=cache_bytes)
    if feasible is not None and not feasible():
        # fused scratch does not fit VMEM at this shape: candidate
        # pruned pre-pricing, evidence records why OFF was forced
        return False, {
            "separate_s": 0.0, "fused_s": 0.0,
            "bytes_avoided": bd["bytes_avoided"],
            "avoided_fraction": bd["avoided_fraction"],
            "pruned_infeasible": 1.0,
        }
    if _quarantined("prefill.fused_ingest", "on"):
        # a bring-up smoke-ladder rung wedged the chip on the fused
        # launch (ISSUE 20): the proven separate composition wins
        # unconditionally until the quarantine is lifted
        return False, {
            "separate_s": 0.0, "fused_s": 0.0,
            "bytes_avoided": bd["bytes_avoided"],
            "avoided_fraction": bd["avoided_fraction"],
            "pruned_quarantined": 1.0,
        }
    att = attention(total_q, total_kv, num_qo_heads, num_kv_heads,
                    head_dim, causal=causal)
    bw = hbm_tbps * 1e12
    t_flops = (att.flops / (peak_tflops * 1e12)) if peak_tflops > 0 \
        else 0.0
    t_sep = (bd["rope_bytes"] / bw + bd["append_bytes"] / bw
             + max(bd["attention_bytes"] / bw, t_flops))
    t_fused = max(bd["fused_bytes"] / bw, t_flops)
    use = t_fused < t_sep * 0.98
    return use, {
        "separate_s": t_sep, "fused_s": t_fused,
        "bytes_avoided": bd["bytes_avoided"],
        "avoided_fraction": bd["avoided_fraction"],
    }


def moe_gmm(tokens: int, num_experts: int, hidden: int, inter: int,
            top_k: int, *, weight_bytes: int = 2, act_bytes: int = 2,
            experts_loaded: Optional[int] = None,
            dtype: str = "bf16") -> Cost:
    """Fused MoE (gate/up + down GEMMs over routed tokens).  Per-expert
    token loads: each ACTIVE expert's weight block is streamed once per
    launch (``experts_loaded``, default every expert hot — the bench
    regime where tokens*top_k >> experts); routed activations are
    gathered in and scattered out per (token, choice)."""
    if experts_loaded is None:
        experts_loaded = min(num_experts, tokens * top_k)
    per_tok = hidden * 2 * inter + inter * hidden  # both GEMMs, madd=2
    return Cost(
        flops=2.0 * tokens * top_k * per_tok,
        bytes_read=(
            float(experts_loaded) * (hidden * 2 * inter + inter * hidden)
            * weight_bytes
            + tokens * hidden * act_bytes  # x in
            + tokens * top_k * (hidden + 2 * inter) * act_bytes),
        bytes_written=(
            float(tokens) * top_k * hidden * act_bytes  # expert outs
            + tokens * hidden * act_bytes),  # combined y
        dtype=dtype, op="moe_gmm",
    )


def gemm(m: int, n: int, k: int, *, a_bytes: int = 2, b_bytes: int = 2,
         out_bytes: int = 2, dtype: str = "bf16") -> Cost:
    return Cost(
        flops=2.0 * m * n * k,
        bytes_read=float(m) * k * a_bytes + float(k) * n * b_bytes,
        bytes_written=float(m) * n * out_bytes, dtype=dtype, op="gemm",
    )


def sampling(batch: int, vocab: int, *, probs_bytes: int = 4) -> Cost:
    """Categorical sampling / filtering over the full distribution:
    one pass over [batch, vocab] probs, a few tokens out."""
    return Cost(
        flops=2.0 * batch * vocab,
        bytes_read=float(batch) * vocab * probs_bytes,
        bytes_written=float(batch) * 4, op="sampling",
    )


def topk(batch: int, vocab: int, k: int = 0, *,
         score_bytes: int = 4) -> Cost:
    """Exact top-k over [batch, vocab] scores: the lower-bound traffic
    is one read of the score matrix + k indices/values out."""
    return Cost(
        flops=2.0 * batch * vocab,
        bytes_read=float(batch) * vocab * score_bytes,
        bytes_written=float(batch) * max(k, 1) * 8, op="topk",
    )


def elementwise(elements: int, *, reads_per: int = 1, writes_per: int = 1,
                bytes_per: int = 2, flops_per: float = 2.0,
                op: str = "elementwise") -> Cost:
    """Gated activations / masks / casts: pure bandwidth."""
    return Cost(
        flops=flops_per * elements,
        bytes_read=float(elements) * reads_per * bytes_per,
        bytes_written=float(elements) * writes_per * bytes_per, op=op,
    )


def norm(tokens: int, hidden: int, *, bytes_per: int = 2,
         fused_residual: bool = False) -> Cost:
    """RMS-norm family: read x (+ residual) + weight, write out
    (+ residual); ~4 FLOPs/element (square, sum, rsqrt-mul, scale)."""
    n = tokens * hidden
    extra = n if fused_residual else 0
    return Cost(
        flops=4.0 * n,
        bytes_read=float(n + extra + hidden) * bytes_per,
        bytes_written=float(n + extra) * bytes_per, op="norm",
    )


def rope(tokens: int, num_heads: int, head_dim: int, *,
         bytes_per: int = 2, quantize_out_bytes: Optional[int] = None
         ) -> Cost:
    """Rotary embedding over q/k rows: read + write each element, ~6
    FLOPs/element (two muls + add per rotated pair, cos/sin amortized);
    the fp8-quantizing variants write at the narrow width."""
    n = tokens * num_heads * head_dim
    wb = bytes_per if quantize_out_bytes is None else quantize_out_bytes
    return Cost(flops=6.0 * n, bytes_read=float(n) * bytes_per,
                bytes_written=float(n) * wb, op="rope")


def page_append(tokens: int, num_kv_heads: int, head_dim: int, *,
                kv_bytes: int = 2, in_bytes: int = 2) -> Cost:
    """append_paged_kv_cache: read the new k+v rows, scatter them into
    the paged cache at the cache's storage width."""
    n = tokens * num_kv_heads * head_dim * 2  # k and v
    return Cost(flops=2.0 * n, bytes_read=float(n) * in_bytes,
                bytes_written=float(n) * kv_bytes, op="page_append")


# -- linear-attention / SSM families (bench.py phase_scans) ---------------


def state_decode(batch: int, num_heads: int, dk: int, dv: int, *,
                 state_bytes: int = 4) -> Cost:
    """One decode step of a state-space / linear-attention model: the
    [heads, dk, dv] f32 state is read + written once per token (the
    bandwidth bound the no-kernel verdicts divide by)."""
    n = batch * num_heads * dk * dv
    return Cost(flops=4.0 * n, bytes_read=float(n) * state_bytes,
                bytes_written=float(n) * state_bytes, op="state_decode")


def ssd_prefill(batch: int, seqlen: int, num_heads: int, head_dim: int,
                state_dim: int, *, chunk: int = 64,
                act_bytes: int = 4) -> Cost:
    """Mamba-2 chunked SSD prefill: intra-chunk scores [Q,Q] via C.B
    plus the state outer products (the bench.py formula, now shared)."""
    flops = (2.0 * batch * seqlen * chunk * num_heads
             * (state_dim + head_dim)
             + 2.0 * batch * seqlen * num_heads * head_dim * state_dim)
    n_io = batch * seqlen * num_heads * head_dim
    return Cost(flops=flops, bytes_read=float(n_io) * act_bytes * 2,
                bytes_written=float(n_io) * act_bytes, op="ssd_prefill")


def gated_delta_prefill(batch: int, seqlen: int, num_heads: int,
                        dk: int, dv: int, *, act_bytes: int = 4) -> Cost:
    """GDN / KDA chunked prefill: state in/out matmuls per token."""
    n_io = batch * seqlen * num_heads * (dk + dv)
    return Cost(flops=2.0 * batch * seqlen * num_heads * (dk * dv * 2),
                bytes_read=float(n_io) * act_bytes,
                bytes_written=float(batch) * seqlen * num_heads * dv
                * act_bytes, op="gated_delta_prefill")


# -- serving decode step (bench.py phase_serving int8 shard pipeline) -----

# dims of the per-chip tp=8 70B shard bench.py measures; keyed by the
# row's `model` field so `obs perf` can attribute banked rows that
# predate cost stamping
SERVING_SHAPES: Dict[str, Dict[str, int]] = {
    "llama70b_tp8shard_int8": dict(
        hidden=8192, hq=8, hkv=1, hd=128, inter=3584, vocab_shard=16032,
        page_size=16, weight_bytes=1, kv_bytes=1,
    ),
}

SERVING_PHASES = ("norm_rope", "attention", "kv_append", "moe_or_mlp",
                  "lm_head", "sampling")


def serving_phase_costs(bs: int, ctx: int, layers: int, *, hidden: int,
                        hq: int, hkv: int, hd: int, inter: int,
                        vocab_shard: int, page_size: int = 16,
                        weight_bytes: int = 1, kv_bytes: int = 1,
                        act_bytes: int = 2) -> Dict[str, Cost]:
    """Per-step cost of each serving-loop phase (the SAME names the
    ``overhead_decomposition`` row and profiler scopes use), aggregated
    over `layers`.  int8-weight GEMMs -> dtype int8."""
    qdim, kvdim = hq * hd, hkv * hd
    L = float(layers)

    def lg(m, n, k):  # one int8 GEMM per layer, activations int8 in
        return dataclasses.replace(
            gemm(m, n, k, a_bytes=1, b_bytes=weight_bytes,
                 out_bytes=act_bytes), dtype="int8")

    attn = (lg(bs, qdim + 2 * kvdim, hidden) + lg(bs, hidden, qdim)
            + dataclasses.replace(
                paged_decode(bs, ctx, hq, hkv, hd, kv_bytes=kv_bytes),
                dtype="int8"))
    mlp = lg(bs, 2 * inter, hidden) + lg(bs, hidden, inter)
    nr = (norm(bs, hidden) + norm(bs, hidden)
          + rope(bs, hq + hkv, hd))
    costs = {
        "norm_rope": _scale(nr, L),
        "attention": _scale(attn, L),
        "kv_append": _scale(
            page_append(bs, hkv, hd, kv_bytes=kv_bytes), L),
        "moe_or_mlp": _scale(mlp, L),
        "lm_head": dataclasses.replace(
            norm(bs, hidden) + lg(bs, vocab_shard, hidden),
            dtype="int8"),
        "sampling": sampling(bs, vocab_shard),
    }
    return costs


def _scale(c: Cost, k: float) -> Cost:
    return dataclasses.replace(
        c, flops=c.flops * k, bytes_read=c.bytes_read * k,
        bytes_written=c.bytes_written * k,
        flops_effective=None if c.flops_effective is None
        else c.flops_effective * k,
        ici_bytes=c.ici_bytes * k)


def serving_step(bs: int, ctx: int, layers: int, *,
                 include_kv_append: bool = True,
                 include_sampling: bool = True, **shape) -> Cost:
    """Whole decode step of the int8 shard pipeline: sum of phases
    (the slope-fit row excludes kv_append + sampling, mirroring what
    it measures)."""
    phases = serving_phase_costs(bs, ctx, layers, **shape)
    total = None
    for name in SERVING_PHASES:
        if name == "kv_append" and not include_kv_append:
            continue
        if name == "sampling" and not include_sampling:
            continue
        total = phases[name] if total is None else total + phases[name]
    return dataclasses.replace(total, dtype="int8", op="serving_step")


def engine_step(num_tokens: int, batch: int, layers: int, *, hidden: int,
                inter: int, hq: int, hkv: int, hd: int, vocab: int,
                kv_tokens: float, kv_rows: Optional[float] = None,
                kv_bytes: int = 2, weight_bytes: int = 2,
                act_bytes: int = 2, dtype: str = "bf16",
                kv_pairs_launched: Optional[float] = None,
                kv_rows_launched: Optional[float] = None) -> Cost:
    """One continuous-batching ENGINE step (serve/engine.py): mixed
    decode + chunked-prefill tokens on one flat axis.

    Counted terms, per layer x ``layers``:

    - projections / MLP / norms / rope / KV append over ``num_tokens``
      flat tokens (q/k/v, o, gate/up/down GEMMs; weights stream once
      per step);
    - attention FLOPs over ``kv_tokens`` attended (query, kv) pairs —
      the scheduler passes the EXACT per-token window sums (a decode
      lane contributes ``kv_len + 1``, a prefill chunk
      ``chunk*kv_before + chunk(chunk+1)/2``), so admission pricing
      sees real traffic, not a shape bound;
    - attention KV BYTES over ``kv_rows`` streamed cache rows (default
      ``kv_tokens``).  A caller that dedupes shared-prefix reads — the
      cascade level-0 group gather reads a shared page run ONCE per
      group instead of once per request — passes the deduped row count
      here, making the prefix-cache HBM win visible to ``obs perf``.
      FLOPs are never deduped (every query still multiplies the shared
      keys).

    Plus the lm_head + per-lane sampling epilogue over ``batch`` lanes.
    The engine's FLOPs-avoided metering prices skipped prefill spans
    with this same formula (``ServingEngine._prefill_cost_flops``).

    Launched-vs-effective (the KERNEL attention backend): when the
    engine runs the Pallas work-unit tier, ``kv_pairs_launched`` /
    ``kv_rows_launched`` carry the REAL unit stats — padded unit
    grids, chunk-aligned page walks, scratch-page DMAs included
    (``ServingEngine.unit_stats``).  The attention term then prices
    ``flops`` from launched pairs and ``bytes`` from launched rows,
    with ``flops_effective`` holding the exact attended-pair work, so
    ``obs perf`` exposes the tier's true padding waste instead of the
    dense window the reference tier attends through.  Left ``None``
    (the reference tier, and every pre-graduation caller) the formula
    is unchanged: launched == effective attended pairs."""
    qdim, kvdim = hq * hd, hkv * hd
    L = float(layers)
    if kv_rows is None:
        kv_rows = kv_tokens

    def g(m, n, k):
        return gemm(m, n, k, a_bytes=act_bytes, b_bytes=weight_bytes,
                    out_bytes=act_bytes, dtype=dtype)

    per_layer = (g(num_tokens, qdim + 2 * kvdim, hidden)
                 + g(num_tokens, hidden, qdim)
                 + g(num_tokens, 2 * inter, hidden)
                 + g(num_tokens, hidden, inter)
                 + norm(num_tokens, hidden, bytes_per=act_bytes)
                 + norm(num_tokens, hidden, bytes_per=act_bytes)
                 + rope(num_tokens, hq + hkv, hd, bytes_per=act_bytes)
                 + page_append(num_tokens, hkv, hd, kv_bytes=kv_bytes))
    attn_pairs = (kv_tokens if kv_pairs_launched is None
                  else kv_pairs_launched)
    attn_rows = kv_rows if kv_rows_launched is None else kv_rows_launched
    attn = Cost(
        flops=2.0 * attn_pairs * hq * (2 * hd),
        bytes_read=(num_tokens * hq * hd * act_bytes
                    + attn_rows * hkv * (2 * hd) * kv_bytes),
        bytes_written=float(num_tokens) * hq * hd * act_bytes,
        flops_effective=(None if kv_pairs_launched is None
                         else 2.0 * kv_tokens * hq * (2 * hd)),
        dtype=dtype, op="engine_attention",
    )
    total = _scale(per_layer, L) + _scale(attn, L)
    total = total + g(batch, vocab, hidden) + sampling(batch, vocab)
    return dataclasses.replace(total, dtype=dtype, op="engine_step")


# -- tiered-KV family (serve/kv_tier.py: host offload + disagg handoff) ---


def kv_page_bytes(pages: int, *, page_size: int, num_kv_heads: int,
                  head_dim: int, layers: int, kv_bytes: int = 2) -> float:
    """Payload bytes of one request's KV page run across all layers —
    the counted term of every tier movement (spill, restore, migrate):
    K and V planes, ``pages * page_size`` rows of ``num_kv_heads *
    head_dim`` lanes at the cache's storage width (quantized caches
    move at 1 byte/element — the compressed wire/host format)."""
    return (2.0 * layers * pages * page_size * num_kv_heads * head_dim
            * kv_bytes)


def kv_page_io(pages: int, *, page_size: int, num_kv_heads: int,
               head_dim: int, layers: int, kv_bytes: int = 2,
               direction: str = "spill", dtype: str = "bf16") -> Cost:
    """One host-tier page movement (``HostKVStore``): ``spill`` reads
    the page run out of HBM (the host-RAM write is not HBM traffic),
    ``restore`` writes it back.  Zero FLOPs — the tier moves bytes, it
    computes nothing — so attribution is pure bandwidth.  The cost
    family of the ``engine.kv_spill`` / ``engine.kv_restore`` ops."""
    if direction not in ("spill", "restore"):
        raise ValueError(f"direction must be spill|restore, "
                         f"got {direction!r}")
    payload = kv_page_bytes(pages, page_size=page_size,
                            num_kv_heads=num_kv_heads, head_dim=head_dim,
                            layers=layers, kv_bytes=kv_bytes)
    return Cost(
        flops=0.0,
        bytes_read=payload if direction == "spill" else 0.0,
        bytes_written=payload if direction == "restore" else 0.0,
        dtype=dtype, op="kv_page_io",
    )


def kv_migrate(tokens: Optional[int] = None, *, pages: Optional[int] = None,
               page_size: int = 16, num_kv_heads: int, head_dim: int,
               layers: int, kv_bytes: int = 2, hops: int = 1,
               dtype: str = "bf16") -> Cost:
    """One prefill-pool -> decode-pool KV handoff (the disaggregated
    serving collective, ``engine.kv_migrate``): the finished prefill's
    page run crosses the ICI once per hop — point-to-point, so wire
    bytes equal the payload (no ring (p-1)/p discount; a multi-hop
    route multiplies).  The HBM legs are real on both ends: the source
    chip reads the run out, the destination writes it in.  Per-request
    page-run x kv-byte-width wire formula — what
    ``roofline.predict_serving_ici`` prices per chip generation and the
    ``serving_disagg`` bench phase stamps on migration rows
    (``bound == "ici"`` wherever the interconnect is the deepest
    floor, which it is on every registered chip)."""
    if pages is None:
        if tokens is None:
            raise ValueError("kv_migrate needs tokens or pages")
        pages = _cdiv(max(int(tokens), 1), page_size)
    payload = kv_page_bytes(pages, page_size=page_size,
                            num_kv_heads=num_kv_heads, head_dim=head_dim,
                            layers=layers, kv_bytes=kv_bytes)
    return Cost(
        flops=0.0, bytes_read=payload, bytes_written=payload,
        ici_bytes=payload * max(int(hops), 1),
        dtype=dtype, op="kv_migrate",
    )


# -- ICI collective family (the sharded serving step's third dimension) ----

# wire bytes each chip moves per payload byte for the canonical ring
# algorithms over p ranks (scaling-book formulas; p=1 moves nothing):
# allreduce = reduce-scatter + all-gather = 2(p-1)/p; gather/scatter
# each (p-1)/p; all_to_all keeps 1/p local and sends the rest.
_COLLECTIVE_WIRE_FACTOR = {
    "allreduce": 2.0, "allgather": 1.0, "reducescatter": 1.0,
    "alltoall": 1.0,
}


def collective(kind: str, payload_bytes: float, axis_size: int, *,
               op: str = "") -> Cost:
    """Per-chip cost of ONE collective over `axis_size` ranks.

    Only the ICI dimension is charged: the payload's HBM staging traffic
    already belongs to the producing op's write and the consuming op's
    read (charging it again here would double-count the phase's HBM
    bytes), and reduction adds are ~1 FLOP/element — noise against the
    GEMMs they join, so FLOPs stay 0 to keep MFU honest."""
    p = max(int(axis_size), 1)
    factor = _COLLECTIVE_WIRE_FACTOR[kind]
    wire = factor * (p - 1) / p * float(payload_bytes) if p > 1 else 0.0
    return Cost(flops=0.0, bytes_read=0.0, bytes_written=0.0,
                ici_bytes=wire, op=op or kind)


def tp_allreduce(tokens: int, hidden: int, tp_size: int, *,
                 act_bytes: int = 2) -> Cost:
    """One TP partial-sum combine of a [tokens, hidden] activation (the
    o_proj / down_proj epilogue — 2 of these per decoder layer)."""
    return collective("allreduce", float(tokens) * hidden * act_bytes,
                      tp_size, op="tp_allreduce")


def ep_all_to_all(tokens: int, hidden: int, top_k: int, ep_size: int, *,
                  act_bytes: int = 2) -> Cost:
    """EP token exchange for one MoE layer: dispatch + combine, each an
    all_to_all of the routed (token, choice) activations — the
    ``fused_moe_ep`` "alltoall" mode's O(T*K*hidden) traffic (balanced
    routing; capacity overflow rounds add multiples of this)."""
    payload = float(tokens) * max(top_k, 1) * hidden * act_bytes
    a2a = collective("alltoall", payload, ep_size, op="ep_all_to_all")
    return dataclasses.replace(a2a, ici_bytes=2.0 * a2a.ici_bytes,
                               op="ep_all_to_all")


def sampling_gather(batch_local: int, vocab: int, tp_size: int, *,
                    dp_size: int = 1, logits_bytes: int = 4) -> Cost:
    """The sampling epilogue's gathers, per chip: the replicated-
    sampler contract (parallel/plan.py) all-gathers the vocab-sharded
    logits over tp AND the batch-sharded logits over dp, so every chip
    holds the FULL [batch, vocab] f32 distribution before sampling
    (this jax's threefry is not partitionable — a sharded sampler
    would fork the random stream).  ``batch_local`` is the per-dp-shard
    batch; the dp leg gathers all ``batch_local * dp`` rows."""
    g_tp = collective("allgather",
                      float(batch_local) * vocab * logits_bytes,
                      tp_size, op="sampling_gather")
    g_dp = collective("allgather",
                      float(batch_local) * dp_size * vocab * logits_bytes,
                      dp_size, op="sampling_gather")
    return dataclasses.replace(g_tp + g_dp, op="sampling_gather")


# GLOBAL dims of the sharded serving pipeline (the whole model, not the
# per-chip shard): tp8 of this entry IS SERVING_SHAPES'
# "llama70b_tp8shard_int8" (hq 64/8=8, hkv 8/8=1, inter 28672/8=3584,
# vocab 128256/8=16032 — pinned by tests/test_sharded_step.py)
SHARDED_SERVING_SHAPES: Dict[str, Dict[str, int]] = {
    "llama70b_int8": dict(
        hidden=8192, hq=64, hkv=8, hd=128, inter=28672,
        vocab_shard=128256, page_size=16, weight_bytes=1, kv_bytes=1,
    ),
}


def serving_phase_costs_sharded(
    bs: int, ctx: int, layers: int, *, dp: int = 1, tp: int = 1,
    ep: int = 1, moe_top_k: int = 0, hidden: int, hq: int, hkv: int,
    hd: int, inter: int, vocab_shard: int, page_size: int = 16,
    weight_bytes: int = 1, kv_bytes: int = 1, act_bytes: int = 2,
) -> Dict[str, Cost]:
    """PER-CHIP cost of each serving phase on a (dp, tp[, ep]) mesh,
    from GLOBAL model dims: the single-chip formulas at the local shard
    dims (batch/dp, heads+inter+vocab/tp — exactly the per-chip shard
    bench.py measures at tp8), plus the collective family per phase:

    - ``attention``  += one TP allreduce per layer (o_proj combine);
    - ``moe_or_mlp`` += one TP allreduce per layer (down combine) and,
      when ``moe_top_k > 0`` and ``ep > 1``, the EP all-to-all pair;
    - ``sampling``   += the vocab all-gather (+ dp token exchange).

    ``tp=dp=1`` degenerates exactly to :func:`serving_phase_costs` —
    the single-chip model is the mesh model's fixed point."""
    if hq % tp or hkv % tp or inter % tp or vocab_shard % tp or bs % dp:
        raise ValueError(
            f"global dims (hq {hq}, hkv {hkv}, inter {inter}, vocab "
            f"{vocab_shard}, bs {bs}) do not tile (dp {dp}, tp {tp})")
    bs_l = bs // dp
    costs = serving_phase_costs(
        bs_l, ctx, layers, hidden=hidden, hq=hq // tp, hkv=hkv // tp,
        hd=hd, inter=inter // tp, vocab_shard=vocab_shard // tp,
        page_size=page_size, weight_bytes=weight_bytes,
        kv_bytes=kv_bytes, act_bytes=act_bytes)
    L = float(layers)
    ar = _scale(tp_allreduce(bs_l, hidden, tp, act_bytes=act_bytes), L)
    costs["attention"] = costs["attention"] + ar
    costs["moe_or_mlp"] = costs["moe_or_mlp"] + ar
    if moe_top_k > 0 and ep > 1:
        costs["moe_or_mlp"] = costs["moe_or_mlp"] + _scale(
            ep_all_to_all(bs_l, hidden, moe_top_k, ep,
                          act_bytes=act_bytes), L)
    costs["sampling"] = costs["sampling"] + sampling_gather(
        bs_l, vocab_shard, tp, dp_size=dp)
    return costs


def serving_step_sharded(bs: int, ctx: int, layers: int, *, dp: int = 1,
                         tp: int = 1, ep: int = 1, moe_top_k: int = 0,
                         **shape) -> Cost:
    """Whole per-chip sharded decode step: phase sum with the
    collective ICI bytes folded in (nothing excluded — the fused
    sharded step dispatches kv_append and sampling too).  The cost
    family of the ``parallel.sharded_step`` public op."""
    phases = serving_phase_costs_sharded(
        bs, ctx, layers, dp=dp, tp=tp, ep=ep, moe_top_k=moe_top_k,
        **shape)
    total = None
    for name in SERVING_PHASES:
        total = phases[name] if total is None else total + phases[name]
    return dataclasses.replace(total, dtype="int8",
                               op="serving_step_sharded")


def predict_step_seconds(cost: Cost, *, hbm_tbps: float,
                         peak_tflops: float, ici_gbps: float) -> float:
    """Roofline-forward prediction of one step's wall time on one chip
    of a mesh: HBM and MXU floors overlap (the deeper one binds), the
    ICI floor adds serially — collectives on the serving critical path
    overlap poorly with the dependent compute that waits on them (the
    conservative no-overlap model; same physics ``obs perf`` attributes
    with, used forward like ``predict_decode_seconds``)."""
    t_mem = cost.bytes_total / (hbm_tbps * 1e12)
    t_comp = cost.flops / (peak_tflops * 1e12)
    t_ici = cost.ici_bytes / (ici_gbps * 1e9) if ici_gbps > 0 else 0.0
    return max(t_mem, t_comp) + t_ici


# -- @flashinfer_api coverage (obs doctor) --------------------------------

# decorated public op -> cost-model family (a function in this module).
# `obs doctor` lists API_OPS absent here, mirroring L005's obs-coverage
# idea: a new public op cannot silently ship roofline-unattributable.
API_OP_COSTS: Dict[str, str] = {
    "silu_and_mul": "elementwise", "gelu_and_mul": "elementwise",
    "gelu_tanh_and_mul": "elementwise",
    "rmsnorm": "norm", "gemma_rmsnorm": "norm",
    "fused_add_rmsnorm": "norm", "gemma_fused_add_rmsnorm": "norm",
    "apply_rope": "rope", "apply_llama31_rope": "rope",
    "rope_quantize_fp8": "rope", "mla_rope_quantize_fp8": "rope",
    "rope_quantize_fp8_append_paged_kv_cache": "rope",
    "append_paged_kv_cache": "page_append",
    "single_decode_with_kv_cache": "attention",
    "single_prefill_with_kv_cache": "attention",
    "sampling_from_probs": "sampling", "sampling_from_logits": "sampling",
    "top_p_sampling_from_probs": "sampling",
    "top_k_sampling_from_probs": "sampling",
    "min_p_sampling_from_probs": "sampling",
    "top_k_top_p_sampling_from_probs": "sampling",
    # the fused serving steps: whole-step cost is the phase-sum model
    # (serving_step = norm_rope + attention + kv_append + moe_or_mlp +
    # lm_head + sampling — the fused step EXCLUDES nothing)
    "serve.step": "serving_step",
    "serve.mixed_step": "serving_step",
    # the mesh twin: phase sum + the collective ICI family (tp
    # allreduces, optional EP all-to-all, sampling gather)
    "parallel.sharded_step": "serving_step_sharded",
    # the continuous-batching engine step: mixed decode + chunked
    # prefill on one flat axis, exact attended-pair accounting and a
    # deduped shared-prefix KV-row term (the cascade level-0 gather)
    "engine.step": "engine_step",
    # the tiered-KV subsystem (serve/kv_tier.py): host-RAM page
    # movements are pure-bandwidth page-run formulas; the disagg
    # handoff adds the point-to-point ICI wire leg
    "engine.kv_spill": "kv_page_io",
    "engine.kv_restore": "kv_page_io",
    "engine.kv_migrate": "kv_migrate",
}


def uncovered_api_ops() -> Tuple[str, ...]:
    """Decorated public ops with no cost-model family (doctor check).

    Delegates to the L013 ``registry_coverage`` pass — the ONE
    implementation of the coverage rule, shared by ``obs doctor`` and
    the static analyzer (ISSUE 15): the lint gate and the doctor can
    never disagree about what "covered" means.  The fallback mirrors
    the delegated implementation so this obs-internal surface survives
    a broken ANALYSIS package (importing the pass runs the full
    package init); the pass remains the enforcement point."""
    try:
        from flashinfer_tpu.analysis.registry_coverage import \
            uncovered_api_ops as _impl
    except Exception:
        from flashinfer_tpu.obs.catalog import API_OPS

        return tuple(sorted(API_OPS - set(API_OP_COSTS)))
    return _impl()


# -- banked-row reconstruction (obs perf on pre-roofline history) ---------

# fixed configs of bench.py's phases that rows don't spell out
# (Llama-3 GQA 32/8/128, DeepSeek MLA 128 heads 512+64, Mixtral 8x7B,
# the scans dims) — used ONLY for rows banked before cost stamping;
# new rows carry their cost fields inline.
_BENCH_DECODE = dict(num_qo_heads=32, num_kv_heads=8, head_dim=128)
_BENCH_PREFILL = dict(HQ=32, HKV=8, D=128)
_BENCH_MOE = dict(num_experts=8, hidden=4096, inter=14336, top_k=2)
_BENCH_SCANS = dict(H=24, dim=64, ds=128, Hg=16, dk=128, dv=128)


def _row_seconds(row: Mapping) -> Optional[float]:
    """Wall time of the measurement a row's stamp refers to."""
    for f in ("us", "us_step", "us_step_80l", "kernel_us"):
        v = row.get(f)
        if isinstance(v, (int, float)) and v > 0:
            return float(v) * 1e-6
    return None


def cost_from_stamped_row(row: Mapping) -> Optional[Tuple[Cost, float]]:
    """(Cost, seconds) straight from a row that obs.roofline already
    stamped (new-generation banked rows are self-describing): launched
    flops + read/write bytes, the optional ``flops_effective`` waste
    split, and the compute dtype — no shape reconstruction needed."""
    try:
        flops = float(row["flops"])
        br = float(row["bytes_read"])
        bw = float(row["bytes_written"])
    except (KeyError, TypeError, ValueError):
        return None
    seconds = _row_seconds(row)
    if seconds is None:
        return None
    eff = row.get("flops_effective")
    ici = row.get("ici_bytes")
    return Cost(
        flops=flops, bytes_read=br, bytes_written=bw,
        flops_effective=float(eff) if isinstance(eff, (int, float))
        else None,
        dtype=str(row.get("dtype", "bf16")), op=str(row.get("phase", "")),
        ici_bytes=float(ici) if isinstance(ici, (int, float)) else 0.0,
    ), seconds


def cost_for_bench_row(row: Mapping) -> Optional[Tuple[Cost, float]]:
    """(Cost, seconds) for a bench row: the row's own roofline stamp
    when present (:func:`cost_from_stamped_row`), else reconstructed
    from the row's configuration via the fixed bench shapes below.
    None when the phase has no model (the CI selftest stub) or the row
    is malformed."""
    stamped = cost_from_stamped_row(row)
    if stamped is not None:
        return stamped
    phase = row.get("phase")
    try:
        if phase == "decode":
            return (paged_decode(int(row["bs"]), int(row["ctx"]),
                                 **_BENCH_DECODE),
                    float(row["us"]) * 1e-6)
        if phase == "prefill":
            p = _BENCH_PREFILL
            if row.get("kind") == "ragged_flash":
                T = int(row["qlen"])
                c = attention(T, T, p["HQ"], p["HKV"], p["D"],
                              causal=True)
            else:
                c = paged_prefill(int(row["bs"]), int(row["qlen"]),
                                  int(row["ctx"]), p["HQ"], p["HKV"],
                                  p["D"], causal=True)
            return c, float(row["us"]) * 1e-6
        if phase == "mla":
            return (mla_decode(int(row["bs"]), int(row["ctx"]),
                               int(row.get("heads", 128))),
                    float(row["us"]) * 1e-6)
        if phase == "moe":
            int8 = "int8" in str(row.get("variant", ""))
            return (moe_gmm(int(row["tokens"]), **_BENCH_MOE,
                            weight_bytes=1 if int8 else 2,
                            dtype="int8" if int8 else "bf16"),
                    float(row["us"]) * 1e-6)
        if phase == "sampling":
            return (sampling(int(row["bs"]), int(row["vocab"])),
                    float(row["kernel_us"]) * 1e-6)
        if phase == "topk":
            return (topk(int(row["bs"]), int(row["vocab"]),
                         int(row.get("k", 0))),
                    float(row["us"]) * 1e-6)
        if phase == "scans":
            return _scans_row_cost(row)
        if phase == "serving":
            return _serving_row_cost(row)
    except (KeyError, TypeError, ValueError):
        return None
    return None


def _scans_row_cost(row: Mapping) -> Optional[Tuple[Cost, float]]:
    op, B = str(row.get("op", "")), int(row["B"])
    s = _BENCH_SCANS
    t = float(row["us"]) * 1e-6
    if op == "mamba_decode":
        return state_decode(B, s["H"], s["dim"], s["ds"]), t
    if op in ("gdn_decode", "kda_decode"):
        return state_decode(B, s["Hg"], s["dk"], s["dv"]), t
    L = int(row["L"])
    if op.startswith("mamba_prefill"):
        chunk = 128 if op.endswith("pallas") else 64
        return ssd_prefill(B, L, s["H"], s["dim"], s["ds"],
                           chunk=chunk), t
    if op.startswith(("gdn_prefill", "kda_prefill")):
        return gated_delta_prefill(B, L, s["Hg"], s["dk"], s["dv"]), t
    return None


def _serving_row_cost(row: Mapping) -> Optional[Tuple[Cost, float]]:
    shape = SERVING_SHAPES.get(str(row.get("model", "")))
    if shape is None:
        return None
    bs, ctx = int(row["bs"]), int(row["ctx"])
    if row.get("mode") == "e2e_measured":
        return (serving_step(bs, ctx, int(row["layers"]), **shape),
                float(row["us_step"]) * 1e-6)
    if "us_step_80l" in row:
        return (serving_step(bs, ctx, 80, include_kv_append=False,
                             include_sampling=False, **shape),
                float(row["us_step_80l"]) * 1e-6)
    return None


# ---------------------------------------------------------------------------
# Cost-launch bindings: the L016 cost-parity registry (launcher -> family)
# ---------------------------------------------------------------------------
#
# Registration contract (the extension point every newly PRICED kernel
# must feed — the costmodel side of the ``PLANNER_KERNELS`` /
# ``KNOB_LAUNCHES`` triple; see analysis/pallas_contract.py and
# analysis/vmem_budget.py for the other two):
#
# A :class:`CostLaunchBinding` ties one Pallas *launcher* (the function
# whose ``pl.pallas_call`` the analyzer resolves) to the cost-model
# *family* that prices it, plus ONE concrete scenario under which the
# L016 ``cost_parity`` pass replays the kernel symbolically and proves
# the formula's bytes/FLOPs against the DMA traffic and MXU dots the
# kernel body actually issues.  Scenarios must (a) make every grid
# trip count and BlockSpec dimension evaluable from ``scenario``
# alone, (b) keep the grid's final axis >= 3 trips so warmup /
# steady-state / epilogue steps are all distinguished (the
# double-buffer warmup is counted once, not per step), and (c) keep
# every in-kernel unrolled loop within the model's unroll ceiling.
# ``adapter`` returns the family's EXPECTED totals for exactly the
# traffic the launch itself moves — terms belonging to sibling
# launches (e.g. the split-decode merge pass) are excluded, and the
# exclusion must be justified in ``notes``.  A deviation beyond
# ``compare``'s tolerance is a machine-proved cost-model drift:
# fix the formula or the kernel, NEVER baseline it (L016 is in the
# analyzer's unbaselineable set, like L014 races).


@dataclasses.dataclass(frozen=True)
class CostLaunchBinding:
    """One launcher's parity contract against its pricing family.

    ``launcher``/``family`` are names (resolved by the pass /
    checked by L017), the callables are scenario -> concrete values:

    - ``vmem_shapes(scenario)``: kernel-visible shape of every VMEM
      ref the kernel's DMAs or dots touch, keyed by KERNEL param (or
      scratch-unpack) name.  Cross-checked against the launch's
      ``scratch_shapes`` exprs via the L009 evaluator where
      ``scratch_names`` maps a name to its scratch index — a
      disagreement is its own L016 finding (binding drift).
    - ``adapter(scenario)``: expected totals per compared category
      (``bytes_read`` / ``bytes_written`` / ``bytes_total`` /
      ``flops``), computed by calling the family formula.
    - ``compare``: category -> relative tolerance (0.0 = exact).
    - ``implicit_fallback(scenario)``: declared BlockSpec-machinery
      bytes, used ONLY for the spec side(s) the analyzer cannot
      statically resolve (flag-conditional spec lists); sides the
      analyzer CAN resolve are always machine-derived and the
      declaration is ignored.  ``notes`` must say why resolution
      fails.
    """

    launcher: str
    family: str
    scenario: Mapping[str, object]
    statics: Mapping[str, object]
    seeds: Mapping[str, int]
    vmem_shapes: object  # Callable[[Mapping], Dict[str, tuple]]
    adapter: object  # Callable[[Mapping], Dict[str, float]]
    compare: Mapping[str, float]
    itemsizes: Mapping[str, int] = dataclasses.field(
        default_factory=dict)
    default_itemsize: int = 2
    spec_itemsizes: Mapping[str, int] = dataclasses.field(
        default_factory=dict)
    scratch_names: Mapping[str, int] = dataclasses.field(
        default_factory=dict)
    implicit_fallback: Optional[object] = None
    notes: str = ""


COST_LAUNCH_BINDINGS: Dict[str, CostLaunchBinding] = {}


def register_cost_launch(binding: CostLaunchBinding) -> CostLaunchBinding:
    COST_LAUNCH_BINDINGS[binding.launcher] = binding
    return binding


# -- knob -> chooser coverage (L017) ----------------------------------------
#
# Every KNOWN_KNOBS surface must either be resolved by a registered
# plan-time chooser (a ``choose_*`` / ``predict_*_win`` function that
# prunes candidates through the real L009 VMEM evaluator before
# pricing them) or carry a REASONED waiver below.  A waiver that
# shadows a registered chooser, or names a knob that no longer
# exists, is itself an L017 finding — same staleness rules as the
# L013 KNOB_WAIVERS idiom.

KNOB_CHOOSERS: Dict[str, str] = {}
CHOOSER_WAIVERS: Dict[str, str] = {}


def register_knob_chooser(knob: str, chooser: str) -> None:
    KNOB_CHOOSERS[knob] = chooser


def waive_chooser(knob: str, reason: str) -> None:
    CHOOSER_WAIVERS[knob] = reason


register_knob_chooser("decode.splits", "choose_decode_splits")
register_knob_chooser("prefill.fused_ingest", "predict_prefill_ingest_win")

waive_chooser("paged_decode.pages_per_chunk",
              "resolved by the shared split_chunk_pages formula "
              "(512/page clamp + 8 MiB double-buffer scratch bound), "
              "a geometry derivation, not a priced choice")
waive_chooser("paged_decode.prefetch",
              "boolean pipeline toggle whose safety is proven by the "
              "L014 race model; perf delta is A/B'd on-chip, no "
              "analytic candidate race exists")
waive_chooser("fused_prefill.blocks",
              "(block_q, pages_per_chunk) is tuned by the offline "
              "banked sweep (scripts/exp_prefill_blocks.py) and "
              "gated by the L009 VMEM proof of the launch binding; "
              "no plan-time pricing loop")
waive_chooser("flash_attention.blocks",
              "offline-swept grid blocks, L009-gated via its "
              "KNOB_LAUNCHES binding; not priced at plan time")
waive_chooser("moe_gmm.tiles",
              "chosen by tune_tiles MEASURED profiling with the "
              "VMEM-ceiling candidate filter — measurement beats the "
              "model where both exist (docs/performance.md)")
waive_chooser("mla_decode.layout",
              "dictated by the latent-cache layout contract of the "
              "serving cache, not a priced per-shape choice")
waive_chooser("rmsnorm.row_block",
              "bandwidth-bound elementwise kernel: row block is a "
              "VMEM-fit resolution (L009 launch binding), every "
              "fitting value moves the same bytes")
waive_chooser("fused_add_rmsnorm.row_block",
              "same as rmsnorm.row_block: VMEM-fit resolution of a "
              "bandwidth-bound elementwise kernel")
waive_chooser("serve.mixed_chunk",
              "priced per-step by predict_step_seconds against the "
              "SLO budget inside the engine scheduler (serve/step), "
              "not by a standalone candidate chooser")
waive_chooser("parallel.dp",
              "mesh topology knob: validity (dp x tp == world) and "
              "capacity math live in parallel/plan.py; no kernel "
              "candidate set to price")
waive_chooser("parallel.tp",
              "mesh topology knob, see parallel.dp")
waive_chooser("parallel.ep",
              "mesh topology knob (must divide parallel.tp), see "
              "parallel.dp")
waive_chooser("engine.block_size",
              "page-pool sharing granularity: a capacity/prefix-"
              "cache trade priced by serving capacity math, not a "
              "kernel-candidate race")
waive_chooser("engine.prefill_budget_tokens",
              "the marginal chunk is priced ONLINE by "
              "predict_step_seconds against slo_step_seconds in the "
              "engine scheduler; the static is a ceiling, not a "
              "candidate choice")
waive_chooser("engine.max_batch",
              "compile-once rung-ladder width: a memory-capacity "
              "ceiling from the HBM budget, not a priced choice")
waive_chooser("engine.kv_offload",
              "deployment capacity toggle (host tier attached or "
              "not); spill pricing happens per-victim under "
              "engine.spill_policy")
waive_chooser("engine.spill_policy",
              "'auto' performs the per-victim restore-vs-recompute "
              "cost comparison inline in the engine (via "
              "predict_step_seconds); the knob selects the policy, "
              "the pricing is not a choose_* surface")
waive_chooser("engine.host_gib",
              "host-RAM capacity budget; LRU eviction over it is "
              "counted, there is no candidate set to price")
waive_chooser("engine.attention_backend",
              "correctness-tier dispatch (reference oracle vs Pallas "
              "kernels); the kernel tier's internal choices are "
              "priced by decode.splits / prefill.fused_ingest")


# -- the five priced kernel families ----------------------------------------


def _gmm_vmem_shapes(sc):
    tm, tk, tn = sc["tm"], sc["tk"], sc["tn"]
    return {"lhs_ref": (tm, tk), "rhs_ref": (tk, tn),
            "out_ref": (tm, tn), "acc_ref": (tm, tn)}


def _gmm_adapter(sc):
    c = gemm(sc["m"], sc["n"], sc["k"])
    return {"bytes_read": c.bytes_read,
            "bytes_written": c.bytes_written, "flops": c.flops}


def _gmm_implicit(sc):
    # in_specs is extended under the quantized flag, so the analyzer
    # cannot statically resolve the list; the bf16 scenario's two
    # operands are declared here: lhs re-streamed per k-tile sweep
    # (tiles_n == 1 in the scenario so lhs streams once), rhs panels
    # once per (group, k) visit.
    return {"bytes_read": float(sc["m"]) * sc["k"] * 2
            + float(sc["k"]) * sc["n"] * 2}


register_cost_launch(CostLaunchBinding(
    launcher="gmm",
    family="gemm",
    scenario=dict(tiles_n=1, num_tiles=1, tiles_k=2, tm=128, tk=512,
                  tn=128, m=128, k=1024, n=128),
    statics=dict(tm=128, tiles_k=2, quantized=False),
    seeds=dict(offsets_s=0, tile_group_s=0, tile_m_s=0),
    vmem_shapes=_gmm_vmem_shapes,
    adapter=_gmm_adapter,
    compare={"bytes_read": 0.0, "bytes_written": 0.0, "flops": 0.0},
    itemsizes={"acc_ref": 4},
    spec_itemsizes={"out0": 2},
    scratch_names={"acc_ref": 0},
    implicit_fallback=_gmm_implicit,
    notes="One expert tile, one n-tile, two k-tiles of the bf16 "
          "grouped matmul: exactly one gemm(m, n, k) with every "
          "operand streamed once, so parity is exact (tol 0). The "
          "masked-partial-store epilogue re-reads the resident out "
          "block in VMEM, not HBM.",
))


def _paged_decode_vmem_shapes(sc):
    ppc, hkv = sc["pages_per_chunk"], sc["num_kv_heads"]
    ps, d, gp = sc["page_size"], sc["head_dim"], sc["gp"]
    return {"k_buf": (2, ppc, hkv, ps, d), "v_buf": (2, ppc, hkv, ps, d),
            "q_ref": (hkv, gp, d), "o_ref": (hkv, gp, d),
            "lse_ref": (hkv, gp, 128)}


def _paged_decode_adapter(sc):
    c = paged_decode(sc["batch"], sc["ctx"], sc["num_qo_heads"],
                     sc["num_kv_heads"], sc["head_dim"])
    return {"bytes_read": c.bytes_read, "flops": c.flops,
            "bytes_total": c.bytes_total}


register_cost_launch(CostLaunchBinding(
    launcher="_paged_decode_hnd_launch",
    family="paged_decode",
    scenario=dict(batch=4, ctx=512, num_qo_heads=16, num_kv_heads=2,
                  group=8, gp=8, head_dim=128, page_size=16,
                  pages_per_chunk=8),
    statics=dict(page_size=16, ppc=8, sm_scale=1.0,
                 logits_soft_cap=0.0, window_left=-1, num_kv_heads=2,
                 cross_step_prefetch=False, compute_dtype="bfloat16"),
    seeds=dict(pages_ref=0, kvlen_ref=512, base_smem=0),
    vmem_shapes=_paged_decode_vmem_shapes,
    adapter=_paged_decode_adapter,
    compare={"bytes_read": 0.0, "flops": 0.0, "bytes_total": 0.02},
    itemsizes={"lse_ref": 4},
    spec_itemsizes={"in0": 2, "out0": 2, "out1": 4},
    scratch_names={"k_buf": 0, "v_buf": 1},
    notes="Full-cache HND decode at 4 requests x 512 ctx: reads and "
          "FLOPs are exact; bytes_total carries a 2% band because "
          "the kernel also writes the f32 LSE block (B*Hkv*Gp*128*4 "
          "= +1.5% here) which the algorithmic formula folds into "
          "the outputs-written-once convention (LSE is consumed by "
          "the cascade merge, not a decode deliverable).",
))


def _decode_split_vmem_shapes(sc):
    ppc, hkv = sc["pages_per_chunk"], sc["num_kv_heads"]
    ps, d, gp = sc["page_size"], sc["head_dim"], sc["gp"]
    return {"k_buf": (2, ppc, hkv, ps, d), "v_buf": (2, ppc, hkv, ps, d),
            "q_ref": (hkv, gp, d), "o_ref": (hkv, gp, d),
            "lse_ref": (hkv, gp, 128)}


def _decode_split_adapter(sc):
    bd = decode_split_breakdown(
        sc["batch"], sc["ctx"], sc["num_qo_heads"],
        sc["num_kv_heads"], sc["head_dim"],
        num_splits=sc["num_splits"], page_size=sc["page_size"],
        pages_per_chunk=sc["pages_per_chunk"])
    per_tok = 2.0 * sc["num_qo_heads"] * 2 * sc["head_dim"]
    return {"bytes_read": bd["kv_bytes"] + bd["q_bytes"],
            "bytes_written": bd["merge_bytes"] / 2.0,
            "flops": bd["kv_tokens_launched"] * per_tok}


register_cost_launch(CostLaunchBinding(
    launcher="paged_decode_attention_split",
    family="decode_split",
    scenario=dict(batch=4, ctx=256, num_splits=2, num_units=8,
                  num_qo_heads=16, num_kv_heads=2, group=8, gp=8,
                  head_dim=128, page_size=16, pages_per_chunk=8),
    statics=dict(page_size=16, ppc=8, sm_scale=1.0,
                 logits_soft_cap=0.0, window_left=-1, num_kv_heads=2,
                 single_chunk=True),
    seeds=dict(pages_ref=0, kvlen_ref=256, req_ref=0, page0_ref=0,
               uklen_ref=128),
    vmem_shapes=_decode_split_vmem_shapes,
    adapter=_decode_split_adapter,
    compare={"bytes_read": 0.0, "bytes_written": 0.0, "flops": 0.0},
    itemsizes={"o_ref": 4, "lse_ref": 4},
    spec_itemsizes={"in0": 2, "out0": 4, "out1": 4},
    scratch_names={"k_buf": 0, "v_buf": 1},
    notes="4 requests x 256 ctx split 2 ways = 8 single-chunk work "
          "units. The kernel's share of decode_split is exact (tol "
          "0): reads = kv_bytes + q_bytes, writes = merge_bytes/2 "
          "(the f32 partial out+lse), flops = the whole-chunk KV "
          "walk. The OTHER half of the family's totals — the "
          "merge_bytes/2 read-back, the merged out_bytes write and "
          "the 2*merge_elems reduction FLOPs — belongs to the "
          "merge_states launch and is excluded here.",
))


def _fused_prefill_stats(sc):
    u = sc["num_units"]
    chunk = sc["ppc"] * sc["page_size"]
    cells = u * sc["bq"] * chunk
    return {"tiles": u, "units": u, "mxu_cells_total": cells,
            "mxu_cells_valid": cells}


def _fused_prefill_vmem_shapes(sc):
    bq, g, d = sc["bq"], sc["group"], sc["head_dim"]
    chunk = sc["ppc"] * sc["page_size"]
    return {"qbuf": (2, bq, g, d), "kbuf": (2, chunk, d),
            "vbuf": (2, chunk, d), "obuf": (bq, g, d),
            "acc_ref": (bq * g, d), "m_ref": (bq * g, 128),
            "l_ref": (bq * g, 128), "lsebuf": (bq, g, 128)}


def _fused_prefill_adapter(sc):
    c = fused_prefill_from_stats(
        _fused_prefill_stats(sc), block_q=sc["bq"],
        pages_per_chunk=sc["ppc"], page_size=sc["page_size"],
        num_qo_heads=sc["num_qo_heads"], num_kv_heads=sc["Hkv"],
        head_dim=sc["head_dim"], total_q=sc["num_units"] * sc["bq"])
    return {"bytes_read": c.bytes_read,
            "bytes_written": c.bytes_written, "flops": c.flops}


def _fused_prefill_implicit(sc):
    # every q/k/v/o operand is ANY (manual DMA); the spec lists are
    # extended under has_mask / return_lse / trace_events flags (all
    # pinned off by the scenario), hence statically unresolvable.
    return {"bytes_read": 0.0, "bytes_written": 0.0}


register_cost_launch(CostLaunchBinding(
    launcher="fused_paged_prefill",
    family="fused_prefill_from_stats",
    scenario=dict(Hkv=2, num_units=4, num_qo_heads=16, bq=128,
                  group=8, head_dim=128, page_size=16, ppc=8),
    statics=dict(bq=128, ppc=8, page_size=16, group=8, sm_scale=1.0,
                 logits_soft_cap=0.0, window_left=-1, causal=True,
                 has_mask=False, return_lse=False, trace_events=False),
    seeds=dict(qstart_ref=0, rowlo_ref=0, rowhi_ref=128, qpos0_ref=0,
               kvstart_ref=0, kvlen_ref=128, first_ref=1, wout_ref=1,
               qslot_ref=0, code_ref=0, pages_ref=0),
    vmem_shapes=_fused_prefill_vmem_shapes,
    adapter=_fused_prefill_adapter,
    compare={"bytes_read": 0.0, "bytes_written": 0.0, "flops": 0.0},
    itemsizes={"acc_ref": 4, "m_ref": 4, "l_ref": 4, "lsebuf": 4},
    implicit_fallback=_fused_prefill_implicit,
    notes="4 work units, each its own q tile and single full KV "
          "chunk (first=wout=1, CODE_FULL): the stats adapter's "
          "tiles/units/cells mirror the plan exactly, so parity is "
          "exact (tol 0) on reads, writes and MXU FLOPs.",
))


def _prefill_ingest_vmem_shapes(sc):
    bq, g, d = sc["bq"], sc["group"], sc["head_dim"]
    chunk = sc["ppc"] * sc["page_size"]
    return {"qbuf": (2, bq, g, d), "kbuf": (2, chunk, d),
            "vbuf": (2, chunk, d), "obuf": (bq, g, d),
            "kqbuf": (chunk, d), "vqbuf": (chunk, d),
            "acc_ref": (bq * g, d), "m_ref": (bq * g, 128),
            "l_ref": (bq * g, 128), "lsebuf": (bq, g, 128)}


def _prefill_ingest_adapter(sc):
    u = sc["num_units"]
    chunk = sc["ppc"] * sc["page_size"]
    total_q, total_kv = u * sc["bq"], u * chunk
    hq, hkv, d = sc["num_qo_heads"], sc["Hkv"], sc["head_dim"]
    c = prefill_ingest(
        total_q, total_kv, hq, hkv, d, stats=_fused_prefill_stats(sc),
        block_q=sc["bq"], pages_per_chunk=sc["ppc"],
        page_size=sc["page_size"])
    rope_flops = 6.0 * (total_q * hq + total_kv * hkv) * d
    quant_flops = 2.0 * 2.0 * total_kv * hkv * d
    return {"bytes_read": c.bytes_read,
            "bytes_written": c.bytes_written,
            "flops": c.flops - rope_flops - quant_flops}


register_cost_launch(CostLaunchBinding(
    launcher="fused_paged_prefill_ingest",
    family="prefill_ingest",
    scenario=dict(Hkv=2, num_units=4, num_qo_heads=16, bq=128,
                  group=8, head_dim=128, page_size=16, ppc=8),
    statics=dict(bq=128, ppc=8, page_size=16, group=8, head_dim=128,
                 sm_scale=1.0, logits_soft_cap=0.0, window_left=-1,
                 causal=True, has_mask=False, return_lse=False,
                 attend=True, rope_scale=1.0, rope_theta=10000.0,
                 rope_interleave=False, kv_quant="none", k_scale=1.0,
                 v_scale=1.0),
    seeds=dict(qstart_ref=0, rowlo_ref=0, rowhi_ref=128, qpos0_ref=0,
               kvstart_ref=0, kvlen_ref=128, first_ref=1, wout_ref=1,
               qslot_ref=0, code_ref=0, pages_ref=0, kvbase_ref=0,
               posoff_ref=0, wkv_ref=1),
    vmem_shapes=_prefill_ingest_vmem_shapes,
    adapter=_prefill_ingest_adapter,
    compare={"bytes_read": 0.0, "bytes_written": 0.0, "flops": 0.0},
    itemsizes={"acc_ref": 4, "m_ref": 4, "l_ref": 4, "lsebuf": 4},
    implicit_fallback=_fused_prefill_implicit,
    notes="4 single-chunk work units owning their cache pages "
          "(wkv=1): raw q/k/v stream in once, quantized pages write "
          "out once, so the stats-mode prefill_ingest reads/writes "
          "are exact (tol 0) — this is the binding whose read side "
          "deletes if the fused-ingest 'avoided Kc re-read' term "
          "regresses.  FLOPs compare MXU dots only: the family's "
          "rope (6/elt) and quantize (4/elt) terms are VPU work the "
          "MXU dot walk never sees, subtracted in the adapter.",
))
