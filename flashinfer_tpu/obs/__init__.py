"""flashinfer_tpu.obs — unified runtime observability.

The metrics half of the observability layer (the tracing half lives in
``profiler.py`` / ``api_logging.py`` / ``trace.py``; this package ties
all of them together).  Components:

- :mod:`~flashinfer_tpu.obs.registry` — process-wide thread-safe
  counters / gauges / fixed-bucket histograms, gated by
  ``FLASHINFER_TPU_METRICS`` (default off, no-op-cheap);
- :mod:`~flashinfer_tpu.obs.catalog` — the authoritative metric list
  (names, types, labels), cross-checked against the decorated public
  API by the L005 analysis pass;
- :mod:`~flashinfer_tpu.obs.export` — JSON snapshot, Prometheus text
  format, and chrome-trace merge of the op timeline;
- :mod:`~flashinfer_tpu.obs.bench_audit` — the self-auditing bench
  telemetry (row quality stamps vs BENCH_BANKED.md history, raw +
  roofline-fraction spaces);
- :mod:`~flashinfer_tpu.obs.hwspec` — the chip-spec registry (peak
  HBM/MXU/VMEM/ICI per generation; the single source of truth);
- :mod:`~flashinfer_tpu.obs.costmodel` — analytic FLOPs/bytes per op
  family (NOT imported here: the zero-overhead test pins that plain
  library use never loads it);
- :mod:`~flashinfer_tpu.obs.roofline` — cost x wall time x spec ->
  ``pct_roofline`` attribution + the ``obs perf`` report builder;
- ``python -m flashinfer_tpu.obs`` — ``report`` / ``doctor`` /
  ``perf`` CLI.

Call-site contract: the module-level helpers below apply the metrics
gate themselves, so instrumentation reads as one line
(``obs.counter_inc("plan.calls", wrapper=...)``) and costs one function
call + one env lookup when disabled.  Hot paths that need the gate
folded into an existing branch (the ``@flashinfer_api`` fast path) use
:func:`metrics_enabled` directly.

See docs/observability.md for the full catalog and env-var matrix.
"""

from __future__ import annotations

import contextlib

from flashinfer_tpu.obs import catalog
from flashinfer_tpu.obs.registry import (Registry, get, metrics_enabled,
                                         spans_enabled, steploop_enabled)

__all__ = [
    "Registry", "get", "metrics_enabled", "spans_enabled",
    "steploop_enabled", "catalog",
    "counter_inc", "gauge_set", "observe", "record_plan",
    "record_dropped_tokens", "snapshot", "reset",
    "span", "record_retrace", "state_signature", "diff_statics",
    "diff_state_sigs", "record_span",
    "request_begin", "prefill_chunk", "decode_step", "request_finish",
    "lifecycle_snapshot",
    "steploop_begin", "steploop_summary",
]

_declared = False


def _registry() -> Registry:
    global _declared
    reg = get()
    if not _declared:
        catalog.declare(reg)
        _declared = True
    return reg


def counter_inc(name: str, value: int = 1, **labels) -> int:
    """Gated counter increment; returns the new total (0 when gated
    off, so callers can't misread a disabled counter as progress)."""
    if not metrics_enabled():
        return 0
    return _registry().counter_inc(name, value, **labels)


def gauge_set(name: str, value: float, **labels) -> None:
    if metrics_enabled():
        _registry().gauge_set(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    if metrics_enabled():
        _registry().observe(name, value, **labels)


def record_plan(wrapper, *, replan: bool, padded_vs_actual=(),
                statics=None) -> None:
    """Plan-lifecycle wiring shared by the decode/prefill/attention
    wrappers: one call per plan() with the padding-waste pairs.

    ``padded_vs_actual``: iterable of ``(axis_name, padded, actual)``.
    ``statics``: the NEW frozen plan (dataclass/dict) — with the spans
    gate on, replans diff it against the wrapper's previous plan and
    attribute the retrace cause (obs.spans.note_plan); with the gate
    off it costs nothing and loads nothing.
    """
    if metrics_enabled():
        reg = _registry()
        name = type(wrapper).__name__
        reg.counter_inc("plan.calls", wrapper=name)
        if replan:
            reg.counter_inc("plan.replans", wrapper=name)
        for axis, padded, actual in padded_vs_actual:
            if padded > 0:
                reg.observe(
                    "plan.padding_waste_pct",
                    100.0 * (1.0 - float(actual) / float(padded)),
                    wrapper=name, axis=axis,
                )
    if statics is not None and spans_enabled():
        from flashinfer_tpu.obs import spans as _spans

        _spans.note_plan(wrapper, replan=replan, statics=statics)


def record_dropped_tokens(dropped, dispatch: str) -> int:
    """Count capacity-dropped MoE routes when the count is CONCRETE.

    ``fused_moe_ep`` computes ``dropped`` on device; under jit /
    shard_map it is a tracer and cannot be read host-side — those calls
    are skipped (the caller still gets the array via
    ``return_dropped=True``).  Eager calls (tests, debugging, capacity
    sizing sweeps) land in the counter.  Returns the count recorded
    (0 when gated off, skipped, or zero-drop).
    """
    if not metrics_enabled():
        return 0
    try:
        import jax

        if isinstance(dropped, jax.core.Tracer):
            return 0
        n = int(jax.numpy.sum(dropped))
    except Exception:
        return 0
    if n:
        _registry().counter_inc("moe.dropped_tokens", n, dispatch=dispatch)
    return n


def snapshot() -> dict:
    """JSON-ready snapshot of everything recorded (works regardless of
    the gate — you can read out what an enabled phase recorded after
    flipping the env var back off)."""
    return _registry().snapshot()


def reset() -> None:
    _registry().reset()


# ---------------------------------------------------------------------------
# Flight-recorder facade (obs.spans; FLASHINFER_TPU_SPANS gate).
# Every helper below checks the gate BEFORE importing the spans module,
# so plain library use never loads it (the subprocess pin in
# tests/test_obs_spans.py) and an instrumented call site reads as one
# line, the same contract as the metric helpers above.
# ---------------------------------------------------------------------------

_NULL_SPAN = contextlib.nullcontext()  # reusable + reentrant


def span(name: str, cat: str = "host", **attrs):
    """Nested host-side span context manager (no-op when gated off)."""
    if not spans_enabled():
        return _NULL_SPAN
    from flashinfer_tpu.obs import spans as _spans

    return _spans.span(name, cat, **attrs)


def state_signature(tree, names=None):
    """Trace signature (structure + shape/dtype) of a run-state pytree,
    or None when the spans gate is off — callers keep it per step and
    diff it on retrace (serve/step.py, parallel/plan.py)."""
    if not spans_enabled():
        return None
    from flashinfer_tpu.obs import spans as _spans

    return _spans.state_signature(tree, names)


def diff_statics(old, new):
    """Diff two plan signatures ({key: summary} dicts); {} when the
    spans gate is off (never imports the machinery, like every helper
    here)."""
    if not spans_enabled():
        return {}
    from flashinfer_tpu.obs import spans as _spans

    return _spans.diff_statics(old, new)


def diff_state_sigs(old, new, tree):
    """Diff two run-state signatures (obs.state_signature results),
    rendering readable leaf keys from ``tree`` — retrace-path only."""
    if not spans_enabled():
        return {}
    from flashinfer_tpu.obs import spans as _spans

    return _spans.diff_state_sigs(old, new, tree)


def record_retrace(wrapper_name: str, changed: dict) -> None:
    """Attribute one retrace: a flight-recorder span with the full
    static diff + `plan.retrace_cause{wrapper,key}` counts per key."""
    if not spans_enabled():
        return
    from flashinfer_tpu.obs import spans as _spans

    _spans.record_retrace(wrapper_name, changed)


def record_span(name: str, cat: str, t0: float, t1: float,
                **attrs) -> None:
    """Record a completed span over an already-measured [t0, t1]
    perf_counter window (no-op when gated off) — for call sites that
    time the work themselves, e.g. the serving steps' trace+compile
    span over a dispatch that traced."""
    if spans_enabled():
        from flashinfer_tpu.obs import spans as _spans

        _spans.record(name, cat, t0, t1, **attrs)


def request_begin(rid: str, **kw) -> None:
    if spans_enabled():
        from flashinfer_tpu.obs import spans as _spans

        _spans.request_begin(rid, **kw)


def prefill_chunk(rid: str, num_tokens: int, **kw) -> None:
    if spans_enabled():
        from flashinfer_tpu.obs import spans as _spans

        _spans.prefill_chunk(rid, num_tokens, **kw)


def decode_step(rid: str, num_tokens: int = 1, **kw) -> None:
    if spans_enabled():
        from flashinfer_tpu.obs import spans as _spans

        _spans.decode_step(rid, num_tokens, **kw)


def request_finish(rid: str, **kw):
    """Close a request's lifecycle; returns the per-request summary
    dict (tokens, ttft_us, tokens_per_s, ...) or None when gated off."""
    if not spans_enabled():
        return None
    from flashinfer_tpu.obs import spans as _spans

    return _spans.request_finish(rid, **kw)


def lifecycle_snapshot():
    """The lifecycle histograms (TTFT/TPOT/queue/tok-s) unflattened, or
    {} when gated off — the per-run summary examples/generate.py
    prints."""
    if not spans_enabled():
        return {}
    from flashinfer_tpu.obs import spans as _spans

    return _spans.lifecycle_snapshot()


# ---------------------------------------------------------------------------
# Step-loop flight deck facade (obs.steploop; FLASHINFER_TPU_STEPLOOP
# gate).  Same contract as the spans facade above: the gate is checked
# BEFORE the module is imported, so plain library use never loads the
# ledger (the zero-overhead subprocess pin in tests/test_steploop.py)
# and a gated-off step surface pays one function call + one env lookup
# + one `if tick is not None` branch per stamp.
# ---------------------------------------------------------------------------


def steploop_begin(surface: str):
    """Open a step-loop ticket for one serving-step dispatch, or None
    when the gate is off — call sites keep the ticket local and guard
    every stamp with ``if tick is not None`` (see serve/step.py)."""
    if not steploop_enabled():
        return None
    from flashinfer_tpu.obs import steploop as _steploop

    return _steploop.begin(surface)


def steploop_summary():
    """The aggregated host-loop report over the retained ledger window
    (host_frac, worst sub-phase, drift tails — obs.steploop.summarize),
    or None when the gate is off."""
    if not steploop_enabled():
        return None
    from flashinfer_tpu.obs import steploop as _steploop

    return _steploop.summarize()
