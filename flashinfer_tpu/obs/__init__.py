"""flashinfer_tpu.obs — unified runtime observability.

The metrics half of the observability layer (the tracing half lives in
``profiler.py`` / ``api_logging.py`` / ``trace.py``; this package ties
all of them together).  Components:

- :mod:`~flashinfer_tpu.obs.registry` — process-wide thread-safe
  counters / gauges / fixed-bucket histograms, gated by
  ``FLASHINFER_TPU_METRICS`` (default off, no-op-cheap);
- :mod:`~flashinfer_tpu.obs.catalog` — the authoritative metric list
  (names, types, labels), cross-checked against the decorated public
  API by the L005 analysis pass;
- :mod:`~flashinfer_tpu.obs.export` — JSON snapshot, Prometheus text
  format, and chrome-trace merge of the op timeline;
- :mod:`~flashinfer_tpu.obs.bench_audit` — the self-auditing bench
  telemetry (row quality stamps vs BENCH_BANKED.md history, raw +
  roofline-fraction spaces);
- :mod:`~flashinfer_tpu.obs.hwspec` — the chip-spec registry (peak
  HBM/MXU/VMEM/ICI per generation; the single source of truth);
- :mod:`~flashinfer_tpu.obs.costmodel` — analytic FLOPs/bytes per op
  family (NOT imported here: the zero-overhead test pins that plain
  library use never loads it);
- :mod:`~flashinfer_tpu.obs.roofline` — cost x wall time x spec ->
  ``pct_roofline`` attribution + the ``obs perf`` report builder;
- ``python -m flashinfer_tpu.obs`` — ``report`` / ``doctor`` /
  ``perf`` CLI.

Call-site contract: the module-level helpers below apply the metrics
gate themselves, so instrumentation reads as one line
(``obs.counter_inc("plan.calls", wrapper=...)``) and costs one function
call + one env lookup when disabled.  Hot paths that need the gate
folded into an existing branch (the ``@flashinfer_api`` fast path) use
:func:`metrics_enabled` directly.

See docs/observability.md for the full catalog and env-var matrix.
"""

from __future__ import annotations

from flashinfer_tpu.obs import catalog
from flashinfer_tpu.obs.registry import Registry, get, metrics_enabled

__all__ = [
    "Registry", "get", "metrics_enabled", "catalog",
    "counter_inc", "gauge_set", "observe", "record_plan",
    "record_dropped_tokens", "snapshot", "reset",
]

_declared = False


def _registry() -> Registry:
    global _declared
    reg = get()
    if not _declared:
        catalog.declare(reg)
        _declared = True
    return reg


def counter_inc(name: str, value: int = 1, **labels) -> int:
    """Gated counter increment; returns the new total (0 when gated
    off, so callers can't misread a disabled counter as progress)."""
    if not metrics_enabled():
        return 0
    return _registry().counter_inc(name, value, **labels)


def gauge_set(name: str, value: float, **labels) -> None:
    if metrics_enabled():
        _registry().gauge_set(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    if metrics_enabled():
        _registry().observe(name, value, **labels)


def record_plan(wrapper, *, replan: bool, padded_vs_actual=()) -> None:
    """Plan-lifecycle wiring shared by the decode/prefill/attention
    wrappers: one call per plan() with the padding-waste pairs.

    ``padded_vs_actual``: iterable of ``(axis_name, padded, actual)``.
    """
    if not metrics_enabled():
        return
    reg = _registry()
    name = type(wrapper).__name__
    reg.counter_inc("plan.calls", wrapper=name)
    if replan:
        reg.counter_inc("plan.replans", wrapper=name)
    for axis, padded, actual in padded_vs_actual:
        if padded > 0:
            reg.observe(
                "plan.padding_waste_pct",
                100.0 * (1.0 - float(actual) / float(padded)),
                wrapper=name, axis=axis,
            )


def record_dropped_tokens(dropped, dispatch: str) -> int:
    """Count capacity-dropped MoE routes when the count is CONCRETE.

    ``fused_moe_ep`` computes ``dropped`` on device; under jit /
    shard_map it is a tracer and cannot be read host-side — those calls
    are skipped (the caller still gets the array via
    ``return_dropped=True``).  Eager calls (tests, debugging, capacity
    sizing sweeps) land in the counter.  Returns the count recorded
    (0 when gated off, skipped, or zero-drop).
    """
    if not metrics_enabled():
        return 0
    try:
        import jax

        if isinstance(dropped, jax.core.Tracer):
            return 0
        n = int(jax.numpy.sum(dropped))
    except Exception:
        return 0
    if n:
        _registry().counter_inc("moe.dropped_tokens", n, dispatch=dispatch)
    return n


def snapshot() -> dict:
    """JSON-ready snapshot of everything recorded (works regardless of
    the gate — you can read out what an enabled phase recorded after
    flipping the env var back off)."""
    return _registry().snapshot()


def reset() -> None:
    _registry().reset()
