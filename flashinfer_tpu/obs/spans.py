"""Serving flight recorder: bounded ring-buffer span recorder +
request-lifecycle metering + retrace-cause attribution.

The tracing half of the request-level observability story (ISSUE 10):
where the registry answers "how often / how long" per op and the
profiler timeline answers "what op ran when", this module answers the
serving engine's questions — *what happened to request R* (queue wait,
TTFT, per-token cadence) and *why did the serving step retrace* (which
frozen static moved).  The reference amortizes exactly these host
costs through its plan/run lifecycle + CUDAGraph capture; on TPU the
recompile is the silent analog, so every retrace carries a structured
diff of the statics that changed (well-defined because L003 freezes
them host-side).

Contracts (same standard as the metrics registry):

- **Zero-overhead-by-default.**  Everything here is gated by
  ``FLASHINFER_TPU_SPANS`` (default off).  The gate itself lives in
  ``obs.registry.spans_enabled`` and the facade helpers in
  ``flashinfer_tpu.obs`` check it BEFORE importing this module — plain
  library use never loads the spans machinery at all (subprocess-pinned
  by ``tests/test_obs_spans.py``, the ``obs.costmodel`` precedent).
- **Bounded.**  The recorder is a ring buffer (capacity
  ``FLASHINFER_TPU_SPANS_CAP``, default 4096): a long-lived serving
  process records forever and keeps the most recent window — a flight
  recorder, not an unbounded log.  Overwrites are counted
  (``dropped()``), never silent.
- **Thread-safe.**  One lock per recorder around every mutation and
  drain; the nesting stack is thread-local, so executor threads nest
  their own spans without cross-talk (the profiler-timeline lesson).
- **One clock.**  Span timestamps are ``time.perf_counter`` values
  converted through ``profiler.perf_to_epoch_us`` at export time — the
  SAME anchor the op timeline uses, so the unified chrome trace nests
  spans and op events on one timeline (the epoch-vs-perf_counter skew
  fix, ISSUE 10 satellite).

Metric side effects (lifecycle histograms, ``plan.retrace_cause``
counters) write straight into the registry regardless of
``FLASHINFER_TPU_METRICS`` — once the spans gate is paid the slow path
is already bought, the same rule the api-log call index and the bench
auditor follow (registry.py module docstring).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

# categories a span may carry (the chrome-trace ``cat`` field); the
# request-lifecycle phases are the ISSUE 10 tentpole set, ``steploop``
# is the step-loop flight deck's lane category (obs.steploop emits its
# host/device lanes with it so unified-trace tooling can filter them)
SPAN_CATEGORIES_VALID = frozenset({
    "plan", "trace", "compile", "dispatch", "request", "prefill",
    "decode", "retrace", "host", "steploop",
})

# Serving-op -> span category: the span analog of
# ``costmodel.API_OP_COSTS`` — ``obs doctor`` flags any op in
# ``catalog.SERVING_OPS`` missing here (a serving op that opens no
# span), extending the L005 ships-observed rule to the flight recorder.
SPAN_CATEGORIES: Dict[str, str] = {
    "serve.step": "dispatch",
    "serve.mixed_step": "dispatch",
    "parallel.sharded_step": "dispatch",
    "engine.step": "dispatch",
    # tiered-KV movements (serve/kv_tier.py): host-side page copies
    "engine.kv_spill": "host",
    "engine.kv_restore": "host",
    "engine.kv_migrate": "host",
}

# small plan arrays get a content fingerprint in plan signatures (value
# changes of closed arrays force retraces too); big run-state arrays
# never do — retraces depend only on structure/shape/dtype
_FINGERPRINT_MAX_ELEMS = 4096
_SIG_DEPTH_MAX = 4


def _reg():
    """The declared global registry (the obs facade's, so the catalog
    bucket pins apply to the lifecycle histograms)."""
    from flashinfer_tpu import obs

    return obs._registry()


# ---------------------------------------------------------------------------
# the recorder
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Span:
    """One recorded host-side span (``dur == 0.0`` for instants)."""

    name: str
    cat: str
    ts: float  # time.perf_counter seconds at span start
    dur: float  # seconds
    tid: int
    span_id: int
    parent_id: Optional[int]
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name, "cat": self.cat, "ts": self.ts,
            "dur": self.dur, "tid": self.tid, "span_id": self.span_id,
            "parent_id": self.parent_id, "attrs": self.attrs,
        }


def _default_capacity() -> int:
    try:
        return max(int(os.environ.get("FLASHINFER_TPU_SPANS_CAP",
                                      "4096")), 1)
    except ValueError:
        return 4096


class SpanRecorder:
    """Process-wide bounded ring buffer of :class:`Span` records.

    ``record`` overwrites the oldest entry once ``capacity`` is
    reached; ``total`` keeps the lifetime count so ``dropped`` is
    always exact (the ring-bound pin in tests/test_obs_spans.py)."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(capacity) if capacity else _default_capacity()
        self._lock = threading.Lock()
        self._buf: List[Optional[Span]] = []
        self._total = 0
        self._next_id = 0

    def next_span_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def record(self, span: Span) -> None:
        with self._lock:
            if len(self._buf) < self.capacity:
                self._buf.append(span)
            else:
                self._buf[self._total % self.capacity] = span
            self._total += 1

    @property
    def total(self) -> int:
        with self._lock:
            return self._total

    def dropped(self) -> int:
        with self._lock:
            return max(0, self._total - self.capacity)

    def spans(self) -> List[Span]:
        """Oldest-to-newest copy of the retained window."""
        with self._lock:
            if self._total <= self.capacity:
                return list(self._buf)
            cut = self._total % self.capacity
            return list(self._buf[cut:]) + list(self._buf[:cut])

    def reset(self) -> None:
        with self._lock:
            self._buf = []
            self._total = 0


_recorder: Optional[SpanRecorder] = None
_recorder_lock = threading.Lock()
_tls = threading.local()


def get_recorder() -> SpanRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = SpanRecorder()
    return _recorder


def reset(capacity: Optional[int] = None) -> None:
    """Drop all recorded spans and in-flight request state; a non-None
    ``capacity`` rebuilds the ring at that size (tests)."""
    global _recorder
    with _recorder_lock:
        _recorder = SpanRecorder(capacity)
    with _req_lock:
        _requests.clear()


def drain() -> List[dict]:
    """The retained window as JSON-ready dicts, oldest first."""
    return [s.to_dict() for s in get_recorder().spans()]


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def record(name: str, cat: str, t0: float, t1: float, **attrs) -> Span:
    """Record a completed span [t0, t1] (perf_counter seconds).  The
    parent is whatever span is OPEN on this thread — a flat record from
    inside a ``span()`` region nests correctly without pushing."""
    rec = get_recorder()
    st = _stack()
    sp = Span(name=name, cat=cat, ts=float(t0),
              dur=max(float(t1) - float(t0), 0.0),
              tid=threading.get_ident(), span_id=rec.next_span_id(),
              parent_id=st[-1] if st else None, attrs=attrs)
    rec.record(sp)
    return sp


def record_instant(name: str, cat: str, t: Optional[float] = None,
                   **attrs) -> Span:
    t = time.perf_counter() if t is None else float(t)
    return record(name, cat, t, t, **attrs)


@contextlib.contextmanager
def span(name: str, cat: str = "host", **attrs) -> Iterator[None]:
    """Nested host-side span: pushes onto the thread-local stack so
    inner spans (and flat :func:`record` calls) parent under it."""
    rec = get_recorder()
    sid = rec.next_span_id()
    st = _stack()
    parent = st[-1] if st else None
    st.append(sid)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        st.pop()
        rec.record(Span(name=name, cat=cat, ts=t0, dur=t1 - t0,
                        tid=threading.get_ident(), span_id=sid,
                        parent_id=parent, attrs=attrs))


# ---------------------------------------------------------------------------
# static signatures + retrace-cause diffs
# ---------------------------------------------------------------------------


def _leaf_summary(x, fingerprint: bool) -> str:
    """One stable string per static: arrays render as ``dtype[shape]``
    (plus a content digest for small plan arrays when asked), scalars
    as their repr — the L003 statics in comparable form."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        s = f"{dtype}[{','.join(str(d) for d in shape)}]"
        if fingerprint:
            try:
                import numpy as np

                arr = np.asarray(x)
                if arr.size <= _FINGERPRINT_MAX_ELEMS:
                    s += "#" + hashlib.sha1(
                        arr.tobytes()).hexdigest()[:8]
            except Exception:
                pass  # a non-materializable leaf keeps shape/dtype only
        return s
    return repr(x)[:120]


def _walk(obj, prefix: str, out: Dict[str, str], depth: int,
          fingerprint: bool) -> None:
    if depth > _SIG_DEPTH_MAX:
        out[prefix or "<root>"] = repr(obj)[:120]
        return
    if getattr(obj, "shape", None) is not None \
            and getattr(obj, "dtype", None) is not None:
        out[prefix or "<root>"] = _leaf_summary(obj, fingerprint)
        return
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            key = f"{prefix}.{f.name}" if prefix else f.name
            _walk(getattr(obj, f.name), key, out, depth + 1, fingerprint)
        return
    if isinstance(obj, dict):
        for k, v in obj.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            _walk(v, key, out, depth + 1, fingerprint)
        return
    if isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _walk(v, f"{prefix}[{i}]", out, depth + 1, fingerprint)
        return
    out[prefix or "<root>"] = repr(obj)[:120]


def plan_signature(statics) -> Dict[str, str]:
    """Flatten a frozen plan (dataclass / dict / nested containers)
    into ``{dotted.field: summary}`` — small closed arrays carry a
    content digest because a value change of an HLO-embedded constant
    retraces just like a shape change."""
    out: Dict[str, str] = {}
    _walk(statics, "", out, 0, fingerprint=True)
    return out


@dataclasses.dataclass
class _StateSig:
    """Cheap per-step trace signature of a run-state pytree: the
    treedef plus raw ``(shape, dtype)`` per leaf — attribute reads
    only, NO string rendering on the hot serving path (readable keys
    are built lazily by :func:`diff_state_sigs`, on the rare retrace
    path).  Holds no array references, so keeping one per wrapper
    never pins a donated buffer."""

    treedef: object
    names: Optional[Tuple[str, ...]]
    leaves: tuple  # per-leaf (shape-tuple | None, dtype | repr)


def _leaf_sig(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), dtype)
    return (None, repr(leaf)[:80])


def state_signature(tree, names: Optional[Tuple[str, ...]] = None
                    ) -> _StateSig:
    """Trace signature of a RUN-state pytree: structure + shape/dtype
    per leaf, NO value fingerprints (jit retraces on structure/shape/
    dtype only; cache-scale arrays must never transfer host-side).
    ``names`` labels the components of a top-level tuple state in the
    readable diff."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return _StateSig(treedef=treedef, names=names,
                     leaves=tuple(_leaf_sig(l) for l in leaves))


def _render_leaf(sig_leaf: tuple) -> str:
    shape, dtype = sig_leaf
    if shape is None:
        return str(dtype)
    return f"{dtype}[{','.join(str(d) for d in shape)}]"


def _leaf_keys(tree, names: Optional[Tuple[str, ...]]) -> List[str]:
    """Readable per-leaf keys (``logits``, ``caches[0][1]``,
    ``params.layers[3]['q_proj']``...) — the expensive path-walk,
    done only when a retrace needs attributing."""
    import jax

    keys = []
    for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if names is not None and path \
                and isinstance(path[0], jax.tree_util.SequenceKey) \
                and path[0].idx < len(names):
            keys.append(names[path[0].idx]
                        + jax.tree_util.keystr(path[1:]))
        else:
            keys.append(jax.tree_util.keystr(path))
    return keys


def diff_state_sigs(old: Optional[_StateSig], new: _StateSig,
                    tree) -> Dict[str, Tuple[Any, Any]]:
    """Diff two run-state signatures; ``tree`` is the CURRENT state
    (same structure as ``new``), used to render readable keys on this
    rare path.  Same old-is-None contract as :func:`diff_statics`."""
    if old is None:
        return {"<unattributed: no prior signature>": (None, None)}
    if old.treedef != new.treedef:
        return {"pytree_structure": (str(old.treedef)[:120],
                                     str(new.treedef)[:120])}
    idxs = [i for i, (a, b) in enumerate(zip(old.leaves, new.leaves))
            if a != b]
    if not idxs:
        return {}
    keys = _leaf_keys(tree, new.names)
    return {keys[i]: (_render_leaf(old.leaves[i]),
                      _render_leaf(new.leaves[i])) for i in idxs}


def diff_statics(old: Optional[Dict[str, str]],
                 new: Dict[str, str]) -> Dict[str, Tuple[Any, Any]]:
    """``{key: (old, new)}`` for every static that moved.  ``old`` may
    be None (spans enabled after the previous trace) — the retrace is
    then real but unattributable, reported under one explicit key
    rather than a misleading everything-changed diff."""
    if old is None:
        return {"<unattributed: no prior signature>": (None, None)}
    changed: Dict[str, Tuple[Any, Any]] = {}
    for k in sorted(set(old) | set(new)):
        a, b = old.get(k, "<absent>"), new.get(k, "<absent>")
        if a != b:
            changed[k] = (a, b)
    return changed


def record_retrace(wrapper_name: str,
                   changed: Dict[str, Tuple[Any, Any]], *,
                   kind: str = "retrace") -> None:
    """One retrace (or replan-with-changed-statics) event: a span in
    the flight recorder carrying the full diff, plus one
    ``plan.retrace_cause{wrapper,key}`` count per changed static — the
    counters behind ``obs doctor``'s ranked top-retrace-causes table."""
    if not changed:
        changed = {"<unattributed: statics identical>": (None, None)}
    reg = _reg()
    for key in changed:
        reg.counter_inc("plan.retrace_cause", wrapper=wrapper_name,
                        key=key)
    record_instant(
        f"{wrapper_name}.{kind}", "retrace", wrapper=wrapper_name,
        kind=kind,
        changed={k: [str(a), str(b)] for k, (a, b) in changed.items()})


def note_plan(wrapper, *, replan: bool, statics) -> None:
    """Plan-lifecycle hook (called from ``obs.record_plan`` when the
    spans gate is on): record a plan span, and on a replan diff the new
    frozen statics against the previous plan's — the exact changed
    static is the recompile cause the next run() will pay for."""
    name = type(wrapper).__name__
    sig = plan_signature(statics)
    prev = getattr(wrapper, "_obs_plan_sig", None)
    record_instant(f"{name}.plan", "plan", wrapper=name, replan=replan)
    if replan and prev is not None:
        changed = diff_statics(prev, sig)
        if changed:
            record_retrace(name, changed, kind="replan")
    wrapper._obs_plan_sig = sig


def top_retrace_causes(snapshot: dict, limit: int = 10) -> List[dict]:
    """Rank the ``plan.retrace_cause`` counter cells:
    ``[{wrapper, key, count}]``, hottest first — the ``obs doctor``
    table that names what keeps retracing."""
    cells = snapshot.get("counters", {}).get("plan.retrace_cause", {})
    rows = []
    for flat, count in cells.items():
        labels = dict(kv.partition("=")[::2] for kv in
                      flat.strip("{}").split(",") if kv)
        rows.append({"wrapper": labels.get("wrapper", "?"),
                     "key": labels.get("key", "?"), "count": int(count)})
    rows.sort(key=lambda r: (-r["count"], r["wrapper"], r["key"]))
    return rows[:limit]


# ---------------------------------------------------------------------------
# request lifecycle (queue / TTFT / TPOT / tokens-per-sec)
# ---------------------------------------------------------------------------


class _Req:
    __slots__ = ("rid", "t_enqueue", "t_begin", "t_first_work",
                 "t_first_token", "t_last_token", "tokens",
                 "prefill_tokens")

    def __init__(self, rid, t_begin, t_enqueue):
        self.rid = rid
        self.t_begin = t_begin
        self.t_enqueue = t_enqueue
        self.t_first_work = None
        self.t_first_token = None
        self.t_last_token = None
        self.tokens = 0
        self.prefill_tokens = 0


_requests: Dict[str, _Req] = {}
_req_lock = threading.Lock()


def _now(now: Optional[float]) -> float:
    return time.perf_counter() if now is None else float(now)


def request_begin(rid: str, *, enqueue_t: Optional[float] = None,
                  now: Optional[float] = None) -> None:
    """Admit request ``rid``.  ``enqueue_t`` (perf_counter seconds) is
    when the request ARRIVED — queue time and TTFT measure from it;
    default: now (no queueing ahead of admission)."""
    t = _now(now)
    with _req_lock:
        _requests[str(rid)] = _Req(str(rid), t,
                                   t if enqueue_t is None
                                   else float(enqueue_t))
    record_instant("request.begin", "request", rid=str(rid))


def prefill_chunk(rid: str, num_tokens: int, *,
                  t0: Optional[float] = None,
                  now: Optional[float] = None) -> None:
    """One prompt chunk of ``num_tokens`` advanced for ``rid``; the
    first chunk closes the queue-time window
    (``lifecycle.queue_us`` = first work - enqueue)."""
    t = _now(now)
    with _req_lock:
        r = _requests.get(str(rid))
        if r is None:
            return
        first = r.t_first_work is None
        if first:
            r.t_first_work = t if t0 is None else float(t0)
            queue_us = (r.t_first_work - r.t_enqueue) * 1e6
        r.prefill_tokens += int(num_tokens)
    if first:
        _reg().observe("lifecycle.queue_us", max(queue_us, 0.0))
    record("request.prefill_chunk", "prefill",
           t if t0 is None else float(t0), t, rid=str(rid),
           num_tokens=int(num_tokens))


def decode_step(rid: str, num_tokens: int = 1, *,
                now: Optional[float] = None) -> None:
    """``num_tokens`` generated for ``rid`` at ``now``.  The first call
    observes TTFT (first token - enqueue); every later call observes
    TPOT as the inter-token gap ``(now - prev) / num_tokens``."""
    t = _now(now)
    ttft_us = tpot_us = queue_us = None
    with _req_lock:
        r = _requests.get(str(rid))
        if r is None:
            return
        if r.t_first_token is None:
            r.t_first_token = t
            ttft_us = (t - r.t_enqueue) * 1e6
            if r.t_first_work is None:
                # decode-only workload: the first token IS the first
                # work, so queue = first token - enqueue (matches the
                # catalog definition and request_finish's summary)
                r.t_first_work = t
                queue_us = max((t - r.t_enqueue) * 1e6, 0.0)
        else:
            tpot_us = (t - r.t_last_token) * 1e6 / max(int(num_tokens), 1)
        r.t_last_token = t
        r.tokens += int(num_tokens)
    reg = _reg()
    if ttft_us is not None:
        reg.observe("lifecycle.ttft_us", max(ttft_us, 0.0))
    if queue_us is not None:
        reg.observe("lifecycle.queue_us", queue_us)
    if tpot_us is not None:
        reg.observe("lifecycle.tpot_us", max(tpot_us, 0.0))
    record_instant("request.decode_step", "decode", t=t, rid=str(rid),
                   num_tokens=int(num_tokens))


def request_finish(rid: str, *, now: Optional[float] = None
                   ) -> Optional[dict]:
    """Close out ``rid``: observes ``lifecycle.tokens_per_s``
    (generated tokens / (finish - enqueue), the whole-request rate) and
    records the request-covering span.  Returns the per-request summary
    (None for an unknown rid)."""
    t = _now(now)
    with _req_lock:
        r = _requests.pop(str(rid), None)
    if r is None:
        return None
    dur = max(t - r.t_enqueue, 1e-9)
    tok_s = r.tokens / dur
    summary = {
        "rid": r.rid,
        "tokens": r.tokens,
        "prefill_tokens": r.prefill_tokens,
        "duration_us": dur * 1e6,
        "queue_us": (None if r.t_first_work is None
                     else (r.t_first_work - r.t_enqueue) * 1e6),
        "ttft_us": (None if r.t_first_token is None
                    else (r.t_first_token - r.t_enqueue) * 1e6),
        "tokens_per_s": tok_s,
    }
    if r.tokens:
        _reg().observe("lifecycle.tokens_per_s", tok_s)
    record("request", "request", r.t_enqueue, t, rid=r.rid,
           tokens=r.tokens, prefill_tokens=r.prefill_tokens,
           ttft_us=summary["ttft_us"])
    return summary


def lifecycle_snapshot() -> Dict[str, dict]:
    """The lifecycle histograms out of the registry snapshot, unflattened
    (``{metric: {count, p50, p99, ...}}``) — what ``examples/
    generate.py`` prints as the per-run summary."""
    snap = _reg().snapshot()
    out: Dict[str, dict] = {}
    for name in ("lifecycle.queue_us", "lifecycle.ttft_us",
                 "lifecycle.tpot_us", "lifecycle.tokens_per_s"):
        cells = snap.get("histograms", {}).get(name)
        if cells:
            # lifecycle histograms carry no labels: one cell
            out[name] = next(iter(cells.values()))
    return out
