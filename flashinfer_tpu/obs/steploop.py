"""Step-loop flight deck: host/device overlap profiler + drift watchdog.

The obs stack meters ops (PR 2/5) and requests (PR 10) but was blind to
the STEP LOOP itself — the host work between device steps that ROADMAP
item 4's pipeline refactor exists to hide.  This module records, for
every serving-step dispatch (``ServingEngine.step`` / ``ServingStep`` /
``MixedServingStep`` / ``ShardedServingStep``), one bounded ledger entry:

- named host sub-phase durations (the engine decomposes into ``admit``
  / ``schedule`` / ``assemble`` (schedule-array assembly) / ``lower``
  (kernel-plan lowering via ``build_engine_work_units``) / ``dispatch``
  (signature + host→device upload + the jitted call); the fused step
  wrappers record ``signature`` + ``dispatch``);
- the device execution window: JAX async dispatch returns before the
  device finishes, so the gate-ON path adds a completion probe
  (``block_until_ready`` — the measurement tax this mode pays) and
  stamps both edges on ``time.perf_counter``, the SAME clock base every
  obs recorder uses, so :func:`trace_events` merges the step lanes into
  the unified chrome trace through ``profiler.perf_to_epoch_us``;
- the derived ``gap_us`` — device idle between step N's completion and
  step N+1's dispatch return, per (surface, thread) lane — from which
  :func:`summarize` computes ``host_frac``, overlap efficiency, and the
  Amdahl projection ``1 / (1 - host_frac)``: the speedup CEILING the
  item-4 two-stage pipeline can buy by hiding host work;
- an online join against ``costmodel.predict_step_seconds``: call sites
  that can price their step pass ``predicted_s`` and the ledger keeps
  ``ratio = predicted_s / measured step wall`` — the
  ``predicted_vs_measured`` drift histogram that used to be a
  hand-driven bench join.

Zero-overhead-by-default: the ``FLASHINFER_TPU_STEPLOOP`` gate lives in
``registry.steploop_enabled`` and the ``obs.steploop_begin`` facade
checks it BEFORE importing this module (the spans/costmodel precedent;
subprocess-pinned by tests/test_steploop.py).  The ledger is a bounded
ring (``FLASHINFER_TPU_STEPLOOP_CAP``, default 2048): the newest N
steps are retained, overwrites are counted, never silent.

Every stamp method takes an optional ``now`` (perf_counter seconds) so
tests can drive hand-computed clocks through the exact production math.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

# Engine sub-phases in loop order (the docs/observability.md table).
ENGINE_PHASES = ("admit", "schedule", "assemble", "lower", "dispatch")

# Synthetic chrome-trace lanes: host sub-phases and the device window
# ride dedicated tids so they never collide with the per-thread span
# tracks (tid = thread ident) or the ops track (tid = 0).
TRACE_TID_HOST = 0x57E0
TRACE_TID_DEVICE = 0x57E1


def _reg():
    from flashinfer_tpu import obs

    return obs._registry()


def _default_capacity() -> int:
    try:
        return max(1, int(os.environ.get("FLASHINFER_TPU_STEPLOOP_CAP",
                                         "2048")))
    except ValueError:
        return 2048


class StepTicket:
    """One in-flight step measurement.

    Stamp protocol (all perf_counter seconds; contiguous — each
    ``mark`` closes the window since the previous stamp):

    ``begin() -> mark(phase)* -> dispatched() -> done() -> commit()``

    ``dispatched()`` closes the ``dispatch`` sub-phase and ends the
    host window; ``done()`` is the completion probe's return (the
    device-window end).  Idle ticks (``commit(idle=True)``) skip
    dispatched/done — an empty-schedule poll has no device lane, and
    the gap math must not mis-attribute it as device time.
    """

    __slots__ = ("surface", "tid", "t_begin", "_t_mark", "phases",
                 "t_dispatch", "t_done")

    def __init__(self, surface: str, now: Optional[float] = None):
        t = time.perf_counter() if now is None else float(now)
        self.surface = surface
        self.tid = threading.get_ident()
        self.t_begin = t
        self._t_mark = t
        self.phases: Dict[str, float] = {}
        self.t_dispatch: Optional[float] = None
        self.t_done: Optional[float] = None

    def mark(self, phase: str, now: Optional[float] = None) -> None:
        """Attribute the window since the previous stamp to ``phase``."""
        t = time.perf_counter() if now is None else float(now)
        self.phases[phase] = self.phases.get(phase, 0.0) \
            + (t - self._t_mark)
        self._t_mark = t

    def dispatched(self, now: Optional[float] = None) -> None:
        """Async dispatch returned: close the ``dispatch`` sub-phase,
        end the host window, open the device window."""
        t = time.perf_counter() if now is None else float(now)
        self.phases["dispatch"] = self.phases.get("dispatch", 0.0) \
            + (t - self._t_mark)
        self._t_mark = t
        self.t_dispatch = t

    def done(self, now: Optional[float] = None) -> None:
        """Completion probe returned: the device window's end."""
        self.t_done = time.perf_counter() if now is None else float(now)

    def commit(self, *, tokens: int = 0,
               predicted_s: Optional[float] = None,
               idle: bool = False, **attrs) -> dict:
        """Seal the ticket into the global ledger; returns the record."""
        return ledger().commit(self, tokens=tokens,
                               predicted_s=predicted_s, idle=idle,
                               attrs=attrs)


class StepLedger:
    """Bounded, thread-safe ring of per-step records (the SpanRecorder
    architecture): the newest ``capacity`` steps are retained,
    overwrites counted via ``dropped``.  ``gap_us`` is derived at
    commit time against the previous committed step of the SAME
    (surface, thread) lane — idle ticks neither produce a gap nor
    break the chain."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._buf: List[Optional[dict]] = [None] * self.capacity
        self._lock = threading.Lock()
        self._total = 0
        self._idle_total = 0
        # (surface, tid) -> t_done of the last committed non-idle step
        self._last_done: Dict[tuple, float] = {}

    @property
    def total(self) -> int:
        return self._total

    @property
    def idle_total(self) -> int:
        return self._idle_total

    def dropped(self) -> int:
        return max(0, self._total - self.capacity)

    def commit(self, ticket: StepTicket, *, tokens: int = 0,
               predicted_s: Optional[float] = None, idle: bool = False,
               attrs: Optional[dict] = None) -> dict:
        host_end = ticket.t_dispatch if ticket.t_dispatch is not None \
            else ticket._t_mark
        rec = {
            "surface": ticket.surface,
            "tid": ticket.tid,
            "idle": bool(idle),
            "tokens": int(tokens),
            "t_begin": ticket.t_begin,
            "t_dispatch": ticket.t_dispatch,
            "t_done": ticket.t_done,
            "phases": dict(ticket.phases),
            "host_us": (host_end - ticket.t_begin) * 1e6,
            "device_us": None,
            "gap_us": None,
            "predicted_s": predicted_s,
            "pred_vs_measured": None,
            "attrs": dict(attrs or {}),
        }
        if ticket.t_done is not None and ticket.t_dispatch is not None:
            rec["device_us"] = (ticket.t_done - ticket.t_dispatch) * 1e6
            if predicted_s is not None:
                wall = ticket.t_done - ticket.t_begin
                if wall > 0:
                    rec["pred_vs_measured"] = float(predicted_s) / wall
        with self._lock:
            if idle:
                self._idle_total += 1
            elif ticket.t_dispatch is not None:
                key = (ticket.surface, ticket.tid)
                prev_done = self._last_done.get(key)
                if prev_done is not None:
                    rec["gap_us"] = (ticket.t_dispatch - prev_done) * 1e6
                if ticket.t_done is not None:
                    self._last_done[key] = ticket.t_done
            rec["seq"] = self._total
            self._buf[self._total % self.capacity] = rec
            self._total += 1
        _observe_record(rec)
        return rec

    def records(self) -> List[dict]:
        """Retained records, oldest to newest."""
        with self._lock:
            if self._total <= self.capacity:
                return [r for r in self._buf[:self._total]]
            cut = self._total % self.capacity
            return [r for r in self._buf[cut:] + self._buf[:cut]]


def _observe_record(rec: dict) -> None:
    """Mirror one committed record into the metrics registry (the
    steploop gate is already paid — the bench-auditor rule: write
    regardless of FLASHINFER_TPU_METRICS, like the lifecycle
    histograms)."""
    reg = _reg()
    surface = rec["surface"]
    if rec["idle"]:
        reg.counter_inc("steploop.idle_ticks", surface=surface)
        return
    reg.counter_inc("steploop.steps", surface=surface)
    reg.observe("steploop.host_us", rec["host_us"], surface=surface)
    for phase, dur in rec["phases"].items():
        reg.observe("steploop.phase_us", dur * 1e6, surface=surface,
                    phase=phase)
    if rec["device_us"] is not None:
        reg.observe("steploop.device_us", rec["device_us"],
                    surface=surface)
    if rec["gap_us"] is not None:
        reg.observe("steploop.gap_us", max(rec["gap_us"], 0.0),
                    surface=surface)
    if rec["pred_vs_measured"] is not None:
        reg.observe("steploop.pred_vs_measured", rec["pred_vs_measured"],
                    surface=surface)


_LEDGER: Optional[StepLedger] = None
_LEDGER_LOCK = threading.Lock()


def ledger() -> StepLedger:
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = StepLedger(_default_capacity())
    return _LEDGER


def reset(capacity: Optional[int] = None) -> None:
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = StepLedger(capacity if capacity is not None
                             else _default_capacity())


def begin(surface: str, now: Optional[float] = None) -> StepTicket:
    """Open a ticket (callers reach this through ``obs.steploop_begin``,
    which owns the gate check)."""
    return StepTicket(surface, now=now)


# ---------------------------------------------------------------------------
# Derived views: summary + unified-trace lanes
# ---------------------------------------------------------------------------


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _dist(vals: List[float]) -> dict:
    s = sorted(vals)
    return {
        "count": len(s),
        "mean": (sum(s) / len(s)) if s else 0.0,
        "p50": _pct(s, 0.50),
        "p90": _pct(s, 0.90),
        "p99": _pct(s, 0.99),
        "max": s[-1] if s else 0.0,
    }


def summarize(records: Optional[List[dict]] = None) -> dict:
    """Aggregate the retained ledger window into the host-loop report
    (``obs doctor`` host_loop section; the selftest's acceptance
    input).

    ``host_frac`` is computed over steady-state lane pairs (records
    carrying a ``gap_us``, i.e. every step after the first per
    (surface, thread) lane): the fraction of the step cadence the
    device spends idle waiting on the host —
    ``Σgap / (Σgap + Σdevice)``.  The Amdahl projection
    ``1 / (1 - host_frac)`` is the speedup CEILING a perfect host/
    device pipeline (ROADMAP item 4) can reach; real wins land below
    it (the host work still exists, it just overlaps).
    """
    led = ledger()
    recs = led.records() if records is None else list(records)
    steps = [r for r in recs if not r["idle"]]
    idle = [r for r in recs if r["idle"]]
    out = {
        "steps": len(steps),
        "idle_ticks": len(idle),
        "total": led.total if records is None else len(recs),
        "dropped": led.dropped() if records is None else 0,
        "surfaces": sorted({r["surface"] for r in steps}),
    }
    if not steps:
        out.update(host_frac=None, overlap_efficiency=None,
                   amdahl_ceiling=None, negative_gaps=0,
                   missing_device_lane=0, phases={}, worst_phase=None,
                   unattributed_frac=None, drift=None)
        return out

    host = [r["host_us"] for r in steps]
    device = [r["device_us"] for r in steps if r["device_us"] is not None]
    out["host_us"] = _dist(host)
    out["device_us"] = _dist(device)
    out["missing_device_lane"] = sum(
        1 for r in steps if r["device_us"] is None)

    # steady-state pairs: gap_us present means the lane saw a prior
    # completed step; host_frac pairs each gap with its own step's
    # device window so the two sides cover the same cadence windows
    pairs = [r for r in steps
             if r["gap_us"] is not None and r["device_us"] is not None]
    gaps = [r["gap_us"] for r in pairs]
    out["gap_us"] = _dist(gaps)
    out["negative_gaps"] = sum(1 for g in gaps if g < 0.0)
    gap_sum = sum(max(g, 0.0) for g in gaps)
    dev_sum = sum(r["device_us"] for r in pairs)
    if pairs and (gap_sum + dev_sum) > 0:
        host_frac = gap_sum / (gap_sum + dev_sum)
        out["host_frac"] = host_frac
        out["overlap_efficiency"] = 1.0 - host_frac
        out["amdahl_ceiling"] = 1.0 / max(1.0 - host_frac, 1e-3)
    else:
        out["host_frac"] = None
        out["overlap_efficiency"] = None
        out["amdahl_ceiling"] = None

    phases: Dict[str, float] = {}
    for r in steps:
        for name, dur in r["phases"].items():
            phases[name] = phases.get(name, 0.0) + dur * 1e6
    out["phases"] = {k: round(v, 1) for k, v in sorted(phases.items())}
    out["worst_phase"] = max(phases, key=phases.get) if phases else None
    # host time the named sub-phases did NOT cover (a call site that
    # skipped a mark); contiguous marking keeps this ~0
    unattr = sum(r["host_us"] for r in steps) - sum(phases.values())
    total_host = max(sum(r["host_us"] for r in steps), 1e-9)
    out["unattributed_frac"] = max(unattr, 0.0) / total_host

    ratios = [r["pred_vs_measured"] for r in steps
              if r["pred_vs_measured"] is not None]
    out["drift"] = _dist(ratios) if ratios else None
    return out


def trace_events(records: Optional[List[dict]] = None) -> List[dict]:
    """Chrome-trace events for the retained ledger window, on the
    shared epoch clock base (``profiler.perf_to_epoch_us``) so
    ``export.to_unified_chrome_trace(..., extra_events=...)`` merges
    the step lanes with the span/op tracks: host sub-phases stack on
    the ``steploop host`` lane, device windows ride the ``steploop
    device`` lane, idle ticks land as instant events."""
    from flashinfer_tpu.profiler import perf_to_epoch_us

    pid = os.getpid()
    recs = ledger().records() if records is None else list(records)
    events: List[dict] = [
        {"name": "thread_name", "ph": "M", "pid": pid,
         "tid": TRACE_TID_HOST,
         "args": {"name": "steploop host (sub-phases)"}},
        {"name": "thread_name", "ph": "M", "pid": pid,
         "tid": TRACE_TID_DEVICE,
         "args": {"name": "steploop device (execution windows)"}},
    ]
    for r in recs:
        if r["idle"]:
            events.append({
                "name": f"{r['surface']}.idle", "ph": "i", "s": "t",
                "pid": pid, "tid": TRACE_TID_HOST, "cat": "steploop",
                "ts": perf_to_epoch_us(r["t_begin"]),
            })
            continue
        t = r["t_begin"]
        for phase, dur in r["phases"].items():
            events.append({
                "name": f"{r['surface']}.{phase}", "ph": "X",
                "pid": pid, "tid": TRACE_TID_HOST, "cat": "steploop",
                "ts": perf_to_epoch_us(t), "dur": max(dur, 0.0) * 1e6,
            })
            t += dur
        if r["t_dispatch"] is not None and r["t_done"] is not None:
            events.append({
                "name": f"{r['surface']}.device", "ph": "X",
                "pid": pid, "tid": TRACE_TID_DEVICE, "cat": "steploop",
                "ts": perf_to_epoch_us(r["t_dispatch"]),
                "dur": max(r["t_done"] - r["t_dispatch"], 0.0) * 1e6,
                "args": {"tokens": r["tokens"], "seq": r["seq"]},
            })
    return events
