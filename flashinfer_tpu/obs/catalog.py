"""The authoritative metric catalog.

Single source of truth for every metric the instrumentation layer may
emit: name, type, label schema, and what it means.  Three consumers:

- ``docs/observability.md`` documents from this table (kept in sync by
  hand; the doc test asserts the doc names every catalog entry);
- the L005 analysis pass (``analysis/obs_coverage.py``) fails CI when a
  ``@flashinfer_api``-decorated public op is missing from ``API_OPS`` —
  new public ops cannot ship unobserved;
- ``obs report`` / the exporters annotate output with ``help`` strings.

``API_OPS`` lists the op names of the decorated public surface (the
decorator's ``name or f.__qualname__``).  Adding a decorated function
means adding its name here (and to the doc) — that is the point.
"""

from __future__ import annotations

from typing import Dict, Tuple

from flashinfer_tpu.obs.registry import PERCENT_BUCKETS

# (type, labels, help)
METRICS: Dict[str, Tuple[str, Tuple[str, ...], str]] = {
    # -- @flashinfer_api decorator (api_logging.py) -----------------------
    "api.calls": (
        "counter", ("op",),
        "calls through each decorated public op (metrics gate on)"),
    "api.calls_total": (
        "counter", (),
        "instrumented-path call index across all ops — the registry-"
        "backed successor of api_logging's ad-hoc _call_counter; also "
        "the [N] index in FLASHINFER_TPU_LOGLEVEL output"),
    "api.dispatch_us": (
        "histogram", ("op",),
        "host dispatch time per call: wrapper entry to op return, no "
        "device sync (the dispatch-cost number VERDICT weak #4 wanted)"),
    # -- plan/run wrapper lifecycle (decode.py / prefill.py / attention.py)
    "plan.calls": (
        "counter", ("wrapper",),
        "plan() invocations per wrapper class"),
    "plan.replans": (
        "counter", ("wrapper",),
        "plan() calls that replaced a live plan (re-plan churn — each "
        "one risks a recompile if the geometry bucket moved)"),
    "plan.sm_scale_rebinds": (
        "counter", ("wrapper",),
        "frozen-plan sm_scale replacements (per-call k_scale/sm_scale "
        "overrides swapping a dataclasses.replace'd plan in and out)"),
    "plan.soft_cap_rebinds": (
        "counter", ("wrapper",),
        "frozen-plan logits_soft_cap replacements (BatchAttention.run "
        "honoring a per-run cap that differs from the planned one — "
        "the reference-parity rebind; each novel cap value compiles a "
        "fresh kernel variant, so a hot counter here means the caller "
        "should re-plan instead)"),
    "plan.padding_waste_pct": (
        "histogram", ("wrapper", "axis"),
        "planned-vs-actual padding waste per plan(): 100*(1 - "
        "actual/padded) for each padded axis (q/kv token axes, decode "
        "batch and page-table slots; the fused-prefill work-unit axes "
        "prefill_unit_rows / prefill_mxu_cells measure idle tile rows "
        "and idle MXU cells across the planned units — the number the "
        "ISSUE 3 tile packing exists to shrink)"),
    "plan.prefill_units_pruned": (
        "counter", ("wrapper",),
        "fused-prefill work units removed at plan time (provably "
        "all-masked: causal chunks above the diagonal, sliding-window "
        "chunks below the window, all-zero custom-mask windows) — MXU "
        "work the pipelined kernel never sees"),
    "plan.decode_splits": (
        "counter", ("wrapper", "splits"),
        "decode plan() split-KV selections by chosen partition factor "
        "(cost-model-guided, L009-feasibility-pruned; splits=1 means "
        "the unsplit kernel was predicted faster — a hot >1 label "
        "means the short-context split path is live)"),
    # -- compile-once serving step (serve/step.py) ------------------------
    "serve.step_retraces": (
        "counter", ("wrapper",),
        "fused serving-step traces beyond the first under a live plan "
        "(ServingStep / MixedServingStep) — the compile-once contract "
        "says this stays at ZERO: a non-zero count means the donated "
        "state's pytree structure, a shape, or a dtype moved between "
        "steps and every step is paying a retrace"),
    "plan.retrace_cause": (
        "counter", ("wrapper", "key"),
        "retrace-cause attribution (FLASHINFER_TPU_SPANS gate): one "
        "count per frozen static that changed when a serving step "
        "retraced under a live plan or a wrapper replan moved its "
        "statics — key names the exact static (the L003 staticness "
        "contract makes the diff well-defined); the ranked table in "
        "`obs doctor` reads these cells"),
    # -- request lifecycle (obs.spans; FLASHINFER_TPU_SPANS gate) ---------
    "lifecycle.queue_us": (
        "histogram", (),
        "request queue wait: enqueue to first work (first prefill "
        "chunk, or first token for decode-only requests)"),
    "lifecycle.ttft_us": (
        "histogram", (),
        "time to first token: enqueue to the first generated token "
        "(explicit TTFT_BUCKETS_US boundaries, 1 ms - 60 s)"),
    "lifecycle.tpot_us": (
        "histogram", (),
        "time per output token: inter-token gap per decode step after "
        "the first (explicit TPOT_BUCKETS_US boundaries, 100 us - 1 s)"),
    "lifecycle.tokens_per_s": (
        "histogram", (),
        "per-request generation rate at finish: generated tokens / "
        "(finish - enqueue)"),
    # -- continuous-batching engine (serve/engine.py) ---------------------
    "engine.requests": (
        "counter", (),
        "requests submitted to the serving engine"),
    "engine.finished": (
        "counter", (),
        "requests completed (max_new_tokens generated)"),
    "engine.steps": (
        "counter", (),
        "engine steps executed (one compiled rung dispatch each)"),
    "engine.step_tokens": (
        "counter", (),
        "scheduled tokens across all engine steps (decode lanes + "
        "prefill chunk tokens; padding excluded)"),
    "engine.prefix_hit_tokens": (
        "counter", (),
        "prompt tokens whose prefill was SKIPPED via a prefix-cache "
        "hit (full-page trie matches adopted at admission) — the "
        "numerator of the prefix hit rate; each hit's avoided FLOPs "
        "are priced by costmodel.engine_step into "
        "ServingEngine.flops_avoided"),
    "engine.prefix_miss_tokens": (
        "counter", (),
        "prompt tokens that had to prefill (no cached block) — the "
        "hit-rate denominator's other half"),
    "engine.evictions": (
        "counter", (),
        "prefix-cache pages LRU-evicted from the block pool (cache-"
        "only pages reclaimed to admit new requests)"),
    "engine.preemptions": (
        "counter", (),
        "running requests preempted-by-eviction (pages released, "
        "recompute-on-resume) so a higher-priority request could "
        "admit"),
    "engine.pool_pages_in_use": (
        "gauge", (),
        "block-pool pages with a non-zero refcount after the latest "
        "engine step (requests + prefix-cache ownership)"),
    "engine.pool_pages_free": (
        "gauge", (),
        "block-pool free-list depth after the latest engine step"),
    "engine.idle_steps": (
        "counter", (),
        "engine.step calls that returned on the empty-schedule early "
        "path (nothing runnable this tick: no dispatch, no device "
        "work) — previously a silent return; counted so host-gap math "
        "and step accounting never mis-attribute idle polls as device "
        "time (the steploop ledger records the same tick as idle)"),
    # -- step-loop flight deck (obs.steploop; FLASHINFER_TPU_STEPLOOP) ----
    "steploop.steps": (
        "counter", ("surface",),
        "serving-step dispatches recorded by the step-loop ledger, per "
        "step surface (ServingEngine / ServingStep / MixedServingStep "
        "/ ShardedServingStep)"),
    "steploop.idle_ticks": (
        "counter", ("surface",),
        "idle ticks recorded by the step-loop ledger (empty-schedule "
        "engine polls — no dispatch, no device lane)"),
    "steploop.host_us": (
        "histogram", ("surface",),
        "per-step host window: step entry to async-dispatch return "
        "(the sum of the named sub-phases)"),
    "steploop.phase_us": (
        "histogram", ("surface", "phase"),
        "named host sub-phase durations per step (engine: admit / "
        "schedule / assemble / lower / dispatch; fused step wrappers: "
        "signature / dispatch) — the host-gap decomposition ROADMAP "
        "item 4's pipeline refactor is judged against"),
    "steploop.device_us": (
        "histogram", ("surface",),
        "per-step device execution window: async-dispatch return to "
        "completion-probe return (the gate-ON path adds the probe — a "
        "per-step device sync this measurement mode pays)"),
    "steploop.gap_us": (
        "histogram", ("surface",),
        "device idle between step N completion and step N+1 dispatch "
        "per (surface, thread) lane — the host gap; host_frac = "
        "gap / (gap + device), Amdahl ceiling = 1/(1-host_frac)"),
    "steploop.pred_vs_measured": (
        "histogram", ("surface",),
        "online predicted-vs-measured drift: costmodel."
        "predict_step_seconds over the measured step wall (ratio; "
        "explicit DRIFT_RATIO_BUCKETS around the perfect-model 1.0) — "
        "the automatic form of the bench pred_step_ratio join"),
    # -- tiered KV: host offload + disaggregated handoff (serve/kv_tier.py)
    "engine.kv_tier.spills": (
        "counter", (),
        "requests whose KV page runs were offloaded to the host-RAM "
        "tier (preemption under spill_policy spill/auto, or an "
        "explicit offload_idle) — the restore path resumes them "
        "bit-exactly"),
    "engine.kv_tier.spill_bytes": (
        "counter", (),
        "KV bytes moved device -> host across all spills (pages at "
        "the cache's storage dtype — int8/fp8 caches spill at 1 "
        "byte/element, the compressed host format)"),
    "engine.kv_tier.restores": (
        "counter", (),
        "staged KV entries restored into fresh device pages at "
        "admission (host-tier spills AND in-flight kv_migrate "
        "handoffs — both ride the same restore path)"),
    "engine.kv_tier.restore_bytes": (
        "counter", (),
        "KV bytes moved host -> device across all restores"),
    "engine.kv_tier.migrations": (
        "counter", (),
        "prefill-pool -> decode-pool KV handoffs (kv_migrate; the "
        "disaggregated serving collective, ICI-priced by "
        "costmodel.kv_migrate)"),
    "engine.kv_tier.migrate_bytes": (
        "counter", (),
        "KV payload bytes handed prefill -> decode across all "
        "migrations (== the predicted ICI wire bytes at hops=1)"),
    "engine.kv_tier.recomputes": (
        "counter", (),
        "preempted/offloaded requests resumed by RECOMPUTE instead of "
        "restore (spill disabled, policy chose recompute, or the host "
        "store LRU-evicted the entry) — the tier's miss attribution; "
        "a spill-policy bench asserts this stays ZERO when the host "
        "tier absorbed every resume"),
    "engine.kv_tier.host_evictions": (
        "counter", (),
        "host-store entries LRU-evicted under capacity pressure (each "
        "one downgrades that request's resume to recompute — never "
        "silent)"),
    "engine.kv_tier.host_pages": (
        "gauge", (),
        "KV pages currently resident in the host-RAM tier"),
    "engine.kv_tier.host_bytes": (
        "gauge", (),
        "bytes currently resident in the host-RAM tier (capacity is "
        "EngineConfig.host_gib — the engine.host_gib knob)"),
    # -- trace.py solution substitution -----------------------------------
    "trace.solution_hits": (
        "counter", ("op",),
        "TRACE_APPLY calls routed to a registered substitute solution"),
    "trace.solution_misses": (
        "counter", ("op",),
        "TRACE_APPLY calls with no matching solution (fell through to "
        "the default implementation)"),
    # -- fused MoE expert parallelism -------------------------------------
    "moe.dropped_tokens": (
        "counter", ("dispatch",),
        "capacity-dropped (token, choice) routes observed at EAGER "
        "fused_moe_ep calls (inside jit the count is a tracer and is "
        "skipped — use return_dropped=True there)"),
    "moe.ep_a2a_bytes": (
        "counter", ("dispatch",),
        "EP all_to_all payload bytes per TRACED fused_moe_ep call "
        "(dispatch + combine buffers; shapes are static, so this is "
        "the per-call traffic of the compiled program — for "
        "alltoall_exact it is the per-ROUND payload, rounds being "
        "data-dependent).  Joins against the predicted ICI bytes of "
        "costmodel.ep_all_to_all"),
    # -- comm collectives --------------------------------------------------
    "comm.allreduce_bytes": (
        "counter", ("axis",),
        "allreduce payload bytes per TRACED comm.allreduce/"
        "allreduce_fusion call (static shapes: the per-call traffic of "
        "the compiled program; wire bytes = 2(p-1)/p x payload, "
        "costmodel.collective).  Joins measured collective traffic "
        "against the roofline's predicted ICI bytes"),
    # -- serving-loop phase decomposition (bench.py) ----------------------
    "serving.phase_us": (
        "histogram", ("phase",),
        "per-step cost of each serving-loop phase from the bench.py "
        "micro-loop decomposition (attention / kv_append / moe_or_mlp / "
        "norm_rope / sampling / lm_head / residual)"),
    # -- bench row quality audit (obs.bench_audit) ------------------------
    "bench.rows": (
        "counter", ("phase", "quality"),
        "bench rows emitted per phase, by audited quality stamp "
        "(ok | degraded | poison)"),
    # -- hardware bring-up observatory (obs.bringup) ----------------------
    "bringup.rungs": (
        "counter", ("outcome",),
        "smoke-ladder rungs executed by `obs bringup`, by outcome "
        "(pass | fail | wedge) — a wedge means the rung was "
        "quarantined and the session halted for --resume"),
}

# histograms whose values are percentages, not microseconds
PERCENT_HISTOGRAMS = ("plan.padding_waste_pct",)

# Explicit request-lifecycle bucket boundaries (ISSUE 10 satellite):
# TTFT spans interactive-serving first-token latencies (1 ms) out to
# the multi-second cold-compile outliers; TPOT spans per-token decode
# cadences (100 us) up to a pathological 1 s/token.  Log-spaced like
# DEFAULT_BUCKETS_US so interpolated p50/p99 stay tight at the scales
# serving SLOs quote.
TTFT_BUCKETS_US: Tuple[float, ...] = (
    1e3, 2e3, 5e3, 1e4, 2e4, 5e4, 1e5, 2e5, 5e5,
    1e6, 2e6, 5e6, 1e7, 2e7, 6e7,
)
TPOT_BUCKETS_US: Tuple[float, ...] = (
    100.0, 200.0, 500.0, 1e3, 2e3, 5e3, 1e4, 2e4, 5e4,
    1e5, 2e5, 5e5, 1e6,
)
TOKENS_PER_S_BUCKETS: Tuple[float, ...] = (
    0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1e3, 2e3, 5e3, 1e4,
)

_LIFECYCLE_BUCKETS = {
    "lifecycle.ttft_us": TTFT_BUCKETS_US,
    "lifecycle.tpot_us": TPOT_BUCKETS_US,
    "lifecycle.tokens_per_s": TOKENS_PER_S_BUCKETS,
    # lifecycle.queue_us keeps DEFAULT_BUCKETS_US (host-latency scale)
}

# Drift-ratio boundaries for steploop.pred_vs_measured (predicted /
# measured step wall): log-spaced around the perfect-model 1.0 so both
# "model optimistic" (<1) and "model pessimistic" (>1) tails resolve.
# Defined HERE (not in obs.steploop) so declaring buckets never imports
# the ledger machinery — the zero-overhead pin covers catalog.declare.
DRIFT_RATIO_BUCKETS: Tuple[float, ...] = (
    0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 0.8, 0.9, 1.0,
    1.1, 1.25, 2.0, 5.0, 10.0, 20.0, 100.0,
)


def declare(registry) -> None:
    """Pin non-default bucket boundaries on `registry`."""
    for name in PERCENT_HISTOGRAMS:
        registry.declare_histogram(name, PERCENT_BUCKETS)
    for name, buckets in _LIFECYCLE_BUCKETS.items():
        registry.declare_histogram(name, buckets)
    registry.declare_histogram("steploop.pred_vs_measured",
                               DRIFT_RATIO_BUCKETS)


# Decorated public-API op names (decorator name= or f.__qualname__).
# L005 (analysis/obs_coverage.py) fails CI when a decorated function is
# absent from this set.
API_OPS = frozenset({
    # activation.py
    "silu_and_mul", "gelu_and_mul", "gelu_tanh_and_mul",
    # norm.py
    "rmsnorm", "gemma_rmsnorm", "fused_add_rmsnorm",
    "gemma_fused_add_rmsnorm",
    # rope.py
    "apply_rope", "apply_llama31_rope", "rope_quantize_fp8",
    "mla_rope_quantize_fp8", "rope_quantize_fp8_append_paged_kv_cache",
    # page.py
    "append_paged_kv_cache",
    # decode.py / prefill.py
    "single_decode_with_kv_cache", "single_prefill_with_kv_cache",
    # sampling.py
    "sampling_from_probs", "sampling_from_logits",
    "top_p_sampling_from_probs", "top_k_sampling_from_probs",
    "min_p_sampling_from_probs", "top_k_top_p_sampling_from_probs",
    # serve/step.py (the compile-once fused serving steps)
    "serve.step", "serve.mixed_step",
    # serve/engine.py (the continuous-batching engine step)
    "engine.step",
    # serve/kv_tier.py (the tiered-KV movements: host spill/restore +
    # the disaggregated prefill->decode handoff)
    "engine.kv_spill", "engine.kv_restore", "engine.kv_migrate",
    # parallel/plan.py (the mesh-sharded fused serving step)
    "parallel.sharded_step",
})

# The serving subset of the decorated surface: ops that drive whole
# serving steps and therefore MUST open a flight-recorder span
# (obs.spans.SPAN_CATEGORIES declares each one's category).  ``obs
# doctor`` flags any op listed here that spans.SPAN_CATEGORIES does not
# cover — the span-layer extension of the L005 ships-observed rule: a
# new serving op cannot silently ship untraceable.
SERVING_OPS = frozenset({
    "serve.step", "serve.mixed_step", "parallel.sharded_step",
    "engine.step",
    "engine.kv_spill", "engine.kv_restore", "engine.kv_migrate",
})
