"""Environment report (reference ``flashinfer/collect_env.py``)."""

from __future__ import annotations

import os
import platform
import sys
from typing import Dict


def collect_env() -> Dict[str, str]:
    info = {
        "python": sys.version.split()[0],
        "platform": platform.platform(),
    }
    try:
        import jax

        info["jax"] = jax.__version__
        try:
            info["backend"] = jax.default_backend()
            devs = jax.devices()
            info["devices"] = f"{len(devs)} x {devs[0].device_kind}"
        except Exception as e:  # device init can fail off-accelerator
            info["devices"] = f"<unavailable: {type(e).__name__}>"
    except ImportError:
        info["jax"] = "<not installed>"
    for mod in ("jaxlib", "flax", "numpy"):
        try:
            info[mod] = __import__(mod).__version__
        except Exception:
            info[mod] = "<not installed>"
    from flashinfer_tpu.version import __version__

    info["flashinfer_tpu"] = __version__
    for k, v in os.environ.items():
        if k.startswith("FLASHINFER_TPU_") or k in ("JAX_PLATFORMS", "XLA_FLAGS"):
            info[f"env:{k}"] = v
    return info


def main() -> None:
    for k, v in collect_env().items():
        print(f"{k:>24}: {v}")


if __name__ == "__main__":
    main()
