"""Shared utilities: enums, layouts, rounding, backend gating.

TPU-native re-design of the reference's ``flashinfer/utils.py`` (enums and
layout canonicalization at utils.py:281, backend gating decorators at
utils.py:1070-1153).  Nothing CUDA-specific survives: "compute capability"
gates become TPU-generation gates, and torch custom-op registration is
unnecessary (jit/abstract-eval come free with JAX).
"""

from __future__ import annotations

import enum
import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp


class PosEncodingMode(enum.IntEnum):
    """Positional encoding applied inside attention kernels.

    Mirrors the reference enum (``flashinfer/utils.py:281``)."""

    NONE = 0
    ROPE_LLAMA = 1
    ALIBI = 2


class MaskMode(enum.IntEnum):
    """Attention mask mode (reference ``flashinfer/utils.py``)."""

    NON_CAUSAL = 0
    CAUSAL = 1
    CUSTOM = 2


class TensorLayout(enum.IntEnum):
    """KV tensor layout: NHD = [seq, heads, dim], HND = [heads, seq, dim]."""

    NHD = 0
    HND = 1


def atomic_write_text(path, text: str) -> None:
    """Write-then-rename so concurrent readers of shared cache files
    (autotuner tactics, quarantine list, compile-status registry) never see
    a torn write — the TPU-side analogue of the reference's compile-cache
    race protections (tests/utils/test_load_cubin_compile_race_condition.py)."""
    import os
    import tempfile
    from pathlib import Path

    import contextlib

    import time

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # opportunistic sweep: a SIGKILL between mkstemp and os.replace leaks
    # the temp file; age-gate so a concurrent writer's live temp survives
    with contextlib.suppress(OSError):
        cutoff = time.time() - 3600.0
        for stale in path.parent.glob(path.name + ".tmp*"):
            with contextlib.suppress(OSError):
                if stale.stat().st_mtime < cutoff:
                    stale.unlink()
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def check_kv_layout(kv_layout: str) -> TensorLayout:
    if kv_layout not in ("NHD", "HND"):
        raise KeyError(f"Invalid kv_layout {kv_layout!r}, expected 'NHD' or 'HND'")
    return TensorLayout[kv_layout]


def check_pos_encoding_mode(pos_encoding_mode: str) -> PosEncodingMode:
    if pos_encoding_mode not in PosEncodingMode.__members__:
        raise KeyError(
            f"Invalid pos_encoding_mode {pos_encoding_mode!r}, expected one of "
            f"{list(PosEncodingMode.__members__)}"
        )
    return PosEncodingMode[pos_encoding_mode]


# ---------------------------------------------------------------------------
# Rounding / shape helpers
# ---------------------------------------------------------------------------


def cdiv(a: int, b: int) -> int:
    """Ceiling division."""
    return -(a // -b)


def round_up(a: int, b: int) -> int:
    """Round ``a`` up to a multiple of ``b``."""
    return cdiv(a, b) * b


def next_power_of_two(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def min_sublane(dtype: Any) -> int:
    """Minimum second-to-last tile dim for a dtype on TPU (lane dim is 128)."""
    itemsize = jnp.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


LANE = 128


# ---------------------------------------------------------------------------
# Platform / backend gating
# ---------------------------------------------------------------------------


@functools.cache
def is_tpu() -> bool:
    return jax.default_backend() == "tpu"


@functools.cache
def tpu_generation() -> int:
    """TPU generation number (4, 5, 6, ...); -1 when not running on TPU.

    The TPU analogue of the reference's compute-capability gates
    (``flashinfer/utils.py:1070``)."""
    if not is_tpu():
        return -1
    kind = jax.devices()[0].device_kind.lower()
    for tok in kind.replace("v", " v").split():
        if tok.startswith("v") and tok[1:2].isdigit():
            return int(tok[1])
    return 4


def use_interpret() -> bool:
    """Whether Pallas kernels should run in interpreter mode.

    True off-TPU (CPU CI — the stand-in for the reference's fake backends,
    SURVEY §4) or when FLASHINFER_TPU_INTERPRET=1."""
    from flashinfer_tpu import env

    return env.force_interpret() or not is_tpu()


_dropped_compiler_params: set = set()


def tpu_compiler_params(**kw):
    """Version-portable ``pltpu.CompilerParams``: JAX renamed
    ``TPUCompilerParams`` -> ``CompilerParams`` across the versions this
    library supports, and a hard reference to either name makes every
    Pallas launch raise AttributeError on the other side of the rename.

    Fields the installed version's dataclass doesn't declare are dropped
    WITH a once-per-field warning, not fatally: on the old side of the
    rename there is no way to express them at all, and a crashed launch
    is strictly worse than a missing hint.  The drop is not always
    numerics-neutral — losing ``has_side_effects`` un-marks an
    effectful kernel and lets XLA DCE it when its outputs go unused —
    so the warning names the field and the risk instead of hiding it."""
    import dataclasses
    import logging

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    known = {f.name for f in dataclasses.fields(cls)}
    for k in kw:
        if k not in known and (cls.__name__, k) not in \
                _dropped_compiler_params:
            _dropped_compiler_params.add((cls.__name__, k))
            logging.getLogger("flashinfer_tpu").warning(
                "dropping Pallas compiler param %r: this JAX's %s does "
                "not declare it (known: %s). If this is "
                "'has_side_effects', ensure every launch's outputs are "
                "consumed or the kernel may be dead-code-eliminated.",
                k, cls.__name__, sorted(known))
    return cls(**{k: v for k, v in kw.items() if k in known})


def jax_shard_map(f, **kw):
    """Version-portable ``jax.shard_map``: the API graduated from
    ``jax.experimental.shard_map.shard_map`` (where the replication
    check is spelled ``check_rep``) to ``jax.shard_map`` (spelled
    ``check_vma``).  Callers use the graduated spelling; this adapter
    translates when running on the experimental version."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):
        params = {}
    if "check_vma" in kw and "check_vma" not in params \
            and "check_rep" in params:
        kw["check_rep"] = kw.pop("check_vma")
    return sm(f, **kw)


def lax_axis_size(axis_name: str) -> int:
    """Static size of a named mesh axis from inside shard_map/pmap —
    ``jax.lax.axis_size`` where it exists, else the classic
    ``psum(1, axis)`` spelling (which constant-folds to a Python int)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


# Reference attention-backend names (``flashinfer/utils.py:522``
# determine_attention_backend picks fa2/fa3/trtllm-gen/... per CUDA arch;
# wrapper ctors accept them verbatim, e.g. mla/_core.py:1397 backend=).
# They select CUDA codegen variants with no TPU meaning and are
# numerics-neutral, so a verbatim reference call resolves like "auto" —
# the north-star contract of a tpu backend registered alongside the
# reference's backend names.
_REFERENCE_BACKEND_NAMES = frozenset({
    "fa2", "fa3", "fa2_tc", "trtllm-gen", "trtllm-gen-native", "trtllm",
    "cutlass", "cudnn", "xqa", "cute-dsl", "cute_dsl", "tpu",
})


def normalize_backend(backend: str) -> str:
    """Map reference CUDA backend names to "auto"; leave TPU-native
    choices ("auto"/"pallas"/"xla"/"pallas_fused") untouched."""
    if isinstance(backend, str) and backend.lower() in _REFERENCE_BACKEND_NAMES:
        return "auto"
    return backend


def resolve_backend(backend: str, op: str = "") -> str:
    """Resolve a per-op backend choice, honoring the global override.

    Mirrors the reference's ``determine_attention_backend``
    (``flashinfer/utils.py:522``) collapsed to the TPU world: "pallas"
    (primary, Mosaic kernels) or "xla" (pure-jnp reference/fallback).
    Reference backend names (fa2/fa3/trtllm-gen/...) are accepted and
    resolve like "auto".
    """
    from flashinfer_tpu import env

    backend = normalize_backend(backend)
    override = env.backend_override()
    if backend == "auto":
        if override != "auto":
            return override
        # off-TPU, interpret-mode Pallas is a debugger, not a backend:
        # auto picks the compiled XLA path there
        return "pallas" if is_tpu() else "xla"
    if backend not in ("pallas", "xla"):
        raise ValueError(f"Unknown backend {backend!r} for op {op or '<unnamed>'}")
    return backend


class GenerationRequirementError(RuntimeError):
    pass


def tpu_requirement(min_generation: int) -> Callable:
    """Declarative hardware gate, mirroring ``@supported_compute_capability``
    (``flashinfer/utils.py:1070``): raises unless running on TPU >= gen or
    off-TPU (interpret/testing mode)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if is_tpu() and tpu_generation() < min_generation:
                raise GenerationRequirementError(
                    f"{fn.__name__} requires TPU v{min_generation}+, "
                    f"running on v{tpu_generation()}"
                )
            return wrapper.__wrapped__(*args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------------
# dtype helpers
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "half": jnp.float16,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "fp8_e4m3": jnp.float8_e4m3fn,
    "fp8_e5m2": jnp.float8_e5m2,
    "int8": jnp.int8,
    "int32": jnp.int32,
}


def canonicalize_dtype(dtype: Any) -> jnp.dtype:
    """Canonicalize a dtype spec (string alias or jnp dtype) to jnp.dtype.

    Reference: ``flashinfer/utils.py`` dtype canonicalization."""
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise KeyError(f"Unknown dtype alias {dtype!r}")
        return jnp.dtype(_DTYPE_ALIASES[dtype])
    return jnp.dtype(dtype)


def reject_unsupported(name: str, **kw) -> None:
    """Shared loud-rejection helper for reference-surface adapters: any
    kwarg that arrived non-None/non-False names a semantic this backend
    does not implement — never silently dropped.  (compat_calls.py keeps
    its own numerics-specific variant with a richer message; both exist
    to enforce the same no-silent-drops policy.)"""
    for k, v in kw.items():
        if v is not None and v is not False:
            raise ValueError(
                f"TPU backend: {name} does not implement {k}; see the "
                "docstring for the supported surface and alternatives"
            )


def fold_scalar_scale(x, name: str) -> Optional[float]:
    """Fold a float-or-single-element-tensor scale to a Python float;
    non-scalar tensors (per-head / per-block fp8 scale factors) are a
    different numerics regime and are rejected loudly.  Shared by the
    pre-compiled attention entries (aliases.py) and
    single_prefill_with_kv_cache's reference scale kwargs."""
    if x is None:
        return None
    if isinstance(x, (int, float)):
        return float(x)
    import numpy as np

    arr = np.asarray(x)
    if arr.size != 1:
        raise ValueError(
            f"TPU backend: {name} must be a float or single-element "
            f"tensor; got shape {arr.shape}. Per-head/per-block scale "
            "factors are not folded here — dequantize the cache "
            "explicitly or use the fp8/int8 decode path "
            "(BatchDecodeWithPagedKVCacheWrapper kv dtypes)"
        )
    return float(arr.reshape(()))


def get_sm_scale(head_dim: int, sm_scale: Optional[float]) -> float:
    return sm_scale if sm_scale is not None else 1.0 / float(head_dim) ** 0.5


def to_nhd(x: jax.Array, kv_layout: str) -> jax.Array:
    """Convert a [.., H, N, D] ("HND") array to [.., N, H, D] ("NHD")."""
    if check_kv_layout(kv_layout) == TensorLayout.HND:
        return jnp.swapaxes(x, -3, -2)
    return x


# the NHD<->HND swap is an involution, so the inverse is the same transform
from_nhd = to_nhd


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


def get_seq_lens(
    kv_indptr: jax.Array, kv_last_page_len: jax.Array, page_size: int
) -> jax.Array:
    """Per-request KV sequence lengths from paged indptr + last-page lengths.

    Reference: ``flashinfer/page.py`` ``get_seq_lens``."""
    pages = kv_indptr[1:] - kv_indptr[:-1]
    return jnp.where(
        pages > 0, (pages - 1) * page_size + kv_last_page_len, jnp.zeros_like(pages)
    )


def expand_dims_to(x: jax.Array, ndim: int) -> jax.Array:
    while x.ndim < ndim:
        x = x[..., None]
    return x


# ---------------------------------------------------------------------------
# Reference utils.py surface (flashinfer/utils.py): small helpers, enums,
# error classes, and the hardware/backend predicate family mapped to TPU
# truth.  test_compat_surface.py machine-checks these names.
# ---------------------------------------------------------------------------

import enum as _enum
import logging as _logging
import math as _math


class LogLevel(_enum.IntEnum):
    """Reference logging levels (utils.py LogLevel)."""

    DEBUG = _logging.DEBUG
    INFO = _logging.INFO
    WARNING = _logging.WARNING
    ERROR = _logging.ERROR


def set_log_level(level) -> None:
    """Set the library logger level (reference set_log_level)."""
    if isinstance(level, str):
        level = getattr(_logging, level.upper())
    _logging.getLogger("flashinfer_tpu").setLevel(int(level))


def get_logging_module():
    return _logging.getLogger("flashinfer_tpu")


class LibraryError(RuntimeError):
    """Base library error (reference LibraryError)."""


class BackendSupportedError(LibraryError):
    """Requested backend unsupported on this hardware."""


class GPUArchitectureError(BackendSupportedError):
    """Reference name; on TPU raised when a CUDA-only path is requested."""


def ceil_div(a: int, b: int) -> int:
    return cdiv(a, b)


def last_positive_power_of_2(x: int) -> int:
    """Largest power of two <= x (reference utils.py:129)."""
    n = next_power_of_two(x)
    return n if n == x else n // 2


def get_indptr(lens):
    """Lengths -> exclusive-prefix indptr (reference get_indptr)."""
    import numpy as _np

    lens = _np.asarray(lens, _np.int64)
    out = _np.zeros(len(lens) + 1, _np.int64)
    out[1:] = _np.cumsum(lens)
    return out


def get_alibi_slopes(n_heads: int, device=None):
    """ALiBi slope vector (reference utils.py:209, same recurrence)."""
    import numpy as _np

    n = 2 ** int(_math.floor(_math.log2(n_heads)))
    m = (2.0 ** (-8.0 / n)) ** _np.arange(1, 1 + n, dtype=_np.float64)
    if n < n_heads:
        m_hat = (2.0 ** (-4.0 / n)) ** _np.arange(
            1, 1 + 2 * (n_heads - n), 2, dtype=_np.float64
        )
        m = _np.concatenate([m, m_hat])
    import jax.numpy as _jnp

    return _jnp.asarray(m, _jnp.float32)


def calculate_tile_tokens_dim(
    num_tokens: int, num_experts: int, top_k: int,
    max_tile_tokens_dim: int = 128,
) -> int:
    """Expert-imbalance-aware tile size for grouped MoE GEMMs (reference
    utils.py:141 heuristic, used to pick the gmm m-tile)."""
    imbalance = 3 if num_tokens * top_k > num_experts else 1
    per_expert = cdiv(num_tokens * top_k, num_experts) * imbalance
    return min(max(next_power_of_two(max(per_expert, 8)), 8),
               max_tile_tokens_dim)


def version_at_least(version: str, base_version: str) -> bool:
    import re as _re

    def parse(v):
        # "2.6.0a0+git1234" -> (2, 6, 0): leading digits of each of the
        # first three dot components (pre-release suffixes compare equal
        # to their base, a fine approximation for gating)
        parts = []
        for p in v.split("+")[0].split(".")[:3]:
            m = _re.match(r"\d+", p)
            parts.append(int(m.group()) if m else 0)
        return tuple(parts)

    return parse(version) >= parse(base_version)


def is_float8(x) -> bool:
    import jax.numpy as _jnp

    return x.dtype in (_jnp.float8_e4m3fn, _jnp.float8_e5m2)


def get_native_fp4_dtype():
    """TPU has no native fp4 dtype; the storage form is packed int8
    nibbles (quantization.quantize_fp4)."""
    import jax.numpy as _jnp

    return _jnp.int8


class FP4Tensor:
    """Packed-fp4 carrier (reference utils.py:900): ``data`` holds two
    4-bit values per int8 along the last dim, ``scale`` the block scales
    (this library's quantize_fp4 output pair)."""

    def __init__(self, data, scale, scale_start_index: int = 0,
                 original_shape=None):
        self.data = data
        self.scale = scale
        self.scale_start_index = scale_start_index
        self.original_shape = original_shape or (
            *data.shape[:-1], data.shape[-1] * 2
        )

    def dequantize(self, block_size: int = 16):
        from flashinfer_tpu.quantization import dequantize_fp4

        return dequantize_fp4(self.data, self.scale, block_size)


# --- hardware/backend predicates: TPU truth for CUDA-world questions ---

def get_compute_capability(device=None):
    """No CUDA compute capability on TPU; returns (0, 0) so reference
    callers' >= checks route away from SM-gated paths."""
    return (0, 0)


def get_device_index(device=None) -> int:
    import jax

    return 0 if device is None else jax.devices().index(device)


def get_device_sm_count(device=None) -> int:
    """Closest TPU analogue: one MXU-owning core per chip (v5e)."""
    return 1


def get_gpu_memory_bandwidth(device=None) -> float:
    """HBM peak in GB/s for the attached chip (reference queries CUDA;
    here the bench table in bench.py is the source of truth)."""
    import jax

    kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    peaks = {"v5p": 2765.0, "v6e": 1640.0, "v4": 1228.0}
    for key, val in peaks.items():
        if key in kind:
            return val
    return 819.0  # v5e / default


def get_cuda_python_version():
    return None  # no CUDA runtime in this build


def has_cuda_cudart() -> bool:
    return False


def is_confidential_compute() -> bool:
    return False


def device_support_pdl(device=None) -> bool:
    return False  # programmatic dependent launch is a CUDA concept


def _cuda_backend_predicate(*_, **__) -> bool:
    """CUDA-arch gates are uniformly False on TPU; resolve_backend picks
    between 'pallas' and 'xla' instead."""
    return False


is_sm90a_supported = _cuda_backend_predicate
is_sm100a_supported = _cuda_backend_predicate
is_sm100f_supported = _cuda_backend_predicate
is_sm110a_supported = _cuda_backend_predicate
is_sm120a_supported = _cuda_backend_predicate
is_sm120f_supported = _cuda_backend_predicate
is_sm121a_supported = _cuda_backend_predicate
is_sm12x_supported = _cuda_backend_predicate
is_fa3_backend_supported = _cuda_backend_predicate
is_fa3_prefill_head_dim_supported = _cuda_backend_predicate
is_cutlass_backend_supported = _cuda_backend_predicate
is_cvt_rs_supported = _cuda_backend_predicate


def supported_compute_capability(*_, **__):
    """Decorator form in the reference (gates ops on SM version); here a
    pass-through — TPU gating happens in resolve_backend."""
    def deco(fn):
        return fn

    return deco


def backend_requirement(*_, **__):
    def deco(fn):
        return fn

    return deco


def determine_attention_backend(*_, **__) -> str:
    return "pallas" if is_tpu() else "xla"


def determine_gemm_backend(*_, **__) -> str:
    return "xla"  # XLA's MXU emitter is the GEMM backend


def determine_mla_backend(*_, **__) -> str:
    return "pallas" if is_tpu() else "xla"


def canonicalize_torch_dtype(dtype):
    """Map a torch-style dtype (or its string) to the jnp equivalent."""
    return canonicalize_dtype(dtype)


def check_shape_dtype_device(x, shape=None, dtype=None, device=None,
                             name: str = "tensor") -> None:
    if shape is not None and tuple(x.shape) != tuple(shape):
        raise ValueError(f"{name}: shape {x.shape} != {shape}")
    if dtype is not None and x.dtype != dtype:
        raise ValueError(f"{name}: dtype {x.dtype} != {dtype}")


def get_default_generators():
    """JAX randomness is explicit keys; no global generators exist."""
    return {}


# CUDA-kernel-layout helpers: identity/zero on TPU (XLA owns layout)
def get_shuffle_block_size(*_, **__) -> int:
    return 1


def get_shuffle_matrix_a_row_indices(w, *_, **__):
    import jax.numpy as _jnp

    return _jnp.arange(w.shape[0], dtype=_jnp.int32)


def get_shuffle_matrix_sf_a_row_indices(s, *_, **__):
    import jax.numpy as _jnp

    return _jnp.arange(s.shape[0], dtype=_jnp.int32)


def get_trtllm_gen_multi_ctas_kv_counter_bytes(*_, **__) -> int:
    return 0  # CTA coordination buffers do not exist on TPU


def get_shared_bytes_per_block_optin(*_, **__) -> int:
    return 0


def get_globaltimer_kernel(*_, **__):
    raise GPUArchitectureError(
        "globaltimer is a CUDA device intrinsic; use jax.profiler / the "
        "op timeline (flashinfer_tpu.profiler) on TPU"
    )


def prepare_jit_additional_args(*_, **__):
    return {}


# reference numeric/workspace constants (decode.py imports): the CUDA
# kernels compute softmax in base-2 (log2e folds into the scale) and
# allocate a fixed single-kernel scratch; TPU kernels use natural log and
# XLA owns scratch, so these exist for import parity and host-side math
log2e = 1.44269504088896340736
SINGLE_KERNEL_TMP_SIZE = 0


def determine_attention_backend(*_, **__) -> str:
    """Reference picks fa2/fa3/trtllm per arch; one answer here."""
    return "pallas"


class FP4Tensor:
    """Packed-fp4 tensor record (reference utils.FP4Tensor): data is the
    block-int4 packed array, scale the per-block f32 scales."""

    def __init__(self, data, scale, original_shape=None):
        self.data = data
        self.scale = scale
        self.original_shape = original_shape or getattr(data, "shape", None)
