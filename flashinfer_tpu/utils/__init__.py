"""Shared utilities: enums, layouts, rounding, backend gating.

TPU-native re-design of the reference's ``flashinfer/utils.py`` (enums and
layout canonicalization at utils.py:281, backend gating decorators at
utils.py:1070-1153).  Nothing CUDA-specific survives: "compute capability"
gates become TPU-generation gates, and torch custom-op registration is
unnecessary (jit/abstract-eval come free with JAX).
"""

from __future__ import annotations

import enum
import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp


class PosEncodingMode(enum.IntEnum):
    """Positional encoding applied inside attention kernels.

    Mirrors the reference enum (``flashinfer/utils.py:281``)."""

    NONE = 0
    ROPE_LLAMA = 1
    ALIBI = 2


class MaskMode(enum.IntEnum):
    """Attention mask mode (reference ``flashinfer/utils.py``)."""

    NON_CAUSAL = 0
    CAUSAL = 1
    CUSTOM = 2


class TensorLayout(enum.IntEnum):
    """KV tensor layout: NHD = [seq, heads, dim], HND = [heads, seq, dim]."""

    NHD = 0
    HND = 1


def atomic_write_text(path, text: str) -> None:
    """Write-then-rename so concurrent readers of shared cache files
    (autotuner tactics, quarantine list, compile-status registry) never see
    a torn write — the TPU-side analogue of the reference's compile-cache
    race protections (tests/utils/test_load_cubin_compile_race_condition.py)."""
    import os
    import tempfile
    from pathlib import Path

    import contextlib

    import time

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # opportunistic sweep: a SIGKILL between mkstemp and os.replace leaks
    # the temp file; age-gate so a concurrent writer's live temp survives
    with contextlib.suppress(OSError):
        cutoff = time.time() - 3600.0
        for stale in path.parent.glob(path.name + ".tmp*"):
            with contextlib.suppress(OSError):
                if stale.stat().st_mtime < cutoff:
                    stale.unlink()
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "w") as fh:
            fh.write(text)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def check_kv_layout(kv_layout: str) -> TensorLayout:
    if kv_layout not in ("NHD", "HND"):
        raise KeyError(f"Invalid kv_layout {kv_layout!r}, expected 'NHD' or 'HND'")
    return TensorLayout[kv_layout]


def check_pos_encoding_mode(pos_encoding_mode: str) -> PosEncodingMode:
    if pos_encoding_mode not in PosEncodingMode.__members__:
        raise KeyError(
            f"Invalid pos_encoding_mode {pos_encoding_mode!r}, expected one of "
            f"{list(PosEncodingMode.__members__)}"
        )
    return PosEncodingMode[pos_encoding_mode]


# ---------------------------------------------------------------------------
# Rounding / shape helpers
# ---------------------------------------------------------------------------


def cdiv(a: int, b: int) -> int:
    """Ceiling division."""
    return -(a // -b)


def round_up(a: int, b: int) -> int:
    """Round ``a`` up to a multiple of ``b``."""
    return cdiv(a, b) * b


def next_power_of_two(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def min_sublane(dtype: Any) -> int:
    """Minimum second-to-last tile dim for a dtype on TPU (lane dim is 128)."""
    itemsize = jnp.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


LANE = 128


# ---------------------------------------------------------------------------
# Platform / backend gating
# ---------------------------------------------------------------------------


@functools.cache
def is_tpu() -> bool:
    return jax.default_backend() == "tpu"


@functools.cache
def tpu_generation() -> int:
    """TPU generation number (4, 5, 6, ...); -1 when not running on TPU.

    The TPU analogue of the reference's compute-capability gates
    (``flashinfer/utils.py:1070``)."""
    if not is_tpu():
        return -1
    kind = jax.devices()[0].device_kind.lower()
    for tok in kind.replace("v", " v").split():
        if tok.startswith("v") and tok[1:2].isdigit():
            return int(tok[1])
    return 4


def use_interpret() -> bool:
    """Whether Pallas kernels should run in interpreter mode.

    True off-TPU (CPU CI — the stand-in for the reference's fake backends,
    SURVEY §4) or when FLASHINFER_TPU_INTERPRET=1."""
    from flashinfer_tpu import env

    return env.force_interpret() or not is_tpu()


def resolve_backend(backend: str, op: str = "") -> str:
    """Resolve a per-op backend choice, honoring the global override.

    Mirrors the reference's ``determine_attention_backend``
    (``flashinfer/utils.py:522``) collapsed to the TPU world: "pallas"
    (primary, Mosaic kernels) or "xla" (pure-jnp reference/fallback).
    """
    from flashinfer_tpu import env

    override = env.backend_override()
    if backend == "auto":
        if override != "auto":
            return override
        # off-TPU, interpret-mode Pallas is a debugger, not a backend:
        # auto picks the compiled XLA path there
        return "pallas" if is_tpu() else "xla"
    if backend not in ("pallas", "xla"):
        raise ValueError(f"Unknown backend {backend!r} for op {op or '<unnamed>'}")
    return backend


class GenerationRequirementError(RuntimeError):
    pass


def tpu_requirement(min_generation: int) -> Callable:
    """Declarative hardware gate, mirroring ``@supported_compute_capability``
    (``flashinfer/utils.py:1070``): raises unless running on TPU >= gen or
    off-TPU (interpret/testing mode)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if is_tpu() and tpu_generation() < min_generation:
                raise GenerationRequirementError(
                    f"{fn.__name__} requires TPU v{min_generation}+, "
                    f"running on v{tpu_generation()}"
                )
            return wrapper.__wrapped__(*args, **kwargs)

        return wrapper

    return deco


# ---------------------------------------------------------------------------
# dtype helpers
# ---------------------------------------------------------------------------

_DTYPE_ALIASES = {
    "half": jnp.float16,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "fp8_e4m3": jnp.float8_e4m3fn,
    "fp8_e5m2": jnp.float8_e5m2,
    "int8": jnp.int8,
    "int32": jnp.int32,
}


def canonicalize_dtype(dtype: Any) -> jnp.dtype:
    """Canonicalize a dtype spec (string alias or jnp dtype) to jnp.dtype.

    Reference: ``flashinfer/utils.py`` dtype canonicalization."""
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise KeyError(f"Unknown dtype alias {dtype!r}")
        return jnp.dtype(_DTYPE_ALIASES[dtype])
    return jnp.dtype(dtype)


def get_sm_scale(head_dim: int, sm_scale: Optional[float]) -> float:
    return sm_scale if sm_scale is not None else 1.0 / float(head_dim) ** 0.5


def to_nhd(x: jax.Array, kv_layout: str) -> jax.Array:
    """Convert a [.., H, N, D] ("HND") array to [.., N, H, D] ("NHD")."""
    if check_kv_layout(kv_layout) == TensorLayout.HND:
        return jnp.swapaxes(x, -3, -2)
    return x


# the NHD<->HND swap is an involution, so the inverse is the same transform
from_nhd = to_nhd


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


def get_seq_lens(
    kv_indptr: jax.Array, kv_last_page_len: jax.Array, page_size: int
) -> jax.Array:
    """Per-request KV sequence lengths from paged indptr + last-page lengths.

    Reference: ``flashinfer/page.py`` ``get_seq_lens``."""
    pages = kv_indptr[1:] - kv_indptr[:-1]
    return jnp.where(
        pages > 0, (pages - 1) * page_size + kv_last_page_len, jnp.zeros_like(pages)
    )


def expand_dims_to(x: jax.Array, ndim: int) -> jax.Array:
    while x.ndim < ndim:
        x = x[..., None]
    return x
