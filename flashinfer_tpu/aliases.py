"""Reference-named pre-compiled attention entry points, call-compatible.

The reference exposes several backend-branded one-shot functions
(``trtllm_batch_decode_with_kv_cache`` decode.py:3005,
``trtllm_batch_context_with_kv_cache`` prefill.py:4669,
``xqa_batch_decode_with_kv_cache`` decode.py:3522, ``cudnn_batch_*``).
On TPU those backends collapse into the Pallas/XLA dispatch, but the
entry points survive with the reference's FULL keyword surface: every
argument is honored, folded, documented-inert (pure scheduling), or
loudly rejected — never silently dropped (round-5 verdict item 6).

Scale semantics (verified against reference tests, e.g.
tests/attention/test_cute_dsl_mla_decode.py:543): ``bmm1_scale`` IS the
complete softmax scale (callers fold q/k dequant scales and 1/sqrt(d)
into it; the default really is 1.0), ``bmm2_scale`` multiplies the
output (v dequant scale), and ``o_scale`` only shifts fp8-out
saturation (net-neutral for the dtypes supported here).  LSE returned
by ``return_lse`` is NATURAL-log (documented deviation — the reference
kernels vary between e and 2 bases internally but surface natural log
from the wrapper paths; see PARITY.md).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.decode import BatchDecodeWithPagedKVCacheWrapper
from flashinfer_tpu.prefill import BatchPrefillWithPagedKVCacheWrapper
from flashinfer_tpu.utils import fold_scalar_scale

_LOG2E = math.log2(math.e)
_warned_default_scale = False  # one-shot contract-change warning


def _scalar(x, name: str) -> Optional[float]:
    return fold_scalar_scale(x, name)


def _sink_vec(sinks, num_heads: int, name: str):
    """Reference ``sinks`` is a per-head logit vector (trtllm entries
    wrap it in a single-element list)."""
    if sinks is None:
        return None
    if isinstance(sinks, (list, tuple)):
        if len(sinks) != 1:
            raise ValueError(
                f"TPU backend: {name} sinks must be a single per-head "
                f"tensor (or a 1-element list); got {len(sinks)} entries"
            )
        sinks = sinks[0]
    s = jnp.asarray(sinks).reshape(-1)
    if s.shape[0] != num_heads:
        raise ValueError(
            f"TPU backend: {name} sinks must have one logit per qo head "
            f"({num_heads}); got {s.shape[0]}"
        )
    return s


def _out_dtype(out_dtype, query, name: str):
    if out_dtype is None:
        return query.dtype
    if isinstance(out_dtype, str):
        raise ValueError(
            f"TPU backend: {name} out_dtype={out_dtype!r} (nvfp4 packed "
            "output) is not supported — quantize the bf16 output with "
            "fp4_quantize / mxfp8_quantize explicitly"
        )
    dt = jnp.dtype(out_dtype)
    if dt not in (jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16),
                  jnp.dtype(jnp.float32)):
        raise ValueError(
            f"TPU backend: {name} out_dtype={dt} is not supported "
            "(bf16/f16/f32 are; fp8/fp4 outputs need an explicit "
            "quantize step)"
        )
    return dt


from flashinfer_tpu.utils import reject_unsupported as _reject  # noqa: E402


def _split_kv(kv_cache, name: str):
    if isinstance(kv_cache, tuple):
        return kv_cache
    if kv_cache.ndim == 5 and kv_cache.shape[1] == 2:
        return kv_cache[:, 0], kv_cache[:, 1]
    raise ValueError(
        f"TPU backend: {name} kv_cache must be a (k, v) tuple or a "
        f"[pages, 2, ...] combined tensor; got shape "
        f"{getattr(kv_cache, 'shape', None)}"
    )


def _shared_tables(block_tables, uses_shared_paged_kv_idx: bool,
                   name: str):
    """``uses_shared_paged_kv_idx=False`` carries [B, 2, P] separate K/V
    tables; the TPU cache kernels address one table, so the split form
    is accepted only when both halves agree."""
    if uses_shared_paged_kv_idx:
        return jnp.asarray(block_tables)
    bt = np.asarray(block_tables)
    if bt.ndim != 3 or bt.shape[1] != 2:
        raise ValueError(
            f"TPU backend: {name} uses_shared_paged_kv_idx=False expects "
            f"block_tables [batch, 2, pages]; got {bt.shape}"
        )
    if not np.array_equal(bt[:, 0], bt[:, 1]):
        raise ValueError(
            f"TPU backend: {name} separate K and V page tables are not "
            "supported (TPU paged kernels share one table); lay out K/V "
            "pages identically or pass uses_shared_paged_kv_idx=True"
        )
    return jnp.asarray(bt[:, 0])


def _decode_sm_scale(bmm1_scale, bmm1_scale_log2, name: str) -> float:
    """bmm1_scale_log2 (precomputed bmm1_scale * log2e, decode.py:2752)
    takes precedence over bmm1_scale, matching the reference FFI."""
    if bmm1_scale_log2 is not None:
        return _scalar(bmm1_scale_log2, f"{name} bmm1_scale_log2") / _LOG2E
    return _scalar(bmm1_scale, f"{name} bmm1_scale")


def _fold_kv_sf(kv_cache_sf, sm_scale: float, out_mul: float,
                name: str) -> Tuple[float, float]:
    """Per-tensor KV dequant scale factors fold into the softmax scale
    (K side) and the output multiplier (V side) — same folding the
    native wrapper does with k_scale/v_scale (decode.py:241-314)."""
    if kv_cache_sf is None:
        return sm_scale, out_mul
    if isinstance(kv_cache_sf, tuple):
        k_sf, v_sf = kv_cache_sf
    else:
        k_sf = v_sf = kv_cache_sf
    return (
        sm_scale * _scalar(k_sf, f"{name} kv_cache_sf[k]"),
        out_mul * _scalar(v_sf, f"{name} kv_cache_sf[v]"),
    )


def _one_shot_paged_decode(
    query, k_cache, v_cache, block_tables, seq_lens, *,
    sm_scale: float, out_mul: float, window_left: int, kv_layout: str,
    q_len_per_req: int, cum_seq_lens_q, sinks, return_lse: bool,
    out_dtype, name: str,
):
    """Shared core for the trtllm/xqa/cudnn decode brand names.

    q_len_per_req == 1 runs the decode kernel; > 1 (speculative / MTP
    windows) runs bottom-right-causal append attention through the
    paged prefill wrapper — the same routing the reference does when it
    hands spec-decode windows to its context kernels."""
    need_lse = return_lse or sinks is not None
    if q_len_per_req == 1 and cum_seq_lens_q is None:
        from flashinfer_tpu.ops.paged_decode import paged_decode_attention
        from flashinfer_tpu.ops.xla_ref import xla_paged_decode
        from flashinfer_tpu.utils import resolve_backend

        fn = (
            paged_decode_attention
            if resolve_backend("auto", "trtllm_batch_decode") == "pallas"
            else xla_paged_decode
        )
        res = fn(
            query, k_cache, v_cache, jnp.asarray(block_tables),
            jnp.asarray(seq_lens), sm_scale=sm_scale,
            window_left=window_left, kv_layout=kv_layout,
            return_lse=need_lse,
        )
        out, lse = res if need_lse else (res, None)
    else:
        # MTP/speculative window: [B*q_len, H, D] queries at the END of
        # each kv sequence, causal within the window.
        seq_np = np.asarray(seq_lens)
        batch = len(seq_np)
        if cum_seq_lens_q is not None:
            qo_indptr = np.asarray(cum_seq_lens_q).astype(np.int32)
            if len(qo_indptr) != batch + 1:
                raise ValueError(
                    f"TPU backend: {name} cum_seq_lens_q must be "
                    f"[batch+1]; got {qo_indptr.shape}"
                )
        else:
            qo_indptr = (np.arange(batch + 1) * q_len_per_req).astype(
                np.int32)
        if query.shape[0] != int(qo_indptr[-1]):
            raise ValueError(
                f"TPU backend: {name} query has {query.shape[0]} tokens "
                f"but cum_seq_lens_q/q_len_per_req imply "
                f"{int(qo_indptr[-1])}"
            )
        page_size = (k_cache.shape[2] if kv_layout == "HND"
                     else k_cache.shape[1])
        num_kv_heads = (k_cache.shape[1] if kv_layout == "HND"
                        else k_cache.shape[2])
        bt = np.asarray(block_tables)
        pages_per_req = np.maximum(-(-seq_np // page_size), 1)
        kv_indptr = np.concatenate(
            [[0], np.cumsum(pages_per_req)]).astype(np.int32)
        indices = np.concatenate(
            [bt[b, : pages_per_req[b]] for b in range(batch)]
        ).astype(np.int32)
        last = (seq_np - (pages_per_req - 1) * page_size).astype(np.int32)
        w = BatchPrefillWithPagedKVCacheWrapper(kv_layout=kv_layout)
        w.plan(
            qo_indptr, kv_indptr, indices, last,
            query.shape[1], num_kv_heads, query.shape[2], page_size,
            causal=True, sm_scale=sm_scale, window_left=window_left,
        )
        res = w.run(query, (k_cache, v_cache), return_lse=need_lse)
        out, lse = res if need_lse else (res, None)
    if sinks is not None:
        from flashinfer_tpu.attention import apply_attention_sink

        out = apply_attention_sink(out, lse, sinks)
        lse = jnp.logaddexp(
            lse.astype(jnp.float32),
            jnp.broadcast_to(sinks.astype(jnp.float32)[None, :], lse.shape),
        )
    if out_mul != 1.0:
        out = (out.astype(jnp.float32) * out_mul).astype(out.dtype)
    out = out.astype(out_dtype)
    return (out, lse) if return_lse else out


def trtllm_batch_decode_with_kv_cache(
    query: jax.Array,
    kv_cache: Union[jax.Array, Tuple[jax.Array, jax.Array]],
    workspace_buffer=None,
    block_tables: jax.Array = None,
    seq_lens: jax.Array = None,
    max_seq_len: int = None,
    bmm1_scale: Union[float, jax.Array] = 1.0,
    bmm2_scale: Union[float, jax.Array] = 1.0,
    window_left: int = -1,
    out=None,
    out_dtype=None,
    o_sf_scale: Optional[float] = None,
    o_sf_vec_size: Optional[int] = None,
    sinks=None,
    kv_layout: str = "HND",
    enable_pdl: Optional[bool] = None,
    backend: str = "auto",
    q_len_per_req: Optional[int] = 1,
    o_scale: Optional[float] = 1.0,
    mask=None,
    max_q_len: Optional[int] = None,
    cum_seq_lens_q=None,
    skip_softmax_threshold_scale_factor: Optional[float] = None,
    kv_cache_sf=None,
    uses_shared_paged_kv_idx: bool = True,
    lse=None,
    return_lse: bool = False,
    bmm1_scale_log2=None,
    multi_ctas_kv_counter_buffer=None,
    enable_block_sparse_attention: bool = False,
    sm_scale: Optional[float] = None,
):
    """Reference ``trtllm_batch_decode_with_kv_cache`` (decode.py:3005),
    full kwargs surface.

    Honored: bmm1_scale (COMPLETE softmax scale, default 1.0 per the
    reference contract — callers fold 1/sqrt(d) and q/k dequant scales
    in), bmm1_scale_log2 (takes precedence, /log2e), bmm2_scale +
    scalar kv_cache_sf (output/V-side multipliers), window_left, sinks,
    kv_layout, out_dtype (bf16/f16/f32), q_len_per_req > 1 and ragged
    cum_seq_lens_q (routed through bottom-right-causal append
    attention), uses_shared_paged_kv_idx=False when both table halves
    agree, return_lse (NATURAL log).  sm_scale= is a TPU keyword
    superset overriding bmm1_scale.

    Inert (CUDA launch knobs; XLA owns TPU scheduling):
    workspace_buffer, max_seq_len, enable_pdl, backend, max_q_len,
    o_scale (net-neutral outside fp8-out), and
    multi_ctas_kv_counter_buffer.

    Rejected loudly (different numerics regime, with alternatives):
    out=/lse= preallocation, nvfp4 output (o_sf_*), spec-decode tree
    mask= (use the prefill wrapper's custom masks),
    skip_softmax_threshold_scale_factor (approximation), non-scalar
    kv_cache_sf, enable_block_sparse_attention (use
    VariableBlockSparseAttentionWrapper).
    """
    name = "trtllm_batch_decode_with_kv_cache"
    _reject(name, out=out, lse=lse, o_sf_scale=o_sf_scale,
            o_sf_vec_size=o_sf_vec_size, mask=mask,
            skip_softmax_threshold_scale_factor=(
                skip_softmax_threshold_scale_factor),
            enable_block_sparse_attention=enable_block_sparse_attention)
    if sm_scale is None and bmm1_scale_log2 is None \
            and isinstance(bmm1_scale, float) and bmm1_scale == 1.0:
        global _warned_default_scale
        if not _warned_default_scale:
            _warned_default_scale = True
            import warnings

            warnings.warn(
                "trtllm_batch_decode_with_kv_cache: bmm1_scale left at "
                "its reference default 1.0 — it is the COMPLETE softmax "
                "scale (1/sqrt(head_dim) is NOT applied implicitly). "
                "Pass bmm1_scale=q_scale*k_scale/sqrt(head_dim) (or the "
                "TPU keyword sm_scale=) if you relied on the pre-parity "
                "implicit default. docs/migration.md",
                stacklevel=2,
            )
    k_cache, v_cache = _split_kv(kv_cache, name)
    tables = _shared_tables(block_tables, uses_shared_paged_kv_idx, name)
    sm = (float(sm_scale) if sm_scale is not None
          else _decode_sm_scale(bmm1_scale, bmm1_scale_log2, name))
    out_mul = _scalar(bmm2_scale, f"{name} bmm2_scale")
    sm, out_mul = _fold_kv_sf(kv_cache_sf, sm, out_mul, name)
    return _one_shot_paged_decode(
        query, k_cache, v_cache, tables, seq_lens,
        sm_scale=sm, out_mul=out_mul, window_left=window_left,
        kv_layout=kv_layout, q_len_per_req=int(q_len_per_req or 1),
        cum_seq_lens_q=cum_seq_lens_q,
        sinks=_sink_vec(sinks, query.shape[-2], name),
        return_lse=return_lse,
        out_dtype=_out_dtype(out_dtype, query, name), name=name,
    )


def xqa_batch_decode_with_kv_cache(
    query: jax.Array,
    kv_cache: Union[jax.Array, Tuple[jax.Array, jax.Array]],
    workspace_buffer=None,
    block_tables: jax.Array = None,
    seq_lens: jax.Array = None,
    max_seq_len: int = None,
    bmm1_scale: Union[float, jax.Array] = 1.0,
    bmm2_scale: Union[float, jax.Array] = 1.0,
    window_left: int = -1,
    out=None,
    sinks=None,
    kv_layout: str = "NHD",
    enable_pdl: bool = None,
    q_len_per_req: Optional[int] = 1,
    o_scale: Optional[float] = 1.0,
    mask=None,
    kv_cache_sf=None,
    sm_scale: Optional[float] = None,
):
    """Reference ``xqa_batch_decode_with_kv_cache`` (decode.py:3522).
    Same core as the trtllm entry (on TPU the XQA GQA-decode trick IS
    the MXU head-group packing of the paged decode kernel); note the
    reference's NHD default layout and tensor-form ``sinks``."""
    name = "xqa_batch_decode_with_kv_cache"
    _reject(name, out=out, mask=mask)
    k_cache, v_cache = _split_kv(kv_cache, name)
    sm = (float(sm_scale) if sm_scale is not None
          else _scalar(bmm1_scale, f"{name} bmm1_scale"))
    out_mul = _scalar(bmm2_scale, f"{name} bmm2_scale")
    sm, out_mul = _fold_kv_sf(kv_cache_sf, sm, out_mul, name)
    return _one_shot_paged_decode(
        query, k_cache, v_cache, jnp.asarray(block_tables), seq_lens,
        sm_scale=sm, out_mul=out_mul, window_left=window_left,
        kv_layout=kv_layout, q_len_per_req=int(q_len_per_req or 1),
        cum_seq_lens_q=None,
        sinks=_sink_vec(sinks, query.shape[-2], name),
        return_lse=False, out_dtype=query.dtype, name=name,
    )


def trtllm_batch_context_with_kv_cache(
    query: jax.Array,
    kv_cache: Union[jax.Array, Tuple[jax.Array, jax.Array]],
    workspace_buffer=None,
    block_tables=None,
    seq_lens=None,
    max_q_len: int = None,
    max_kv_len: int = None,
    bmm1_scale: Union[float, jax.Array] = None,
    bmm2_scale: Union[float, jax.Array] = None,
    batch_size: int = None,
    cum_seq_lens_q=None,
    cum_seq_lens_kv=None,
    window_left: int = -1,
    out=None,
    out_dtype=None,
    o_sf_scale: Optional[float] = None,
    o_sf_vec_size: Optional[int] = None,
    kv_layout: str = "HND",
    enable_pdl: Optional[bool] = None,
    sinks=None,
    kv_cache_sf=None,
    skip_softmax_threshold_scale_factor: Optional[float] = None,
    uses_shared_paged_kv_idx: bool = True,
    causal: bool = True,
    lse=None,
    return_lse: bool = False,
    multi_ctas_kv_counter_buffer=None,
    sm_scale: Optional[float] = None,
):
    """Reference ``trtllm_batch_context_with_kv_cache``
    (prefill.py:4669), reference positional order (bmm scales and
    batch_size sit BETWEEN seq_lens and the cum_seq_lens arrays).

    bmm1_scale is the complete softmax scale; when left None (the
    reference marks it required) the TPU entry falls back to
    1/sqrt(head_dim).  sinks/kv_cache_sf/return_lse behave as in the
    decode entry; o_sf_* (nvfp4 out), out=/lse= preallocation,
    skip-softmax approximation, and split K/V tables with differing
    halves are rejected loudly."""
    name = "trtllm_batch_context_with_kv_cache"
    _reject(name, out=out, lse=lse, o_sf_scale=o_sf_scale,
            o_sf_vec_size=o_sf_vec_size,
            skip_softmax_threshold_scale_factor=(
                skip_softmax_threshold_scale_factor))
    k_cache, v_cache = _split_kv(kv_cache, name)
    tables = np.asarray(
        _shared_tables(block_tables, uses_shared_paged_kv_idx, name))
    seq_np = np.asarray(seq_lens)
    batch = len(seq_np)
    if batch_size is not None and int(batch_size) != batch:
        raise ValueError(
            f"TPU backend: {name} batch_size={batch_size} disagrees with "
            f"len(seq_lens)={batch}"
        )
    if cum_seq_lens_q is None:
        raise ValueError(
            f"TPU backend: {name} requires cum_seq_lens_q (the reference "
            "marks it positional-required)"
        )
    page_size = (
        k_cache.shape[2] if kv_layout == "HND" else k_cache.shape[1])
    num_kv_heads = (
        k_cache.shape[1] if kv_layout == "HND" else k_cache.shape[2])
    if sm_scale is not None:
        sm = float(sm_scale)
    elif bmm1_scale is not None:
        sm = _scalar(bmm1_scale, f"{name} bmm1_scale")
    else:
        sm = 1.0 / math.sqrt(query.shape[-1])
    out_mul = _scalar(bmm2_scale, f"{name} bmm2_scale")
    out_mul = 1.0 if out_mul is None else out_mul
    sm, out_mul = _fold_kv_sf(kv_cache_sf, sm, out_mul, name)
    pages_per_req = np.maximum(-(-seq_np // page_size), 1)
    kv_indptr = np.concatenate([[0], np.cumsum(pages_per_req)]).astype(
        np.int32)
    indices = np.concatenate(
        [tables[b, : pages_per_req[b]] for b in range(batch)]
    ).astype(np.int32)
    last = (seq_np - (pages_per_req - 1) * page_size).astype(np.int32)
    if cum_seq_lens_kv is not None:
        ckv = np.asarray(cum_seq_lens_kv)
        if not np.array_equal(np.diff(ckv), seq_np):
            raise ValueError(
                f"TPU backend: {name} cum_seq_lens_kv disagrees with "
                "seq_lens"
            )
    w = BatchPrefillWithPagedKVCacheWrapper(kv_layout=kv_layout)
    w.plan(
        np.asarray(cum_seq_lens_q), kv_indptr, indices, last,
        query.shape[1], num_kv_heads, query.shape[2], page_size,
        causal=causal, sm_scale=sm, window_left=window_left,
    )
    s = _sink_vec(sinks, query.shape[-2], name)
    need_lse = return_lse or s is not None
    res = w.run(query, (k_cache, v_cache), return_lse=need_lse)
    o, lse_out = res if need_lse else (res, None)
    if s is not None:
        from flashinfer_tpu.attention import apply_attention_sink

        o = apply_attention_sink(o, lse_out, s)
        lse_out = jnp.logaddexp(
            lse_out.astype(jnp.float32),
            jnp.broadcast_to(s.astype(jnp.float32)[None, :],
                             lse_out.shape),
        )
    if out_mul != 1.0:
        o = (o.astype(jnp.float32) * out_mul).astype(o.dtype)
    o = o.astype(_out_dtype(out_dtype, query, name))
    return (o, lse_out) if return_lse else o


def cudnn_batch_decode_with_kv_cache(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    scale: float,
    workspace_buffer=None,
    *,
    max_sequence_kv: int = None,
    actual_seq_lens_kv=None,
    block_tables=None,
    is_cuda_graph_compatible: bool = False,
    batch_offsets_q=None,
    batch_offsets_o=None,
    batch_offsets_k=None,
    batch_offsets_v=None,
    out=None,
):
    """Reference ``cudnn_batch_decode_with_kv_cache``
    (cudnn/decode.py:267): separate k/v caches in HND page layout,
    POSITIONAL ``scale`` (the full softmax scale), keyword-only geometry.
    The previous plain alias onto the trtllm entry MISBOUND these
    positionals (scale landed on block_tables) — this adapter carries
    the real signature.  ``is_cuda_graph_compatible`` is inert (jit +
    static shapes); non-None batch_offsets_* (strided non-packed
    layouts) are rejected — pack tokens contiguously."""
    name = "cudnn_batch_decode_with_kv_cache"
    _reject(name, out=out, batch_offsets_q=batch_offsets_q,
            batch_offsets_o=batch_offsets_o,
            batch_offsets_k=batch_offsets_k,
            batch_offsets_v=batch_offsets_v)
    return _one_shot_paged_decode(
        q, k_cache, v_cache, jnp.asarray(block_tables),
        jnp.asarray(actual_seq_lens_kv).reshape(-1),
        sm_scale=float(scale), out_mul=1.0, window_left=-1,
        kv_layout="HND", q_len_per_req=1, cum_seq_lens_q=None,
        sinks=None, return_lse=False, out_dtype=q.dtype, name=name,
    )


def cudnn_batch_prefill_with_kv_cache(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    scale: float,
    workspace_buffer=None,
    *,
    max_token_per_sequence: int = None,
    max_sequence_kv: int = None,
    actual_seq_lens_q=None,
    actual_seq_lens_kv=None,
    block_tables=None,
    causal: bool = True,
    return_lse: bool = False,
    q_scale=None,
    k_scale=None,
    v_scale=None,
    batch_offsets_q=None,
    batch_offsets_o=None,
    batch_offsets_k=None,
    batch_offsets_v=None,
    batch_offsets_stats=None,
    batch_offsets_units: str = "elements",
    out=None,
    lse=None,
    is_cuda_graph_compatible: bool = False,
    backend=None,
    o_data_type=None,
):
    """Reference ``cudnn_batch_prefill_with_kv_cache``
    (cudnn/prefill.py:689): packed ragged q, paged (4-D) or ragged
    (3-D) k/v caches, positional ``scale``; RETURNS A TUPLE
    ``(out, lse-or-None)`` like the reference.  Scalar q/k scales fold
    into the softmax scale, v_scale folds into the output; strided
    batch_offsets_* layouts are rejected (pack tokens contiguously)."""
    name = "cudnn_batch_prefill_with_kv_cache"
    _reject(name, out=out, lse=lse, batch_offsets_q=batch_offsets_q,
            batch_offsets_o=batch_offsets_o,
            batch_offsets_k=batch_offsets_k,
            batch_offsets_v=batch_offsets_v,
            batch_offsets_stats=batch_offsets_stats)
    sm = float(scale)
    for s, nm in ((q_scale, "q_scale"), (k_scale, "k_scale")):
        f = _scalar(s, f"{name} {nm}")
        if f is not None:
            sm *= f
    vmul = _scalar(v_scale, f"{name} v_scale")
    vmul = 1.0 if vmul is None else vmul
    q_lens = np.asarray(actual_seq_lens_q).reshape(-1)
    kv_lens = np.asarray(actual_seq_lens_kv).reshape(-1)
    batch = len(q_lens)
    qo_indptr = np.concatenate([[0], np.cumsum(q_lens)]).astype(np.int32)
    if k_cache.ndim == 4:  # paged HND cache
        page_size = k_cache.shape[2]
        tables = np.asarray(block_tables)
        pages_per_req = np.maximum(-(-kv_lens // page_size), 1)
        kv_indptr = np.concatenate(
            [[0], np.cumsum(pages_per_req)]).astype(np.int32)
        indices = np.concatenate(
            [tables[b, : pages_per_req[b]] for b in range(batch)]
        ).astype(np.int32)
        last = (kv_lens - (pages_per_req - 1) * page_size).astype(np.int32)
        w = BatchPrefillWithPagedKVCacheWrapper(kv_layout="HND")
        w.plan(
            qo_indptr, kv_indptr, indices, last,
            q.shape[1], k_cache.shape[1], q.shape[2], page_size,
            causal=causal, sm_scale=sm,
        )
        res = w.run(q, (k_cache, v_cache), return_lse=return_lse)
    else:  # ragged (total_kv_tokens, Hkv, D)
        from flashinfer_tpu.prefill import (
            BatchPrefillWithRaggedKVCacheWrapper,
        )

        kv_indptr = np.concatenate(
            [[0], np.cumsum(kv_lens)]).astype(np.int32)
        w = BatchPrefillWithRaggedKVCacheWrapper(kv_layout="NHD")
        w.plan(qo_indptr, kv_indptr, q.shape[1], k_cache.shape[1],
               q.shape[2], causal=causal, sm_scale=sm)
        res = w.run(q, k_cache, v_cache, return_lse=return_lse)
    o, lse_out = res if return_lse else (res, None)
    if vmul != 1.0:
        o = (o.astype(jnp.float32) * vmul).astype(o.dtype)
    if o_data_type is not None:
        o = o.astype(jnp.dtype(o_data_type))
    return o, lse_out


def fast_decode_plan(wrapper: BatchDecodeWithPagedKVCacheWrapper, *args, **kw):
    """Trimmed replanning entry for engines that replan every step
    (reference ``fast_decode_plan``, decode.py:3700 — skips host validation).
    The TPU plan is already a thin native-planner call, so this simply
    forwards; the name exists for drop-in compatibility."""
    return wrapper.plan(*args, **kw)


def trtllm_batch_decode_with_kv_cache_mla(
    query, kv_cache, workspace_buffer=None, qk_nope_head_dim=128,
    kv_lora_rank=512, qk_rope_head_dim=64, block_tables=None,
    seq_lens=None, max_seq_len=None, sparse_mla_top_k=0, out=None,
    bmm1_scale=1.0, bmm2_scale=1.0, **_unused,
):
    """One-shot absorbed-MLA paged decode (reference mla/_core.py:2571):
    ``query`` [B, H, kv_lora_rank + rope] against the COMBINED
    [pages, page_size, kv_lora_rank + rope] cache; bmm1_scale is the
    softmax scale, bmm2_scale scales the output."""
    import jax.numpy as jnp

    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )
    from flashinfer_tpu.utils import is_tpu

    if out is not None:
        raise ValueError(
            "TPU backend: out= pre-allocated outputs are not supported"
        )
    if sparse_mla_top_k:
        raise ValueError(
            "TPU backend: sparse MLA goes through "
            "BatchMLAPagedAttentionWrapper.run_sparse (the top-k rows come "
            "from topk.top_k_page_table_transform)"
        )
    # reference query layout is [batch, q_len_per_request, heads, dim]
    # (mla/_core.py:2571); the decode op takes [batch, heads, dim], so
    # the standard q_len=1 axis is squeezed and q_len>1 (MTP) rejected
    q4 = query.ndim == 4
    if q4:
        if query.shape[1] != 1:
            raise ValueError(
                "TPU backend: trtllm_batch_decode_with_kv_cache_mla "
                f"supports q_len_per_request == 1, got {query.shape[1]} "
                "(run MTP windows through the MLA wrapper's ragged mode)"
            )
        query = query[:, 0]
    q_nope = query[..., :kv_lora_rank]
    q_pe = query[..., kv_lora_rank:]
    ckv = kv_cache[..., :kv_lora_rank]
    kpe = kv_cache[..., kv_lora_rank:]
    fn = mla_paged_decode_attention if is_tpu() else xla_mla_paged_decode
    o = fn(q_nope, q_pe, ckv, kpe, block_tables, seq_lens,
           sm_scale=float(bmm1_scale))
    o = o * float(bmm2_scale) if bmm2_scale != 1.0 else o
    return o[:, None] if q4 else o


xqa_batch_decode_with_kv_cache_mla = trtllm_batch_decode_with_kv_cache_mla
trtllm_batch_decode_sparse_mla_dsv4 = trtllm_batch_decode_with_kv_cache_mla


def trtllm_batch_decode_trace_dispatch(*args, **kw):
    """Reference trace-dispatch shim for the trtllm decode entry — the
    traced path here is the same call (fi_trace wraps at the API layer)."""
    return trtllm_batch_decode_with_kv_cache(*args, **kw)
