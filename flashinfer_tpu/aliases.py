"""Reference-named convenience entry points.

The reference exposes several backend-branded functions
(``trtllm_batch_decode_with_kv_cache`` decode.py:3005,
``trtllm_batch_context_with_kv_cache`` prefill.py:4669,
``xqa_batch_decode_with_kv_cache`` decode.py:3522, ``cudnn_batch_*``).
On TPU those backends collapse into the Pallas/XLA dispatch, but the entry
points survive as one-shot conveniences (plan+run in a single call) so
engine integrations keyed to these names keep working.
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.decode import BatchDecodeWithPagedKVCacheWrapper
from flashinfer_tpu.prefill import BatchPrefillWithPagedKVCacheWrapper


def trtllm_batch_decode_with_kv_cache(
    query: jax.Array,  # [batch, num_qo_heads, head_dim]
    kv_cache: Union[Tuple[jax.Array, jax.Array], jax.Array],
    workspace_buffer=None,
    block_tables: jax.Array = None,  # [batch, max_pages] padded page table
    seq_lens: jax.Array = None,  # [batch]
    max_seq_len: int = None,
    kv_layout: str = "HND",
    window_left: int = -1,
    sm_scale: Optional[float] = None,
    **_unused,
):
    """One-shot padded-page-table batch decode (reference
    ``trtllm_batch_decode_with_kv_cache`` shape: block_tables + seq_lens
    instead of ragged indptr)."""
    from flashinfer_tpu.ops.paged_decode import paged_decode_attention
    from flashinfer_tpu.ops.xla_ref import xla_paged_decode
    from flashinfer_tpu.utils import get_sm_scale, resolve_backend

    if isinstance(kv_cache, tuple):
        k_cache, v_cache = kv_cache
    else:
        k_cache, v_cache = kv_cache[:, 0], kv_cache[:, 1]
    sm = get_sm_scale(query.shape[-1], sm_scale)
    fn = (
        paged_decode_attention
        if resolve_backend("auto", "trtllm_batch_decode") == "pallas"
        else xla_paged_decode
    )
    return fn(
        query, k_cache, v_cache, block_tables, seq_lens,
        sm_scale=sm, window_left=window_left, kv_layout=kv_layout,
    )


def trtllm_batch_context_with_kv_cache(
    query: jax.Array,  # [total_q, num_qo_heads, head_dim]
    kv_cache,
    workspace_buffer=None,
    block_tables=None,
    seq_lens=None,
    max_q_len: int = None,
    max_kv_len: int = None,
    cum_seq_lens_q=None,  # [batch+1] qo_indptr
    cum_seq_lens_kv=None,
    kv_layout: str = "HND",
    causal: bool = True,
    sm_scale: Optional[float] = None,
    **_unused,
):
    """One-shot paged context/prefill attention (reference
    ``trtllm_batch_context_with_kv_cache``)."""
    seq_lens = np.asarray(seq_lens)
    block_tables = np.asarray(block_tables)
    batch = len(seq_lens)
    page_size = (
        kv_cache[0].shape[2] if kv_layout == "HND" else kv_cache[0].shape[1]
    ) if isinstance(kv_cache, tuple) else kv_cache.shape[3 if kv_layout == "HND" else 2]
    pages_per_req = -(-seq_lens // page_size)
    kv_indptr = np.concatenate([[0], np.cumsum(pages_per_req)]).astype(np.int32)
    indices = np.concatenate(
        [block_tables[b, : pages_per_req[b]] for b in range(batch)]
    ).astype(np.int32)
    last = (seq_lens - (np.maximum(pages_per_req, 1) - 1) * page_size).astype(
        np.int32
    )
    if isinstance(kv_cache, tuple):
        k_cache, v_cache = kv_cache
    else:
        k_cache, v_cache = kv_cache[:, 0], kv_cache[:, 1]
    num_kv_heads = k_cache.shape[1] if kv_layout == "HND" else k_cache.shape[2]
    w = BatchPrefillWithPagedKVCacheWrapper(kv_layout=kv_layout)
    w.plan(
        np.asarray(cum_seq_lens_q), kv_indptr, indices, last,
        query.shape[1], num_kv_heads, query.shape[2], page_size,
        causal=causal, sm_scale=sm_scale,
    )
    return w.run(query, (k_cache, v_cache))


# XQA decode: TRT-LLM's GQA decode kernels; on TPU this IS the paged decode
# kernel (MXU group packing).  Alias for engine integrations.
xqa_batch_decode_with_kv_cache = trtllm_batch_decode_with_kv_cache

# cudnn-named entry points collapse the same way.
cudnn_batch_decode_with_kv_cache = trtllm_batch_decode_with_kv_cache


def fast_decode_plan(wrapper: BatchDecodeWithPagedKVCacheWrapper, *args, **kw):
    """Trimmed replanning entry for engines that replan every step
    (reference ``fast_decode_plan``, decode.py:3700 — skips host validation).
    The TPU plan is already a thin native-planner call, so this simply
    forwards; the name exists for drop-in compatibility."""
    return wrapper.plan(*args, **kw)


def trtllm_batch_decode_with_kv_cache_mla(
    query, kv_cache, workspace_buffer=None, qk_nope_head_dim=128,
    kv_lora_rank=512, qk_rope_head_dim=64, block_tables=None,
    seq_lens=None, max_seq_len=None, sparse_mla_top_k=0, out=None,
    bmm1_scale=1.0, bmm2_scale=1.0, **_unused,
):
    """One-shot absorbed-MLA paged decode (reference mla/_core.py:2571):
    ``query`` [B, H, kv_lora_rank + rope] against the COMBINED
    [pages, page_size, kv_lora_rank + rope] cache; bmm1_scale is the
    softmax scale, bmm2_scale scales the output."""
    import jax.numpy as jnp

    from flashinfer_tpu.ops.mla_decode import (
        mla_paged_decode_attention, xla_mla_paged_decode,
    )
    from flashinfer_tpu.utils import is_tpu

    if out is not None:
        raise ValueError(
            "TPU backend: out= pre-allocated outputs are not supported"
        )
    if sparse_mla_top_k:
        raise ValueError(
            "TPU backend: sparse MLA goes through "
            "BatchMLAPagedAttentionWrapper.run_sparse (the top-k rows come "
            "from topk.top_k_page_table_transform)"
        )
    # reference query layout is [batch, q_len_per_request, heads, dim]
    # (mla/_core.py:2571); the decode op takes [batch, heads, dim], so
    # the standard q_len=1 axis is squeezed and q_len>1 (MTP) rejected
    q4 = query.ndim == 4
    if q4:
        if query.shape[1] != 1:
            raise ValueError(
                "TPU backend: trtllm_batch_decode_with_kv_cache_mla "
                f"supports q_len_per_request == 1, got {query.shape[1]} "
                "(run MTP windows through the MLA wrapper's ragged mode)"
            )
        query = query[:, 0]
    q_nope = query[..., :kv_lora_rank]
    q_pe = query[..., kv_lora_rank:]
    ckv = kv_cache[..., :kv_lora_rank]
    kpe = kv_cache[..., kv_lora_rank:]
    fn = mla_paged_decode_attention if is_tpu() else xla_mla_paged_decode
    o = fn(q_nope, q_pe, ckv, kpe, block_tables, seq_lens,
           sm_scale=float(bmm1_scale))
    o = o * float(bmm2_scale) if bmm2_scale != 1.0 else o
    return o[:, None] if q4 else o


xqa_batch_decode_with_kv_cache_mla = trtllm_batch_decode_with_kv_cache_mla
trtllm_batch_decode_sparse_mla_dsv4 = trtllm_batch_decode_with_kv_cache_mla


def trtllm_batch_decode_trace_dispatch(*args, **kw):
    """Reference trace-dispatch shim for the trtllm decode entry — the
    traced path here is the same call (fi_trace wraps at the API layer)."""
    return trtllm_batch_decode_with_kv_cache(*args, **kw)


# cudnn prefill brand name collapses onto the one-shot context entry
cudnn_batch_prefill_with_kv_cache = trtllm_batch_context_with_kv_cache
