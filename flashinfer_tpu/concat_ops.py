"""Concat helper ops (reference ``flashinfer/concat_ops.py`` +
``csrc/concat_mla.cu``): MLA-specific head assembly concats.  Pure-XLA —
these exist as named ops for API parity; jit fuses them into neighbors."""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def concat_mla_q(q_nope: jax.Array, q_pe: jax.Array) -> jax.Array:
    """[T, H, d_ckv] + [T, H, d_kpe] -> [T, H, d_ckv + d_kpe]."""
    return jnp.concatenate([q_nope, q_pe.astype(q_nope.dtype)], axis=-1)


@jax.jit
def concat_mla_k(
    k_nope: jax.Array,  # [T, H, d] per-head decompressed keys
    k_pe: jax.Array,  # [T, d_kpe] shared rope keys
) -> jax.Array:
    """Broadcast the shared k_pe across heads and concat (reference
    concat_mla.cu semantics for MLA prefill head assembly)."""
    T, H, _ = k_nope.shape
    pe = jnp.broadcast_to(k_pe[:, None, :], (T, H, k_pe.shape[-1]))
    return jnp.concatenate([k_nope, pe.astype(k_nope.dtype)], axis=-1)
