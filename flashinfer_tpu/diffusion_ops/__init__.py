"""Diffusion-transformer (DiT) op namespace (reference
``flashinfer/diffusion_ops/__init__.py``)."""

from flashinfer_tpu.norm import (  # noqa: F401
    gate_residual,
    layernorm,
    layernorm_scale_shift,
    qk_rmsnorm,
)
from flashinfer_tpu.rope import apply_rope_pos_ids  # noqa: F401
