"""Compat shim: the wedge-pattern lint now lives in
``flashinfer_tpu.analysis.wedge`` as the L004 pass of the multi-pass
static analyzer (``python -m flashinfer_tpu.analysis``), behind the
shared driver, suppression, and baseline machinery.

This module re-exports the complete historical surface so existing
callers/tests keep working unchanged, but importing it now emits a
``DeprecationWarning``: the runtime compile guard goes straight to
``flashinfer_tpu.analysis.wedge``, and new code should too
(docs/migration.md "wedge_lint deprecation").
"""

from __future__ import annotations

import warnings

warnings.warn(
    "flashinfer_tpu.wedge_lint is a deprecated compat shim — the wedge "
    "lint is pass L004 of the multi-pass analyzer: run `python -m "
    "flashinfer_tpu.analysis` and import from "
    "flashinfer_tpu.analysis.wedge (docs/migration.md)",
    DeprecationWarning, stacklevel=2)

# the tests monkeypatch `wedge_lint.inspect` — it must be the same
# module object the implementation reads (modules are singletons)
import inspect  # noqa: F401,E402
import os  # noqa: F401,E402

from flashinfer_tpu.analysis.core import Finding  # noqa: F401,E402
from flashinfer_tpu.analysis.wedge import (  # noqa: F401,E402
    DMA_UNROLL_LIMIT,
    DOT_UNROLL_LIMIT,
    WedgeLintError,
    _module_findings,
    check_module,
    lint_file,
    lint_source,
    lint_tree,
)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="lint Pallas kernel sources for chip-wedging patterns")
    p.add_argument("paths", nargs="+")
    args = p.parse_args(argv)
    findings = []
    for path in args.paths:
        findings.extend(
            lint_tree(path) if os.path.isdir(path) else lint_file(path))
    for f in findings:
        print(f)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
