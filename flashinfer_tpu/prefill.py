"""Prefill/append attention: stateless single op + batch plan/run wrappers.

TPU-native re-design of the reference prefill layer
(``flashinfer/prefill.py:1117,1492,2947``; kernels prefill.cuh:2448-4057;
plan ``PrefillPlan``/``PrefillSplitQOKVIndptr`` scheduler.cuh:545-897).

The reference's plan bin-packs (request, qo-tile, kv-chunk) work units onto
CTAs.  The TPU design replaces that with *flattened token axes + segment
ids*: plan() lays all requests end-to-end on one padded token axis and
emits per-token segment/position arrays; the one flash kernel
(ops/flash_attention.py) then serves single, ragged-batch and paged-batch
prefill.  Padding is bucketed (powers of two) to bound recompiles.

For the paged case, plan() precomputes the flat cache-row gather index for
every kv token, so run() is gather + flash kernel — prefill is
compute-bound, so the one extra HBM pass is cheap relative to the matmuls
(documented trade-off; a fused paged-prefill kernel is a later
optimization).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from flashinfer_tpu.api_logging import flashinfer_api
import numpy as np

from flashinfer_tpu.ops.flash_attention import flash_attention
from flashinfer_tpu.ops.xla_ref import xla_ragged_attention
from flashinfer_tpu.utils import (
    check_kv_layout,
    check_pos_encoding_mode,
    fold_scalar_scale,
    get_alibi_slopes,
    get_sm_scale,
    next_power_of_two,
    normalize_backend,
    resolve_backend,
    TensorLayout,
)

_Q_PAD_SEG = -1
_KV_PAD_SEG = -2


def _apply_plan_rope(plan, q, k):
    """ROPE_LLAMA pre-pass shared by the batch wrappers' run() paths:
    rotate q/k at the plan's absolute positions (sub-16-bit caches upcast
    first — rotating in fp8 would re-quantize every key; bf16 keeps the
    native dtype, the same one-rounding a rotated-at-append cache has)."""
    if plan.rope is None:
        return q, k
    from flashinfer_tpu.rope import rotate_at_positions

    rs, rt = plan.rope
    if k.dtype.itemsize < 2:
        k = k.astype(jnp.bfloat16)
    return (rotate_at_positions(q, plan.q_pos, rs, rt),
            rotate_at_positions(k, plan.kv_pos, rs, rt))

# ALiBi rides the dense xla path, which materializes [H, Tq_pad, Tkv_pad]
# f32 logits; cap that tensor so a long-context ALiBi prefill fails with
# instructions instead of an opaque device OOM (the Pallas flash kernel
# has no bias mode yet — chunk the prefill or precompute additive masks)
_ALIBI_DENSE_LOGITS_CAP = 4 << 30


def _check_alibi_dense_size(num_heads: int, tq: int, tkv: int) -> None:
    need = num_heads * tq * tkv * 4
    if need > _ALIBI_DENSE_LOGITS_CAP:
        raise NotImplementedError(
            f"pos_encoding_mode='ALIBI' runs on the dense path; this "
            f"geometry needs {need / (1 << 30):.1f} GiB of logits "
            f"({num_heads} heads x {tq} x {tkv}). Chunk the prefill to "
            f"shorter qo spans (kv length is the roofline term that "
            f"matters) or open an issue for a biased flash kernel."
        )

# flash-kernel launch-geometry candidates: (block_q, block_kv).  The tactic
# space the reference explores per-arch via jinja template instantiation
# (prefill.cuh CTA_TILE_Q x CTA_TILE_KV) collapses on TPU to these two
# Pallas grid block sizes; VMEM (scratch = bq x D f32 + 2 x bq x 128) and
# MXU utilization trade off across them.
_FLASH_BLOCK_CANDIDATES = (
    (256, 512), (128, 512), (512, 512), (256, 1024), (128, 1024), (256, 256),
)


def flash_block_key(total_q, total_kv, num_qo_heads, num_kv_heads,
                    head_dim, dtype, causal) -> tuple:
    """The ``flash_attention.blocks`` tactic key for a shape — pow2-
    bucketed token axes keep the key space finite and make shipped-config
    keys hit across nearby lengths.  THE key builder: ``_tuned_flash``
    and bench.py's block-metadata lookup both call it, so the bench can
    never bank metadata under a desynced hand-copied key."""
    return (
        next_power_of_two(max(int(total_q), 16)),
        next_power_of_two(max(int(total_kv), 128)),
        num_qo_heads, num_kv_heads, head_dim, str(dtype), int(causal),
    )


def _tuned_flash(
    q, k, v, q_seg, kv_seg, q_pos, kv_pos, *,
    causal, sm_scale, logits_soft_cap, window_left, return_lse,
    alibi_slopes=None,
):
    """flash_attention with autotuned (block_q, block_kv).

    Zero-overhead outside an ``autotune()`` context: shipped v5e/v5p config
    or defaults are used (reference AutoTuner.choose_one over kernel
    tactics, autotuner.py:1419)."""
    from flashinfer_tpu.autotuner import AutoTuner

    from flashinfer_tpu.ops import flash_attention as _fa_module

    kwargs = dict(
        causal=causal, sm_scale=sm_scale, logits_soft_cap=logits_soft_cap,
        window_left=window_left, return_lse=return_lse,
    )
    if alibi_slopes is not None:
        kwargs["alibi_slopes"] = alibi_slopes
    key = flash_block_key(
        q.shape[0], k.shape[0], q.shape[1], k.shape[1], q.shape[2],
        q.dtype, causal,
    )
    bq, bkv = AutoTuner.get().choose_one(
        "flash_attention.blocks", key, _FLASH_BLOCK_CANDIDATES,
        lambda c: (lambda: flash_attention(
            q, k, v, q_seg, kv_seg, q_pos, kv_pos,
            block_q=c[0], block_kv=c[1], **kwargs,
        )),
        default=_FLASH_BLOCK_CANDIDATES[0],
        module=_fa_module,
    )
    from flashinfer_tpu import compile_guard

    try:
        return compile_guard.guarded(
            "flash_attention",
            # key buckets shapes; the remaining jit statics must also be in
            # the fingerprint so their recompiles stay inside the guard
            (key, int(bq), int(bkv), float(sm_scale),
             float(logits_soft_cap), int(window_left), bool(return_lse)),
            lambda: flash_attention(
                q, k, v, q_seg, kv_seg, q_pos, kv_pos,
                block_q=int(bq), block_kv=int(bkv), **kwargs,
            ),
            module=_fa_module,
        )
    except compile_guard.KernelQuarantined:
        return xla_ragged_attention(
            q, k, v, q_seg, kv_seg, q_pos, kv_pos, **kwargs
        )


@flashinfer_api
def single_prefill_with_kv_cache(
    q: jax.Array,  # [qo_len, num_qo_heads, head_dim]
    k: jax.Array,  # [kv_len, num_kv_heads, head_dim] (NHD) or HND
    v: jax.Array,
    scale_q: Optional[jax.Array] = None,
    scale_k: Optional[jax.Array] = None,
    scale_v: Optional[jax.Array] = None,
    o_dtype=None,
    custom_mask: Optional[jax.Array] = None,
    packed_custom_mask: Optional[jax.Array] = None,
    causal: bool = False,
    kv_layout: str = "NHD",
    pos_encoding_mode: str = "NONE",
    use_fp16_qk_reduction: bool = False,
    sm_scale: Optional[float] = None,
    window_left: int = -1,
    logits_soft_cap: Optional[float] = None,
    rope_scale: Optional[float] = None,
    rope_theta: Optional[float] = None,
    backend: str = "auto",
    return_lse: bool = False,
    kv_cache_sf=None,
    k_scale: Optional[float] = None,
    v_scale: Optional[float] = None,
):
    """Single-request prefill/append attention (reference
    ``single_prefill_with_kv_cache``, flashinfer/prefill.py:1117) with
    the reference's FULL kwargs surface and positional order (scale_q/
    scale_k/scale_v sit between v and o_dtype).

    Causal alignment is bottom-right: query ``i`` attends to kv positions
    ``<= kv_len - qo_len + i`` (matching the reference's append semantics).
    ``custom_mask`` ([qo_len, kv_len] bool) / ``packed_custom_mask``
    (packbits form) route through the xla backend (dense mask — the
    reference's MaskMode::kCustom).

    Scale handling mirrors the reference fp8 regime by FOLDING:
    per-tensor scale_q/scale_k (and float k_scale, scalar
    kv_cache_sf[k]) multiply the softmax scale; scale_v / v_scale /
    kv_cache_sf[v] multiply the output.  Non-scalar (per-head/block)
    scale tensors are a different numerics regime and are rejected.
    ``use_fp16_qk_reduction`` is a CUDA-accumulator knob (inert: the MXU
    accumulates f32).  ``pos_encoding_mode="ROPE_LLAMA"`` rotates q/k at
    their absolute positions as an elementwise pre-pass (rope_scale/
    rope_theta honored; position-equivalent to the reference's in-kernel
    rotation) before any backend.  ``pos_encoding_mode="ALIBI"`` adds
    ``slope_h * (kv_pos - q_pos)`` to the scaled logits (reference
    variants.cuh:68) on the dense xla backend by default, in-kernel with
    explicit backend="pallas"."""
    check_pos_encoding_mode(pos_encoding_mode)  # typos raise KeyError
    alibi = pos_encoding_mode == "ALIBI"
    if check_kv_layout(kv_layout) == TensorLayout.HND:
        k = jnp.swapaxes(k, 0, 1)
        v = jnp.swapaxes(v, 0, 1)
    qo_len, _, head_dim = q.shape
    kv_len = k.shape[0]
    sm_scale = get_sm_scale(head_dim, sm_scale)
    if pos_encoding_mode == "ROPE_LLAMA":
        # in-attention RoPE (reference applies it in-kernel from an
        # unrotated cache): rotate q at its bottom-right-aligned absolute
        # positions and k at 0..kv_len-1 as an elementwise pre-pass —
        # position-equivalent, and every backend (incl. the flash
        # kernel) then serves the rotated tensors at full speed
        from flashinfer_tpu.rope import rotate_at_positions

        q = rotate_at_positions(
            q, jnp.arange(qo_len, dtype=jnp.int32) + (kv_len - qo_len),
            rope_scale=rope_scale or 1.0, rope_theta=rope_theta or 1e4,
        )
        k = rotate_at_positions(
            k, jnp.arange(kv_len, dtype=jnp.int32),
            rope_scale=rope_scale or 1.0, rope_theta=rope_theta or 1e4,
        )

    def _fold(x, name):
        return fold_scalar_scale(
            x, f"single_prefill_with_kv_cache {name}")

    out_mul = 1.0
    for s, nm in ((scale_q, "scale_q"), (scale_k, "scale_k"),
                  (k_scale, "k_scale")):
        f = _fold(s, nm)
        if f is not None:
            sm_scale *= f
    for s, nm in ((scale_v, "scale_v"), (v_scale, "v_scale")):
        f = _fold(s, nm)
        if f is not None:
            out_mul *= f
    if kv_cache_sf is not None:
        ksf, vsf = (kv_cache_sf if isinstance(kv_cache_sf, tuple)
                    else (kv_cache_sf, kv_cache_sf))
        ksf = _fold(ksf, "kv_cache_sf[k]")
        vsf = _fold(vsf, "kv_cache_sf[v]")
        sm_scale *= 1.0 if ksf is None else ksf
        out_mul *= 1.0 if vsf is None else vsf
    if packed_custom_mask is not None and custom_mask is None:
        # reference mask-bit convention is LSB-first within each byte
        # (flashinfer packbits bitorder='little')
        bits = jnp.unpackbits(
            packed_custom_mask.view(jnp.uint8), count=qo_len * kv_len,
            bitorder="little",
        )
        custom_mask = bits.reshape(qo_len, kv_len).astype(bool)
    explicit_pallas = backend == "pallas"
    backend = resolve_backend(backend, "single_prefill")
    kw = {}
    if alibi:
        kw["alibi_slopes"] = get_alibi_slopes(q.shape[1])
        if explicit_pallas and custom_mask is None:
            # explicit backend="pallas": the flash kernel's in-kernel bias
            # (SMEM slope per grid head) — no dense logits tensor.
            # Opt-in until the biased kernel has an on-chip verdict.
            # (a custom_mask call still lands on the dense path below, so
            # it keeps the size guard in the else branch)
            pass
        else:
            _check_alibi_dense_size(q.shape[1], qo_len, kv_len)
            backend = "xla"  # auto: dense reference path until hw-banked
    args = (
        q, k, v,
        jnp.zeros((qo_len,), jnp.int32), jnp.zeros((kv_len,), jnp.int32),
        jnp.arange(qo_len, dtype=jnp.int32) + (kv_len - qo_len),
        jnp.arange(kv_len, dtype=jnp.int32),
    )
    if custom_mask is not None:
        # MaskMode::CUSTOM semantics (reference variants.cuh LogitsMask):
        # the custom mask replaces causal, but sliding window still ANDs in
        res = xla_ragged_attention(
            *args, custom_mask=custom_mask, causal=False,
            window_left=window_left, sm_scale=sm_scale,
            logits_soft_cap=logits_soft_cap or 0.0, return_lse=return_lse,
            **kw,
        )
    else:
        fn = _tuned_flash if backend == "pallas" else xla_ragged_attention
        res = fn(
            *args, causal=causal, sm_scale=sm_scale,
            logits_soft_cap=logits_soft_cap or 0.0,
            window_left=window_left, return_lse=return_lse, **kw,
        )
    if out_mul == 1.0 and o_dtype is None:
        return res
    o, lse = res if return_lse else (res, None)
    if out_mul != 1.0:
        o = (o.astype(jnp.float32) * out_mul).astype(o.dtype)
    if o_dtype is not None:
        o = o.astype(jnp.dtype(o_dtype))
    return (o, lse) if return_lse else o


def build_multi_item_mask(
    prefix_len: int,
    item_lens,
    qo_len: Optional[int] = None,
) -> jax.Array:
    """Mask for multi-item scoring (reference prefill.py multi-item params
    ``prefix_len_ptr``/``token_pos_in_items_ptr``): the sequence is a shared
    prefix followed by independent items; each item's tokens attend the
    prefix and their own item causally, never other items — one packed
    forward scores many candidate continuations (reward-model batching).

    Returns a [qo_len, kv_len] bool mask for the custom-mask path, where
    ``kv_len = prefix_len + sum(item_lens)`` and q covers the same tokens
    (pass ``qo_len`` for append-style suffixes covering only the tail).
    """
    import numpy as np

    item_lens = [int(x) for x in np.asarray(item_lens).reshape(-1)]
    kv_len = prefix_len + sum(item_lens)
    q_len = qo_len if qo_len is not None else kv_len
    off = kv_len - q_len  # q tokens are the tail of the kv axis
    mask = np.zeros((q_len, kv_len), bool)
    # prefix visible to everyone, causal within the prefix rows
    starts = [prefix_len]
    for l in item_lens:
        starts.append(starts[-1] + l)
    for qi in range(q_len):
        pos = qi + off
        if pos < prefix_len:
            mask[qi, : pos + 1] = True
            continue
        # which item does pos belong to?
        for s, e in zip(starts[:-1], starts[1:]):
            if s <= pos < e:
                mask[qi, :prefix_len] = True
                mask[qi, s : pos + 1] = True
                break
    return jnp.asarray(mask)


def _flat_mask_bits(qo_lens, kv_lens, custom_mask, packed_custom_mask):
    """Validate and normalize the reference's flat per-request mask concat
    (MaskMode::CUSTOM, packed LSB-first takes precedence) to a flat bool
    array of ``sum(qo_len*kv_len)`` bits.  Returns None if no mask."""
    total_bits = int(np.sum(qo_lens * kv_lens))
    if packed_custom_mask is not None:
        custom_mask = np.unpackbits(
            np.asarray(packed_custom_mask).view(np.uint8), bitorder="little"
        )[:total_bits].astype(bool)
    if custom_mask is None:
        return None
    flat = np.asarray(custom_mask).astype(bool).reshape(-1)
    if flat.size != total_bits:
        raise ValueError(
            f"custom_mask has {flat.size} bits; expected sum(qo_len*kv_len) "
            f"= {total_bits} (flat per-request concat, not a dense mask)"
        )
    return flat


def _expand_flat_mask(
    qo_indptr, kv_indptr, qo_lens, kv_lens, tq_pad, tkv_pad,
    custom_mask, packed_custom_mask,
):
    """Expand the flat mask into the dense [tq_pad, tkv_pad] mask the
    flattened-token-axis XLA backend consumes.  Returns None if no mask."""
    flat = _flat_mask_bits(qo_lens, kv_lens, custom_mask, packed_custom_mask)
    if flat is None:
        return None
    dense = np.zeros((tq_pad, tkv_pad), bool)
    off = 0
    for r in range(len(qo_lens)):
        qn, kn = int(qo_lens[r]), int(kv_lens[r])
        dense[
            int(qo_indptr[r]) : int(qo_indptr[r]) + qn,
            int(kv_indptr[r]) : int(kv_indptr[r]) + kn,
        ] = flat[off : off + qn * kn].reshape(qn, kn)
        off += qn * kn
    return jnp.asarray(dense)


@dataclass(frozen=True)
class _PrefillPlan:
    # token-axis fields are None in the "light" plan built for the fused
    # paged backend (deferred to the gather-plan builder on first fallback)
    q_seg: Optional[jax.Array]  # [Tq_pad] int32 (-1 pad)
    q_pos: Optional[jax.Array]  # [Tq_pad]
    kv_seg: Optional[jax.Array]  # [Tkv_pad] int32 (-2 pad)
    kv_pos: Optional[jax.Array]  # [Tkv_pad]
    kv_gather_rows: Optional[jax.Array]  # [Tkv_pad] flat cache rows (paged)
    out_scatter: Optional[jax.Array]  # [Tq_pad] original token idx (unpad)
    total_q: int
    total_kv: int
    tq_pad: int
    tkv_pad: int
    batch_size: int
    num_qo_heads: int
    num_kv_heads: int
    head_dim: int
    page_size: int
    causal: bool
    sm_scale: float
    logits_soft_cap: float
    window_left: int
    custom_mask: Optional[jax.Array] = None  # [Tq_pad, Tkv_pad] bool (dense)
    # pos_encoding_mode="ALIBI": plan-derived slope vector (dense xla path)
    alibi_slopes: Optional[jax.Array] = None
    # pos_encoding_mode="ROPE_LLAMA": (rope_scale, rope_theta) — q/k are
    # rotated at plan positions in run() (any backend)
    rope: Optional[Tuple[float, float]] = None
    # ISSUE 14 ingest-mode plan static: True = run_ingest() launches the
    # fused RoPE+quantize-append+attention kernel, False = it composes
    # the separate ops, None = resolve lazily (knob -> cost-model
    # chooser) on first run_ingest()
    fused_ingest: Optional[bool] = None


_INGEST_PROJECT_CACHE: list = []  # one-element AST-project cache


def _ingest_vmem_feasible(fused_key) -> bool:
    """Prune the fused-ingest candidate through the L009 VMEM
    evaluator before the roofline race (the decode.py
    ``_split_vmem_feasible`` pattern).  The ingest launcher's own
    binding (``prefill.fused_ingest``) registers the launch but its
    scratch shapes hinge on launch statics the key does not carry —
    per the binding's contract the compile-feasibility proof rides the
    ``fused_prefill.blocks`` evaluation of the shared chunk/tile
    shapes, priced at the (block_q, pages_per_chunk) tactic the ingest
    launch would actually run with (same key, same tuner lookup and
    default as plan()).  The evaluator is a LOWER bound, so False is a
    proof of infeasibility; anything unresolvable (or any analysis
    failure) keeps the candidate — pruning must never be a guess."""
    try:
        from flashinfer_tpu.analysis.core import Project
        from flashinfer_tpu.analysis.vmem_budget import (KNOB_LAUNCHES,
                                                         _estimate)
        from flashinfer_tpu.autotuner import AutoTuner
        from flashinfer_tpu.obs import hwspec
        from flashinfer_tpu.ops import paged_prefill as _pp

        if not _INGEST_PROJECT_CACHE:
            _INGEST_PROJECT_CACHE.append(
                Project.from_paths([_pp.__file__]))
        page_size = int(fused_key[5])
        bq, ppc = AutoTuner.get().lookup(
            "fused_prefill.blocks", fused_key,
            default=(128, max(1, 128 // page_size)))
        est = _estimate(
            _INGEST_PROJECT_CACHE[0],
            KNOB_LAUNCHES["fused_prefill.blocks"],
            (int(bq), int(ppc)), [str(f) for f in fused_key])
        if est is None:
            return True
        total, declared, _launcher = est
        budget = declared if declared is not None \
            else hwspec.current_spec().vmem_bytes
        return total <= budget
    except Exception:
        return True


def resolve_prefill_ingest(
    fused_key, *, total_q: int, total_kv: int, num_qo_heads: int,
    num_kv_heads: int, head_dim: int, q_bytes: int = 2,
    kv_bytes: int = 2, cache_bytes: int = 2,
) -> bool:
    """Resolve the ``prefill.fused_ingest`` knob for one shape: a
    shipped/tuned config entry wins; absent entries default via the
    cost-model chooser (``costmodel.predict_prefill_ingest_win`` — the
    ``choose_decode_splits`` pattern: the fused launch must beat the
    separate-op composition by >2% predicted time or the proven
    composition stays).  THE single resolution point — the wrapper,
    MixedServingStep, and the engine all route here so the knob can
    never mean different things per surface."""
    from flashinfer_tpu.autotuner import AutoTuner

    v = AutoTuner.get().lookup("prefill.fused_ingest", fused_key,
                               default=None)
    if v is not None:
        return str(v) == "on"
    from flashinfer_tpu.obs import costmodel, hwspec

    spec = hwspec.current_spec()
    use, _ = costmodel.predict_prefill_ingest_win(
        total_q, total_kv, num_qo_heads, num_kv_heads, head_dim,
        hbm_tbps=spec.hbm_tbps, peak_tflops=spec.peak_tflops("bf16"),
        q_bytes=q_bytes, kv_bytes=kv_bytes, cache_bytes=cache_bytes,
        feasible=lambda: _ingest_vmem_feasible(fused_key))
    return use


def _build_token_axis(
    indptr: np.ndarray, pad_to: int, pad_seg: int, pos_offset: np.ndarray
):
    """Flatten ragged requests to one token axis: returns (seg, pos, total).
    Hot host loop -> native planner (csrc/planner.cpp token_axis_plan)."""
    from flashinfer_tpu import native

    seg, pos = native.token_axis_plan(indptr, pos_offset, pad_to, pad_seg)
    return seg, pos, int(indptr[-1])


class BatchPrefillWithRaggedKVCacheWrapper:
    """Ragged-KV batch prefill (reference
    ``BatchPrefillWithRaggedKVCacheWrapper``, flashinfer/prefill.py:2947)."""

    def __init__(
        self,
        float_workspace_buffer=None,
        kv_layout: str = "NHD",
        use_cuda_graph: bool = False,
        backend: str = "auto",
        jit_args=None,
        **_unused,
    ):
        check_kv_layout(kv_layout)
        self._kv_layout = kv_layout
        self._backend = normalize_backend(backend)
        self._plan: Optional[_PrefillPlan] = None
        # reference custom-variant declaration (prefill.py:2947 jit_args):
        # positions 7/9 name the extra run() tensors/scalars in call
        # order.  The TPU build has no jinja codegen, but the DECLARED
        # extras define how positional run() extras are interpreted —
        # "sink" (LSE epilogue) and "sm_scale" (plan rebind) are honored,
        # anything else is rejected loudly.
        self._extra_names: tuple = ()
        if jit_args is not None and len(jit_args) >= 10:
            self._extra_names = tuple(jit_args[7]) + tuple(jit_args[9])

    def plan(
        self,
        qo_indptr,
        kv_indptr,
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        custom_mask=None,  # flat concat of per-request [qo_i*kv_i] bools
        packed_custom_mask=None,  # packbits(LSB-first) form; takes precedence
        causal: bool = False,
        pos_encoding_mode: str = "NONE",
        window_left: int = -1,
        logits_soft_cap: Optional[float] = None,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        kv_data_type=None,
        rope_scale: Optional[float] = None,
        rope_theta: Optional[float] = None,
        **_unused,
    ) -> None:
        check_pos_encoding_mode(pos_encoding_mode)  # typos raise KeyError
        replan = self._plan is not None
        alibi = pos_encoding_mode == "ALIBI"
        rope = (
            (rope_scale or 1.0, rope_theta or 1e4)
            if pos_encoding_mode == "ROPE_LLAMA" else None
        )
        qo_indptr = np.asarray(qo_indptr)
        kv_indptr = np.asarray(kv_indptr)
        batch = len(qo_indptr) - 1
        qo_lens = qo_indptr[1:] - qo_indptr[:-1]
        kv_lens = kv_indptr[1:] - kv_indptr[:-1]
        tq_pad = max(next_power_of_two(int(qo_indptr[-1])), 128)
        tkv_pad = max(next_power_of_two(int(kv_indptr[-1])), 128)
        if alibi:
            _check_alibi_dense_size(num_qo_heads, tq_pad, tkv_pad)
        # bottom-right causal alignment: q token i of request r sits at
        # absolute position kv_len_r - qo_len_r + i
        q_seg, q_pos, total_q = _build_token_axis(
            qo_indptr, tq_pad, _Q_PAD_SEG, kv_lens - qo_lens
        )
        kv_seg, kv_pos, total_kv = _build_token_axis(
            kv_indptr, tkv_pad, _KV_PAD_SEG, np.zeros(batch, np.int64)
        )
        # MaskMode::CUSTOM: causal is ignored; window still applies
        dense_mask = _expand_flat_mask(
            qo_indptr, kv_indptr, qo_lens, kv_lens, tq_pad, tkv_pad,
            custom_mask, packed_custom_mask,
        )
        if dense_mask is not None:
            causal = False  # custom mask overrides causal (only)
        self._plan = _PrefillPlan(
            q_seg=jnp.asarray(q_seg), q_pos=jnp.asarray(q_pos),
            kv_seg=jnp.asarray(kv_seg), kv_pos=jnp.asarray(kv_pos),
            kv_gather_rows=None,
            out_scatter=jnp.arange(tq_pad, dtype=jnp.int32),
            total_q=total_q, total_kv=total_kv,
            tq_pad=tq_pad, tkv_pad=tkv_pad, batch_size=batch,
            num_qo_heads=num_qo_heads, num_kv_heads=num_kv_heads,
            head_dim=head_dim, page_size=0,
            causal=causal, sm_scale=get_sm_scale(head_dim, sm_scale),
            logits_soft_cap=logits_soft_cap or 0.0, window_left=window_left,
            custom_mask=dense_mask,
            alibi_slopes=(
                get_alibi_slopes(num_qo_heads) if alibi else None
            ),
            rope=rope,
        )
        from flashinfer_tpu import obs

        obs.record_plan(
            self, replan=replan,
            padded_vs_actual=(("q_tokens", tq_pad, total_q),
                              ("kv_tokens", tkv_pad, total_kv)),
            statics=self._plan,  # retrace-cause diff source (obs.spans)
        )

    def run(
        self,
        q: jax.Array,  # [total_q, num_qo_heads, head_dim]
        k: jax.Array,  # [total_kv, num_kv_heads, head_dim]
        v: jax.Array,
        *extra,
        return_lse: bool = False,
    ):
        plan = self._plan
        if plan is None:
            raise RuntimeError("plan() must be called before run()")
        sink = None
        if extra:
            # custom-variant positional extras, in the ctor-declared order
            # (e.g. the attention-sink module: run(q, k, v, sink,
            # sm_scale)).  sm_scale is PER-CALL (reference kernels take
            # it as a run scalar): it overrides the plan locally, never
            # stickily.
            if len(extra) > len(self._extra_names):
                raise TypeError(
                    f"run() got {len(extra)} positional extras but the "
                    f"wrapper declares {self._extra_names or 'none'} "
                    "(pass jit_args at construction)")
            for name, val in zip(self._extra_names, extra):
                if name == "sink":
                    sink = jnp.asarray(val)
                elif name == "sm_scale":
                    if val is not None and float(val) != plan.sm_scale:
                        import dataclasses

                        from flashinfer_tpu import obs

                        obs.counter_inc("plan.sm_scale_rebinds",
                                        wrapper=type(self).__name__)
                        plan = dataclasses.replace(
                            plan, sm_scale=float(val))
                else:
                    raise NotImplementedError(
                        f"custom-variant extra {name!r} has no TPU "
                        "implementation (supported: sink, sm_scale)")
        if sink is not None:
            from flashinfer_tpu.attention import sink_epilogue

            out, lse = self._run_planned(plan, q, k, v, return_lse=True)
            return sink_epilogue(out, lse, sink, return_lse)
        return self._run_planned(plan, q, k, v, return_lse=return_lse)

    def _run_planned(self, plan, q, k, v, *, return_lse: bool):
        tq, tkv = plan.tq_pad, plan.tkv_pad
        if q.shape[0] != tq:
            q = jnp.pad(q, ((0, tq - q.shape[0]), (0, 0), (0, 0)))
        if k.shape[0] != tkv:
            k = jnp.pad(k, ((0, tkv - k.shape[0]), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, tkv - v.shape[0]), (0, 0), (0, 0)))
        q, k = _apply_plan_rope(plan, q, k)
        backend = resolve_backend(self._backend, "batch_prefill_ragged")
        alibi_kw = {}
        if plan.alibi_slopes is not None:
            backend = "xla"  # the bias term lives on the dense path
            alibi_kw["alibi_slopes"] = plan.alibi_slopes
        if plan.custom_mask is not None:
            # custom-mask mode runs on the dense xla backend; sliding window
            # still ANDs in (reference variants.cuh LogitsMask — only causal
            # is subsumed by the custom mask)
            out = xla_ragged_attention(
                q, k, v, plan.q_seg, plan.kv_seg, plan.q_pos, plan.kv_pos,
                causal=False, sm_scale=plan.sm_scale,
                logits_soft_cap=plan.logits_soft_cap,
                window_left=plan.window_left,
                return_lse=return_lse, custom_mask=plan.custom_mask,
                **alibi_kw,
            )
        else:
            fn = _tuned_flash if backend == "pallas" else xla_ragged_attention
            out = fn(
                q, k, v, plan.q_seg, plan.kv_seg, plan.q_pos, plan.kv_pos,
                causal=plan.causal, sm_scale=plan.sm_scale,
                logits_soft_cap=plan.logits_soft_cap,
                window_left=plan.window_left, return_lse=return_lse,
                **alibi_kw,
            )
        if return_lse:
            return out[0][: plan.total_q], out[1][: plan.total_q]
        return out[: plan.total_q]

    forward = run

    def run_return_lse(self, q, k, v, *extra, **kw):
        """Reference ``run_return_lse`` (prefill.py:2900, partialmethod
        with return_lse=True)."""
        kw.pop("return_lse", None)
        return self.run(q, k, v, *extra, return_lse=True, **kw)

    forward_return_lse = run_return_lse

    def end_forward(self) -> None:
        pass


class BatchPrefillWithPagedKVCacheWrapper:
    """Paged-KV batch prefill/append (reference
    ``BatchPrefillWithPagedKVCacheWrapper``, flashinfer/prefill.py:1492).

    plan() precomputes flat gather rows for every kv token of every request;
    run() gathers the paged cache into the flattened ragged KV axis and
    invokes the segment flash kernel."""

    def __init__(
        self,
        float_workspace_buffer=None,
        kv_layout: str = "NHD",
        use_cuda_graph: bool = False,
        backend: str = "auto",
        **_unused,
    ):
        check_kv_layout(kv_layout)
        self._kv_layout = kv_layout
        self._backend = normalize_backend(backend)
        self._plan: Optional[_PrefillPlan] = None
        self._fused_plan = None  # work-unit plan for backend="pallas_fused"
        self._ingest_plan = None  # lazy ingest-mode plan (run_ingest)

    def plan(
        self,
        qo_indptr,
        paged_kv_indptr,
        paged_kv_indices,
        paged_kv_last_page_len,
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        page_size: int,
        causal: bool = False,
        custom_mask=None,  # flat concat of per-request [qo_i*kv_i] bools
        packed_custom_mask=None,  # packbits(LSB-first) form; takes precedence
        pos_encoding_mode: str = "NONE",
        window_left: int = -1,
        logits_soft_cap: Optional[float] = None,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        kv_data_type=None,
        rope_scale: Optional[float] = None,
        rope_theta: Optional[float] = None,
        fused_ingest: Optional[bool] = None,
        **_unused,
    ) -> None:
        check_pos_encoding_mode(pos_encoding_mode)  # typos raise KeyError
        replan = self._plan is not None
        alibi = pos_encoding_mode == "ALIBI"
        rope = (
            (rope_scale or 1.0, rope_theta or 1e4)
            if pos_encoding_mode == "ROPE_LLAMA" else None
        )
        self._ingest_plan = None  # rebuilt lazily per plan geometry
        qo_indptr = np.asarray(qo_indptr)
        kv_indptr_pages = np.asarray(paged_kv_indptr)
        kv_indices = np.asarray(paged_kv_indices)
        last_page_len = np.asarray(paged_kv_last_page_len)
        batch = len(qo_indptr) - 1
        pages_per_req = kv_indptr_pages[1:] - kv_indptr_pages[:-1]
        kv_lens = np.where(
            pages_per_req > 0,
            (pages_per_req - 1) * page_size + last_page_len,
            0,
        ).astype(np.int64)
        kv_indptr = np.concatenate([[0], np.cumsum(kv_lens)])
        qo_lens = qo_indptr[1:] - qo_indptr[:-1]

        tq_pad = max(next_power_of_two(int(qo_indptr[-1])), 128)
        tkv_pad = max(next_power_of_two(int(kv_indptr[-1])), 128)
        if alibi:
            _check_alibi_dense_size(num_qo_heads, tq_pad, tkv_pad)

        # paged-batch MaskMode::CUSTOM (reference prefill.py:1117-2947):
        # the fused work-unit kernel consumes the packed mask directly
        # (per-unit byte bitmaps, no dense [qo, kv] materialization —
        # reference analogue prefill.cuh:2682).  Packed input stays
        # packed end-to-end on the fused path (the native planner reads
        # LSB-first bytes); bool input is validated here; the gather
        # fallback expands densely, lazily, from the original args.
        mask_total_bits = int(np.sum(qo_lens * kv_lens))
        if packed_custom_mask is not None:
            mask_flat = np.asarray(packed_custom_mask).view(
                np.uint8
            ).reshape(-1)
            if mask_flat.size * 8 < mask_total_bits:
                raise ValueError(
                    f"packed_custom_mask has {mask_flat.size * 8} bits; "
                    f"expected sum(qo_len*kv_len) = {mask_total_bits}"
                )
        else:
            mask_flat = _flat_mask_bits(qo_lens, kv_lens, custom_mask, None)
        if mask_flat is not None:
            causal = False  # custom mask overrides causal (only)

        def build_gather_plan() -> _PrefillPlan:
            # token axes + flat gather rows — O(tkv_pad) host work that the
            # fused default never consumes; built lazily on first fallback
            dense_mask = _expand_flat_mask(
                qo_indptr, kv_indptr, qo_lens, kv_lens, tq_pad, tkv_pad,
                custom_mask, packed_custom_mask,
            )
            q_seg, q_pos, total_q = _build_token_axis(
                qo_indptr, tq_pad, _Q_PAD_SEG, kv_lens - qo_lens
            )
            kv_seg, kv_pos, total_kv = _build_token_axis(
                kv_indptr, tkv_pad, _KV_PAD_SEG, np.zeros(batch, np.int64)
            )
            from flashinfer_tpu import native

            rows = native.paged_gather_plan(
                kv_indptr, kv_indptr_pages, kv_indices, page_size, tkv_pad
            )
            return _PrefillPlan(
                q_seg=jnp.asarray(q_seg), q_pos=jnp.asarray(q_pos),
                kv_seg=jnp.asarray(kv_seg), kv_pos=jnp.asarray(kv_pos),
                kv_gather_rows=jnp.asarray(rows, dtype=jnp.int32),
                out_scatter=jnp.arange(tq_pad, dtype=jnp.int32),
                total_q=total_q, total_kv=total_kv,
                tq_pad=tq_pad, tkv_pad=tkv_pad, batch_size=batch,
                num_qo_heads=num_qo_heads, num_kv_heads=num_kv_heads,
                head_dim=head_dim, page_size=page_size,
                causal=causal, sm_scale=get_sm_scale(head_dim, sm_scale),
                logits_soft_cap=logits_soft_cap or 0.0,
                window_left=window_left,
                custom_mask=dense_mask,
                alibi_slopes=(
                    get_alibi_slopes(num_qo_heads) if alibi else None
                ),
                rope=rope,
            )

        self._gather_plan_builder = build_gather_plan
        # ALiBi is a dense-path mode (the fused kernel has no bias term);
        # in-attention RoPE needs the gathered token axis to rotate
        use_fused = (not alibi) and (rope is None) and (
            self._backend == "pallas_fused" or (
            # hardware-validated default for the TPU-preferred HND layout;
            # NHD would need a whole-cache transpose per run() to feed the
            # fused kernel's contiguous page DMAs, so it keeps gather+flash.
            # resolve_backend gates on is_tpu() and the env override, so
            # off-TPU auto stays on compiled XLA and FLASHINFER_TPU_BACKEND
            # =xla can force the fallback on TPU.
            self._backend == "auto"
            and check_kv_layout(self._kv_layout) == TensorLayout.HND
            and resolve_backend("auto", "batch_prefill_paged") == "pallas"
        ))
        if use_fused:
            from flashinfer_tpu.ops.paged_prefill import (
                build_prefill_work_units,
            )
            from flashinfer_tpu.autotuner import AutoTuner

            # (block_q, pages_per_chunk) comes from the shipped/tuned config;
            # profiling happens in run() (inside autotune()) where live
            # tensors exist, then the work-unit plan is rebuilt with the
            # winner — the raw indptr arrays are kept for that rebuild.
            fused_key = (
                batch, tq_pad, num_qo_heads, num_kv_heads, head_dim,
                page_size,
            )
            bq_u, ppc_u = AutoTuner.get().lookup(
                "fused_prefill.blocks", fused_key,
                default=(128, max(1, 128 // page_size)),
            )
            self._fused_raw = (
                np.asarray(qo_indptr), np.asarray(kv_indptr_pages),
                np.asarray(kv_indices), np.asarray(kv_lens), page_size,
                fused_key, mask_flat, mask_total_bits,
                causal, window_left,
            )
            self._fused_tuned = False
            units = build_prefill_work_units(
                qo_indptr, kv_indptr_pages, kv_indices, kv_lens,
                block_q=int(bq_u), pages_per_chunk=int(ppc_u),
                page_size=page_size, mask_flat=mask_flat,
                mask_total_bits=mask_total_bits,
                # the plan prunes + FULL-codes units under the SAME
                # causal/window the kernel will run with (paged_prefill
                # module contract)
                causal=causal, window_left=window_left,
            )
            statics = dict(
                num_units=units.pop("num_units"),
                block_q=units.pop("block_q"),
                pages_per_chunk=units.pop("pages_per_chunk"),
            )
            fused_stats = units.pop("stats")
            self._fused_plan = (
                {k: jnp.asarray(v) for k, v in units.items()}, statics,
            )
            # light plan: config fields only — the heavy gather arrays are
            # deferred to _gather_plan_builder on first fallback run()
            self._plan = _PrefillPlan(
                q_seg=None, q_pos=None, kv_seg=None, kv_pos=None,
                kv_gather_rows=None,
                out_scatter=None,
                total_q=int(qo_indptr[-1]), total_kv=int(kv_indptr[-1]),
                tq_pad=tq_pad, tkv_pad=tkv_pad, batch_size=batch,
                num_qo_heads=num_qo_heads, num_kv_heads=num_kv_heads,
                head_dim=head_dim, page_size=page_size,
                causal=causal, sm_scale=get_sm_scale(head_dim, sm_scale),
                logits_soft_cap=logits_soft_cap or 0.0,
                window_left=window_left,
                fused_ingest=fused_ingest,
            )
        else:
            self._fused_plan = None
            fused_stats = None
            self._plan = build_gather_plan()
        # plan-time work accounting (launched vs effective MXU cells,
        # tiles, pruned units) — the cost model's input for roofline
        # attribution (obs.costmodel.fused_prefill_from_stats)
        self._fused_stats = fused_stats
        from flashinfer_tpu import obs

        # work-unit fill axes ride the same padding-waste histograms the
        # token axes use, so the packing win (ISSUE 3 tentpole d) is
        # measurable: unit_rows = idle qo-tile rows across all units,
        # mxu_cells = idle (row, kv-col) positions across all MXU dots
        unit_axes = ()
        if fused_stats is not None:
            unit_axes = (
                ("prefill_unit_rows", fused_stats["unit_rows_total"],
                 fused_stats["unit_rows_valid"]),
                ("prefill_mxu_cells", fused_stats["mxu_cells_total"],
                 fused_stats["mxu_cells_valid"]),
            )
            if fused_stats["units_pruned"]:
                obs.counter_inc(
                    "plan.prefill_units_pruned",
                    fused_stats["units_pruned"],
                    wrapper=type(self).__name__,
                )
        obs.record_plan(
            self, replan=replan,
            padded_vs_actual=(("q_tokens", tq_pad, int(qo_indptr[-1])),
                              ("kv_tokens", tkv_pad, int(kv_indptr[-1])),
                              *unit_axes),
            statics=self._plan,  # retrace-cause diff source (obs.spans)
        )

    @property
    def fused_prefill_config(self) -> Optional[dict]:
        """The live fused-path launch config (block_q / pages_per_chunk /
        num_units) or None on the gather path — bench rows carry this as
        block-config metadata (docs/performance.md)."""
        if self._fused_plan is None:
            return None
        return dict(self._fused_plan[1])

    @property
    def fused_prefill_stats(self) -> Optional[dict]:
        """The live plan's post-pruning/post-packing work accounting
        (``build_prefill_work_units`` ``stats``: units/tiles/pruned +
        launched-vs-valid unit rows and MXU cells), or None on the
        gather path — obs.costmodel derives launched-vs-effective
        roofline work from this."""
        if self._fused_plan is None or self._fused_stats is None:
            return None
        return dict(self._fused_stats)

    @property
    def plan_arrays(self) -> dict:
        """Export the frozen gather-path plan arrays + statics for
        closure into a compile-once mixed serving step
        (``flashinfer_tpu.serve.step.MixedServingStep``): the flattened
        token axes (``q_seg``/``q_pos``/``kv_seg``/``kv_pos``), the
        flat paged-cache gather rows, the padded extents, and the
        attention statics.  The light fused-path plan defers these
        arrays; exporting materializes the gather plan once (same
        contract as a ``return_lse`` fallback run), preserving any live
        sm_scale / soft-cap rebind."""
        if self._plan is None:
            raise RuntimeError("plan() must be called before plan_arrays")
        plan = self._materialize_gather_plan()
        return dict(
            q_seg=plan.q_seg, q_pos=plan.q_pos,
            kv_seg=plan.kv_seg, kv_pos=plan.kv_pos,
            kv_gather_rows=plan.kv_gather_rows,
            total_q=plan.total_q, total_kv=plan.total_kv,
            tq_pad=plan.tq_pad, tkv_pad=plan.tkv_pad,
            batch_size=plan.batch_size,
            num_qo_heads=plan.num_qo_heads,
            num_kv_heads=plan.num_kv_heads,
            head_dim=plan.head_dim, page_size=plan.page_size,
            causal=plan.causal, sm_scale=plan.sm_scale,
            logits_soft_cap=plan.logits_soft_cap,
            window_left=plan.window_left,
            kv_layout=self._kv_layout,
        )

    def _materialize_gather_plan(self) -> "_PrefillPlan":
        """Materialize the deferred gather plan if the light fused-path
        plan is live (the builder recomputes PLANNED values, so any
        live sm_scale / logits_soft_cap rebind is carried over) — the
        ONE copy of this logic, shared by run()'s return_lse fallback
        and the ``plan_arrays`` export.  Returns the (possibly new)
        live plan."""
        plan = self._plan
        if plan.kv_gather_rows is None:
            new_plan = self._gather_plan_builder()
            if new_plan.sm_scale != plan.sm_scale \
                    or new_plan.logits_soft_cap != plan.logits_soft_cap:
                import dataclasses

                new_plan = dataclasses.replace(
                    new_plan, sm_scale=plan.sm_scale,
                    logits_soft_cap=plan.logits_soft_cap)
            plan = self._plan = new_plan
        return plan

    def _rebind_sm_scale(self, *, absolute=None, multiplier=None):
        """Per-call sm_scale override: swap in a plan with the new scale
        and return the plan to restore in the caller's ``finally`` (or
        None if nothing changed).  A later lazy gather-plan rebuild
        preserves the live rebind (see run()'s materialization site), so
        no eager plan build is needed here."""
        if self._plan is None or (absolute is None and multiplier is None):
            return None
        new = (float(absolute) if absolute is not None
               else self._plan.sm_scale * float(multiplier))
        if new == self._plan.sm_scale:
            return None
        import dataclasses

        from flashinfer_tpu import obs

        obs.counter_inc("plan.sm_scale_rebinds", wrapper=type(self).__name__)
        restore = self._plan
        self._plan = dataclasses.replace(restore, sm_scale=new)
        return restore

    def _rebind_soft_cap(self, soft_cap):
        """Per-call logits_soft_cap override (the reference forwards the
        run() value to the kernel, attention/_core.py:250): swap in a
        plan with the new cap and return the plan to restore in the
        caller's ``finally`` (None if unchanged).  The cap is a kernel
        jit-static, so a novel value compiles a fresh variant — the same
        frozen-plan-replace contract as ``_rebind_sm_scale``."""
        if self._plan is None or soft_cap is None:
            return None
        new = float(soft_cap)
        if new == self._plan.logits_soft_cap:
            return None
        import dataclasses

        from flashinfer_tpu import obs

        obs.counter_inc("plan.soft_cap_rebinds", wrapper=type(self).__name__)
        restore = self._plan
        self._plan = dataclasses.replace(restore, logits_soft_cap=new)
        return restore

    def run(
        self,
        q: jax.Array,  # [total_q, num_qo_heads, head_dim]
        paged_kv_cache: Union[Tuple[jax.Array, jax.Array], jax.Array],
        *,
        k_scale=None,
        v_scale=None,
        sinks=None,
        out=None,
        lse=None,
        return_lse: bool = False,
    ):
        plan = self._plan
        if plan is None:
            raise RuntimeError("plan() must be called before run()")
        if out is not None or lse is not None:
            raise NotImplementedError(
                "pre-allocated out=/lse= buffers are not supported (XLA "
                "owns buffers; docs/migration.md)")
        if k_scale is not None or v_scale is not None or sinks is not None:
            # reference per-run kwargs (prefill.py:2520): k_scale folds
            # into sm_scale FOR THIS CALL, v_scale scales the output,
            # sinks renormalize via the LSE epilogue.  The inner call is
            # NON-VIRTUAL: a subclass run (e.g. the sink wrapper's) must
            # not re-apply its own epilogue on this internal re-entry.
            restore_plan = self._rebind_sm_scale(multiplier=k_scale)
            try:
                need_lse = return_lse or sinks is not None
                res = BatchPrefillWithPagedKVCacheWrapper.run(
                    self, q, paged_kv_cache, return_lse=need_lse)
            finally:
                if restore_plan is not None:
                    self._plan = restore_plan
            o, l = res if need_lse else (res, None)
            if sinks is not None:
                from flashinfer_tpu.attention import sink_epilogue

                res2 = sink_epilogue(o, l, sinks, return_lse)
                o, l = res2 if return_lse else (res2, None)
            if v_scale is not None:
                o = (o.astype(jnp.float32) * float(v_scale)).astype(o.dtype)
            return (o, l) if return_lse else o
        if isinstance(paged_kv_cache, tuple):
            k_cache, v_cache = paged_kv_cache
        else:
            k_cache, v_cache = paged_kv_cache[:, 0], paged_kv_cache[:, 1]
        if self._fused_plan is not None and not return_lse:
            # fused work-unit kernel: KV pages DMA'd straight from the cache
            from flashinfer_tpu import compile_guard
            from flashinfer_tpu.ops import paged_prefill as _pp_module
            from flashinfer_tpu.ops.paged_prefill import fused_paged_prefill

            if check_kv_layout(self._kv_layout) == TensorLayout.NHD:
                k_hnd = jnp.swapaxes(k_cache, 1, 2)
                v_hnd = jnp.swapaxes(v_cache, 1, 2)
            else:
                k_hnd, v_hnd = k_cache, v_cache
            unit_plan, statics = self._fused_plan
            total_q = q.shape[0]
            # bucketed q padding bounds recompiles (same contract as the
            # gather path; pad rows are touched by no work unit)
            if total_q != plan.tq_pad:
                q = jnp.pad(q, ((0, plan.tq_pad - total_q), (0, 0), (0, 0)))

            from flashinfer_tpu.autotuner import AutoTuner

            tuner = AutoTuner.get()
            if tuner.tuning_enabled and not self._fused_tuned:
                self._fused_tuned = True
                from flashinfer_tpu.ops.paged_prefill import (
                    build_prefill_work_units,
                )

                (qo_i, kvp_i, kvi_i, kvl_i, ps, fkey, mflat,
                 mbits, causal_p, wl_p) = self._fused_raw
                from flashinfer_tpu.ops.paged_prefill import (
                    block_candidates,
                )

                # the shared grid (W002-safe chunk ceiling documented at
                # the definition) — the offline sweep explores the same
                cands = block_candidates(ps)

                def _build(c):
                    u = build_prefill_work_units(
                        qo_i, kvp_i, kvi_i, kvl_i,
                        block_q=c[0], pages_per_chunk=c[1], page_size=ps,
                        mask_flat=mflat, mask_total_bits=mbits,
                        causal=causal_p, window_left=wl_p,
                    )
                    st = dict(
                        num_units=u.pop("num_units"),
                        block_q=u.pop("block_q"),
                        pages_per_chunk=u.pop("pages_per_chunk"),
                    )
                    stats = u.pop("stats")
                    return ({k2: jnp.asarray(v2) for k2, v2 in u.items()},
                            st, stats)

                def _runner(c):
                    up, st, _ = _build(c)
                    return lambda: fused_paged_prefill(
                        q, k_hnd, v_hnd, up,
                        sm_scale=plan.sm_scale,
                        logits_soft_cap=plan.logits_soft_cap,
                        window_left=plan.window_left, causal=plan.causal,
                        **st,
                    )

                cur = (statics["block_q"], statics["pages_per_chunk"])
                best = tuner.choose_one(
                    "fused_prefill.blocks", fkey, cands, _runner, default=cur,
                    module=_pp_module,
                )
                best = (int(best[0]), int(best[1]))
                if best != cur:
                    # stats are per-block-config (unit/tile/cell counts):
                    # the retuned plan must refresh them or the cost
                    # model would attribute the OLD launch shape
                    unit_plan, statics, self._fused_stats = _build(best)
                    self._fused_plan = (unit_plan, statics)

            try:
                out = compile_guard.guarded(
                    "fused_paged_prefill",
                    (q.shape, k_hnd.shape, str(q.dtype), plan.causal,
                     plan.window_left, float(plan.sm_scale),
                     float(plan.logits_soft_cap),
                     "mask_bytes" in unit_plan,  # masked kernel variant
                     tuple(sorted(statics.items()))),
                    lambda: fused_paged_prefill(
                        q, k_hnd, v_hnd, unit_plan,
                        sm_scale=plan.sm_scale,
                        logits_soft_cap=plan.logits_soft_cap,
                        window_left=plan.window_left, causal=plan.causal,
                        **statics,
                    ),
                    module=_pp_module,
                )
                return out[:total_q]
            except compile_guard.KernelQuarantined:
                pass  # fall through to the gather + flash path below
        if plan.kv_gather_rows is None:
            # fused plan was active but this call needs the gather path
            # (return_lse): materialize the deferred plan once, rebinds
            # preserved (shared helper with the plan_arrays export)
            plan = self._materialize_gather_plan()
        if check_kv_layout(self._kv_layout) == TensorLayout.HND:
            k_cache = jnp.swapaxes(k_cache, 1, 2)
            v_cache = jnp.swapaxes(v_cache, 1, 2)
        # [num_pages, page_size, Hkv, D] -> row gather
        kflat = k_cache.reshape(-1, *k_cache.shape[2:])
        vflat = v_cache.reshape(-1, *v_cache.shape[2:])
        k = kflat[plan.kv_gather_rows]
        v = vflat[plan.kv_gather_rows]
        tq = plan.tq_pad
        if q.shape[0] != tq:
            q = jnp.pad(q, ((0, tq - q.shape[0]), (0, 0), (0, 0)))
        q, k = _apply_plan_rope(plan, q, k)
        alibi_kw = {}
        if plan.alibi_slopes is not None:
            alibi_kw["alibi_slopes"] = plan.alibi_slopes
        if plan.custom_mask is not None:
            # paged-batch MaskMode::CUSTOM runs on the dense xla backend
            # over the gathered KV (same contract as the ragged wrapper)
            out = xla_ragged_attention(
                q, k, v, plan.q_seg, plan.kv_seg, plan.q_pos, plan.kv_pos,
                causal=False, sm_scale=plan.sm_scale,
                logits_soft_cap=plan.logits_soft_cap,
                window_left=plan.window_left, return_lse=return_lse,
                custom_mask=plan.custom_mask, **alibi_kw,
            )
        else:
            backend = resolve_backend(
                "pallas" if self._backend == "pallas_fused" else self._backend,
                "batch_prefill_paged",
            )
            if plan.alibi_slopes is not None:
                backend = "xla"  # the bias term lives on the dense path
            fn = _tuned_flash if backend == "pallas" else xla_ragged_attention
            out = fn(
                q, k, v, plan.q_seg, plan.kv_seg, plan.q_pos, plan.kv_pos,
                causal=plan.causal, sm_scale=plan.sm_scale,
                logits_soft_cap=plan.logits_soft_cap,
                window_left=plan.window_left, return_lse=return_lse,
                **alibi_kw,
            )
        if return_lse:
            return out[0][: plan.total_q], out[1][: plan.total_q]
        return out[: plan.total_q]

    forward = run

    def _resolve_ingest(self) -> bool:
        """The plan's ``fused_ingest`` static, resolved at most once:
        an explicit plan(fused_ingest=) wins; None routes through
        :func:`resolve_prefill_ingest` (knob -> cost-model chooser).
        The resolution is frozen back onto the plan so the flight
        recorder's replan diffs see which mode served."""
        plan = self._plan
        if plan.fused_ingest is None:
            fkey = self._fused_raw[5]
            resolved = resolve_prefill_ingest(
                fkey, total_q=plan.total_q, total_kv=plan.total_kv,
                num_qo_heads=plan.num_qo_heads,
                num_kv_heads=plan.num_kv_heads, head_dim=plan.head_dim)
            import dataclasses

            self._plan = plan = dataclasses.replace(
                plan, fused_ingest=resolved)
        return bool(plan.fused_ingest)

    def _ingest_positions(self):
        """Host-side (q_pos, kv_pos, kv_req) of the planned geometry —
        the separate-op composition's rotation/append coordinates
        (kv positions 0..kv_len-1 per request: run_ingest serves the
        from-scratch prefill form, where the raw rows ARE the kv)."""
        qo_i, _, _, kvl_i = self._fused_raw[:4]
        B = len(qo_i) - 1
        qo_lens = (qo_i[1:] - qo_i[:-1]).astype(np.int64)
        kvl = np.asarray(kvl_i, np.int64)
        kv_pos = np.concatenate(
            [np.arange(n) for n in kvl] or [np.zeros(0)]).astype(np.int32)
        kv_req = np.repeat(np.arange(B), kvl).astype(np.int32)
        q_pos = np.concatenate(
            [np.arange(n) + (kvl[r] - n)
             for r, n in enumerate(qo_lens)] or [np.zeros(0)]
        ).astype(np.int32)
        return q_pos, kv_pos, kv_req

    def run_ingest(
        self,
        q: jax.Array,  # [total_q, num_qo_heads, head_dim] RAW (pre-RoPE)
        k_new: jax.Array,  # [total_kv, num_kv_heads, head_dim] RAW
        v_new: jax.Array,
        paged_kv_cache: Tuple[jax.Array, jax.Array],
        *,
        rope_scale: float = 1.0,
        rope_theta: float = 1e4,
        rope_interleave: bool = False,
        k_scale: Optional[float] = None,
        v_scale: Optional[float] = None,
        return_lse: bool = False,
    ):
        """Fused prefill INGEST (ISSUE 14): RoPE + KV-quantize-append +
        attention over RAW pre-RoPE q/k/v in one launch.  The raw k/v
        rows ARE the planned KV axis (from-scratch prefill: positions
        0..kv_len-1 per request); returns ``(out, (k_cache, v_cache))``
        (+ ``lse`` in the middle with ``return_lse``) with the caches
        updated to exactly the bits the separate rotate -> quant-append
        composition writes (bit-for-bit, tests/test_prefill_ingest.py;
        rows past each sequence's end in its last partial page are
        deterministically zeroed — see fused_paged_prefill_ingest).

        Dispatch follows the ``fused_ingest`` plan static (explicit
        plan(fused_ingest=), else knob -> chooser): OFF composes the
        separate ops through the SAME entry point — the oracle tier —
        so A/B and fallback share one call shape.  ``k_scale`` /
        ``v_scale`` are the quant-append scales (high = code * scale)
        and are REQUIRED for int8/fp8 caches."""
        plan = self._plan
        if plan is None:
            raise RuntimeError("plan() must be called before run_ingest()")
        if self._fused_plan is None:
            raise NotImplementedError(
                "run_ingest needs the fused work-unit path (HND layout, "
                "no ALIBI/ROPE_LLAMA plan mode, TPU or "
                "FLASHINFER_TPU_BACKEND=pallas) — this plan resolved to "
                "the gather fallback")
        k_cache, v_cache = paged_kv_cache
        kv_quant = (
            "int8" if k_cache.dtype == jnp.int8 else
            "fp8" if k_cache.dtype in (jnp.float8_e4m3fn, jnp.float8_e5m2)
            else "none")
        if kv_quant != "none" and (k_scale is None or v_scale is None):
            raise ValueError(
                f"{kv_quant} KV cache needs explicit k_scale/v_scale "
                "(the quant-append scales: high_precision = code * scale)")
        ks = float(k_scale) if k_scale is not None else 1.0
        vs = float(v_scale) if v_scale is not None else 1.0
        if k_new.shape[0] != plan.total_kv:
            raise ValueError(
                f"k_new has {k_new.shape[0]} raw rows; the plan's kv "
                f"axis is {plan.total_kv} tokens (run_ingest ingests "
                "the WHOLE planned KV — from-scratch prefill)")

        if self._resolve_ingest():
            from flashinfer_tpu import compile_guard
            from flashinfer_tpu.ops import paged_prefill as _pp_module
            from flashinfer_tpu.ops.paged_prefill import (
                build_prefill_ingest_units, fused_paged_prefill_ingest,
            )

            _, statics = self._fused_plan
            if self._ingest_plan is None:
                (qo_i, kvp_i, kvi_i, kvl_i, ps, _fkey, mflat, mbits,
                 causal_p, wl_p) = self._fused_raw
                up = build_prefill_ingest_units(
                    qo_i, kvp_i, kvi_i, kvl_i,
                    block_q=statics["block_q"],
                    pages_per_chunk=statics["pages_per_chunk"],
                    page_size=ps, mask_flat=mflat, mask_total_bits=mbits,
                    causal=causal_p, window_left=wl_p,
                )
                ist = dict(
                    num_units=up.pop("num_units"),
                    block_q=up.pop("block_q"),
                    pages_per_chunk=up.pop("pages_per_chunk"),
                )
                self._ingest_stats = up.pop("stats")
                self._ingest_plan = (
                    {k2: jnp.asarray(v2) for k2, v2 in up.items()}, ist)
            unit_plan, ist = self._ingest_plan
            total_q = q.shape[0]
            if total_q != plan.tq_pad:
                q = jnp.pad(q, ((0, plan.tq_pad - total_q), (0, 0),
                                (0, 0)))
            try:
                res = compile_guard.guarded(
                    "fused_paged_prefill_ingest",
                    (q.shape, k_new.shape, str(q.dtype),
                     str(k_cache.dtype), plan.causal, plan.window_left,
                     float(plan.sm_scale), float(plan.logits_soft_cap),
                     rope_scale, rope_theta, rope_interleave, kv_quant,
                     ks, vs, return_lse,
                     "mask_bytes" in unit_plan,
                     tuple(sorted(ist.items()))),
                    lambda: fused_paged_prefill_ingest(
                        q, k_new, v_new, k_cache, v_cache, unit_plan,
                        sm_scale=plan.sm_scale,
                        logits_soft_cap=plan.logits_soft_cap,
                        window_left=plan.window_left, causal=plan.causal,
                        return_lse=return_lse, rope_scale=rope_scale,
                        rope_theta=rope_theta,
                        rope_interleave=rope_interleave,
                        kv_quant=kv_quant, k_scale=ks, v_scale=vs,
                        **ist,
                    ),
                    module=_pp_module,
                )
                if return_lse:
                    out, lse, caches = res
                    return out[:total_q], lse[:total_q], caches
                out, caches = res
                return out[:total_q], caches
            except compile_guard.KernelQuarantined:
                q = q[:total_q]  # fall through to the composed oracle

        # ---- the separate-op composition (the oracle tier) ----
        from flashinfer_tpu.page import (
            append_paged_kv_cache, append_paged_kv_cache_quant_fp8,
            append_paged_kv_cache_quant_int8,
        )
        from flashinfer_tpu.rope import rotate_at_positions_static

        q_pos, kv_pos, kv_req = self._ingest_positions()
        # static-scale/theta rotation — bitwise what the ingest kernel
        # computes (rotate_at_positions_static docstring: a traced
        # theta's runtime pow would drift the oracle ~1 ULP)
        q_rot = rotate_at_positions_static(
            q, jnp.asarray(q_pos), rope_scale=rope_scale,
            rope_theta=rope_theta, interleave=rope_interleave)
        k_rot = rotate_at_positions_static(
            k_new, jnp.asarray(kv_pos), rope_scale=rope_scale,
            rope_theta=rope_theta, interleave=rope_interleave)
        kvi = jnp.asarray(self._fused_raw[2])
        kvp = jnp.asarray(self._fused_raw[1])
        if kv_quant == "int8":
            caches = append_paged_kv_cache_quant_int8(
                k_rot, v_new, jnp.asarray(kv_req), jnp.asarray(kv_pos),
                (k_cache, v_cache), kvi, kvp, jnp.float32(ks),
                jnp.float32(vs), self._kv_layout)
        elif kv_quant == "fp8":
            caches = append_paged_kv_cache_quant_fp8(
                k_rot, v_new, jnp.asarray(kv_req), jnp.asarray(kv_pos),
                (k_cache, v_cache), kvi, kvp, jnp.float32(ks),
                jnp.float32(vs), self._kv_layout)
        else:
            caches = append_paged_kv_cache(
                k_rot, v_new, jnp.asarray(kv_req), jnp.asarray(kv_pos),
                (k_cache, v_cache), kvi, kvp, None, self._kv_layout)
        scale_kw = {}
        if kv_quant != "none":
            scale_kw = dict(k_scale=ks, v_scale=vs)
        res = self.run(q_rot, caches, return_lse=return_lse, **scale_kw)
        if return_lse:
            return res[0], res[1], caches
        return res, caches

    def run_return_lse(self, q, paged_kv_cache, **kw):
        """Reference ``run_return_lse`` (prefill.py:4075, partialmethod
        with return_lse=True)."""
        kw.pop("return_lse", None)
        return self.run(q, paged_kv_cache, return_lse=True, **kw)

    forward_return_lse = run_return_lse

    def end_forward(self) -> None:
        pass
