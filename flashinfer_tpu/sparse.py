"""Block-sparse attention wrappers.

TPU re-design of ``flashinfer/sparse.py`` (BlockSparseAttentionWrapper
sparse.py:195, VariableBlockSparseAttentionWrapper sparse.py:1075): BSR
attention where only listed (row-block, col-block) pairs are computed.
Fixed-size blocks go through the scalar-prefetch Pallas kernel
(ops/block_sparse.py); variable block sizes go through the segment flash
kernel with an expanded token-level mask via the xla path (documented v1
trade-off).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.ops.block_sparse import bsr_attention
from flashinfer_tpu.ops.xla_ref import xla_ragged_attention
from flashinfer_tpu.utils import get_sm_scale, next_power_of_two, resolve_backend


class BlockSparseAttentionWrapper:
    """BSR attention with plan/run lifecycle (reference sparse.py:195).

    plan() takes the BSR structure (indptr over row blocks, column-block
    indices) exactly like the reference's (indptr, indices, M, N, R, C)."""

    def __init__(self, float_workspace_buffer=None, backend: str = "auto",
                 **_unused):
        self._backend = backend
        self._plan = None

    def plan(
        self,
        indptr,  # [MB+1]
        indices,  # [nnz] column-block ids
        M: int,
        N: int,
        R: int,  # block row size
        C: int,  # block col size
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        mask=None,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        **_unused,
    ) -> None:
        if mask is not None:
            raise NotImplementedError("per-block bitmasks: later round")
        if M % R or N % C:
            raise ValueError("M/N must be multiples of R/C")
        from flashinfer_tpu import native

        indptr = np.asarray(indptr)
        indices = np.asarray(indices)
        MB = M // R
        nnz_per_row = indptr[1:] - indptr[:-1]
        max_nnz = max(next_power_of_two(int(nnz_per_row.max(initial=1))), 1)
        cols = native.bsr_plan(indptr, indices, max_nnz)
        self._plan = dict(
            indptr=jnp.asarray(indptr, dtype=jnp.int32),
            cols=jnp.asarray(cols),
            M=M, N=N, R=R, C=C, max_nnz=max_nnz,
            num_qo_heads=num_qo_heads, num_kv_heads=num_kv_heads,
            head_dim=head_dim,
            sm_scale=get_sm_scale(head_dim, sm_scale),
        )

    def run(self, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        p = self._plan
        if p is None:
            raise RuntimeError("plan() must be called before run()")
        backend = resolve_backend(self._backend, "block_sparse")
        if backend == "pallas":
            return bsr_attention(
                q, k, v, p["indptr"], p["cols"],
                block_row=p["R"], block_col=p["C"], max_nnz=p["max_nnz"],
                sm_scale=p["sm_scale"],
            )
        # xla fallback: expand BSR to a token-level segment trick — assign
        # each (row-block, col-block) nonzero its own "virtual request"
        # would duplicate tokens; instead use a dense mask reference.
        return _xla_bsr_dense(q, k, v, p)

    forward = run

    def end_forward(self) -> None:
        pass


def _dense_masked_attention(q, k, v, mask, sm_scale):
    """Dense masked-softmax attention over a [M, N] boolean mask (shared by
    both xla fallback paths)."""
    group = q.shape[1] // k.shape[1]
    qf = q.astype(jnp.float32)
    kf = jnp.repeat(k.astype(jnp.float32), group, axis=1)
    vf = jnp.repeat(v.astype(jnp.float32), group, axis=1)
    s = jnp.einsum("qhd,khd->hqk", qf, kf) * sm_scale
    s = jnp.where(mask[None], s, -1e30)
    m = jnp.max(s, -1, keepdims=True)
    pr = jnp.where(mask[None], jnp.exp(s - m), 0.0)
    l = jnp.sum(pr, -1, keepdims=True)
    out = jnp.einsum("hqk,khd->qhd", pr / jnp.where(l > 0, l, 1.0), vf)
    return out.astype(q.dtype)


def _xla_bsr_dense(q, k, v, p):
    M, N, R, C = p["M"], p["N"], p["R"], p["C"]
    MB = M // R
    indptr = np.asarray(p["indptr"])
    cols = np.asarray(p["cols"]).reshape(MB, p["max_nnz"])
    rows_np = np.zeros((MB, N // C), bool)
    for i in range(MB):
        n = int(indptr[i + 1] - indptr[i])
        rows_np[i, cols[i, :n]] = True
    mask = jnp.asarray(np.repeat(np.repeat(rows_np, R, 0), C, 1))
    return _dense_masked_attention(q, k, v, mask, p["sm_scale"])


class VariableBlockSparseAttentionWrapper(BlockSparseAttentionWrapper):
    """Variable-block-size BSR (reference sparse.py:1075).  v1 routes
    through the dense-mask xla path after expanding the variable blocks."""

    def plan(
        self,
        block_mask_map,  # [MB, NB] bool dense block mask
        block_row_sz,  # [MB] row sizes
        block_col_sz,  # [NB] col sizes
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        **_unused,
    ) -> None:
        block_mask_map = np.asarray(block_mask_map)
        rs = np.asarray(block_row_sz)
        cs = np.asarray(block_col_sz)
        mask = np.repeat(np.repeat(block_mask_map, rs, axis=0), cs, axis=1)
        self._plan = dict(
            dense_mask=jnp.asarray(mask),
            sm_scale=get_sm_scale(head_dim, sm_scale),
        )

    def run(self, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        p = self._plan
        if p is None:
            raise RuntimeError("plan() must be called before run()")
        return _dense_masked_attention(q, k, v, p["dense_mask"], p["sm_scale"])
