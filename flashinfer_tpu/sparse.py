"""Block-sparse attention wrappers.

TPU re-design of ``flashinfer/sparse.py`` (BlockSparseAttentionWrapper
sparse.py:195, VariableBlockSparseAttentionWrapper sparse.py:1075): BSR
attention where only listed (row-block, col-block) pairs are computed.
Fixed-size blocks go through the scalar-prefetch Pallas kernel
(ops/block_sparse.py); variable block sizes go through the segment flash
kernel with an expanded token-level mask via the xla path (documented v1
trade-off).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.ops.block_sparse import bsr_attention
from flashinfer_tpu.ops.xla_ref import xla_ragged_attention
from flashinfer_tpu.utils import get_sm_scale, next_power_of_two, resolve_backend


class BlockSparseAttentionWrapper:
    """BSR attention with plan/run lifecycle (reference sparse.py:195).

    plan() takes the BSR structure (indptr over row blocks, column-block
    indices) exactly like the reference's (indptr, indices, M, N, R, C)."""

    def __init__(self, float_workspace_buffer=None, backend: str = "auto",
                 **_unused):
        self._backend = backend
        self._plan = None

    def plan(
        self,
        indptr,  # [MB+1]
        indices,  # [nnz] column-block ids
        M: int,
        N: int,
        R: int,  # block row size
        C: int,  # block col size
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        mask=None,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        **_unused,
    ) -> None:
        if M % R or N % C:
            raise ValueError("M/N must be multiples of R/C")
        from flashinfer_tpu import native

        indptr = np.asarray(indptr)
        indices = np.asarray(indices)
        MB = M // R
        nnz_per_row = indptr[1:] - indptr[:-1]
        max_nnz = max(next_power_of_two(int(nnz_per_row.max(initial=1))), 1)
        cols = native.bsr_plan(indptr, indices, max_nnz)
        dense_mask = None
        if mask is not None:
            # per-block interior bitmask (reference sparse.py plan(mask=)):
            # [nnz, R, C] bool selecting elements WITHIN each nonzero
            # block, or the flattened per-row-of-blocks layout produced by
            # convert_bsr_mask_layout.  Honored on the dense-mask path —
            # run() routes there when a mask is planned (the Pallas BSR
            # kernel has no interior-mask term; same dispatch pattern as
            # ALiBi).  Expanded to the dense [M, N] mask HERE, once — not
            # per run().
            mask = np.asarray(mask).astype(bool)
            nnz = len(indices)
            if mask.shape == (nnz * R * C,):
                # undo convert_bsr_mask_layout's within-row transpose
                blocks = np.empty((nnz, R, C), bool)
                for i in range(MB):
                    lo, hi = int(indptr[i]), int(indptr[i + 1])
                    seg = mask[lo * R * C: hi * R * C]
                    blocks[lo:hi] = seg.reshape(R, hi - lo, C).transpose(
                        1, 0, 2)
                mask = blocks
            if mask.shape != (nnz, R, C):
                raise ValueError(
                    f"mask must be [nnz={nnz}, R={R}, C={C}] or the "
                    f"flattened ({nnz * R * C},) convert_bsr_mask_layout "
                    f"form, got {mask.shape}")
            mask_np = np.zeros((M, N), bool)
            for i in range(MB):
                for pos in range(int(indptr[i]), int(indptr[i + 1])):
                    j = int(indices[pos])
                    mask_np[i * R:(i + 1) * R, j * C:(j + 1) * C] = mask[pos]
            dense_mask = jnp.asarray(mask_np)
        self._plan = dict(
            indptr=jnp.asarray(indptr, dtype=jnp.int32),
            cols=jnp.asarray(cols),
            block_mask=dense_mask,
            M=M, N=N, R=R, C=C, max_nnz=max_nnz,
            num_qo_heads=num_qo_heads, num_kv_heads=num_kv_heads,
            head_dim=head_dim,
            sm_scale=get_sm_scale(head_dim, sm_scale),
        )

    def run(self, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        p = self._plan
        if p is None:
            raise RuntimeError("plan() must be called before run()")
        backend = resolve_backend(self._backend, "block_sparse")
        if backend == "pallas" and p.get("block_mask") is None:
            return bsr_attention(
                q, k, v, p["indptr"], p["cols"],
                block_row=p["R"], block_col=p["C"], max_nnz=p["max_nnz"],
                sm_scale=p["sm_scale"],
            )
        # xla fallback: expand BSR to a token-level segment trick — assign
        # each (row-block, col-block) nonzero its own "virtual request"
        # would duplicate tokens; instead use a dense mask reference.
        return _xla_bsr_dense(q, k, v, p)

    forward = run

    def end_forward(self) -> None:
        pass


def _dense_masked_attention(q, k, v, mask, sm_scale):
    """Dense masked-softmax attention over a [M, N] boolean mask (shared by
    both xla fallback paths)."""
    group = q.shape[1] // k.shape[1]
    qf = q.astype(jnp.float32)
    kf = jnp.repeat(k.astype(jnp.float32), group, axis=1)
    vf = jnp.repeat(v.astype(jnp.float32), group, axis=1)
    s = jnp.einsum("qhd,khd->hqk", qf, kf) * sm_scale
    s = jnp.where(mask[None], s, -1e30)
    m = jnp.max(s, -1, keepdims=True)
    pr = jnp.where(mask[None], jnp.exp(s - m), 0.0)
    l = jnp.sum(pr, -1, keepdims=True)
    out = jnp.einsum("hqk,khd->qhd", pr / jnp.where(l > 0, l, 1.0), vf)
    return out.astype(q.dtype)


def _xla_bsr_dense(q, k, v, p):
    M, N, R, C = p["M"], p["N"], p["R"], p["C"]
    MB = M // R
    mask = p.get("block_mask")  # dense [M, N], pre-expanded at plan()
    if mask is None:
        indptr = np.asarray(p["indptr"])
        cols = np.asarray(p["cols"]).reshape(MB, p["max_nnz"])
        rows_np = np.zeros((MB, N // C), bool)
        for i in range(MB):
            n = int(indptr[i + 1] - indptr[i])
            rows_np[i, cols[i, :n]] = True
        mask = jnp.asarray(np.repeat(np.repeat(rows_np, R, 0), C, 1))
    return _dense_masked_attention(q, k, v, mask, p["sm_scale"])


class VariableBlockSparseAttentionWrapper(BlockSparseAttentionWrapper):
    """Variable-block-size BSR (reference sparse.py:1075, which lowers to
    vector-sparse prefill).  TPU-native design: plan() re-tiles the variable
    block structure onto fixed hardware tiles, emitting a fixed-size BSR
    (tile indptr/cols) plus a full/partial flag per tile pair; run() feeds
    the scalar-prefetch Pallas kernel (ops/block_sparse.py
    ``vbsr_attention``) whose compute and KV DMA scale with the number of
    overlapped tiles, not O(M*N).  Partially covered tiles reconstruct the
    exact token mask in-kernel from per-token block ids and the block map.
    Oversized block maps (VMEM-resident mask table > ~6 MiB) or degenerate
    row spans fall back to the dense-mask xla path."""

    _TR = 128  # q-tile rows
    _TC = 128  # kv-tile cols

    def plan(
        self,
        block_mask_map,  # [MB, NB] bool — or [num_kv_heads, MB, NB]
        block_row_sz,  # [MB] row sizes — or [num_kv_heads, MB]
        block_col_sz,  # [NB] col sizes — or [num_kv_heads, NB]
        num_qo_heads: int,
        num_kv_heads: int,
        head_dim: int,
        sm_scale: Optional[float] = None,
        q_data_type=jnp.bfloat16,
        **_unused,
    ) -> None:
        """Two input forms, as in the reference (sparse.py:1075): a
        single shared block structure (2-D map, token-major [len, heads,
        dim] tensors in run()), or PER-KV-HEAD structures (3-D map —
        the reference test matrix's form; run() then takes HND
        [heads, len, dim] tensors and returns [num_qo_heads, len, dim],
        each q-head group attending under its kv head's structure)."""
        map_all = np.asarray(block_mask_map, dtype=bool)
        sm = get_sm_scale(head_dim, sm_scale)
        if map_all.ndim == 3:
            rs_all = np.asarray(block_row_sz, dtype=np.int64)
            cs_all = np.asarray(block_col_sz, dtype=np.int64)
            if map_all.shape[0] != num_kv_heads or num_qo_heads % num_kv_heads:
                raise ValueError(
                    "3-D block_mask_map must be [num_kv_heads, MB, NB] with "
                    "num_qo_heads divisible by num_kv_heads")
            MB, NB = map_all.shape[1:]
            if rs_all.shape != (num_kv_heads, MB) or \
                    cs_all.shape != (num_kv_heads, NB):
                raise ValueError(
                    f"with a 3-D block_mask_map, block_row_sz must be "
                    f"[{num_kv_heads}, {MB}] and block_col_sz "
                    f"[{num_kv_heads}, {NB}]; got {rs_all.shape} / "
                    f"{cs_all.shape}")
            self._plan = dict(
                per_head=True, group=num_qo_heads // num_kv_heads,
                heads=[
                    self._plan_single(map_all[h], rs_all[h], cs_all[h], sm)
                    for h in range(num_kv_heads)
                ],
            )
            return
        self._plan = self._plan_single(
            map_all, np.asarray(block_row_sz, dtype=np.int64),
            np.asarray(block_col_sz, dtype=np.int64), sm)

    def _plan_single(self, map_np, rs, cs, sm):
        from flashinfer_tpu.utils import round_up

        MB, NB = map_np.shape
        M, N = int(rs.sum()), int(cs.sum())
        TR, TC = self._TR, self._TC

        Mpad, Npad = round_up(M, TR), round_up(N, TC)
        # per-token variable-block ids; padding tokens get the sentinel id
        # MB/NB whose map row/col is all-zero, so they mask out naturally
        row_id = np.concatenate(
            [np.repeat(np.arange(MB), rs), np.full(Mpad - M, MB)]
        ).astype(np.int32)
        col_id = np.concatenate(
            [np.repeat(np.arange(NB), cs), np.full(Npad - N, NB)]
        ).astype(np.int32)

        MT, NT = Mpad // TR, Npad // TC
        rb0 = row_id.reshape(MT, TR).min(1)
        rb1 = row_id.reshape(MT, TR).max(1)
        cb0 = col_id.reshape(NT, TC).min(1)
        cb1 = col_id.reshape(NT, TC).max(1)
        k_span = int(next_power_of_two(int((rb1 - rb0 + 1).max(initial=1))))

        # integral image over the (sentinel-extended) block map gives the
        # any/full coverage of every (q-tile, kv-tile) span in O(1)
        ext = np.zeros((MB + 1, NB + 1), np.int64)
        ext[:MB, :NB] = map_np
        S = np.zeros((MB + 2, NB + 2), np.int64)
        S[1:, 1:] = ext.cumsum(0).cumsum(1)
        r0, r1 = rb0[:, None], rb1[:, None]
        c0, c1 = cb0[None, :], cb1[None, :]
        rect = S[r1 + 1, c1 + 1] - S[r0, c1 + 1] - S[r1 + 1, c0] + S[r0, c0]
        area = (r1 - r0 + 1) * (c1 - c0 + 1)
        any_t = rect > 0  # [MT, NT]
        full_t = rect == area

        nnz_per_row = any_t.sum(1)
        max_nnz = int(next_power_of_two(int(nnz_per_row.max(initial=1))))
        cols = np.zeros((MT, max_nnz), np.int32)
        flags = np.zeros((MT, max_nnz), np.int32)
        for i in range(MT):
            js = np.nonzero(any_t[i])[0]
            cols[i, : len(js)] = js
            flags[i, : len(js)] = np.where(full_t[i, js], 1, 2)
        indptr = np.concatenate([[0], np.cumsum(nnz_per_row)]).astype(np.int32)

        # VMEM-resident block-map table: sentinel row/col + slack so the
        # kernel's dynamic k_span row slice never reads out of bounds
        MBpad = round_up(int(rb0.max(initial=0)) + k_span, 8)
        MBpad = max(MBpad, round_up(MB + 1, 8))
        NBpad = round_up(NB + 1, 128)
        mappad = np.zeros((MBpad, NBpad), np.float32)
        mappad[:MB, :NB] = map_np

        use_kernel = (MBpad * NBpad * 4 <= 6 << 20) and k_span <= 32
        return dict(
            variable=True, use_kernel=use_kernel,
            M=M, N=N, Mpad=Mpad, Npad=Npad,
            indptr=jnp.asarray(indptr),
            cols=jnp.asarray(cols.reshape(-1)),
            flags=jnp.asarray(flags.reshape(-1)),
            rb0=jnp.asarray(rb0.astype(np.int32)),
            row_id=jnp.asarray(row_id),
            col_id=jnp.asarray(col_id),
            block_map=jnp.asarray(mappad),
            max_nnz=max_nnz, k_span=k_span, sm_scale=sm,
            dense_mask=None,
            map_np=map_np, rs=rs, cs=cs,
        )

    def _dense_mask(self, p):
        if p["dense_mask"] is None:
            p["dense_mask"] = jnp.asarray(
                np.repeat(np.repeat(p["map_np"], p["rs"], 0), p["cs"], 1)
            )
        return p["dense_mask"]

    def run(self, q: jax.Array, k: jax.Array, v: jax.Array) -> jax.Array:
        p = self._plan
        if p is None:
            raise RuntimeError("plan() must be called before run()")
        backend = resolve_backend(self._backend, "block_sparse")
        if p.get("per_head"):
            # reference per-kv-head form: HND tensors, each q-head group
            # under its kv head's structure (one kernel/dense call per kv
            # head — the structures genuinely differ per head)
            G = p["group"]
            outs = []
            for h, ph in enumerate(p["heads"]):
                oh = self._run_single(
                    ph, backend,
                    jnp.swapaxes(q[h * G:(h + 1) * G], 0, 1),
                    jnp.swapaxes(k[h:h + 1], 0, 1),
                    jnp.swapaxes(v[h:h + 1], 0, 1),
                )
                outs.append(jnp.swapaxes(oh, 0, 1))
            return jnp.concatenate(outs, axis=0)
        return self._run_single(p, backend, q, k, v)

    def _run_single(self, p, backend, q, k, v):
        if backend != "pallas" or not p["use_kernel"]:
            return _dense_masked_attention(
                q, k, v, self._dense_mask(p), p["sm_scale"]
            )
        from flashinfer_tpu.ops.block_sparse import vbsr_attention

        M, N = p["M"], p["N"]
        if q.shape[0] != p["Mpad"]:
            q = jnp.pad(q, ((0, p["Mpad"] - M), (0, 0), (0, 0)))
        if k.shape[0] != p["Npad"]:
            k = jnp.pad(k, ((0, p["Npad"] - N), (0, 0), (0, 0)))
            v = jnp.pad(v, ((0, p["Npad"] - N), (0, 0), (0, 0)))
        out = vbsr_attention(
            q, k, v, p["indptr"], p["cols"], p["flags"], p["rb0"],
            p["row_id"], p["col_id"], p["block_map"],
            block_row=self._TR, block_col=self._TC,
            max_nnz=p["max_nnz"], k_span=p["k_span"],
            sm_scale=p["sm_scale"],
        )
        return out[:M]

    # rebind: the base class set `forward = run` to ITS run; without this,
    # forward() on a variable/per-head plan would dispatch to the BSR run
    forward = run


def convert_bsr_mask_layout(mask, indptr):
    """BSR per-block mask [nnz, R, C] -> the flattened per-row-of-blocks
    layout the wrappers consume (reference sparse.py:170: within each
    block-row, block masks transpose to row-major over (R, nnz_row, C))."""
    import numpy as np

    mask = np.asarray(mask)
    indptr = np.asarray(indptr)
    nnz, R, C = mask.shape
    out = np.empty((nnz * R * C,), dtype=mask.dtype)
    for i in range(len(indptr) - 1):
        out[indptr[i] * R * C : indptr[i + 1] * R * C] = (
            mask[indptr[i] : indptr[i + 1]].transpose(1, 0, 2).reshape(-1)
        )
    return jnp.asarray(out)
