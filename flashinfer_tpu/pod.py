"""POD (Prefill-On-Decode) attention module.

Module-path parity with the reference (``flashinfer/pod.py:61``); on TPU
the holistic segment kernel already co-schedules prefill and decode work,
so POD aliases BatchAttention — see flashinfer_tpu/attention.py for the
design note.
"""

from flashinfer_tpu.attention import PODWithPagedKVCacheWrapper  # noqa: F401
