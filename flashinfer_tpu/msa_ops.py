"""MSA sparse attention ops (MiniMax-style).

TPU re-design of the reference's ``flashinfer/msa_ops/`` family: dynamic
sparse attention where each query block attends only the top-k KV blocks
ranked by a cheap *proxy score* (mean-pooled keys).  Pipeline:

1. ``msa_proxy_score``: block-mean keys vs block-mean queries -> [QB, KB]
   score matrix (the reference's proxy-score kernel);
2. ``msa_topk_select``: per-query-block top-k KV block ids (+ always the
   diagonal/local block for causal integrity);
3. ``msa_sparse_attention``: BSR attention over the selected blocks via
   the scalar-prefetch Pallas kernel (ops/block_sparse.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.utils import get_sm_scale


@functools.partial(jax.jit, static_argnames=("block_q", "block_kv"))
def msa_proxy_score(
    q: jax.Array,  # [M, H, D]
    k: jax.Array,  # [N, Hkv, D]
    block_q: int = 64,
    block_kv: int = 64,
) -> jax.Array:
    """Head-summed block-pooled attention proxy -> [M//bq, N//bkv] f32."""
    M, H, D = q.shape
    N = k.shape[0]
    qb = q.astype(jnp.float32).reshape(M // block_q, block_q, H, D).mean(1)
    kb = k.astype(jnp.float32).reshape(N // block_kv, block_kv, -1, D).mean(1)
    group = H // kb.shape[1]
    kb = jnp.repeat(kb, group, axis=1)
    return jnp.einsum("ihd,jhd->ij", qb, kb)


def msa_topk_select(
    scores: jax.Array,  # [QB, KB]
    top_k: int,
    causal: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side BSR structure from proxy scores: per row, the top-k blocks
    (restricted to j <= i when causal, diagonal always kept).
    Returns (indptr [QB+1], indices [nnz]) numpy arrays for plan()."""
    s = np.asarray(scores, np.float32)
    QB, KB = s.shape
    indptr = [0]
    indices = []
    for i in range(QB):
        row = s[i].copy()
        if causal:
            row[i + 1 :] = -np.inf
        k_eff = min(top_k, i + 1 if causal else KB)
        sel = set(np.argsort(-row)[:k_eff].tolist())
        sel.add(min(i, KB - 1))  # local block
        cols = sorted(sel)
        indices.extend(cols)
        indptr.append(len(indices))
    return np.asarray(indptr, np.int32), np.asarray(indices, np.int32)


def msa_sparse_attention(
    q: jax.Array,  # [M, H, D]
    k: jax.Array,  # [N, Hkv, D]
    v: jax.Array,
    top_k: int = 8,
    block_q: int = 64,
    block_kv: int = 64,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    backend: str = "auto",
) -> jax.Array:
    """End-to-end MSA sparse attention: proxy -> select -> BSR attention.

    Note: block-granular sparsity — within selected blocks attention is
    dense (no intra-block causal mask), matching the proxy-sparse design."""
    from flashinfer_tpu.sparse import BlockSparseAttentionWrapper

    scores = msa_proxy_score(q, k, block_q, block_kv)
    indptr, indices = msa_topk_select(scores, top_k, causal)
    w = BlockSparseAttentionWrapper(backend=backend)
    w.plan(
        indptr, indices, q.shape[0], k.shape[0], block_q, block_kv,
        q.shape[1], k.shape[1], q.shape[2],
        sm_scale=get_sm_scale(q.shape[2], sm_scale),
    )
    return w.run(q, k, v)
