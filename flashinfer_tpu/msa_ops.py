"""MSA sparse attention ops (MiniMax-style).

TPU re-design of the reference's ``flashinfer/msa_ops/`` family: dynamic
sparse attention where each query block attends only the top-k KV blocks
ranked by a cheap *proxy score* (mean-pooled keys).  Pipeline:

1. ``msa_proxy_score``: block-mean keys vs block-mean queries -> [QB, KB]
   score matrix (the reference's proxy-score kernel);
2. ``msa_topk_select``: per-query-block top-k KV block ids (+ always the
   diagonal/local block for causal integrity);
3. ``msa_sparse_attention``: BSR attention over the selected blocks via
   the scalar-prefetch Pallas kernel (ops/block_sparse.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flashinfer_tpu.utils import get_sm_scale


@functools.partial(jax.jit, static_argnames=("block_q", "block_kv"))
def msa_proxy_score(
    q: jax.Array,  # [M, H, D]
    k: jax.Array,  # [N, Hkv, D]
    block_q: int = 64,
    block_kv: int = 64,
) -> jax.Array:
    """Head-summed block-pooled attention proxy -> [M//bq, N//bkv] f32."""
    M, H, D = q.shape
    N = k.shape[0]
    qb = q.astype(jnp.float32).reshape(M // block_q, block_q, H, D).mean(1)
    kb = k.astype(jnp.float32).reshape(N // block_kv, block_kv, -1, D).mean(1)
    group = H // kb.shape[1]
    kb = jnp.repeat(kb, group, axis=1)
    return jnp.einsum("ihd,jhd->ij", qb, kb)


def msa_topk_select(
    scores: jax.Array,  # [QB, KB]
    top_k: int,
    causal: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side BSR structure from proxy scores: per row, the top-k blocks
    (restricted to j <= i when causal, diagonal always kept).
    Returns (indptr [QB+1], indices [nnz]) numpy arrays for plan()."""
    s = np.asarray(scores, np.float32)
    QB, KB = s.shape
    indptr = [0]
    indices = []
    for i in range(QB):
        row = s[i].copy()
        if causal:
            row[i + 1 :] = -np.inf
        k_eff = min(top_k, i + 1 if causal else KB)
        sel = set(np.argsort(-row)[:k_eff].tolist())
        sel.add(min(i, KB - 1))  # local block
        cols = sorted(sel)
        indices.extend(cols)
        indptr.append(len(indices))
    return np.asarray(indptr, np.int32), np.asarray(indices, np.int32)


@functools.partial(jax.jit, static_argnames=("block_kv",))
def msa_proxy_score_per_token(
    q: jax.Array,  # [M, H, D]
    k: jax.Array,  # [N, Hkv, D]
    block_kv: int = 64,
) -> jax.Array:
    """Per-*token* proxy: every query token vs block-mean-pooled keys ->
    [M, N//bkv] f32 (the reference MSA ranking granularity, where each
    token keeps its own top-k KV blocks)."""
    M, H, D = q.shape
    N = k.shape[0]
    kb = k.astype(jnp.float32).reshape(N // block_kv, block_kv, -1, D).mean(1)
    group = H // kb.shape[1]
    kb = jnp.repeat(kb, group, axis=1)
    return jnp.einsum("mhd,jhd->mj", q.astype(jnp.float32), kb)


def msa_topk_select_per_token(
    scores: jax.Array,  # [M, KB] per-token block scores
    top_k: int,
    block_q: int,
    block_kv: int,
    causal: bool = True,
):
    """Token-granular selection -> (union BSR structure per q row-block,
    per-token selection bitmap padded to 128 lanes).

    Every token keeps its top-k blocks (restricted to blocks at or before
    its own position when causal; its local block always kept); the BSR
    cols of a q row-block are the union over its tokens, and the bitmap
    resolves per-token membership inside the kernel."""
    from flashinfer_tpu.utils import round_up

    s = np.asarray(scores, np.float32)
    M, KB = s.shape
    if causal:
        if M != KB * block_kv:
            raise ValueError(
                "causal token-granular MSA assumes self-attention "
                f"(M == N): got M={M}, N={KB * block_kv}"
            )
        tok_blk = np.arange(M) // block_kv  # kv-block of each token's pos
        mask = np.arange(KB)[None, :] > tok_blk[:, None]
        s = np.where(mask, -np.inf, s)
    k_eff = min(top_k, KB)
    top = np.argpartition(-s, min(k_eff, KB - 1), axis=1)[:, :k_eff]
    bitmap = np.zeros((M, KB), bool)
    np.put_along_axis(bitmap, top, True, axis=1)
    if causal:
        bitmap &= ~mask
        bitmap[np.arange(M), np.minimum(np.arange(M) // block_kv, KB - 1)] = True
    MB = M // block_q
    per_row = bitmap.reshape(MB, block_q, KB).any(1)  # union per q block
    indptr = [0]
    indices = []
    for i in range(MB):
        cols = np.nonzero(per_row[i])[0]
        indices.extend(cols.tolist())
        indptr.append(len(indices))
    kb_pad = round_up(KB, 128)
    bitmap_pad = np.zeros((M, kb_pad), np.float32)
    bitmap_pad[:, :KB] = bitmap
    return (
        np.asarray(indptr, np.int32),
        np.asarray(indices, np.int32),
        bitmap_pad,
    )


def msa_sparse_attention(
    q: jax.Array,  # [M, H, D]
    k: jax.Array,  # [N, Hkv, D]
    v: jax.Array,
    top_k: int = 8,
    block_q: int = 64,
    block_kv: int = 64,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    backend: str = "auto",
    granularity: str = "token",
) -> jax.Array:
    """End-to-end MSA sparse attention: proxy -> select -> sparse kernel.

    ``granularity="token"`` (default, the reference semantics): every query
    token ranks KV blocks by its own proxy score and keeps its top-k, with
    token-level causal masking — runs on the per-token-selection BSR kernel
    (ops/block_sparse.py bsr_attention_token_select).
    ``granularity="block"``: the coarser v1 design — one selection per
    query *block*, dense within selected blocks, no intra-block causal."""
    sm = get_sm_scale(q.shape[2], sm_scale)
    if granularity == "token":
        from flashinfer_tpu.ops.block_sparse import bsr_attention_token_select
        from flashinfer_tpu.utils import next_power_of_two, resolve_backend

        scores = msa_proxy_score_per_token(q, k, block_kv)
        indptr, indices, bitmap = msa_topk_select_per_token(
            scores, top_k, block_q, block_kv, causal
        )
        if resolve_backend(backend, "msa_sparse_attention") != "pallas":
            # xla fallback: dense attention under the selection mask
            from flashinfer_tpu.sparse import _dense_masked_attention

            KB = k.shape[0] // block_kv
            tok_mask = np.repeat(
                np.asarray(bitmap[:, :KB], bool), block_kv, axis=1
            )
            if causal:
                M = q.shape[0]
                tok_mask &= np.arange(M)[None, :] <= np.arange(M)[:, None]
            return _dense_masked_attention(q, k, v, jnp.asarray(tok_mask), sm)
        nnz_per_row = indptr[1:] - indptr[:-1]
        max_nnz = max(int(next_power_of_two(int(nnz_per_row.max(initial=1)))), 1)
        MB = q.shape[0] // block_q
        cols = np.zeros((MB, max_nnz), np.int32)
        for i in range(MB):
            row = indices[indptr[i]:indptr[i + 1]]
            cols[i, : len(row)] = row
        return bsr_attention_token_select(
            q, k, v, jnp.asarray(indptr), jnp.asarray(cols.reshape(-1)),
            jnp.asarray(bitmap),
            block_row=block_q, block_col=block_kv, max_nnz=max_nnz,
            causal=causal, sm_scale=sm,
        )
    if granularity != "block":
        raise ValueError(f"unknown granularity {granularity!r}")
    from flashinfer_tpu.sparse import BlockSparseAttentionWrapper

    scores = msa_proxy_score(q, k, block_q, block_kv)
    indptr, indices = msa_topk_select(scores, top_k, causal)
    w = BlockSparseAttentionWrapper(backend=backend)
    w.plan(
        indptr, indices, q.shape[0], k.shape[0], block_q, block_kv,
        q.shape[1], k.shape[1], q.shape[2],
        sm_scale=sm,
    )
    return w.run(q, k, v)


# reference msa_ops name surface (msa_ops/__init__.py)
msa_sparse_decode_attention = msa_sparse_attention
"""Reference ``msa_sparse_decode_attention`` -> the token-granular
sparse attention entry (same selection semantics at qo_len == 1)."""


def msa_proxy_score_fp4(q, k, block_q: int = 64, block_kv: int = 64):
    """Reference fp4-quantized proxy scoring (msa_ops/proxy_score.py,
    cute_dsl fp4 variant): the fp4 path exists to cheapen the PROXY
    ranking pass on Blackwell tensor cores; on TPU the proxy runs on the
    bf16 MXU directly (ranking is already the cheap pass), so this is
    the same block-pooled score."""
    return msa_proxy_score(q, k, block_q=block_q, block_kv=block_kv)
