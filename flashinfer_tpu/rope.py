"""Rotary position embedding (RoPE) family.

TPU-native re-design of the reference RoPE ops (``flashinfer/rope.py:768-1159``,
``include/flashinfer/pos_enc.cuh:294-1580``): plain Llama RoPE, Llama-3.1
frequency-scaled RoPE, position-id and ragged-indptr input forms, and the
cos/sin-cache form.

Functional (out-of-place) semantics; the reference's ``*_inplace`` variants
map to the same functions under jit buffer donation.  All forms are pure-XLA:
RoPE is a cheap elementwise transform that XLA fuses into neighbouring ops —
a dedicated Pallas kernel only adds a fusion barrier (SURVEY §7 design note).
"""

from __future__ import annotations

import collections
import functools
import weakref
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.api_logging import flashinfer_api


def _rope_freqs(
    rotary_dim: int, rope_theta: float, rope_scale: float
) -> jax.Array:
    """Inverse frequencies, shape [rotary_dim // 2], fp32."""
    i = jnp.arange(rotary_dim // 2, dtype=jnp.float32)
    return 1.0 / (rope_scale * rope_theta ** (2.0 * i / rotary_dim))


def _llama31_scale_freqs(
    freqs: jax.Array,
    rope_scale: float,
    low_freq_factor: float,
    high_freq_factor: float,
    old_context_len: int,
) -> jax.Array:
    """Llama-3.1 piecewise frequency rescaling (pos_enc.cuh Llama31 path)."""
    wavelen = 2.0 * jnp.pi / freqs
    low_bound = old_context_len / low_freq_factor
    high_bound = old_context_len / high_freq_factor
    smooth = (old_context_len / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor
    )
    scaled = jnp.where(
        wavelen > low_bound,
        freqs / rope_scale,
        jnp.where(
            wavelen < high_bound,
            freqs,
            (1.0 - smooth) * freqs / rope_scale + smooth * freqs,
        ),
    )
    return scaled


def _apply_rotary(
    x: jax.Array,  # [n, heads, head_dim]
    cos: jax.Array,  # [n, rotary_dim // 2]
    sin: jax.Array,  # [n, rotary_dim // 2]
    rotary_dim: int,
    interleave: bool,
) -> jax.Array:
    xf = x.astype(jnp.float32)
    rot, rest = xf[..., :rotary_dim], xf[..., rotary_dim:]
    c = cos[:, None, :]
    s = sin[:, None, :]
    if interleave:
        x1 = rot[..., 0::2]
        x2 = rot[..., 1::2]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        out_rot = jnp.stack([o1, o2], axis=-1).reshape(rot.shape)
    else:
        half = rotary_dim // 2
        x1 = rot[..., :half]
        x2 = rot[..., half:]
        out_rot = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return jnp.concatenate([out_rot, rest], axis=-1).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("interleave",))
def rotate_at_positions(
    x: jax.Array,  # [nnz, heads, head_dim]
    pos_ids: jax.Array,  # [nnz] int
    rope_scale=1.0,
    rope_theta=1e4,
    *,
    interleave: bool = False,
) -> jax.Array:
    """Rotate one tensor by per-row absolute positions — the in-attention
    RoPE primitive the pos_encoding_mode="ROPE_LLAMA" paths use (the
    reference rotates q/k inside the kernel from an UNROTATED cache;
    here rotation happens as an elementwise pass before attention, which
    is position-equivalent up to one rounding in x.dtype — callers with
    sub-16-bit caches upcast first).  scale/theta ride as traced scalars
    (plan-derived), so one compiled rotation serves every geometry.
    ``interleave=True`` is the GPT-NeoX-interleaved (is_neox=False)
    pairing."""
    head_dim = x.shape[-1]
    freqs = _rope_freqs(head_dim, rope_theta, rope_scale)
    angles = pos_ids.astype(jnp.float32)[:, None] * freqs[None, :]
    return _apply_rotary(
        x, jnp.cos(angles), jnp.sin(angles), head_dim, interleave
    )


@functools.partial(
    jax.jit,
    static_argnames=("rope_scale", "rope_theta", "interleave"),
)
def rotate_at_positions_static(
    x: jax.Array,
    pos_ids: jax.Array,
    *,
    rope_scale: float = 1.0,
    rope_theta: float = 1e4,
    interleave: bool = False,
) -> jax.Array:
    """:func:`rotate_at_positions` with STATIC scale/theta — the
    fused-ingest ORACLE rotation (prefill.run_ingest composed tier and
    the parity tests).  The ingest kernel's trace closes over python-
    float scale/theta, so its freq ``pow`` lowers with a CONSTANT base;
    a traced theta's runtime pow rounds ~1 ULP differently on XLA CPU —
    enough to break the f32 bitwise fused-vs-composed pin.  Statics
    here reproduce the kernel's constant-base lowering exactly."""
    head_dim = x.shape[-1]
    freqs = _rope_freqs(head_dim, float(rope_theta), float(rope_scale))
    angles = pos_ids.astype(jnp.float32)[:, None] * freqs[None, :]
    return _apply_rotary(
        x, jnp.cos(angles), jnp.sin(angles), head_dim, interleave
    )


@functools.partial(
    jax.jit,
    static_argnames=("rotary_dim", "interleave", "rope_scale", "rope_theta"),
)
def apply_rope_pos_ids(
    q: jax.Array,  # [nnz, num_qo_heads, head_dim]
    k: jax.Array,  # [nnz, num_kv_heads, head_dim]
    pos_ids: jax.Array,  # [nnz] int32
    rotary_dim: Optional[int] = None,
    interleave: bool = False,
    rope_scale: float = 1.0,
    rope_theta: float = 1e4,
) -> Tuple[jax.Array, jax.Array]:
    """Apply RoPE at explicit positions (reference ``apply_rope_pos_ids``,
    flashinfer/rope.py:768 family)."""
    head_dim = q.shape[-1]
    rd = rotary_dim or head_dim
    freqs = _rope_freqs(rd, rope_theta, rope_scale)
    angles = pos_ids.astype(jnp.float32)[:, None] * freqs[None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    return (
        _apply_rotary(q, cos, sin, rd, interleave),
        _apply_rotary(k, cos, sin, rd, interleave),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "rotary_dim", "interleave", "rope_scale", "rope_theta",
        "low_freq_factor", "high_freq_factor", "old_context_len",
    ),
)
def apply_llama31_rope_pos_ids(
    q: jax.Array,
    k: jax.Array,
    pos_ids: jax.Array,
    rotary_dim: Optional[int] = None,
    interleave: bool = False,
    rope_scale: float = 8.0,
    rope_theta: float = 5e5,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    old_context_len: int = 8192,
) -> Tuple[jax.Array, jax.Array]:
    """Llama-3.1-style RoPE with piecewise NTK frequency scaling
    (reference ``apply_llama31_rope_pos_ids``)."""
    head_dim = q.shape[-1]
    rd = rotary_dim or head_dim
    base = _rope_freqs(rd, rope_theta, 1.0)
    freqs = _llama31_scale_freqs(
        base, rope_scale, low_freq_factor, high_freq_factor, old_context_len
    )
    angles = pos_ids.astype(jnp.float32)[:, None] * freqs[None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    return (
        _apply_rotary(q, cos, sin, rd, interleave),
        _apply_rotary(k, cos, sin, rd, interleave),
    )


def _pos_ids_from_indptr(indptr: jax.Array, offsets: jax.Array, nnz: int) -> jax.Array:
    """Per-token positions for ragged batches: token i of request r gets
    ``offsets[r] + i`` (reference indptr/offset form, rope.py)."""
    req = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
    return (jnp.arange(nnz) - indptr[req] + offsets[req]).astype(jnp.int32)


@flashinfer_api
def apply_rope(
    q: jax.Array,
    k: jax.Array,
    indptr: jax.Array,
    offsets: jax.Array,
    rotary_dim: Optional[int] = None,
    interleave: bool = False,
    rope_scale: float = 1.0,
    rope_theta: float = 1e4,
) -> Tuple[jax.Array, jax.Array]:
    """Ragged-batch RoPE (reference ``apply_rope``): ``indptr`` delimits
    requests in the flattened token axis, ``offsets`` gives each request's
    starting position."""
    pos_ids = _pos_ids_from_indptr(indptr, offsets, q.shape[0])
    return apply_rope_pos_ids(
        q, k, pos_ids, rotary_dim, interleave, rope_scale, rope_theta
    )


@flashinfer_api
def apply_llama31_rope(
    q: jax.Array,
    k: jax.Array,
    indptr: jax.Array,
    offsets: jax.Array,
    rotary_dim: Optional[int] = None,
    interleave: bool = False,
    rope_scale: float = 8.0,
    rope_theta: float = 5e5,
    low_freq_factor: float = 1.0,
    high_freq_factor: float = 4.0,
    old_context_len: int = 8192,
) -> Tuple[jax.Array, jax.Array]:
    pos_ids = _pos_ids_from_indptr(indptr, offsets, q.shape[0])
    return apply_llama31_rope_pos_ids(
        q, k, pos_ids, rotary_dim, interleave, rope_scale, rope_theta,
        low_freq_factor, high_freq_factor, old_context_len,
    )


@functools.partial(jax.jit, static_argnames=("interleave",))
def apply_rope_with_cos_sin_cache(
    q: jax.Array,
    k: jax.Array,
    cos_sin_cache: jax.Array,  # [max_pos, rotary_dim] = [cos || sin] halves
    pos_ids: jax.Array,
    interleave: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """RoPE from a precomputed cos/sin cache (vLLM layout: ``cos_sin_cache``
    rows are ``[cos(rotary_dim/2) || sin(rotary_dim/2)]``; reference
    ``apply_rope_with_cos_sin_cache``, flashinfer/rope.py)."""
    rotary_dim = cos_sin_cache.shape[-1]
    half = rotary_dim // 2
    entry = cos_sin_cache[pos_ids].astype(jnp.float32)
    cos, sin = entry[:, :half], entry[:, half:]
    return (
        _apply_rotary(q, cos, sin, rotary_dim, interleave),
        _apply_rotary(k, cos, sin, rotary_dim, interleave),
    )


def generate_cos_sin_cache(
    max_position: int,
    rotary_dim: int,
    rope_theta: float = 1e4,
    rope_scale: float = 1.0,
    dtype=jnp.float32,
) -> jax.Array:
    """Build the [max_pos, rotary_dim] cos/sin cache in vLLM layout."""
    freqs = _rope_freqs(rotary_dim, rope_theta, rope_scale)
    angles = jnp.arange(max_position, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(angles), jnp.sin(angles)], axis=-1).astype(dtype)


def _rope_2d_guard(x):
    """MLA tensors may arrive 2-D [T, dim] (no head axis, reference
    rope.py:1286 layout); lift to 3-D and remember to squeeze back."""
    if x is None:
        return None, False
    if x.ndim == 2:
        return x[:, None, :], True
    return x, False


def _fp8_static(x, scale, dtype=jnp.float8_e4m3fn):
    """Static-scale fp8 cast (reference quant_scale semantics:
    fp8_value = high_precision * scale, saturating)."""
    finfo = jnp.finfo(dtype)
    return jnp.clip(
        x.astype(jnp.float32) * scale, float(finfo.min), float(finfo.max)
    ).astype(dtype)


@flashinfer_api
def rope_quantize_fp8(
    q_rope: jax.Array,  # [T, Hq, rotary_dim] (or [T, rotary_dim] MLA form)
    k_rope: jax.Array,  # [T, Hk, rotary_dim] / [T, rotary_dim]
    q_nope: Optional[jax.Array],  # [T, Hq, d_nope] / [T, d_nope]
    k_nope: Optional[jax.Array],
    cos_sin_cache: jax.Array,
    pos_ids: jax.Array,
    is_neox: bool = True,
    quant_scale_q: float = 1.0,
    quant_scale_kv: float = 1.0,
):
    """RoPE the rotary halves, fp8-quantize rotary and nope parts with
    static scales (reference ``rope_quantize_fp8``, flashinfer/rope.py:1364
    — the pre-attention quantized-QK path).

    Matches the reference contract: ``is_neox=True`` is the split-half
    (non-interleaved) rotation; returns the 4-tuple
    ``(q_rope_fp8, k_rope_fp8, q_nope_fp8, k_nope_fp8)`` (``None``
    entries pass through as ``None``) so MLA callers can route kpe/ckv to
    their separate caches; dequantize with ``1/scale``.  2-D MLA-layout
    tensors (no head axis) are accepted."""
    (qr3, q2d), (kr3, k2d) = _rope_2d_guard(q_rope), _rope_2d_guard(k_rope)
    qo, ko = apply_rope_with_cos_sin_cache(
        qr3, kr3, cos_sin_cache, pos_ids, interleave=not is_neox
    )
    if q2d:
        qo = qo[:, 0]
    if k2d:
        ko = ko[:, 0]
    return (
        _fp8_static(qo, quant_scale_q),
        _fp8_static(ko, quant_scale_kv),
        None if q_nope is None else _fp8_static(q_nope, quant_scale_q),
        None if k_nope is None else _fp8_static(k_nope, quant_scale_kv),
    )


@flashinfer_api
def mla_rope_quantize_fp8(q_rope, k_rope, q_nope, k_nope, cos_sin_cache,
                          pos_ids, is_neox: bool = True,
                          quant_scale_q: float = 1.0,
                          quant_scale_kv: float = 1.0):
    """MLA variant of :func:`rope_quantize_fp8` (reference rope.py:1286):
    the same op over the MLA split — 2-D ``k_rope`` (kpe, shared across
    heads) and ``k_nope`` (ckv) are the expected layout."""
    return rope_quantize_fp8(
        q_rope, k_rope, q_nope, k_nope, cos_sin_cache, pos_ids,
        is_neox=is_neox, quant_scale_q=quant_scale_q,
        quant_scale_kv=quant_scale_kv,
    )


def _ingest_append_runs(batch_indices, positions, pos_ids, page_size):
    """Host-side geometry gate for the fused-ingest append reroute:
    concrete arrays forming ascending per-request runs with contiguous
    append positions covering WHOLE pages (page-aligned start AND end)
    and contiguous rope positions.  Returns ``(B_runs, req_ids,
    append_lens, pos0s, rope_pos0s)`` or None when the geometry (or
    tracing context) rules the fused path out.

    The end-alignment requirement is a correctness gate, not a
    convenience: the ingest kernel writes back whole pages and zeroes
    a last partial page's rows past the run, while the composed append
    preserves whatever the cache held there — on an interior re-append
    (a request whose cached sequence extends past this run) those rows
    are LIVE KV.  This call cannot know the sequence length, so only
    runs that never produce a partial page reroute; the composed tier
    serves every tail chunk."""
    import numpy as np

    try:
        bi = np.asarray(batch_indices)
        pos = np.asarray(positions)
        rp = np.asarray(pos_ids)
    except Exception:  # noqa: BLE001 - tracers: stay on the composed tier
        return None
    if bi.ndim != 1 or bi.size == 0 or pos.shape != bi.shape \
            or rp.shape != bi.shape:
        return None
    if np.any(np.diff(bi) < 0):
        return None  # runs must be request-ascending (flat-concat order)
    req_ids, starts = np.unique(bi, return_index=True)
    ends = np.append(starts[1:], bi.size)
    lens = ends - starts
    for s, e in zip(starts, ends):
        if np.any(np.diff(pos[s:e]) != 1) or np.any(np.diff(rp[s:e]) != 1):
            return None  # non-contiguous run
        if int(pos[s]) % page_size != 0:
            return None  # mid-page start would need a sub-page merge
        if int(pos[e - 1] + 1) % page_size != 0:
            return None  # mid-page end: the whole-page write-back
            #              would zero rows the composed append keeps
    return req_ids, starts, lens, pos[starts], rp[starts]


@functools.lru_cache(maxsize=8)
def _default_csc_np(max_pos: int, rot_dim: int):
    """Host copy of the analytically-default cos/sin cache, built once
    per geometry (the reroute's equality reference)."""
    import numpy as np

    return np.asarray(generate_cos_sin_cache(max_pos, rot_dim))


# id(cos_sin_cache) -> (weakref-to-it, verdict).  The weakref guards
# against id reuse after GC; the memo makes the per-call cost of the
# default-cache check one dict hit on the serving path (per layer,
# per step) instead of a device sync + O(max_pos*rd) compare.
_INGEST_CSC_OK: dict = {}
# run-geometry key -> (device plan, statics): the host planner's
# output is pure in its inputs, and serving calls repeat the same
# geometry every layer of every step.
_INGEST_PLAN_CACHE: "collections.OrderedDict" = collections.OrderedDict()
_INGEST_PLAN_CAP = 64


def _fused_ingest_append(
    k_rope, v, cos_sin_cache, pos_ids, paged_kv_cache,
    kv_indices, kv_indptr, batch_indices, positions,
    is_neox: bool, quant_scale_kv: float,
):
    """The ISSUE 14 reroute: run the K/V half of the rope-quantize-
    append through :func:`~flashinfer_tpu.ops.paged_prefill.
    fused_paged_prefill_ingest` (append-only form) — one raw read + one
    quantized-page write instead of the composed three passes.  Returns
    the updated caches, or None when geometry keeps the composed tier:
    HND fp8 caches, full-head rotary, an analytically-default cos/sin
    cache (the kernel recomputes the rotation in-register — only a
    ``generate_cos_sin_cache``-default cache is bitwise reproducible),
    concrete whole-page append runs (page-aligned start AND end — see
    :func:`_ingest_append_runs` for why a partial last page is a
    correctness hazard, not a missed optimisation), and a resolved
    pallas backend (off-TPU auto stays composed;
    FLASHINFER_TPU_BACKEND=pallas forces, the fused-prefill
    precedent)."""
    import numpy as np

    from flashinfer_tpu.utils import resolve_backend

    if resolve_backend("auto", "rope_quantize_ingest") != "pallas":
        return None
    if not isinstance(paged_kv_cache, tuple):
        return None
    k_cache, v_cache = paged_kv_cache
    if k_cache.dtype not in (jnp.float8_e4m3fn, jnp.float8_e5m2):
        return None
    if k_rope.ndim != 3 or k_rope.shape[-1] != k_cache.shape[-1]:
        return None  # partial rotary / MLA 2-D layouts stay composed
    page_size = int(k_cache.shape[2])
    runs = _ingest_append_runs(batch_indices, positions, pos_ids,
                               page_size)
    if runs is None:
        return None
    req_ids, _starts, lens, pos0s, rope0s = runs
    rd = int(cos_sin_cache.shape[-1])
    if rd != k_rope.shape[-1]:
        return None
    # the kernel recomputes cos/sin analytically: only the default
    # Llama cache (theta 1e4, scale 1 — generate_cos_sin_cache's
    # constant-base pow, bitwise the kernel's) reroutes.  Verdict
    # memoized by object identity — the serving path passes the same
    # cache array every layer of every step.
    memo = _INGEST_CSC_OK.get(id(cos_sin_cache))
    if memo is not None and memo[0]() is cos_sin_cache:
        csc_ok = memo[1]
    else:
        try:
            csc = np.asarray(cos_sin_cache)
            ref = weakref.ref(cos_sin_cache)
        except Exception:  # noqa: BLE001 - tracers: stay composed
            return None
        csc_ok = np.array_equal(csc, _default_csc_np(csc.shape[0], rd))
        _INGEST_CSC_OK[id(cos_sin_cache)] = (ref, csc_ok)
        if len(_INGEST_CSC_OK) > 4 * _INGEST_PLAN_CAP:
            _INGEST_CSC_OK.clear()  # dead-id hygiene, verdicts are cheap
    if not csc_ok:
        return None
    try:
        kvi = np.asarray(kv_indices)
        kvp = np.asarray(kv_indptr)
    except Exception:  # noqa: BLE001
        return None
    from flashinfer_tpu.ops.paged_prefill import (
        build_prefill_ingest_units, fused_paged_prefill_ingest,
        ingest_pages_per_chunk,
    )
    from flashinfer_tpu.utils import cdiv

    # per-run page tables sliced to the APPEND region (chunk 0 starts
    # at the run's first page; pos0 is page-aligned by the gate)
    pages: list = []
    pi = [0]
    for r, p0, ln in zip(req_ids, pos0s, lens):
        p0 = int(p0)
        lo = int(kvp[r]) + p0 // page_size
        hi = int(kvp[r]) + cdiv(p0 + int(ln), page_size)
        pages.extend(kvi[lo:hi])
        pi.append(len(pages))
    B = len(req_ids)
    plan_key = (page_size, tuple(int(x) for x in lens),
                tuple(int(x) for x in rope0s),
                tuple(int(x) for x in pages))
    cached = _INGEST_PLAN_CACHE.get(plan_key)
    if cached is not None:
        _INGEST_PLAN_CACHE.move_to_end(plan_key)
        plan, statics = cached
    else:
        ppc = ingest_pages_per_chunk(page_size)
        plan_np = build_prefill_ingest_units(
            np.arange(B + 1, dtype=np.int64), np.asarray(pi, np.int64),
            np.asarray(pages, np.int64), np.asarray(lens, np.int64),
            block_q=8, pages_per_chunk=ppc, page_size=page_size,
            causal=False, prune=False,
            fused_ingest={"pos_offsets": np.asarray(rope0s, np.int64)},
        )
        statics = dict(
            num_units=plan_np.pop("num_units"),
            block_q=plan_np.pop("block_q"),
            pages_per_chunk=plan_np.pop("pages_per_chunk"),
        )
        plan_np.pop("stats")
        plan = {k: jnp.asarray(a) for k, a in plan_np.items()}
        _INGEST_PLAN_CACHE[plan_key] = (plan, statics)
        if len(_INGEST_PLAN_CACHE) > _INGEST_PLAN_CAP:
            _INGEST_PLAN_CACHE.popitem(last=False)
    scale = 1.0 / max(quant_scale_kv, 1e-12)
    return fused_paged_prefill_ingest(
        None, k_rope, v, k_cache, v_cache, plan,
        causal=False, attend=False, kv_quant="fp8",
        k_scale=scale, v_scale=scale,
        rope_interleave=not is_neox, **statics,
    )


@flashinfer_api
def rope_quantize_fp8_append_paged_kv_cache(
    q_rope, k_rope, q_nope, k_nope, v,
    cos_sin_cache, pos_ids,
    paged_kv_cache, kv_indices, kv_indptr,
    batch_indices, positions,
    kv_layout: str = "NHD",
    is_neox: bool = True,
    quant_scale_q: float = 1.0,
    quant_scale_kv: float = 1.0,
):
    """RoPE + fp8 quantize + quantizing paged append in one call
    (reference rope.py:1504, GQA/MHA form).  Returns
    ``(q_fp8 [T, Hq, rd(+dn)], (k_cache, v_cache))`` with the caches
    updated (functional JAX: new arrays; in-place under jit donation).

    When geometry allows (HND fp8 caches, full-head rotary with the
    default cos/sin cache, page-aligned contiguous append runs, pallas
    backend resolved) the K/V half REROUTES onto the fused-ingest
    work-unit kernel — one raw read + one quantized-page write, cache
    bits identical (tests/test_prefill_ingest.py pins fused == composed
    bit-for-bit; rows past each run's end in its last partial page are
    deterministically zeroed, see ``fused_paged_prefill_ingest``).  The
    separate-op composition below stays as the oracle tier and serves
    every other geometry.

    MLA (``v is None``) is not fused here — BY CONTRACT it exits before
    the reroute is ever considered: MLA appends target the split
    ckv/kpe caches — use :func:`mla_rope_quantize_fp8` +
    ``page.append_paged_mla_kv_cache``."""
    if v is None:
        raise NotImplementedError(
            "MLA form (v=None): use mla_rope_quantize_fp8 + "
            "page.append_paged_mla_kv_cache (split ckv/kpe caches)"
        )
    from flashinfer_tpu.page import append_paged_kv_cache_quant_fp8
    from flashinfer_tpu.utils import TensorLayout, check_kv_layout

    caches = None
    if k_nope is None and check_kv_layout(kv_layout) == TensorLayout.HND:
        caches = _fused_ingest_append(
            k_rope, v, cos_sin_cache, pos_ids, paged_kv_cache,
            kv_indices, kv_indptr, batch_indices, positions,
            is_neox, quant_scale_kv,
        )
    if caches is not None:
        qr, _ = apply_rope_with_cos_sin_cache(
            q_rope, q_rope, cos_sin_cache, pos_ids,
            interleave=not is_neox
        )
        q_hp = qr if q_nope is None else jnp.concatenate([qr, q_nope], -1)
        return _fp8_static(q_hp, quant_scale_q), caches

    qr, kr = apply_rope_with_cos_sin_cache(
        q_rope, k_rope, cos_sin_cache, pos_ids, interleave=not is_neox
    )
    q_hp = qr if q_nope is None else jnp.concatenate([qr, q_nope], -1)
    k_hp = kr if k_nope is None else jnp.concatenate([kr, k_nope], -1)
    qq = _fp8_static(q_hp, quant_scale_q)
    # the quantizing append owns the k/v fp8 conversion (scale semantics:
    # high_precision = fp8 * scale, so the append scale is 1/quant_scale)
    caches = append_paged_kv_cache_quant_fp8(
        k_hp, v, batch_indices, positions, paged_kv_cache,
        kv_indices, kv_indptr,
        jnp.float32(1.0 / max(quant_scale_kv, 1e-12)),
        jnp.float32(1.0 / max(quant_scale_kv, 1e-12)),
        kv_layout,
    )
    return qq, caches
