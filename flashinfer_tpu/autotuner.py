"""Runtime kernel-parameter autotuner.

TPU re-design of the reference autotuner (``flashinfer/autotuner/
autotuner.py:560-1419`` — TunableRunner interface, ``autotune()`` context,
profiling cache with hardware/version metadata validation).  GPU "tactics"
(kernel template choices) map to Pallas launch parameters: block sizes for
the flash kernel, pages-per-chunk for the decode kernels.  Outside an
``autotune()`` context, cached or default parameters are used with zero
profiling overhead; inside, every new (op, bucketed-shape) key is profiled
once across its candidate set and persisted to a JSON cache keyed by
device kind + library version (invalid on mismatch, like the reference's
metadata validation, autotuner.py:297-382).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import dataclasses

from flashinfer_tpu import env
from flashinfer_tpu.version import __version__


# ---------------------------------------------------------------------------
# Knob registry: the autotuner's first-class tactic surface.  Every op that
# consults the tuner (lookup / choose_one) registers its knob here — name
# and legal value shape — so (a) the
# shipped tuning_configs/*.json files are lint-checkable (analysis pass
# L006 `tuning_schema` rejects stale/misspelled keys at CI time) and (b) a
# corrupt or hand-edited config entry is ignored instead of crashing a
# kernel launch with a nonsense block shape.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KnobSpec:
    """One tunable launch parameter.

    ``arity=0`` means a scalar value; ``arity=n`` a list/tuple of n ints
    (JSON lists round-trip to tuples at lookup).  ``choices`` restricts
    string-valued knobs to an enum."""

    op_name: str
    arity: int = 0
    kind: str = "int"  # "int" | "str"
    choices: Optional[Tuple[str, ...]] = None
    description: str = ""

    def validate(self, value) -> Optional[str]:
        """Error message if `value` is not a legal tactic, else None."""
        if self.arity == 0:
            if self.kind == "str":
                if not isinstance(value, str):
                    return f"expected a string, got {value!r}"
                if self.choices and value not in self.choices:
                    return (f"{value!r} not in allowed choices "
                            f"{list(self.choices)}")
                return None
            if not isinstance(value, int) or isinstance(value, bool) \
                    or value <= 0:
                return f"expected a positive int, got {value!r}"
            return None
        if not isinstance(value, (list, tuple)) or len(value) != self.arity:
            return (f"expected a list of {self.arity} positive ints, "
                    f"got {value!r}")
        for v in value:
            if not isinstance(v, int) or isinstance(v, bool) or v <= 0:
                return (f"expected a list of {self.arity} positive ints, "
                        f"got {value!r}")
        return None


KNOWN_KNOBS: Dict[str, KnobSpec] = {}


def register_knob(op_name: str, *, arity: int = 0, kind: str = "int",
                  choices: Optional[Sequence[str]] = None,
                  description: str = "") -> KnobSpec:
    spec = KnobSpec(op_name, arity, kind,
                    tuple(choices) if choices else None, description)
    KNOWN_KNOBS[op_name] = spec
    return spec


# The registered surface (one entry per choose_one/lookup op name in the
# tree; L006 cross-checks tuning_configs/*.json against this table).
register_knob("rmsnorm.row_block",
              description="rmsnorm Pallas kernel row-block size")
register_knob("fused_add_rmsnorm.row_block",
              description="fused_add_rmsnorm Pallas kernel row-block size")
register_knob("paged_decode.pages_per_chunk",
              description="decode kernel KV pages per DMA chunk")
register_knob("paged_decode.prefetch", kind="str",
              choices=("static", "off"),
              description="decode kernel cross-step prefetch mode")
register_knob("decode.splits",
              description="split-KV decode partition factor per request "
                          "(1 = unsplit; plan-time, overrides the "
                          "cost-model choice — see docs/performance.md)")
register_knob("fused_prefill.blocks", arity=2,
              description="fused work-unit prefill (block_q, "
                          "pages_per_chunk) — the qo-tile/kv-chunk "
                          "shapes of the pipelined mainloop")
register_knob("prefill.fused_ingest", kind="str",
              choices=("off", "on"),
              description="fused prefill INGEST mode (ISSUE 14): 'on' "
                          "folds RoPE + KV-quantize-append into the "
                          "work-unit prefill mainloop "
                          "(ops/paged_prefill.fused_paged_prefill_"
                          "ingest) where geometry allows; absent "
                          "entries default via costmodel."
                          "predict_prefill_ingest_win (>2% predicted "
                          "win required, the choose_decode_splits "
                          "pattern)")
register_knob("flash_attention.blocks", arity=2,
              description="ragged flash kernel (block_q, block_kv) "
                          "grid blocks")
register_knob("moe_gmm.tiles", arity=3,
              description="MoE grouped-matmul (tm, tk, tn) tile shape")
register_knob("mla_decode.layout", kind="str",
              choices=("split", "packed"),
              description="MLA decode scratch layout")
register_knob("serve.mixed_chunk",
              description="chunked-prefill chunk size (tokens per "
                          "prefilling request per mixed serving step) "
                          "— serve.step.mixed_chunk_tokens; larger "
                          "amortizes the step launch, smaller bounds "
                          "decode-latency interference")
# sharded serving mesh axes (parallel/plan.py plan_axes, shape key
# world_hidden_hq_hkv): dp x tp must equal the world size and tp must
# tile both head counts — invalid entries fall back to the all-tp
# default instead of building an uncompilable mesh
register_knob("parallel.dp",
              description="serving mesh data-parallel axis size "
                          "(batch + page-pool sharding)")
register_knob("parallel.tp",
              description="serving mesh tensor-parallel axis size "
                          "(heads/inter/vocab sharding; must tile "
                          "num_qo_heads and num_kv_heads)")
register_knob("parallel.ep",
              description="expert-parallel factor of the tp axis for "
                          "MoE serving steps (1 = dense; must divide "
                          "parallel.tp — the Mapping moe_ep contract)")
# continuous-batching engine scheduler statics (serve/engine.py,
# EngineConfig.from_knobs; shape key = (hidden, hq, hkv, hd) of the
# served model) — the shape ladder the engine compiles is derived from
# these, so each chip generation can trade batch width against the
# chunked-prefill budget
register_knob("engine.block_size",
              description="serving-engine KV block (page) size in "
                          "tokens — the block-pool / prefix-cache "
                          "sharing granularity (full blocks hash into "
                          "the prefix trie)")
register_knob("engine.prefill_budget_tokens",
              description="chunked-prefill token budget per engine "
                          "step — bounds prefill's latency "
                          "interference on decode lanes; the marginal "
                          "chunk is additionally priced by "
                          "costmodel.predict_step_seconds against "
                          "EngineConfig.slo_step_seconds")
register_knob("engine.max_batch",
              description="serving-engine batch slots (concurrent "
                          "running requests); also the decode floor "
                          "of the compile-once rung ladder")
# tiered-KV statics (serve/kv_tier.py; same (hidden, hq, hkv, hd)
# shape key as the other engine.* knobs): whether the host-RAM tier
# below the block pool is attached, how preemption resumes, and how
# much host RAM the tier may hold — each chip generation trades its
# HBM GiB budget against host capacity + restore bandwidth here
register_knob("engine.kv_offload", kind="str",
              choices=("off", "host"),
              description="serving-engine KV offload tier: 'off' = "
                          "device-only (PR 11 behavior), 'host' = "
                          "attach a HostKVStore so preempted/idle "
                          "requests spill their page runs to host RAM "
                          "and restore bit-exactly on resume — "
                          "effective KV capacity exceeds hwspec "
                          "hbm_gib")
register_knob("engine.spill_policy", kind="str",
              choices=("recompute", "spill", "auto"),
              description="preemption resume policy: 'recompute' = "
                          "fold + re-prefill (PR 11), 'spill' = "
                          "always offload to the host tier, 'auto' = "
                          "per-victim cost-model comparison (restore "
                          "bytes over the HBM roofline vs recompute "
                          "FLOPs via predict_step_seconds — the "
                          "choose_decode_splits pattern)")
register_knob("engine.host_gib",
              description="host-RAM KV store capacity in GiB "
                          "(HostKVStore; LRU-evicts spilled entries "
                          "over this budget, downgrading their resume "
                          "to recompute — counted, never silent)")
register_knob("engine.attention_backend", kind="str",
              choices=("reference", "kernel"),
              description="serving-engine attention tier: 'reference' "
                          "= the dense XLA oracle form (bitwise-"
                          "provable, interpret-mode correctness "
                          "anchor), 'kernel' = the Pallas work-unit "
                          "lowering (serve/engine_kernels.py — PR 3 "
                          "prefill mainloop + PR 6 split-KV decode "
                          "composed by the cascade merge)")


def validate_tactic(op_name: str, value) -> Optional[str]:
    """Error message if (op_name, value) is not a registered legal
    tactic; None when valid.  Unknown op names are errors — that is the
    stale-config bug class L006 exists to catch."""
    spec = KNOWN_KNOBS.get(op_name)
    if spec is None:
        return (f"unknown autotuner knob {op_name!r} (registered: "
                f"{sorted(KNOWN_KNOBS)})")
    return spec.validate(value)


def _device_config_key() -> Optional[str]:
    """Normalize ``device_kind`` to a shipped-config file stem.

    The reference ships per-GPU tuned configs (``flashinfer/tuning_configs/``
    keyed by SM arch); the TPU analogue keys on generation: v5e / v5p / v4 /
    v6e."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    if "lite" in kind and "v5" in kind:
        return "v5e"
    if "v6" in kind or "trillium" in kind:
        return "v6e"
    if "v5" in kind:
        return "v5p"
    if "v4" in kind:
        return "v4"
    return None


def _flatten_config(data: dict) -> Dict[str, Any]:
    """Merge a shipped config file's tactic tables.

    Schema: a top-level ``"tactics"`` dict plus any number of named
    SECTIONS — dicts carrying their own ``"tactics"`` (and optionally
    ``"seed": true`` for entries derived off-chip, plus a ``"comment"``).
    Sections group an op family's entries (the ``"prefill"`` section
    feeds the pipelined prefill path; see docs/performance.md) and merge
    after the flat table, so a section entry wins on key collision.
    Entries that fail :func:`validate_tactic` are dropped — a stale or
    hand-mangled config key must not reach a kernel launch (L006 catches
    it at lint time; this is the runtime belt to that suspender)."""
    out: Dict[str, Any] = {}
    tables = [data.get("tactics", {})]
    tables += [sec["tactics"] for key, sec in sorted(data.items())
               if isinstance(sec, dict) and key != "tactics"
               and isinstance(sec.get("tactics"), dict)]
    for table in tables:
        for key, val in table.items():
            op_name = key.split("|", 1)[0]
            if validate_tactic(op_name, val) is None:
                out[key] = val
    return out


class AutoTuner:
    _instance: Optional["AutoTuner"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._cache: Dict[str, Any] = {}
        self._shipped: Dict[str, Any] = {}
        self._loaded = False
        self._tuning_enabled = False

    @classmethod
    def get(cls) -> "AutoTuner":
        with cls._lock:
            if cls._instance is None:
                cls._instance = AutoTuner()
            return cls._instance

    @property
    def tuning_enabled(self) -> bool:
        return self._tuning_enabled

    # ---- persistence -----------------------------------------------------
    def _meta(self) -> Dict[str, str]:
        import jax

        return {
            "version": __version__,
            "device": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
        }

    def _cache_path(self) -> Path:
        return env.cache_dir() / "autotuner" / "tactics.json"

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        # shipped per-generation defaults (reference tuning_configs/ role):
        # loaded first, overridden by anything the user's own tuning cached.
        # Shape-keyed, version-independent — a library upgrade keeps them.
        try:
            stem = _device_config_key()
        except Exception:
            stem = None
        if stem is not None:
            # package copy first, then a bundle-installed copy in the
            # cache dir (artifacts.unpack_artifacts target) — the
            # bundle is the newer/fleet-specific table, so it wins.
            # Per-file try: a corrupt package JSON must not block the
            # bundle copy the fleet explicitly distributed (or vice
            # versa)
            for root in (
                Path(__file__).parent / "tuning_configs",
                env.cache_dir() / "tuning_configs",
            ):
                try:
                    p = root / f"{stem}.json"
                    if p.is_file():
                        self._shipped.update(
                            _flatten_config(json.loads(p.read_text()))
                        )
                except Exception:
                    pass
        p = self._cache_path()
        try:
            data = json.loads(p.read_text())
            if data.get("meta") == self._meta():
                self._cache = data.get("tactics", {})
        except Exception:
            pass

    def _save(self) -> None:
        from flashinfer_tpu.utils import atomic_write_text

        atomic_write_text(
            self._cache_path(),
            json.dumps({"meta": self._meta(), "tactics": self._cache}, indent=1),
        )

    # ---- tuning ----------------------------------------------------------
    def lookup(self, op_name: str, shape_key: Sequence, default: Any = None) -> Any:
        """Non-profiling fetch: user cache -> shipped config -> default.

        For call sites (e.g. plan()) where profiling is impossible because
        live tensors don't exist yet; ``choose_one`` is the profiling path."""
        from flashinfer_tpu.tactics_blocklist import blocked

        self._load()
        key = f"{op_name}|{'_'.join(map(str, shape_key))}"
        for store in (self._cache, self._shipped):
            if key in store and not blocked(op_name, store[key]):
                val = store[key]
                return tuple(val) if isinstance(val, list) else val
        return default

    def choose_one(
        self,
        op_name: str,
        shape_key: Sequence,
        candidates: Sequence[Any],
        runner: Callable[[Any], Callable[[], Any]],
        default: Any = None,
        module: Any = None,  # kernel module for wedge-quarantine fingerprints
    ) -> Any:
        """Pick the best candidate for (op, shape_key).

        ``runner(candidate)`` returns a nullary callable executing the op
        with that candidate; it is timed with ``block_until_ready``.
        Mirrors ``AutoTuner.choose_one`` (reference autotuner.py:1419)."""
        from flashinfer_tpu.tactics_blocklist import blocked, filter_candidates

        self._load()
        candidates = filter_candidates(op_name, list(candidates))
        key = f"{op_name}|{'_'.join(map(str, shape_key))}"
        if key in self._cache:
            val = self._cache[key]
            # a later-blocklisted cached tactic must not be served
            if not blocked(op_name, val):
                return tuple(val) if isinstance(val, list) else val
            del self._cache[key]
        if not self._tuning_enabled:
            if key in self._shipped and not blocked(op_name, self._shipped[key]):
                val = self._shipped[key]
                return tuple(val) if isinstance(val, list) else val
            return default if default is not None else candidates[0]

        import jax

        from flashinfer_tpu.compile_guard import trace_state_clean

        # called under a jit trace (op embedded in a user model):
        # wall-clock profiling is meaningless there and must not
        # poison the persistent cache
        if not trace_state_clean():
            return default if default is not None else candidates[0]

        from flashinfer_tpu import compile_guard

        best, best_t = None, float("inf")
        for cand in candidates:
            try:
                f = runner(cand)
                # first call runs under the wedge-quarantine marker (a hang
                # while profiling this tactic blocklists it for the next
                # process); the extra warm call keeps compile time and
                # first-run allocator noise out of every timing rep
                # module-inclusive fingerprint: a kernel edit (the fix for a
                # wedge) must automatically clear a tuning-time quarantine
                compile_guard.guarded(
                    op_name, (tuple(map(str, shape_key)), cand), f,
                    module=module,
                )
                jax.block_until_ready(f())
                dt = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    for _ in range(5):
                        out = f()
                    jax.block_until_ready(out)
                    dt = min(dt, (time.perf_counter() - t0) / 5)
            except Exception:
                continue
            if dt < best_t:
                best, best_t = cand, dt
        if best is None:
            best = default if default is not None else candidates[0]
        self._cache[key] = list(best) if isinstance(best, tuple) else best
        self._save()
        return best


@contextlib.contextmanager
def autotune(enable: bool = True):
    """Enable profiling-based tactic selection inside the context
    (reference ``with autotune():`` surface)."""
    t = AutoTuner.get()
    prev = t._tuning_enabled
    t._tuning_enabled = enable
    try:
        yield t
    finally:
        t._tuning_enabled = prev


# ---------------------------------------------------------------------------
# Reference autotuner profile-API surface (flashinfer/autotuner.py).  The
# reference tunes against FAKE tensors described by specs/profiles; this
# tuner times REAL tensors at call sites, so these classes are lightweight
# records that carry the same information into AutoTuner.choose_one keys.
# ---------------------------------------------------------------------------

import dataclasses as _dc
from typing import Callable as _Callable, Tuple as _Tuple


@_dc.dataclass
class Dim:
    """A tensor dimension (reference autotuner.Dim)."""

    value: int = 0


class StaticDim(Dim):
    """Fixed-size dimension."""


@_dc.dataclass
class DynamicDim(Dim):
    """Bucketed dynamic dimension (reference DynamicDim): tuning runs per
    bucket; this package buckets via next-power-of-two shape keys."""

    min: int = 1
    opt: int = 1
    max: int = 1


@_dc.dataclass
class DynamicTensorSpec:
    """Which input dims vary + their bucketing (reference
    DynamicTensorSpec)."""

    input_idx: _Tuple = ()
    dim_idx: _Tuple = ()
    gen_tuning_buckets: object = ()
    map_to_tuning_buckets: object = None


@_dc.dataclass
class ConstraintSpec:
    """Derived-dimension constraint (reference ConstraintSpec)."""

    input_idx: int = 0
    dim_idx: int = 0
    infer_shape: object = None


@_dc.dataclass
class OptimizationProfile:
    """One tuning bucket's concrete shapes (reference
    OptimizationProfile)."""

    shapes: _Tuple = ()


@_dc.dataclass(frozen=True)
class ProfilingCacheKey:
    """Cache key record (reference ProfilingCacheKey); this tuner's keys
    are the `op|shape` strings in tactics.json."""

    op_name: str = ""
    shape_key: str = ""


class FakeTensor:
    """Shape/dtype-only tensor stand-in (reference FakeTensor, used to
    describe profiles without allocating)."""

    def __init__(self, shape=(), dtype=None):
        self.shape = tuple(shape)
        self.dtype = dtype


class TuningConfig:
    """Bundle of dynamic specs + constraints (reference TuningConfig)."""

    def __init__(self, dynamic_tensor_specs=(), constraint_specs=(),
                 **_unused):
        self.dynamic_tensor_specs = tuple(dynamic_tensor_specs)
        self.constraint_specs = tuple(constraint_specs)


class TunableRunner:
    """Base class for tunable op runners (reference TunableRunner): a
    runner exposes candidate tactics and a forward; AutoTuner.choose_one
    times them on the live shapes."""

    def get_valid_tactics(self, inputs, profile) -> list:
        return [-1]

    def forward(self, inputs, tactic: int = -1):
        raise NotImplementedError


class AutoTunerStatistics:
    """Tuning-run counters (reference AutoTunerStatistics)."""

    def __init__(self):
        self.cache_hits = 0
        self.cache_misses = 0
        self.tuned_ops = {}


def autotuner_initializer_empty(shape, dtype):
    import jax.numpy as jnp

    return jnp.empty(shape, dtype)


def autotuner_initializer_ones(shape, dtype):
    import jax.numpy as jnp

    return jnp.ones(shape, dtype)


def autotuner_initializer_rand(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.random.uniform(jax.random.PRNGKey(0), shape).astype(dtype)


def autotuner_initializer_zeros(shape, dtype):
    import jax.numpy as jnp

    return jnp.zeros(shape, dtype)


def autotuner_initializer_randn(shape, dtype):
    import jax
    import jax.numpy as jnp

    return jax.random.normal(jax.random.PRNGKey(0), shape).astype(dtype)


def autotuner_initializer_rand_scaled(shape, dtype, scale: float = 1.0):
    return autotuner_initializer_rand(shape, dtype) * scale


def round_to_nearest_bucket(value: int, buckets) -> int:
    """Snap a dynamic dim to its tuning bucket (reference
    round_to_nearest_bucket): smallest bucket >= value, else the max."""
    bs = sorted(int(b) for b in buckets)
    for b in bs:
        if value <= b:
            return b
    return bs[-1] if bs else value


def make_bucket_mapper(buckets):
    """Bucket-mapping closure (reference make_bucket_mapper)."""
    frozen = tuple(sorted(int(b) for b in buckets))

    def mapper(value: int) -> int:
        return round_to_nearest_bucket(value, frozen)

    return mapper


_AUTOTUNE_PROCESS_GROUP = None


def set_autotune_process_group(group) -> None:
    """Reference: a torch.distributed group for sharing tuning results;
    the mesh-wide analogue is the shared tactics.json file, so the group
    handle is recorded but unused."""
    global _AUTOTUNE_PROCESS_GROUP
    _AUTOTUNE_PROCESS_GROUP = group


def get_autotune_process_group():
    return _AUTOTUNE_PROCESS_GROUP


def is_in_profile_measurement() -> bool:
    """True while the tuner is timing candidates (reference
    is_in_profile_measurement) — this tuner times inline, so this is
    simply whether tuning is enabled."""
    return AutoTuner.get().tuning_enabled
