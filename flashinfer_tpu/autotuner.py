"""Runtime kernel-parameter autotuner.

TPU re-design of the reference autotuner (``flashinfer/autotuner/
autotuner.py:560-1419`` — TunableRunner interface, ``autotune()`` context,
profiling cache with hardware/version metadata validation).  GPU "tactics"
(kernel template choices) map to Pallas launch parameters: block sizes for
the flash kernel, pages-per-chunk for the decode kernels.  Outside an
``autotune()`` context, cached or default parameters are used with zero
profiling overhead; inside, every new (op, bucketed-shape) key is profiled
once across its candidate set and persisted to a JSON cache keyed by
device kind + library version (invalid on mismatch, like the reference's
metadata validation, autotuner.py:297-382).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from flashinfer_tpu import env
from flashinfer_tpu.version import __version__


class AutoTuner:
    _instance: Optional["AutoTuner"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._cache: Dict[str, Any] = {}
        self._loaded = False
        self._tuning_enabled = False

    @classmethod
    def get(cls) -> "AutoTuner":
        with cls._lock:
            if cls._instance is None:
                cls._instance = AutoTuner()
            return cls._instance

    # ---- persistence -----------------------------------------------------
    def _meta(self) -> Dict[str, str]:
        import jax

        return {
            "version": __version__,
            "device": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
        }

    def _cache_path(self) -> Path:
        return env.cache_dir() / "autotuner" / "tactics.json"

    def _load(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        p = self._cache_path()
        try:
            data = json.loads(p.read_text())
            if data.get("meta") == self._meta():
                self._cache = data.get("tactics", {})
        except Exception:
            pass

    def _save(self) -> None:
        p = self._cache_path()
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(
            json.dumps({"meta": self._meta(), "tactics": self._cache}, indent=1)
        )

    # ---- tuning ----------------------------------------------------------
    def choose_one(
        self,
        op_name: str,
        shape_key: Sequence,
        candidates: Sequence[Any],
        runner: Callable[[Any], Callable[[], Any]],
        default: Any = None,
    ) -> Any:
        """Pick the best candidate for (op, shape_key).

        ``runner(candidate)`` returns a nullary callable executing the op
        with that candidate; it is timed with ``block_until_ready``.
        Mirrors ``AutoTuner.choose_one`` (reference autotuner.py:1419)."""
        from flashinfer_tpu.tactics_blocklist import blocked, filter_candidates

        self._load()
        candidates = filter_candidates(op_name, list(candidates))
        key = f"{op_name}|{'_'.join(map(str, shape_key))}"
        if key in self._cache:
            val = self._cache[key]
            # a later-blocklisted cached tactic must not be served
            if not blocked(op_name, val):
                return tuple(val) if isinstance(val, list) else val
            del self._cache[key]
        if not self._tuning_enabled:
            return default if default is not None else candidates[0]

        import jax

        best, best_t = None, float("inf")
        for cand in candidates:
            try:
                f = runner(cand)
                out = f()
                jax.block_until_ready(out)  # compile+warm
                t0 = time.perf_counter()
                for _ in range(5):
                    out = f()
                jax.block_until_ready(out)
                dt = (time.perf_counter() - t0) / 5
            except Exception:
                continue
            if dt < best_t:
                best, best_t = cand, dt
        if best is None:
            best = default if default is not None else candidates[0]
        self._cache[key] = list(best) if isinstance(best, tuple) else best
        self._save()
        return best


@contextlib.contextmanager
def autotune(enable: bool = True):
    """Enable profiling-based tactic selection inside the context
    (reference ``with autotune():`` surface)."""
    t = AutoTuner.get()
    prev = t._tuning_enabled
    t._tuning_enabled = enable
    try:
        yield t
    finally:
        t._tuning_enabled = prev
