"""Call-compatible adapters for high-traffic reference entry points.

Name-resolvable aliases are not migration parity — a reference caller's
CALL SITES must run (VERDICT r3 #5).  Each adapter here accepts the
reference signature verbatim (cited per function), maps and validates
the arguments onto the TPU-native ops, and raises actionable errors for
semantics this backend cannot carry:

- ``out=`` pre-allocated outputs: JAX is functional — the result is the
  return value; accepting-and-ignoring would silently break callers that
  read the buffer they passed, so it raises.
- ``do_finalize=False`` (un-combined per-expert partials + permutation
  metadata): the TPU pipeline always finalizes; raises.
- CUDA weight shuffles / block-major layouts (``weight_layout != 0`` on
  4-D weights): XLA owns TPU layout, and this package's layout-prep
  shims (``shuffle_matrix_a`` etc.) are identities — weights must arrive
  in the logical MajorK form; raises with that instruction.

Accepted-and-inert knobs (``pdl``, ``backend`` strings, tuning hints,
swizzle flags) are CUDA scheduling details with no TPU meaning; see
``docs/migration.md`` for the per-name deviation table.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from flashinfer_tpu import gemm as _gemm
from flashinfer_tpu.fused_moe import fused_moe as _fused_moe
from flashinfer_tpu.fused_moe.routing import (
    route_deepseek_v3,
    route_llama4,
    route_renormalize,
    route_topk,
)
from flashinfer_tpu.quantization import quantize_fp4 as _quantize_fp4


def _map_activation(activation_type: int, name: str) -> str:
    """Reference ``ActivationType`` (tllm_enums.py: Swiglu=3, Geglu=4) ->
    the fused pipeline's gated-activation names."""
    if activation_type == 3:
        return "silu"
    if activation_type == 4:
        return "gelu"
    raise ValueError(
        f"TPU backend: {name} activation_type={activation_type} is not "
        "supported (3 Swiglu and 4 Geglu are)"
    )


def _reject_numerics_args(name: str, **kw) -> None:
    """Arguments that CHANGE NUMERICS must never be silently ignored —
    raise for any that arrived non-None (the inert set is scheduling
    knobs only; see the module docstring)."""
    bad = [k for k, v in kw.items() if v is not None]
    if bad:
        raise ValueError(
            f"TPU backend: {name} does not implement {', '.join(bad)} — "
            "these change numerics and are not silently droppable; fold "
            "them into the weights/activations before the call or remove "
            "them"
        )


def _reject_out(out, name: str) -> None:
    if out is not None:
        raise ValueError(
            f"TPU backend: {name}(out=...) pre-allocated outputs are not "
            "supported — JAX arrays are immutable; use the return value"
        )


def _reject_no_finalize(do_finalize: bool, name: str) -> None:
    if not do_finalize:
        raise ValueError(
            f"TPU backend: {name}(do_finalize=False) is not supported — "
            "the fused pipeline always combines expert partials; drop the "
            "flag (the default, do_finalize=True, is what you get)"
        )


def _weight_ehm(w: jax.Array, name: str, arg: str) -> jax.Array:
    """Reference MoE weights arrive output-major ``[E, M, H]`` (MajorK);
    return the TPU form ``[E, H, M]``.  Block-major 4-D layouts are CUDA
    kernel swizzles with no TPU meaning."""
    if w.ndim != 3:
        raise ValueError(
            f"TPU backend: {name}({arg}=...) expects the logical MajorK "
            f"[num_experts, out_dim, in_dim] 3-D weight (weight_layout=0, "
            f"use_shuffled_weight=False); got shape {w.shape}.  This "
            "package's weight-shuffle helpers are identities, so pass the "
            "unshuffled weights"
        )
    return jnp.swapaxes(w, 1, 2)


def _route_by_method(
    routing_logits: jax.Array,
    routing_bias: Optional[jax.Array],
    top_k: int,
    n_group: Optional[int],
    topk_group: Optional[int],
    routed_scaling_factor: Optional[float],
    routing_method_type: int,
    name: str,
):
    """Reference ``RoutingMethodType`` (tllm_enums.py) -> the routing
    module.  0 Default (softmax->topk), 1 Renormalize (topk->softmax),
    2 DeepSeekV3 (sigmoid+bias grouped), 3 Llama4 (top1 sigmoid),
    4 RenormalizeNaive (softmax->topk->renorm)."""
    logits = routing_logits.astype(jnp.float32)
    if routing_method_type == 0:
        return route_topk(logits, top_k)
    if routing_method_type == 1:
        return route_renormalize(logits, top_k)
    if routing_method_type == 2:
        if routing_bias is None or n_group is None or topk_group is None:
            raise ValueError(
                f"TPU backend: {name} routing_method_type=2 (DeepSeekV3) "
                "needs routing_bias, n_group and topk_group"
            )
        return route_deepseek_v3(
            logits, routing_bias.astype(jnp.float32), top_k,
            int(n_group), int(topk_group),
            float(routed_scaling_factor or 1.0),
        )
    if routing_method_type == 3:
        return route_llama4(logits)
    if routing_method_type == 4:
        probs = jax.nn.softmax(logits, axis=-1)
        w, ids = jax.lax.top_k(probs, top_k)
        return w / jnp.maximum(w.sum(-1, keepdims=True), 1e-20), (
            ids.astype(jnp.int32)
        )
    raise ValueError(
        f"TPU backend: {name} routing_method_type={routing_method_type} "
        "is not implemented (supported: 0 Default, 1 Renormalize, "
        "2 DeepSeekV3, 3 Llama4, 4 RenormalizeNaive)"
    )


def _expand_block_scale(scale: jax.Array, m: int, k: int) -> jax.Array:
    """[.., M//bm, K//bk] block scales -> [.., M, K] elementwise."""
    bm = m // scale.shape[-2]
    bk = k // scale.shape[-1]
    s = jnp.repeat(scale.astype(jnp.float32), bm, axis=-2)
    return jnp.repeat(s, bk, axis=-1)


def _check_local_experts(num_experts, local_expert_offset, local_num_experts,
                         name):
    if local_expert_offset or (
        local_num_experts not in (None, num_experts)
    ):
        raise ValueError(
            f"TPU backend: {name} single-call expert-parallel slicing "
            f"(local_expert_offset={local_expert_offset}, local_num_experts="
            f"{local_num_experts}) is not supported here — shard experts "
            "with fused_moe_ep inside shard_map instead"
        )


def trtllm_bf16_moe(
    routing_logits, routing_bias, hidden_states,
    gemm1_weights, gemm2_weights,
    num_experts: int, top_k: int,
    n_group: Optional[int], topk_group: Optional[int],
    intermediate_size: int,
    local_expert_offset: int = 0,
    local_num_experts: Optional[int] = None,
    routed_scaling_factor: Optional[float] = None,
    routing_method_type: int = 0,
    use_shuffled_weight: bool = True,
    weight_layout: int = 0,
    do_finalize: bool = True,
    enable_pdl=None, tune_max_num_tokens: int = 8192,
    activation_type: int = 3, norm_topk_prob: bool = True,
    routing_replay_out=None, gemm1_alpha=None, gemm1_beta=None,
    gemm1_clamp_limit=None, output=None,
):
    """Reference ``trtllm_bf16_moe`` (fused_moe/core.py:3012) on the TPU
    fused-MoE pipeline.  ``use_shuffled_weight`` is accepted because this
    package's shuffle helpers are identities (weights are already in
    logical form); 4-D block-major weights are rejected, as are the
    swiglu alpha/beta/clamp tensors (numerics-affecting, not droppable)."""
    _reject_no_finalize(do_finalize, "trtllm_bf16_moe")
    _reject_out(output, "trtllm_bf16_moe")
    _reject_numerics_args(
        "trtllm_bf16_moe", gemm1_alpha=gemm1_alpha, gemm1_beta=gemm1_beta,
        gemm1_clamp_limit=gemm1_clamp_limit,
        routing_replay_out=routing_replay_out,
    )
    act = _map_activation(activation_type, "trtllm_bf16_moe")
    _check_local_experts(num_experts, local_expert_offset,
                         local_num_experts, "trtllm_bf16_moe")
    wts, ids = _route_by_method(
        routing_logits, routing_bias, top_k, n_group, topk_group,
        routed_scaling_factor, routing_method_type, "trtllm_bf16_moe",
    )
    w1 = _weight_ehm(jnp.asarray(gemm1_weights), "trtllm_bf16_moe",
                     "gemm1_weights")
    w2 = _weight_ehm(jnp.asarray(gemm2_weights), "trtllm_bf16_moe",
                     "gemm2_weights")
    return _fused_moe(
        jnp.asarray(hidden_states), w1, w2, wts, ids, num_experts,
        activation=act,
    )


def trtllm_fp8_block_scale_moe(
    routing_logits, routing_bias, hidden_states, hidden_states_scale,
    gemm1_weights, gemm1_weights_scale, gemm2_weights, gemm2_weights_scale,
    num_experts: int, top_k: int,
    n_group: Optional[int], topk_group: Optional[int],
    intermediate_size: int,
    local_expert_offset: int = 0,
    local_num_experts: Optional[int] = None,
    routed_scaling_factor: Optional[float] = None,
    routing_method_type: int = 0,
    use_shuffled_weight: bool = False, weight_layout: int = 0,
    do_finalize: bool = True, enable_pdl=None,
    tune_max_num_tokens: int = 8192, fp8_quantization_type=None,
    num_fused_shared_experts: Optional[int] = None,
    activation_type: int = 3, norm_topk_prob: bool = True,
    routing_replay_out=None, gemm1_alpha=None, gemm1_beta=None,
    gemm1_clamp_limit=None, output=None,
):
    """Reference ``trtllm_fp8_block_scale_moe`` (fused_moe/core.py:3571).

    fp8 values + [E, M//bs, H//bs] block scales are dequantized to bf16
    and run on the bf16 MXU pipeline (v5e has no native fp8 matmul; the
    NATIVE low-precision serving path here is int8 — see fused_moe's
    w1_scale int8 route).  ``hidden_states_scale`` follows the reference
    layout ``[H//bs, T]``."""
    name = "trtllm_fp8_block_scale_moe"
    _reject_no_finalize(do_finalize, name)
    _reject_out(output, name)
    _reject_numerics_args(
        name, gemm1_alpha=gemm1_alpha, gemm1_beta=gemm1_beta,
        gemm1_clamp_limit=gemm1_clamp_limit,
        routing_replay_out=routing_replay_out,
        num_fused_shared_experts=num_fused_shared_experts or None,
    )
    act = _map_activation(activation_type, name)
    _check_local_experts(num_experts, local_expert_offset,
                         local_num_experts, name)
    wts, ids = _route_by_method(
        routing_logits, routing_bias, top_k, n_group, topk_group,
        routed_scaling_factor, routing_method_type, name,
    )
    x = jnp.asarray(hidden_states)
    t, h = x.shape
    if hidden_states_scale is not None:
        hs = jnp.asarray(hidden_states_scale, jnp.float32)  # [H//bs, T]
        if hs.shape[-1] != t:
            raise ValueError(
                f"TPU backend: {name} hidden_states_scale must be "
                f"[hidden//block, seq_len] per the reference layout; got "
                f"{hs.shape} for seq_len={t}"
            )
        x = x.astype(jnp.float32) * jnp.repeat(
            hs.T, h // hs.shape[0], axis=-1
        )
    w1 = jnp.asarray(gemm1_weights)
    w2 = jnp.asarray(gemm2_weights)
    if w1.ndim != 3 or w2.ndim != 3:
        raise ValueError(
            f"TPU backend: {name} expects MajorK 3-D weights "
            "(weight_layout=0); block-major layouts are CUDA swizzles "
            "with no TPU meaning"
        )
    w1f = w1.astype(jnp.float32) * _expand_block_scale(
        jnp.asarray(gemm1_weights_scale), w1.shape[1], w1.shape[2]
    )
    w2f = w2.astype(jnp.float32) * _expand_block_scale(
        jnp.asarray(gemm2_weights_scale), w2.shape[1], w2.shape[2]
    )
    return _fused_moe(
        x.astype(jnp.bfloat16),
        jnp.swapaxes(w1f, 1, 2).astype(jnp.bfloat16),
        jnp.swapaxes(w2f, 1, 2).astype(jnp.bfloat16),
        wts, ids, num_experts, activation=act,
    )


def trtllm_fp8_per_tensor_scale_moe(
    routing_logits, routing_bias, hidden_states,
    gemm1_weights, output1_scales_scalar, output1_scales_gate_scalar,
    gemm2_weights, output2_scales_scalar,
    num_experts: int, top_k: int,
    n_group: Optional[int], topk_group: Optional[int],
    intermediate_size: int,
    local_expert_offset: int = 0,
    local_num_experts: Optional[int] = None,
    routed_scaling_factor: Optional[float] = None,
    use_routing_scales_on_input: bool = False,
    routing_method_type: int = 0,
    do_finalize: bool = True, activation_type: int = 3,
    routing_replay_out=None, **_inert,
):
    """Reference ``trtllm_fp8_per_tensor_scale_moe`` (fused_moe/
    core.py:3417): fp8 weights with per-expert-scalar output scales.
    Dequantized to bf16 (see trtllm_fp8_block_scale_moe note).  The
    gate/linear halves of gemm1 share ``output1_scales_scalar`` /
    ``output1_scales_gate_scalar`` in the reference's swiglu fusion; the
    same folding happens here on the dequantized weights."""
    name = "trtllm_fp8_per_tensor_scale_moe"
    _reject_no_finalize(do_finalize, name)
    _reject_numerics_args(
        name,
        gemm1_alpha=_inert.pop("gemm1_alpha", None),
        gemm1_beta=_inert.pop("gemm1_beta", None),
        gemm1_clamp_limit=_inert.pop("gemm1_clamp_limit", None),
        output=_inert.pop("output", None),
        routing_replay_out=routing_replay_out,
    )
    act = _map_activation(activation_type, name)
    _check_local_experts(num_experts, local_expert_offset,
                         local_num_experts, name)
    if use_routing_scales_on_input:
        raise ValueError(
            f"TPU backend: {name} use_routing_scales_on_input=True "
            "(Llama4-style input scaling) is not supported; scale "
            "hidden_states before the call"
        )
    wts, ids = _route_by_method(
        routing_logits, routing_bias, top_k, n_group, topk_group,
        routed_scaling_factor, routing_method_type, name,
    )
    w1 = _weight_ehm(jnp.asarray(gemm1_weights), name, "gemm1_weights")
    w2 = _weight_ehm(jnp.asarray(gemm2_weights), name, "gemm2_weights")
    # per-expert scalars scale each expert's dequantized weights: the
    # reference applies s1*s1gate to the gemm1 halves and s2 to gemm2
    inter = w1.shape[2] // 2
    s_gate = jnp.asarray(output1_scales_gate_scalar,
                         jnp.float32).reshape(-1, 1, 1)
    s_lin = jnp.asarray(output1_scales_scalar, jnp.float32).reshape(-1, 1, 1)
    w1f = w1.astype(jnp.float32)
    w1f = jnp.concatenate(
        [w1f[..., :inter] * s_gate, w1f[..., inter:] * s_lin], axis=-1
    )
    w2f = w2.astype(jnp.float32) * jnp.asarray(
        output2_scales_scalar, jnp.float32
    ).reshape(-1, 1, 1)
    return _fused_moe(
        jnp.asarray(hidden_states).astype(jnp.bfloat16),
        w1f.astype(jnp.bfloat16), w2f.astype(jnp.bfloat16),
        wts, ids, num_experts, activation=act,
    )


def trtllm_fp4_block_scale_moe(
    routing_logits, routing_bias, hidden_states, hidden_states_scale,
    gemm1_weights, gemm1_weights_scale, gemm1_bias, gemm1_alpha,
    gemm1_beta, gemm1_clamp_limit, gemm2_weights, gemm2_weights_scale,
    gemm2_bias, output1_scale_scalar, output1_scale_gate_scalar,
    output2_scale_scalar,
    num_experts: int, top_k: int,
    n_group: Optional[int] = None, topk_group: Optional[int] = None,
    intermediate_size: int = 0,
    local_expert_offset: int = 0,
    local_num_experts: Optional[int] = None,
    routed_scaling_factor: Optional[float] = None,
    routing_method_type: int = 0,
    do_finalize: bool = True, activation_type: int = 3,
    routing_replay_out=None, **_inert,
):
    """Reference ``trtllm_fp4_block_scale_moe`` (fused_moe/core.py:4011).

    fp4 weights in THIS package's storage form (block-int4 packed pairs +
    f32 block scales, the output of the aliased ``fp4_quantize``) are
    dequantized to bf16 and run on the bf16 pipeline.  Reference-side
    e2m1+ue8m0 buffers serialized by the CUDA library are a different
    storage format and are rejected by the shape check."""
    name = "trtllm_fp4_block_scale_moe"
    _reject_no_finalize(do_finalize, name)
    _reject_numerics_args(
        name, gemm1_alpha=gemm1_alpha, gemm1_beta=gemm1_beta,
        gemm1_clamp_limit=gemm1_clamp_limit,
        output1_scale_scalar=output1_scale_scalar,
        output1_scale_gate_scalar=output1_scale_gate_scalar,
        output2_scale_scalar=output2_scale_scalar,
        per_token_scale=_inert.pop("per_token_scale", None),
        output=_inert.pop("output", None),
        routing_replay_out=routing_replay_out,
    )
    act = _map_activation(activation_type, name)
    _check_local_experts(num_experts, local_expert_offset,
                         local_num_experts, name)
    if gemm1_bias is not None or gemm2_bias is not None:
        raise ValueError(
            f"TPU backend: {name} expert biases are not supported"
        )
    wts, ids = _route_by_method(
        routing_logits, routing_bias, top_k, n_group, topk_group,
        routed_scaling_factor, routing_method_type, name,
    )
    from flashinfer_tpu.quantization import dequantize_fp4

    def deq(w, s, arg):
        w, s = jnp.asarray(w), jnp.asarray(s)
        if w.ndim != 3 or w.shape[-1] * 2 % s.shape[-1]:
            raise ValueError(
                f"TPU backend: {name}({arg}) expects this package's fp4 "
                "storage (packed [E, M, K//2] int8 + [E, M, K//block] "
                f"scales from fp4_quantize); got {w.shape} / {s.shape}"
            )
        return dequantize_fp4(w, s).astype(jnp.bfloat16)

    w1 = jnp.swapaxes(deq(gemm1_weights, gemm1_weights_scale,
                          "gemm1_weights"), 1, 2)
    w2 = jnp.swapaxes(deq(gemm2_weights, gemm2_weights_scale,
                          "gemm2_weights"), 1, 2)
    x = jnp.asarray(hidden_states)
    if hidden_states_scale is not None:
        x = dequantize_fp4(x, jnp.asarray(hidden_states_scale))
    return _fused_moe(
        x.astype(jnp.bfloat16), w1, w2, wts, ids, num_experts,
        activation=act,
    )


def cutlass_fused_moe(
    input, token_selected_experts, token_final_scales,
    fc1_expert_weights, fc2_expert_weights, output_dtype,
    quant_scales: Optional[List] = None,
    fc1_expert_biases=None, fc2_expert_biases=None,
    input_sf=None, swiglu_alpha=None, swiglu_beta=None, swiglu_limit=None,
    tp_size: int = 1, tp_rank: int = 0, ep_size: int = 1, ep_rank: int = 0,
    cluster_size: int = 1, cluster_rank: int = 0,
    output=None, enable_alltoall: bool = False,
    use_deepseek_fp8_block_scale: bool = False,
    use_w4_group_scaling: bool = False,
    use_mxfp8_act_scaling: bool = False,
    min_latency_mode: bool = False, **_inert,
):
    """Reference ``cutlass_fused_moe`` (fused_moe/core.py:873): the
    pre-routed entry — caller supplies (token_selected_experts,
    token_final_scales) and output-major expert weights."""
    name = "cutlass_fused_moe"
    _reject_out(output, name)
    # quantized call paths carry their scales in quant_scales/input_sf —
    # running the raw quantized codes without them would be silently
    # wrong by orders of magnitude, so they are rejected, not dropped
    _reject_numerics_args(
        name, quant_scales=quant_scales or None, input_sf=input_sf,
        swiglu_alpha=swiglu_alpha, swiglu_beta=swiglu_beta,
        swiglu_limit=swiglu_limit,
    )
    if (use_deepseek_fp8_block_scale or use_w4_group_scaling
            or use_mxfp8_act_scaling):
        raise ValueError(
            f"TPU backend: {name} quantization-mode flags "
            "(use_deepseek_fp8_block_scale / use_w4_group_scaling / "
            "use_mxfp8_act_scaling) are not implemented — use the "
            "trtllm_fp8_*_moe adapters or fused_moe's int8 path"
        )
    if fc1_expert_biases is not None or fc2_expert_biases is not None:
        raise ValueError(
            f"TPU backend: {name} expert biases are not supported"
        )
    if tp_size != 1 or ep_size != 1 or enable_alltoall:
        raise ValueError(
            f"TPU backend: {name} in-op tp/ep slicing is not supported — "
            "shard with fused_moe_ep inside shard_map"
        )
    if min_latency_mode:
        raise ValueError(
            f"TPU backend: {name} min_latency_mode returns CUDA-specific "
            "buffers; not supported"
        )
    w1 = _weight_ehm(jnp.asarray(fc1_expert_weights), name,
                     "fc1_expert_weights")
    w2 = _weight_ehm(jnp.asarray(fc2_expert_weights), name,
                     "fc2_expert_weights")
    num_experts = w1.shape[0]
    out = _fused_moe(
        jnp.asarray(input), w1, w2,
        jnp.asarray(token_final_scales, jnp.float32),
        jnp.asarray(token_selected_experts, jnp.int32),
        num_experts,
    )
    return out.astype(output_dtype) if output_dtype is not None else out


# ---------------------------------------------------------------------------
# grouped_mm family (reference grouped_mm/core.py): b is [E, n, k], the
# segment result is a[start:end] @ b[e]^T, segments from an indptr
# ---------------------------------------------------------------------------


def _grouped_mm(a, b, m_indptr, alpha=None, out=None,
                out_dtype=jnp.bfloat16, name="grouped_mm_bf16"):
    _reject_out(out, name)
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if b.ndim != 3:
        raise ValueError(
            f"TPU backend: {name} expects b of shape "
            f"[num_groups, n, k]; got {b.shape}"
        )
    indptr = jnp.asarray(m_indptr, jnp.int32).reshape(-1)
    group_sizes = indptr[1:] - indptr[:-1]
    af = a.astype(jnp.float32)
    if alpha is not None:
        af = af * jnp.asarray(alpha, jnp.float32).reshape(())
    res = _gemm.grouped_gemm(
        af.astype(jnp.bfloat16), jnp.swapaxes(b, 1, 2).astype(jnp.bfloat16),
        group_sizes,
    )
    return res.astype(out_dtype)


def grouped_mm_bf16(a, b, m_indptr, out=None, out_dtype=jnp.bfloat16,
                    *, backend: str = "cudnn", tactic: int = -1):
    """Reference ``grouped_mm_bf16`` (grouped_mm/core.py:81)."""
    return _grouped_mm(a, b, m_indptr, None, out, out_dtype,
                       "grouped_mm_bf16")


def grouped_mm_fp8(a, b, m_indptr, alpha=None, out=None,
                   out_dtype=jnp.bfloat16, *, backend: str = "cudnn",
                   tactic: int = -1):
    """Reference ``grouped_mm_fp8`` (grouped_mm/core.py): fp8 operands
    upcast through the bf16 MXU (no native fp8 matmul on v5)."""
    return _grouped_mm(a, b, m_indptr, alpha, out, out_dtype,
                       "grouped_mm_fp8")


grouped_mm_mxfp8 = grouped_mm_fp8


def grouped_mm_fp4(a, b, m_indptr, alpha=None, out=None,
                   out_dtype=jnp.bfloat16, *, backend: str = "cudnn",
                   tactic: int = -1):
    """Reference ``grouped_mm_fp4``: packed-fp4 b in this package's
    storage is not accepted here (pass the dequantized weight); fp8/bf16
    b works as grouped_mm_fp8."""
    return _grouped_mm(a, b, m_indptr, alpha, out, out_dtype,
                       "grouped_mm_fp4")


# ---------------------------------------------------------------------------
# dense mm family (reference gemm/gemm_base.py)
# ---------------------------------------------------------------------------


def mm_bf16(a, b, bias=None, pdl: bool = False, out=None,
            out_dtype=jnp.bfloat16, backend: str = "auto"):
    """Reference ``mm_bf16`` (gemm_base.py:542): a [m, k] x b [k, n]
    (+ optional bias [n]).  backend strings select CUDA engines and are
    inert here (one MXU path)."""
    _reject_out(out, "mm_bf16")
    res = _gemm.mm_bf16(jnp.asarray(a), jnp.asarray(b),
                        out_dtype=jnp.float32)
    if bias is not None:
        res = res + jnp.asarray(bias, jnp.float32)[None, :]
    return res.astype(out_dtype)


def bmm_bf16(a, b, bias=None, pdl: bool = False, out=None,
             out_dtype=jnp.bfloat16, backend: str = "auto"):
    """Batched twin of :func:`mm_bf16` (reference bmm_bf16,
    gemm_base.py:806)."""
    _reject_out(out, "bmm_bf16")
    res = _gemm.bmm_bf16(jnp.asarray(a), jnp.asarray(b),
                         out_dtype=jnp.float32)
    if bias is not None:
        res = res + jnp.asarray(bias, jnp.float32)
    return res.astype(out_dtype)


def mm_fp8(a, b, alpha=None, out_dtype=jnp.bfloat16, out=None,
           backend: str = "trtllm_low_latency",
           a_scale=None, b_scale=None):
    """Reference ``mm_fp8`` (gemm_base.py:4190): fp8 a [m, k] with a
    combined output scale ``alpha``.  ``b`` is EITHER the reference's
    prepared 3-D layout ``(k // 128, n, 128)`` from
    ``prepare_low_latency_gemm_weights`` (reconstructed to [k, n] here)
    OR a native 2-D [k, n] weight.  A raw un-prepared [n, k] 2-D weight
    is indistinguishable when square — keep the prepare step when
    porting (ADVICE r4; docs/migration.md).  The TPU-native keyword pair
    (a_scale=, b_scale=) is kept as a KEYWORD superset — positional
    callers get the reference argument order (gemm.mm_fp8 keeps the
    native positional form)."""
    _reject_out(out, "mm_fp8")
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if b.ndim == 3:
        kb, n, blk = b.shape
        if blk != 128:
            raise ValueError(
                "TPU backend: mm_fp8 prepared-b last dim must be the "
                f"reference block_size 128; got {b.shape}. Produce b with "
                "prepare_low_latency_gemm_weights"
            )
        b = jnp.swapaxes(b, 0, 1).reshape(n, kb * blk).T
    elif b.ndim == 2:
        if b.shape[0] != a.shape[-1]:
            raise ValueError(
                f"TPU backend: mm_fp8 2-D b must be [k, n] with k="
                f"{a.shape[-1]}; got {b.shape}. If this is a raw [n, k] "
                "weight, pass it through prepare_low_latency_gemm_weights "
                "first (reference flow, gemm_base.py:4240)"
            )
    return _gemm.mm_fp8(
        a, b,
        a_scale=alpha if alpha is not None else a_scale,
        b_scale=b_scale, out_dtype=out_dtype,
    )


def bmm_fp8(A, B, A_scale=None, B_scale=None, dtype=None, out=None,
            backend: str = "cublas", out_dtype=None):
    """Reference ``bmm_fp8`` (gemm_base.py:6739): batched fp8 matmul with
    per-tensor scales.  ``dtype`` is the reference's output-dtype name;
    ``out_dtype`` kept as the TPU-native keyword."""
    _reject_out(out, "bmm_fp8")
    return _gemm.bmm_fp8(
        jnp.asarray(A), jnp.asarray(B), A_scale, B_scale,
        out_dtype=(dtype or out_dtype or jnp.bfloat16),
    )


def bmm_mxfp8(A, B, A_scale=None, B_scale=None, dtype=None, out=None,
              backend: str = "auto", out_dtype=None):
    """Reference ``bmm_mxfp8`` (gemm_base.py:9065) -> the fp8 batched
    path (mx block scales collapse to per-tensor on the dequantizing
    MXU route)."""
    return bmm_fp8(A, B, A_scale, B_scale, dtype, out, backend, out_dtype)


# ---------------------------------------------------------------------------
# quantize family (reference quantization/): (values, scales) pairs
# ---------------------------------------------------------------------------


def mxfp8_quantize(input, is_sf_swizzled_layout: bool = True,
                   alignment: int = 32, enable_pdl=None,
                   backend: str = "cuda", sf_swizzle_layout=None):
    """Reference ``mxfp8_quantize`` (quantization/fp8_quantization.py:172):
    block-scaled fp8 -> (x_q [M, K] fp8, sf [M, K//alignment]).

    Deviations (documented in docs/migration.md): scales are returned
    row-major f32 (XLA owns layout — the swizzle flags are inert) rather
    than ue8m0."""
    x = jnp.asarray(input)
    m, k = x.shape[-2], x.shape[-1]
    if k % alignment:
        raise ValueError(
            f"TPU backend: mxfp8_quantize needs K % alignment == 0, got "
            f"K={k} alignment={alignment}"
        )
    finfo = jnp.finfo(jnp.float8_e4m3fn)
    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], k // alignment,
                                       alignment)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(xf / scale, float(finfo.min), float(finfo.max))
    return (
        q.astype(jnp.float8_e4m3fn).reshape(x.shape),
        scale[..., 0].astype(jnp.float32),
    )


def fp4_quantize(input, global_scale=None, sf_vec_size: int = 16,
                 sf_use_ue8m0: bool = False,
                 is_sf_swizzled_layout: bool = True,
                 is_sf_8x4_layout: bool = False,
                 is_global_scale_inversed: bool = False,
                 enable_pdl=None, backend: str = "cuda"):
    """Reference ``fp4_quantize`` (quantization/fp4_quantization.py:889)
    -> this package's fp4 storage (packed int4 pairs + f32 block scales).

    ``global_scale`` exists in the reference because e4m3 block scales
    need range compensation; the f32 scales returned here already satisfy
    ``x ~= dequantize_fp4(x_q, sf)`` exactly, so it is accepted and
    inert.  Swizzle flags are inert (identity layout)."""
    return _quantize_fp4(jnp.asarray(input), block_size=sf_vec_size)


def trtllm_mxint4_block_scale_moe(
    routing_logits, routing_bias, hidden_states,
    gemm1_weights, gemm1_weights_scale, gemm1_alpha, gemm1_beta,
    gemm1_clamp_limit, gemm2_weights, gemm2_weights_scale,
    num_experts: int, top_k: int,
    n_group: Optional[int] = None, topk_group: Optional[int] = None,
    intermediate_size: int = 0,
    local_expert_offset: int = 0,
    local_num_experts: Optional[int] = None,
    routed_scaling_factor: Optional[float] = None,
    routing_method_type: int = 0,
    do_finalize: bool = True, **_inert,
):
    """Reference ``trtllm_mxint4_block_scale_moe`` (fused_moe/
    core.py:4398): int4-packed weights + block scales.  The TPU int4
    storage form is the same block-int4 packing as fp4 (two codes per
    int8 + f32 block scales from the quantize family), so this shares
    the fp4 adapter's dequantize-to-bf16 route."""
    return trtllm_fp4_block_scale_moe(
        routing_logits, routing_bias, hidden_states, None,
        gemm1_weights, gemm1_weights_scale, None, gemm1_alpha, gemm1_beta,
        gemm1_clamp_limit, gemm2_weights, gemm2_weights_scale, None,
        None, None, None,
        num_experts, top_k, n_group, topk_group, intermediate_size,
        local_expert_offset, local_num_experts, routed_scaling_factor,
        routing_method_type, do_finalize, **_inert,
    )


def _unpack_routed_topk_ids(packed):
    """The trtllm routed-MoE entries take PACKED routing:
    ``(expert_id << 16) | bf16_bits(weight)`` per (token, choice)
    (reference fused_moe/core.py packed-topk-ids contract)."""
    p = jnp.asarray(packed, jnp.int32)
    ids = (p >> 16).astype(jnp.int32)
    w = jax.lax.bitcast_convert_type(
        (p & 0xFFFF).astype(jnp.uint16), jnp.bfloat16
    ).astype(jnp.float32)
    return ids, w


def trtllm_mxint4_block_scale_routed_moe(
    topk_ids, hidden_states,
    gemm1_weights, gemm1_weights_scale, gemm1_alpha, gemm1_beta,
    gemm1_clamp_limit, gemm2_weights, gemm2_weights_scale,
    num_experts: int, top_k: int,
    n_group: Optional[int] = None, topk_group: Optional[int] = None,
    intermediate_size: int = 0,
    local_expert_offset: int = 0,
    local_num_experts: Optional[int] = None,
    routed_scaling_factor: Optional[float] = None,
    routing_method_type: int = 0,
    do_finalize: bool = True,
    enable_pdl=None, gemm1_lora_delta=None, output=None, **_inert,
):
    """Reference ``trtllm_mxint4_block_scale_routed_moe``
    (fused_moe/core.py:4546): PRE-ROUTED entry — ``topk_ids`` arrives
    PACKED as ``(expert_id << 16) | bf16_bits(weight)`` and is unpacked
    here; weights in this package's block-int4 storage dequantize to
    bf16 (see trtllm_mxint4_block_scale_moe)."""
    name = "trtllm_mxint4_block_scale_routed_moe"
    _reject_no_finalize(do_finalize, name)
    _reject_out(output, name)
    _reject_numerics_args(
        name, gemm1_alpha=gemm1_alpha, gemm1_beta=gemm1_beta,
        gemm1_clamp_limit=gemm1_clamp_limit,
        gemm1_lora_delta=gemm1_lora_delta,
    )
    _check_local_experts(num_experts, local_expert_offset,
                         local_num_experts, name)
    ids, wts = _unpack_routed_topk_ids(topk_ids)
    w1 = jnp.swapaxes(_int4_to_bf16(gemm1_weights, gemm1_weights_scale,
                                    name), 1, 2)
    w2 = jnp.swapaxes(_int4_to_bf16(gemm2_weights, gemm2_weights_scale,
                                    name), 1, 2)
    return _fused_moe(
        jnp.asarray(hidden_states).astype(jnp.bfloat16), w1, w2,
        wts, ids, num_experts,
    )


def _int4_to_bf16(w, s, name):
    from flashinfer_tpu.quantization import dequantize_fp4

    w, s = jnp.asarray(w), jnp.asarray(s)
    if w.ndim != 3 or (w.shape[-1] * 2) % s.shape[-1]:
        raise ValueError(
            f"TPU backend: {name} expects this package's block-int4 "
            f"storage (packed [E, M, K//2] + [E, M, K//block] scales); "
            f"got {w.shape} / {s.shape}"
        )
    return dequantize_fp4(w, s).astype(jnp.bfloat16)
