"""RMSNorm family.

TPU-native re-design of the reference norm ops (``flashinfer/norm/``,
``include/flashinfer/norm.cuh:37-686``): ``rmsnorm``, ``fused_add_rmsnorm``,
``gemma_rmsnorm``, ``gemma_fused_add_rmsnorm``, ``layernorm``.

Differences from the CUDA reference, by design:
- Functional semantics: the reference mutates ``input``/``residual`` in place;
  on TPU we return new arrays (XLA donation makes this zero-copy under jit).
- One Pallas kernel serves the whole family (residual add and the Gemma
  ``weight + 1`` convention are closure specializations — the TPU analogue of
  the reference's jinja-specialized kernel instantiations).
- fp32 accumulation regardless of IO dtype, matching norm.cuh behavior.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.api_logging import flashinfer_api
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashinfer_tpu.utils import cdiv, resolve_backend, use_interpret

_ROW_BLOCK = 256
# row-block tactic space: bandwidth-bound kernel, the knob trades grid
# parallelism against per-invocation DMA size
_ROW_BLOCK_CANDIDATES = (128, 256, 512, 1024)


_row_block_memo: dict = {}


def _tuned_row_block(n: int, hidden: int, dtype, op: str, runner) -> int:
    """Autotuned Pallas row-block (reference tactic selection analogue);
    shipped-config/default outside an autotune() context.  Resolved values
    are memoized per (op, shape, dtype): rmsnorm is a microsecond-scale op
    called once per layer per step, so the hot path must not pay the
    tuner's lock + key-string + blocklist machinery every call."""
    from flashinfer_tpu.autotuner import AutoTuner

    memo_key = (op, n, hidden, str(dtype))
    tuner = AutoTuner.get()
    if not tuner.tuning_enabled:
        rb = _row_block_memo.get(memo_key)
        if rb is not None:
            return rb
    import flashinfer_tpu.norm as _norm_module

    rb = tuner.choose_one(
        f"{op}.row_block",
        (n, hidden, str(dtype)),
        [c for c in _ROW_BLOCK_CANDIDATES if c <= max(n, 128)],
        runner,
        default=_ROW_BLOCK,
        module=_norm_module,
    )
    rb = min(int(rb), n)
    _row_block_memo[memo_key] = rb
    return rb


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float, weight_bias: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    w = w_ref[...].astype(jnp.float32) + weight_bias
    o_ref[...] = (y * w[None, :]).astype(o_ref.dtype)


def _fused_add_rms_kernel(
    x_ref, r_ref, w_ref, o_ref, res_ref, *, eps: float, weight_bias: float
):
    s = x_ref[...].astype(jnp.float32) + r_ref[...].astype(jnp.float32)
    res_ref[...] = s.astype(res_ref.dtype)
    var = jnp.mean(s * s, axis=-1, keepdims=True)
    y = s * jax.lax.rsqrt(var + eps)
    w = w_ref[...].astype(jnp.float32) + weight_bias
    o_ref[...] = (y * w[None, :]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("eps", "weight_bias", "backend", "row_block")
)
def _rmsnorm_impl(
    x, weight, eps: float, weight_bias: float, backend: str,
    row_block: Optional[int] = None,
):
    orig_shape = x.shape
    hidden = orig_shape[-1]
    x2 = x.reshape(-1, hidden)
    n = x2.shape[0]
    if backend == "xla" or n < 8:
        xf = x2.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(var + eps)
        out = (y * (weight.astype(jnp.float32) + weight_bias)).astype(x.dtype)
        return out.reshape(orig_shape)
    rb = min(row_block or _ROW_BLOCK, n)
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps, weight_bias=weight_bias),
        grid=(cdiv(n, rb),),
        in_specs=[
            pl.BlockSpec((rb, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((hidden,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rb, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, hidden), x.dtype),
        interpret=use_interpret(),
    )(x2, weight)
    return out.reshape(orig_shape)


@functools.partial(
    jax.jit, static_argnames=("eps", "weight_bias", "backend", "row_block")
)
def _fused_add_rmsnorm_impl(
    x, residual, weight, eps, weight_bias, backend,
    row_block: Optional[int] = None,
):
    orig_shape = x.shape
    hidden = orig_shape[-1]
    x2 = x.reshape(-1, hidden)
    r2 = residual.reshape(-1, hidden)
    n = x2.shape[0]
    if backend == "xla" or n < 8:
        s = x2.astype(jnp.float32) + r2.astype(jnp.float32)
        var = jnp.mean(s * s, axis=-1, keepdims=True)
        y = s * jax.lax.rsqrt(var + eps)
        out = (y * (weight.astype(jnp.float32) + weight_bias)).astype(x.dtype)
        return out.reshape(orig_shape), s.astype(residual.dtype).reshape(orig_shape)
    rb = min(row_block or _ROW_BLOCK, n)
    out, res = pl.pallas_call(
        functools.partial(_fused_add_rms_kernel, eps=eps, weight_bias=weight_bias),
        grid=(cdiv(n, rb),),
        in_specs=[
            pl.BlockSpec((rb, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((hidden,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rb, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rb, hidden), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, hidden), x.dtype),
            jax.ShapeDtypeStruct((n, hidden), residual.dtype),
        ],
        interpret=use_interpret(),
    )(x2, r2, weight)
    return out.reshape(orig_shape), res.reshape(orig_shape)


def _norm_parity_kw(name, out, enable_pdl):
    """Reference-surface kwargs shared by the norm family: ``enable_pdl``
    is a CUDA launch knob (inert on TPU); ``out=`` preallocation is
    loudly rejected (functional arrays + donation, docs/migration.md)."""
    del enable_pdl  # programmatic-dependent-launch: no TPU meaning
    if out is not None:
        raise ValueError(
            f"TPU backend: {name} out= pre-allocated outputs are not "
            "supported (functional arrays; jit donation replaces "
            "preallocation)"
        )


@flashinfer_api
def rmsnorm(
    x: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    out=None,
    enable_pdl=None,
    backend: str = "auto",
) -> jax.Array:
    r"""Root-mean-square normalization: ``out = x / sqrt(mean(x^2)+eps) * w``.

    Reference: ``flashinfer.norm.rmsnorm`` (flashinfer/norm/, norm.cuh:37).
    """
    _norm_parity_kw("rmsnorm", out, enable_pdl)
    be = resolve_backend(backend, "rmsnorm")
    rb = _tuned_row_block(
        x.size // x.shape[-1], x.shape[-1], x.dtype, "rmsnorm",
        lambda c: (lambda: _rmsnorm_impl(x, weight, eps, 0.0, be, c)),
    )
    return _rmsnorm_impl(x, weight, eps, 0.0, be, rb)


@flashinfer_api
def gemma_rmsnorm(
    x: jax.Array, weight: jax.Array, eps: float = 1e-6, out=None,
    enable_pdl=None, backend: str = "auto",
) -> jax.Array:
    """Gemma-style RMSNorm: scales by ``(weight + 1)`` (norm.cuh Gemma family)."""
    _norm_parity_kw("gemma_rmsnorm", out, enable_pdl)
    return _rmsnorm_impl(x, weight, eps, 1.0, resolve_backend(backend, "gemma_rmsnorm"))


@flashinfer_api
def fused_add_rmsnorm(
    x: jax.Array,
    residual: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    enable_pdl=None,
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Fused residual-add + RMSNorm.

    Returns ``(normed, new_residual)`` where ``new_residual = x + residual``
    — the functional form of the reference's in-place
    ``fused_add_rmsnorm`` (norm.cuh FusedAddRMSNorm).
    """
    _norm_parity_kw("fused_add_rmsnorm", None, enable_pdl)
    be = resolve_backend(backend, "fused_add_rmsnorm")
    rb = _tuned_row_block(
        x.size // x.shape[-1], x.shape[-1], x.dtype, "fused_add_rmsnorm",
        lambda c: (
            lambda: _fused_add_rmsnorm_impl(x, residual, weight, eps, 0.0, be, c)
        ),
    )
    return _fused_add_rmsnorm_impl(x, residual, weight, eps, 0.0, be, rb)


@flashinfer_api
def gemma_fused_add_rmsnorm(
    x: jax.Array,
    residual: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    enable_pdl=None,
    backend: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    _norm_parity_kw("gemma_fused_add_rmsnorm", None, enable_pdl)
    return _fused_add_rmsnorm_impl(
        x, residual, weight, eps, 1.0,
        resolve_backend(backend, "gemma_fused_add_rmsnorm"),
    )


@functools.partial(jax.jit, static_argnames=("eps",))
def layernorm(
    x: jax.Array, gamma: jax.Array, beta: jax.Array, eps: float = 1e-5
) -> jax.Array:
    """Plain LayerNorm (reference ``flashinfer/norm/`` layernorm)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def qk_rmsnorm(
    q: jax.Array,  # [..., num_q_heads, head_dim]
    k: jax.Array,  # [..., num_k_heads, head_dim]
    q_weight: jax.Array,  # [head_dim]
    k_weight: jax.Array,  # [head_dim]
    eps: float = 1e-6,
) -> Tuple[jax.Array, jax.Array]:
    """Per-head RMSNorm of q and k over head_dim (reference QK-RMSNorm
    family, flashinfer/norm/ — used by Qwen3/Gemma-style attention)."""

    def _norm(x, w):
        xf = x.astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        return (xf * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)).astype(
            x.dtype
        )

    return _norm(q, q_weight), _norm(k, k_weight)


@functools.partial(jax.jit, static_argnames=("eps",))
def rmsnorm_silu(
    x: jax.Array, weight: jax.Array, gate: jax.Array, eps: float = 1e-6
) -> jax.Array:
    """Fused RMSNorm + SiLU gating: ``rmsnorm(x) * silu(gate)`` (reference
    ``csrc/rmsnorm_silu.cu``)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    return (y * jax.nn.silu(gate.astype(jnp.float32))).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def layernorm_scale_shift(
    x: jax.Array,  # [tokens, hidden]
    scale: jax.Array,  # [hidden] or [tokens, hidden] adaLN modulation
    shift: jax.Array,
    eps: float = 1e-6,
) -> jax.Array:
    """DiT adaLN: ``layernorm(x, affine=False) * (1 + scale) + shift``
    (reference DiT layernorm family, flashinfer/norm/)."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    s = scale.astype(jnp.float32)
    t = shift.astype(jnp.float32)
    if s.ndim == 1:
        s, t = s[None], t[None]
    return (y * (1.0 + s) + t).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "quant_dtype"))
def rmsnorm_quant_fp8(
    x: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    quant_dtype=jnp.float8_e4m3fn,
) -> Tuple[jax.Array, jax.Array]:
    """Fused RMSNorm + per-tensor fp8 quantize -> (values, scale)
    (reference quantizing-norm variants, flashinfer/norm/ FP8-out family)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    finfo = jnp.finfo(quant_dtype)
    amax = jnp.max(jnp.abs(y))
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(y / scale, float(finfo.min), float(finfo.max)).astype(quant_dtype)
    return q, scale.astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("eps", "quant_dtype"))
def fused_add_rmsnorm_quant_fp8(
    x: jax.Array,
    residual: jax.Array,
    weight: jax.Array,
    eps: float = 1e-6,
    quant_dtype=jnp.float8_e4m3fn,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused residual-add + RMSNorm + fp8 quantize -> (values, scale,
    new_residual) — the AR-free half of the reference's
    AllReduceFusionPattern quantizing epilogues."""
    s = x.astype(jnp.float32) + residual.astype(jnp.float32)
    new_residual = s.astype(x.dtype)
    var = jnp.mean(s * s, axis=-1, keepdims=True)
    y = s * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    finfo = jnp.finfo(quant_dtype)
    amax = jnp.max(jnp.abs(y))
    scale = jnp.maximum(amax / float(finfo.max), 1e-12)
    q = jnp.clip(y / scale, float(finfo.min), float(finfo.max)).astype(quant_dtype)
    return q, scale.astype(jnp.float32), new_residual


@jax.jit
def gate_residual(
    residual: jax.Array, gate: jax.Array, x: jax.Array
) -> jax.Array:
    """DiT gated residual add: ``residual + gate * x``."""
    g = gate.astype(jnp.float32)
    if g.ndim == 1:
        g = g[None]
    return (residual.astype(jnp.float32) + g * x.astype(jnp.float32)).astype(
        residual.dtype
    )


def select_knobs(*_, **__):
    """Reference norm.select_knobs picks CUDA launch knobs per shape; the
    TPU row-block choice lives in the autotuner (rmsnorm.row_block
    tactics), so there is nothing to select here."""
    return {}
