"""Kernel-level profiling.

TPU re-design of the reference profiler (``include/flashinfer/
profiler.cuh:33-80`` device event buffer -> Perfetto,
``flashinfer/profiler/__init__.py:33-95``): on TPU the runtime already
emits a full device-side timeline — ``jax.profiler`` captures XLA/Mosaic
kernel spans to a Perfetto/TensorBoard trace, so the in-kernel tag
machinery collapses into this context manager plus named annotations.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def kernel_profiler(log_dir: str = "/tmp/flashinfer_tpu_trace") -> Iterator[str]:
    """Capture a device trace for the enclosed region.

    View with Perfetto (ui.perfetto.dev) or TensorBoard's profile plugin —
    the analogue of the reference's Perfetto export (profiler/__init__.py).
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span in the device trace (reference profiler event tags)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def scope(name: str) -> Iterator[None]:
    """Named span usable INSIDE jit-traced code: the emitted XLA ops
    carry ``name`` so device traces (and the bench.py serving-loop
    phase decomposition) attribute work to serving phases.
    ``annotate`` is the host-side twin (TraceAnnotation does nothing
    under tracing)."""
    import jax

    with jax.named_scope(name):
        yield


# ---------------------------------------------------------------------------
# Op-level event timeline (reference profiler.cuh event-buffer analogue):
# every @flashinfer_api call between start_timeline()/stop_timeline() is
# recorded and exportable as chrome://tracing JSON.  Host-side spans by
# default (dispatch cost); set FLASHINFER_TPU_TIMELINE_SYNC=1 to
# block_until_ready each op for true wall durations.
#
# Thread-safe (ISSUE 2 satellite): serving loops drive decorated ops
# from executor threads, and the previous bare-global-list design could
# lose events (append after a concurrent stop) or double-export (two
# concurrent stops returning the same list).  Same pattern trace.py
# already uses for its jsonl writes: one module lock around every
# mutation; timeline_active() stays lock-free (a benign race — the
# recorder re-checks under the lock).
# ---------------------------------------------------------------------------

import threading as _threading
import time as _time

_timeline_lock = _threading.Lock()
_timeline_events: Optional[list] = None

# ---- the shared clock base (ISSUE 10 satellite) ----------------------------
# Every host-side recorder in the tree (this op timeline, obs.spans'
# flight recorder) stamps time.perf_counter() values; chrome-trace
# exports used to write those RAW (perf_counter epoch ~= process start)
# while other tooling wrote wall-clock — two trace files whose
# timelines could never merge.  One anchor, captured once at import,
# converts every perf_counter stamp to a common epoch-based µs value,
# so `obs trace` nests spans and op events on ONE timeline.  (The two
# clocks drift only by NTP slew after import — harmless at trace
# scale; what matters is that every exporter uses the SAME anchor.)
_EPOCH_ANCHOR = _time.time() - _time.perf_counter()


def epoch_anchor() -> float:
    """Epoch seconds at ``time.perf_counter() == 0`` (this process)."""
    return _EPOCH_ANCHOR


def perf_to_epoch_us(t: float) -> float:
    """A ``time.perf_counter`` stamp -> epoch-based microseconds on the
    shared trace timeline."""
    return (float(t) + _EPOCH_ANCHOR) * 1e6


def timeline_active() -> bool:
    return _timeline_events is not None


def start_timeline() -> None:
    global _timeline_events
    with _timeline_lock:
        _timeline_events = []


def record_event(name: str, t0: float, t1: float) -> None:
    with _timeline_lock:
        if _timeline_events is not None:
            _timeline_events.append({"name": name, "ts": t0, "dur": t1 - t0})


def stop_timeline(path: Optional[str] = None) -> list:
    """Stop recording; return the events and optionally write a
    chrome://tracing / Perfetto-loadable JSON file.  Concurrent-stop
    safe: the event list is swapped out under the lock, so exactly one
    caller gets the events — a second stop returns []."""
    global _timeline_events
    with _timeline_lock:
        events = _timeline_events or []
        _timeline_events = None
    if path is not None:
        import json
        import os

        trace = {
            "traceEvents": [
                {
                    "name": e["name"], "ph": "X", "pid": os.getpid(), "tid": 0,
                    # epoch-based µs via the shared anchor, so this file
                    # and the obs.export traces share one clock base
                    "ts": perf_to_epoch_us(e["ts"]), "dur": e["dur"] * 1e6,
                    "cat": "flashinfer_tpu",
                }
                for e in events
            ]
        }
        from flashinfer_tpu.utils import atomic_write_text

        atomic_write_text(path, json.dumps(trace))
    return events


@contextlib.contextmanager
def timeline(path: Optional[str] = None) -> Iterator[None]:
    """``with timeline("trace.json"):`` — record every flashinfer_tpu API
    call in the region to a chrome://tracing file."""
    start_timeline()
    try:
        yield
    finally:
        stop_timeline(path)


# ---------------------------------------------------------------------------
# Reference in-kernel profiler surface (flashinfer/profiler/__init__.py:28-
# 120: device tag buffer -> decode_tag -> perfetto export).  TPU re-design:
# Mosaic exposes no in-kernel clock, but the TPU grid executes
# SEQUENTIALLY per core, so an ordered tag stream fully determines the
# schedule; timestamps are synthesized from stream order.  Real wall-time
# kernel profiles come from jax.profiler (Mosaic regions are visible
# there) and the op timeline above; this surface decodes/export-formats
# tag buffers in the reference's layout so tooling ports unchanged.
# ---------------------------------------------------------------------------

import enum as _enum


class EventType(_enum.Enum):
    kBegin = 0
    kEnd = 1
    kInstant = 2


def decode_tag(tag: int, num_blocks: int, num_groups: int):
    """Decode a profiler tag (reference bit layout — bits 0-1 event_type,
    2-11 event_idx, 12-23 block_group_idx, 24-31 sm_id; on TPU the
    "sm_id" field carries the core index, 0 on single-core chips)."""
    sm_id = (tag >> 24) & 0xFF
    block_group_idx = (tag >> 12) & 0xFFF
    event_idx = (tag >> 2) & 0x3FF
    event_type = tag & 0x3
    return (
        block_group_idx // num_groups,
        block_group_idx % num_groups,
        event_idx,
        event_type,
        sm_id,
    )


def encode_tag(block_idx: int, group_idx: int, num_groups: int,
               event_idx: int, event_type: EventType,
               sm_id: int = 0) -> int:
    """Inverse of :func:`decode_tag` — kernels (or host-side recorders)
    build tags with it."""
    bg = block_idx * num_groups + group_idx
    return (
        (int(sm_id) & 0xFF) << 24
        | (bg & 0xFFF) << 12
        | (int(event_idx) & 0x3FF) << 2
        | int(
            event_type.value if isinstance(event_type, EventType)
            else event_type
        )
    )


def export_to_perfetto_trace(profiler_buffer, event_names, file_name):
    """Export a tag buffer to a chrome-trace JSON that Perfetto opens
    directly (reference export_to_perfetto_trace; tg4perfetto protobuf
    replaced with the dependency-free JSON form).

    ``profiler_buffer``: int/uint array — element 0 packs
    (num_blocks, num_groups) as two uint16-in-int32 fields like the
    reference's header; subsequent NONZERO elements are either packed
    ``(tag << 32) | timestamp`` uint64s (reference layout) or plain tags
    (TPU sequential-grid form — timestamps synthesized from order)."""
    import json as _json

    import numpy as _np

    buf = _np.asarray(profiler_buffer).reshape(-1)
    header = int(buf[0])
    num_blocks = max(header & 0xFFFF, 1)
    num_groups = max((header >> 16) & 0xFFFF, 1)
    events = []
    seq = 0
    for raw in buf[1:]:
        raw = int(raw)
        if raw == 0:
            continue
        if raw > 0xFFFFFFFF:  # packed (tag, timestamp)
            tag, ts = raw >> 32, raw & 0xFFFFFFFF
        else:
            tag, ts = raw, seq
            seq += 1
        blk, grp, ev, et, sm = decode_tag(tag, num_blocks, num_groups)
        name = (
            event_names[ev] if ev < len(event_names) else f"event_{ev}"
        )
        ph = {0: "B", 1: "E", 2: "i"}[et & 0x3]
        events.append({
            "name": name, "ph": ph, "ts": ts,
            "pid": sm, "tid": blk * num_groups + grp,
            **({"s": "t"} if ph == "i" else {}),
        })
    with open(file_name, "w") as fh:
        _json.dump({"traceEvents": events}, fh)


def grid_trace_to_buffer(tags) -> "object":
    """Pack a kernel's per-grid-step tag array (e.g.
    ``fused_paged_prefill(..., trace_events=True)``'s ``[Hkv, units]``)
    into the reference profiler-buffer layout consumable by
    :func:`export_to_perfetto_trace`: element 0 = header
    (num_blocks | num_groups << 16), then the tags in grid order."""
    import numpy as _np

    tags = _np.asarray(tags)
    num_blocks = tags.shape[-1]
    if num_blocks > 0xFFFF:
        raise ValueError(f"{num_blocks} blocks exceed the 16-bit header")
    # the kernel encodes the unit straight into the block_group field
    # (group = 0; the head rides sm_id), so the header declares
    # num_groups = 1 — consumers decoding with header fields then get
    # blk == unit exactly
    header = num_blocks | (1 << 16)
    return _np.concatenate(
        [_np.array([header], _np.int64), tags.reshape(-1).astype(_np.int64)]
    )


class TraceGenerator:
    """Reference profiler.TraceGenerator: accumulates profiler events and
    emits a trace file.  Wraps this module's timeline recorder."""

    def __init__(self, path: str = "/tmp/flashinfer_tpu_timeline.json"):
        self.path = path
        start_timeline()

    def record(self, name: str, t0: float, t1: float) -> None:
        record_event(name, t0, t1)

    def flush(self):
        return stop_timeline(self.path)
