"""Kernel-level profiling.

TPU re-design of the reference profiler (``include/flashinfer/
profiler.cuh:33-80`` device event buffer -> Perfetto,
``flashinfer/profiler/__init__.py:33-95``): on TPU the runtime already
emits a full device-side timeline — ``jax.profiler`` captures XLA/Mosaic
kernel spans to a Perfetto/TensorBoard trace, so the in-kernel tag
machinery collapses into this context manager plus named annotations.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def kernel_profiler(log_dir: str = "/tmp/flashinfer_tpu_trace") -> Iterator[str]:
    """Capture a device trace for the enclosed region.

    View with Perfetto (ui.perfetto.dev) or TensorBoard's profile plugin —
    the analogue of the reference's Perfetto export (profiler/__init__.py).
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span in the device trace (reference profiler event tags)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
