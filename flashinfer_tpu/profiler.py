"""Kernel-level profiling.

TPU re-design of the reference profiler (``include/flashinfer/
profiler.cuh:33-80`` device event buffer -> Perfetto,
``flashinfer/profiler/__init__.py:33-95``): on TPU the runtime already
emits a full device-side timeline — ``jax.profiler`` captures XLA/Mosaic
kernel spans to a Perfetto/TensorBoard trace, so the in-kernel tag
machinery collapses into this context manager plus named annotations.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def kernel_profiler(log_dir: str = "/tmp/flashinfer_tpu_trace") -> Iterator[str]:
    """Capture a device trace for the enclosed region.

    View with Perfetto (ui.perfetto.dev) or TensorBoard's profile plugin —
    the analogue of the reference's Perfetto export (profiler/__init__.py).
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span in the device trace (reference profiler event tags)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


# ---------------------------------------------------------------------------
# Op-level event timeline (reference profiler.cuh event-buffer analogue):
# every @flashinfer_api call between start_timeline()/stop_timeline() is
# recorded and exportable as chrome://tracing JSON.  Host-side spans by
# default (dispatch cost); set FLASHINFER_TPU_TIMELINE_SYNC=1 to
# block_until_ready each op for true wall durations.
# ---------------------------------------------------------------------------

_timeline_events: Optional[list] = None


def timeline_active() -> bool:
    return _timeline_events is not None


def start_timeline() -> None:
    global _timeline_events
    _timeline_events = []


def record_event(name: str, t0: float, t1: float) -> None:
    if _timeline_events is not None:
        _timeline_events.append({"name": name, "ts": t0, "dur": t1 - t0})


def stop_timeline(path: Optional[str] = None) -> list:
    """Stop recording; return the events and optionally write a
    chrome://tracing / Perfetto-loadable JSON file."""
    global _timeline_events
    events = _timeline_events or []
    _timeline_events = None
    if path is not None:
        import json
        import os

        trace = {
            "traceEvents": [
                {
                    "name": e["name"], "ph": "X", "pid": os.getpid(), "tid": 0,
                    "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6,
                    "cat": "flashinfer_tpu",
                }
                for e in events
            ]
        }
        from flashinfer_tpu.utils import atomic_write_text

        atomic_write_text(path, json.dumps(trace))
    return events


@contextlib.contextmanager
def timeline(path: Optional[str] = None) -> Iterator[None]:
    """``with timeline("trace.json"):`` — record every flashinfer_tpu API
    call in the region to a chrome://tracing file."""
    start_timeline()
    try:
        yield
    finally:
        stop_timeline(path)
