"""Paged KV-cache operations.

TPU-native re-design of the reference page ops (``flashinfer/page.py:251-743``,
``include/flashinfer/page.cuh``).  The paged cache is a pair of arrays
``(k_cache, v_cache)``:

- NHD layout: ``[num_pages, page_size, num_kv_heads, head_dim]``
- HND layout: ``[num_pages, num_kv_heads, page_size, head_dim]``

(the reference's combined ``[num_pages, 2, ...]`` tensor form is also accepted
where noted).  Appends are functional scatters — under jit with donated cache
buffers XLA performs them in place, which is the TPU replacement for the
reference's mutating CUDA kernels (page.cuh:299 AppendPagedKVCache).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from flashinfer_tpu.api_logging import flashinfer_api

from flashinfer_tpu.utils import check_kv_layout, TensorLayout, get_seq_lens  # noqa: F401


def get_batch_indices_positions(
    append_indptr: jax.Array, seq_lens: jax.Array, nnz: int
) -> Tuple[jax.Array, jax.Array]:
    """Per-token (request index, kv position) for a ragged append batch.

    Token ``i`` in request ``r`` (i.e. ``append_indptr[r] <= i <
    append_indptr[r+1]``) is assigned position
    ``seq_lens[r] - (append_indptr[r+1] - append_indptr[r]) + (i - append_indptr[r])``
    — identical semantics to the reference helper (``flashinfer/page.py:251``).
    """
    token = jnp.arange(nnz)
    req = jnp.searchsorted(append_indptr, token, side="right") - 1
    append_len = append_indptr[req + 1] - append_indptr[req]
    pos = seq_lens[req] - append_len + (token - append_indptr[req])
    return req.astype(jnp.int32), pos.astype(jnp.int32)


def _flatten_cache(cache: jax.Array, layout: TensorLayout):
    """View cache as [num_pages * page_size, H, D] rows regardless of layout."""
    if layout == TensorLayout.HND:
        cache = jnp.swapaxes(cache, 1, 2)  # -> NHD
    p, ps, h, d = cache.shape
    return cache.reshape(p * ps, h, d), (p, ps, h, d)


def _unflatten_cache(flat: jax.Array, dims, layout: TensorLayout):
    p, ps, h, d = dims
    cache = flat.reshape(p, ps, h, d)
    if layout == TensorLayout.HND:
        cache = jnp.swapaxes(cache, 1, 2)
    return cache


@functools.partial(jax.jit, static_argnames=("kv_layout", "page_size"))
def _append_impl(
    append_key, append_value, batch_indices, positions,
    k_cache, v_cache, kv_indices, kv_indptr, kv_layout: str, page_size: int,
):
    layout = check_kv_layout(kv_layout)
    kflat, dims = _flatten_cache(k_cache, layout)
    vflat, _ = _flatten_cache(v_cache, layout)
    page_in_req = positions // page_size
    slot = positions % page_size
    page_id = kv_indices[kv_indptr[batch_indices] + page_in_req]
    rows = page_id * page_size + slot
    kflat = kflat.at[rows].set(append_key.astype(kflat.dtype))
    vflat = vflat.at[rows].set(append_value.astype(vflat.dtype))
    return (
        _unflatten_cache(kflat, dims, layout),
        _unflatten_cache(vflat, dims, layout),
    )


@flashinfer_api
def append_paged_kv_cache(
    append_key: jax.Array,  # [nnz, num_kv_heads, head_dim]
    append_value: jax.Array,  # [nnz, num_kv_heads, head_dim]
    batch_indices: jax.Array,  # [nnz]
    positions: jax.Array,  # [nnz]
    paged_kv_cache: Union[Tuple[jax.Array, jax.Array], jax.Array],
    kv_indices: jax.Array,
    kv_indptr: jax.Array,
    kv_last_page_len: jax.Array = None,  # accepted for API parity; unused
    kv_layout: str = "NHD",
) -> Tuple[jax.Array, jax.Array]:
    """Scatter ragged new K/V tokens into the paged cache.

    Functional form of the reference ``append_paged_kv_cache``
    (``flashinfer/page.py:443``): returns the updated ``(k_cache, v_cache)``.
    ``kv_last_page_len`` is accepted for signature parity but the positions
    array fully determines target slots.
    """
    if isinstance(paged_kv_cache, tuple):
        k_cache, v_cache = paged_kv_cache
    else:
        # combined [num_pages, 2, ...] layout
        k_cache, v_cache = paged_kv_cache[:, 0], paged_kv_cache[:, 1]
    layout = check_kv_layout(kv_layout)
    page_size = (
        k_cache.shape[1] if layout == TensorLayout.NHD else k_cache.shape[2]
    )
    return _append_impl(
        append_key, append_value, batch_indices, positions,
        k_cache, v_cache, kv_indices, kv_indptr, kv_layout, page_size,
    )


@functools.partial(jax.jit, static_argnames=("page_size",))
def append_paged_mla_kv_cache(
    append_ckv: jax.Array,  # [nnz, ckv_dim]
    append_kpe: jax.Array,  # [nnz, kpe_dim]
    batch_indices: jax.Array,
    positions: jax.Array,
    ckv_cache: jax.Array,  # [num_pages, page_size, ckv_dim]
    kpe_cache: jax.Array,  # [num_pages, page_size, kpe_dim]
    kv_indices: jax.Array,
    kv_indptr: jax.Array,
    page_size: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """MLA (compressed-KV) paged append: ckv (latent, 512) + kpe (rope, 64)
    caches (reference ``append_paged_mla_kv_cache``, page.cuh:441).

    ``kpe_cache`` may be allocated wider than ``append_kpe`` (the TPU-native
    layout lane-pads kpe to 128 so the decode kernel can page-DMA it without
    a per-call padded copy — see ops/mla_decode.py); the pad columns are
    left untouched.
    """
    ps = ckv_cache.shape[1]
    page_in_req = positions // ps
    slot = positions % ps
    page_id = kv_indices[kv_indptr[batch_indices] + page_in_req]
    rows = page_id * ps + slot
    cflat = ckv_cache.reshape(-1, ckv_cache.shape[-1])
    pflat = kpe_cache.reshape(-1, kpe_cache.shape[-1])
    cflat = cflat.at[rows].set(append_ckv.astype(cflat.dtype))
    kpe_dim = append_kpe.shape[-1]
    pflat = pflat.at[rows, :kpe_dim].set(append_kpe.astype(pflat.dtype))
    return cflat.reshape(ckv_cache.shape), pflat.reshape(kpe_cache.shape)


@functools.partial(jax.jit, static_argnames=("kv_layout",))
def append_paged_kv_cache_quant_fp8(
    append_key: jax.Array,  # [nnz, num_kv_heads, head_dim] high precision
    append_value: jax.Array,
    batch_indices: jax.Array,
    positions: jax.Array,
    paged_kv_cache: Tuple[jax.Array, jax.Array],  # fp8 caches
    kv_indices: jax.Array,
    kv_indptr: jax.Array,
    k_scale: jax.Array,  # scalar f32: high_precision = fp8 * scale
    v_scale: jax.Array,
    kv_layout: str = "NHD",
) -> Tuple[jax.Array, jax.Array]:
    """Fused quantize-and-append into an fp8 paged cache (the reference's
    quantizing-append path, fp4_kv_quantization.cu / rope-quantize-append
    family, mapped to the v5 fp8-storage story): new K/V rows are divided by
    the running scales, saturating-cast to the cache dtype, and scattered.
    Decode then folds the same scales back in via run(k_scale=, v_scale=)."""
    k_cache, v_cache = paged_kv_cache
    finfo = jnp.finfo(k_cache.dtype)
    kq = jnp.clip(
        append_key.astype(jnp.float32) / k_scale, float(finfo.min),
        float(finfo.max),
    ).astype(k_cache.dtype)
    vq = jnp.clip(
        append_value.astype(jnp.float32) / v_scale, float(finfo.min),
        float(finfo.max),
    ).astype(v_cache.dtype)
    layout = check_kv_layout(kv_layout)
    page_size = (
        k_cache.shape[1] if layout == TensorLayout.NHD else k_cache.shape[2]
    )
    return _append_impl(
        kq, vq, batch_indices, positions, k_cache, v_cache,
        kv_indices, kv_indptr, kv_layout, page_size,
    )


@functools.partial(jax.jit, static_argnames=("kv_layout",))
def append_paged_kv_cache_quant_int8(
    append_key: jax.Array,  # [nnz, num_kv_heads, head_dim] high precision
    append_value: jax.Array,
    batch_indices: jax.Array,
    positions: jax.Array,
    paged_kv_cache: Tuple[jax.Array, jax.Array],  # int8 caches
    kv_indices: jax.Array,
    kv_indptr: jax.Array,
    k_scale: jax.Array,  # scalar f32: high_precision = int8 * scale
    v_scale: jax.Array,
    kv_layout: str = "NHD",
) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantize-and-append — int8 is the low-precision MXU
    story on v5e/v5p (SURVEY §7: "FP8/FP4 → int8 fallback story"), so this
    is the serving-path twin of ``append_paged_kv_cache_quant_fp8``.
    Rows are divided by the running scales, rounded and saturated to
    [-127, 127]; decode folds the scales back in via run(k_scale=,
    v_scale=)."""
    from flashinfer_tpu.quantization import quantize_symmetric_int8

    k_cache, v_cache = paged_kv_cache
    kq = quantize_symmetric_int8(append_key, k_scale)
    vq = quantize_symmetric_int8(append_value, v_scale)
    layout = check_kv_layout(kv_layout)
    page_size = (
        k_cache.shape[1] if layout == TensorLayout.NHD else k_cache.shape[2]
    )
    return _append_impl(
        kq, vq, batch_indices, positions, k_cache, v_cache,
        kv_indices, kv_indptr, kv_layout, page_size,
    )


def block_sparse_indices_to_vector_sparse_offsets(
    block_indices: jax.Array,
    indptr: jax.Array,
    vector_sparse_offsets: jax.Array,
    vector_sparse_indptr: jax.Array,
    kv_len_arr: jax.Array,
    stride_block: int,
    stride_n: int,
    batch_size: int,
    block_size: int,
) -> jax.Array:
    """Expand block-sparse page indices to per-token element offsets
    (reference ``flashinfer/page.py`` helper for vector-sparse attention).

    Fills ``vector_sparse_offsets``-shaped output: entry for token ``j`` of
    request ``b`` is ``block_indices[indptr[b] + j // block_size] *
    stride_block + (j % block_size) * stride_n``.  The output buffer's static
    length bounds the token count; slots past ``vector_sparse_indptr[-1]``
    are zeroed (jit-safe — no host sync on the traced total).
    """
    nnz_max = vector_sparse_offsets.shape[0]
    token = jnp.arange(nnz_max)
    if block_size == 1:
        valid = token < block_indices.shape[0]
        blk = block_indices[jnp.minimum(token, block_indices.shape[0] - 1)]
        return jnp.where(valid, blk * stride_block, 0).astype(jnp.int32)
    req = jnp.searchsorted(vector_sparse_indptr, token, side="right") - 1
    req = jnp.clip(req, 0, batch_size - 1)
    j = token - vector_sparse_indptr[req]
    blk = block_indices[jnp.clip(indptr[req] + j // block_size, 0,
                                 block_indices.shape[0] - 1)]
    out = blk * stride_block + (j % block_size) * stride_n
    valid = token < vector_sparse_indptr[batch_size]
    return jnp.where(valid, out, 0).astype(jnp.int32)
