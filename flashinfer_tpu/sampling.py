"""Sampling / renorm / mask ops and chain speculative sampling.

TPU-native re-design of the reference sampling family
(``flashinfer/sampling.py:737-1980``, ``include/flashinfer/sampling.cuh``).

API mapping notes:
- JAX is functional: every sampling op takes an explicit PRNG ``key`` instead
  of the reference's implicit ``generator``/``philox`` state.
- The reference's sorting-free dual-pivot rejection kernels
  (sampling.cuh:293-1519) exist to avoid GPU-global sorts.  The TPU
  equivalent is the single-HBM-pass VMEM-resident threshold-bisection
  kernel (``ops/sampling_kernels.py``) — the default (``backend="pallas"``)
  for the renorm/mask/filter family on TPU.  The sort-based XLA forms
  remain as the ``backend="xla"`` oracle.  Sampling itself is
  Gumbel-argmax (``jax.random.categorical``) — already sort-free.
  fp32 throughout.
- Threshold tie semantics: like the reference kernels, *all* tokens tied
  at the cut value are kept (a sort's arbitrary tie-cut differs only on
  exactly-equal probabilities).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from flashinfer_tpu.api_logging import flashinfer_api
from flashinfer_tpu.utils import resolve_backend

# plain float, not jnp.float32(): a module-level jnp scalar dispatches a
# device op at import time, which initializes the backend — and hangs the
# *import* when the tunneled chip is wedged (observed round 3)
_NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=())
def softmax(
    logits: jax.Array, temperature: Optional[jax.Array] = None
) -> jax.Array:
    """Temperature-scaled softmax (reference ``flashinfer.sampling.softmax``)."""
    x = logits.astype(jnp.float32)
    if temperature is not None:
        t = jnp.asarray(temperature, jnp.float32)
        t = jnp.maximum(t, 1e-6)
        if t.ndim == 1:
            t = t[:, None]
        x = x / t
    return jax.nn.softmax(x, axis=-1)


@flashinfer_api
def sampling_from_probs(
    probs: jax.Array,  # [batch, vocab]
    key: jax.Array,
    indices: Optional[jax.Array] = None,
    deterministic: bool = True,  # parity arg; TPU sampling is deterministic per key
) -> jax.Array:
    """Categorical sampling from probabilities (reference
    ``sampling_from_probs``, sampling.py:737). ``indices`` selects a probs row
    per output (for shared distributions)."""
    if indices is not None:
        probs = probs[indices]
    logp = jnp.log(jnp.maximum(probs.astype(jnp.float32), 1e-30))
    return jax.random.categorical(key, logp, axis=-1).astype(jnp.int32)


@flashinfer_api
def sampling_from_logits(
    logits: jax.Array, key: jax.Array, indices: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    if indices is not None:
        logits = logits[indices]
    return jax.random.categorical(key, logits.astype(jnp.float32), axis=-1).astype(
        jnp.int32
    )


# ---------------------------------------------------------------------------
# Renorm / mask family
# ---------------------------------------------------------------------------


def _as_batch_param(p, batch: int) -> jax.Array:
    p = jnp.asarray(p)
    if p.ndim == 0:
        p = jnp.broadcast_to(p, (batch,))
    return p


def top_p_renorm_probs(probs: jax.Array, top_p, backend: str = "auto") -> jax.Array:
    """Renormalize to the smallest threshold set of probs whose mass
    reaches ``top_p``; everything else zeroed (reference
    ``top_p_renorm_probs``)."""
    if resolve_backend(backend, "top_p_renorm_probs") == "pallas":
        from flashinfer_tpu.ops.sampling_kernels import threshold_select

        tp = _as_batch_param(top_p, probs.shape[0]).astype(jnp.float32)
        return threshold_select(probs, tp, tp, mode="top_p")
    return _top_p_renorm_probs_xla(probs, top_p)


@jax.jit
def _top_p_renorm_probs_xla(probs: jax.Array, top_p) -> jax.Array:
    p = probs.astype(jnp.float32)
    tp = _as_batch_param(top_p, p.shape[0]).astype(jnp.float32)[:, None]
    sorted_p = jnp.sort(p, axis=-1)[:, ::-1]
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep entries whose preceding cumulative mass is < top_p; always keep
    # the top-1 token (top_p=0 means greedy, matching the reference kernels)
    rank0 = jnp.arange(p.shape[-1])[None, :] == 0
    keep_sorted = ((cum - sorted_p) < tp) | rank0
    # threshold = smallest kept probability
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_p, jnp.inf), axis=-1, keepdims=True
    )
    kept = jnp.where(p >= thresh, p, 0.0)
    return kept / jnp.sum(kept, axis=-1, keepdims=True)


def top_k_renorm_probs(probs: jax.Array, top_k, backend: str = "auto") -> jax.Array:
    """Keep the top-k probs and renormalize (reference ``top_k_renorm_probs``)."""
    if resolve_backend(backend, "top_k_renorm_probs") == "pallas":
        from flashinfer_tpu.ops.sampling_kernels import threshold_select

        k = _as_batch_param(top_k, probs.shape[0]).astype(jnp.float32)
        return threshold_select(probs, k, k, mode="top_k")
    return _top_k_renorm_probs_xla(probs, top_k)


@jax.jit
def _top_k_renorm_probs_xla(probs: jax.Array, top_k) -> jax.Array:
    p = probs.astype(jnp.float32)
    batch, vocab = p.shape
    k = _as_batch_param(top_k, batch).astype(jnp.int32)
    sorted_p = jnp.sort(p, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sorted_p, jnp.clip(k[:, None] - 1, 0, vocab - 1), axis=-1
    )
    kept = jnp.where(p >= kth, p, 0.0)
    return kept / jnp.sum(kept, axis=-1, keepdims=True)


def top_k_mask_logits(logits: jax.Array, top_k, backend: str = "auto") -> jax.Array:
    """Mask all but the top-k logits to -inf (reference ``top_k_mask_logits``)."""
    if resolve_backend(backend, "top_k_mask_logits") == "pallas":
        from flashinfer_tpu.ops.sampling_kernels import threshold_select

        k = _as_batch_param(top_k, logits.shape[0]).astype(jnp.float32)
        return threshold_select(logits, k, k, mode="top_k_logits")
    return _top_k_mask_logits_xla(logits, top_k)


@jax.jit
def _top_k_mask_logits_xla(logits: jax.Array, top_k) -> jax.Array:
    x = logits.astype(jnp.float32)
    batch, vocab = x.shape
    k = _as_batch_param(top_k, batch).astype(jnp.int32)
    sorted_x = jnp.sort(x, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sorted_x, jnp.clip(k[:, None] - 1, 0, vocab - 1), axis=-1
    )
    return jnp.where(x >= kth, x, _NEG_INF)


# ---------------------------------------------------------------------------
# Filtered sampling
# ---------------------------------------------------------------------------


@flashinfer_api
def top_p_sampling_from_probs(
    probs: jax.Array, key: jax.Array, top_p, indices: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    if indices is not None:
        probs = probs[indices]
    return sampling_from_probs(top_p_renorm_probs(probs, top_p), key)


@flashinfer_api
def top_k_sampling_from_probs(
    probs: jax.Array, key: jax.Array, top_k, indices: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    if indices is not None:
        probs = probs[indices]
    return sampling_from_probs(top_k_renorm_probs(probs, top_k), key)


@flashinfer_api
def min_p_sampling_from_probs(
    probs: jax.Array, key: jax.Array, min_p, indices: Optional[jax.Array] = None,
    deterministic: bool = True,
) -> jax.Array:
    """Sample keeping tokens with ``p >= min_p * max(p)`` (reference
    ``min_p_sampling_from_probs``)."""
    if indices is not None:
        probs = probs[indices]
    p = probs.astype(jnp.float32)
    mp = _as_batch_param(min_p, p.shape[0]).astype(jnp.float32)[:, None]
    thresh = mp * jnp.max(p, axis=-1, keepdims=True)
    kept = jnp.where(p >= thresh, p, 0.0)
    kept = kept / jnp.sum(kept, axis=-1, keepdims=True)
    return sampling_from_probs(kept, key)


def _top_k_top_p_filter(probs: jax.Array, top_k, top_p, joint: bool) -> jax.Array:
    """Apply top-k and top-p filters.

    ``joint=False`` ("top_k_first", reference default): top-k renorm first,
    then top-p measured on the *renormalized* distribution.  ``joint=True``:
    both filters measured on the original distribution (reference
    flashinfer/sampling.py joint branch).  On TPU this runs the
    single-pass threshold kernel; off-TPU the one-shared-sort XLA form.
    """
    if resolve_backend("auto", "top_k_top_p_filter") == "pallas":
        from flashinfer_tpu.ops.sampling_kernels import threshold_select

        batch = probs.shape[0]
        k = _as_batch_param(top_k, batch).astype(jnp.float32)
        tp = _as_batch_param(top_p, batch).astype(jnp.float32)
        mode = "top_k_top_p_joint" if joint else "top_k_top_p_seq"
        return threshold_select(probs, k, tp, mode=mode)
    return _top_k_top_p_filter_xla(probs, top_k, top_p, joint)


@functools.partial(jax.jit, static_argnames=("joint",))
def _top_k_top_p_filter_xla(probs, top_k, top_p, joint: bool) -> jax.Array:
    p = probs.astype(jnp.float32)
    batch, vocab = p.shape
    k = _as_batch_param(top_k, batch).astype(jnp.int32)[:, None]
    tp = _as_batch_param(top_p, batch).astype(jnp.float32)[:, None]
    sorted_p = jnp.sort(p, axis=-1)[:, ::-1]
    rank = jnp.arange(vocab)[None, :]
    # always keep at least the top-1 token (top_k=0 / top_p=0 mean greedy)
    topk_mask_sorted = (rank < k) | (rank == 0)
    cum = jnp.cumsum(sorted_p, axis=-1)
    if joint:
        topp_mask_sorted = ((cum - sorted_p) < tp) | (rank == 0)
    else:
        topk_mass = jnp.sum(jnp.where(topk_mask_sorted, sorted_p, 0.0), axis=-1,
                            keepdims=True)
        cum_renormed = jnp.cumsum(
            jnp.where(topk_mask_sorted, sorted_p, 0.0), axis=-1
        ) / jnp.maximum(topk_mass, 1e-30)
        topp_mask_sorted = (
            (cum_renormed - sorted_p / jnp.maximum(topk_mass, 1e-30)) < tp
        ) | (rank == 0)
    keep_sorted = topk_mask_sorted & topp_mask_sorted
    thresh = jnp.min(
        jnp.where(keep_sorted, sorted_p, jnp.inf), axis=-1, keepdims=True
    )
    kept = jnp.where(p >= thresh, p, 0.0)
    return kept / jnp.sum(kept, axis=-1, keepdims=True)


def _check_filter_order(filter_apply_order: str) -> bool:
    if filter_apply_order not in ("top_k_first", "joint"):
        raise ValueError(
            f"unknown filter_apply_order {filter_apply_order!r}, "
            "expected 'top_k_first' or 'joint'"
        )
    return filter_apply_order == "joint"


@flashinfer_api
def top_k_top_p_sampling_from_probs(
    probs: jax.Array, key: jax.Array, top_k, top_p,
    indices: Optional[jax.Array] = None, deterministic: bool = True,
    filter_apply_order: str = "top_k_first",
) -> jax.Array:
    joint = _check_filter_order(filter_apply_order)
    if indices is not None:
        probs = probs[indices]
    return sampling_from_probs(_top_k_top_p_filter(probs, top_k, top_p, joint), key)


def top_k_top_p_sampling_from_logits(
    logits: jax.Array, key: jax.Array, top_k, top_p,
    indices: Optional[jax.Array] = None, deterministic: bool = True,
    filter_apply_order: str = "top_k_first",
) -> jax.Array:
    joint = _check_filter_order(filter_apply_order)
    if indices is not None:
        logits = logits[indices]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return sampling_from_probs(_top_k_top_p_filter(probs, top_k, top_p, joint), key)


# ---------------------------------------------------------------------------
# Chain speculative sampling
# ---------------------------------------------------------------------------


@jax.jit
def chain_speculative_sampling(
    draft_probs: jax.Array,  # [batch, num_spec, vocab]
    draft_token_ids: jax.Array,  # [batch, num_spec]
    target_probs: jax.Array,  # [batch, num_spec + 1, vocab]
    key: jax.Array,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Rejection-based speculative verification (reference
    ``chain_speculative_sampling``, sampling.py / sampling.cuh:1519).

    Returns ``(output_token_ids [batch, num_spec+1] with -1 padding,
    accepted_counts [batch], emitted_counts [batch])``.  Count semantics match
    the reference (sampling.cuh ChainSpeculativeSampling epilogue):
    ``accepted`` counts every draft position whose independent accept test
    passes (even after the first rejection — an acceptance-rate telemetry
    number), while ``emitted`` counts the draft tokens actually emitted
    (the leading accepted run, excluding the bonus token).
    """
    batch, num_spec, vocab = draft_probs.shape
    ku, ks = jax.random.split(key)
    u = jax.random.uniform(ku, (batch, num_spec), dtype=jnp.float32)

    d = draft_probs.astype(jnp.float32)
    t = target_probs.astype(jnp.float32)
    tok = draft_token_ids
    bidx = jnp.arange(batch)[:, None]
    sidx = jnp.arange(num_spec)[None, :]
    p_draft = d[bidx, sidx, tok]
    p_target = t[bidx, sidx, tok]
    accept = u < jnp.minimum(1.0, p_target / jnp.maximum(p_draft, 1e-30))
    # leading accepted run = number of draft tokens emitted
    emitted = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=-1), axis=-1)
    # telemetry count: every position passing its independent test
    accepted = jnp.sum(accept.astype(jnp.int32), axis=-1)

    # residual distribution at the first rejected position (or bonus position)
    pos = emitted  # in [0, num_spec]
    t_at = t[jnp.arange(batch), pos]  # [batch, vocab]
    d_at = jnp.where(
        (pos < num_spec)[:, None],
        d[jnp.arange(batch), jnp.minimum(pos, num_spec - 1)],
        jnp.zeros_like(t_at),
    )
    resid = jnp.maximum(t_at - d_at, 0.0)
    resid_sum = jnp.sum(resid, axis=-1, keepdims=True)
    resid = jnp.where(resid_sum > 0, resid / jnp.maximum(resid_sum, 1e-30), t_at)
    extra = jax.random.categorical(
        ks, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1
    ).astype(jnp.int32)

    out_pos = jnp.arange(num_spec + 1)[None, :]
    out = jnp.where(
        out_pos < pos[:, None],
        jnp.pad(tok, ((0, 0), (0, 1))),
        jnp.where(out_pos == pos[:, None], extra[:, None], -1),
    ).astype(jnp.int32)
    return out, accepted.astype(jnp.int32), emitted.astype(jnp.int32)


def get_default_generators(*_, **__):
    """Reference returns per-device torch.Generators for the sampling
    kernels.  JAX sampling is functional — every entry takes an explicit
    ``key=jax.random.PRNGKey(...)`` — so there is no generator registry;
    returns an empty mapping for import parity."""
    return {}
