"""Sorting-free top-k / top-p selection Pallas kernel.

TPU re-design of the reference's sorting-free sampling kernels
(``include/flashinfer/sampling.cuh:293-1519`` — dual-pivot rejection over
rounds of global-memory traffic).  The TPU version exploits VMEM capacity:
a full 128k-vocab f32 row is only 512 KB, so the whole distribution is
loaded into VMEM *once* and the threshold search (value-space bisection on
the kept count / kept mass) runs entirely on-chip — one HBM read + one
write per row, versus O(log V) passes for a sort or multi-round rejection.
Tie semantics match the reference's threshold-based kernels (all tokens at
the threshold value are kept), not the arbitrary tie-cut of a sort.

Modes:
- ``top_k``: keep the k largest probs, renormalize.
- ``top_p``: keep the smallest value-threshold set with mass >= p, renorm.
- ``top_k_top_p_seq``: top-k first, then top-p measured on the
  renormalized survivor mass (reference ``filter_apply_order="top_k_first"``).
- ``top_k_top_p_joint``: both constraints measured on the original
  distribution (reference ``"joint"``).
- ``top_k_logits``: mask all but the top-k logits to -inf (no renorm).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashinfer_tpu.utils import round_up, tpu_compiler_params, use_interpret

_BISECT_ITERS = 32
_NEG_INF = -1e30
# values at or below this are treated as masked-out (-inf class): they can
# never be selected, and letting them into the bisection range would either
# poison it (lo0 = -inf -> mid stays -inf forever) or stretch it so wide
# (1e30) that 32 halvings leave ~1e20 resolution
_FINITE_FLOOR = -1e20


def _bisect(p, valid, target_fn, lo, hi):
    """Largest threshold t with ``target_fn(mask(p >= t)) >= target`` via
    value-space bisection; p stays resident in VMEM across iterations."""

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        ge = valid & (p >= mid)
        ok = target_fn(ge)  # [rows, 1] bool: constraint still satisfied
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, hi = jax.lax.fori_loop(0, _BISECT_ITERS, body, (lo, hi))
    return lo


def _bisect_prologue(p, vocab):
    """Shared range setup for the bisection kernels: the valid mask
    (in-vocab, not pre-masked to the -inf class), the (lo0, hi0) search
    range, and the all-masked-row collapse (see _FINITE_FLOOR note)."""
    valid = (
        jax.lax.broadcasted_iota(jnp.int32, p.shape, 1) < vocab
    ) & (p > _FINITE_FLOOR)  # pre-masked (-inf class) tokens never selected
    lo0 = jnp.min(jnp.where(valid, p, jnp.inf), axis=1, keepdims=True) - 1e-6
    hi0 = jnp.max(jnp.where(valid, p, -jnp.inf), axis=1, keepdims=True)
    # all-masked row: collapse to an empty kept set instead of nan/inf math
    any_valid = jnp.isfinite(hi0)
    lo0 = jnp.where(any_valid, lo0, 0.0)
    hi0 = jnp.where(any_valid, hi0, 1.0)
    return valid, lo0, hi0, any_valid


def _count_ge_target(a):
    def count_ge(ge):
        return jnp.sum(ge.astype(jnp.float32), axis=1, keepdims=True) >= a

    return count_ge


def _threshold_kernel(
    p_ref,  # [rb, Vpad] f32
    a_ref,  # [rb, 1] f32 (k as float, or top_p)
    b_ref,  # [rb, 1] f32 (top_p for the combined modes; unused otherwise)
    o_ref,  # [rb, Vpad]
    *,
    vocab: int,
    mode: str,
):
    p = p_ref[...]
    valid, lo0, hi0, _ = _bisect_prologue(p, vocab)
    pv = jnp.where(valid, p, 0.0)
    a = a_ref[...]
    count_ge = _count_ge_target(a)

    def mass_ge_target(target):
        def f(ge):
            return (
                jnp.sum(jnp.where(ge, pv, 0.0), axis=1, keepdims=True)
                >= target
            )
        return f

    if mode == "top_k" or mode == "top_k_logits":
        t = _bisect(p, valid, count_ge, lo0, hi0)
    elif mode == "top_p":
        t = _bisect(p, valid, mass_ge_target(a), lo0, hi0)
    elif mode in ("top_k_top_p_seq", "top_k_top_p_joint"):
        tp = b_ref[...]
        tk = _bisect(p, valid, count_ge, lo0, hi0)
        if mode == "top_k_top_p_seq":
            # top-p measured on the mass surviving the top-k filter
            mass_k = jnp.sum(
                jnp.where(valid & (p >= tk), pv, 0.0), axis=1, keepdims=True
            )
            tpv = _bisect(p, valid, mass_ge_target(tp * mass_k), tk, hi0)
        else:
            tpv = _bisect(p, valid, mass_ge_target(tp), lo0, hi0)
        t = jnp.maximum(tk, tpv)
    else:
        raise ValueError(f"unknown mode {mode!r}")

    keep = valid & (p >= t)
    if mode == "top_k_logits":
        o_ref[...] = jnp.where(keep, p, _NEG_INF)
    else:
        kept = jnp.where(keep, pv, 0.0)
        s = jnp.sum(kept, axis=1, keepdims=True)
        o_ref[...] = kept / jnp.maximum(s, 1e-30)


def _f32_sort_key(p):
    """Order-isomorphic int32 key of an f32 array (the radix-sort float
    transform): key comparisons == value comparisons, including -0.0/+0.0
    adjacency and +/-inf extremes."""
    i = jax.lax.bitcast_convert_type(p, jnp.int32)
    return i ^ ((i >> 31) & jnp.int32(0x7FFFFFFF))


def _key_to_f32(key):
    i = jnp.where(key >= 0, key, key ^ jnp.int32(0x7FFFFFFF))
    return jax.lax.bitcast_convert_type(i, jnp.float32)


def key_ge(scores, t):
    """Order-key comparisons ``scores >= t`` / ``scores > t`` computed in
    int32 key space -> (ge, gt) bool arrays.

    Float comparisons flush subnormals to zero under XLA (CPU and TPU),
    which breaks top-k selection for subnormal-scale scores; the key
    compare is exact and matches the bisection kernel's own ordering.
    NaN scores are excluded from both results (a NaN key would otherwise
    sort above +inf)."""
    ks = _f32_sort_key(scores.astype(jnp.float32))
    kt = _f32_sort_key(t.astype(jnp.float32))
    if kt.ndim == ks.ndim - 1:
        kt = kt[..., None]
    ok = ~jnp.isnan(scores)
    return (ks >= kt) & ok, (ks > kt) & ok


def _threshold_only_kernel(
    p_ref,  # [rb, Vpad] f32
    a_ref,  # [rb, 1] f32 (k as float)
    o_ref,  # [rb, 128] f32 (threshold, lane-broadcast)
    *,
    vocab: int,
):
    """EXACT k-th-largest threshold via bit-space bisection.

    Value-space bisection (``_bisect``) cannot converge over wide dynamic
    ranges — one ``-1e15`` "effectively -inf" entry leaves the interval
    ~1e15 * 2^-32 wide after 32 halvings, misranking thousands of entries.
    Bisecting on the order-isomorphic int32 KEY instead (the same trick as
    the reference's radix top-k, ``include/flashinfer/topk.cuh``) halves
    an integer interval < 2^32 wide, so 32 iterations pin the threshold to
    the exact k-th value regardless of magnitudes."""
    p = p_ref[...]
    valid, _, _, any_valid = _bisect_prologue(p, vocab)
    keys = _f32_sort_key(p)
    imax = jnp.int32(0x7FFFFFFF)
    lo = jnp.min(jnp.where(valid, keys, imax), axis=1, keepdims=True)
    hi = jnp.max(jnp.where(valid, keys, -imax - 1), axis=1, keepdims=True)
    a = a_ref[...]

    def body(_, carry):
        lo, hi = carry
        # overflow-safe midpoint of two int32s (lo+hi can exceed int32)
        mid = (lo >> 1) + (hi >> 1) + (lo & hi & 1)
        mid = jnp.maximum(mid, lo + 1)  # progress when hi == lo + 1
        ge = valid & (keys >= mid)
        ok = jnp.sum(ge.astype(jnp.float32), axis=1, keepdims=True) >= a
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    # 33, not 32: the key interval can span up to ~2^32 (negative to
    # positive keys), and ceil-halving a >2^31 interval 32 times can end
    # with hi - lo == 1 and hi untested (reviewer-simulated: 53/4000 rows
    # one ULP low at 32 iters, 0/4000 at 33)
    lo, hi = jax.lax.fori_loop(0, 33, body, (lo, hi))
    # lo is the exact key of the k-th largest (or the row min when the row
    # has fewer than k valid entries — keeps everything, short-row rule)
    t = _key_to_f32(lo)[:, :1]
    t = jnp.where(any_valid, t, jnp.inf)  # all-masked row keeps nothing
    o_ref[...] = jnp.broadcast_to(t, o_ref.shape)


def _launch_bisect(kernel, x, scalars, out_cols, block_rows):
    """Shared pad-and-launch scaffold for the row-wise bisection kernels:
    f32 cast, 128-lane vocab pad, row pad to the block, per-row scalar
    operands padded with a harmless 1.0, one grid dim over row blocks.
    ``out_cols=None`` means a full-width [rpad, vpad] output."""
    x = x.astype(jnp.float32)
    batch, vocab = x.shape
    vpad = round_up(vocab, 128)
    rpad = round_up(batch, block_rows)
    if vpad != vocab or rpad != batch:
        x = jnp.pad(x, ((0, rpad - batch), (0, vpad - vocab)))
    ops = [x] + [
        jnp.pad(
            jnp.asarray(s, jnp.float32).reshape(-1, 1),
            ((0, rpad - batch), (0, 0)), constant_values=1.0,
        )
        for s in scalars
    ]
    oc = vpad if out_cols is None else out_cols
    out = pl.pallas_call(
        kernel,
        grid=(rpad // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, vpad), lambda i: (i, 0))]
        + [
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0))
            for _ in scalars
        ],
        out_specs=pl.BlockSpec((block_rows, oc), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rpad, oc), jnp.float32),
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=use_interpret(),
    )(*ops)
    return out, batch, vocab


@functools.partial(jax.jit, static_argnames=("block_rows",))
def top_k_thresholds(
    scores: jax.Array,  # [batch, vocab] f32 (logits or probs)
    k: jax.Array,  # [batch] int/float per-row k
    *,
    block_rows: int = 8,
) -> jax.Array:
    """Per-row EXACT k-th-largest value via bit-space bisection -> [batch].

    The index-free half of the sorting-free top-k (reference
    ``include/flashinfer/topk.cuh`` radix threshold pass, re-designed for
    VMEM residency): one HBM read of the row and a [rows, 1] write —
    2x less traffic than :func:`threshold_select`, which writes the
    filtered row back.  The returned threshold is the exact k-th-largest
    value (bit-space bisection, see kernel docstring), so
    ``scores >= t`` keeps >= k entries where the excess is exactly the
    equality tie class at t; callers trim ties to exactly k
    (``flashinfer_tpu.topk``).  Rows with fewer than k selectable entries
    get their row minimum (keep-all); all-masked rows get +inf."""
    out, batch, _ = _launch_bisect(
        functools.partial(_threshold_only_kernel, vocab=scores.shape[1]),
        scores, [k], 128, block_rows,
    )
    return out[:batch, 0]


@functools.partial(jax.jit, static_argnames=("mode", "block_rows"))
def threshold_select(
    probs_or_logits: jax.Array,  # [batch, vocab] f32
    a: jax.Array,  # [batch] k (as float) or top_p
    b: jax.Array,  # [batch] top_p for combined modes (ignored otherwise)
    *,
    mode: str,
    block_rows: int = 8,
):
    """Threshold-based top-k/top-p filtering (see module docstring for modes).

    Epsilon-tie semantics: the value-space bisection runs in f32, so the
    threshold resolves to at best ``~range * 2**-_BISECT_ITERS`` (f32 also
    caps effective resolution near ``range * 2**-24``).  Every token within
    float resolution of the cut is treated as tied and KEPT — on
    near-uniform tails (e.g. flat logits at 128k vocab) the kept set can
    therefore exceed k (or the top-p mass) beyond true exact ties, where a
    sort-based oracle would cut arbitrarily among equals.  This is the
    library's documented tie contract (reference threshold kernels share
    it, ``sampling.cuh:293``); callers needing strict-k must post-trim.
    ``tests/test_sampling.py::test_threshold_near_uniform_ties`` bounds the
    deviation."""
    out, batch, vocab = _launch_bisect(
        functools.partial(
            _threshold_kernel, vocab=probs_or_logits.shape[1], mode=mode
        ),
        probs_or_logits, [a, b], None, block_rows,
    )
    return out[:batch, :vocab]
