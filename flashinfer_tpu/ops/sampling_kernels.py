"""Sorting-free top-k / top-p selection Pallas kernel.

TPU re-design of the reference's sorting-free sampling kernels
(``include/flashinfer/sampling.cuh:293-1519`` — dual-pivot rejection over
rounds of global-memory traffic).  The TPU version exploits VMEM capacity:
a full 128k-vocab f32 row is only 512 KB, so the whole distribution is
loaded into VMEM *once* and the threshold search (value-space bisection on
the kept count / kept mass) runs entirely on-chip — one HBM read + one
write per row, versus O(log V) passes for a sort or multi-round rejection.
Tie semantics match the reference's threshold-based kernels (all tokens at
the threshold value are kept), not the arbitrary tie-cut of a sort.

Modes:
- ``top_k``: keep the k largest probs, renormalize.
- ``top_p``: keep the smallest value-threshold set with mass >= p, renorm.
- ``top_k_top_p_seq``: top-k first, then top-p measured on the
  renormalized survivor mass (reference ``filter_apply_order="top_k_first"``).
- ``top_k_top_p_joint``: both constraints measured on the original
  distribution (reference ``"joint"``).
- ``top_k_logits``: mask all but the top-k logits to -inf (no renorm).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashinfer_tpu.utils import round_up, use_interpret

_BISECT_ITERS = 32
_NEG_INF = -1e30
# values at or below this are treated as masked-out (-inf class): they can
# never be selected, and letting them into the bisection range would either
# poison it (lo0 = -inf -> mid stays -inf forever) or stretch it so wide
# (1e30) that 32 halvings leave ~1e20 resolution
_FINITE_FLOOR = -1e20


def _bisect(p, valid, target_fn, lo, hi):
    """Largest threshold t with ``target_fn(mask(p >= t)) >= target`` via
    value-space bisection; p stays resident in VMEM across iterations."""

    def body(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        ge = valid & (p >= mid)
        ok = target_fn(ge)  # [rows, 1] bool: constraint still satisfied
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo, hi = jax.lax.fori_loop(0, _BISECT_ITERS, body, (lo, hi))
    return lo


def _threshold_kernel(
    p_ref,  # [rb, Vpad] f32
    a_ref,  # [rb, 1] f32 (k as float, or top_p)
    b_ref,  # [rb, 1] f32 (top_p for the combined modes; unused otherwise)
    o_ref,  # [rb, Vpad]
    *,
    vocab: int,
    mode: str,
):
    p = p_ref[...]
    valid = (
        jax.lax.broadcasted_iota(jnp.int32, p.shape, 1) < vocab
    ) & (p > _FINITE_FLOOR)  # pre-masked (-inf class) tokens never selected
    pv = jnp.where(valid, p, 0.0)
    lo0 = jnp.min(jnp.where(valid, p, jnp.inf), axis=1, keepdims=True) - 1e-6
    hi0 = jnp.max(jnp.where(valid, p, -jnp.inf), axis=1, keepdims=True)
    # all-masked row: collapse to an empty kept set instead of nan/inf math
    any_valid = jnp.isfinite(hi0)
    lo0 = jnp.where(any_valid, lo0, 0.0)
    hi0 = jnp.where(any_valid, hi0, 1.0)
    a = a_ref[...]

    def count_ge(ge):
        return jnp.sum(ge.astype(jnp.float32), axis=1, keepdims=True) >= a

    def mass_ge_target(target):
        def f(ge):
            return (
                jnp.sum(jnp.where(ge, pv, 0.0), axis=1, keepdims=True)
                >= target
            )
        return f

    if mode == "top_k" or mode == "top_k_logits":
        t = _bisect(p, valid, count_ge, lo0, hi0)
    elif mode == "top_p":
        t = _bisect(p, valid, mass_ge_target(a), lo0, hi0)
    elif mode in ("top_k_top_p_seq", "top_k_top_p_joint"):
        tp = b_ref[...]
        tk = _bisect(p, valid, count_ge, lo0, hi0)
        if mode == "top_k_top_p_seq":
            # top-p measured on the mass surviving the top-k filter
            mass_k = jnp.sum(
                jnp.where(valid & (p >= tk), pv, 0.0), axis=1, keepdims=True
            )
            tpv = _bisect(p, valid, mass_ge_target(tp * mass_k), tk, hi0)
        else:
            tpv = _bisect(p, valid, mass_ge_target(tp), lo0, hi0)
        t = jnp.maximum(tk, tpv)
    else:
        raise ValueError(f"unknown mode {mode!r}")

    keep = valid & (p >= t)
    if mode == "top_k_logits":
        o_ref[...] = jnp.where(keep, p, _NEG_INF)
    else:
        kept = jnp.where(keep, pv, 0.0)
        s = jnp.sum(kept, axis=1, keepdims=True)
        o_ref[...] = kept / jnp.maximum(s, 1e-30)


@functools.partial(jax.jit, static_argnames=("mode", "block_rows"))
def threshold_select(
    probs_or_logits: jax.Array,  # [batch, vocab] f32
    a: jax.Array,  # [batch] k (as float) or top_p
    b: jax.Array,  # [batch] top_p for combined modes (ignored otherwise)
    *,
    mode: str,
    block_rows: int = 8,
):
    """Threshold-based top-k/top-p filtering (see module docstring for modes).

    Epsilon-tie semantics: the value-space bisection runs in f32, so the
    threshold resolves to at best ``~range * 2**-_BISECT_ITERS`` (f32 also
    caps effective resolution near ``range * 2**-24``).  Every token within
    float resolution of the cut is treated as tied and KEPT — on
    near-uniform tails (e.g. flat logits at 128k vocab) the kept set can
    therefore exceed k (or the top-p mass) beyond true exact ties, where a
    sort-based oracle would cut arbitrarily among equals.  This is the
    library's documented tie contract (reference threshold kernels share
    it, ``sampling.cuh:293``); callers needing strict-k must post-trim.
    ``tests/test_sampling.py::test_threshold_near_uniform_ties`` bounds the
    deviation."""
    x = probs_or_logits.astype(jnp.float32)
    batch, vocab = x.shape
    vpad = round_up(vocab, 128)
    rpad = round_up(batch, block_rows)
    if vpad != vocab or rpad != batch:
        x = jnp.pad(x, ((0, rpad - batch), (0, vpad - vocab)))
    a2 = jnp.pad(
        jnp.asarray(a, jnp.float32).reshape(-1, 1), ((0, rpad - batch), (0, 0)),
        constant_values=1.0,
    )
    b2 = jnp.pad(
        jnp.asarray(b, jnp.float32).reshape(-1, 1), ((0, rpad - batch), (0, 0)),
        constant_values=1.0,
    )
    out = pl.pallas_call(
        functools.partial(_threshold_kernel, vocab=vocab, mode=mode),
        grid=(rpad // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, vpad), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, vpad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rpad, vpad), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=use_interpret(),
    )(x, a2, b2)
    return out[:batch, :vocab]
