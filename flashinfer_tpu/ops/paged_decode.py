"""Paged GQA decode attention Pallas kernel.

TPU-native re-design of the reference batch-decode path
(``include/flashinfer/attention/decode.cuh:613`` +
``scheduler.cuh:426 DecodePlan``).  Key design departures, per SURVEY §7:

- The KV page table is a *scalar-prefetch* operand; KV pages are gathered
  HBM→VMEM with double-buffered async DMAs inside the kernel (the Pallas
  paged-attention pattern), instead of the reference's ``paged_kv_t`` global
  loads.
- GQA "use_tensor_cores" trick maps to MXU-shaped q packing: the q heads of
  one KV head are processed together as an [group_padded, head_dim] tile.
- Split-KV work partitioning (reference ``scheduler.cuh:150,426``) exists
  here as a *pipeline-shape* tool, not an SM-filling one: the default
  kernel walks a request's whole KV range sequentially with pipelined DMA
  (grid starvation doesn't exist on a TPU core), but short-context /
  large-batch shapes pay a per-request cold-start DMA stall that the
  split path removes.  ``build_decode_split_units`` partitions each
  request's page list into ``num_splits`` contiguous chunk-aligned KV
  spans at plan time (PR 3 work-unit style scalar-prefetch arrays);
  ``_decode_split_kernel_fused_heads`` writes per-unit ``(out, lse)``
  partials — when every unit is a single DMA chunk the unit stream is
  cross-unit double-buffered with zero cold start anywhere — and
  ``ops/merge.py merge_states`` reduces the partials by the
  online-softmax merge identity.  The split factor is chosen by the
  analytic cost model at plan time (``obs/costmodel.choose_decode_splits``;
  ``decode.splits`` autotune knob overrides).  On-chip proof pending;
  interpret-mode parity is pinned by tests/test_split_decode.py.  LSE
  output remains available for cascade/DCP merging on both paths.

Cache layouts: "HND" ``[num_pages, num_kv_heads, page_size, head_dim]``
(TPU-preferred: one page+head slice is a contiguous [page_size, head_dim]
DMA) or "NHD" ``[num_pages, page_size, num_kv_heads, head_dim]``
(reference default; strided DMA).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashinfer_tpu.utils import cdiv, round_up, use_interpret

_NEG_INF = -1e30

# Plan-static cast targets: the launch knows every dtype in play at
# trace time, so the decode kernels take the cast TARGET as a static
# name selecting from this literal map.  An unsupported dtype fails at
# trace instead of lowering through an unproven Mosaic cast path — the
# enumerable, per-pair-testable set the L015 [cast] lint asks for.
_CAST_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def _decode_kernel(
    # scalar prefetch
    pages_ref,  # [B, P] int32 page table (padded with a valid page id)
    kvlen_ref,  # [B] int32
    # inputs
    q_ref,  # [Gp, D] (block of [B, Hkv, Gp, D])
    k_hbm,  # full cache in ANY/HBM
    v_hbm,
    # outputs
    o_ref,  # [Gp, D]
    lse_ref,  # [Gp, 128]
    # scratch
    k_buf,  # [2, chunk_tokens, D]
    v_buf,  # [2, chunk_tokens, D]
    sem,  # DMA sems [2, 2, ppc]
    *,
    page_size: int,
    ppc: int,  # pages per chunk
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
    out_dtype: str,  # o_ref's dtype name, from _CAST_DTYPES
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    kv_len = kvlen_ref[b]
    chunk_tokens = ppc * page_size
    num_chunks = pl.cdiv(kv_len, chunk_tokens)

    def page_dmas(chunk_idx, slot):
        dmas = []
        for j in range(ppc):  # wedge-lint: ok on-chip validated round 2 at ppc=16 (banked 0.71 TB/s decode); clamp min(512//PS,16)
            page = pages_ref[b, chunk_idx * ppc + j]
            # NHD page layout: per-head strided DMA [PS, h, D]
            k_src = k_hbm.at[page, :, h, :]
            v_src = v_hbm.at[page, :, h, :]
            dst = pl.ds(j * page_size, page_size)
            dmas.append(
                pltpu.make_async_copy(k_src, k_buf.at[slot, dst, :], sem.at[slot, 0, j])
            )
            dmas.append(
                pltpu.make_async_copy(v_src, v_buf.at[slot, dst, :], sem.at[slot, 1, j])
            )
        return dmas

    def start_chunk(chunk_idx, slot):
        for dma in page_dmas(chunk_idx, slot):
            dma.start()

    def wait_chunk(chunk_idx, slot):
        for dma in page_dmas(chunk_idx, slot):
            dma.wait()

    @pl.when(num_chunks > 0)
    def _warmup():
        start_chunk(0, 0)

    q = q_ref[...].astype(jnp.float32) * sm_scale  # [Gp, D]
    gp = q.shape[0]

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < num_chunks)
        def _prefetch():
            start_chunk(i + 1, jax.lax.rem(i + 1, 2))

        wait_chunk(i, slot)
        k = k_buf[slot].astype(jnp.float32)  # [chunk_tokens, D]
        v = v_buf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Gp, chunk_tokens]
        if logits_soft_cap > 0.0:
            s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
        tok = i * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )
        valid = tok < kv_len
        if window_left >= 0:
            valid = valid & (tok >= kv_len - 1 - window_left)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((gp, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((gp, 1), jnp.float32)
    acc0 = jnp.zeros_like(q)
    m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))

    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (acc / l_safe).astype(_CAST_DTYPES[out_dtype])
    lse = jnp.where(l > 0, m + jnp.log(l), _NEG_INF)
    lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _decode_kernel_fused_heads(
    # scalar prefetch
    pages_ref,  # [B, P] int32 page table (padded with a valid page id)
    kvlen_ref,  # [B] int32
    # inputs
    q_ref,  # [Hkv, Gp, D] (block of [B, Hkv, Gp, D])
    k_hbm,  # [num_pages, Hkv, PS, D] in ANY/HBM
    v_hbm,
    # outputs
    o_ref,  # [Hkv, Gp, D]
    lse_ref,  # [Hkv, Gp, 128]
    # scratch
    k_buf,  # [2, ppc, Hkv, PS, D]
    v_buf,
    sem,  # DMA sems [2, 2, ppc]
    base_smem,  # [1] int32: slot parity carried across grid steps
    *,
    page_size: int,
    ppc: int,
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
    num_kv_heads: int,
    cross_step_prefetch: bool,
    compute_dtype: str,  # q's (== o's) dtype name, from _CAST_DTYPES
):
    """HND fast path: one DMA per whole page serves every KV head.

    The per-(batch, kv_head) grid of ``_decode_kernel`` re-reads each page
    once per head in 4 KB slices — 8x the DMA transactions the data needs.
    Here the grid is ``(batch,)``; each 32 KB page ``[Hkv, PS, D]`` is
    gathered once and all head groups are computed from it, with bf16 MXU
    dots (f32 accumulate) instead of VPU upcasts.  This is the TPU analogue
    of the reference's one-CTA-per-request split-KV decode kernel
    (include/flashinfer/attention/decode.cuh:613) with its per-warp head
    parallelism collapsed into the head loop of a single core.

    Cross-step pipelining (``cross_step_prefetch``): each step issues the
    *next* request's first chunk before finishing, hiding the per-request
    cold-start DMA stall (~one chunk's fetch per request; at bs=64/ctx=4k
    that stall is ~6% of the whole step).  Two variants:

    - ``True`` (dynamic): slot parity carried across grid steps in SMEM
      (chunk counts differ per request, so parity is data-dependent).
      Measured LOSING on v5e — the dynamic slot indexing it forces costs
      more than the stall it hides (0.68 vs 0.75 TB/s at bs=64/ctx=4k).
    - ``"static"``: prefetch only when the current request's chunk count
      is EVEN, so the free buffer slot is always slot 0 and every slot
      index stays a compile-time constant; odd-chunk requests simply keep
      the cold-start stall.  All conditions derive from the scalar-
      prefetched ``kvlen_ref``, so there is no carried state at all.  At
      the tuned bs=64/ctx=4k shape (16 chunks/request) every request has
      an even count and the whole stall disappears.
    """
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    kv_len = kvlen_ref[b]
    chunk_tokens = ppc * page_size
    num_chunks = pl.cdiv(kv_len, chunk_tokens)
    static_pf = cross_step_prefetch == "static"
    if cross_step_prefetch is True:
        # kv_len == 0 still walks one (fully masked) chunk: the cross-step
        # pipeline depends on every step consuming the chunk-0 DMA its
        # predecessor issued (dangling semaphore signals otherwise)
        num_chunks = jnp.maximum(num_chunks, 1)

    def page_dmas(bb, chunk_idx, slot):
        dmas = []
        for j in range(ppc):  # wedge-lint: ok ppc bounded by the 8 MiB VMEM clamp at call site; on-chip validated round 2
            page = pages_ref[bb, chunk_idx * ppc + j]
            dmas.append(
                pltpu.make_async_copy(
                    k_hbm.at[page], k_buf.at[slot, j], sem.at[slot, 0, j]
                )
            )
            dmas.append(
                pltpu.make_async_copy(
                    v_hbm.at[page], v_buf.at[slot, j], sem.at[slot, 1, j]
                )
            )
        return dmas

    def start_chunk(bb, chunk_idx, slot):
        for dma in page_dmas(bb, chunk_idx, slot):
            dma.start()

    def wait_chunk(bb, chunk_idx, slot):
        for dma in page_dmas(bb, chunk_idx, slot):
            dma.wait()

    if cross_step_prefetch is True:
        base = jnp.where(b == 0, 0, base_smem[0])

        @pl.when(b == 0)
        def _warmup():
            start_chunk(b, 0, 0)
    elif static_pf:
        base = 0
        # predecessor's epilogue prefetched our chunk 0 into slot 0 iff it
        # ran chunks (nc_prev > 0), had an even count (slot 0 free), and
        # we have chunks to run (its nc_next > 0 check — same formula)
        prev_nc = pl.cdiv(kvlen_ref[jnp.maximum(b - 1, 0)], chunk_tokens)
        prev_prefetched = (
            (b > 0) & (prev_nc > 0) & (jax.lax.rem(prev_nc, 2) == 0)
        )

        @pl.when((num_chunks > 0) & ~prev_prefetched)
        def _warmup():
            start_chunk(b, 0, 0)
    else:
        base = 0

        @pl.when(num_chunks > 0)
        def _warmup():
            start_chunk(b, 0, 0)

    q = q_ref[...]  # [Hkv, Gp, D] native dtype
    gp = q.shape[1]
    head_dim = q.shape[2]
    cdt = _CAST_DTYPES[compute_dtype]  # literal cast target (== q.dtype)

    def body(i, carry):
        m, l, acc = carry  # [Hkv, Gp, 1] x2, [Hkv, Gp, D]
        slot = jax.lax.rem(base + i, 2)

        @pl.when(i + 1 < num_chunks)
        def _prefetch():
            start_chunk(b, i + 1, jax.lax.rem(base + i + 1, 2))

        wait_chunk(b, i, slot)
        tok = i * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )
        valid = tok < kv_len
        if window_left >= 0:
            valid = valid & (tok >= kv_len - 1 - window_left)

        ss, pvs = [], []
        # wedge-lint: ok bounded by num_kv_heads (<=16 served models, 2 dots/head); on-chip validated round 2
        for h in range(num_kv_heads):
            kh = k_buf[slot, :, h, :, :].reshape(chunk_tokens, head_dim)
            if kh.dtype != q.dtype:
                # quantized (fp8/int8) KV: cache bytes cross HBM at half
                # width, dequant is an in-register cast; the scalar
                # k_scale/v_scale are folded into sm_scale / output by the
                # wrapper (reference decode.py:2004 scale folding)
                kh = kh.astype(cdt)
            s = jax.lax.dot_general(
                q[h], kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # [Gp, chunk_tokens] f32
            if logits_soft_cap > 0.0:
                s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
            ss.append(jnp.where(valid, s, _NEG_INF))
        s_all = jnp.stack(ss)  # [Hkv, Gp, chunk]
        m_cur = jnp.max(s_all, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p_all = jnp.where(valid[None], jnp.exp(s_all - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p_all, axis=-1, keepdims=True)
        for h in range(num_kv_heads):  # wedge-lint: ok bounded by num_kv_heads; on-chip validated round 2
            vh = v_buf[slot, :, h, :, :].reshape(chunk_tokens, head_dim)
            if vh.dtype != q.dtype:
                vh = vh.astype(cdt)
            pvs.append(
                jax.lax.dot_general(
                    p_all[h].astype(cdt), vh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        pv = jnp.stack(pvs)  # [Hkv, Gp, D]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((num_kv_heads, gp, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((num_kv_heads, gp, 1), jnp.float32)
    acc0 = jnp.zeros((num_kv_heads, gp, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))

    if cross_step_prefetch is True:
        # hand the free slot to the next request's first chunk before the
        # epilogue so its gather overlaps the output write + step transition
        next_base = jax.lax.rem(base + num_chunks, 2)

        @pl.when(b + 1 < nb)
        def _prefetch_next_request():
            start_chunk(b + 1, 0, next_base)

        base_smem[0] = next_base
    elif static_pf:
        next_nc = pl.cdiv(kvlen_ref[jnp.minimum(b + 1, nb - 1)], chunk_tokens)

        @pl.when(
            (b + 1 < nb) & (num_chunks > 0)
            & (jax.lax.rem(num_chunks, 2) == 0) & (next_nc > 0)
        )
        def _prefetch_next_request_static():
            start_chunk(b + 1, 0, 0)

    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (acc / l_safe).astype(cdt)
    lse = jnp.where(l > 0, m + jnp.log(l), _NEG_INF)
    lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def decode_tactic_key(batch, max_pages, num_qo_heads, num_kv_heads,
                      head_dim, page_size, q_dtype):
    """The ONE shape key for paged-decode tactic caches
    (``paged_decode.pages_per_chunk`` / ``paged_decode.prefetch``): built
    here so every lookup site (wrapper run, model decode steps) stays in
    sync when a field is added."""
    return (batch, max_pages, num_qo_heads, num_kv_heads, head_dim,
            page_size, str(q_dtype))


def _paged_decode_hnd_launch(
    q: jax.Array,  # [batch, num_qo_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, Hkv, PS, D]
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, P_padded] int32
    kv_lens: jax.Array,  # [batch] int32
    *,
    page_size: int,
    pages_per_chunk: int,
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
    cross_step_prefetch,
):
    """Head-fused HND fast path: one 32KB page DMA serves all KV heads.

    Module-level (not a branch body of ``paged_decode_attention``) so
    the ``paged_decode.pages_per_chunk`` KNOB_LAUNCHES binding can
    resolve ONE launch with a once-assigned grid spec and prove shipped
    config entries fit the double-buffered chunk-pair scratch (L009) —
    the same hoist ``paged_decode_attention_split`` already has.
    Returns the padded-group ``(out, lse)`` pair; the caller slices the
    group padding off."""
    batch, num_qo_heads, head_dim = q.shape
    _num_pages, num_kv_heads, _ps, _ = k_cache.shape
    group = num_qo_heads // num_kv_heads
    gp = round_up(group, 8)
    # [B, Hq, D] -> [B, Hkv, Gp, D] with zero padding in the group dim
    qg = q.reshape(batch, num_kv_heads, group, head_dim)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    kernel = functools.partial(
        _decode_kernel_fused_heads,
        page_size=page_size,
        ppc=pages_per_chunk,
        sm_scale=sm_scale,
        logits_soft_cap=logits_soft_cap,
        window_left=window_left,
        num_kv_heads=num_kv_heads,
        cross_step_prefetch=cross_step_prefetch,
        compute_dtype=jnp.dtype(q.dtype).name,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch,),
        in_specs=[
            pl.BlockSpec(
                (None, num_kv_heads, gp, head_dim),
                lambda b, *_: (b, 0, 0, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, num_kv_heads, gp, head_dim),
                lambda b, *_: (b, 0, 0, 0),
            ),
            pl.BlockSpec(
                (None, num_kv_heads, gp, 128), lambda b, *_: (b, 0, 0, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, num_kv_heads, page_size, head_dim),
                k_cache.dtype,
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, num_kv_heads, page_size, head_dim),
                v_cache.dtype,
            ),
            pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk)),
            pltpu.SMEM((1,), jnp.int32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((batch, num_kv_heads, gp, head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch, num_kv_heads, gp, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), qg, k_cache, v_cache)


def _paged_decode_nhd_launch(
    q: jax.Array,  # [batch, num_qo_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, PS, Hkv, D]
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, P_padded] int32
    kv_lens: jax.Array,  # [batch] int32
    *,
    page_size: int,
    pages_per_chunk: int,
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
):
    """Per-head NHD launch (the layout-general slow path); module-level
    for the same launch-resolution reason as ``_paged_decode_hnd_launch``.
    Returns the padded-group ``(out, lse)`` pair."""
    batch, num_qo_heads, head_dim = q.shape
    _num_pages, _ps, num_kv_heads, _ = k_cache.shape
    group = num_qo_heads // num_kv_heads
    gp = round_up(group, 8)
    qg = q.reshape(batch, num_kv_heads, group, head_dim)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))
    chunk_tokens = pages_per_chunk * page_size
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        ppc=pages_per_chunk,
        sm_scale=sm_scale,
        logits_soft_cap=logits_soft_cap,
        window_left=window_left,
        out_dtype=jnp.dtype(q.dtype).name,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, num_kv_heads),
        in_specs=[
            pl.BlockSpec(
                (None, None, gp, head_dim), lambda b, h, *_: (b, h, 0, 0)
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, None, gp, head_dim), lambda b, h, *_: (b, h, 0, 0)
            ),
            pl.BlockSpec((None, None, gp, 128), lambda b, h, *_: (b, h, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, chunk_tokens, head_dim), k_cache.dtype),
            pltpu.VMEM((2, chunk_tokens, head_dim), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((batch, num_kv_heads, gp, head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch, num_kv_heads, gp, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), qg, k_cache, v_cache)


@functools.partial(
    jax.jit,
    static_argnames=(
        "sm_scale", "logits_soft_cap", "window_left", "kv_layout",
        "pages_per_chunk", "return_lse", "cross_step_prefetch",
    ),
)
def paged_decode_attention(
    q: jax.Array,  # [batch, num_qo_heads, head_dim]
    k_cache: jax.Array,
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, max_pages] int32, padded with valid ids
    kv_lens: jax.Array,  # [batch] int32
    *,
    sm_scale: float = 1.0,
    logits_soft_cap: float = 0.0,
    window_left: int = -1,
    kv_layout: str = "HND",
    pages_per_chunk: Optional[int] = None,
    return_lse: bool = False,
    cross_step_prefetch: bool = False,
):
    """Batched paged decode attention over a padded page table.

    ``page_table``/``kv_lens`` are the plan arrays produced by
    ``BatchDecodeWithPagedKVCacheWrapper.plan`` (padded-rectangular page
    table replaces the reference's ragged indptr + CUDAGraph buffer pinning).
    """
    # identity checks, matching the kernel's dispatch (`is True` /
    # == "static"): a truthy 1 or np.True_ must not pass validation and
    # then silently run the no-prefetch path
    if not (cross_step_prefetch is False or cross_step_prefetch is True
            or cross_step_prefetch == "static"):
        raise ValueError(
            f"cross_step_prefetch must be False, True (dynamic SMEM "
            f"parity) or 'static', got {cross_step_prefetch!r}"
        )
    if cross_step_prefetch == "static":
        cross_step_prefetch = "static"  # normalize np.str_ etc.
    batch, num_qo_heads, head_dim = q.shape
    if kv_layout == "HND":
        num_pages, num_kv_heads, page_size, _ = k_cache.shape
    else:
        num_pages, page_size, num_kv_heads, _ = k_cache.shape
    assert num_qo_heads % num_kv_heads == 0
    group = num_qo_heads // num_kv_heads

    if pages_per_chunk is None:
        pages_per_chunk = max(1, min(512 // page_size, 16))
    if kv_layout == "HND":
        # fused-heads scratch scales with num_kv_heads: clamp the
        # double-buffered K+V footprint (2 slots x 2 bufs x ppc x Hkv x
        # PS x D) to ~8 MiB so large heads/pages still compile — applies
        # to explicit/autotuned values too, which would otherwise exceed
        # a v5e core's VMEM at e.g. Hkv=16, PS=16, ppc=64
        per_page = 4 * num_kv_heads * page_size * head_dim * k_cache.dtype.itemsize
        pages_per_chunk = max(1, min(pages_per_chunk, (8 << 20) // per_page))
    max_pages = page_table.shape[1]
    # pad page table columns to a multiple of pages-per-chunk
    p_padded = round_up(max_pages, pages_per_chunk)
    if p_padded != max_pages:
        page_table = jnp.pad(page_table, ((0, 0), (0, p_padded - max_pages)))

    if kv_layout == "HND":
        # head-fused fast path: one 32KB page DMA serves all KV heads
        out, lse = _paged_decode_hnd_launch(
            q, k_cache, v_cache, page_table, kv_lens,
            page_size=page_size, pages_per_chunk=pages_per_chunk,
            sm_scale=sm_scale, logits_soft_cap=logits_soft_cap,
            window_left=window_left,
            cross_step_prefetch=cross_step_prefetch,
        )
    else:
        out, lse = _paged_decode_nhd_launch(
            q, k_cache, v_cache, page_table, kv_lens,
            page_size=page_size, pages_per_chunk=pages_per_chunk,
            sm_scale=sm_scale, logits_soft_cap=logits_soft_cap,
            window_left=window_left,
        )

    out = out[:, :, :group, :].reshape(batch, num_qo_heads, head_dim)
    if return_lse:
        return out, lse[:, :, :group, 0].reshape(batch, num_qo_heads)
    return out


# ---------------------------------------------------------------------------
# Split-KV decode: plan-time work partitioning + partial-state kernel + merge
# (reference scheduler.cuh:150,426 split-KV-then-merge, TPU-reshaped: the
# split removes per-request DMA cold starts instead of filling idle SMs)
# ---------------------------------------------------------------------------


def split_pages_per_chunk(page_size: int, num_kv_heads: int, head_dim: int,
                          itemsize: int = 2) -> int:
    """The ONE pages-per-chunk formula of the split path, shared by the
    planner (unit boundaries are chunk-aligned), the kernel entry
    (scratch shapes), and the plan-time cost model — a skew between any
    two would misalign unit spans against the DMA loop.  Same default +
    8 MiB double-buffer clamp as the unsplit fused-heads path."""
    ppc = max(1, min(512 // page_size, 16))
    per_page = 4 * num_kv_heads * page_size * head_dim * itemsize
    return max(1, min(ppc, (8 << 20) // per_page))


def decode_split_tactic_key(batch, max_pages, num_qo_heads, num_kv_heads,
                            head_dim, page_size, pages_per_chunk, q_dtype):
    """Shape key for the ``decode.splits`` knob: ``decode_tactic_key``
    fields + the pages-per-chunk the plan was built for (the split
    choice and the L009 VMEM proof both depend on it)."""
    return (batch, max_pages, num_qo_heads, num_kv_heads, head_dim,
            page_size, pages_per_chunk, str(q_dtype))


def build_decode_split_units(
    page_table: np.ndarray,  # [B_pad, P] int32 padded page table
    kv_lens: np.ndarray,  # [B_pad] kv token lengths (0 for pad rows)
    *,
    num_splits: int,
    page_size: int,
    pages_per_chunk: int,
):
    """Host-side split planner: partition every request's page list into
    ``num_splits`` contiguous, chunk-aligned KV spans (the decode
    analogue of ``build_prefill_work_units``; reference ``DecodePlan``
    split-KV work estimation, scheduler.cuh:150).

    Unit ``u = b * num_splits + s`` covers request ``b``'s pages
    ``[s * per_b, (s + 1) * per_b)`` where ``per_b = ceil(pages_b /
    num_splits)`` rounded up to a whole number of DMA chunks — chunk
    alignment keeps every unit's page walk a whole-chunk loop, so
    splits below chunk granularity degenerate into empty units (kvlen
    0) which the kernel skips without issuing DMA.  The unit order is
    split-major within each request, so partials reshape to
    ``[B_pad, num_splits, ...]`` for one batched ``merge_states`` call.

    Returns a plan dict whose five array keys (``pages``, ``kvlen``,
    ``wu_req``, ``wu_page0``, ``wu_kvlen``) are the scalar-prefetch
    operands of ``_decode_split_kernel_fused_heads`` IN ORDER (the
    L007 planner/kernel contract), plus statics (``num_units``,
    ``num_splits``, ``single_chunk``, ``pages_per_chunk``) and a
    ``stats`` dict (empty-unit count, launched-vs-real page traffic —
    the padding-waste numbers the cost model charges)."""
    pt = np.asarray(page_table)
    lens = np.asarray(kv_lens, np.int64).reshape(-1)
    B, P = pt.shape
    S = int(num_splits)
    assert S >= 1, num_splits
    W = B * S
    pages_r = -(-lens // page_size)  # cdiv; 0 for empty/pad rows
    per = -(-np.maximum(pages_r, 1) // S)
    per = -(-per // pages_per_chunk) * pages_per_chunk  # chunk-align
    wu_req = np.repeat(np.arange(B, dtype=np.int64), S)
    s_idx = np.tile(np.arange(S, dtype=np.int64), B)
    per_u = np.repeat(per, S)
    page0 = per_u * s_idx
    start_tok = page0 * page_size
    end_tok = np.minimum(start_tok + per_u * page_size, np.repeat(lens, S))
    uklen = np.maximum(end_tok - start_tok, 0)
    page0 = np.where(uklen > 0, page0, 0)  # empty units never DMA
    chunks_u = -(-uklen // (pages_per_chunk * page_size))
    max_chunks = int(chunks_u.max(initial=0))
    # pad table columns so every unit's whole-chunk walk stays in bounds
    width = max(P, int((per * (pages_r > 0)).max(initial=0)) * S,
                pages_per_chunk)
    if width != P:
        pt = np.pad(pt, ((0, 0), (0, width - P)))
    stats = {
        "units": W,
        "units_empty": int((uklen == 0).sum()),
        "max_chunks_per_unit": max_chunks,
        "pages_real": int(pages_r.sum()),
        "pages_launched": int((chunks_u * pages_per_chunk).sum()),
    }
    return dict(
        pages=pt.astype(np.int32),
        kvlen=lens.astype(np.int32),
        wu_req=wu_req.astype(np.int32),
        wu_page0=page0.astype(np.int32),
        wu_kvlen=uklen.astype(np.int32),
        num_units=W,
        num_splits=S,
        single_chunk=bool(max_chunks <= 1),
        pages_per_chunk=pages_per_chunk,
        stats=stats,
    )


def _decode_split_kernel_fused_heads(
    # scalar prefetch (the build_decode_split_units plan arrays, in order)
    pages_ref,  # [B_pad, P_w] int32 page table (padded with valid ids)
    kvlen_ref,  # [B_pad] int32 full per-request kv lengths
    req_ref,  # [W] int32 request id per work unit
    page0_ref,  # [W] int32 first page-table column of the unit's span
    uklen_ref,  # [W] int32 kv tokens in the unit's span (0 = empty unit)
    # inputs
    q_ref,  # [Hkv, Gp, D] (block of [B_pad, Hkv, Gp, D], gathered by req)
    k_hbm,  # [num_pages, Hkv, PS, D] in ANY/HBM
    v_hbm,
    # outputs (per-unit partial state)
    o_ref,  # [Hkv, Gp, D] f32 — softmax-normalized partial output
    lse_ref,  # [Hkv, Gp, 128] f32 — partial log-sum-exp (natural log)
    # scratch
    k_buf,  # [2, ppc, Hkv, PS, D]
    v_buf,
    sem,  # DMA sems [2, 2, ppc]
    *,
    page_size: int,
    ppc: int,
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
    num_kv_heads: int,
    single_chunk: bool,
):
    """Partial-state variant of ``_decode_kernel_fused_heads``: one grid
    step per work unit, per-unit ``(out, lse)`` written unreduced for a
    downstream ``merge_states`` pass.

    Pipeline shape by plan certificate: with ``single_chunk`` (every
    unit at most one DMA chunk — the shape the plan-time split chooser
    targets) the unit stream is cross-UNIT double-buffered: each unit
    issues its successor's chunk before waiting on its own, so no unit
    ever exposes a cold-start DMA stall (the per-request stall the
    unsplit kernel pays on short-context/large-batch shapes).  The
    general path (multi-chunk units) keeps the unsplit kernel's
    intra-unit double buffer and pays one cold start per unit."""
    u = pl.program_id(0)
    nu = pl.num_programs(0)
    b = req_ref[u]
    kv_len = kvlen_ref[b]
    page0 = page0_ref[u]
    uklen = uklen_ref[u]
    chunk_tokens = ppc * page_size
    num_chunks = pl.cdiv(uklen, chunk_tokens)

    def page_dmas(uu, chunk_idx, slot):
        dmas = []
        for j in range(ppc):  # wedge-lint: ok ppc bounded by the shared 8 MiB VMEM clamp (split_pages_per_chunk) — same on-chip-validated bound as the unsplit fused-heads kernel
            page = pages_ref[
                req_ref[uu], page0_ref[uu] + chunk_idx * ppc + j
            ]
            dmas.append(
                pltpu.make_async_copy(
                    k_hbm.at[page], k_buf.at[slot, j], sem.at[slot, 0, j]
                )
            )
            dmas.append(
                pltpu.make_async_copy(
                    v_hbm.at[page], v_buf.at[slot, j], sem.at[slot, 1, j]
                )
            )
        return dmas

    def start_chunk(uu, chunk_idx, slot):
        for dma in page_dmas(uu, chunk_idx, slot):
            dma.start()

    def wait_chunk(uu, chunk_idx, slot):
        for dma in page_dmas(uu, chunk_idx, slot):
            dma.wait()

    q = q_ref[...]  # [Hkv, Gp, D] native dtype
    gp = q.shape[1]
    head_dim = q.shape[2]

    def chunk_update(i, carry, slot):
        m, l, acc = carry  # [Hkv, Gp, 1] x2, [Hkv, Gp, D]
        tok = i * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )
        valid = tok < uklen
        if window_left >= 0:
            # window positions are GLOBAL kv positions of the full request
            valid = valid & (
                page0 * page_size + tok >= kv_len - 1 - window_left
            )
        ss, pvs = [], []
        # wedge-lint: ok bounded by num_kv_heads (<=16 served models, 2 dots/head); same loop as the unsplit fused-heads kernel
        for h in range(num_kv_heads):
            kh = k_buf[slot, :, h, :, :].reshape(chunk_tokens, head_dim)
            if kh.dtype != q.dtype:
                # quantized (fp8/int8) KV: in-register dequant cast, the
                # same scale-folding contract as the unsplit kernel
                kh = kh.astype(q.dtype)
            s = jax.lax.dot_general(
                q[h], kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # [Gp, chunk_tokens] f32
            if logits_soft_cap > 0.0:
                s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
            ss.append(jnp.where(valid, s, _NEG_INF))
        s_all = jnp.stack(ss)  # [Hkv, Gp, chunk]
        m_cur = jnp.max(s_all, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p_all = jnp.where(valid[None], jnp.exp(s_all - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p_all, axis=-1, keepdims=True)
        for h in range(num_kv_heads):  # wedge-lint: ok bounded by num_kv_heads; same loop as the unsplit fused-heads kernel
            vh = v_buf[slot, :, h, :, :].reshape(chunk_tokens, head_dim)
            if vh.dtype != q.dtype:
                vh = vh.astype(q.dtype)
            pvs.append(
                jax.lax.dot_general(
                    p_all[h].astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        pv = jnp.stack(pvs)  # [Hkv, Gp, D]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((num_kv_heads, gp, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((num_kv_heads, gp, 1), jnp.float32)
    acc0 = jnp.zeros((num_kv_heads, gp, head_dim), jnp.float32)

    def finalize(m, l, acc):
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(l), _NEG_INF)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)

    if single_chunk:
        # cross-unit double buffer: successor's chunk issued before this
        # unit's wait; empty units (uklen 0) neither issue nor wait, and
        # the issue/wait conditions are the SAME uklen test, so the
        # semaphore chain stays balanced
        slot = jax.lax.rem(u, 2)

        @pl.when((u == 0) & (uklen > 0))
        def _warmup():
            start_chunk(u, 0, slot)

        @pl.when((u + 1 < nu)
                 & (uklen_ref[jnp.minimum(u + 1, nu - 1)] > 0))
        def _prefetch_next_unit():
            start_chunk(u + 1, 0, jax.lax.rem(u + 1, 2))

        @pl.when(uklen > 0)
        def _compute():
            wait_chunk(u, 0, slot)
            finalize(*chunk_update(0, (m0, l0, acc0), slot))

        @pl.when(uklen <= 0)
        def _empty():
            finalize(m0, l0, acc0)
    else:
        @pl.when(num_chunks > 0)
        def _warmup_general():
            start_chunk(u, 0, 0)

        def body(i, carry):
            slot = jax.lax.rem(i, 2)

            @pl.when(i + 1 < num_chunks)
            def _prefetch():
                start_chunk(u, i + 1, jax.lax.rem(i + 1, 2))

            wait_chunk(u, i, slot)
            return chunk_update(i, carry, slot)

        m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))
        finalize(m, l, acc)


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_units", "num_splits", "single_chunk", "pages_per_chunk",
        "sm_scale", "logits_soft_cap", "window_left", "return_lse",
    ),
)
def paged_decode_attention_split(
    q: jax.Array,  # [B_pad, num_qo_heads, head_dim]
    k_cache: jax.Array,  # [num_pages, Hkv, PS, D] (HND only)
    v_cache: jax.Array,
    plan: dict,  # jnp arrays from build_decode_split_units
    *,
    num_units: int,
    num_splits: int,
    single_chunk: bool,
    pages_per_chunk: int,
    sm_scale: float = 1.0,
    logits_soft_cap: float = 0.0,
    window_left: int = -1,
    return_lse: bool = False,
):
    """Split-KV batched paged decode over plan-time work units: per-unit
    partial states from ``_decode_split_kernel_fused_heads`` reduced by
    the batched ``merge_states`` operator (the online-softmax merge
    identity, ops/merge.py) — the TPU form of the reference's
    split-KV-then-merge decode (scheduler.cuh:426 + cascade.cuh:214).

    HND caches only (the fused-heads fast path); ``plan`` statics
    (``num_units``/``num_splits``/``single_chunk``/``pages_per_chunk``)
    must come from the SAME ``build_decode_split_units`` call that built
    the arrays — unit spans are chunk-aligned to that pages_per_chunk.
    Partials are f32 and the merge runs in f32, so the result matches
    the unsplit kernel to accumulation rounding (pinned by
    tests/test_split_decode.py)."""
    from flashinfer_tpu.ops.merge import merge_states

    batch, num_qo_heads, head_dim = q.shape
    _num_pages, num_kv_heads, page_size, _ = k_cache.shape
    assert num_qo_heads % num_kv_heads == 0
    group = num_qo_heads // num_kv_heads
    gp = round_up(group, 8)
    assert num_units == num_splits * batch, (num_units, num_splits, batch)

    qg = q.reshape(batch, num_kv_heads, group, head_dim)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    kernel = functools.partial(
        _decode_split_kernel_fused_heads,
        page_size=page_size,
        ppc=pages_per_chunk,
        sm_scale=sm_scale,
        logits_soft_cap=logits_soft_cap,
        window_left=window_left,
        num_kv_heads=num_kv_heads,
        single_chunk=single_chunk,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(num_units,),
        in_specs=[
            pl.BlockSpec(
                (None, num_kv_heads, gp, head_dim),
                lambda u, pages, kvlen, req, *_: (req[u], 0, 0, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (None, num_kv_heads, gp, head_dim),
                lambda u, *_: (u, 0, 0, 0),
            ),
            pl.BlockSpec(
                (None, num_kv_heads, gp, 128), lambda u, *_: (u, 0, 0, 0)
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, num_kv_heads, page_size, head_dim),
                k_cache.dtype,
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, num_kv_heads, page_size, head_dim),
                v_cache.dtype,
            ),
            pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk)),
        ],
    )
    o_part, lse_part = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(
                (num_units, num_kv_heads, gp, head_dim), jnp.float32),
            jax.ShapeDtypeStruct(
                (num_units, num_kv_heads, gp, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(
        plan["pages"], plan["kvlen"], plan["wu_req"], plan["wu_page0"],
        plan["wu_kvlen"], qg, k_cache, v_cache,
    )

    # batched merge reduction: [B, S, Hkv*Gp, ...] partials -> one state
    v_p = o_part.reshape(batch, num_splits, num_kv_heads * gp, head_dim)
    s_p = lse_part[..., 0].reshape(batch, num_splits, num_kv_heads * gp)
    merged_v, merged_s = merge_states(v_p, s_p)
    out = merged_v.reshape(batch, num_kv_heads, gp, head_dim)
    out = out[:, :, :group, :].reshape(
        batch, num_qo_heads, head_dim).astype(q.dtype)
    if return_lse:
        lse = merged_s.reshape(batch, num_kv_heads, gp)[:, :, :group]
        return out, lse.reshape(batch, num_qo_heads)
    return out
