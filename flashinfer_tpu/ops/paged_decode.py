"""Paged GQA decode attention Pallas kernel.

TPU-native re-design of the reference batch-decode path
(``include/flashinfer/attention/decode.cuh:613`` +
``scheduler.cuh:426 DecodePlan``).  Key design departures, per SURVEY §7:

- The KV page table is a *scalar-prefetch* operand; KV pages are gathered
  HBM→VMEM with double-buffered async DMAs inside the kernel (the Pallas
  paged-attention pattern), instead of the reference's ``paged_kv_t`` global
  loads.
- GQA "use_tensor_cores" trick maps to MXU-shaped q packing: the q heads of
  one KV head are processed together as an [group_padded, head_dim] tile.
- No split-KV grid balancing: a TPU core runs the grid sequentially with
  pipelined DMA, so one kernel instance walks a request's whole KV range;
  the reference's split-KV-then-merge machinery (needed to fill idle SMs)
  is unnecessary.  LSE output is still available for cascade/DCP merging.

Cache layouts: "HND" ``[num_pages, num_kv_heads, page_size, head_dim]``
(TPU-preferred: one page+head slice is a contiguous [page_size, head_dim]
DMA) or "NHD" ``[num_pages, page_size, num_kv_heads, head_dim]``
(reference default; strided DMA).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashinfer_tpu.utils import cdiv, round_up, use_interpret

_NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    pages_ref,  # [B, P] int32 page table (padded with a valid page id)
    kvlen_ref,  # [B] int32
    # inputs
    q_ref,  # [Gp, D] (block of [B, Hkv, Gp, D])
    k_hbm,  # full cache in ANY/HBM
    v_hbm,
    # outputs
    o_ref,  # [Gp, D]
    lse_ref,  # [Gp, 128]
    # scratch
    k_buf,  # [2, chunk_tokens, D]
    v_buf,  # [2, chunk_tokens, D]
    sem,  # DMA sems [2, 2, ppc]
    *,
    page_size: int,
    ppc: int,  # pages per chunk
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
):
    b = pl.program_id(0)
    h = pl.program_id(1)
    kv_len = kvlen_ref[b]
    chunk_tokens = ppc * page_size
    num_chunks = pl.cdiv(kv_len, chunk_tokens)

    def page_dmas(chunk_idx, slot):
        dmas = []
        for j in range(ppc):  # wedge-lint: ok on-chip validated round 2 at ppc=16 (banked 0.71 TB/s decode); clamp min(512//PS,16)
            page = pages_ref[b, chunk_idx * ppc + j]
            # NHD page layout: per-head strided DMA [PS, h, D]
            k_src = k_hbm.at[page, :, h, :]
            v_src = v_hbm.at[page, :, h, :]
            dst = pl.ds(j * page_size, page_size)
            dmas.append(
                pltpu.make_async_copy(k_src, k_buf.at[slot, dst, :], sem.at[slot, 0, j])
            )
            dmas.append(
                pltpu.make_async_copy(v_src, v_buf.at[slot, dst, :], sem.at[slot, 1, j])
            )
        return dmas

    def start_chunk(chunk_idx, slot):
        for dma in page_dmas(chunk_idx, slot):
            dma.start()

    def wait_chunk(chunk_idx, slot):
        for dma in page_dmas(chunk_idx, slot):
            dma.wait()

    @pl.when(num_chunks > 0)
    def _warmup():
        start_chunk(0, 0)

    q = q_ref[...].astype(jnp.float32) * sm_scale  # [Gp, D]
    gp = q.shape[0]

    def body(i, carry):
        m, l, acc = carry
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < num_chunks)
        def _prefetch():
            start_chunk(i + 1, jax.lax.rem(i + 1, 2))

        wait_chunk(i, slot)
        k = k_buf[slot].astype(jnp.float32)  # [chunk_tokens, D]
        v = v_buf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [Gp, chunk_tokens]
        if logits_soft_cap > 0.0:
            s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
        tok = i * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )
        valid = tok < kv_len
        if window_left >= 0:
            valid = valid & (tok >= kv_len - 1 - window_left)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((gp, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((gp, 1), jnp.float32)
    acc0 = jnp.zeros_like(q)
    m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))

    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(l), _NEG_INF)
    lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _decode_kernel_fused_heads(
    # scalar prefetch
    pages_ref,  # [B, P] int32 page table (padded with a valid page id)
    kvlen_ref,  # [B] int32
    # inputs
    q_ref,  # [Hkv, Gp, D] (block of [B, Hkv, Gp, D])
    k_hbm,  # [num_pages, Hkv, PS, D] in ANY/HBM
    v_hbm,
    # outputs
    o_ref,  # [Hkv, Gp, D]
    lse_ref,  # [Hkv, Gp, 128]
    # scratch
    k_buf,  # [2, ppc, Hkv, PS, D]
    v_buf,
    sem,  # DMA sems [2, 2, ppc]
    base_smem,  # [1] int32: slot parity carried across grid steps
    *,
    page_size: int,
    ppc: int,
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
    num_kv_heads: int,
    cross_step_prefetch: bool,
):
    """HND fast path: one DMA per whole page serves every KV head.

    The per-(batch, kv_head) grid of ``_decode_kernel`` re-reads each page
    once per head in 4 KB slices — 8x the DMA transactions the data needs.
    Here the grid is ``(batch,)``; each 32 KB page ``[Hkv, PS, D]`` is
    gathered once and all head groups are computed from it, with bf16 MXU
    dots (f32 accumulate) instead of VPU upcasts.  This is the TPU analogue
    of the reference's one-CTA-per-request split-KV decode kernel
    (include/flashinfer/attention/decode.cuh:613) with its per-warp head
    parallelism collapsed into the head loop of a single core.

    Cross-step pipelining (``cross_step_prefetch``): each step issues the
    *next* request's first chunk before finishing, hiding the per-request
    cold-start DMA stall (~one chunk's fetch per request; at bs=64/ctx=4k
    that stall is ~6% of the whole step).  Two variants:

    - ``True`` (dynamic): slot parity carried across grid steps in SMEM
      (chunk counts differ per request, so parity is data-dependent).
      Measured LOSING on v5e — the dynamic slot indexing it forces costs
      more than the stall it hides (0.68 vs 0.75 TB/s at bs=64/ctx=4k).
    - ``"static"``: prefetch only when the current request's chunk count
      is EVEN, so the free buffer slot is always slot 0 and every slot
      index stays a compile-time constant; odd-chunk requests simply keep
      the cold-start stall.  All conditions derive from the scalar-
      prefetched ``kvlen_ref``, so there is no carried state at all.  At
      the tuned bs=64/ctx=4k shape (16 chunks/request) every request has
      an even count and the whole stall disappears.
    """
    b = pl.program_id(0)
    nb = pl.num_programs(0)
    kv_len = kvlen_ref[b]
    chunk_tokens = ppc * page_size
    num_chunks = pl.cdiv(kv_len, chunk_tokens)
    static_pf = cross_step_prefetch == "static"
    if cross_step_prefetch is True:
        # kv_len == 0 still walks one (fully masked) chunk: the cross-step
        # pipeline depends on every step consuming the chunk-0 DMA its
        # predecessor issued (dangling semaphore signals otherwise)
        num_chunks = jnp.maximum(num_chunks, 1)

    def page_dmas(bb, chunk_idx, slot):
        dmas = []
        for j in range(ppc):  # wedge-lint: ok ppc bounded by the 8 MiB VMEM clamp at call site; on-chip validated round 2
            page = pages_ref[bb, chunk_idx * ppc + j]
            dmas.append(
                pltpu.make_async_copy(
                    k_hbm.at[page], k_buf.at[slot, j], sem.at[slot, 0, j]
                )
            )
            dmas.append(
                pltpu.make_async_copy(
                    v_hbm.at[page], v_buf.at[slot, j], sem.at[slot, 1, j]
                )
            )
        return dmas

    def start_chunk(bb, chunk_idx, slot):
        for dma in page_dmas(bb, chunk_idx, slot):
            dma.start()

    def wait_chunk(bb, chunk_idx, slot):
        for dma in page_dmas(bb, chunk_idx, slot):
            dma.wait()

    if cross_step_prefetch is True:
        base = jnp.where(b == 0, 0, base_smem[0])

        @pl.when(b == 0)
        def _warmup():
            start_chunk(b, 0, 0)
    elif static_pf:
        base = 0
        # predecessor's epilogue prefetched our chunk 0 into slot 0 iff it
        # ran chunks (nc_prev > 0), had an even count (slot 0 free), and
        # we have chunks to run (its nc_next > 0 check — same formula)
        prev_nc = pl.cdiv(kvlen_ref[jnp.maximum(b - 1, 0)], chunk_tokens)
        prev_prefetched = (
            (b > 0) & (prev_nc > 0) & (jax.lax.rem(prev_nc, 2) == 0)
        )

        @pl.when((num_chunks > 0) & ~prev_prefetched)
        def _warmup():
            start_chunk(b, 0, 0)
    else:
        base = 0

        @pl.when(num_chunks > 0)
        def _warmup():
            start_chunk(b, 0, 0)

    q = q_ref[...]  # [Hkv, Gp, D] native dtype
    gp = q.shape[1]
    head_dim = q.shape[2]

    def body(i, carry):
        m, l, acc = carry  # [Hkv, Gp, 1] x2, [Hkv, Gp, D]
        slot = jax.lax.rem(base + i, 2)

        @pl.when(i + 1 < num_chunks)
        def _prefetch():
            start_chunk(b, i + 1, jax.lax.rem(base + i + 1, 2))

        wait_chunk(b, i, slot)
        tok = i * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )
        valid = tok < kv_len
        if window_left >= 0:
            valid = valid & (tok >= kv_len - 1 - window_left)

        ss, pvs = [], []
        # wedge-lint: ok bounded by num_kv_heads (<=16 served models, 2 dots/head); on-chip validated round 2
        for h in range(num_kv_heads):
            kh = k_buf[slot, :, h, :, :].reshape(chunk_tokens, head_dim)
            if kh.dtype != q.dtype:
                # quantized (fp8/int8) KV: cache bytes cross HBM at half
                # width, dequant is an in-register cast; the scalar
                # k_scale/v_scale are folded into sm_scale / output by the
                # wrapper (reference decode.py:2004 scale folding)
                kh = kh.astype(q.dtype)
            s = jax.lax.dot_general(
                q[h], kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * sm_scale  # [Gp, chunk_tokens] f32
            if logits_soft_cap > 0.0:
                s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
            ss.append(jnp.where(valid, s, _NEG_INF))
        s_all = jnp.stack(ss)  # [Hkv, Gp, chunk]
        m_cur = jnp.max(s_all, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p_all = jnp.where(valid[None], jnp.exp(s_all - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p_all, axis=-1, keepdims=True)
        for h in range(num_kv_heads):  # wedge-lint: ok bounded by num_kv_heads; on-chip validated round 2
            vh = v_buf[slot, :, h, :, :].reshape(chunk_tokens, head_dim)
            if vh.dtype != q.dtype:
                vh = vh.astype(q.dtype)
            pvs.append(
                jax.lax.dot_general(
                    p_all[h].astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        pv = jnp.stack(pvs)  # [Hkv, Gp, D]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((num_kv_heads, gp, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((num_kv_heads, gp, 1), jnp.float32)
    acc0 = jnp.zeros((num_kv_heads, gp, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_chunks, body, (m0, l0, acc0))

    if cross_step_prefetch is True:
        # hand the free slot to the next request's first chunk before the
        # epilogue so its gather overlaps the output write + step transition
        next_base = jax.lax.rem(base + num_chunks, 2)

        @pl.when(b + 1 < nb)
        def _prefetch_next_request():
            start_chunk(b + 1, 0, next_base)

        base_smem[0] = next_base
    elif static_pf:
        next_nc = pl.cdiv(kvlen_ref[jnp.minimum(b + 1, nb - 1)], chunk_tokens)

        @pl.when(
            (b + 1 < nb) & (num_chunks > 0)
            & (jax.lax.rem(num_chunks, 2) == 0) & (next_nc > 0)
        )
        def _prefetch_next_request_static():
            start_chunk(b + 1, 0, 0)

    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[...] = (acc / l_safe).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(l), _NEG_INF)
    lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def decode_tactic_key(batch, max_pages, num_qo_heads, num_kv_heads,
                      head_dim, page_size, q_dtype):
    """The ONE shape key for paged-decode tactic caches
    (``paged_decode.pages_per_chunk`` / ``paged_decode.prefetch``): built
    here so every lookup site (wrapper run, model decode steps) stays in
    sync when a field is added."""
    return (batch, max_pages, num_qo_heads, num_kv_heads, head_dim,
            page_size, str(q_dtype))


@functools.partial(
    jax.jit,
    static_argnames=(
        "sm_scale", "logits_soft_cap", "window_left", "kv_layout",
        "pages_per_chunk", "return_lse", "cross_step_prefetch",
    ),
)
def paged_decode_attention(
    q: jax.Array,  # [batch, num_qo_heads, head_dim]
    k_cache: jax.Array,
    v_cache: jax.Array,
    page_table: jax.Array,  # [batch, max_pages] int32, padded with valid ids
    kv_lens: jax.Array,  # [batch] int32
    *,
    sm_scale: float = 1.0,
    logits_soft_cap: float = 0.0,
    window_left: int = -1,
    kv_layout: str = "HND",
    pages_per_chunk: Optional[int] = None,
    return_lse: bool = False,
    cross_step_prefetch: bool = False,
):
    """Batched paged decode attention over a padded page table.

    ``page_table``/``kv_lens`` are the plan arrays produced by
    ``BatchDecodeWithPagedKVCacheWrapper.plan`` (padded-rectangular page
    table replaces the reference's ragged indptr + CUDAGraph buffer pinning).
    """
    # identity checks, matching the kernel's dispatch (`is True` /
    # == "static"): a truthy 1 or np.True_ must not pass validation and
    # then silently run the no-prefetch path
    if not (cross_step_prefetch is False or cross_step_prefetch is True
            or cross_step_prefetch == "static"):
        raise ValueError(
            f"cross_step_prefetch must be False, True (dynamic SMEM "
            f"parity) or 'static', got {cross_step_prefetch!r}"
        )
    if cross_step_prefetch == "static":
        cross_step_prefetch = "static"  # normalize np.str_ etc.
    batch, num_qo_heads, head_dim = q.shape
    if kv_layout == "HND":
        num_pages, num_kv_heads, page_size, _ = k_cache.shape
    else:
        num_pages, page_size, num_kv_heads, _ = k_cache.shape
    assert num_qo_heads % num_kv_heads == 0
    group = num_qo_heads // num_kv_heads
    gp = round_up(group, 8)

    if pages_per_chunk is None:
        pages_per_chunk = max(1, min(512 // page_size, 16))
    if kv_layout == "HND":
        # fused-heads scratch scales with num_kv_heads: clamp the
        # double-buffered K+V footprint (2 slots x 2 bufs x ppc x Hkv x
        # PS x D) to ~8 MiB so large heads/pages still compile — applies
        # to explicit/autotuned values too, which would otherwise exceed
        # a v5e core's VMEM at e.g. Hkv=16, PS=16, ppc=64
        per_page = 4 * num_kv_heads * page_size * head_dim * k_cache.dtype.itemsize
        pages_per_chunk = max(1, min(pages_per_chunk, (8 << 20) // per_page))
    max_pages = page_table.shape[1]
    # pad page table columns to a multiple of pages-per-chunk
    p_padded = round_up(max_pages, pages_per_chunk)
    if p_padded != max_pages:
        page_table = jnp.pad(page_table, ((0, 0), (0, p_padded - max_pages)))

    # [B, Hq, D] -> [B, Hkv, Gp, D] with zero padding in the group dim
    qg = q.reshape(batch, num_kv_heads, group, head_dim)
    if gp != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - group), (0, 0)))

    chunk_tokens = pages_per_chunk * page_size
    if kv_layout == "HND":
        # head-fused fast path: one 32KB page DMA serves all KV heads
        kernel = functools.partial(
            _decode_kernel_fused_heads,
            page_size=page_size,
            ppc=pages_per_chunk,
            sm_scale=sm_scale,
            logits_soft_cap=logits_soft_cap,
            window_left=window_left,
            num_kv_heads=num_kv_heads,
            cross_step_prefetch=cross_step_prefetch,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch,),
            in_specs=[
                pl.BlockSpec(
                    (None, num_kv_heads, gp, head_dim),
                    lambda b, *_: (b, 0, 0, 0),
                ),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec(
                    (None, num_kv_heads, gp, head_dim),
                    lambda b, *_: (b, 0, 0, 0),
                ),
                pl.BlockSpec(
                    (None, num_kv_heads, gp, 128), lambda b, *_: (b, 0, 0, 0)
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM(
                    (2, pages_per_chunk, num_kv_heads, page_size, head_dim),
                    k_cache.dtype,
                ),
                pltpu.VMEM(
                    (2, pages_per_chunk, num_kv_heads, page_size, head_dim),
                    v_cache.dtype,
                ),
                pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk)),
                pltpu.SMEM((1,), jnp.int32),
            ],
        )
    else:
        kernel = functools.partial(
            _decode_kernel,
            page_size=page_size,
            ppc=pages_per_chunk,
            sm_scale=sm_scale,
            logits_soft_cap=logits_soft_cap,
            window_left=window_left,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, num_kv_heads),
            in_specs=[
                pl.BlockSpec(
                    (None, None, gp, head_dim), lambda b, h, *_: (b, h, 0, 0)
                ),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec(
                    (None, None, gp, head_dim), lambda b, h, *_: (b, h, 0, 0)
                ),
                pl.BlockSpec((None, None, gp, 128), lambda b, h, *_: (b, h, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, chunk_tokens, head_dim), k_cache.dtype),
                pltpu.VMEM((2, chunk_tokens, head_dim), v_cache.dtype),
                pltpu.SemaphoreType.DMA((2, 2, pages_per_chunk)),
            ],
        )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((batch, num_kv_heads, gp, head_dim), q.dtype),
            jax.ShapeDtypeStruct((batch, num_kv_heads, gp, 128), jnp.float32),
        ],
        interpret=use_interpret(),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), qg, k_cache, v_cache)

    out = out[:, :, :group, :].reshape(batch, num_qo_heads, head_dim)
    if return_lse:
        return out, lse[:, :, :group, 0].reshape(batch, num_qo_heads)
    return out
