"""Fused paged-KV batch prefill Pallas kernel (work-unit scheduled).

The TPU translation of the reference's prefill work queue
(``PrefillPlan``/``PrefillSplitQOKVIndptr``, scheduler.cuh:545-897 +
``BatchPrefillWithPagedKVCacheDispatched``, prefill.cuh:4057): the plan
splits every request into (qo-tile, kv-chunk) work units; the kernel walks
the unit list sequentially, double-buffering the next unit's KV pages while
computing the current one, and carries the online-softmax accumulator
across the kv-chunks of each qo tile (reset on first-chunk, write-out on
last-chunk flags — plan-encoded, no in-kernel scheduling).

Grid is ``(num_kv_heads, num_units)``: each unit computes ALL q heads of
one KV head's GQA group, so every KV page is fetched from HBM exactly once
per kv head — the same bandwidth discipline as the decode kernel.

vs the gather+flash path (prefill.py): no extra HBM round trip for KV —
for chunked prefill (small qo vs large kv) the gather pass costs ~50% of
the attention time, which this kernel eliminates.

Correctness invariant (relied on by the partial-tile write-back): units
are ordered by ascending qstart within each kv head, and the unit grid
dimension executes sequentially — a partial tile's full-block output DMA
may clobber the next request's rows, which later units then rewrite.
``build_prefill_work_units`` asserts the ordering; do not mark the unit
dim "parallel".

Hardware-validated on v5e (tests/test_tpu_hw.py — mixed ragged batch with
append semantics vs dense oracle) and the default paged-prefill backend
for HND caches; the GQA group rides one merged [bq*group, chunk] MXU dot.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashinfer_tpu.utils import cdiv, next_power_of_two, round_up, tpu_compiler_params, use_interpret

_NEG_INF = -1e30


def mask_lane_bytes(chunk_tokens: int) -> int:
    """Lane width of the per-unit packed-mask bitmap (>= 128 for Mosaic
    VMEM blocks)."""
    return max(round_up(cdiv(chunk_tokens, 8), 128), 128)


def build_prefill_work_units(
    qo_indptr: np.ndarray,  # [B+1] token offsets
    kv_page_indptr: np.ndarray,  # [B+1] page offsets
    kv_page_indices: np.ndarray,
    kv_lens: np.ndarray,  # [B] kv token lengths
    block_q: int,
    pages_per_chunk: int,
    page_size: int,
    mask_flat: Optional[np.ndarray] = None,  # concat per-request [qo*kv]:
    #   bool bits, or uint8 LSB-first packed bytes (+ mask_total_bits)
    mask_total_bits: Optional[int] = None,
):
    """Host-side plan: flatten (request, qo-tile, kv-chunk) units.

    Returns a dict of numpy arrays padded to a power-of-two unit count
    (padding units have qlen 0 and last=0 so they neither write nor
    corrupt), plus the static (block_q, pages_per_chunk) the arrays were
    built for.

    With ``mask_flat`` (MaskMode::CUSTOM, the reference's flat
    per-request mask concat, prefill.py:1492), each unit additionally
    gets its window of the mask re-packed as a little-endian byte bitmap
    ``mask_bytes [num_units, block_q, mask_lane_bytes(chunk)]``, shaped
    for a direct per-unit VMEM fetch; the kernel expands bits in-register
    (selector dot + shifts), so no dense [qo, kv] array ever exists on
    device (reference analogue: packed_custom_mask consumed inside the
    kernel, prefill.cuh:2682).  Byte budget per unit is
    ``block_q * max(128, chunk/8)`` — the 128-lane Mosaic floor means the
    bit-packing only wins HBM bytes over a dense bool tile at
    chunk_tokens > 1024; at the default chunk of 128-256 the win is the
    in-kernel consumption (no [tq_pad, tkv_pad] dense mask built or
    shipped), not the packing."""
    chunk_tokens = pages_per_chunk * page_size
    units = []  # (qstart, qlen, qpos0, kvstart, kvlen_req, first, last, pages)
    unit_masks = []  # packed [block_q, ceil(chunk/8)] per unit (numpy path)
    use_native_mask = False
    mask_offsets = None
    if mask_flat is not None:
        from flashinfer_tpu import native

        if mask_total_bits is None:
            if mask_flat.dtype == np.uint8:
                raise ValueError(
                    "packed mask bytes require mask_total_bits (the byte "
                    "count is 8x short and would truncate the mask)"
                )
            mask_total_bits = int(mask_flat.size)
        # the per-unit re-pack touches every mask bit of every tile — the
        # hottest host-plan loop; the C++ planner does it with two shifts
        # per output byte straight from the packed bytes (numpy per-tile
        # packbits is the fallback, which needs the unpacked bool form)
        use_native_mask = native.get_lib() is not None
        if not use_native_mask:
            if mask_flat.dtype == np.uint8:
                mask_flat = np.unpackbits(
                    mask_flat.reshape(-1), bitorder="little"
                )[:mask_total_bits].astype(bool)
            mask_offsets = np.concatenate(
                [[0], np.cumsum(
                    (qo_indptr[1:] - qo_indptr[:-1]).astype(np.int64)
                    * np.asarray(kv_lens, np.int64)
                )]
            )
    B = len(qo_indptr) - 1
    for r in range(B):
        qs, qe = int(qo_indptr[r]), int(qo_indptr[r + 1])
        kv_len = int(kv_lens[r])
        pages = kv_page_indices[
            int(kv_page_indptr[r]) : int(kv_page_indptr[r + 1])
        ]
        if (mask_flat is not None and not use_native_mask
                and qe > qs and kv_len > 0):
            req_mask = np.asarray(
                mask_flat[mask_offsets[r] : mask_offsets[r + 1]], bool
            ).reshape(qe - qs, kv_len)
        else:
            req_mask = None
        n_tiles = max(cdiv(qe - qs, block_q), 1) if qe > qs else 0
        n_chunks = max(cdiv(kv_len, chunk_tokens), 1) if kv_len > 0 else 1
        for t in range(n_tiles):
            qstart = qs + t * block_q
            qlen = min(block_q, qe - qstart)
            qpos0 = kv_len - (qe - qs) + t * block_q
            for c in range(n_chunks):
                pg = pages[c * pages_per_chunk : (c + 1) * pages_per_chunk]
                pg = np.pad(pg, (0, pages_per_chunk - len(pg)))
                units.append((
                    qstart, qlen, qpos0, c * chunk_tokens, kv_len,
                    1 if c == 0 else 0, 1 if c == n_chunks - 1 else 0, pg,
                ))
                if mask_flat is not None and not use_native_mask:
                    tile = np.zeros((block_q, chunk_tokens), bool)
                    if req_mask is not None:
                        r0 = qstart - qs
                        c0 = c * chunk_tokens
                        w = min(chunk_tokens, kv_len - c0)
                        tile[:qlen, :w] = req_mask[
                            r0 : r0 + qlen, c0 : c0 + w
                        ]
                    # pack per tile: keeps transient host memory at the
                    # packed size instead of 8x unpacked bools for the
                    # whole unit list (matters at 64k+ units)
                    unit_masks.append(
                        np.packbits(tile, axis=-1, bitorder="little")
                    )
    # the partial-tile write-back rewrite depends on ascending qstart order
    starts = [u[0] for u in units]
    assert starts == sorted(starts), "work units must be qstart-ordered"
    U = max(next_power_of_two(max(len(units), 1)), 8)
    # pad units: first=1 (reset, harmless), last=0 (MUST NOT write output)
    pad_unit = (0, 0, 0, 0, 0, 1, 0, np.zeros(pages_per_chunk, np.int64))
    while len(units) < U:
        units.append(pad_unit)
        if mask_flat is not None and not use_native_mask:
            unit_masks.append(
                np.zeros((block_q, cdiv(chunk_tokens, 8)), np.uint8)
            )
    arr = lambda i, dt: np.asarray([u[i] for u in units], dt)
    plan = dict(
        qstart=arr(0, np.int32), qlen=arr(1, np.int32), qpos0=arr(2, np.int32),
        kvstart=arr(3, np.int32), kvlen=arr(4, np.int32),
        first=arr(5, np.int32), last=arr(6, np.int32),
        pages=np.stack([u[7] for u in units]).astype(np.int32).reshape(-1),
        num_units=U,
        block_q=block_q,
        pages_per_chunk=pages_per_chunk,
    )
    if mask_flat is not None:
        mb = mask_lane_bytes(chunk_tokens)
        if use_native_mask:
            plan["mask_bytes"] = native.prefill_mask_plan(
                mask_flat, mask_total_bits,
                qo_indptr, np.asarray(kv_lens, np.int64),
                block_q, chunk_tokens, mb, U,
            )
        else:
            packed = np.stack(unit_masks)  # [U, block_q, ceil(chunk/8)]
            plan["mask_bytes"] = np.pad(
                packed, ((0, 0), (0, 0), (0, mb - packed.shape[-1]))
            )
    return plan


def _fused_prefill_kernel(
    # scalar prefetch
    qstart_ref, qlen_ref, qpos0_ref, kvstart_ref, kvlen_ref,
    first_ref, last_ref, pages_ref,
    # inputs: q/k/v in ANY (manual DMA); with has_mask, a pipelined
    # per-unit packed-mask block [bq, mask_lane_bytes] uint8 follows
    *refs,
    bq: int,
    ppc: int,
    page_size: int,
    group: int,
    sm_scale: float,
    logits_soft_cap: float,
    window_left: int,
    causal: bool,
    num_units: int,
    has_mask: bool,
    trace_events: bool,
):
    i = 3
    q_hbm, k_hbm, v_hbm = refs[0], refs[1], refs[2]
    mask_ref = refs[i] if has_mask else None
    i += 1 if has_mask else 0
    o_hbm = refs[i]
    i += 1
    ev_ref = refs[i] if trace_events else None
    i += 1 if trace_events else 0
    (qbuf, kbuf, vbuf, obuf, acc_ref, m_ref, l_ref,
     qsem, ksem, vsem, osem) = refs[i:]
    hkv = pl.program_id(0)
    u = pl.program_id(1)
    chunk_tokens = ppc * page_size

    if trace_events:
        # device-side event tag, reference profiler bit layout
        # (profiler.decode_tag): sm_id <- kv head, block <- work unit,
        # event 0, kInstant; slot order == the sequential grid order, so
        # stream position doubles as the timestamp.  The block shape
        # covers 8 consecutive units (row u % 8) so the buffer costs
        # 512 B per (head, unit) octet instead of 4 KB per step.
        tag = (hkv << 24) | ((u & 0xFFF) << 12) | 2
        ev_ref[pl.ds(jax.lax.rem(u, 8), 1), :] = jnp.full(
            (1, 128), tag, jnp.int32
        )

    def kv_dmas(unit, slot):
        dmas = []
        # wedge-lint: ok default ppc=8 (2 DMAs/page <= 2x queue depth, round-2-validated shape); autotuner candidates guarded; never-compiled kernel stays hw-queue item 3
        for j in range(ppc):
            page = pages_ref[unit * ppc + j]
            dst = pl.ds(j * page_size, page_size)
            dmas.append(pltpu.make_async_copy(
                k_hbm.at[page, hkv], kbuf.at[slot, dst, :], ksem.at[slot, j]))
            dmas.append(pltpu.make_async_copy(
                v_hbm.at[page, hkv], vbuf.at[slot, dst, :], vsem.at[slot, j]))
        return dmas

    def q_dma(unit):
        # all q heads of this kv head's group in one DMA: q is laid out
        # [Hkv, tq, group, D] by the wrapper so the head dim is a full
        # index, not a partial sublane slice (Mosaic requires 8-aligned
        # sublane slices; group can be 4)
        return pltpu.make_async_copy(
            q_hbm.at[hkv, pl.ds(qstart_ref[unit], bq)],
            qbuf, qsem,
        )

    # this unit's q fetch (single buffer: fetched and consumed per step)
    q_dma(u).start()

    # KV double buffering: unit 0 warm-up, then prefetch u+1 into the
    # other slot while computing u
    @pl.when(u == 0)
    def _():
        for d in kv_dmas(0, 0):
            d.start()

    @pl.when(u + 1 < num_units)
    def _():
        for d in kv_dmas(u + 1, jax.lax.rem(u + 1, 2)):
            d.start()

    slot = jax.lax.rem(u, 2)
    q_dma(u).wait()
    for d in kv_dmas(u, slot):
        d.wait()

    @pl.when(first_ref[u] == 1)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # the whole GQA group rides one MXU dot: merged rows r = q_row*group+g,
    # so the q-row of merged row r is r // group (computed by iota, no
    # relayout), and [bq*group, D] -> [bq, group, D] is a free reshape
    bqg = bq * group
    rows_q = jax.lax.broadcasted_iota(jnp.int32, (bqg, 1), 0) // group
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, chunk_tokens), 1)
    q_pos = qpos0_ref[u] + rows_q
    kv_pos = kvstart_ref[u] + cols
    valid = (rows_q < qlen_ref[u]) & (kv_pos < kvlen_ref[u])
    if causal:
        valid = valid & (kv_pos <= q_pos)
    if window_left >= 0:
        valid = valid & (kv_pos >= q_pos - window_left)
    if has_mask:
        # expand the packed per-unit bitmap in-register.  Lane-dim
        # byte->column expansion is an unsupported Mosaic shape cast, so
        # it rides a constant selector-matrix MXU dot (byte values <= 255
        # are exact in f32); the bit extract is VPU shifts.
        mb = mask_ref.shape[-1]
        # Mosaic has no direct uint8 -> f32 cast ("Unsupported cast",
        # banked 2026-07-31 hw tier); widen through int32 first
        bytes_f = mask_ref[...].astype(jnp.int32).astype(jnp.float32)
        sel = (
            jax.lax.broadcasted_iota(jnp.int32, (mb, chunk_tokens), 1) // 8
            == jax.lax.broadcasted_iota(jnp.int32, (mb, chunk_tokens), 0)
        ).astype(jnp.float32)
        byte_col = jax.lax.dot_general(
            bytes_f, sel, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, chunk]: the byte holding each column's bit
        shift = jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        ) % 8
        bit = (byte_col.astype(jnp.int32) >> shift) & 1  # [bq, chunk]
        # q-row -> merged GQA rows: sublane-side broadcast + free
        # leading-dim reshape (lane dim untouched)
        bit_g = jnp.broadcast_to(
            (bit > 0).reshape(bq, 1, chunk_tokens),
            (bq, group, chunk_tokens),
        ).reshape(bqg, chunk_tokens)
        valid = valid & bit_g

    k = kbuf[slot]
    v = vbuf[slot]
    qm = qbuf[...].reshape(bqg, k.shape[-1])  # [bq*group, D]
    s = jax.lax.dot_general(
        qm, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * sm_scale  # [bq*group, chunk]
    if logits_soft_cap > 0.0:
        s = logits_soft_cap * jnp.tanh(s / logits_soft_cap)
    s = jnp.where(valid, s, _NEG_INF)
    m_prev = m_ref[...][:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_ref[...] = jnp.broadcast_to(
        alpha * l_ref[...][:, :1] + jnp.sum(p, -1, keepdims=True),
        (bqg, 128),
    )
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[...] = acc_ref[...] * alpha + pv
    m_ref[...] = jnp.broadcast_to(m_new, (bqg, 128))

    @pl.when((last_ref[u] == 1) & (qlen_ref[u] > 0))
    def _():
        l = l_ref[...][:, :1]
        o = (acc_ref[...] / jnp.where(l > 0, l, 1.0)).astype(obuf.dtype)
        obuf[...] = o.reshape(obuf.shape)
        out_dma = pltpu.make_async_copy(
            obuf,
            o_hbm.at[hkv, pl.ds(qstart_ref[u], bq)],
            osem,
        )
        out_dma.start()
        out_dma.wait()


@functools.partial(
    jax.jit,
    static_argnames=(
        "num_units", "block_q", "pages_per_chunk", "sm_scale",
        "logits_soft_cap", "window_left", "causal", "trace_events",
    ),
)
def fused_paged_prefill(
    q: jax.Array,  # [tq_pad, H, D] — PRE-PADDED (bucketed) by the caller
    k_cache: jax.Array,  # [pages, Hkv, page_size, D] (HND)
    v_cache: jax.Array,
    plan: dict,  # jnp arrays from build_prefill_work_units
    *,
    num_units: int,
    block_q: int = 128,
    pages_per_chunk: int = 8,
    sm_scale: float = 1.0,
    logits_soft_cap: float = 0.0,
    window_left: int = -1,
    causal: bool = True,
    trace_events: bool = False,
):
    total_q, H, D = q.shape
    _, Hkv, page_size, _ = k_cache.shape
    group = H // Hkv
    chunk_tokens = pages_per_chunk * page_size
    # packed custom mask rides in the plan ([U, bq, mb] from
    # build_prefill_work_units(mask_flat=...)); presence changes the jit
    # pytree structure, so the masked/unmasked variants compile separately
    mask_bytes = plan.get("mask_bytes")
    has_mask = mask_bytes is not None
    if has_mask:
        causal = False  # MaskMode::CUSTOM replaces causal (window still ANDs)
    # extra block so full-bq tile DMAs at the tail stay in bounds; lay q
    # out [Hkv, tq, group, D] so the kernel's per-unit q DMA indexes the
    # kv-head dim instead of slicing a sub-sublane head range
    q_pad = jnp.pad(q, ((0, block_q), (0, 0), (0, 0)))
    q_pad = jnp.transpose(
        q_pad.reshape(total_q + block_q, Hkv, group, D), (1, 0, 2, 3)
    )

    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    if has_mask:
        mb = mask_bytes.shape[-1]
        in_specs.append(
            pl.BlockSpec(
                (None, block_q, mb),
                lambda h, u, *prefetch: (u, 0, 0),
            )
        )
    out_specs = pl.BlockSpec(memory_space=pl.ANY)
    out_shape = jax.ShapeDtypeStruct(
        (Hkv, total_q + block_q, group, D), q.dtype
    )
    if trace_events:
        # one tag row per grid step (reference profiler.cuh device tag
        # buffer, TPU form: see flashinfer_tpu.profiler module docs);
        # the 12-bit block field of the reference layout caps traceable
        # plans — refuse loudly rather than alias units
        if num_units > 4096:
            raise ValueError(
                "trace_events supports plans up to 4096 work units "
                f"(12-bit tag block field), got {num_units}"
            )
        out_specs = [out_specs, pl.BlockSpec(
            (None, None, 8, 128), lambda h, u, *prefetch: (h, u // 8, 0, 0)
        )]
        out_shape = [out_shape, jax.ShapeDtypeStruct(
            (Hkv, cdiv(num_units, 8), 8, 128), jnp.int32
        )]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=8,
        grid=(Hkv, num_units),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, group, D), q.dtype),
            pltpu.VMEM((2, chunk_tokens, D), k_cache.dtype),
            pltpu.VMEM((2, chunk_tokens, D), v_cache.dtype),
            pltpu.VMEM((block_q, group, D), q.dtype),
            pltpu.VMEM((block_q * group, D), jnp.float32),
            pltpu.VMEM((block_q * group, 128), jnp.float32),
            pltpu.VMEM((block_q * group, 128), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2, pages_per_chunk)),
            pltpu.SemaphoreType.DMA((2, pages_per_chunk)),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    operands = [q_pad, k_cache, v_cache]
    if has_mask:
        operands.append(mask_bytes)
    out = pl.pallas_call(
        functools.partial(
            _fused_prefill_kernel,
            bq=block_q, ppc=pages_per_chunk, page_size=page_size,
            group=group, sm_scale=sm_scale, logits_soft_cap=logits_soft_cap,
            window_left=window_left, causal=causal, num_units=num_units,
            has_mask=has_mask, trace_events=trace_events,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=64 * 1024 * 1024,
            has_side_effects=True,
        ),
        interpret=use_interpret(),
    )(
        plan["qstart"], plan["qlen"], plan["qpos0"], plan["kvstart"],
        plan["kvlen"], plan["first"], plan["last"], plan["pages"],
        *operands,
    )
    if trace_events:
        out, ev = out
        # [Hkv, ceil(U/8), 8, 128] -> [Hkv, num_units] tags, grid order
        events = ev[..., 0].reshape(Hkv, -1)[:, :num_units]
    # [Hkv, tq_pad, group, D] -> [tq, H, D]
    result = jnp.transpose(out[:, :total_q], (1, 0, 2, 3)).reshape(
        total_q, H, D
    )
    return (result, events) if trace_events else result
